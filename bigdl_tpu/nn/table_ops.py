"""Table (multi-tensor) containers and ops.

Rebuild of the reference's Table-valued layers («bigdl»/nn/ConcatTable.scala,
CAddTable.scala, JoinTable.scala, Concat.scala...).  The reference's
``Table`` activity type maps to Python tuples/lists of arrays, which are
ordinary pytrees — so ``jax.vjp`` differentiates through them for free.
"""

from __future__ import annotations

from typing import Sequence

from bigdl_tpu.nn.module import AbstractModule, Container


def _jnp():
    import jax.numpy as jnp

    return jnp


class ConcatTable(Container):
    """«bigdl»/nn/ConcatTable.scala — apply each child to the same input,
    return the table of outputs."""

    def apply(self, params, state, input, *, training=False, rng=None):
        import jax

        outs, new_state = [], {}
        for i, m in enumerate(self.modules):
            r = None if rng is None else jax.random.fold_in(rng, i)
            y, s = m.apply(
                params[str(i)], state[str(i)], input, training=training, rng=r
            )
            outs.append(y)
            new_state[str(i)] = s
        return tuple(outs), new_state


class ParallelTable(Container):
    """«bigdl»/nn/ParallelTable.scala — i-th child gets i-th table entry."""

    def apply(self, params, state, input, *, training=False, rng=None):
        import jax

        outs, new_state = [], {}
        for i, m in enumerate(self.modules):
            r = None if rng is None else jax.random.fold_in(rng, i)
            y, s = m.apply(
                params[str(i)], state[str(i)], input[i], training=training, rng=r
            )
            outs.append(y)
            new_state[str(i)] = s
        return tuple(outs), new_state


class _TableReduce(AbstractModule):
    def __init__(self, **config):
        super().__init__()
        self._config = config


class CAddTable(_TableReduce):
    """«bigdl»/nn/CAddTable.scala — elementwise sum of a table."""

    def __init__(self, inplace: bool = False):
        super().__init__()

    def update_output_pure(self, params, input, *, training=False, rng=None):
        y = input[0]
        for t in input[1:]:
            y = y + t
        return y


class CSubTable(_TableReduce):
    """«bigdl»/nn/CSubTable.scala"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return input[0] - input[1]


class CMulTable(_TableReduce):
    """«bigdl»/nn/CMulTable.scala"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        y = input[0]
        for t in input[1:]:
            y = y * t
        return y


class CDivTable(_TableReduce):
    """«bigdl»/nn/CDivTable.scala"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return input[0] / input[1]


class CMaxTable(_TableReduce):
    """«bigdl»/nn/CMaxTable.scala"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        y = input[0]
        for t in input[1:]:
            y = jnp.maximum(y, t)
        return y


class CMinTable(_TableReduce):
    """«bigdl»/nn/CMinTable.scala"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        y = input[0]
        for t in input[1:]:
            y = jnp.minimum(y, t)
        return y


class InTopK(_TableReduce):
    """TF-interop vocabulary (InTopK) — table [predictions (B, C),
    targets (B,)] -> {0,1} floats: is the target class within the top
    ``k`` predictions?  TF tie semantics: the target is in the top k
    iff fewer than k classes score STRICTLY higher."""

    def __init__(self, k: int = 1):
        super().__init__(k=k)
        self.k = k

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        preds, tgt = input
        idx = tgt.astype(jnp.int32)[:, None]
        score = jnp.take_along_axis(preds, idx, axis=1)
        n_higher = jnp.sum((preds > score).astype(jnp.int32), axis=1)
        ok = n_higher < self.k
        # TF kernel guards (in_topk_op): a non-finite target prediction
        # is never in the top k, and an out-of-range target index is
        # false (jnp's gather would silently clamp it)
        ok = ok & jnp.isfinite(score[:, 0])
        valid = (tgt >= 0) & (tgt < preds.shape[1])
        return (ok & valid).astype(jnp.float32)


class WhereTable(_TableReduce):
    """TF-interop vocabulary (Select / SelectV2) — ``cond ? x : y``
    over a table ``[cond, x, y]``; cond is {0, 1} floats (this f32
    runtime's boolean convention).  Gradients flow to x and y; the
    predicate gets none.

    ``leading_broadcast`` encodes TF's two spellings: Select (v1)
    broadcasts a lower-rank cond along the LEADING axes (a rank-1 cond
    is a row mask), SelectV2 broadcasts numpy-style (trailing)."""

    def __init__(self, leading_broadcast: bool = False):
        super().__init__(leading_broadcast=leading_broadcast)
        self.leading_broadcast = leading_broadcast

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        cond, x, y = input
        if self.leading_broadcast and cond.ndim < x.ndim:
            cond = cond.reshape(
                cond.shape + (1,) * (x.ndim - cond.ndim))
        return jnp.where(cond != 0, x, y)


class JoinTable(_TableReduce):
    """«bigdl»/nn/JoinTable.scala — concat a table along 1-based dim;
    n_input_dims handles the batch-dim shift like the reference."""

    def __init__(self, dimension: int, n_input_dims: int = -1):
        super().__init__(dimension=dimension, n_input_dims=n_input_dims)
        self.dimension = dimension
        self.n_input_dims = n_input_dims

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        d = self.dimension - 1
        if self.n_input_dims > 0 and input[0].ndim > self.n_input_dims:
            d += 1
        return jnp.concatenate(list(input), axis=d)


class SelectTable(_TableReduce):
    """«bigdl»/nn/SelectTable.scala — pick 1-based entry of a table."""

    def __init__(self, index: int):
        super().__init__(index=index)
        self.index = index

    def update_output_pure(self, params, input, *, training=False, rng=None):
        i = self.index - 1 if self.index > 0 else self.index
        return input[i]


class FlattenTable(_TableReduce):
    """«bigdl»/nn/FlattenTable.scala — flatten nested tables."""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        out = []

        def rec(t):
            if isinstance(t, (tuple, list)):
                for u in t:
                    rec(u)
            else:
                out.append(t)

        rec(input)
        return tuple(out)


class MM(_TableReduce):
    """«bigdl»/nn/MM.scala — batched matmul of a 2-table, with transpose
    flags."""

    def __init__(self, trans_a: bool = False, trans_b: bool = False):
        super().__init__(trans_a=trans_a, trans_b=trans_b)
        self.trans_a, self.trans_b = trans_a, trans_b

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        a, b = input
        if self.trans_a:
            a = jnp.swapaxes(a, -1, -2)
        if self.trans_b:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)


class MV(_TableReduce):
    """«bigdl»/nn/MV.scala — (batched) matrix-vector product."""

    def __init__(self, trans: bool = False):
        super().__init__(trans=trans)
        self.trans = trans

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        m, v = input
        if self.trans:
            m = jnp.swapaxes(m, -1, -2)
        return jnp.einsum("...ij,...j->...i", m, v)


class DotProduct(_TableReduce):
    """«bigdl»/nn/DotProduct.scala"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        a, b = input
        return jnp.sum(a * b, axis=-1)


class CosineDistance(_TableReduce):
    """«bigdl»/nn/CosineDistance.scala"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        a, b = input
        na = jnp.linalg.norm(a, axis=-1)
        nb = jnp.linalg.norm(b, axis=-1)
        return jnp.sum(a * b, axis=-1) / jnp.maximum(na * nb, 1e-12)


class Concat(Container):
    """«bigdl»/nn/Concat.scala — the DepthConcat-style container used by
    Inception: run children on the same input, concat outputs along a
    1-based dim (channel dim 2 for NCHW batches)."""

    def __init__(self, dimension: int):
        super().__init__()
        self._config = dict(dimension=dimension)
        self.dimension = dimension

    def apply(self, params, state, input, *, training=False, rng=None):
        import jax

        jnp = _jnp()
        outs, new_state = [], {}
        for i, m in enumerate(self.modules):
            r = None if rng is None else jax.random.fold_in(rng, i)
            y, s = m.apply(
                params[str(i)], state[str(i)], input, training=training, rng=r
            )
            outs.append(y)
            new_state[str(i)] = s
        return jnp.concatenate(outs, axis=self.dimension - 1), new_state

    def __repr__(self):
        body = " | ".join(repr(m) for m in self.modules)
        return f"Concat(dim={self.dimension}: {body})"


class CAveTable(_TableReduce):
    """⟦«bigdl»/nn/CAveTable.scala⟧ — elementwise average of the table."""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        total = input[0]
        for x in input[1:]:
            total = total + x
        return total / len(input)


class SplitTable(_TableReduce):
    """⟦«bigdl»/nn/SplitTable.scala⟧ — split a tensor along 1-based
    ``dimension`` into a table of slices (``n_input_dims`` enables the
    reference's unbatched-input promotion)."""

    def __init__(self, dimension: int, n_input_dims: int = -1):
        super().__init__(dimension=dimension, n_input_dims=n_input_dims)
        self.dimension = dimension
        self.n_input_dims = n_input_dims

    def update_output_pure(self, params, input, *, training=False, rng=None):
        if self.dimension > 0:
            d = self.dimension - 1
            # batch promotion shifts positive (1-based, unbatched) dims
            # only; negative dims already count from the end
            if self.n_input_dims > 0 and input.ndim > self.n_input_dims:
                d += 1
        else:
            d = input.ndim + self.dimension
        jnp = _jnp()
        return tuple(
            jnp.squeeze(s, axis=d)
            for s in jnp.split(input, input.shape[d], axis=d)
        )


class BifurcateSplitTable(_TableReduce):
    """⟦«bigdl»/nn/BifurcateSplitTable.scala⟧ — halve a tensor along
    1-based ``dimension`` into a 2-entry table."""

    def __init__(self, dimension: int):
        super().__init__(dimension=dimension)
        self.dimension = dimension

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        d = self.dimension - 1
        left, right = jnp.split(input, 2, axis=d)
        return (left, right)


class NarrowTable(_TableReduce):
    """⟦«bigdl»/nn/NarrowTable.scala⟧ — table slice: ``length`` entries
    from 1-based ``offset`` (length −1 = through the end)."""

    def __init__(self, offset: int, length: int = 1):
        super().__init__(offset=offset, length=length)
        self.offset, self.length = offset, length

    def update_output_pure(self, params, input, *, training=False, rng=None):
        start = self.offset - 1
        if self.length == -1:
            return tuple(input[start:])
        return tuple(input[start:start + self.length])


class Pack(_TableReduce):
    """⟦«bigdl»/nn/Pack.scala⟧ — stack the table's tensors along a new
    1-based ``dim``."""

    def __init__(self, dim: int = 1):
        super().__init__(dim=dim)
        self.dim = dim

    def update_output_pure(self, params, input, *, training=False, rng=None):
        xs = input if isinstance(input, (tuple, list)) else (input,)
        return _jnp().stack(list(xs), axis=self.dim - 1)


class MixtureTable(_TableReduce):
    """⟦«bigdl»/nn/MixtureTable.scala⟧ — mixture-of-experts blend:
    input is (gater (B, K), experts), experts either a table of K
    (B, ...) tensors or one (B, K, ...) tensor; output is the
    gater-weighted sum of experts."""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        gater, experts = input
        if isinstance(experts, (tuple, list)):
            experts = jnp.stack(list(experts), axis=1)   # (B, K, ...)
        g = gater.reshape(gater.shape + (1,) * (experts.ndim - 2))
        return jnp.sum(g * experts, axis=1)


class MapTable(Container):
    """⟦«bigdl»/nn/MapTable.scala⟧ — apply ONE shared child module to
    every entry of the input table (weights shared across entries, like
    the reference's clone-with-shared-parameters)."""

    def __init__(self, module: AbstractModule = None):
        super().__init__()
        if module is not None:
            self.add(module)

    def add(self, module: AbstractModule):
        if len(self.modules) > 0:
            raise ValueError("MapTable takes exactly one module")
        return super().add(module)

    def apply(self, params, state, input, *, training=False, rng=None):
        import jax

        m = self.modules[0]
        outs = []
        s = state["0"]
        for i, x in enumerate(input):
            r = None if rng is None else jax.random.fold_in(rng, i)
            y, s = m.apply(params["0"], s, x, training=training, rng=r)
            outs.append(y)
        return tuple(outs), {"0": s}


class Bottle(Container):
    """⟦«bigdl»/nn/Bottle.scala⟧ — fold the leading ``n_input_dim``
    dims into one batch dim, apply the child, unfold.  The reference's
    trick for running a 2-D layer over N-D input."""

    def __init__(self, module: AbstractModule = None, n_input_dim: int = 2,
                 n_output_dim: int = 2):
        super().__init__()
        self._config = dict(n_input_dim=n_input_dim,
                            n_output_dim=n_output_dim)
        self.n_input_dim = n_input_dim
        self.n_output_dim = n_output_dim
        if module is not None:
            self.add(module)

    def add(self, module: AbstractModule):
        if len(self.modules) > 0:
            raise ValueError("Bottle takes exactly one module")
        return super().add(module)

    def apply(self, params, state, input, *, training=False, rng=None):
        lead = input.shape[: input.ndim - self.n_input_dim + 1]
        n = 1
        for s in lead:
            n *= s
        merged = input.reshape((n,) + input.shape[input.ndim
                                                  - self.n_input_dim + 1:])
        y, s = self.modules[0].apply(
            params["0"], state["0"], merged, training=training, rng=rng
        )
        if y.ndim != self.n_output_dim:
            raise ValueError(
                f"Bottle: child produced a rank-{y.ndim} output but "
                f"n_output_dim={self.n_output_dim}"
            )
        out = y.reshape(lead + y.shape[1:])
        return out, {"0": s}
