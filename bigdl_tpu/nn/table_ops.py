"""Table (multi-tensor) containers and ops.

Rebuild of the reference's Table-valued layers («bigdl»/nn/ConcatTable.scala,
CAddTable.scala, JoinTable.scala, Concat.scala...).  The reference's
``Table`` activity type maps to Python tuples/lists of arrays, which are
ordinary pytrees — so ``jax.vjp`` differentiates through them for free.
"""

from __future__ import annotations

from typing import Sequence

from bigdl_tpu.nn.module import AbstractModule, Container


def _jnp():
    import jax.numpy as jnp

    return jnp


class ConcatTable(Container):
    """«bigdl»/nn/ConcatTable.scala — apply each child to the same input,
    return the table of outputs."""

    def apply(self, params, state, input, *, training=False, rng=None):
        import jax

        outs, new_state = [], {}
        for i, m in enumerate(self.modules):
            r = None if rng is None else jax.random.fold_in(rng, i)
            y, s = m.apply(
                params[str(i)], state[str(i)], input, training=training, rng=r
            )
            outs.append(y)
            new_state[str(i)] = s
        return tuple(outs), new_state


class ParallelTable(Container):
    """«bigdl»/nn/ParallelTable.scala — i-th child gets i-th table entry."""

    def apply(self, params, state, input, *, training=False, rng=None):
        import jax

        outs, new_state = [], {}
        for i, m in enumerate(self.modules):
            r = None if rng is None else jax.random.fold_in(rng, i)
            y, s = m.apply(
                params[str(i)], state[str(i)], input[i], training=training, rng=r
            )
            outs.append(y)
            new_state[str(i)] = s
        return tuple(outs), new_state


class _TableReduce(AbstractModule):
    def __init__(self, **config):
        super().__init__()
        self._config = config


class CAddTable(_TableReduce):
    """«bigdl»/nn/CAddTable.scala — elementwise sum of a table."""

    def __init__(self, inplace: bool = False):
        super().__init__()

    def update_output_pure(self, params, input, *, training=False, rng=None):
        y = input[0]
        for t in input[1:]:
            y = y + t
        return y


class CSubTable(_TableReduce):
    """«bigdl»/nn/CSubTable.scala"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return input[0] - input[1]


class CMulTable(_TableReduce):
    """«bigdl»/nn/CMulTable.scala"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        y = input[0]
        for t in input[1:]:
            y = y * t
        return y


class CDivTable(_TableReduce):
    """«bigdl»/nn/CDivTable.scala"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return input[0] / input[1]


class CMaxTable(_TableReduce):
    """«bigdl»/nn/CMaxTable.scala"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        y = input[0]
        for t in input[1:]:
            y = jnp.maximum(y, t)
        return y


class CMinTable(_TableReduce):
    """«bigdl»/nn/CMinTable.scala"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        y = input[0]
        for t in input[1:]:
            y = jnp.minimum(y, t)
        return y


class JoinTable(_TableReduce):
    """«bigdl»/nn/JoinTable.scala — concat a table along 1-based dim;
    n_input_dims handles the batch-dim shift like the reference."""

    def __init__(self, dimension: int, n_input_dims: int = -1):
        super().__init__(dimension=dimension, n_input_dims=n_input_dims)
        self.dimension = dimension
        self.n_input_dims = n_input_dims

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        d = self.dimension - 1
        if self.n_input_dims > 0 and input[0].ndim > self.n_input_dims:
            d += 1
        return jnp.concatenate(list(input), axis=d)


class SelectTable(_TableReduce):
    """«bigdl»/nn/SelectTable.scala — pick 1-based entry of a table."""

    def __init__(self, index: int):
        super().__init__(index=index)
        self.index = index

    def update_output_pure(self, params, input, *, training=False, rng=None):
        i = self.index - 1 if self.index > 0 else self.index
        return input[i]


class FlattenTable(_TableReduce):
    """«bigdl»/nn/FlattenTable.scala — flatten nested tables."""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        out = []

        def rec(t):
            if isinstance(t, (tuple, list)):
                for u in t:
                    rec(u)
            else:
                out.append(t)

        rec(input)
        return tuple(out)


class MM(_TableReduce):
    """«bigdl»/nn/MM.scala — batched matmul of a 2-table, with transpose
    flags."""

    def __init__(self, trans_a: bool = False, trans_b: bool = False):
        super().__init__(trans_a=trans_a, trans_b=trans_b)
        self.trans_a, self.trans_b = trans_a, trans_b

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        a, b = input
        if self.trans_a:
            a = jnp.swapaxes(a, -1, -2)
        if self.trans_b:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)


class MV(_TableReduce):
    """«bigdl»/nn/MV.scala — (batched) matrix-vector product."""

    def __init__(self, trans: bool = False):
        super().__init__(trans=trans)
        self.trans = trans

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        m, v = input
        if self.trans:
            m = jnp.swapaxes(m, -1, -2)
        return jnp.einsum("...ij,...j->...i", m, v)


class DotProduct(_TableReduce):
    """«bigdl»/nn/DotProduct.scala"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        a, b = input
        return jnp.sum(a * b, axis=-1)


class CosineDistance(_TableReduce):
    """«bigdl»/nn/CosineDistance.scala"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        a, b = input
        na = jnp.linalg.norm(a, axis=-1)
        nb = jnp.linalg.norm(b, axis=-1)
        return jnp.sum(a * b, axis=-1) / jnp.maximum(na * nb, 1e-12)


class Concat(Container):
    """«bigdl»/nn/Concat.scala — the DepthConcat-style container used by
    Inception: run children on the same input, concat outputs along a
    1-based dim (channel dim 2 for NCHW batches)."""

    def __init__(self, dimension: int):
        super().__init__()
        self._config = dict(dimension=dimension)
        self.dimension = dimension

    def apply(self, params, state, input, *, training=False, rng=None):
        import jax

        jnp = _jnp()
        outs, new_state = [], {}
        for i, m in enumerate(self.modules):
            r = None if rng is None else jax.random.fold_in(rng, i)
            y, s = m.apply(
                params[str(i)], state[str(i)], input, training=training, rng=r
            )
            outs.append(y)
            new_state[str(i)] = s
        return jnp.concatenate(outs, axis=self.dimension - 1), new_state

    def __repr__(self):
        body = " | ".join(repr(m) for m in self.modules)
        return f"Concat(dim={self.dimension}: {body})"
