"""Volumetric (3-D) layer family.

Rebuild of the reference's 3-D modules (SURVEY.md §2.1 "Layer library",
⟦«bigdl»/nn/VolumetricConvolution.scala⟧, ⟦VolumetricFullConvolution.scala⟧,
⟦VolumetricMaxPooling.scala⟧, ⟦VolumetricAveragePooling.scala⟧,
⟦UpSampling3D.scala⟧, ⟦Cropping3D.scala⟧).  Input layout is NCDHW
(batch, plane, time/depth, height, width), matching the reference's
time-first convention; the reference's width-first argument order
(kT, kW, kH, dT, dW, dH, padT, padW, padH) is kept.

TPU notes: 3-D convs lower to one ``lax.conv_general_dilated`` with a
3-long spatial spec — XLA tiles the contraction onto the MXU the same way
it does 2-D convs; pooling is ``lax.reduce_window`` over three window
dims.  No im2col / MKL path to port (SURVEY.md §2.3).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from bigdl_tpu.nn.layers import (
    BatchNormalization,
    InitializationMethod,
    MsraFiller,
    _auto_batch,
    _pool_pad,
    _to_device,
)
from bigdl_tpu.nn.module import AbstractModule


def _jnp():
    import jax.numpy as jnp

    return jnp


def _lax():
    import jax.lax as lax

    return lax


_DNUMS = ("NCDHW", "OIDHW", "NCDHW")  # lax conv dimension_numbers for 3-D


class VolumetricConvolution(AbstractModule):
    """⟦«bigdl»/nn/VolumetricConvolution.scala⟧ — 3-D conv over NCDHW.

    Reference arg order (nInputPlane, nOutputPlane, kT, kW, kH, dT, dW,
    dH, padT, padW, padH) is kept; weight is laid out OIDHW so the kernel
    maps straight onto ``lax.conv_general_dilated``.
    """

    param_names = ("weight", "bias")

    def __init__(
        self,
        n_input_plane: int,
        n_output_plane: int,
        k_t: int,
        k_w: int,
        k_h: int,
        d_t: int = 1,
        d_w: int = 1,
        d_h: int = 1,
        pad_t: int = 0,
        pad_w: int = 0,
        pad_h: int = 0,
        with_bias: bool = True,
        init_method: Optional[InitializationMethod] = None,
    ):
        super().__init__()
        self._config = dict(
            n_input_plane=n_input_plane, n_output_plane=n_output_plane,
            k_t=k_t, k_w=k_w, k_h=k_h, d_t=d_t, d_w=d_w, d_h=d_h,
            pad_t=pad_t, pad_w=pad_w, pad_h=pad_h, with_bias=with_bias,
        )
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.k_t, self.k_w, self.k_h = k_t, k_w, k_h
        self.d_t, self.d_w, self.d_h = d_t, d_w, d_h
        self.pad_t, self.pad_w, self.pad_h = pad_t, pad_w, pad_h
        self.with_bias = with_bias
        self._init_method = init_method or MsraFiller(False)
        self.weight = None
        self.bias = None
        self.reset()

    def reset(self):
        k_vol = self.k_t * self.k_h * self.k_w
        fan_in = self.n_input_plane * k_vol
        fan_out = self.n_output_plane * k_vol
        w = self._init_method.init(
            (self.n_output_plane, self.n_input_plane,
             self.k_t, self.k_h, self.k_w),
            fan_in,
            fan_out,
        )
        self.weight = _to_device(w)
        if self.with_bias:
            self.bias = _to_device(
                np.zeros(self.n_output_plane, dtype=np.float32)
            )
        return self

    def _pads(self):
        if -1 in (self.pad_t, self.pad_h, self.pad_w):
            return "SAME"
        return [
            (self.pad_t, self.pad_t),
            (self.pad_h, self.pad_h),
            (self.pad_w, self.pad_w),
        ]

    def update_output_pure(self, params, input, *, training=False, rng=None):
        lax = _lax()
        x, squeezed = _auto_batch(input, 5)
        y = lax.conv_general_dilated(
            x,
            params["weight"].astype(x.dtype),
            window_strides=(self.d_t, self.d_h, self.d_w),
            padding=self._pads(),
            dimension_numbers=_DNUMS,
        )
        if self.with_bias:
            y = y + params["bias"].astype(y.dtype).reshape(1, -1, 1, 1, 1)
        return y[0] if squeezed else y

    def __repr__(self):
        return (
            f"VolumetricConvolution({self.n_input_plane}->"
            f"{self.n_output_plane}, {self.k_t}x{self.k_h}x{self.k_w})"
        )


class VolumetricFullConvolution(VolumetricConvolution):
    """⟦«bigdl»/nn/VolumetricFullConvolution.scala⟧ — transposed 3-D conv
    (the gradient of VolumetricConvolution w.r.t. its input), plus the
    reference's ``adjT/adjW/adjH`` extra output padding."""

    def __init__(
        self,
        n_input_plane: int,
        n_output_plane: int,
        k_t: int,
        k_w: int,
        k_h: int,
        d_t: int = 1,
        d_w: int = 1,
        d_h: int = 1,
        pad_t: int = 0,
        pad_w: int = 0,
        pad_h: int = 0,
        adj_t: int = 0,
        adj_w: int = 0,
        adj_h: int = 0,
        with_bias: bool = True,
        init_method: Optional[InitializationMethod] = None,
    ):
        super().__init__(
            n_input_plane, n_output_plane, k_t, k_w, k_h, d_t, d_w, d_h,
            pad_t, pad_w, pad_h, with_bias, init_method,
        )
        self.adj_t, self.adj_w, self.adj_h = adj_t, adj_w, adj_h
        self._config.update(adj_t=adj_t, adj_w=adj_w, adj_h=adj_h)

    def reset(self):
        # transposed conv weight: (in, out, kT, kH, kW) — IODHW
        k_vol = self.k_t * self.k_h * self.k_w
        fan_in = self.n_input_plane * k_vol
        fan_out = self.n_output_plane * k_vol
        w = self._init_method.init(
            (self.n_input_plane, self.n_output_plane,
             self.k_t, self.k_h, self.k_w),
            fan_in,
            fan_out,
        )
        self.weight = _to_device(w)
        if self.with_bias:
            self.bias = _to_device(
                np.zeros(self.n_output_plane, dtype=np.float32)
            )
        return self

    def update_output_pure(self, params, input, *, training=False, rng=None):
        lax = _lax()
        x, squeezed = _auto_batch(input, 5)
        # lhs-dilated conv == transposed conv; padding k-1-p (+adj on hi)
        pads = [
            (self.k_t - 1 - self.pad_t, self.k_t - 1 - self.pad_t + self.adj_t),
            (self.k_h - 1 - self.pad_h, self.k_h - 1 - self.pad_h + self.adj_h),
            (self.k_w - 1 - self.pad_w, self.k_w - 1 - self.pad_w + self.adj_w),
        ]
        jnp = _jnp()
        w = params["weight"].astype(x.dtype)
        # IODHW -> OIDHW with spatially flipped kernel
        w = jnp.flip(w.transpose(1, 0, 2, 3, 4), axis=(2, 3, 4))
        y = lax.conv_general_dilated(
            x,
            w,
            window_strides=(1, 1, 1),
            padding=pads,
            lhs_dilation=(self.d_t, self.d_h, self.d_w),
            dimension_numbers=_DNUMS,
        )
        if self.with_bias:
            y = y + params["bias"].astype(y.dtype).reshape(1, -1, 1, 1, 1)
        return y[0] if squeezed else y

    def __repr__(self):
        return (
            f"VolumetricFullConvolution({self.n_input_plane}->"
            f"{self.n_output_plane}, {self.k_t}x{self.k_h}x{self.k_w})"
        )


class VolumetricMaxPooling(AbstractModule):
    """⟦«bigdl»/nn/VolumetricMaxPooling.scala⟧ — NCDHW max pooling with
    the reference's floor/ceil output-size convention."""

    def __init__(self, k_t, k_w=None, k_h=None, d_t=None, d_w=None, d_h=None,
                 pad_t=0, pad_w=0, pad_h=0, ceil_mode=False):
        super().__init__()
        self.k_t = k_t
        self.k_w = k_w if k_w is not None else k_t
        self.k_h = k_h if k_h is not None else k_t
        self.d_t = d_t if d_t is not None else self.k_t
        self.d_w = d_w if d_w is not None else self.k_w
        self.d_h = d_h if d_h is not None else self.k_h
        self.pad_t, self.pad_w, self.pad_h = pad_t, pad_w, pad_h
        self.ceil_mode = ceil_mode
        self._config = dict(
            k_t=self.k_t, k_w=self.k_w, k_h=self.k_h,
            d_t=self.d_t, d_w=self.d_w, d_h=self.d_h,
            pad_t=pad_t, pad_w=pad_w, pad_h=pad_h, ceil_mode=ceil_mode,
        )

    def ceil(self):
        self.ceil_mode = True
        self._config["ceil_mode"] = True
        return self

    def update_output_pure(self, params, input, *, training=False, rng=None):
        lax = _lax()
        jnp = _jnp()
        x, squeezed = _auto_batch(input, 5)
        t, h, w = x.shape[2], x.shape[3], x.shape[4]
        _, pt = _pool_pad(t, self.k_t, self.d_t, self.pad_t, self.ceil_mode)
        _, ph = _pool_pad(h, self.k_h, self.d_h, self.pad_h, self.ceil_mode)
        _, pw = _pool_pad(w, self.k_w, self.d_w, self.pad_w, self.ceil_mode)
        y = lax.reduce_window(
            x,
            -jnp.inf,
            lax.max,
            window_dimensions=(1, 1, self.k_t, self.k_h, self.k_w),
            window_strides=(1, 1, self.d_t, self.d_h, self.d_w),
            padding=[(0, 0), (0, 0), pt, ph, pw],
        )
        return y[0] if squeezed else y

    def __repr__(self):
        return f"VolumetricMaxPooling({self.k_t}x{self.k_h}x{self.k_w})"


class VolumetricAveragePooling(AbstractModule):
    """⟦«bigdl»/nn/VolumetricAveragePooling.scala⟧ — NCDHW average
    pooling (countIncludePad=true default like the 2-D layer)."""

    def __init__(self, k_t, k_w=None, k_h=None, d_t=None, d_w=None, d_h=None,
                 pad_t=0, pad_w=0, pad_h=0, count_include_pad=True,
                 ceil_mode=False):
        super().__init__()
        self.k_t = k_t
        self.k_w = k_w if k_w is not None else k_t
        self.k_h = k_h if k_h is not None else k_t
        self.d_t = d_t if d_t is not None else self.k_t
        self.d_w = d_w if d_w is not None else self.k_w
        self.d_h = d_h if d_h is not None else self.k_h
        self.pad_t, self.pad_w, self.pad_h = pad_t, pad_w, pad_h
        self.count_include_pad = count_include_pad
        self.ceil_mode = ceil_mode
        self._config = dict(
            k_t=self.k_t, k_w=self.k_w, k_h=self.k_h,
            d_t=self.d_t, d_w=self.d_w, d_h=self.d_h,
            pad_t=pad_t, pad_w=pad_w, pad_h=pad_h,
            count_include_pad=count_include_pad, ceil_mode=ceil_mode,
        )

    def ceil(self):
        self.ceil_mode = True
        self._config["ceil_mode"] = True
        return self

    def update_output_pure(self, params, input, *, training=False, rng=None):
        lax = _lax()
        jnp = _jnp()
        x, squeezed = _auto_batch(input, 5)
        t, h, w = x.shape[2], x.shape[3], x.shape[4]
        _, pt = _pool_pad(t, self.k_t, self.d_t, self.pad_t, self.ceil_mode)
        _, ph = _pool_pad(h, self.k_h, self.d_h, self.pad_h, self.ceil_mode)
        _, pw = _pool_pad(w, self.k_w, self.d_w, self.pad_w, self.ceil_mode)
        dims = (1, 1, self.k_t, self.k_h, self.k_w)
        strides = (1, 1, self.d_t, self.d_h, self.d_w)
        pads = [(0, 0), (0, 0), pt, ph, pw]
        summed = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
        if self.count_include_pad:
            y = summed / (self.k_t * self.k_h * self.k_w)
        else:
            counts = lax.reduce_window(
                jnp.ones_like(x), 0.0, lax.add, dims, strides, pads
            )
            y = summed / counts
        return y[0] if squeezed else y

    def __repr__(self):
        return f"VolumetricAveragePooling({self.k_t}x{self.k_h}x{self.k_w})"


class VolumetricBatchNormalization(BatchNormalization):
    """3-D BN over NCDHW — per-channel statistics (the volumetric member
    of the reference's BN family, SURVEY.md §2.1 "Layer library")."""

    _feature_ndim = 5

    def _axes_and_shape(self, input):
        if input.ndim == 5:
            return (0, 2, 3, 4), (1, self.n_output, 1, 1, 1)
        raise ValueError(
            f"VolumetricBatchNormalization expects 5-d input, got "
            f"{input.ndim}-d"
        )


class UpSampling3D(AbstractModule):
    """⟦«bigdl»/nn/UpSampling3D.scala⟧ — nearest-neighbour repeat of the
    three spatial dims of an NCDHW tensor by ``size=(sT, sH, sW)``."""

    def __init__(self, size=(2, 2, 2)):
        super().__init__()
        self.size = tuple(size)
        self._config = dict(size=list(self.size))

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        x, squeezed = _auto_batch(input, 5)
        st, sh, sw = self.size
        y = jnp.repeat(jnp.repeat(jnp.repeat(x, st, 2), sh, 3), sw, 4)
        return y[0] if squeezed else y

    def __repr__(self):
        return f"UpSampling3D({self.size})"


class Cropping3D(AbstractModule):
    """⟦«bigdl»/nn/Cropping3D.scala⟧ — crop (lo, hi) cells from each of
    the three spatial dims of an NCDHW tensor."""

    def __init__(self, dim1_crop=(1, 1), dim2_crop=(1, 1), dim3_crop=(1, 1)):
        super().__init__()
        self.dim1_crop = tuple(dim1_crop)
        self.dim2_crop = tuple(dim2_crop)
        self.dim3_crop = tuple(dim3_crop)
        self._config = dict(
            dim1_crop=list(self.dim1_crop),
            dim2_crop=list(self.dim2_crop),
            dim3_crop=list(self.dim3_crop),
        )

    def update_output_pure(self, params, input, *, training=False, rng=None):
        x, squeezed = _auto_batch(input, 5)
        (t0, t1), (h0, h1), (w0, w1) = (
            self.dim1_crop, self.dim2_crop, self.dim3_crop
        )
        y = x[
            :, :,
            t0: x.shape[2] - t1 or None,
            h0: x.shape[3] - h1 or None,
            w0: x.shape[4] - w1 or None,
        ]
        return y[0] if squeezed else y

    def __repr__(self):
        return (
            f"Cropping3D({self.dim1_crop}, {self.dim2_crop}, "
            f"{self.dim3_crop})"
        )


__all__ = [
    "VolumetricConvolution",
    "VolumetricFullConvolution",
    "VolumetricMaxPooling",
    "VolumetricAveragePooling",
    "VolumetricBatchNormalization",
    "UpSampling3D",
    "Cropping3D",
]
