"""Attention / Transformer layers — the long-context stack.

The reference framework has **no attention anywhere** (SURVEY.md §5:
sequence handling is `Recurrent`'s per-timestep loop; long-context is
explicitly absent).  These layers are the rebuild's new capability,
designed TPU-first:

* the hot op is ``bigdl_tpu.ops.dot_product_attention`` (measured
  ``auto`` policy: lax reference until the long-context regime, the
  Pallas flash kernel at T >= 4096 on TPU — see ops/attention.py);
* all shapes are static, heads are a batch dimension for the MXU;
* the sequence axis is left shardable: ``MultiHeadAttention`` accepts an
  ``attn_impl`` override so ``parallel.ring_attention`` can slot in a
  sequence-parallel implementation without touching the layer
  (parallel/ring_attention.py).

They keep the framework's module contract (params()/apply()) so they
serialize, gradcheck, and compose with Sequential/Graph like every other
layer.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

from bigdl_tpu.nn.module import AbstractModule
from bigdl_tpu.nn.layers import Xavier, _to_device


def _jnp():
    import jax.numpy as jnp

    return jnp


class LayerNorm(AbstractModule):
    """Layer normalization over the last dimension (new capability; the
    reference's closest analogue is Normalize, «bigdl»/nn/Normalize.scala).
    """

    param_names = ("weight", "bias")

    def __init__(self, n_output: int, eps: float = 1e-5):
        super().__init__()
        self._config = dict(n_output=n_output, eps=eps)
        self.n_output = n_output
        self.eps = eps
        self.reset()

    def reset(self):
        self.weight = _to_device(np.ones(self.n_output, np.float32))
        self.bias = _to_device(np.zeros(self.n_output, np.float32))
        return self

    def update_output_pure(self, params, input, *, training=False, rng=None):
        import jax

        jnp = _jnp()
        x32 = input.astype(jnp.float32)
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
        y = (x32 - mean) * jax.lax.rsqrt(var + self.eps)
        return (y * params["weight"] + params["bias"]).astype(input.dtype)

    def __repr__(self):
        return f"LayerNorm({self.n_output})"


class MultiHeadAttention(AbstractModule):
    """Multi-head self/cross attention.

    Input (batch, seq, dim) -> output (batch, seq, dim).  Projections are
    single fused matmuls (one MXU call each); head split/merge are free
    reshapes.  ``attn_impl`` picks the inner kernel ("auto" is the
    measured policy in ops/attention.py: lax below T=4096, Pallas
    flash in the long-context regime on TPU).
    """

    param_names = ("wq", "wk", "wv", "wo", "bq", "bk", "bv", "bo")

    def __init__(self, dim: int, n_head: int, causal: bool = False,
                 with_bias: bool = True, attn_impl: str = "auto",
                 dropout: float = 0.0):
        super().__init__()
        if dim % n_head:
            raise ValueError(f"dim {dim} not divisible by n_head {n_head}")
        self._config = dict(dim=dim, n_head=n_head, causal=causal,
                            with_bias=with_bias, dropout=dropout,
                            attn_impl=attn_impl)
        self.dim = dim
        self.n_head = n_head
        self.head_dim = dim // n_head
        self.causal = causal
        self.with_bias = with_bias
        self.attn_impl = attn_impl
        self.dropout = dropout
        self._init_method = Xavier()
        self.reset()

    def reset(self):
        d = self.dim
        for name in ("wq", "wk", "wv", "wo"):
            setattr(self, name, _to_device(self._init_method.init((d, d), d, d)))
        for name in ("bq", "bk", "bv", "bo"):
            setattr(
                self, name,
                _to_device(np.zeros(d, np.float32)) if self.with_bias else None,
            )
        return self

    def _split(self, x):
        b, t, _ = x.shape
        return x.reshape(b, t, self.n_head, self.head_dim).transpose(0, 2, 1, 3)

    def _inner_attention(self, q, k, v):
        """softmax(QKᵀ)V on (B, H, T, D) heads — the override seam for
        parallel.RingMultiHeadAttention and other attention variants."""
        from bigdl_tpu.ops import dot_product_attention

        return dot_product_attention(q, k, v, causal=self.causal,
                                     impl=self.attn_impl)

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        x = input
        q = jnp.matmul(x, params["wq"].T)
        k = jnp.matmul(x, params["wk"].T)
        v = jnp.matmul(x, params["wv"].T)
        if self.with_bias:
            q, k, v = q + params["bq"], k + params["bk"], v + params["bv"]
        q, k, v = self._split(q), self._split(k), self._split(v)
        o = self._inner_attention(q, k, v)
        b, h, t, hd = o.shape
        o = o.transpose(0, 2, 1, 3).reshape(b, t, h * hd)
        if training and self.dropout > 0 and rng is not None:
            import jax

            keep = 1.0 - self.dropout
            mask = jax.random.bernoulli(rng, keep, o.shape)
            o = jnp.where(mask, o / keep, 0.0)
        y = jnp.matmul(o, params["wo"].T)
        if self.with_bias:
            y = y + params["bo"]
        return y

    def __repr__(self):
        return (f"MultiHeadAttention(dim={self.dim}, heads={self.n_head},"
                f" causal={self.causal})")


class _Composite(AbstractModule):
    """Module built from named children; params/state nest by child name."""

    def __init__(self):
        super().__init__()
        self._children: dict[str, AbstractModule] = {}

    def _add_child(self, name: str, module: AbstractModule):
        self._children[name] = module
        return module

    def params(self):
        return {n: m.params() for n, m in self._children.items()}

    def set_params(self, params):
        for n, m in self._children.items():
            m.set_params(params.get(n, {}))

    def state(self):
        return {n: m.state() for n, m in self._children.items()}

    def set_state(self, state):
        for n, m in self._children.items():
            m.set_state(state.get(n, {}))

    def _ordered_params(self):
        out = []
        for m in self._children.values():
            out.extend(m._ordered_params())
        return out

    def reset(self):
        for m in self._children.values():
            m.reset()
        return self

    def regularization_loss(self, params):
        loss = super().regularization_loss(params)
        for n, m in self._children.items():
            loss = loss + m.regularization_loss(params.get(n, {}))
        return loss

    def training(self):
        super().training()
        for m in self._children.values():
            m.training()
        return self

    def evaluate(self, dataset=None, methods=None, batch_size: int = 32):
        for m in self._children.values():
            m.evaluate()
        return super().evaluate(dataset, methods, batch_size)


class TransformerBlock(_Composite):
    """Pre-LN transformer block: x + MHA(LN(x)); x + MLP(LN(x)).

    The MLP hidden is ``mlp_ratio * dim`` with GELU — all MXU-friendly
    big matmuls that XLA fuses with the residual adds.
    """

    def __init__(self, dim: int, n_head: int, mlp_ratio: int = 4,
                 causal: bool = True, attn_impl: str = "auto",
                 dropout: float = 0.0):
        super().__init__()
        from bigdl_tpu.nn.layers import Linear

        self._config = dict(dim=dim, n_head=n_head, mlp_ratio=mlp_ratio,
                            causal=causal, dropout=dropout,
                            attn_impl=attn_impl)
        self.dim = dim
        self._add_child("ln1", LayerNorm(dim))
        self._add_child("attn", MultiHeadAttention(
            dim, n_head, causal=causal, attn_impl=attn_impl, dropout=dropout))
        self._add_child("ln2", LayerNorm(dim))
        self._add_child("fc1", Linear(dim, mlp_ratio * dim))
        self._add_child("fc2", Linear(mlp_ratio * dim, dim))

    def apply(self, params, state, input, *, training=False, rng=None):
        c = self._children
        h, _ = c["ln1"].apply(params["ln1"], {}, input)
        a, _ = c["attn"].apply(params["attn"], {}, h, training=training, rng=rng)
        x = input + a
        return self._mlp(params, x), state

    def _mlp(self, params, x):
        """Shared pre-LN MLP half — used by apply, prefill and
        decode_step so the three paths cannot drift apart."""
        import jax

        c = self._children
        h, _ = c["ln2"].apply(params["ln2"], {}, x)
        h, _ = c["fc1"].apply(params["fc1"], {}, h)
        h = jax.nn.gelu(h)
        h, _ = c["fc2"].apply(params["fc2"], {}, h)
        return x + h

    def _project_qkv(self, pa, h):
        jnp = _jnp()
        q = jnp.matmul(h, pa["wq"].T)
        k = jnp.matmul(h, pa["wk"].T)
        v = jnp.matmul(h, pa["wv"].T)
        if pa.get("bq") is not None:
            q, k, v = q + pa["bq"], k + pa["bk"], v + pa["bv"]
        return q, k, v

    def _out_proj(self, pa, o):
        jnp = _jnp()
        y = jnp.matmul(o, pa["wo"].T)
        if pa.get("bo") is not None:
            y = y + pa["bo"]
        return y

    def prefill(self, params, x):
        """Full-prefix block forward that ALSO returns the per-head
        K/V (B, H, T, Dh) for a decode cache.  Attention math is the
        identical projection + ``_inner_attention`` path apply() takes
        (dropout off — decoding is inference)."""
        attn = self._children["attn"]
        h, _ = self._children["ln1"].apply(params["ln1"], {}, x)
        q, k, v = self._project_qkv(params["attn"], h)
        qh, kh, vh = attn._split(q), attn._split(k), attn._split(v)
        o = attn._inner_attention(qh, kh, vh)
        b, nh, t, hd = o.shape
        o = o.transpose(0, 2, 1, 3).reshape(b, t, nh * hd)
        x = x + self._out_proj(params["attn"], o)
        return self._mlp(params, x), kh, vh

    def decode_step(self, params, x, cache_k, cache_v, t):
        """One-token decode: ``x`` is (B, 1, dim), caches are
        (B, H, T_total, Dh) buffers updated in place at position ``t``
        (static shapes; the single query attends over positions <= t).
        Returns (out, cache_k, cache_v)."""
        import jax
        from jax import lax

        jnp = _jnp()
        attn = self._children["attn"]
        h, _ = self._children["ln1"].apply(params["ln1"], {}, x)
        q, k, v = self._project_qkv(params["attn"], h)
        qh = attn._split(q)
        # the caches may be narrower than the activations (bf16 K/V on
        # an f32 model — generate()'s cache_dtype); cast on write
        cache_k = lax.dynamic_update_slice(
            cache_k, attn._split(k).astype(cache_k.dtype), (0, 0, t, 0))
        cache_v = lax.dynamic_update_slice(
            cache_v, attn._split(v).astype(cache_v.dtype), (0, 0, t, 0))
        scale = 1.0 / float(np.sqrt(attn.head_dim))
        scores = jnp.einsum("bhqd,bhkd->bhqk", qh, cache_k) * scale
        mask = (jnp.arange(cache_k.shape[2]) <= t)[None, None, None, :]
        scores = jnp.where(mask, scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", probs, cache_v)
        b, nh, _, hd = o.shape
        o = o.transpose(0, 2, 1, 3).reshape(b, 1, nh * hd)
        x = x + self._out_proj(params["attn"], o)
        return self._mlp(params, x), cache_k, cache_v

    def __repr__(self):
        return f"TransformerBlock(dim={self.dim})"


class PositionalEmbedding(AbstractModule):
    """Learned absolute positional embedding added to (B, T, D) input."""

    param_names = ("weight",)

    def __init__(self, max_len: int, dim: int):
        super().__init__()
        self._config = dict(max_len=max_len, dim=dim)
        self.max_len = max_len
        self.dim = dim
        self.reset()

    def reset(self):
        from bigdl_tpu.common import RandomGenerator

        self.weight = _to_device(
            RandomGenerator.RNG.normal(
                0.0, 0.02, size=(self.max_len, self.dim)
            ).astype(np.float32)
        )
        return self

    def update_output_pure(self, params, input, *, training=False, rng=None):
        t = input.shape[1]
        return input + params["weight"][:t][None, :, :]


__all__ = [
    "LayerNorm",
    "MultiHeadAttention",
    "TransformerBlock",
    "PositionalEmbedding",
]
