"""Layer library.

Rebuild of the «bigdl»/nn/ one-file-per-layer library (SURVEY.md §2.1 "Layer
library", ~200-300 layers with hand-derived backwards).  Each class here
implements only the *pure forward* (``update_output_pure`` /  ``apply``);
``updateGradInput``/``accGradParameters`` parity comes from ``jax.vjp`` in
the base class.  Docstrings cite the reference file each layer rebuilds.

TPU notes: convolutions lower to ``lax.conv_general_dilated`` which XLA
tiles onto the MXU; elementwise layers fuse into their producers.  Data
layout follows the reference's NCHW API; XLA's layout assignment re-tiles
for the MXU internally, so no ``MemoryData``/reorder machinery is needed
(SURVEY.md §2.3: the mkldnn layout layer is deleted, not ported).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np

from bigdl_tpu.common import RandomGenerator
from bigdl_tpu.nn.module import AbstractModule


def _jnp():
    import jax.numpy as jnp

    return jnp


def _lax():
    import jax.lax as lax

    return lax


# --------------------------------------------------------------------------
# Initialization methods («bigdl»/nn/InitializationMethod.scala)
# --------------------------------------------------------------------------


class InitializationMethod:
    def init(self, shape, fan_in, fan_out):
        raise NotImplementedError


class Zeros(InitializationMethod):
    def init(self, shape, fan_in, fan_out):
        return np.zeros(shape, dtype=np.float32)


class Ones(InitializationMethod):
    def init(self, shape, fan_in, fan_out):
        return np.ones(shape, dtype=np.float32)


class ConstInitMethod(InitializationMethod):
    def __init__(self, value):
        self.value = value

    def init(self, shape, fan_in, fan_out):
        return np.full(shape, self.value, dtype=np.float32)


class RandomUniform(InitializationMethod):
    """Torch-style default: U(-1/sqrt(fanIn), 1/sqrt(fanIn)) when no bounds
    given («bigdl»/nn/InitializationMethod.scala RandomUniform)."""

    def __init__(self, lower=None, upper=None):
        self.lower, self.upper = lower, upper

    def init(self, shape, fan_in, fan_out):
        if self.lower is None:
            stdv = 1.0 / math.sqrt(max(1, fan_in))
            lo, hi = -stdv, stdv
        else:
            lo, hi = self.lower, self.upper
        return RandomGenerator.RNG.uniform(lo, hi, size=shape).astype(np.float32)


class RandomNormal(InitializationMethod):
    def __init__(self, mean=0.0, stdv=1.0):
        self.mean, self.stdv = mean, stdv

    def init(self, shape, fan_in, fan_out):
        return RandomGenerator.RNG.normal(self.mean, self.stdv, size=shape).astype(
            np.float32
        )


class Xavier(InitializationMethod):
    """Glorot uniform («bigdl»/nn/InitializationMethod.scala Xavier) —
    the reference's default for Linear/SpatialConvolution weights."""

    def init(self, shape, fan_in, fan_out):
        limit = math.sqrt(6.0 / (fan_in + fan_out))
        return RandomGenerator.RNG.uniform(-limit, limit, size=shape).astype(
            np.float32
        )


class MsraFiller(InitializationMethod):
    """Kaiming/He init («bigdl»: MsraFiller, used by the ResNet recipe)."""

    def __init__(self, variance_norm_average=True):
        self.avg = variance_norm_average

    def init(self, shape, fan_in, fan_out):
        n = (fan_in + fan_out) / 2.0 if self.avg else fan_in
        std = math.sqrt(2.0 / max(1.0, n))
        return RandomGenerator.RNG.normal(0.0, std, size=shape).astype(np.float32)


def _to_device(x):
    jnp = _jnp()
    return jnp.asarray(x)


# --------------------------------------------------------------------------
# Dense / embedding
# --------------------------------------------------------------------------


class Linear(AbstractModule):
    """«bigdl»/nn/Linear.scala — y = x W^T + b.

    On TPU this is one MXU matmul; keep batch large and let XLA fuse the
    bias add.
    """

    param_names = ("weight", "bias")

    def __init__(
        self,
        input_size: int,
        output_size: int,
        with_bias: bool = True,
        w_regularizer=None,
        b_regularizer=None,
        init_weight=None,
        init_bias=None,
        init_method: Optional[InitializationMethod] = None,
    ):
        super().__init__()
        self._config = dict(
            input_size=input_size, output_size=output_size, with_bias=with_bias
        )
        self.input_size = input_size
        self.output_size = output_size
        self.with_bias = with_bias
        self._init_method = init_method or Xavier()
        self._regularizers = []
        if w_regularizer is not None:
            self._regularizers.append(("weight", w_regularizer))
        if b_regularizer is not None:
            self._regularizers.append(("bias", b_regularizer))
        self.weight = None
        self.bias = None
        self.reset()
        if init_weight is not None:
            self.weight = _to_device(init_weight)
        if init_bias is not None and with_bias:
            self.bias = _to_device(init_bias)

    def reset(self):
        w = self._init_method.init(
            (self.output_size, self.input_size), self.input_size, self.output_size
        )
        self.weight = _to_device(w)
        if self.with_bias:
            self.bias = _to_device(np.zeros(self.output_size, dtype=np.float32))
        return self

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        y = jnp.matmul(input, params["weight"].T)
        if self.with_bias:
            y = y + params["bias"]
        return y

    def __repr__(self):
        return f"Linear({self.input_size} -> {self.output_size})"


class LookupTable(AbstractModule):
    """«bigdl»/nn/LookupTable.scala — embedding lookup.

    Reference semantics: indices are **1-based**; optional ``paddingValue``
    rows stay zero; optional ``maxNorm`` renormalises looked-up rows.
    """

    param_names = ("weight",)

    def __init__(
        self,
        n_index: int,
        n_output: int,
        padding_value: float = 0.0,
        max_norm: float = float("inf"),
        norm_type: float = 2.0,
        w_regularizer=None,
    ):
        super().__init__()
        self._config = dict(
            n_index=n_index, n_output=n_output, padding_value=padding_value
        )
        self.n_index = n_index
        self.n_output = n_output
        self.padding_value = padding_value
        self.max_norm = max_norm
        self.norm_type = norm_type
        self._regularizers = (
            [("weight", w_regularizer)] if w_regularizer is not None else []
        )
        self.weight = None
        self.reset()

    def reset(self):
        w = RandomGenerator.RNG.normal(
            0.0, 1.0, size=(self.n_index, self.n_output)
        ).astype(np.float32)
        if self.padding_value > 0:
            w[int(self.padding_value) - 1] = 0.0
        self.weight = _to_device(w)
        return self

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        idx = input.astype(jnp.int32) - 1  # reference is 1-based
        w = params["weight"]
        if self.max_norm != float("inf"):
            norms = jnp.linalg.norm(w, ord=self.norm_type, axis=1, keepdims=True)
            w = w * jnp.minimum(1.0, self.max_norm / (norms + 1e-7))
        return jnp.take(w, idx, axis=0)

    def __repr__(self):
        return f"LookupTable({self.n_index}, {self.n_output})"


# --------------------------------------------------------------------------
# Convolutions
# --------------------------------------------------------------------------


def _auto_batch(x, full_ndim):
    if x.ndim == full_ndim - 1:
        return x[None], True
    return x, False


def _conv_pads(pad_h, pad_w, kh, kw, dh, dw):
    """Reference: pad == -1 means TF-style SAME («bigdl»/nn/
    SpatialConvolution.scala)."""
    if pad_h == -1 or pad_w == -1:
        return "SAME"
    return [(pad_h, pad_h), (pad_w, pad_w)]


class SpatialConvolution(AbstractModule):
    """«bigdl»/nn/SpatialConvolution.scala — 2-D conv over NCHW input.

    Reference arg order is width-first (kW, kH, dW, dH, padW, padH), kept
    here.  ``n_group`` maps to ``feature_group_count``.  The reference's
    im2col + MKL gemm path (SURVEY.md §3.3 native boundary) is replaced by
    one ``lax.conv_general_dilated`` that XLA maps onto the MXU directly.
    """

    param_names = ("weight", "bias")

    def __init__(
        self,
        n_input_plane: int,
        n_output_plane: int,
        kernel_w: int,
        kernel_h: int,
        stride_w: int = 1,
        stride_h: int = 1,
        pad_w: int = 0,
        pad_h: int = 0,
        n_group: int = 1,
        with_bias: bool = True,
        w_regularizer=None,
        b_regularizer=None,
        init_method: Optional[InitializationMethod] = None,
    ):
        super().__init__()
        self._config = dict(
            n_input_plane=n_input_plane,
            n_output_plane=n_output_plane,
            kernel_w=kernel_w,
            kernel_h=kernel_h,
            stride_w=stride_w,
            stride_h=stride_h,
            pad_w=pad_w,
            pad_h=pad_h,
            n_group=n_group,
            with_bias=with_bias,
        )
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel_w, self.kernel_h = kernel_w, kernel_h
        self.stride_w, self.stride_h = stride_w, stride_h
        self.pad_w, self.pad_h = pad_w, pad_h
        self.n_group = n_group
        self.with_bias = with_bias
        self._init_method = init_method or MsraFiller(False)
        self._regularizers = []
        if w_regularizer is not None:
            self._regularizers.append(("weight", w_regularizer))
        if b_regularizer is not None:
            self._regularizers.append(("bias", b_regularizer))
        self.weight = None
        self.bias = None
        self.reset()

    def reset(self):
        fan_in = self.n_input_plane // self.n_group * self.kernel_h * self.kernel_w
        fan_out = self.n_output_plane // self.n_group * self.kernel_h * self.kernel_w
        w = self._init_method.init(
            (
                self.n_output_plane,
                self.n_input_plane // self.n_group,
                self.kernel_h,
                self.kernel_w,
            ),
            fan_in,
            fan_out,
        )
        self.weight = _to_device(w)
        if self.with_bias:
            self.bias = _to_device(
                np.zeros(self.n_output_plane, dtype=np.float32)
            )
        return self

    def update_output_pure(self, params, input, *, training=False, rng=None):
        lax = _lax()
        x, squeezed = _auto_batch(input, 4)
        y = lax.conv_general_dilated(
            x,
            params["weight"],
            window_strides=(self.stride_h, self.stride_w),
            padding=_conv_pads(
                self.pad_h,
                self.pad_w,
                self.kernel_h,
                self.kernel_w,
                self.stride_h,
                self.stride_w,
            ),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=self.n_group,
        )
        if self.with_bias:
            y = y + params["bias"][None, :, None, None]
        return y[0] if squeezed else y

    def __repr__(self):
        return (
            f"SpatialConvolution({self.n_input_plane} -> {self.n_output_plane}, "
            f"{self.kernel_w}x{self.kernel_h}, {self.stride_w},{self.stride_h}, "
            f"{self.pad_w},{self.pad_h})"
        )


class SpatialDilatedConvolution(SpatialConvolution):
    """«bigdl»/nn/SpatialDilatedConvolution.scala"""

    def __init__(
        self,
        n_input_plane,
        n_output_plane,
        kernel_w,
        kernel_h,
        stride_w=1,
        stride_h=1,
        pad_w=0,
        pad_h=0,
        dilation_w=1,
        dilation_h=1,
        **kw,
    ):
        self.dilation_w, self.dilation_h = dilation_w, dilation_h
        super().__init__(
            n_input_plane,
            n_output_plane,
            kernel_w,
            kernel_h,
            stride_w,
            stride_h,
            pad_w,
            pad_h,
            **kw,
        )
        self._config.update(dilation_w=dilation_w, dilation_h=dilation_h)

    def update_output_pure(self, params, input, *, training=False, rng=None):
        lax = _lax()
        x, squeezed = _auto_batch(input, 4)
        y = lax.conv_general_dilated(
            x,
            params["weight"],
            window_strides=(self.stride_h, self.stride_w),
            padding=[(self.pad_h, self.pad_h), (self.pad_w, self.pad_w)],
            rhs_dilation=(self.dilation_h, self.dilation_w),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=self.n_group,
        )
        if self.with_bias:
            y = y + params["bias"][None, :, None, None]
        return y[0] if squeezed else y


class SpatialFullConvolution(AbstractModule):
    """«bigdl»/nn/SpatialFullConvolution.scala — transposed conv
    (deconvolution).  out = (in-1)*stride - 2*pad + kernel + adj."""

    param_names = ("weight", "bias")

    def __init__(
        self,
        n_input_plane,
        n_output_plane,
        kernel_w,
        kernel_h,
        stride_w=1,
        stride_h=1,
        pad_w=0,
        pad_h=0,
        adj_w=0,
        adj_h=0,
        n_group=1,
        with_bias=True,
        init_method: Optional[InitializationMethod] = None,
    ):
        super().__init__()
        self._config = dict(
            n_input_plane=n_input_plane,
            n_output_plane=n_output_plane,
            kernel_w=kernel_w,
            kernel_h=kernel_h,
            stride_w=stride_w,
            stride_h=stride_h,
            pad_w=pad_w,
            pad_h=pad_h,
            adj_w=adj_w,
            adj_h=adj_h,
            n_group=n_group,
            with_bias=with_bias,
        )
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel_w, self.kernel_h = kernel_w, kernel_h
        self.stride_w, self.stride_h = stride_w, stride_h
        self.pad_w, self.pad_h = pad_w, pad_h
        self.adj_w, self.adj_h = adj_w, adj_h
        self.n_group = n_group
        self.with_bias = with_bias
        self._init_method = init_method or MsraFiller(False)
        self.weight = None
        self.bias = None
        self.reset()

    def reset(self):
        fan_in = self.n_input_plane * self.kernel_h * self.kernel_w
        fan_out = self.n_output_plane * self.kernel_h * self.kernel_w
        # stored as (out, in/group, kh, kw) so the transposed pass below can
        # run as a regular conv with lhs dilation + flipped kernel
        w = self._init_method.init(
            (
                self.n_output_plane,
                self.n_input_plane // self.n_group,
                self.kernel_h,
                self.kernel_w,
            ),
            fan_in,
            fan_out,
        )
        self.weight = _to_device(w)
        if self.with_bias:
            self.bias = _to_device(np.zeros(self.n_output_plane, dtype=np.float32))
        return self

    def update_output_pure(self, params, input, *, training=False, rng=None):
        lax = _lax()
        jnp = _jnp()
        x, squeezed = _auto_batch(input, 4)
        # transposed conv == conv with input dilation, flipped kernel, and
        # swapped in/out channel roles
        w = params["weight"]  # (out, in/g, kh, kw)
        w = jnp.flip(w, axis=(-2, -1))
        w = jnp.swapaxes(w, 0, 1)  # (in/g, out, kh, kw) -> conv 'IOHW'
        y = lax.conv_general_dilated(
            x,
            w,
            window_strides=(1, 1),
            padding=[
                (
                    self.kernel_h - 1 - self.pad_h,
                    self.kernel_h - 1 - self.pad_h + self.adj_h,
                ),
                (
                    self.kernel_w - 1 - self.pad_w,
                    self.kernel_w - 1 - self.pad_w + self.adj_w,
                ),
            ],
            lhs_dilation=(self.stride_h, self.stride_w),
            dimension_numbers=("NCHW", "IOHW", "NCHW"),
            feature_group_count=self.n_group,
        )
        if self.with_bias:
            y = y + params["bias"][None, :, None, None]
        return y[0] if squeezed else y


class TemporalConvolution(AbstractModule):
    """«bigdl»/nn/TemporalConvolution.scala — 1-D conv over (N, T, C_in)
    frames (the text-classification CNN path)."""

    param_names = ("weight", "bias")

    def __init__(
        self,
        input_frame_size,
        output_frame_size,
        kernel_w,
        stride_w=1,
        with_bias=True,
        init_method=None,
    ):
        super().__init__()
        self._config = dict(
            input_frame_size=input_frame_size,
            output_frame_size=output_frame_size,
            kernel_w=kernel_w,
            stride_w=stride_w,
        )
        self.input_frame_size = input_frame_size
        self.output_frame_size = output_frame_size
        self.kernel_w = kernel_w
        self.stride_w = stride_w
        self.with_bias = with_bias
        self._init_method = init_method or Xavier()
        self.reset()

    def reset(self):
        fan_in = self.input_frame_size * self.kernel_w
        fan_out = self.output_frame_size * self.kernel_w
        self.weight = _to_device(
            self._init_method.init(
                (self.output_frame_size, self.input_frame_size, self.kernel_w),
                fan_in,
                fan_out,
            )
        )
        self.bias = (
            _to_device(np.zeros(self.output_frame_size, dtype=np.float32))
            if self.with_bias
            else None
        )
        return self

    def update_output_pure(self, params, input, *, training=False, rng=None):
        lax = _lax()
        x, squeezed = _auto_batch(input, 3)
        y = lax.conv_general_dilated(
            x,
            params["weight"],
            window_strides=(self.stride_w,),
            padding=[(0, 0)],
            dimension_numbers=("NWC", "OIW", "NWC"),
        )
        if self.with_bias:
            y = y + params["bias"]
        return y[0] if squeezed else y


# --------------------------------------------------------------------------
# Pooling
# --------------------------------------------------------------------------


def _pool_pad(in_size, k, s, pad, ceil_mode):
    """Output size + (lo, hi) padding for one spatial dim, honoring the
    reference's floor/ceil mode («bigdl»/nn/SpatialMaxPooling.scala).
    pad == -1 means TF-style SAME (matching the conv convention)."""
    if pad == -1:
        out = -(-in_size // s)
        needed = max(0, (out - 1) * s + k - in_size)
        lo = needed // 2
        return out, (lo, needed - lo)
    if ceil_mode:
        out = int(math.ceil((in_size + 2 * pad - k) / s)) + 1
    else:
        out = int(math.floor((in_size + 2 * pad - k) / s)) + 1
    if pad > 0 or ceil_mode:
        # reference guard: last window must start inside the padded input
        if (out - 1) * s >= in_size + pad:
            out -= 1
    needed = max(0, (out - 1) * s + k - in_size - pad)
    return out, (pad, needed)


class SpatialMaxPooling(AbstractModule):
    """«bigdl»/nn/SpatialMaxPooling.scala (NCHW; width-first args;
    ``ceil()`` switches to ceil mode)."""

    def __init__(self, kw, kh, dw=None, dh=None, pad_w=0, pad_h=0,
                 ceil_mode=False):
        super().__init__()
        self.kw, self.kh = kw, kh
        self.dw = dw if dw is not None else kw
        self.dh = dh if dh is not None else kh
        self.pad_w, self.pad_h = pad_w, pad_h
        self.ceil_mode = ceil_mode
        self._config = dict(
            kw=kw, kh=kh, dw=self.dw, dh=self.dh, pad_w=pad_w, pad_h=pad_h,
            ceil_mode=ceil_mode,
        )

    def ceil(self):
        self.ceil_mode = True
        self._config["ceil_mode"] = True
        return self

    def floor(self):
        self.ceil_mode = False
        self._config["ceil_mode"] = False
        return self

    def update_output_pure(self, params, input, *, training=False, rng=None):
        lax = _lax()
        jnp = _jnp()
        x, squeezed = _auto_batch(input, 4)
        h, w = x.shape[2], x.shape[3]
        _, ph = _pool_pad(h, self.kh, self.dh, self.pad_h, self.ceil_mode)
        _, pw = _pool_pad(w, self.kw, self.dw, self.pad_w, self.ceil_mode)
        y = lax.reduce_window(
            x,
            -jnp.inf,
            lax.max,
            window_dimensions=(1, 1, self.kh, self.kw),
            window_strides=(1, 1, self.dh, self.dw),
            padding=[(0, 0), (0, 0), ph, pw],
        )
        return y[0] if squeezed else y

    def __repr__(self):
        return f"SpatialMaxPooling({self.kw}x{self.kh}, {self.dw},{self.dh})"


class SpatialAveragePooling(AbstractModule):
    """«bigdl»/nn/SpatialAveragePooling.scala — default counts padded
    cells in the divisor (countIncludePad=true), like the reference."""

    def __init__(
        self,
        kw,
        kh,
        dw=1,
        dh=1,
        pad_w=0,
        pad_h=0,
        global_pooling=False,
        ceil_mode=False,
        count_include_pad=True,
        divide=True,
    ):
        super().__init__()
        self.kw, self.kh, self.dw, self.dh = kw, kh, dw, dh
        self.pad_w, self.pad_h = pad_w, pad_h
        self.global_pooling = global_pooling
        self.ceil_mode = ceil_mode
        self.count_include_pad = count_include_pad
        self.divide = divide
        self._config = dict(
            kw=kw, kh=kh, dw=dw, dh=dh, pad_w=pad_w, pad_h=pad_h,
            global_pooling=global_pooling, ceil_mode=ceil_mode,
            count_include_pad=count_include_pad, divide=divide,
        )

    def ceil(self):
        self.ceil_mode = True
        self._config["ceil_mode"] = True
        return self

    def update_output_pure(self, params, input, *, training=False, rng=None):
        lax = _lax()
        jnp = _jnp()
        x, squeezed = _auto_batch(input, 4)
        kh, kw = self.kh, self.kw
        if self.global_pooling:
            kh, kw = x.shape[2], x.shape[3]
        h, w = x.shape[2], x.shape[3]
        _, ph = _pool_pad(h, kh, self.dh, self.pad_h, self.ceil_mode)
        _, pw = _pool_pad(w, kw, self.dw, self.pad_w, self.ceil_mode)
        summed = lax.reduce_window(
            x,
            0.0,
            lax.add,
            window_dimensions=(1, 1, kh, kw),
            window_strides=(1, 1, self.dh, self.dw),
            padding=[(0, 0), (0, 0), ph, pw],
        )
        if not self.divide:
            y = summed
        elif self.count_include_pad:
            y = summed / (kh * kw)
        else:
            ones = jnp.ones_like(x)
            counts = lax.reduce_window(
                ones,
                0.0,
                lax.add,
                window_dimensions=(1, 1, kh, kw),
                window_strides=(1, 1, self.dh, self.dw),
                padding=[(0, 0), (0, 0), ph, pw],
            )
            y = summed / counts
        return y[0] if squeezed else y


# --------------------------------------------------------------------------
# Activations (all stateless; fuse into producers under XLA)
# --------------------------------------------------------------------------


class _Elementwise(AbstractModule):
    def __init__(self, **config):
        super().__init__()
        self._config = config

    def __repr__(self):
        return type(self).__name__


class ReLU(_Elementwise):
    """«bigdl»/nn/ReLU.scala (ip=true in-place flag is a no-op here: XLA
    fuses, there is no buffer to save)."""

    def __init__(self, ip: bool = False):
        super().__init__()

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return _jnp().maximum(input, 0)


class ReLU6(_Elementwise):
    """«bigdl»/nn/ReLU6.scala"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return _jnp().clip(input, 0, 6)


class Tanh(_Elementwise):
    """«bigdl»/nn/Tanh.scala"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return _jnp().tanh(input)


class Sigmoid(_Elementwise):
    """«bigdl»/nn/Sigmoid.scala"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        import jax

        return jax.nn.sigmoid(input)


class LogSoftMax(_Elementwise):
    """«bigdl»/nn/LogSoftMax.scala — over the last dim (class dim)."""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        import jax

        return jax.nn.log_softmax(input, axis=-1)


class SoftMax(_Elementwise):
    """«bigdl»/nn/SoftMax.scala"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        import jax

        return jax.nn.softmax(input, axis=-1)


class SoftMin(_Elementwise):
    """«bigdl»/nn/SoftMin.scala"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        import jax

        return jax.nn.softmax(-input, axis=-1)


class SoftPlus(_Elementwise):
    """«bigdl»/nn/SoftPlus.scala (beta param)"""

    def __init__(self, beta: float = 1.0):
        super().__init__(beta=beta)
        self.beta = beta

    def update_output_pure(self, params, input, *, training=False, rng=None):
        import jax

        return jax.nn.softplus(self.beta * input) / self.beta


class SoftSign(_Elementwise):
    """«bigdl»/nn/SoftSign.scala"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        return input / (1 + jnp.abs(input))


class ELU(_Elementwise):
    """«bigdl»/nn/ELU.scala"""

    def __init__(self, alpha: float = 1.0, inplace: bool = False):
        super().__init__(alpha=alpha)
        self.alpha = alpha

    def update_output_pure(self, params, input, *, training=False, rng=None):
        import jax

        return jax.nn.elu(input, alpha=self.alpha)


class LeakyReLU(_Elementwise):
    """«bigdl»/nn/LeakyReLU.scala"""

    def __init__(self, negval: float = 0.01, inplace: bool = False):
        super().__init__(negval=negval)
        self.negval = negval

    def update_output_pure(self, params, input, *, training=False, rng=None):
        import jax

        return jax.nn.leaky_relu(input, negative_slope=self.negval)


class HardTanh(_Elementwise):
    """«bigdl»/nn/HardTanh.scala"""

    def __init__(self, min_value: float = -1.0, max_value: float = 1.0, inplace=False):
        super().__init__(min_value=min_value, max_value=max_value)
        self.min_value, self.max_value = min_value, max_value

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return _jnp().clip(input, self.min_value, self.max_value)


class HardSigmoid(_Elementwise):
    """«bigdl»/nn/HardSigmoid.scala — clip(0.2x + 0.5, 0, 1)"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return _jnp().clip(0.2 * input + 0.5, 0.0, 1.0)


class Clamp(HardTanh):
    """«bigdl»/nn/Clamp.scala"""

    def __init__(self, min_value, max_value):
        super().__init__(min_value, max_value)


class Threshold(_Elementwise):
    """«bigdl»/nn/Threshold.scala — x if x > th else value"""

    def __init__(self, th: float = 1e-6, v: float = 0.0, ip: bool = False):
        super().__init__(th=th, v=v)
        self.th, self.v = th, v

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        return jnp.where(input > self.th, input, self.v)


class PReLU(AbstractModule):
    """«bigdl»/nn/PReLU.scala — learnable negative slope (shared or
    per-channel)."""

    param_names = ("weight",)

    def __init__(self, n_output_plane: int = 0):
        super().__init__()
        self._config = dict(n_output_plane=n_output_plane)
        self.n_output_plane = n_output_plane
        n = max(1, n_output_plane)
        self.weight = _to_device(np.full(n, 0.25, dtype=np.float32))

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        w = params["weight"]
        if self.n_output_plane > 0 and input.ndim >= 3:
            # per-channel over NCHW / CHW
            shape = [1] * input.ndim
            shape[-3] = self.n_output_plane
            w = w.reshape(shape)
        return jnp.where(input > 0, input, w * input)


class GELU(_Elementwise):
    """TPU-era addition (not in the 0.x reference; used by modern recipes)."""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        import jax

        return jax.nn.gelu(input)


class SELU(_Elementwise):
    """«bigdl»/nn/SELU.scala — scaled exponential linear unit (fixed
    lambda/alpha from Klambauer et al.)."""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        import jax

        return jax.nn.selu(input)


# --------------------------------------------------------------------------
# Elementwise math layers
# --------------------------------------------------------------------------


class Abs(_Elementwise):
    """«bigdl»/nn/Abs.scala"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return _jnp().abs(input)


class Square(_Elementwise):
    """«bigdl»/nn/Square.scala"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return input * input


class Sqrt(_Elementwise):
    """«bigdl»/nn/Sqrt.scala"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return _jnp().sqrt(input)


class Power(_Elementwise):
    """«bigdl»/nn/Power.scala — (shift + scale*x)^power"""

    def __init__(self, power, scale=1.0, shift=0.0):
        super().__init__(power=power, scale=scale, shift=shift)
        self.power, self.scale, self.shift = power, scale, shift

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return (self.shift + self.scale * input) ** self.power


class Log(_Elementwise):
    """«bigdl»/nn/Log.scala"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return _jnp().log(input)


class Exp(_Elementwise):
    """«bigdl»/nn/Exp.scala"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return _jnp().exp(input)


class Negative(_Elementwise):
    """«bigdl»/nn/Negative.scala"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return -input


class Floor(_Elementwise):
    """TF-interop vocabulary («bigdl»/utils/tf/loaders/Floor.scala)."""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return _jnp().floor(input)


class Ceil(_Elementwise):
    """TF-interop vocabulary («bigdl»/utils/tf/loaders/Ceil.scala)."""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return _jnp().ceil(input)


class Round(_Elementwise):
    """TF-interop vocabulary («bigdl»/utils/tf/loaders/Round.scala)."""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return _jnp().round(input)


class Sign(_Elementwise):
    """TF-interop vocabulary («bigdl»/utils/tf/loaders/Sign.scala)."""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return _jnp().sign(input)


class Log1p(_Elementwise):
    """«bigdl»/nn/Log1p — numerically stable log(1 + x)."""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return _jnp().log1p(input)


class Expm1(_Elementwise):
    """TF-interop vocabulary — numerically stable exp(x) - 1."""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return _jnp().expm1(input)


class Erf(_Elementwise):
    """TF-interop vocabulary («bigdl»/utils/tf/loaders/Erf.scala)."""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        import jax

        return jax.scipy.special.erf(input)


class Sin(_Elementwise):
    """TF-interop vocabulary («bigdl»/utils/tf/loaders/Sin.scala)."""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return _jnp().sin(input)


class Cos(_Elementwise):
    """TF-interop vocabulary («bigdl»/utils/tf/loaders/Cos.scala)."""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return _jnp().cos(input)


class ArgMax(_Elementwise):
    """TF-interop vocabulary («bigdl»/utils/tf/loaders/ArgMax.scala).

    Returns float32 indices along ``dim`` (1-based, counting the batch
    axis, matching :class:`Max`'s convention).  Non-differentiable: the
    integer argmax carries no tangent, so gradients through it are zero.
    """

    def __init__(self, dim=1):
        super().__init__(dim=dim)
        self.dim = dim

    def update_output_pure(self, params, input, *, training=False, rng=None):
        axis = self.dim - 1 if self.dim > 0 else self.dim
        return _jnp().argmax(input, axis=axis).astype("float32")


class AddConstant(_Elementwise):
    """«bigdl»/nn/AddConstant.scala"""

    def __init__(self, constant_scalar, inplace=False):
        super().__init__(constant_scalar=constant_scalar)
        self.constant_scalar = constant_scalar

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return input + self.constant_scalar


class DivConstant(_Elementwise):
    """TF-interop vocabulary — exact ``x / constant``.

    FloorDiv lowering needs true division: multiplying by a rounded
    reciprocal is off by one ulp at exact multiples, which Floor
    amplifies into an off-by-one result.
    """

    def __init__(self, constant_scalar):
        super().__init__(constant_scalar=constant_scalar)
        self.constant_scalar = constant_scalar

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return input / self.constant_scalar


class MulConstant(_Elementwise):
    """«bigdl»/nn/MulConstant.scala"""

    def __init__(self, scalar, inplace=False):
        super().__init__(scalar=scalar)
        self.scalar = scalar

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return input * self.scalar


# --------------------------------------------------------------------------
# Learnable elementwise layers
# --------------------------------------------------------------------------


class CMul(AbstractModule):
    """«bigdl»/nn/CMul.scala — learnable broadcast multiply."""

    param_names = ("weight",)

    def __init__(self, size: Sequence[int]):
        super().__init__()
        self._config = dict(size=list(size))
        self.size = tuple(size)
        self.weight = _to_device(np.ones(self.size, dtype=np.float32))

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return input * params["weight"]


class CAdd(AbstractModule):
    """«bigdl»/nn/CAdd.scala — learnable broadcast add."""

    param_names = ("bias",)

    def __init__(self, size: Sequence[int]):
        super().__init__()
        self._config = dict(size=list(size))
        self.size = tuple(size)
        self.bias = _to_device(np.zeros(self.size, dtype=np.float32))

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return input + params["bias"]


class Add(AbstractModule):
    """«bigdl»/nn/Add.scala — learnable bias over last dim."""

    param_names = ("bias",)

    def __init__(self, input_size: int):
        super().__init__()
        self._config = dict(input_size=input_size)
        self.bias = _to_device(np.zeros(input_size, dtype=np.float32))

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return input + params["bias"]


class Mul(AbstractModule):
    """«bigdl»/nn/Mul.scala — single learnable scalar multiplier."""

    param_names = ("weight",)

    def __init__(self):
        super().__init__()
        self.weight = _to_device(
            RandomGenerator.RNG.uniform(-1, 1, size=(1,)).astype(np.float32)
        )

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return input * params["weight"][0]


class Scale(AbstractModule):
    """«bigdl»/nn/Scale.scala — CMul then CAdd."""

    param_names = ("weight", "bias")

    def __init__(self, size: Sequence[int]):
        super().__init__()
        self._config = dict(size=list(size))
        self.size = tuple(size)
        self.weight = _to_device(np.ones(self.size, dtype=np.float32))
        self.bias = _to_device(np.zeros(self.size, dtype=np.float32))

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return input * params["weight"] + params["bias"]


# --------------------------------------------------------------------------
# Normalization
# --------------------------------------------------------------------------


class BatchNormalization(AbstractModule):
    """«bigdl»/nn/BatchNormalization.scala — over (N, C) input.

    Reference conventions kept: eps=1e-5, momentum=0.1, running stats
    updated as (1-momentum)*running + momentum*batch, running variance
    stored unbiased, batch normalisation uses biased variance; training
    mode uses batch stats, evaluate mode uses running stats.

    Momentum-warmup caveat (single-pass shifted statistics): training
    stats are computed in one pass shifted by the RUNNING mean, and
    the r05 A/B hunt removed every in-step rescue for a stale shift
    (each was measured slower on chip — see the ``apply`` comment and
    scripts/bn_ab.py).  So for roughly the first 1/momentum training
    steps (~10 at the default 0.1), while ``running_mean`` is still
    cold (zeros) on heavily un-normalized input, the batch variance
    ``m2 - d^2`` cancels digits and the normalized output can be
    mis-scaled.  The running mean converges geometrically at the
    momentum rate and the variance self-heals within
    ``~log(d^2/var)/(2*momentum)`` steps; the batch MEAN is exact at
    any shift, so only the scale (not the centering) wobbles during
    warmup.  If the input distribution is pathological (|E[x]| more
    than ~64 batch-stds from 0), normalize the data or warm the
    running stats instead of expecting the first steps' outputs to be
    unit-variance.
    """

    param_names = ("weight", "bias")
    state_names = ("running_mean", "running_var")

    # which axes are reduced over; subclass overrides
    _feature_ndim = 2

    def __init__(
        self,
        n_output: int,
        eps: float = 1e-5,
        momentum: float = 0.1,
        affine: bool = True,
        init_weight=None,
        init_bias=None,
    ):
        super().__init__()
        self._config = dict(
            n_output=n_output, eps=eps, momentum=momentum, affine=affine
        )
        self.n_output = n_output
        self.eps = eps
        self.momentum = momentum
        self.affine = affine
        jnp = _jnp()
        if affine:
            self.weight = (
                _to_device(init_weight)
                if init_weight is not None
                else jnp.ones(n_output, dtype=jnp.float32)
            )
            self.bias = (
                _to_device(init_bias)
                if init_bias is not None
                else jnp.zeros(n_output, dtype=jnp.float32)
            )
        else:
            self.weight = None
            self.bias = None
        self.running_mean = jnp.zeros(n_output, dtype=jnp.float32)
        self.running_var = jnp.ones(n_output, dtype=jnp.float32)

    def _axes_and_shape(self, input):
        if input.ndim == self._feature_ndim:  # batched
            if self._feature_ndim == 2:
                return (0,), (1, self.n_output)
            return (0, 2, 3), (1, self.n_output, 1, 1)
        raise ValueError(
            f"{type(self).__name__} expects {self._feature_ndim}-d input, "
            f"got {input.ndim}-d"
        )

    def _fold(self, params, mean, var, center):
        """Fold (mean, var, weight, bias) into per-channel f32
        (scale, offset) for the CENTERED normalize
        ``y = (x - center) * scale + offset``.

        Centering keeps full precision at any activation magnitude: the
        uncentered ``x*scale + offset`` form loses ~mean/std * 2^-24 of
        the output to f32 rounding of the large ``x*scale`` product,
        while here the big terms cancel before scaling.  ``center`` is
        whatever per-channel vector is cheaply available — the stats
        mean itself (exact), or the running mean (off by the tiny
        shifted-mean d, equally good)."""
        jnp = _jnp()
        lax = _lax()
        inv = lax.rsqrt(var + self.eps)
        if self.affine:
            scale = inv * params["weight"].astype(jnp.float32)
            offset = params["bias"].astype(jnp.float32) \
                - (mean - center) * scale
        else:
            scale = inv
            offset = -(mean - center) * scale
        return scale, offset

    def apply(self, params, state, input, *, training=False, rng=None):
        jnp = _jnp()
        lax = _lax()
        axes, bshape = self._axes_and_shape(input)

        def _normalize(scale, offset, center):
            # elementwise pass in the INPUT dtype: under a bf16 compute
            # policy it runs at half the HBM bytes (measured ~4% of a
            # ResNet-50 step, scripts/perf_probe.py), and no full-tensor
            # f32 copy of the input is ever materialized.  The centered
            # subtract is exact-ish at any magnitude (nearby values),
            # so low-precision here costs only the input's own ulp.
            dt = input.dtype
            return (input - center.astype(dt).reshape(bshape)) \
                * scale.astype(dt).reshape(bshape) \
                + offset.astype(dt).reshape(bshape)

        if not training:
            rm = state["running_mean"]
            scale, offset = self._fold(
                params, rm, state["running_var"], rm
            )
            return _normalize(scale, offset, rm), state

        # statistics always accumulate in f32: under a bf16 compute
        # policy the batch reductions would otherwise lose ~3 decimal
        # digits and drift the running stats
        xf = input.astype(jnp.float32)
        # BN is the bandwidth tax of conv nets on TPU (BASELINE.md):
        # naive mean-then-var reads the activation twice.  Shifted
        # single-pass stats read it once — E[x-s] and E[(x-s)^2] are
        # two reductions over the same fused operand, with s = the
        # running mean.  The shift MUST be loop-carried, not derived
        # from the batch: any data-derived s puts a reduction barrier
        # between the producing op and the stats pass, forcing an
        # extra HBM read of the activation (chip A/B at b128,
        # scripts/bn_ab.py: rm-shift 50.1 ms/step, single-pixel shift
        # 53.4, sample-0-mean shift 64.5, naive two-pass 57.8).
        #
        # Numerics contract: m2 - d^2 loses digits when the shift is
        # very stale (|E[x] - rm| > ~64 batch-stds: cold running_mean
        # on extremely un-normalized input).  Because mean = rm + d is
        # EXACT at any shift, the running mean converges geometrically
        # at the momentum rate and the variance self-heals within
        # ~log(d^2/var)/(2*momentum) steps — and this form is strictly
        # more accurate than the uncentered E[x^2]-E[x]^2 single-pass
        # that flax/haiku ship (their s = 0 is the worst case of ours).
        # Every guarded alternative was measured SLOWER on chip
        # (scripts/bn_ab.py variant names, b128 ms/step): nocond 50.1,
        # where (jnp.where subsample rescue) 85.5, s0 (sample-0-mean
        # shift) 64.5, cond (lax.cond rescue) 89.8-at-b32-scale + OOM
        # at b64+, twopass 57.8.
        # The relay's 2026-07 XLA wants BN as one straight-line
        # dependency chain; anything else defeats fusion/scheduling.
        rm = state["running_mean"]
        xc = xf - rm.reshape(bshape)
        d = jnp.mean(xc, axis=axes)
        m2 = jnp.mean(lax.square(xc), axis=axes)
        mean = rm + d  # exact at any shift
        var = jnp.maximum(m2 - lax.square(d), 0.0)  # biased
        scale, offset = self._fold(params, mean, var, rm)
        y = _normalize(scale, offset, rm)
        n = 1
        for a in axes:
            n *= input.shape[a]
        unbiased = var * (n / max(1, n - 1))
        new_state = {
            "running_mean": (1 - self.momentum) * state["running_mean"]
            + self.momentum * mean,
            "running_var": (1 - self.momentum) * state["running_var"]
            + self.momentum * unbiased,
        }
        return y, new_state

    def __repr__(self):
        return f"{type(self).__name__}({self.n_output})"


class SpatialBatchNormalization(BatchNormalization):
    """«bigdl»/nn/SpatialBatchNormalization.scala — NCHW input, stats per
    channel."""

    _feature_ndim = 4


class Normalize(_Elementwise):
    """«bigdl»/nn/Normalize.scala — Lp-normalise along dim 1."""

    def __init__(self, p: float = 2.0, eps: float = 1e-10):
        super().__init__(p=p, eps=eps)
        self.p, self.eps = p, eps

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        if self.p == float("inf"):
            norm = jnp.max(jnp.abs(input), axis=1, keepdims=True)
        else:
            norm = jnp.sum(jnp.abs(input) ** self.p, axis=1, keepdims=True) ** (
                1.0 / self.p
            )
        return input / (norm + self.eps)


class SpatialCrossMapLRN(_Elementwise):
    """«bigdl»/nn/SpatialCrossMapLRN.scala — AlexNet/Inception local
    response normalisation across channels:
    out = in * (k + alpha/size * sum_window in^2)^(-beta)."""

    def __init__(self, size=5, alpha=1.0, beta=0.75, k=1.0):
        super().__init__(size=size, alpha=alpha, beta=beta, k=k)
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k

    def update_output_pure(self, params, input, *, training=False, rng=None):
        lax = _lax()
        x, squeezed = _auto_batch(input, 4)
        sq = x * x
        half = (self.size - 1) // 2
        summed = lax.reduce_window(
            sq,
            0.0,
            lax.add,
            window_dimensions=(1, self.size, 1, 1),
            window_strides=(1, 1, 1, 1),
            padding=[(0, 0), (half, self.size - 1 - half), (0, 0), (0, 0)],
        )
        y = x * (self.k + self.alpha / self.size * summed) ** (-self.beta)
        return y[0] if squeezed else y


# --------------------------------------------------------------------------
# Dropout
# --------------------------------------------------------------------------


class Dropout(AbstractModule):
    """«bigdl»/nn/Dropout.scala — inverted dropout: at train time zero with
    prob p and scale by 1/(1-p); identity at eval (scale handled so eval
    needs no rescale, matching the reference's default scale=true)."""

    def __init__(self, init_p: float = 0.5, inplace: bool = False, scale: bool = True):
        super().__init__()
        self._config = dict(init_p=init_p, scale=scale)
        self.p = init_p
        self.scale = scale

    def update_output_pure(self, params, input, *, training=False, rng=None):
        if not training or self.p <= 0.0 or rng is None:
            return input
        import jax

        jnp = _jnp()
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(rng, keep, shape=input.shape)
        y = jnp.where(mask, input, 0.0)
        if self.scale:
            y = y / keep
        return y

    def set_p(self, p):
        self.p = p
        return self

    def __repr__(self):
        return f"Dropout({self.p})"


# --------------------------------------------------------------------------
# Shape ops
# --------------------------------------------------------------------------


class Reshape(AbstractModule):
    """«bigdl»/nn/Reshape.scala — batch_mode None: auto-detect whether the
    first dim is a batch dim (reference semantics)."""

    def __init__(self, size: Sequence[int], batch_mode: Optional[bool] = None):
        super().__init__()
        self._config = dict(size=list(size), batch_mode=batch_mode)
        self.size = tuple(int(s) for s in size)
        self.batch_mode = batch_mode
        self._nelement = int(np.prod(self.size))

    def update_output_pure(self, params, input, *, training=False, rng=None):
        total = int(np.prod(input.shape))
        batched = self.batch_mode
        if batched is None:
            # reference auto-detect: first dim is a batch dim when the
            # element count doesn't match, or (batch==1 case) when the
            # remaining dims alone carry exactly nelement
            batched = total != self._nelement or (
                input.shape[0] == 1
                and input.ndim > len(self.size)
                and int(np.prod(input.shape[1:])) == self._nelement
            )
        if batched:
            return input.reshape((input.shape[0],) + self.size)
        return input.reshape(self.size)

    def __repr__(self):
        return f"Reshape({'x'.join(map(str, self.size))})"


class View(AbstractModule):
    """«bigdl»/nn/View.scala — reshape with -1 wildcard; num_input_dims
    governs batch handling (simplified: -1 resolves against the full
    element count, keeping batch when sizes don't consume it)."""

    def __init__(self, *sizes, **kwargs):
        super().__init__()
        if not sizes and "sizes" in kwargs:
            sizes = tuple(kwargs["sizes"])
        if len(sizes) == 1 and isinstance(sizes[0], (list, tuple)):
            sizes = tuple(sizes[0])
        self._config = dict(sizes=list(sizes))
        self.sizes = tuple(int(s) for s in sizes)

    def update_output_pure(self, params, input, *, training=False, rng=None):
        total = int(np.prod(input.shape))
        known = int(np.prod([s for s in self.sizes if s != -1]))
        if -1 in self.sizes:
            return input.reshape(
                tuple(total // known if s == -1 else s for s in self.sizes)
            )
        if known == total:
            return input.reshape(self.sizes)
        return input.reshape((input.shape[0],) + self.sizes)


class Squeeze(AbstractModule):
    """«bigdl»/nn/Squeeze.scala — 1-based dim."""

    def __init__(self, dim: Optional[int] = None, num_input_dims: int = 0):
        super().__init__()
        self._config = dict(dim=dim)
        self.dim = dim

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        if self.dim is None:
            return jnp.squeeze(input)
        return jnp.squeeze(input, axis=self.dim - 1)


class Unsqueeze(AbstractModule):
    """«bigdl»/nn/Unsqueeze.scala — 1-based position."""

    def __init__(self, pos: int, num_input_dims: int = 0):
        super().__init__()
        self._config = dict(pos=pos)
        self.pos = pos

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return _jnp().expand_dims(input, axis=self.pos - 1)


class Transpose(AbstractModule):
    """«bigdl»/nn/Transpose.scala — sequence of (dim1, dim2) swaps,
    1-based."""

    def __init__(self, permutations: Sequence[Sequence[int]]):
        super().__init__()
        self._config = dict(permutations=[list(p) for p in permutations])
        self.permutations = [tuple(p) for p in permutations]

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        y = input
        for d1, d2 in self.permutations:
            y = jnp.swapaxes(y, d1 - 1, d2 - 1)
        return y


class Contiguous(AbstractModule):
    """«bigdl»/nn/Contiguous.scala — no-op under XLA (layout is the
    compiler's concern)."""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return input


class Replicate(AbstractModule):
    """«bigdl»/nn/Replicate.scala — repeat along a new 1-based dim."""

    def __init__(self, n_features: int, dim: int = 1, n_dim: int = float("inf")):
        super().__init__()
        self._config = dict(n_features=n_features, dim=dim)
        self.n_features, self.dim = n_features, dim

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        y = jnp.expand_dims(input, axis=self.dim - 1)
        reps = [1] * y.ndim
        reps[self.dim - 1] = self.n_features
        return jnp.tile(y, reps)


class Narrow(AbstractModule):
    """«bigdl»/nn/Narrow.scala — 1-based offset slice along dim."""

    def __init__(self, dim: int, offset: int, length: int = 1):
        super().__init__()
        self._config = dict(dim=dim, offset=offset, length=length)
        self.dim, self.offset, self.length = dim, offset, length

    def update_output_pure(self, params, input, *, training=False, rng=None):
        d = self.dim - 1 if self.dim > 0 else input.ndim + self.dim
        length = self.length
        if length < 0:
            length = input.shape[d] - self.offset + 2 + length
        start = self.offset - 1
        idx = [slice(None)] * input.ndim
        idx[d] = slice(start, start + length)
        return input[tuple(idx)]


class Padding(AbstractModule):
    """«bigdl»/nn/Padding.scala — pad `pad` cells (negative: before) along
    1-based dim with value."""

    def __init__(self, dim, pad, n_input_dim, value=0.0, n_index=1):
        super().__init__()
        self._config = dict(dim=dim, pad=pad, n_input_dim=n_input_dim, value=value)
        self.dim, self.pad, self.n_input_dim, self.value = dim, pad, n_input_dim, value

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        d = self.dim - 1
        if input.ndim > self.n_input_dim:
            d += 1  # batch dim present
        widths = [(0, 0)] * input.ndim
        widths[d] = (-self.pad, 0) if self.pad < 0 else (0, self.pad)
        return jnp.pad(input, widths, constant_values=self.value)


class SpatialZeroPadding(AbstractModule):
    """«bigdl»/nn/SpatialZeroPadding.scala — NCHW edge padding."""

    def __init__(self, pad_left, pad_right=None, pad_top=None, pad_bottom=None):
        super().__init__()
        pad_right = pad_left if pad_right is None else pad_right
        pad_top = pad_left if pad_top is None else pad_top
        pad_bottom = pad_left if pad_bottom is None else pad_bottom
        self._config = dict(
            pad_left=pad_left,
            pad_right=pad_right,
            pad_top=pad_top,
            pad_bottom=pad_bottom,
        )
        self.pads = (pad_left, pad_right, pad_top, pad_bottom)

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        l, r, t, b = self.pads
        widths = [(0, 0)] * (input.ndim - 2) + [(t, b), (l, r)]
        return jnp.pad(input, widths)


class SpatialUpSamplingNearest(AbstractModule):
    """«bigdl»/nn/SpatialUpSamplingNearest.scala"""

    def __init__(self, scale: int):
        super().__init__()
        self._config = dict(scale=scale)
        self.scale = scale

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        y = jnp.repeat(input, self.scale, axis=-2)
        return jnp.repeat(y, self.scale, axis=-1)


class SpatialUpSamplingBilinear(AbstractModule):
    """«bigdl»/nn/SpatialUpSamplingBilinear.scala (align_corners=true,
    matching the reference)."""

    def __init__(self, output_height: int, output_width: int):
        super().__init__()
        self._config = dict(output_height=output_height, output_width=output_width)
        self.oh, self.ow = output_height, output_width

    def update_output_pure(self, params, input, *, training=False, rng=None):
        import jax

        x, squeezed = _auto_batch(input, 4)
        y = jax.image.resize(
            x, (x.shape[0], x.shape[1], self.oh, self.ow), method="linear"
        )
        return y[0] if squeezed else y


class Mean(AbstractModule):
    """«bigdl»/nn/Mean.scala — 1-based dim; squeeze by default."""

    def __init__(self, dim: int = 1, n_input_dims: int = -1, squeeze: bool = True):
        super().__init__()
        self._config = dict(dim=dim, n_input_dims=n_input_dims, squeeze=squeeze)
        self.dim, self.n_input_dims, self.squeeze = dim, n_input_dims, squeeze

    def _axis(self, input):
        d = self.dim - 1
        if self.n_input_dims > 0 and input.ndim > self.n_input_dims:
            d += 1
        return d

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return _jnp().mean(input, axis=self._axis(input), keepdims=not self.squeeze)


class Sum(Mean):
    """«bigdl»/nn/Sum.scala"""

    def __init__(self, dim=1, n_input_dims=-1, size_average=False, squeeze=True):
        super().__init__(dim, n_input_dims, squeeze)
        self.size_average = size_average
        self._config["size_average"] = size_average

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        ax = self._axis(input)
        y = jnp.sum(input, axis=ax, keepdims=not self.squeeze)
        if self.size_average:
            y = y / input.shape[ax]
        return y


class Max(AbstractModule):
    """«bigdl»/nn/Max.scala — max over 1-based dim (values only)."""

    def __init__(self, dim: int = 1, num_input_dims: int = -1):
        super().__init__()
        self._config = dict(dim=dim)
        self.dim = dim

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return _jnp().max(input, axis=self.dim - 1)


class Min(AbstractModule):
    """«bigdl»/nn/Min.scala"""

    def __init__(self, dim: int = 1, num_input_dims: int = -1):
        super().__init__()
        self._config = dict(dim=dim)
        self.dim = dim

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return _jnp().min(input, axis=self.dim - 1)


class Index(AbstractModule):
    """«bigdl»/nn/Index.scala — table input (tensor, 1-based indices)."""

    def __init__(self, dimension: int):
        super().__init__()
        self._config = dict(dimension=dimension)
        self.dimension = dimension

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        t, idx = input
        return jnp.take(t, idx.astype(jnp.int32) - 1, axis=self.dimension - 1)


class Masking(AbstractModule):
    """«bigdl»/nn/Masking.scala — zero timesteps equal to mask_value."""

    def __init__(self, mask_value: float = 0.0):
        super().__init__()
        self._config = dict(mask_value=mask_value)
        self.mask_value = mask_value

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        mask = jnp.any(input != self.mask_value, axis=-1, keepdims=True)
        return jnp.where(mask, input, 0.0)


# --------------------------------------------------------------------------
# Gradient-shaping layers (need custom vjp)
# --------------------------------------------------------------------------


def _gradient_reversal_fn():
    import jax

    @jax.custom_vjp
    def f(x, lam):
        return x

    def fwd(x, lam):
        return x, lam

    def bwd(lam, g):
        return (-lam * g, None)

    f.defvjp(fwd, bwd)
    return f


class GradientReversal(AbstractModule):
    """«bigdl»/nn/GradientReversal.scala — identity forward, negated
    (scaled) gradient backward (domain-adaptation trick)."""

    def __init__(self, the_lambda: float = 1.0):
        super().__init__()
        self._config = dict(the_lambda=the_lambda)
        self.the_lambda = the_lambda
        self._fn = None

    def set_lambda(self, lam):
        self.the_lambda = lam
        return self

    def update_output_pure(self, params, input, *, training=False, rng=None):
        if self._fn is None:
            self._fn = _gradient_reversal_fn()
        return self._fn(input, self.the_lambda)


def _l1_penalty_fn():
    import jax
    import jax.numpy as jnp

    @jax.custom_vjp
    def f(x, w):
        return x

    def fwd(x, w):
        return x, (x, w)

    def bwd(res, g):
        x, w = res
        return (g + w * jnp.sign(x), None)

    f.defvjp(fwd, bwd)
    return f


class L1Penalty(AbstractModule):
    """«bigdl»/nn/L1Penalty.scala — identity forward that injects an L1
    sparsity gradient on the way back."""

    def __init__(self, l1weight: float, size_average: bool = False, provide_output=True):
        super().__init__()
        self._config = dict(l1weight=l1weight, size_average=size_average)
        self.l1weight = l1weight
        self.size_average = size_average
        self._fn = None

    def update_output_pure(self, params, input, *, training=False, rng=None):
        if self._fn is None:
            self._fn = _l1_penalty_fn()
        w = self.l1weight
        if self.size_average:
            w = w / int(np.prod(input.shape))
        return self._fn(input, w)


# --------------------------------------------------------------------------
# Misc similarity layers
# --------------------------------------------------------------------------


class Cosine(AbstractModule):
    """«bigdl»/nn/Cosine.scala — cosine similarity of input to each weight
    row."""

    param_names = ("weight",)

    def __init__(self, input_size: int, output_size: int):
        super().__init__()
        self._config = dict(input_size=input_size, output_size=output_size)
        stdv = 1.0 / math.sqrt(input_size)
        self.weight = _to_device(
            RandomGenerator.RNG.uniform(
                -stdv, stdv, size=(output_size, input_size)
            ).astype(np.float32)
        )

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        w = params["weight"]
        xn = input / (jnp.linalg.norm(input, axis=-1, keepdims=True) + 1e-12)
        wn = w / (jnp.linalg.norm(w, axis=-1, keepdims=True) + 1e-12)
        return jnp.matmul(xn, wn.T)


class Euclidean(AbstractModule):
    """«bigdl»/nn/Euclidean.scala — distance of input to each weight
    column."""

    param_names = ("weight",)

    def __init__(self, input_size: int, output_size: int, fast_backward=True):
        super().__init__()
        self._config = dict(input_size=input_size, output_size=output_size)
        stdv = 1.0 / math.sqrt(input_size)
        self.weight = _to_device(
            RandomGenerator.RNG.uniform(
                -stdv, stdv, size=(output_size, input_size)
            ).astype(np.float32)
        )

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        diff = input[..., None, :] - params["weight"]
        return jnp.sqrt(jnp.sum(diff * diff, axis=-1) + 1e-12)


class Bilinear(AbstractModule):
    """«bigdl»/nn/Bilinear.scala — y_k = x1^T W_k x2 + b_k over a table
    input (x1, x2)."""

    param_names = ("weight", "bias")

    def __init__(self, input_size1, input_size2, output_size, bias_res=True):
        super().__init__()
        self._config = dict(
            input_size1=input_size1,
            input_size2=input_size2,
            output_size=output_size,
            bias_res=bias_res,
        )
        stdv = 1.0 / math.sqrt(input_size1)
        self.weight = _to_device(
            RandomGenerator.RNG.uniform(
                -stdv, stdv, size=(output_size, input_size1, input_size2)
            ).astype(np.float32)
        )
        self.bias = (
            _to_device(np.zeros(output_size, dtype=np.float32)) if bias_res else None
        )

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        x1, x2 = input
        y = jnp.einsum("bi,kij,bj->bk", x1, params["weight"], x2)
        if "bias" in params:
            y = y + params["bias"]
        return y


__all__ = [
    "InitializationMethod", "Zeros", "Ones", "ConstInitMethod",
    "RandomUniform", "RandomNormal", "Xavier", "MsraFiller",
    "Linear", "LookupTable",
    "SpatialConvolution", "SpatialDilatedConvolution",
    "SpatialFullConvolution", "TemporalConvolution",
    "SpatialMaxPooling", "SpatialAveragePooling",
    "ReLU", "ReLU6", "Tanh", "Sigmoid", "LogSoftMax", "SoftMax", "SoftMin",
    "SoftPlus", "SoftSign", "ELU", "LeakyReLU", "HardTanh", "HardSigmoid",
    "Clamp", "Threshold", "PReLU", "GELU",
    "SELU",
    "Abs", "Square", "Sqrt", "Power", "Log", "Exp", "Negative",
    "Floor", "Ceil", "Round", "Sign", "Log1p", "Expm1", "Erf",
    "Sin", "Cos", "ArgMax",
    "AddConstant", "MulConstant", "DivConstant",
    "CMul", "CAdd", "Add", "Mul", "Scale",
    "BatchNormalization", "SpatialBatchNormalization", "Normalize",
    "SpatialCrossMapLRN",
    "Dropout",
    "Reshape", "View", "Squeeze", "Unsqueeze", "Transpose", "Contiguous",
    "Replicate", "Narrow", "Padding", "SpatialZeroPadding",
    "SpatialUpSamplingNearest", "SpatialUpSamplingBilinear",
    "Mean", "Sum", "Max", "Min", "Index", "Masking",
    "GradientReversal", "L1Penalty",
    "Cosine", "Euclidean", "Bilinear",
]
