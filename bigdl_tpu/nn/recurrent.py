"""Recurrent stack.

Rebuild of the reference sequence-modeling layers (SURVEY.md §2.1
"Recurrent stack"): «bigdl»/nn/Recurrent.scala (unrolls Cells over time,
reusing state tensors), LSTM.scala, LSTMPeephole.scala, GRU.scala,
RnnCell.scala, BiRecurrent.scala, TimeDistributed.scala, Select.scala.

TPU-native mechanics instead of the reference's per-timestep Scala loop:

* the time loop is ``lax.scan`` — one compiled program, no per-step
  dispatch;
* input-to-hidden projections for *all* timesteps are hoisted out of the
  scan into a single large (B*T, in) x (in, gates*H) matmul that the MXU
  eats whole; the scan body only carries the small recurrent matmul;
* gate weights are packed into one matrix per direction so each step is
  one fused matmul, not 3-4 small ones;
* the reference's per-gate input Dropout(p) applies independent masks to
  the input of each gate — done here as one (gates, B, T, in) masked
  einsum, still outside the scan.

Input layout is batch-first (B, T, F), matching the reference's
``batchNormParams``-free default.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

from bigdl_tpu.common import RandomGenerator
from bigdl_tpu.nn.module import AbstractModule, Container
from bigdl_tpu.nn.layers import Sigmoid, Tanh, _to_device


def _jnp():
    import jax.numpy as jnp

    return jnp


def _gate_dropout(x, n_gates: int, p: float, training: bool, rng):
    """Reference: each gate's input connection has its own Dropout(p)
    («bigdl»/nn/LSTM.scala wires Dropout before every i2h Linear).
    Returns (n_gates, B, T, in) with independent inverted-dropout masks,
    or None when dropout is inactive (caller uses the plain x @ W path)."""
    if p <= 0.0 or not training or rng is None:
        return None
    import jax

    jnp = _jnp()
    keep = 1.0 - p
    masks = jax.random.bernoulli(rng, keep, shape=(n_gates,) + x.shape)
    return jnp.where(masks, x[None], 0.0) / keep


class Cell(AbstractModule):
    """Base recurrent cell (reference: «bigdl»/nn/Cell.scala).

    Subclasses define:
      * ``hidden_size`` and gate packing
      * ``precompute(params, x, training=..., rng=...)`` — (B, T, in) ->
        (B, T, gates*H), the hoisted input projection (incl. per-gate
        input dropout)
      * ``step(params, carry, proj_t)`` -> (new_carry, output_t)
      * ``init_carry(batch, dtype)``
    """

    hidden_size: int = 0

    def precompute(self, params, x, *, training=False, rng=None):
        raise NotImplementedError

    def step(self, params, carry, proj_t):
        raise NotImplementedError

    def init_carry(self, batch: int, dtype, input_shape=None):
        raise NotImplementedError

    def run_sequence(self, params, x, *, training=False, rng=None):
        """(B, T, ...) -> (B, T, ...): hoisted precompute + lax.scan over
        step.  Recurrent delegates here; composite cells (MultiRNNCell)
        override to thread rng/dropout into every sub-cell."""
        import jax.lax as lax

        jnp = _jnp()
        proj = self.precompute(params, x, training=training, rng=rng)
        proj_t = jnp.swapaxes(proj, 0, 1)               # time-major for scan
        carry0 = self.init_carry(x.shape[0], x.dtype, input_shape=x.shape)

        def body(carry, p_t):
            return self.step(params, carry, p_t)

        _, ys = lax.scan(body, carry0, proj_t)
        return jnp.swapaxes(ys, 0, 1)

    # a bare cell can also be applied to a single timestep; the common
    # path is through Recurrent, so apply() runs one step.
    def update_output_pure(self, params, input, *, training=False, rng=None):
        proj = self.precompute(params, input[:, None, :], training=training,
                               rng=rng)[:, 0]
        carry = self.init_carry(input.shape[0], input.dtype,
                                input_shape=input[:, None].shape)
        _, out = self.step(params, carry, proj)
        return out


def _uniform(shape, stdv):
    return _to_device(
        RandomGenerator.RNG.uniform(-stdv, stdv, size=shape).astype(np.float32)
    )


def _gated_projection(x, w, b, n_gates, hidden, dropped):
    """x @ w + b, or the per-gate-masked equivalent when dropout is on.
    w: (in, n_gates*H)."""
    jnp = _jnp()
    if dropped is None:
        return x @ w + b
    wg = w.reshape(w.shape[0], n_gates, hidden)
    proj = jnp.einsum("gbti,igh->btgh", dropped, wg)
    return proj.reshape(x.shape[0], x.shape[1], n_gates * hidden) + b


class RnnCell(Cell):
    """«bigdl»/nn/RnnCell.scala — h' = act(W x + U h + b)."""

    param_names = ("w", "u", "b")

    def __init__(self, input_size: int, hidden_size: int, activation=None):
        super().__init__()
        self._config = dict(input_size=input_size, hidden_size=hidden_size)
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation or Tanh()
        self.reset()

    def reset(self):
        stdv = 1.0 / math.sqrt(self.hidden_size)
        self.w = _uniform((self.input_size, self.hidden_size), stdv)
        self.u = _uniform((self.hidden_size, self.hidden_size), stdv)
        self.b = _to_device(np.zeros(self.hidden_size, dtype=np.float32))
        return self

    def precompute(self, params, x, *, training=False, rng=None):
        return x @ params["w"] + params["b"]

    def init_carry(self, batch, dtype, input_shape=None):
        jnp = _jnp()
        return jnp.zeros((batch, self.hidden_size), dtype=dtype)

    def step(self, params, carry, proj_t):
        h = self.activation.update_output_pure({}, proj_t + carry @ params["u"])
        return h, h


class LSTM(Cell):
    """«bigdl»/nn/LSTM.scala — gates packed (i, f, g, o) into one
    (in, 4H) input matrix and one (H, 4H) recurrent matrix.

    Reference options honored: ``p`` (per-gate input dropout),
    ``activation`` (candidate/output nonlinearity, default Tanh),
    ``inner_activation`` (gate nonlinearity, default Sigmoid).
    """

    param_names = ("w", "u", "b")
    n_gates = 4

    def __init__(
        self,
        input_size: int,
        hidden_size: int,
        p: float = 0.0,
        activation=None,
        inner_activation=None,
        w_regularizer=None,
        u_regularizer=None,
        b_regularizer=None,
    ):
        super().__init__()
        self._config = dict(input_size=input_size, hidden_size=hidden_size, p=p)
        self.input_size, self.hidden_size = input_size, hidden_size
        self.p = p
        self.activation = activation or Tanh()
        self.inner_activation = inner_activation or Sigmoid()
        self._regularizers = []
        for name, reg in (("w", w_regularizer), ("u", u_regularizer),
                          ("b", b_regularizer)):
            if reg is not None:
                self._regularizers.append((name, reg))
        self.reset()

    def reset(self):
        stdv = 1.0 / math.sqrt(self.hidden_size)
        self.w = _uniform((self.input_size, 4 * self.hidden_size), stdv)
        self.u = _uniform((self.hidden_size, 4 * self.hidden_size), stdv)
        self.b = _to_device(np.zeros(4 * self.hidden_size, dtype=np.float32))
        return self

    def precompute(self, params, x, *, training=False, rng=None):
        dropped = _gate_dropout(x, self.n_gates, self.p, training, rng)
        return _gated_projection(x, params["w"], params["b"], self.n_gates,
                                 self.hidden_size, dropped)

    def init_carry(self, batch, dtype, input_shape=None):
        jnp = _jnp()
        z = jnp.zeros((batch, self.hidden_size), dtype=dtype)
        return (z, z)

    def step(self, params, carry, proj_t):
        jnp = _jnp()
        h, c = carry
        gates = proj_t + h @ params["u"]
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        act, inner = self.activation, self.inner_activation
        i = inner.update_output_pure({}, i)
        f = inner.update_output_pure({}, f)
        o = inner.update_output_pure({}, o)
        g = act.update_output_pure({}, g)
        c_new = f * c + i * g
        h_new = o * act.update_output_pure({}, c_new)
        return (h_new, c_new), h_new

    def __repr__(self):
        return f"LSTM({self.input_size}, {self.hidden_size})"


class LSTMPeephole(Cell):
    """«bigdl»/nn/LSTMPeephole.scala — LSTM with diagonal peephole
    connections from the cell state into i/f/o gates."""

    param_names = ("w", "u", "b", "p_i", "p_f", "p_o")
    n_gates = 4

    def __init__(self, input_size: int, hidden_size: int, p: float = 0.0):
        super().__init__()
        self._config = dict(input_size=input_size, hidden_size=hidden_size, p=p)
        self.input_size, self.hidden_size = input_size, hidden_size
        self.p = p
        self.reset()

    def reset(self):
        stdv = 1.0 / math.sqrt(self.hidden_size)
        self.w = _uniform((self.input_size, 4 * self.hidden_size), stdv)
        self.u = _uniform((self.hidden_size, 4 * self.hidden_size), stdv)
        self.b = _to_device(np.zeros(4 * self.hidden_size, dtype=np.float32))
        self.p_i = _uniform((self.hidden_size,), stdv)
        self.p_f = _uniform((self.hidden_size,), stdv)
        self.p_o = _uniform((self.hidden_size,), stdv)
        return self

    def precompute(self, params, x, *, training=False, rng=None):
        dropped = _gate_dropout(x, self.n_gates, self.p, training, rng)
        return _gated_projection(x, params["w"], params["b"], self.n_gates,
                                 self.hidden_size, dropped)

    def init_carry(self, batch, dtype, input_shape=None):
        jnp = _jnp()
        z = jnp.zeros((batch, self.hidden_size), dtype=dtype)
        return (z, z)

    def step(self, params, carry, proj_t):
        import jax

        jnp = _jnp()
        h, c = carry
        gates = proj_t + h @ params["u"]
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i + params["p_i"] * c)
        f = jax.nn.sigmoid(f + params["p_f"] * c)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        o = jax.nn.sigmoid(o + params["p_o"] * c_new)
        h_new = o * jnp.tanh(c_new)
        return (h_new, c_new), h_new


class GRU(Cell):
    """«bigdl»/nn/GRU.scala — gates packed (r, z) + candidate; honors
    ``p`` per-gate input dropout like the reference.  ``activation`` /
    ``inner_activation`` default to the reference's Tanh/Sigmoid; the
    Keras importer passes hard_sigmoid gates for Keras-1.2.2 parity."""

    param_names = ("w_rz", "u_rz", "b_rz", "w_h", "u_h", "b_h")

    def __init__(self, input_size: int, hidden_size: int, p: float = 0.0,
                 activation=None, inner_activation=None,
                 w_regularizer=None, u_regularizer=None,
                 b_regularizer=None):
        super().__init__()
        self._config = dict(input_size=input_size, hidden_size=hidden_size, p=p)
        self.input_size, self.hidden_size = input_size, hidden_size
        self.p = p
        self.activation = activation or Tanh()
        self.inner_activation = inner_activation or Sigmoid()
        self._regularizers = []
        for names, reg in ((("w_rz", "w_h"), w_regularizer),
                           (("u_rz", "u_h"), u_regularizer),
                           (("b_rz", "b_h"), b_regularizer)):
            if reg is not None:
                for n in names:
                    self._regularizers.append((n, reg))
        self.reset()

    def reset(self):
        stdv = 1.0 / math.sqrt(self.hidden_size)
        self.w_rz = _uniform((self.input_size, 2 * self.hidden_size), stdv)
        self.u_rz = _uniform((self.hidden_size, 2 * self.hidden_size), stdv)
        self.b_rz = _to_device(np.zeros(2 * self.hidden_size, dtype=np.float32))
        self.w_h = _uniform((self.input_size, self.hidden_size), stdv)
        self.u_h = _uniform((self.hidden_size, self.hidden_size), stdv)
        self.b_h = _to_device(np.zeros(self.hidden_size, dtype=np.float32))
        return self

    def precompute(self, params, x, *, training=False, rng=None):
        jnp = _jnp()
        dropped = _gate_dropout(x, 3, self.p, training, rng)
        if dropped is None:
            rz = x @ params["w_rz"] + params["b_rz"]
            hcand = x @ params["w_h"] + params["b_h"]
        else:
            H = self.hidden_size
            rz = _gated_projection(x, params["w_rz"], params["b_rz"], 2, H,
                                   dropped[:2])
            hcand = dropped[2] @ params["w_h"] + params["b_h"]
        return jnp.concatenate([rz, hcand], axis=-1)

    def init_carry(self, batch, dtype, input_shape=None):
        jnp = _jnp()
        return jnp.zeros((batch, self.hidden_size), dtype=dtype)

    def step(self, params, carry, proj_t):
        jnp = _jnp()
        h = carry
        H = self.hidden_size
        rz = proj_t[..., : 2 * H] + h @ params["u_rz"]
        r, z = jnp.split(
            self.inner_activation.update_output_pure({}, rz), 2, axis=-1)
        cand = self.activation.update_output_pure(
            {}, proj_t[..., 2 * H:] + (r * h) @ params["u_h"])
        h_new = (1 - z) * cand + z * h
        return h_new, h_new

    def __repr__(self):
        return f"GRU({self.input_size}, {self.hidden_size})"


class Recurrent(Container):
    """«bigdl»/nn/Recurrent.scala — wraps one Cell, maps (B, T, in) ->
    (B, T, H).  The reference's per-timestep loop with reused state
    tensors becomes ``lax.scan``; see module docstring for what gets
    hoisted."""

    def __init__(self):
        super().__init__()

    def add(self, cell: Cell):
        if len(self.modules) > 0:
            raise ValueError("Recurrent takes exactly one Cell")
        if not isinstance(cell, Cell):
            raise TypeError("Recurrent.add expects a recurrent Cell")
        return super().add(cell)

    @property
    def cell(self) -> Cell:
        return self.modules[0]

    def apply(self, params, state, input, *, training=False, rng=None):
        out = self.cell.run_sequence(
            params["0"], input, training=training, rng=rng
        )
        return out, state

    def __repr__(self):
        return f"Recurrent({self.modules[0]!r})" if self.modules else "Recurrent()"


class BiRecurrent(Container):
    """«bigdl»/nn/BiRecurrent.scala — forward + time-reversed cells;
    outputs merged (default: concat on the feature dim, like the
    reference's JoinTable default).  The reverse cell is independently
    re-initialized, as the reference constructs a fresh cell."""

    def __init__(self, merge=None):
        super().__init__()
        self.merge = merge  # None -> concat last dim; else a table module

    def add(self, cell: Cell):
        import copy

        if len(self.modules) > 0:
            raise ValueError("BiRecurrent takes exactly one Cell")
        fwd = Recurrent().add(cell)
        bwd_cell = copy.deepcopy(cell)
        bwd_cell.reset()  # fresh draw — cells implement reset()
        bwd = Recurrent().add(bwd_cell)
        super().add(fwd)
        super().add(bwd)
        return self

    def apply(self, params, state, input, *, training=False, rng=None):
        import jax

        jnp = _jnp()
        r_f = None if rng is None else jax.random.fold_in(rng, 0)
        r_b = None if rng is None else jax.random.fold_in(rng, 1)
        fwd_out, _ = self.modules[0].apply(
            params["0"], state["0"], input, training=training, rng=r_f
        )
        rev = jnp.flip(input, axis=1)
        bwd_out, _ = self.modules[1].apply(
            params["1"], state["1"], rev, training=training, rng=r_b
        )
        bwd_out = jnp.flip(bwd_out, axis=1)
        if self.merge is None:
            return jnp.concatenate([fwd_out, bwd_out], axis=-1), state
        merged = self.merge.update_output_pure({}, (fwd_out, bwd_out))
        return merged, state


class TimeDistributed(Container):
    """«bigdl»/nn/TimeDistributed.scala — fold time into batch, apply the
    wrapped layer, unfold (the reference's trick for applying Linear/
    LogSoftMax per step)."""

    def __init__(self, layer: Optional[AbstractModule] = None):
        super().__init__()
        if layer is not None:
            self.add(layer)

    def apply(self, params, state, input, *, training=False, rng=None):
        b, t = input.shape[0], input.shape[1]
        merged = input.reshape((b * t,) + input.shape[2:])
        y, s = self.modules[0].apply(
            params["0"], state["0"], merged, training=training, rng=rng
        )
        return y.reshape((b, t) + y.shape[1:]), {"0": s}


class Select(AbstractModule):
    """«bigdl»/nn/Select.scala — select one 1-based index along a 1-based
    dim (negative index counts from the end); commonly
    ``Select(2, -1)`` for "last timestep"."""

    def __init__(self, dim: int, index: int):
        super().__init__()
        self._config = dict(dim=dim, index=index)
        self.dim, self.index = dim, index

    def update_output_pure(self, params, input, *, training=False, rng=None):
        d = self.dim - 1 if self.dim > 0 else input.ndim + self.dim
        i = self.index - 1 if self.index > 0 else input.shape[d] + self.index
        return _jnp().take(input, i, axis=d)


class MultiRNNCell(Cell, Container):
    """⟦«bigdl»/nn/MultiRNNCell.scala⟧ — a vertical stack of Cells run as
    one Cell: the output of cell *k* feeds cell *k+1* at the same
    timestep.  There is no feedback from upper to lower cells, so the
    stack factorizes into one scan per cell run in sequence — which lets
    every cell hoist its full input projection (incl. per-gate input
    dropout with its own rng) out of its scan; ``run_sequence`` does
    exactly that.  A Container so serialization recurses into the cells
    (params/state keyed by position, like Sequential)."""

    def __init__(self, cells=None):
        super().__init__()
        self.modules = []
        for c in (cells or []):
            self.add(c)

    def add(self, cell):
        if not isinstance(cell, Cell):
            raise TypeError("MultiRNNCell takes recurrent Cells")
        return Container.add(self, cell)

    @property
    def cells(self):
        return self.modules

    @property
    def hidden_size(self):
        return self.modules[-1].hidden_size if self.modules else 0

    def run_sequence(self, params, x, *, training=False, rng=None):
        import jax

        y = x
        for i, c in enumerate(self.cells):
            r = None if rng is None else jax.random.fold_in(rng, i)
            y = c.run_sequence(params[str(i)], y, training=training, rng=r)
        return y

    def update_output_pure(self, params, input, *, training=False, rng=None):
        # single-timestep application: chain the cells' single-step paths
        import jax

        y = input
        for i, c in enumerate(self.cells):
            r = None if rng is None else jax.random.fold_in(rng, i)
            y = c.update_output_pure(params[str(i)], y, training=training,
                                     rng=r)
        return y

    def init_carry(self, batch, dtype, input_shape=None):
        return tuple(
            c.init_carry(batch, dtype, input_shape=input_shape)
            for c in self.cells
        )

    def precompute(self, params, x, *, training=False, rng=None):
        raise NotImplementedError(
            "MultiRNNCell runs whole sub-cell scans (run_sequence); it has "
            "no single hoisted projection"
        )

    def step(self, params, carry, proj_t):
        raise NotImplementedError(
            "MultiRNNCell runs whole sub-cell scans (run_sequence)"
        )

    def __repr__(self):
        return f"MultiRNNCell({self.cells!r})"


class ConvLSTMPeephole(Cell):
    """⟦«bigdl»/nn/ConvLSTMPeephole.scala⟧ — 2-D convolutional LSTM with
    optional per-channel peephole connections.

    Input per step is (B, C_in, H, W); the hoisted input projection is a
    single conv over the folded (B*T) batch (one big MXU contraction),
    the scan body carries only the recurrent conv.  ``stride`` must be 1
    (the recurrent state must keep its spatial shape), matching the
    reference's practical use.
    """

    param_names = ("w_i", "w_h", "b", "p_i", "p_f", "p_o")
    n_gates = 4

    def __init__(
        self,
        input_size: int,
        output_size: int,
        kernel_i: int = 3,
        kernel_c: int = 3,
        stride: int = 1,
        with_peephole: bool = True,
    ):
        super().__init__()
        if stride != 1:
            raise ValueError("ConvLSTMPeephole supports stride=1 only")
        self._config = dict(
            input_size=input_size, output_size=output_size,
            kernel_i=kernel_i, kernel_c=kernel_c, stride=stride,
            with_peephole=with_peephole,
        )
        self.input_size, self.output_size = input_size, output_size
        self.kernel_i, self.kernel_c = kernel_i, kernel_c
        self.with_peephole = with_peephole
        self.hidden_size = output_size
        self.reset()

    def reset(self):
        k_i, k_c = self.kernel_i, self.kernel_c
        fan = self.input_size * k_i * k_i
        stdv = 1.0 / math.sqrt(max(1, fan))
        self.w_i = _uniform(
            (4 * self.output_size, self.input_size, k_i, k_i), stdv
        )
        stdv_h = 1.0 / math.sqrt(max(1, self.output_size * k_c * k_c))
        self.w_h = _uniform(
            (4 * self.output_size, self.output_size, k_c, k_c), stdv_h
        )
        self.b = _to_device(np.zeros(4 * self.output_size, dtype=np.float32))
        if self.with_peephole:
            self.p_i = _uniform((self.output_size,), stdv)
            self.p_f = _uniform((self.output_size,), stdv)
            self.p_o = _uniform((self.output_size,), stdv)
        else:
            self.p_i = self.p_f = self.p_o = None
        return self

    def _conv(self, x, w, dtype):
        import jax.lax as lax

        return lax.conv_general_dilated(
            x,
            w.astype(dtype),
            window_strides=(1, 1),
            padding="SAME",
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )

    def precompute(self, params, x, *, training=False, rng=None):
        # x: (B, T, C, H, W) -> fold time into batch for one big conv
        b, t = x.shape[0], x.shape[1]
        merged = x.reshape((b * t,) + x.shape[2:])
        proj = self._conv(merged, params["w_i"], x.dtype)
        proj = proj + params["b"].astype(x.dtype).reshape(1, -1, 1, 1)
        return proj.reshape((b, t) + proj.shape[1:])

    def init_carry(self, batch, dtype, input_shape=None):
        jnp = _jnp()
        if input_shape is None:
            raise ValueError("ConvLSTMPeephole needs the input shape")
        h, w = input_shape[-2], input_shape[-1]
        z = jnp.zeros((batch, self.output_size, h, w), dtype=dtype)
        return (z, z)

    def step(self, params, carry, proj_t):
        jnp = _jnp()
        h, c = carry
        gates = proj_t + self._conv(h, params["w_h"], h.dtype)
        i, f, g, o = jnp.split(gates, 4, axis=1)
        import jax

        if self.with_peephole:
            pk = lambda k: params[k].astype(c.dtype).reshape(1, -1, 1, 1)
            i = i + pk("p_i") * c
            f = f + pk("p_f") * c
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        if self.with_peephole:
            o = o + params["p_o"].astype(c.dtype).reshape(1, -1, 1, 1) * c_new
        o = jax.nn.sigmoid(o)
        h_new = o * jnp.tanh(c_new)
        return (h_new, c_new), h_new

    def __repr__(self):
        return f"ConvLSTMPeephole({self.input_size}, {self.output_size})"
