"""Graph container — DAG execution.

Rebuild of «bigdl»/nn/Graph.scala + «bigdl»/utils/DirectedGraph.scala
(SURVEY.md §2.1 "Graph container": topological sort at build, fwd/bwd
scheduling, Input/Output nodes; backward replays reverse topo order and
sums fan-in gradients).

The rebuild only needs the *forward* scheduler: reverse-topo backward and
fan-in gradient summation fall out of ``jax.vjp`` over the whole-graph
pure apply.  The reference's ``DynamicGraph`` (data-dependent control
flow) maps to ``lax.cond``/``lax.while_loop`` inside individual modules
rather than a separate graph engine — under XLA the *static* graph is the
only graph.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from bigdl_tpu.nn.module import AbstractModule, Container


class Node:
    """A module wired into a DAG (reference: «bigdl»/utils/Node.scala)."""

    _counter = 0

    def __init__(self, module: AbstractModule, prev_nodes: Sequence["Node"] = ()):
        Node._counter += 1
        self.id = Node._counter
        self.module = module
        self.prev_nodes: List[Node] = list(prev_nodes)
        # back-edge source for cyclic graphs (DynamicGraph): set via
        # feedback_from(); NOT in prev_nodes so topo sort ignores it
        self.feedback_node: Optional["Node"] = None

    def feedback_from(self, src: "Node"):
        """Declare ``src`` as this node's feedback source (the cycle's
        back-edge; reference: TF NextIteration input).  Only meaningful
        on a NextIteration node inside a DynamicGraph."""
        self.feedback_node = src
        return self

    def __repr__(self):
        return f"Node[{self.id}]({self.module!r})"


def _as_nodes(nodes):
    flat = []
    for n in nodes:
        if isinstance(n, (list, tuple)):
            flat.extend(n)
        elif n is not None:
            flat.append(n)
    return flat


class _InputModule(AbstractModule):
    def update_output_pure(self, params, input, *, training=False, rng=None):
        return input

    def __repr__(self):
        return "Input"


def Input(name: Optional[str] = None):
    """Reference: «bigdl»/nn/Input.scala — a placeholder source node."""
    m = _InputModule()
    if name:
        m.set_name(name)
    return Node(m, [])


class Graph(Container):
    """«bigdl»/nn/Graph.scala (StaticGraph).

    Built from output nodes + input nodes; executes children in
    topological order.  A node with multiple predecessors receives a
    *table* (tuple) of their outputs, matching the reference's Table
    convention.
    """

    def __init__(self, input, output):
        super().__init__()
        self.input_nodes: List[Node] = (
            list(input) if isinstance(input, (list, tuple)) else [input]
        )
        self.output_nodes: List[Node] = (
            list(output) if isinstance(output, (list, tuple)) else [output]
        )
        self._topo = self._topological_sort()
        # children registered in topo order so params()/state() line up
        for node in self._topo:
            self.modules.append(node.module)
        self._node_index = {node.id: i for i, node in enumerate(self._topo)}

    # -------------------------------------------------------------- topology
    def _topological_sort(self) -> List[Node]:
        visited, order, on_stack = set(), [], set()

        def visit(node: Node):
            if node.id in visited:
                return
            if node.id in on_stack:
                raise ValueError("Graph contains a cycle")
            on_stack.add(node.id)
            for p in node.prev_nodes:
                visit(p)
            on_stack.discard(node.id)
            visited.add(node.id)
            order.append(node)

        for out in self.output_nodes:
            visit(out)
        # inputs may be disconnected placeholders; make sure they're present
        for inp in self.input_nodes:
            if inp.id not in visited:
                order.insert(0, inp)
                visited.add(inp.id)
        return order

    def topo_order(self) -> List[Node]:
        """Nodes in execution order (used by the Caffe/TF exporters)."""
        return list(self._topo)

    # --------------------------------------------------------------- forward
    def _as_input_list(self, input):
        if len(self.input_nodes) == 1 and not isinstance(input, (tuple, list)):
            inputs = [input]
        else:
            inputs = list(input)
        if len(inputs) != len(self.input_nodes):
            raise ValueError(
                f"Graph expects {len(self.input_nodes)} inputs, got {len(inputs)}"
            )
        return inputs

    def _run_topo(self, params, state, inputs, feed_vals=None, *,
                  training=False, rng=None):
        """One pass over the topo order.  ``feed_vals`` (node.id -> value),
        used by DynamicGraph, overrides a node's output without executing
        it (the cycle's carried value).  Returns (values, new_state)."""
        import jax

        values = {}
        new_state = {}
        input_ids = {n.id: i for i, n in enumerate(self.input_nodes)}
        for node in self._topo:
            i = self._node_index[node.id]
            key = str(i)
            if feed_vals is not None and node.id in feed_vals:
                values[node.id] = feed_vals[node.id]
                new_state[key] = state[key]
                continue
            if node.id in input_ids:
                x = inputs[input_ids[node.id]]
            elif len(node.prev_nodes) == 1:
                x = values[node.prev_nodes[0].id]
            else:
                x = tuple(values[p.id] for p in node.prev_nodes)
            r = None if rng is None else jax.random.fold_in(rng, i)
            y, s = node.module.apply(
                params[key], state[key], x, training=training, rng=r
            )
            values[node.id] = y
            new_state[key] = s
        return values, new_state

    def apply(self, params, state, input, *, training=False, rng=None):
        inputs = self._as_input_list(input)
        values, new_state = self._run_topo(
            params, state, inputs, training=training, rng=rng
        )
        outs = tuple(values[n.id] for n in self.output_nodes)
        return (outs[0] if len(outs) == 1 else outs), new_state

    def __repr__(self):
        return f"Graph({len(self._topo)} nodes)"


class DynamicGraph(Graph):
    """Reference: ⟦«bigdl»/nn/Graph.scala⟧ ``DynamicGraph`` — execution
    that supports control flow, including cycles (VERDICT r2 #6).

    TPU-first lowering (see nn/control_ops.py docstring): the reference
    schedules nodes eagerly so a cycle simply re-executes; under XLA the
    cycle becomes a **fixed-length masked ``lax.scan``** over the graph
    body.  ``NextIteration`` nodes (back-edge declared via
    ``node.feedback_from(src)``) carry values between iterations; a
    ``LoopCondition`` node's scalar-bool output gates a mask that
    freezes the carry once false — same results as a data-dependent
    trip count, but static shapes, reverse-differentiable, and
    MXU-friendly.  ``max_iterations`` bounds the unroll (the compiled
    program always scans that many steps; masked steps are cheap).

    ⚠ Loop semantics are **do-while**: the body executes at least once
    (the graph's outputs only exist downstream of the body, so a
    zero-trip result is undefinable here), and the condition — computed
    within the same pass — gates every subsequent iteration.  A loop
    whose trip count can be zero needs :class:`WhileLoop`
    (``lax.while_loop``), which pre-checks the condition like TF.

    Acyclic DynamicGraphs (e.g. Switch/Merge conditionals) execute
    exactly like the static Graph — select semantics make the DAG
    engine sufficient.
    """

    def __init__(self, input, output, max_iterations: int = 32,
                 condition: Optional[Node] = None):
        # the LoopCondition chain is often a side branch unreachable from
        # the outputs (it gates, it doesn't feed) — pass it explicitly
        self._condition_node = condition
        super().__init__(input, output)
        self._config = {"max_iterations": max_iterations}
        self.max_iterations = max_iterations
        from bigdl_tpu.nn.control_ops import LoopCondition, NextIteration

        self._feedback_nodes = [
            n for n in self._topo
            if isinstance(n.module, NextIteration) and n.feedback_node is not None
        ]
        self._cond_nodes = [
            n for n in self._topo if isinstance(n.module, LoopCondition)
        ]

    def _topological_sort(self) -> List[Node]:
        """Graph's sort from the outputs, widened to (a) the explicit
        condition node and (b) the transitive closure over feedback
        back-edges: a feedback source's chain must execute every
        iteration even when no output depends on it within-iteration."""
        visited, order, on_stack = set(), [], set()

        def visit(node: Node):
            if node.id in visited:
                return
            if node.id in on_stack:
                raise ValueError(
                    "DynamicGraph: within-iteration cycle — feedback "
                    "edges must go through NextIteration.feedback_from()"
                )
            on_stack.add(node.id)
            for p in node.prev_nodes:
                visit(p)
            on_stack.discard(node.id)
            visited.add(node.id)
            order.append(node)

        for out in self.output_nodes:
            visit(out)
        if self._condition_node is not None:
            visit(self._condition_node)
        # fixpoint: feedback sources (and their chains) join the order
        changed = True
        while changed:
            changed = False
            for node in list(order):
                fb = node.feedback_node
                if fb is not None and fb.id not in visited:
                    visit(fb)
                    changed = True
        for inp in self.input_nodes:
            if inp.id not in visited:
                order.insert(0, inp)
                visited.add(inp.id)
        return order

    def apply(self, params, state, input, *, training=False, rng=None):
        import jax
        import jax.numpy as jnp
        from jax import lax

        if not self._feedback_nodes:
            return super().apply(params, state, input, training=training,
                                 rng=rng)

        inputs = self._as_input_list(input)

        feed_ids = [n.id for n in self._feedback_nodes]
        src_ids = {n.id: n.feedback_node.id for n in self._feedback_nodes}
        out_ids = [n.id for n in self.output_nodes]

        def one_iter(feed_vals, it):
            r = None if rng is None else jax.random.fold_in(rng, it)
            values, new_state = self._run_topo(
                params, state, inputs,
                feed_vals, training=training, rng=r,
            )
            next_feed = {fid: values[src_ids[fid]] for fid in feed_ids}
            outs = tuple(values[oid] for oid in out_ids)
            if self._cond_nodes:
                cond = jnp.asarray(
                    values[self._cond_nodes[0].id], bool
                ).reshape(())
            else:
                cond = jnp.asarray(True)
            return next_feed, outs, cond, new_state

        # iteration 0 eager-in-trace: NextIteration uses its init edge
        feed, outs, alive, new_state = one_iter(None, 0)

        def body(carry, it):
            feed, outs, alive = carry
            new_feed, new_outs, cond, _ = one_iter(feed, it)
            keep = lambda new, old: jax.tree.map(
                lambda a, b: jnp.where(alive, a, b), new, old
            )
            feed = keep(new_feed, feed)
            outs = keep(new_outs, outs)
            alive = jnp.logical_and(alive, cond)
            return (feed, outs, alive), None

        if self.max_iterations > 1:
            (feed, outs, alive), _ = lax.scan(
                body, (feed, outs, alive),
                jnp.arange(1, self.max_iterations),
            )
        # loop-carried module state is not supported (the masked-scan
        # lowering would need per-iteration state trees); iteration-0
        # state is returned — keep looped bodies stateless
        return (outs[0] if len(outs) == 1 else outs), new_state

    def __repr__(self):
        return (f"DynamicGraph({len(self._topo)} nodes, "
                f"{len(self._feedback_nodes)} back-edges)")


def Model(input, output):
    """Python-BigDL spelling («py»/nn/layer.py Model) for Graph."""
    return Graph(input, output)
