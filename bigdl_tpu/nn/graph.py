"""Graph container — DAG execution.

Rebuild of «bigdl»/nn/Graph.scala + «bigdl»/utils/DirectedGraph.scala
(SURVEY.md §2.1 "Graph container": topological sort at build, fwd/bwd
scheduling, Input/Output nodes; backward replays reverse topo order and
sums fan-in gradients).

The rebuild only needs the *forward* scheduler: reverse-topo backward and
fan-in gradient summation fall out of ``jax.vjp`` over the whole-graph
pure apply.  The reference's ``DynamicGraph`` (data-dependent control
flow) maps to ``lax.cond``/``lax.while_loop`` inside individual modules
rather than a separate graph engine — under XLA the *static* graph is the
only graph.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from bigdl_tpu.nn.module import AbstractModule, Container


class Node:
    """A module wired into a DAG (reference: «bigdl»/utils/Node.scala)."""

    _counter = 0

    def __init__(self, module: AbstractModule, prev_nodes: Sequence["Node"] = ()):
        Node._counter += 1
        self.id = Node._counter
        self.module = module
        self.prev_nodes: List[Node] = list(prev_nodes)

    def __repr__(self):
        return f"Node[{self.id}]({self.module!r})"


def _as_nodes(nodes):
    flat = []
    for n in nodes:
        if isinstance(n, (list, tuple)):
            flat.extend(n)
        elif n is not None:
            flat.append(n)
    return flat


class _InputModule(AbstractModule):
    def update_output_pure(self, params, input, *, training=False, rng=None):
        return input

    def __repr__(self):
        return "Input"


def Input(name: Optional[str] = None):
    """Reference: «bigdl»/nn/Input.scala — a placeholder source node."""
    m = _InputModule()
    if name:
        m.set_name(name)
    return Node(m, [])


class Graph(Container):
    """«bigdl»/nn/Graph.scala (StaticGraph).

    Built from output nodes + input nodes; executes children in
    topological order.  A node with multiple predecessors receives a
    *table* (tuple) of their outputs, matching the reference's Table
    convention.
    """

    def __init__(self, input, output):
        super().__init__()
        self.input_nodes: List[Node] = (
            list(input) if isinstance(input, (list, tuple)) else [input]
        )
        self.output_nodes: List[Node] = (
            list(output) if isinstance(output, (list, tuple)) else [output]
        )
        self._topo = self._topological_sort()
        # children registered in topo order so params()/state() line up
        for node in self._topo:
            self.modules.append(node.module)
        self._node_index = {node.id: i for i, node in enumerate(self._topo)}

    # -------------------------------------------------------------- topology
    def _topological_sort(self) -> List[Node]:
        visited, order, on_stack = set(), [], set()

        def visit(node: Node):
            if node.id in visited:
                return
            if node.id in on_stack:
                raise ValueError("Graph contains a cycle")
            on_stack.add(node.id)
            for p in node.prev_nodes:
                visit(p)
            on_stack.discard(node.id)
            visited.add(node.id)
            order.append(node)

        for out in self.output_nodes:
            visit(out)
        # inputs may be disconnected placeholders; make sure they're present
        for inp in self.input_nodes:
            if inp.id not in visited:
                order.insert(0, inp)
                visited.add(inp.id)
        return order

    def topo_order(self) -> List[Node]:
        """Nodes in execution order (used by the Caffe/TF exporters)."""
        return list(self._topo)

    # --------------------------------------------------------------- forward
    def apply(self, params, state, input, *, training=False, rng=None):
        import jax

        if len(self.input_nodes) == 1 and not isinstance(input, (tuple, list)):
            inputs = [input]
        else:
            inputs = list(input)
        if len(inputs) != len(self.input_nodes):
            raise ValueError(
                f"Graph expects {len(self.input_nodes)} inputs, got {len(inputs)}"
            )
        values = {}
        new_state = {}
        input_ids = {n.id: i for i, n in enumerate(self.input_nodes)}
        for node in self._topo:
            i = self._node_index[node.id]
            key = str(i)
            if node.id in input_ids:
                x = inputs[input_ids[node.id]]
            elif len(node.prev_nodes) == 1:
                x = values[node.prev_nodes[0].id]
            else:
                x = tuple(values[p.id] for p in node.prev_nodes)
            r = None if rng is None else jax.random.fold_in(rng, i)
            y, s = node.module.apply(
                params[key], state[key], x, training=training, rng=r
            )
            values[node.id] = y
            new_state[key] = s
        outs = tuple(values[n.id] for n in self.output_nodes)
        return (outs[0] if len(outs) == 1 else outs), new_state

    def __repr__(self):
        return f"Graph({len(self._topo)} nodes)"


def Model(input, output):
    """Python-BigDL spelling («py»/nn/layer.py Model) for Graph."""
    return Graph(input, output)
