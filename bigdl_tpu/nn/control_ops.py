"""Control-flow ops + dynamic execution support.

Rebuild of the reference's DynamicGraph control-flow surface
(⟦«bigdl»/nn/Graph.scala⟧ DynamicGraph + ⟦«bigdl»/nn/ops/⟧ control ops:
SwitchOps/MergeOps/LoopCondition/NextIteration, used by the TF loader —
SURVEY.md §2.1 "Graph container", VERDICT r2 #6).

TPU-first design note.  The reference executes control flow *eagerly*
on the JVM: Switch routes a tensor to one of two live branches and the
dead branch never runs.  Under XLA everything is traced once, so the
rebuild lowers the same ops to compiler-friendly primitives instead of
an eager scheduler:

* ``SwitchOps``/``MergeOps`` use **select semantics**: both branches
  trace, ``Merge`` keeps the branch chosen by the predicate
  (``jnp.where``).  For the pure modules the loader builds, this is
  observationally equivalent to branch pruning, fuses into the
  surrounding HLO, and is differentiable.  (XLA itself lowers small TF
  conds exactly this way.)
* ``IfElse`` maps to ``lax.cond`` — a *real* short-circuit when the
  branches are expensive; also differentiable.
* Cycles (``NextIteration`` feedback + ``LoopCondition``) lower to a
  fixed-length masked ``lax.scan`` in ``DynamicGraph`` — reverse-mode
  differentiable, static shapes, no data-dependent trip count in the
  compiled program (the mask freezes the carry once the condition goes
  false).  ``WhileLoop`` offers the unbounded ``lax.while_loop``
  variant for forward-only use.
"""

from __future__ import annotations

from bigdl_tpu.nn.module import AbstractModule, Container


def _jnp():
    import jax.numpy as jnp

    return jnp


class SwitchOps(AbstractModule):
    """Reference: ⟦«bigdl»/nn/ops/Switch⟧ (TF ``Switch``).

    Input ``(data, pred)`` -> output ``(data, data)``: element 0 feeds
    the false branch, element 1 the true branch.  Select semantics:
    both branches receive (and compute on) the live tensor; the
    matching :class:`MergeOps` — wired with the same predicate —
    selects the taken branch's result (see module docstring)."""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        data, pred = input
        return (data, data)


class MergeOps(AbstractModule):
    """Reference: ⟦«bigdl»/nn/ops/Merge⟧ (TF ``Merge``).

    Input ``(false_data, true_data, pred)`` — the two branch results
    plus the controlling Switch's predicate — returns
    ``where(pred, true_data, false_data)``.  (TF's Merge has no pred
    input — it takes whichever branch is live; under select semantics
    both are live, so the predicate is wired explicitly.  The TF
    loader finds it by walking to the controlling Switch.)"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        jnp = _jnp()
        false_data, true_data, pred = input
        return jnp.where(pred, true_data, false_data)


class IfElse(Container):
    """``lax.cond`` over two child modules (the short-circuit variant).

    Input ``(pred, data)``; runs ``then_module(data)`` when ``pred``
    else ``else_module(data)``.  Branches must produce matching
    shapes/dtypes (an XLA requirement the reference never had — its
    eager scheduler allowed ragged branches)."""

    def __init__(self, then_module: AbstractModule = None,
                 else_module: AbstractModule = None):
        # default-None constructor keeps the generic serializer path
        # (construct empty, then graft children) working
        super().__init__()
        self._config = {}
        if then_module is not None:
            self.add(then_module)
        if else_module is not None:
            self.add(else_module)

    def apply(self, params, state, input, *, training=False, rng=None):
        import jax
        from jax import lax

        pred, data = input
        then_m, else_m = self.modules

        def run_then(operand):
            p, s, x, r = operand
            out, _ = then_m.apply(p["0"], s["0"], x, training=training, rng=r)
            return out

        def run_else(operand):
            p, s, x, r = operand
            out, _ = else_m.apply(p["1"], s["1"], x, training=training, rng=r)
            return out

        r = rng if rng is None else jax.random.fold_in(rng, 0)
        jnp = _jnp()
        out = lax.cond(
            jnp.asarray(pred, bool).reshape(()),
            run_then, run_else, (params, state, data, r),
        )
        # branch-local state (e.g. BN running stats) cannot cross a cond
        # with divergent structures; state passes through unchanged —
        # use stateless branches (the reference's control ops are too)
        return out, dict(state)

    def __repr__(self):
        return f"IfElse({self.modules[0]!r}, {self.modules[1]!r})"


class WhileLoop(Container):
    """``lax.while_loop`` over a condition module and a body module.

    Input = initial loop carry.  ``cond_module(carry)`` must return a
    scalar bool; ``body_module(carry)`` the next carry (same pytree
    structure/shapes — XLA requirement).  Forward-only: reverse-mode
    through an unbounded while is undefined; use :class:`DynamicGraph`
    with ``max_iterations`` (masked scan) when gradients are needed."""

    def __init__(self, cond_module: AbstractModule = None,
                 body_module: AbstractModule = None):
        super().__init__()
        self._config = {}
        if cond_module is not None:
            self.add(cond_module)
        if body_module is not None:
            self.add(body_module)

    def apply(self, params, state, input, *, training=False, rng=None):
        from jax import lax

        cond_m, body_m = self.modules
        jnp = _jnp()

        def cond_fn(carry):
            out, _ = cond_m.apply(params["0"], state["0"], carry,
                                  training=training, rng=None)
            return jnp.asarray(out, bool).reshape(())

        def body_fn(carry):
            out, _ = body_m.apply(params["1"], state["1"], carry,
                                  training=training, rng=None)
            return out

        return lax.while_loop(cond_fn, body_fn, input), dict(state)

    def __repr__(self):
        return f"WhileLoop({self.modules[0]!r}, {self.modules[1]!r})"


class LoopCondition(AbstractModule):
    """Reference: ⟦«bigdl»/nn/ops/LoopCondition⟧ (TF ``LoopCond``).

    Marks its (scalar-bool) input as the continue-condition of the
    enclosing :class:`DynamicGraph` iteration.  Passes the value
    through so it can also be consumed downstream."""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return input


class NextIteration(AbstractModule):
    """Reference: TF ``NextIteration`` — the feedback edge of a cycle.

    Wired with its *initial value* node as the ordinary predecessor and
    the *feedback source* attached after the fact via
    ``node.feedback_from(src_node)`` (a back-edge the topological sort
    must not follow).  On iteration 0 it emits the initial value; on
    iteration t>0, the feedback source's value from iteration t-1."""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return input
