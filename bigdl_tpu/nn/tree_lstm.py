"""BinaryTreeLSTM — tree-structured composition (Tai et al. 2015).

Rebuild of ⟦«bigdl»/nn/BinaryTreeLSTM.scala⟧ (the tree-LSTM sentiment
example's model — SURVEY.md §2.1 "Examples": tree-LSTM sentiment).

TPU-first encoding: the reference walks a pointer-based tree object per
sample on the JVM; under XLA the tree becomes **arrays** and the walk a
``lax.scan`` with static shapes:

* nodes are topologically numbered with **node 0 = root** and every
  child index strictly greater than its parent's, so one reverse scan
  (i = N-1 … 0) visits children before parents;
* ``children``: (B, N, 2) int32 — left/right child indices, ``-1`` on
  both marks a leaf, ``-1`` rows pad unused node slots;
* ``embeddings``: (B, N, D) — leaf word vectors (zeros on internal
  nodes).

Each scan step computes BOTH the leaf transform and the binary
composer for node *i* across the whole batch and selects per sample
with ``jnp.where`` — branch-free, fixed shapes, MXU-batched gates.
Output: (B, N, H) hidden states for every node (root at index 0, the
convention ``TreeNNAccuracy`` reads).
"""

from __future__ import annotations

import numpy as np

from bigdl_tpu.common import RandomGenerator
from bigdl_tpu.nn.module import AbstractModule


def _jnp():
    import jax.numpy as jnp

    return jnp


class BinaryTreeLSTM(AbstractModule):
    """Input ``(embeddings (B,N,D), children (B,N,2))`` ->
    hidden states (B, N, H)."""

    param_names = ("leaf_w", "leaf_b", "comp_w", "comp_b")

    def __init__(self, input_size: int, hidden_size: int):
        super().__init__()
        self._config = dict(input_size=input_size, hidden_size=hidden_size)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.reset()

    def reset(self):
        h, d = self.hidden_size, self.input_size
        jnp = _jnp()
        s_leaf = 1.0 / np.sqrt(max(1, d))
        s_comp = 1.0 / np.sqrt(max(1, 2 * h))
        # leaf: x -> (i, o, u) gates; composer: [h_l, h_r] -> (i, f_l,
        # f_r, o, u) gates
        self.leaf_w = jnp.asarray(
            RandomGenerator.RNG.uniform(-s_leaf, s_leaf, (d, 3 * h)),
            jnp.float32)
        self.leaf_b = jnp.zeros((3 * h,), jnp.float32)
        self.comp_w = jnp.asarray(
            RandomGenerator.RNG.uniform(-s_comp, s_comp, (2 * h, 5 * h)),
            jnp.float32)
        self.comp_b = jnp.zeros((5 * h,), jnp.float32)
        return self

    def apply(self, params, state, input, *, training=False, rng=None):
        import jax
        from jax import lax

        jnp = _jnp()
        emb, children = input
        children = jnp.asarray(children, jnp.int32)
        b, n, _ = emb.shape
        hsz = self.hidden_size

        leaf_w, leaf_b = params["leaf_w"], params["leaf_b"]
        comp_w, comp_b = params["comp_w"], params["comp_b"]

        def step(carry, i):
            h_buf, c_buf = carry  # (B, N, H) each
            kid = children[:, i, :]                      # (B, 2)
            is_leaf = jnp.all(kid < 0, axis=-1)          # (B,)
            safe = jnp.clip(kid, 0, n - 1)
            h_l = jnp.take_along_axis(
                h_buf, safe[:, 0][:, None, None].repeat(hsz, -1), axis=1
            )[:, 0]
            h_r = jnp.take_along_axis(
                h_buf, safe[:, 1][:, None, None].repeat(hsz, -1), axis=1
            )[:, 0]
            c_l = jnp.take_along_axis(
                c_buf, safe[:, 0][:, None, None].repeat(hsz, -1), axis=1
            )[:, 0]
            c_r = jnp.take_along_axis(
                c_buf, safe[:, 1][:, None, None].repeat(hsz, -1), axis=1
            )[:, 0]

            # leaf transform
            g = emb[:, i, :] @ leaf_w + leaf_b           # (B, 3H)
            li, lo, lu = jnp.split(g, 3, axis=-1)
            lc = jax.nn.sigmoid(li) * jnp.tanh(lu)
            lh = jax.nn.sigmoid(lo) * jnp.tanh(lc)

            # binary composer
            hc = jnp.concatenate([h_l, h_r], axis=-1)    # (B, 2H)
            gg = hc @ comp_w + comp_b                    # (B, 5H)
            ci, cfl, cfr, co, cu = jnp.split(gg, 5, axis=-1)
            cc = (jax.nn.sigmoid(ci) * jnp.tanh(cu)
                  + jax.nn.sigmoid(cfl) * c_l
                  + jax.nn.sigmoid(cfr) * c_r)
            ch = jax.nn.sigmoid(co) * jnp.tanh(cc)

            sel = is_leaf[:, None]
            new_h = jnp.where(sel, lh, ch)
            new_c = jnp.where(sel, lc, cc)
            h_buf = lax.dynamic_update_slice(
                h_buf, new_h[:, None, :], (0, i, 0))
            c_buf = lax.dynamic_update_slice(
                c_buf, new_c[:, None, :], (0, i, 0))
            return (h_buf, c_buf), None

        init = (jnp.zeros((b, n, hsz), emb.dtype),
                jnp.zeros((b, n, hsz), emb.dtype))
        # reverse order: children (higher indices) before parents
        (h_buf, _), _ = lax.scan(step, init, jnp.arange(n - 1, -1, -1))
        return h_buf, state

    def __repr__(self):
        return (f"BinaryTreeLSTM({self.input_size} -> {self.hidden_size})")


def random_binary_trees(batch: int, n_leaves: int, seed: int = 0):
    """Batch of random full binary tree skeletons in the module's array
    encoding: returns (children (B,N,2) int32, leaf_slots list-of-lists)
    with N = 2*n_leaves - 1, node 0 = root, child indices > parent's.

    Allocation: each subtree with k leaves owns a contiguous block of
    2k-1 node slots starting at its root — so children always land at
    higher indices than their parent, the reverse-scan invariant."""
    rs = np.random.RandomState(seed)
    n = 2 * n_leaves - 1
    children = np.full((batch, n, 2), -1, np.int32)
    leaf_slots = []
    for bi in range(batch):
        leaves = []

        def build(node: int, k: int):
            if k == 1:
                leaves.append(node)
                return
            kl = int(rs.randint(1, k))  # leaves in the left subtree
            left = node + 1
            right = left + (2 * kl - 1)
            children[bi, node] = (left, right)
            build(left, kl)
            build(right, k - kl)

        build(0, n_leaves)
        leaf_slots.append(sorted(leaves))
    return children, leaf_slots
