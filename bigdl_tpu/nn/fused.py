"""Fused conv + BatchNorm (+ReLU) module and model transform.

TPU-era fusion (no reference analogue — the reference's fusion layer
is the mkldnn backend's ConvBnRelu, SURVEY.md §2.1, deleted by design):
``SpatialConvolutionBatchNorm`` computes a bias-free 1x1 or 3x3
convolution with the BN statistics accumulated in the conv epilogue
(ops/conv_bn.py Pallas kernels), so training-mode BN never re-reads
the activation.  Semantics match ``SpatialConvolution(with_bias=False)
-> SpatialBatchNormalization (-> ReLU)`` exactly: same shifted
single-pass statistics and numerics contract, same running-stat
EMA conventions (layers.py BatchNormalization).

``fuse_conv_bn(model)`` rewrites those triples inside ``Sequential``
containers in place and returns the model; weights are shared (same
arrays), so a fused model stays checkpoint-compatible with its source
architecture's values at fuse time.
"""

from __future__ import annotations


from bigdl_tpu.nn.layers import (
    MsraFiller,
    ReLU,
    SpatialBatchNormalization,
    SpatialConvolution,
    _to_device,
)
from bigdl_tpu.nn.module import AbstractModule, Sequential


def _jnp():
    import jax.numpy as jnp

    return jnp


class SpatialConvolutionBatchNorm(AbstractModule):
    """Fused ``conv (no bias) + SpatialBatchNormalization`` with an
    optional fused ReLU.  Kernel 1 or 3 (torch-style symmetric padding
    ``(k-1)//2``).  Weight layout: (n_output, n_input) for the 1x1 case
    — the kernel as a matrix, kept for checkpoint compatibility — and
    (n_output, n_input, k, k) otherwise."""

    param_names = ("weight", "bn_weight", "bn_bias")
    state_names = ("running_mean", "running_var")

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 stride: int = 1, eps: float = 1e-5,
                 momentum: float = 0.1, with_relu: bool = False,
                 kernel: int = 1):
        super().__init__()
        self._config = dict(
            n_input_plane=n_input_plane, n_output_plane=n_output_plane,
            stride=stride, eps=eps, momentum=momentum, with_relu=with_relu,
            kernel=kernel,
        )
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.stride = stride
        self.eps = eps
        self.momentum = momentum
        self.with_relu = with_relu
        self.kernel = kernel
        self.pad = (kernel - 1) // 2
        jnp = _jnp()
        shape = (n_output_plane, n_input_plane) if kernel == 1 \
            else (n_output_plane, n_input_plane, kernel, kernel)
        fan_in = n_input_plane * kernel * kernel
        w = MsraFiller(False).init(shape, fan_in, n_output_plane)
        self.weight = _to_device(w)
        self.bn_weight = jnp.ones(n_output_plane, dtype=jnp.float32)
        self.bn_bias = jnp.zeros(n_output_plane, dtype=jnp.float32)
        self.running_mean = jnp.zeros(n_output_plane, dtype=jnp.float32)
        self.running_var = jnp.ones(n_output_plane, dtype=jnp.float32)

    @classmethod
    def from_pair(cls, conv: SpatialConvolution,
                  bn: SpatialBatchNormalization, with_relu: bool):
        k = conv.kernel_w
        assert conv.kernel_h == k and k in (1, 3)
        assert conv.stride_w == conv.stride_h
        assert conv.pad_w == conv.pad_h == (k - 1) // 2
        assert not conv.with_bias and conv.n_group == 1
        m = cls(conv.n_input_plane, conv.n_output_plane,
                stride=conv.stride_w, eps=bn.eps, momentum=bn.momentum,
                with_relu=with_relu, kernel=k)
        m.weight = conv.weight[:, :, 0, 0] if k == 1 else conv.weight
        if bn.affine:
            m.bn_weight = bn.weight
            m.bn_bias = bn.bias
        m.running_mean = bn.running_mean
        m.running_var = bn.running_var
        if getattr(conv, "_name", None):
            m.set_name(conv._name + "+bn")
        return m

    def _fold(self, params, mean, var, center):
        jnp = _jnp()
        import jax.lax as lax

        inv = lax.rsqrt(var + self.eps)
        scale = inv * params["bn_weight"].astype(jnp.float32)
        offset = params["bn_bias"].astype(jnp.float32) \
            - (mean - center) * scale
        return scale, offset

    def apply(self, params, state, input, *, training=False, rng=None):
        jnp = _jnp()
        import jax.lax as lax

        from bigdl_tpu.ops.conv_bn import conv_bn_stats

        w = params["weight"].astype(input.dtype)
        rm = state["running_mean"]

        def _normalize(y, scale, offset, center):
            dt = y.dtype
            out = (y - center.astype(dt)[None, :, None, None]) \
                * scale.astype(dt)[None, :, None, None] \
                + offset.astype(dt)[None, :, None, None]
            return jnp.maximum(out, 0) if self.with_relu else out

        if not training:
            if self.kernel == 1:
                if self.stride != 1:
                    input = input[:, :, ::self.stride, ::self.stride]
                y = jnp.einsum("oc,nchw->nohw", w, input)
            else:
                y = lax.conv_general_dilated(
                    input, w, (self.stride, self.stride),
                    [(self.pad, self.pad), (self.pad, self.pad)],
                    dimension_numbers=("NCHW", "OIHW", "NCHW"),
                )
            scale, offset = self._fold(
                params, rm, state["running_var"], rm)
            return _normalize(y, scale, offset, rm), state

        # epilogue statistics centered on the loop-carried running mean,
        # straight-line — the same design, numerics contract (exact
        # mean at any shift, geometrically self-healing variance), and
        # chip measurements as BatchNormalization in layers.py: every
        # guarded rescue variant (lax.cond, jnp.where-subsample)
        # measured far slower under the relay's 2026-07 XLA
        # (scripts/bn_ab.py).
        y, s1, s2 = conv_bn_stats(input, w, rm, stride=self.stride,
                                  pad=self.pad)
        n = y.shape[0] * y.shape[2] * y.shape[3]
        d = s1 / n
        m2 = s2 / n
        mean = rm + d  # exact at any shift
        var = jnp.maximum(m2 - lax.square(d), 0.0)
        scale, offset = self._fold(params, mean, var, rm)
        out = _normalize(y, scale, offset, rm)
        unbiased = var * (n / max(1, n - 1))
        new_state = {
            "running_mean": (1 - self.momentum) * rm + self.momentum * mean,
            "running_var": (1 - self.momentum) * state["running_var"]
            + self.momentum * unbiased,
        }
        return out, new_state

    def __repr__(self):
        tail = " + ReLU" if self.with_relu else ""
        return (f"SpatialConvolutionBatchNorm({self.n_input_plane} -> "
                f"{self.n_output_plane}, {self.kernel}x{self.kernel}"
                f"/{self.stride}{tail})")


def _is_fusable_conv(m, kernels=(1, 3)):
    # 1x1 and 3x3 torch-padded convs have Pallas epilogue-stats kernels
    # (ops/conv_bn.py); the 7x7 stem stays on XLA's native conv — its
    # C=3 tap dots would starve the MXU
    return (
        isinstance(m, SpatialConvolution)
        and type(m) is SpatialConvolution
        and m.kernel_w == m.kernel_h
        and m.kernel_w in kernels
        and m.stride_w == m.stride_h
        and m.stride_w in (1, 2)
        and m.pad_w == m.pad_h == (m.kernel_w - 1) // 2
        and m.n_group == 1 and not m.with_bias
    )


def fuse_conv_bn(model, kernels=(1, 3)):
    """Rewrite every ``[1x1/3x3 conv (no bias),
    SpatialBatchNormalization, (ReLU)]`` run inside ``Sequential``
    containers into one ``SpatialConvolutionBatchNorm``, recursively.
    In-place; returns the model.  ``kernels`` restricts which conv
    sizes fuse — ``(1,)`` keeps 3x3s on XLA (useful when a toolchain
    rejects the kxk Pallas kernel; see scripts/mosaic_probe.py)."""
    for child in getattr(model, "modules", []):
        fuse_conv_bn(child, kernels)
    if isinstance(model, Sequential):
        mods = model.modules
        out = []
        i = 0
        while i < len(mods):
            m = mods[i]
            nxt = mods[i + 1] if i + 1 < len(mods) else None
            if (
                _is_fusable_conv(m, kernels)
                and isinstance(nxt, SpatialBatchNormalization)
                and type(nxt) is SpatialBatchNormalization
                and nxt.affine
                and nxt.n_output == m.n_output_plane
            ):
                with_relu = i + 2 < len(mods) and type(mods[i + 2]) is ReLU
                out.append(
                    SpatialConvolutionBatchNorm.from_pair(m, nxt, with_relu)
                )
                i += 3 if with_relu else 2
            else:
                out.append(m)
                i += 1
        model.modules = out
    return model
