"""The module contract + basic containers.

Rebuild of «bigdl»/nn/abstractnn/AbstractModule.scala and
«bigdl»/nn/Sequential.scala.  The reference contract is

    updateOutput / updateGradInput / accGradParameters

with a **hand-written backward per layer — no autograd** (SURVEY.md §1 L2).
The rebuild keeps that API surface (``forward``/``backward``/
``update_grad_input``/``acc_grad_parameters``, mutable ``output``/
``gradInput``, ``zeroGradParameters``...) but derives every backward from
``jax.vjp`` over a **pure functional core**:

    apply(params, state, input, *, training, rng) -> (output, new_state)

``params`` is a pytree of ``jnp`` arrays (weights), ``state`` a pytree of
non-trained buffers (e.g. BatchNormalization running stats).  Optimizers
never touch the stateful API: they jit one train step over
``module.apply`` + ``criterion.loss`` — that single XLA program replaces
the reference's per-core threaded replica loop (SURVEY.md §3.2 hot loop).

Parameter *initialisation* stays eager and host-side at construction time,
drawn from the global seedable ``RandomGenerator.RNG`` exactly like the
reference, so seeded unit tests translate directly.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from bigdl_tpu.common import RandomGenerator


def _jnp():
    import jax.numpy as jnp

    return jnp


class AbstractModule:
    """Base of every layer and container."""

    # names of attributes that are trainable parameters / non-trained state
    param_names: tuple = ()
    state_names: tuple = ()

    def __init__(self):
        self.output = None
        self.grad_input = None
        self.is_training = True
        self._name: Optional[str] = None
        self._grad_params = None  # pytree matching params(), lazily allocated
        self._forward_count = 0
        self._last_rng = None

    # ------------------------------------------------------------ functional
    def params(self) -> Dict[str, Any]:
        """Pytree of trainable parameters (empty dict if none)."""
        out = {}
        for n in self.param_names:
            v = getattr(self, n, None)
            if v is not None:
                out[n] = v
        return out

    def set_params(self, params: Dict[str, Any]):
        for n in self.param_names:
            if n in params:
                setattr(self, n, params[n])

    def state(self) -> Dict[str, Any]:
        out = {}
        for n in self.state_names:
            v = getattr(self, n, None)
            if v is not None:
                out[n] = v
        return out

    def set_state(self, state: Dict[str, Any]):
        for n in self.state_names:
            if n in state:
                setattr(self, n, state[n])

    def apply(self, params, state, input, *, training: bool = False, rng=None):
        """Pure forward.  Default: stateless layer delegating to
        :meth:`update_output_pure`."""
        return (
            self.update_output_pure(params, input, training=training, rng=rng),
            state,
        )

    def update_output_pure(self, params, input, *, training: bool = False, rng=None):
        raise NotImplementedError(
            f"{type(self).__name__} must implement update_output_pure or apply"
        )

    # ------------------------------------------------------- stateful parity
    def _next_rng(self):
        import jax

        base = jax.random.key(RandomGenerator.RNG.seed + 1013904223)
        self._forward_count += 1
        self._last_rng = jax.random.fold_in(base, self._forward_count)
        return self._last_rng

    def forward(self, input):
        """Stateful forward (reference: AbstractModule.forward ->
        updateOutput).  Updates ``self.output`` and any internal state
        (e.g. BN running stats when training)."""
        out, new_state = self.apply(
            self.params(),
            self.state(),
            input,
            training=self.is_training,
            rng=self._next_rng(),
        )
        self.set_state(new_state)
        self.output = out
        return out

    update_output = forward  # parity alias (updateOutput)

    def _vjp(self, input):
        import jax

        params = self.params()
        state = self.state()
        rng = self._last_rng

        def f(p, x):
            return self.apply(p, state, x, training=self.is_training, rng=rng)[0]

        return jax.vjp(f, params, input)

    def update_grad_input(self, input, grad_output):
        """Reference: updateGradInput — input gradient only, no parameter
        gradient accumulation."""
        _, vjp_fn = self._vjp(input)
        _, grad_in = vjp_fn(grad_output)
        self.grad_input = grad_in
        return grad_in

    def acc_grad_parameters(self, input, grad_output):
        """Reference: accGradParameters — accumulate parameter gradients."""
        _, vjp_fn = self._vjp(input)
        grad_p, _ = vjp_fn(grad_output)
        self._accumulate(grad_p)

    def backward(self, input, grad_output):
        """updateGradInput + accGradParameters in one vjp call."""
        _, vjp_fn = self._vjp(input)
        grad_p, grad_in = vjp_fn(grad_output)
        self._accumulate(grad_p)
        self.grad_input = grad_in
        return grad_in

    def _accumulate(self, grad_p):
        import jax

        if self._grad_params is None:
            self._grad_params = grad_p
        else:
            self._grad_params = jax.tree.map(
                lambda a, b: a + b, self._grad_params, grad_p
            )

    def zero_grad_parameters(self):
        import jax

        p = self.params()
        jnp = _jnp()
        self._grad_params = jax.tree.map(jnp.zeros_like, p)

    zeroGradParameters = zero_grad_parameters

    def grad_params(self):
        if self._grad_params is None:
            self.zero_grad_parameters()
        return self._grad_params

    def update_parameters(self, learning_rate: float):
        """Reference: updateParameters — vanilla SGD step in place."""
        import jax

        g = self.grad_params()
        p = self.params()
        new_p = jax.tree.map(lambda w, gw: w - learning_rate * gw, p, g)
        self.set_params(new_p)

    def parameters(self):
        """Reference: parameters() -> (Array[Tensor] weights,
        Array[Tensor] gradWeights) — flat leaf lists here."""
        import jax

        w = jax.tree.leaves(self.params())
        g = jax.tree.leaves(self.grad_params())
        return w, g

    # ---------------------------------------------------------- weights I/O
    def _ordered_params(self):
        """(module, attr) pairs in declaration order — weight before bias,
        children in add order — matching the reference's parameters()
        ordering (a dict pytree would sort alphabetically)."""
        return [
            (self, n) for n in self.param_names if getattr(self, n, None) is not None
        ]

    def get_weights(self):
        return [np.asarray(getattr(m, n)) for m, n in self._ordered_params()]

    def set_weights(self, weights):
        jnp = _jnp()
        slots = self._ordered_params()
        if len(weights) != len(slots):
            raise ValueError(
                f"expected {len(slots)} weight arrays, got {len(weights)}"
            )
        for (m, n), new in zip(slots, weights):
            old = getattr(m, n)
            new = jnp.asarray(new, dtype=old.dtype)
            if new.shape != old.shape:
                raise ValueError(f"shape mismatch: {new.shape} vs {old.shape}")
            setattr(m, n, new)
        return self

    # ------------------------------------------------------------ mode/name
    def training(self):
        self.is_training = True
        return self

    def evaluate(self, dataset=None, methods=None, batch_size: int = 32):
        """No args: switch to eval mode (reference ``evaluate()``).
        With a dataset + validation methods: run distributed evaluation
        and return the ValidationResults (reference
        ``model.evaluate(rdd, Array(new Top1Accuracy))`` — SURVEY §3.6),
        sharded over the Engine mesh when one is initialized."""
        self.is_training = False
        if dataset is None:
            return self
        if not methods:
            raise ValueError(
                "evaluate(dataset, methods): pass validation methods, "
                "e.g. [Top1Accuracy()]"
            )
        from bigdl_tpu.dataset import to_dataset
        from bigdl_tpu.optim.evaluator import evaluate_dataset

        return evaluate_dataset(
            self, to_dataset(dataset, batch_size), methods
        )

    def predict(self, features, batch_size: int = 32):
        """Reference: model.predict — batched forward, host outputs."""
        from bigdl_tpu.optim.evaluator import predict as _predict

        return _predict(self, features, batch_size)

    def predict_class(self, features, batch_size: int = 32):
        """Reference: model.predictClass — argmax + 1 (1-based)."""
        from bigdl_tpu.optim.evaluator import predict_class as _pc

        return _pc(self, features, batch_size)

    predictClass = predict_class

    def quantize(self):
        """Reference: AbstractModule.quantize() — swap Linear/Conv layers
        for int8 twins («bigdl»/nn/quantized/, see nn/quantized.py)."""
        from bigdl_tpu.nn.quantized import quantize as _q

        return _q(self)

    def set_name(self, name: str):
        self._name = name
        return self

    setName = set_name

    def get_name(self) -> str:
        return self._name or f"{type(self).__name__}@{id(self):x}"

    getName = get_name

    def reset(self):
        """Re-draw initial parameters from RandomGenerator.RNG."""
        return self

    # ------------------------------------------------------- regularization
    def regularization_loss(self, params) -> Any:
        """Sum of regularizer penalties (reference applies wRegularizer /
        bRegularizer gradients inside accGradParameters; the rebuild adds
        the penalty to the jitted loss instead — same gradients).  A
        frozen module contributes nothing (its parameters must not
        move, including via weight decay)."""
        if getattr(self, "_frozen", False):
            return 0.0
        loss = 0.0
        regs = getattr(self, "_regularizers", None)
        if regs:
            for pname, reg in regs:
                if pname in params:
                    loss = loss + reg(params[pname])
        return loss

    # ---------------------------------------------------------- persistence
    def save(self, path: str, over_write: bool = False):
        """Reference: ``model.save(path)`` — persist through the
        ``.bigdl`` protobuf serializer (see utils/serializer)."""
        import os

        from bigdl_tpu.utils.serializer import save_module

        if not over_write and os.path.exists(path):
            raise FileExistsError(
                f"{path} exists; pass over_write=True (reference "
                "overWrite semantics)")
        return save_module(self, path)

    saveModule = save

    def save_weights(self, path: str, over_write: bool = False):
        """Reference: ``model.saveWeights(path)`` — weights-only npz."""
        import os

        import numpy as np

        if not path.endswith(".npz"):
            path += ".npz"  # np.savez appends it; keep check+return true
        if not over_write and os.path.exists(path):
            raise FileExistsError(
                f"{path} exists; pass over_write=True")
        arrays = {str(i): np.asarray(w)
                  for i, w in enumerate(self.get_weights())}
        np.savez(path, **arrays)
        return path

    def load_weights(self, path: str):
        """Reference: ``model.loadWeights(path)`` — restore npz weights
        in :meth:`get_weights` order."""
        import numpy as np

        with np.load(path) as data:
            weights = [data[str(i)] for i in range(len(data.files))]
        self.set_weights(weights)
        return self

    saveWeights = save_weights
    loadWeights = load_weights

    # reference: model.test(dataset, methods) — evaluation spelling
    def test(self, dataset, methods, batch_size: int = 32):
        return self.evaluate(dataset, methods, batch_size)

    # ------------------------------------------------------------ freezing
    def freeze(self, *names):
        """Reference: ``module.freeze(names*)`` — with no names, freeze
        this module and every descendant; with names, freeze the named
        submodules (recursively).  Frozen parameters receive zero
        updates (the optimizers mask their gradients) and contribute no
        regularization."""
        if not names:
            self._frozen = True
            for m in getattr(self, "modules", []):
                m.freeze()
            return self
        for name in names:
            target = self.find_module(name) if hasattr(self, "find_module") \
                else None
            if target is None:
                raise ValueError(f"freeze: no module named {name!r}")
            target.freeze()
        return self

    def unfreeze(self, *names):
        """Reference: ``module.unFreeze(names*)``."""
        if not names:
            self._frozen = False
            for m in getattr(self, "modules", []):
                m.unfreeze()
            return self
        for name in names:
            target = self.find_module(name) if hasattr(self, "find_module") \
                else None
            if target is None:
                raise ValueError(f"unfreeze: no module named {name!r}")
            target.unfreeze()
        return self

    unFreeze = unfreeze

    def is_frozen(self) -> bool:
        return getattr(self, "_frozen", False)

    def has_frozen(self) -> bool:
        """True when this module or any descendant is frozen."""
        if self.is_frozen():
            return True
        return any(m.has_frozen() for m in getattr(self, "modules", []))

    def grad_mask(self):
        """Pytree shaped like :meth:`params` with 0.0 at frozen
        parameters, 1.0 elsewhere — the optimizers multiply gradients
        by this when any module is frozen."""
        scale = 0.0 if self.is_frozen() else 1.0
        return {n: scale for n in self.params()}

    def get_parameters_table(self):
        """Reference: ``getParametersTable()`` — name-keyed view of each
        parameterised module's tensors."""
        table = {}

        def walk(m):
            for child in getattr(m, "modules", []):
                walk(child)
            p = {n: getattr(m, n) for n in m.param_names
                 if getattr(m, n, None) is not None}
            if p:
                table[m.get_name()] = p

        walk(self)
        return table

    getParametersTable = get_parameters_table

    # ------------------------------------------------------------- graph fn
    def __call__(self, *nodes):
        """Functional-graph sugar: wrap this module in a Node wired to
        predecessor nodes (reference: ``module.inputs(n1, n2)``)."""
        from bigdl_tpu.nn.graph import Node, _as_nodes

        return Node(self, _as_nodes(nodes))

    inputs = __call__

    # ------------------------------------------------------------- helpers
    def __repr__(self):
        return f"{type(self).__name__}"

    # serialization hook: constructor arguments, captured by subclasses
    def get_config(self) -> Dict[str, Any]:
        return dict(getattr(self, "_config", {}))


class Container(AbstractModule):
    """Base container (reference: «bigdl»/nn/Container.scala)."""

    def __init__(self):
        super().__init__()
        self.modules: list[AbstractModule] = []

    def add(self, module: AbstractModule):
        self.modules.append(module)
        return self

    # params/state pytrees keyed by child index (stable structure: every
    # child contributes a key even when empty, so jit retraces never see a
    # structure change)
    def params(self):
        return {str(i): m.params() for i, m in enumerate(self.modules)}

    def set_params(self, params):
        for i, m in enumerate(self.modules):
            m.set_params(params.get(str(i), {}))

    def state(self):
        return {str(i): m.state() for i, m in enumerate(self.modules)}

    def set_state(self, state):
        for i, m in enumerate(self.modules):
            m.set_state(state.get(str(i), {}))

    def training(self):
        super().training()
        for m in self.modules:
            m.training()
        return self

    def evaluate(self, dataset=None, methods=None, batch_size: int = 32):
        for m in self.modules:
            m.evaluate()
        return super().evaluate(dataset, methods, batch_size)

    def reset(self):
        for m in self.modules:
            m.reset()
        return self

    def regularization_loss(self, params):
        if getattr(self, "_frozen", False):
            return 0.0
        loss = 0.0
        for i, m in enumerate(self.modules):
            loss = loss + m.regularization_loss(params.get(str(i), {}))
        return loss

    def grad_mask(self):
        if self.is_frozen():
            import jax

            return jax.tree.map(lambda _: 0.0, self.params())
        return {str(i): m.grad_mask() for i, m in enumerate(self.modules)}

    def _ordered_params(self):
        out = []
        for m in self.modules:
            out.extend(m._ordered_params())
        return out

    def find_module(self, name: str):
        """Reference: Container.apply(name) — find a child by name."""
        for m in self.modules:
            if m._name == name:
                return m
            if isinstance(m, Container):
                found = m.find_module(name)
                if found is not None:
                    return found
        return None


class Remat(Container):
    """Gradient checkpointing (rematerialisation): the wrapped module's
    forward activations are NOT stored for backward — they are
    recomputed from the wrapper's input during the VJP, trading FLOPs
    for HBM (the standard long-context/deep-model memory lever on TPU;
    no reference analogue — the reference's hand-written backwards
    always stored activations).

    ``policy`` optionally names a ``jax.checkpoint_policies`` entry
    (e.g. ``"dots_with_no_batch_dims_saveable"``) so matmul outputs can
    be kept while elementwise intermediates are recomputed.
    """

    def __init__(self, module: AbstractModule = None, policy: str = None):
        super().__init__()
        if policy:
            import jax

            if not hasattr(jax.checkpoint_policies, policy):
                raise ValueError(
                    f"unknown jax.checkpoint_policies entry {policy!r}")
        self._config = dict(policy=policy)
        self.policy = policy
        if module is not None:
            self.add(module)

    def add(self, module: AbstractModule):
        if self.modules:
            raise ValueError(
                "Remat wraps exactly one module; wrap a Sequential for "
                "multi-layer spans")
        return super().add(module)

    def apply(self, params, state, input, *, training=False, rng=None):
        import jax

        if not self.modules:
            raise ValueError("Remat has no wrapped module; add() one")
        child = self.modules[0]

        def fwd(p, s, x):
            return child.apply(p, s, x, training=training, rng=rng)

        if self.policy:
            fwd = jax.checkpoint(
                fwd, policy=getattr(jax.checkpoint_policies, self.policy))
        else:
            fwd = jax.checkpoint(fwd)
        out, new_child_state = fwd(params["0"], state["0"], input)
        return out, {"0": new_child_state}

    def __repr__(self):
        inner = self.modules[0] if self.modules else "?"
        return f"Remat({inner!r})"


class Sequential(Container):
    """Feed-forward chain (reference: «bigdl»/nn/Sequential.scala;
    forward loops ``output = module.forward(prevOutput)`` — SURVEY.md
    §3.3)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        import jax

        x = input
        new_state = {}
        for i, m in enumerate(self.modules):
            r = None if rng is None else jax.random.fold_in(rng, i)
            x, s = m.apply(
                params[str(i)], state[str(i)], x, training=training, rng=r
            )
            new_state[str(i)] = s
        return x, new_state

    def to_graph(self, input_node=None):
        """Convert this chain (incl. nested Sequential/Concat branches —
        the Inception shape) into a node Graph.  The Graph shares the
        child module objects, so weights stay live; interop exporters
        (CaffePersister, TensorflowSaver) need the node topology.
        Reference analogue: StaticGraph conversion (toGraph) in
        ⟦«bigdl»/nn/Graph.scala⟧."""
        from bigdl_tpu.nn.graph import Graph, Input
        from bigdl_tpu.nn.table_ops import Concat, JoinTable

        root = input_node if input_node is not None else Input("data")

        def chain(seq, node):
            for m in seq.modules:
                if isinstance(m, Sequential):
                    node = chain(m, node)
                elif isinstance(m, Concat):
                    tails = []
                    for branch in m.modules:
                        if isinstance(branch, Sequential):
                            tails.append(chain(branch, node))
                        else:
                            tails.append(branch(node))
                    join = JoinTable(m.dimension)
                    if m._name:
                        join.set_name(m._name)
                    node = join(*tails)
                else:
                    node = m(node)
            return node

        out = chain(self, root)
        if input_node is not None:
            return out  # caller wires the enclosing graph
        g = Graph(root, out)
        if self._name:
            g.set_name(self._name)
        return g

    def __repr__(self):
        body = "\n".join(f"  ({i}): {m!r}" for i, m in enumerate(self.modules))
        return f"Sequential {{\n{body}\n}}"


class Identity(AbstractModule):
    """«bigdl»/nn/Identity.scala"""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        return input


class Echo(AbstractModule):
    """«bigdl»/nn/Echo.scala — prints shape on forward (debug aid).  The
    print happens at trace time (host), matching its debugging purpose."""

    def update_output_pure(self, params, input, *, training=False, rng=None):
        shape = getattr(input, "shape", None)
        print(f"Echo[{self.get_name()}]: shape={shape}")
        return input
