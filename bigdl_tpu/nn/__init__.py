"""bigdl_tpu.nn — the module library.

Rebuild of «bigdl»/nn/ (layer library, containers, criterions) and
«bigdl»/nn/abstractnn/ (the module contract).  One import surface exposing
every layer by its reference name, so user code reads like classic BigDL:

    from bigdl_tpu.nn import Sequential, SpatialConvolution, ReLU, Linear
"""

from bigdl_tpu.nn.module import (
    AbstractModule,
    Container,
    Sequential,
    Remat,
    Identity,
    Echo,
)
from bigdl_tpu.nn.layers import *  # noqa: F401,F403
from bigdl_tpu.nn.layers import __all__ as _layers_all
from bigdl_tpu.nn.graph import DynamicGraph, Graph, Input, Node, Model
from bigdl_tpu.nn.control_ops import (
    IfElse,
    LoopCondition,
    MergeOps,
    NextIteration,
    SwitchOps,
    WhileLoop,
)
from bigdl_tpu.nn.tree_lstm import BinaryTreeLSTM
from bigdl_tpu.nn.quantized import (
    QuantizedLinear,
    QuantizedSpatialConvolution,
    Quantizer,
)
from bigdl_tpu.nn.sparse import (
    LookupTableSparse,
    SparseJoinTable,
    SparseLinear,
    SparseTensor,
    SparseTensorMath,
)
from bigdl_tpu.nn.attention import (
    LayerNorm,
    MultiHeadAttention,
    TransformerBlock,
    PositionalEmbedding,
)
from bigdl_tpu.nn.table_ops import (
    ConcatTable,
    ParallelTable,
    CAddTable,
    CSubTable,
    CMulTable,
    CDivTable,
    CMaxTable,
    CMinTable,
    JoinTable,
    SelectTable,
    WhereTable,
    InTopK,
    FlattenTable,
    MM,
    MV,
    CosineDistance,
    DotProduct,
    Concat,
)
from bigdl_tpu.nn.criterion import (
    AbstractCriterion,
    ClassNLLCriterion,
    CrossEntropyCriterion,
    MSECriterion,
    AbsCriterion,
    SmoothL1Criterion,
    BCECriterion,
    BCECriterionWithLogits,
    MultiLabelSoftMarginCriterion,
    MarginCriterion,
    HingeEmbeddingCriterion,
    DistKLDivCriterion,
    CosineEmbeddingCriterion,
    SoftmaxWithCriterion,
    MultiCriterion,
    ParallelCriterion,
    TimeDistributedCriterion,
    ClassSimplexCriterion,
    L1Cost,
    MarginRankingCriterion,
    MultiMarginCriterion,
)
from bigdl_tpu.nn.recurrent import (
    Recurrent,
    RnnCell,
    LSTM,
    LSTMPeephole,
    GRU,
    BiRecurrent,
    TimeDistributed,
    Select,
    MultiRNNCell,
    ConvLSTMPeephole,
)
from bigdl_tpu.nn.table_ops import (
    CAveTable,
    SplitTable,
    BifurcateSplitTable,
    NarrowTable,
    Pack,
    MixtureTable,
    MapTable,
    Bottle,
)
from bigdl_tpu.nn.criterion import (
    CosineDistanceCriterion,
    DiceCoefficientCriterion,
    SoftMarginCriterion,
    MultiLabelMarginCriterion,
    GaussianCriterion,
    KLDCriterion,
    L1HingeEmbeddingCriterion,
    PoissonCriterion,
    CosineProximityCriterion,
    MeanAbsolutePercentageCriterion,
    MeanSquaredLogarithmicCriterion,
)
from bigdl_tpu.nn.volumetric import *  # noqa: F401,F403
from bigdl_tpu.nn.volumetric import __all__ as _volumetric_all
from bigdl_tpu.nn.fused import (
    SpatialConvolutionBatchNorm,
    fuse_conv_bn,
)
from bigdl_tpu.nn.layers_extra import *  # noqa: F401,F403
from bigdl_tpu.nn.layers_extra import __all__ as _extra_all

__all__ = (
    [
        "AbstractModule", "Container", "Sequential", "Identity", "Echo",
        "Graph", "DynamicGraph", "Input", "Node", "Model",
        "SwitchOps", "MergeOps", "IfElse", "WhileLoop", "LoopCondition",
        "NextIteration", "BinaryTreeLSTM",
        "ConcatTable", "ParallelTable", "CAddTable", "CSubTable", "CMulTable",
        "CDivTable", "CMaxTable", "CMinTable", "JoinTable", "SelectTable",
        "WhereTable", "InTopK",
        "FlattenTable", "MM", "MV", "CosineDistance", "DotProduct", "Concat",
        "CAveTable", "SplitTable", "BifurcateSplitTable", "NarrowTable",
        "Pack", "MixtureTable", "MapTable", "Bottle",
        "AbstractCriterion", "ClassNLLCriterion", "CrossEntropyCriterion",
        "MSECriterion", "AbsCriterion", "SmoothL1Criterion", "BCECriterion",
        "BCECriterionWithLogits", "MultiLabelSoftMarginCriterion",
        "MarginCriterion", "HingeEmbeddingCriterion", "DistKLDivCriterion",
        "CosineEmbeddingCriterion", "SoftmaxWithCriterion", "MultiCriterion",
        "ParallelCriterion", "TimeDistributedCriterion",
        "ClassSimplexCriterion", "L1Cost", "MarginRankingCriterion",
        "MultiMarginCriterion",
        "CosineDistanceCriterion", "DiceCoefficientCriterion",
        "SoftMarginCriterion", "MultiLabelMarginCriterion",
        "GaussianCriterion", "KLDCriterion", "L1HingeEmbeddingCriterion",
        "PoissonCriterion", "CosineProximityCriterion",
        "MeanAbsolutePercentageCriterion",
        "MeanSquaredLogarithmicCriterion",
        "Recurrent", "RnnCell", "LSTM", "LSTMPeephole", "GRU", "BiRecurrent",
        "TimeDistributed", "Select", "MultiRNNCell", "ConvLSTMPeephole",
        "LayerNorm", "MultiHeadAttention", "TransformerBlock",
        "PositionalEmbedding",
        "SpatialConvolutionBatchNorm", "fuse_conv_bn",
    ]
    + list(_layers_all)
    + list(_volumetric_all)
    + list(_extra_all)
)
