"""Native host-side runtime — ctypes bindings + numpy fallbacks.

The reference keeps its data plane native (BigDL-core: MKL/MKL-DNN/
bigquant/OpenCV shipped as ``.so`` inside jars — SURVEY.md §2.3).  The
TPU rebuild's chip compute is XLA, but the host feeding path stays
native: ``native/bigdl_tpu_native.cpp`` provides the fp16 wire codec,
one-pass minibatch gather/normalize, and the OpenCV-replacement image
ops.  This wrapper builds the library on first use (``make`` in
``native/``) and falls back to numpy implementations when no compiler
is available, so the framework never hard-requires the binary.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

import numpy as np

log = logging.getLogger("bigdl_tpu.native")

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_SO_PATH = os.path.join(_NATIVE_DIR, "libbigdl_tpu_native.so")

_lib = None
_lib_lock = threading.Lock()
_build_attempted = False

_i64 = ctypes.c_int64
_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_u16p = np.ctypeslib.ndpointer(np.uint16, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")


def _try_build() -> bool:
    global _build_attempted
    if _build_attempted:
        return os.path.exists(_SO_PATH)
    _build_attempted = True
    from bigdl_tpu.config import config, refresh_from_env

    refresh_from_env()
    if config.no_native:
        return False
    try:
        subprocess.run(
            ["make", "-s"], cwd=_NATIVE_DIR, check=True,
            capture_output=True, timeout=120,
        )
        return os.path.exists(_SO_PATH)
    except Exception as e:  # noqa: BLE001 - fall back to numpy
        log.info("native build unavailable (%s); using numpy fallbacks", e)
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO_PATH) and not _try_build():
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError as e:
            log.info("native load failed (%s); using numpy fallbacks", e)
            return None
        lib.fp16_compress.argtypes = [_f32p, _u16p, _i64]
        lib.fp16_decompress.argtypes = [_u16p, _f32p, _i64]
        lib.gather_rows.argtypes = [_f32p, _i64p, _f32p, _i64, _i64]
        lib.gather_rows_mt.argtypes = [_f32p, _i64p, _f32p, _i64, _i64,
                                       ctypes.c_int]
        lib.gather_normalize_u8.argtypes = [_u8p, _i64p, _f32p, _i64, _i64,
                                            _i64, _f32p, _f32p]
        lib.resize_bilinear_chw.argtypes = [_f32p, _f32p] + [_i64] * 5
        lib.crop_chw.argtypes = [_f32p, _f32p] + [_i64] * 7
        lib.hflip_chw.argtypes = [_f32p, _f32p] + [_i64] * 3
        lib.normalize_chw.argtypes = [_f32p, _i64, _i64, _f32p, _f32p]
        lib.native_abi_version.restype = ctypes.c_int
        if lib.native_abi_version() != 1:
            log.warning("native ABI mismatch; using numpy fallbacks")
            return None
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


# ==========================================================================
# fp16 codec («bigdl»/parameters/FP16CompressedTensor wire format)
# ==========================================================================


def fp16_compress(arr: np.ndarray) -> np.ndarray:
    a = np.ascontiguousarray(arr, np.float32)
    lib = _load()
    if lib is None:
        return a.astype(np.float16).view(np.uint16).reshape(a.shape)
    out = np.empty(a.shape, np.uint16)
    lib.fp16_compress(a.reshape(-1), out.reshape(-1), a.size)
    return out


def fp16_decompress(arr: np.ndarray) -> np.ndarray:
    a = np.ascontiguousarray(arr, np.uint16)
    lib = _load()
    if lib is None:
        return a.view(np.float16).astype(np.float32).reshape(a.shape)
    out = np.empty(a.shape, np.float32)
    lib.fp16_decompress(a.reshape(-1), out.reshape(-1), a.size)
    return out


# ==========================================================================
# minibatch assembly
# ==========================================================================


def gather_rows(src: np.ndarray, idx: np.ndarray,
                n_threads: int = 0) -> np.ndarray:
    """dst[i] = src[idx[i]] for 2-D-viewable float32 src (one memcpy per
    row, parallel across rows)."""
    s = np.ascontiguousarray(src, np.float32)
    flat = s.reshape(s.shape[0], -1)
    ix = np.ascontiguousarray(idx, np.int64)
    lib = _load()
    if lib is None:
        return flat[ix].reshape((len(ix),) + s.shape[1:])
    out = np.empty((len(ix), flat.shape[1]), np.float32)
    if n_threads <= 0:
        n_threads = min(4, os.cpu_count() or 1)
    lib.gather_rows_mt(flat, ix, out, len(ix), flat.shape[1], n_threads)
    return out.reshape((len(ix),) + s.shape[1:])


def gather_normalize_u8(src: np.ndarray, idx: np.ndarray,
                        mean: np.ndarray, std: np.ndarray) -> np.ndarray:
    """One-pass uint8 gather + per-channel (x-mean)/std, for (N, C, H, W)
    uint8 datasets — the MNIST/CIFAR feeding path."""
    s = np.ascontiguousarray(src, np.uint8)
    n, c = s.shape[0], s.shape[1]
    hw = int(np.prod(s.shape[2:]))
    ix = np.ascontiguousarray(idx, np.int64)
    m = np.ascontiguousarray(mean, np.float32).reshape(-1)
    sd = np.ascontiguousarray(std, np.float32).reshape(-1)
    if m.size == 1:
        m = np.repeat(m, c)
    if sd.size == 1:
        sd = np.repeat(sd, c)
    lib = _load()
    if lib is None:
        g = s[ix].astype(np.float32)
        return (g - m.reshape(1, c, *([1] * (s.ndim - 2)))) / \
            sd.reshape(1, c, *([1] * (s.ndim - 2)))
    out = np.empty((len(ix), c * hw), np.float32)
    lib.gather_normalize_u8(s.reshape(n, -1).reshape(-1), ix,
                            out.reshape(-1), len(ix), c, hw, m, sd)
    return out.reshape((len(ix),) + s.shape[1:])


# ==========================================================================
# image ops (OpenCV replacements; CHW float32)
# ==========================================================================


def resize_bilinear(img: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    a = np.ascontiguousarray(img, np.float32)
    c, h, w = a.shape
    lib = _load()
    if lib is None:
        import jax

        return np.asarray(jax.image.resize(a, (c, out_h, out_w), "bilinear"))
    out = np.empty((c, out_h, out_w), np.float32)
    lib.resize_bilinear_chw(a, out, c, h, w, out_h, out_w)
    return out


def crop(img: np.ndarray, y: int, x: int, out_h: int, out_w: int) -> np.ndarray:
    a = np.ascontiguousarray(img, np.float32)
    c, h, w = a.shape
    lib = _load()
    if lib is None:
        return a[:, y : y + out_h, x : x + out_w].copy()
    out = np.empty((c, out_h, out_w), np.float32)
    lib.crop_chw(a, out, c, h, w, y, x, out_h, out_w)
    return out


def hflip(img: np.ndarray) -> np.ndarray:
    a = np.ascontiguousarray(img, np.float32)
    lib = _load()
    if lib is None:
        return a[:, :, ::-1].copy()
    out = np.empty_like(a)
    lib.hflip_chw(a, out, *a.shape)
    return out


def normalize(img: np.ndarray, mean, std) -> np.ndarray:
    a = np.ascontiguousarray(img, np.float32).copy()
    c = a.shape[0]
    hw = int(np.prod(a.shape[1:]))
    m = np.ascontiguousarray(np.broadcast_to(np.asarray(mean, np.float32),
                                             (c,)))
    sd = np.ascontiguousarray(np.broadcast_to(np.asarray(std, np.float32),
                                              (c,)))
    lib = _load()
    if lib is None:
        return (a - m.reshape(c, *([1] * (a.ndim - 1)))) / \
            sd.reshape(c, *([1] * (a.ndim - 1)))
    lib.normalize_chw(a.reshape(-1), c, hw, m, sd)
    return a


# ==========================================================================
# prefetching loader — double-buffered background minibatch assembly
# ==========================================================================


class PrefetchIterator:
    """Wraps a batch-producing iterable; a daemon thread assembles the
    next batch while the chip consumes the current one (the reference's
    Engine.default prefetch role on the data path)."""

    def __init__(self, iterable, depth: int = 2):
        import queue

        self._iterable = iterable
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._done = object()
        self._thread: Optional[threading.Thread] = None
        self._err: Optional[BaseException] = None

    def _put(self, item, stop: threading.Event) -> bool:
        """Bounded put that gives up when the consumer has stopped — the
        producer must never block forever on an abandoned queue."""
        import queue

        while not stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def __iter__(self):
        stop = threading.Event()

        def worker():
            try:
                for item in self._iterable:
                    if not self._put(item, stop):
                        return  # consumer broke out early
            except BaseException as e:  # noqa: BLE001 - forwarded to consumer
                self._err = e
            finally:
                self._put(self._done, stop)

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()
        try:
            while True:
                item = self._queue.get()
                if item is self._done:
                    if self._err is not None:
                        raise self._err
                    return
                yield item
        finally:
            # consumer stopped (break / exception / GC): release the
            # producer so the thread and its pinned batches are freed
            stop.set()
