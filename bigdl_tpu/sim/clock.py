"""Virtual clock — deterministic time for the control-plane simulator.

Every policy object in the tree already takes an injectable ``clock``
callable (``AutoscaleController(clock=...)``, ``AlertEngine(clock=...)``,
the supervisor's injectable ``sleep``), precisely so policy branches
unit-test without wall time.  The simulator leans on that seam: ONE
:class:`VirtualClock` instance is handed to every real component, the
scenario timeline advances it tick by tick, and an hour of fleet
history costs microseconds — while staying exactly reproducible, which
is what turns a chaos scenario into a regression test.
"""

from __future__ import annotations


class VirtualClock:
    """Monotonic virtual time: ``now()`` reads, ``advance()`` moves.

    Passed as the ``clock=`` callable of the real policy objects
    (instances are themselves callable, so either ``clock=vc`` or
    ``clock=vc.now`` works)."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def now(self) -> float:
        return self._now

    __call__ = now

    def advance(self, dt: float) -> float:
        """Move time forward by ``dt`` virtual seconds (never back —
        a scenario that rewinds time is a scenario bug, loudly)."""
        dt = float(dt)
        if dt < 0:
            raise ValueError(f"virtual time only advances, got {dt}")
        self._now += dt
        return self._now

    def sleep(self, dt: float):
        """Injectable stand-in for ``time.sleep`` (the supervisor's
        backoff sleeps advance virtual time instead of blocking)."""
        self.advance(max(0.0, float(dt)))

    def __repr__(self) -> str:
        return f"VirtualClock(t={self._now:.3f})"
