"""Serving data-plane chaos simulator — real router policies, synthetic
replicas, virtual clock.

The fleet simulator (sim/runner.py) proved the *control* plane at
scale; this module does the same for the *data* plane the router tier
(serving/router.py) owns.  A :class:`SimServeReplica` models one
serving replica's request flow — admission queue, bounded decode
slots, per-request service time, a paged-KV pool whose occupancy is
the ``kv_frac`` placement signal — while the REAL policy objects make
every decision, exactly as they do behind HTTP:

* the real :class:`~bigdl_tpu.serving.placement.PlacementPolicy`
  places every request (session affinity + least-loaded by queue
  depth / in-flight / KV pressure);
* the real :class:`~bigdl_tpu.resilience.retry.RetryBudget` gates
  every retry — budget exhausted means shed, not queue;
* the real :class:`~bigdl_tpu.serving.drain.HandoffLedger` claim-gates
  every checkpoint replay and deduplicates every delivery.

Three builtin chaos scenarios (:data:`SERVE_SCENARIOS`, all at 8
replicas):

* ``preemption_storm`` — half the fleet is preempted mid-run over a
  shared KV pool; their dumped queues are claim-gated handoff replays
  the survivors absorb.  The SLO-burn alert may fire once for the
  storm and must resolve after recovery — no flapping — and not one
  request is lost or duplicated;
* ``brownout`` — one replica turns 40x slow without dying; requests
  stuck on it time out and re-place elsewhere, and the shared retry
  budget must cap backend amplification at the configured factor
  (attempts/requests <= 1 + ratio + slack) while late completions
  from the zombie are discarded, never double-answered;
* ``drain_wave`` — replicas drain under a diurnal wave;
  checkpoint-and-replay must conserve every request: zero dropped,
  zero duplicated, zero shed across the full drain/handoff cycle;
* ``weight_rollout`` — the live-weight-rollout control loop (the REAL
  :class:`~bigdl_tpu.serving.rollout.CanaryController` driving sim
  replicas): a good version canaries and promotes cleanly; a bad
  version (injected latency + divergent logits) triggers exactly one
  hysteresis-gated rollback whose canary drains replay everything; a
  corrupt-mid-publish checkpoint is rejected by the verify gate and
  reaches zero replicas.  Invariants: ``rollback_exactly_once``,
  ``no_version_skew_after_settle``, ``corrupt_never_loaded``,
  ``zero_dropped_requests``.

:func:`run_serve_scenario` runs one scenario tick by tick and hands
the observation bundle to the serve invariants
(:func:`bigdl_tpu.sim.invariants.check_serve_scenario`).
``scripts/router_smoke.py`` (``run-tests.sh --router``) banks the
matrix into ``ROUTER_SMOKE.json`` for BENCH ``extras.router``.
"""

from __future__ import annotations

import dataclasses
import json
import random
import time
from typing import Dict, List, Optional

from bigdl_tpu.obs import reqtrace
from bigdl_tpu.resilience.retry import RetryBudget, backoff_delay
from bigdl_tpu.serving import spans
from bigdl_tpu.serving.drain import HandoffLedger
from bigdl_tpu.serving.placement import (NoReplicaAvailable,
                                         PlacementPolicy, ReplicaView)
from bigdl_tpu.sim.clock import VirtualClock
from bigdl_tpu.sim.invariants import InvariantResult, check_serve_scenario


# ----------------------------------------------------------- sim replica
class _SimJob:
    """One admitted request inside a sim replica."""

    __slots__ = ("rid", "remaining_s")

    def __init__(self, rid: str, remaining_s: float):
        self.rid = rid
        self.remaining_s = float(remaining_s)


class SimServeReplica:
    """Request-flow model of one serving replica.

    Bounded decode slots drain a bounded admission queue at
    ``service_s`` virtual seconds per request (scaled by
    ``slow_factor`` — a brownout replica still works, just slowly);
    each active request holds ``pages_per_req`` pages of the
    ``kv_pages`` pool, so ``signals()`` exports the same
    queue-depth / KV-pressure shape the real engine's ``stats()``
    does.  ``preempt()`` models losing the host: everything in flight
    is dumped as (rid, remaining) checkpoints for the router to
    replay; ``drain()`` models the graceful path — same checkpoints,
    but the replica stays reachable and refuses admissions."""

    def __init__(self, name: str, *, slots: int = 4,
                 max_queue: int = 128, kv_pages: int = 64,
                 pages_per_req: int = 4):
        self.name = str(name)
        self.slots = int(slots)
        self.max_queue = int(max_queue)
        self.kv_pages = int(kv_pages)
        self.pages_per_req = int(pages_per_req)
        self.up = True
        self.draining = False
        self.slow_factor = 1.0
        self.version = "v0"     # weight version served (rollout tier)
        self.queue: List[_SimJob] = []
        self.active: List[_SimJob] = []

    # -- router-facing surface (the shape EngineReplica exports) --------
    def admit(self, rid: str, service_s: float) -> bool:
        if not self.up or self.draining:
            return False
        if len(self.queue) >= self.max_queue:
            return False
        self.queue.append(_SimJob(rid, service_s))
        return True

    def signals(self) -> dict:
        if not self.up:
            raise RuntimeError(f"{self.name}: connection refused")
        return {"up": True, "draining": self.draining,
                "queue_depth": float(len(self.queue)),
                "kv_frac": min(1.0, len(self.active)
                               * self.pages_per_req / self.kv_pages)}

    def backlog(self) -> int:
        return len(self.queue) + len(self.active)

    # -- physics ---------------------------------------------------------
    def tick(self, dt: float) -> List[str]:
        """Advance ``dt`` virtual seconds; returns completed rids.

        Each of the ``slots`` decode lanes gets ``dt`` seconds of
        work (scaled by ``slow_factor``) and pulls the next queued
        job the moment its current one finishes — so throughput is
        ``slots / service_s`` whenever there is work, independent of
        the tick quantum."""
        if not self.up:
            return []
        done: List[str] = []
        rate = 1.0 / max(1.0, self.slow_factor)
        lanes = list(self.active)
        self.active = []
        for lane in range(self.slots):
            t_avail = dt * rate
            job = lanes[lane] if lane < len(lanes) else None
            while t_avail > 1e-12:
                if job is None:
                    if not self.queue:
                        break
                    job = self.queue.pop(0)
                spent = min(t_avail, job.remaining_s)
                job.remaining_s -= spent
                t_avail -= spent
                if job.remaining_s <= 1e-9:
                    done.append(job.rid)
                    job = None
            if job is not None:
                self.active.append(job)
        return done

    # -- chaos -----------------------------------------------------------
    def preempt(self) -> List[tuple]:
        """The host is gone: dump every in-flight/queued request as a
        (rid, remaining_s) checkpoint and go down."""
        dumped = [(j.rid, j.remaining_s) for j in self.active + self.queue]
        self.active, self.queue = [], []
        self.up = False
        return dumped

    def recover(self):
        self.up = True
        self.draining = False
        self.slow_factor = 1.0

    def drain(self) -> List[tuple]:
        """Graceful drain: stop admissions and checkpoint everything —
        active jobs keep their progress (remaining < full service), the
        exactly-once replay must not lose or duplicate any of it."""
        self.draining = True
        dumped = [(j.rid, j.remaining_s) for j in self.active + self.queue]
        self.active, self.queue = [], []
        return dumped

    def undrain(self):
        self.draining = False


# -------------------------------------------------------------- scenario
@dataclasses.dataclass
class ServeScenario:
    """One declarative serving chaos scenario."""

    name: str
    duration_s: float
    tick_s: float = 0.5
    replicas: int = 8
    slots: int = 4
    service_s: float = 0.2          # mean per-request decode time
    service_jitter: float = 0.2     # +- fraction of service_s
    arrival_rps: float = 40.0
    wave_amp_rps: float = 0.0       # diurnal modulation on top
    wave_period_s: float = 120.0
    arrival_stop_s: Optional[float] = None   # default duration - 30
    session_frac: float = 0.25      # share of requests with a session
    sessions: int = 16
    request_timeout_s: float = 30.0
    max_retries: int = 3
    budget_ratio: float = 0.2
    budget_burst: float = 20.0
    backoff_base_s: float = 0.05
    affinity_ttl_s: float = 300.0
    kv_weight: float = 4.0
    slo_fire_backlog: float = 1.5   # x total slots -> alert fires
    slo_resolve_backlog: float = 0.8
    # rollout tier (active when publish_* events appear): canary
    # evaluation cadence, the incumbent everyone starts on, and the
    # damage a "bad" version injects — extra per-request latency on
    # its canaries plus a divergent pinned-prompt replay signal
    rollout_eval_s: float = 5.0
    incumbent_version: str = "v0"
    bad_slow_factor: float = 6.0
    bad_divergence: float = 0.5
    events: List[dict] = dataclasses.field(default_factory=list)
    expect: dict = dataclasses.field(default_factory=dict)

    def n_ticks(self) -> int:
        return max(1, int(round(self.duration_s / self.tick_s)))


#: the builtin serving chaos matrix (see the module docstring)
SERVE_SCENARIOS: Dict[str, dict] = {
    "preemption_storm": dict(
        name="preemption_storm", duration_s=220.0, replicas=8,
        service_s=0.25, arrival_rps=100.0, arrival_stop_s=180.0,
        budget_burst=50.0,
        events=[
            # half the fleet preempted at once: the survivors' 64 rps
            # against 100 rps offered load saturates their queues —
            # dumped work is claim-gated replay, overflow is explicit
            # budget-gated shedding, and the SLO-burn alert gets ONE
            # episode that must resolve after recovery
            {"t": 60.0, "kind": "preempt",
             "replicas": ["r0", "r1", "r2", "r3"]},
            {"t": 100.0, "kind": "recover",
             "replicas": ["r0", "r1", "r2", "r3"]},
        ],
        expect={"max_lost": 0, "max_duplicates": 0,
                "min_handoff_replays": 1, "min_retries": 10,
                "max_slo_flaps": 1, "slo_resolved": True,
                "amplification_slack": 0.1}),
    "brownout": dict(
        name="brownout", duration_s=240.0, replicas=8,
        arrival_rps=50.0, arrival_stop_s=200.0,
        request_timeout_s=5.0,
        events=[
            {"t": 40.0, "kind": "slow", "replicas": ["r4"],
             "factor": 40.0},
            {"t": 160.0, "kind": "recover", "replicas": ["r4"]},
        ],
        expect={"max_lost": 0, "max_duplicates": 0, "min_retries": 5,
                "amplification_slack": 0.1, "max_slo_flaps": 1,
                "slo_resolved": True}),
    "drain_wave": dict(
        name="drain_wave", duration_s=260.0, replicas=8,
        service_s=0.25, arrival_rps=40.0, wave_amp_rps=25.0,
        wave_period_s=120.0, arrival_stop_s=210.0,
        events=[
            # drains land at the wave peaks (t=30, t=150): the drained
            # replicas are holding real work to checkpoint
            {"t": 28.0, "kind": "drain", "replicas": ["r2"]},
            {"t": 32.0, "kind": "drain", "replicas": ["r5"]},
            {"t": 100.0, "kind": "undrain", "replicas": ["r2", "r5"]},
            {"t": 152.0, "kind": "drain", "replicas": ["r6"]},
            {"t": 200.0, "kind": "undrain", "replicas": ["r6"]},
        ],
        expect={"max_lost": 0, "max_duplicates": 0, "max_shed": 0,
                "max_late_discarded": 0, "min_handoff_replays": 1,
                "min_drains": 3, "max_slo_flaps": 1,
                "amplification_slack": 0.1}),
    "weight_rollout": dict(
        name="weight_rollout", duration_s=200.0, replicas=8,
        arrival_rps=40.0, arrival_stop_s=170.0,
        events=[
            # a good version canaries on the fraction, holds clean for
            # hold_evals rounds, and promotes fleet-wide
            {"t": 30.0, "kind": "publish_good", "version": "v1"},
            # a bad version (6x latency + 0.5 token divergence on its
            # canaries) must trigger EXACTLY one rollback — hysteresis,
            # not flapping — and the canary drains replay everything
            {"t": 80.0, "kind": "publish_bad", "version": "v2"},
            # a corrupt-mid-publish checkpoint is refused by the
            # verify-before-swap gate and reaches zero replicas
            {"t": 140.0, "kind": "publish_corrupt", "version": "v3"},
        ],
        expect={"max_lost": 0, "max_duplicates": 0, "max_shed": 0,
                "min_handoff_replays": 1, "rollbacks": 1,
                "settle_version": "v1", "promotions": ["v1"],
                "min_corrupt_rejected": 1, "max_slo_flaps": 1,
                "amplification_slack": 0.1}),
}


def load_serve_scenario(spec, replicas: Optional[int] = None,
                        time_compression: float = 1.0) -> ServeScenario:
    """Builtin name, JSON string, or dict -> validated ServeScenario.

    The builtin ``expect`` blocks are calibrated at their declared
    replica count and offered load (the storm must saturate the
    survivors for ``min_retries`` to mean anything) — the ``replicas``
    override is for custom scenario specs, which carry their own
    expectations."""
    if isinstance(spec, ServeScenario):
        sc = spec
    else:
        if isinstance(spec, str):
            d = (SERVE_SCENARIOS.get(spec)
                 or (json.loads(spec) if spec.lstrip().startswith("{")
                     else None))
            if d is None:
                raise ValueError(
                    f"unknown serve scenario {spec!r} (builtins: "
                    f"{sorted(SERVE_SCENARIOS)})")
        elif isinstance(spec, dict):
            d = spec
        else:
            raise TypeError(f"scenario spec {type(spec).__name__}")
        sc = ServeScenario(**d)
    if replicas is not None:
        sc = dataclasses.replace(sc, replicas=int(replicas))
    c = max(1.0, float(time_compression))
    if c > 1.0:
        sc = dataclasses.replace(
            sc, duration_s=sc.duration_s / c,
            wave_period_s=sc.wave_period_s / c,
            arrival_stop_s=(None if sc.arrival_stop_s is None
                            else sc.arrival_stop_s / c),
            events=[dict(ev, t=ev["t"] / c) for ev in sc.events])
    if sc.replicas < 2:
        raise ValueError("a router scenario needs >= 2 replicas")
    for ev in sc.events:
        if ev["kind"] not in ("preempt", "recover", "slow", "drain",
                              "undrain", "publish_good", "publish_bad",
                              "publish_corrupt"):
            raise ValueError(f"unknown event kind {ev['kind']!r}")
        if ev["kind"].startswith("publish") and not ev.get("version"):
            raise ValueError(f"publish event at t={ev['t']} needs a "
                             "version")
        if not 0 <= float(ev["t"]) <= sc.duration_s:
            raise ValueError(f"event at t={ev['t']} outside the "
                             f"{sc.duration_s:g}s scenario")
    return sc


# ---------------------------------------------------------------- result
@dataclasses.dataclass
class ServeScenarioResult:
    """One serve scenario's outcome: counters + invariant verdicts."""

    name: str
    ok: bool
    replicas: int
    ticks: int
    duration_s: float
    wall_s: float
    requests: int
    completed: int
    shed: int
    lost: int
    duplicates: int
    retries: int
    backend_attempts: int
    handoff_replays: int
    drains: int
    late_discarded: int
    amplification: float
    affinity_hits: int
    slo_flaps: int
    slo_firing_at_end: bool
    p50_latency_s: Optional[float]
    p99_latency_s: Optional[float]
    budget: dict
    invariants: List[InvariantResult]
    # buffered request traces of the requests that broke an invariant
    # (lost / duplicated), dumped when tracing is on — the postmortem
    # is IN the verdict, not a separate archaeology dig
    offending_traces: List[dict] = dataclasses.field(
        default_factory=list)
    # rollout observations (versions at end, rollback/promotion
    # episodes, corrupt-publish accounting) when the scenario drove a
    # CanaryController; None otherwise
    rollout: Optional[dict] = None

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["invariants"] = [dataclasses.asdict(r)
                           for r in self.invariants]
        return d

    def summary(self) -> str:
        inv = ", ".join(f"{r.name}={'ok' if r.ok else 'FAIL'}"
                        for r in self.invariants)
        return (f"serve scenario {self.name}: "
                f"{'PASS' if self.ok else 'FAIL'} "
                f"({self.replicas} replicas, {self.requests} requests, "
                f"{self.completed} completed / {self.shed} shed / "
                f"{self.lost} lost / {self.duplicates} dup, "
                f"{self.retries} retries, {self.handoff_replays} "
                f"replays, amp {self.amplification:.3f}, "
                f"{self.wall_s:.1f}s wall) [{inv}]")


class _ClientReq:
    """Router-side state of one client request in the sim."""

    __slots__ = ("rid", "session", "arrival_t", "attempts", "tried",
                 "ready_t", "remaining_s", "replayed")

    def __init__(self, rid, session, arrival_t, remaining_s):
        self.rid = rid
        self.session = session
        self.arrival_t = float(arrival_t)
        self.attempts = 0
        self.tried: set = set()
        self.ready_t = float(arrival_t)
        self.remaining_s = float(remaining_s)
        self.replayed = 0


# ------------------------------------------------------------------ loop
def run_serve_scenario(spec, replicas: Optional[int] = None,
                       seed: int = 0,
                       time_compression: float = 1.0,
                       max_drainout_ticks: int = 4000
                       ) -> ServeScenarioResult:
    """Run one serving chaos scenario on the virtual clock.

    The loop is the router's decision procedure, one virtual tick at a
    time, with the REAL policy objects making every call: placement by
    :class:`PlacementPolicy`, every retry spending the shared
    :class:`RetryBudget`, every checkpoint replay claim-gated and
    every delivery deduplicated through the :class:`HandoffLedger`.
    After arrivals stop the loop drains out until every request is
    answered (or ``max_drainout_ticks`` passes — anything still
    unanswered then is *lost*, which the conservation invariant pins
    at zero)."""
    sc = load_serve_scenario(spec, replicas=replicas,
                             time_compression=time_compression)
    rng = random.Random(int(seed))
    clock = VirtualClock()
    # request tracing (obs/reqtrace.py): span starts are VIRTUAL-clock
    # stamps here — the value of a sim trace is its hop *sequence and
    # durations* for invariant postmortems, not wall alignment
    col = reqtrace.get_collector()
    ctxs: Dict[str, object] = {}         # rid -> RequestTraceContext
    placement = PlacementPolicy(affinity_ttl_s=sc.affinity_ttl_s,
                                kv_weight=sc.kv_weight, clock=clock)
    budget = RetryBudget(ratio=sc.budget_ratio, burst=sc.budget_burst)
    ledger = HandoffLedger()
    fleet = {f"r{i}": SimServeReplica(f"r{i}", slots=sc.slots)
             for i in range(sc.replicas)}

    pending: List[_ClientReq] = []       # waiting for (re)placement
    live: Dict[str, _ClientReq] = {}     # rid -> request state
    outstanding: Dict[str, tuple] = {}   # rid -> (replica, deadline_t)
    answers: Dict[str, int] = {}         # rid -> times answered
    latencies: List[float] = []
    counts = {"requests": 0, "completed": 0, "shed": 0, "retries": 0,
              "backend_attempts": 0, "handoff_replays": 0, "drains": 0,
              "late_discarded": 0}
    slo = {"firing": False, "flaps": 0}
    total_slots = sc.replicas * sc.slots
    arrival_stop = (sc.arrival_stop_s if sc.arrival_stop_s is not None
                    else max(0.0, sc.duration_s - 30.0))
    events = sorted(sc.events, key=lambda ev: ev["t"])
    next_event = 0
    acc = 0.0
    rid_seq = 0

    # -- rollout tier: the REAL CanaryController over sim callables ----
    controller = None
    rollout = {"bad": set(), "corrupt_rejected": 0, "corrupt_loaded": 0,
               "refused_offers": 0, "next_eval": 0.0, "t": 0.0}
    if any(ev["kind"].startswith("publish") for ev in sc.events):
        from bigdl_tpu.serving.rollout import (SLO_BURN_ALERT,
                                               CanaryController)

        for rep in fleet.values():
            rep.version = sc.incumbent_version

        def _apply_version(name: str, version: str):
            # the harness's set_version: a sim hot-swap.  A bad version
            # manifests as injected per-request latency (its divergence
            # rides the probe below)
            rep = fleet[name]
            rep.version = version
            rep.slow_factor = (sc.bad_slow_factor
                               if version in rollout["bad"] else 1.0)

        def _divergence() -> float:
            # pinned-prompt replay signal: a bad candidate's canaries
            # produce divergent tokens, a good one's are bit-equal
            return (sc.bad_divergence
                    if controller.candidate in rollout["bad"] else 0.0)

        def _alerts():
            return [SLO_BURN_ALERT] if slo["firing"] else []

        def _drain_cb(name: str):
            counts["drains"] += 1
            for rid, rem in fleet[name].drain():
                outstanding.pop(rid, None)
                replay(rid, rem, name, rollout["t"])
            placement.unbind_replica(name)

        def _undrain_cb(name: str):
            fleet[name].undrain()

        controller = CanaryController(
            sorted(fleet), set_version=_apply_version,
            incumbent=sc.incumbent_version,
            measure_divergence=_divergence, alerts=_alerts,
            drain=_drain_cb, undrain=_undrain_cb, clock=clock)

    def views() -> Dict[str, ReplicaView]:
        out = {}
        in_flight: Dict[str, int] = {}
        for rid, (name, _dl) in outstanding.items():
            in_flight[name] = in_flight.get(name, 0) + 1
        for name, rep in fleet.items():
            try:
                sig = rep.signals()
            except RuntimeError:
                out[name] = ReplicaView(name, up=False)
                continue
            out[name] = ReplicaView(
                name, up=True, draining=sig["draining"],
                queue_depth=sig["queue_depth"],
                in_flight=in_flight.get(name, 0),
                kv_frac=sig["kv_frac"])
        return out

    def answer(req: _ClientReq, t: float):
        answers[req.rid] = answers.get(req.rid, 0) + 1
        live.pop(req.rid, None)
        c = ctxs.pop(req.rid, None)
        if c is not None:
            e2e = max(0.0, t - req.arrival_t)
            col.span(c, spans.SPAN_ROUTE, req.arrival_t, e2e,
                     retries=req.attempts, replays=req.replayed)
            col.finish(c, request=req.rid, retries=req.attempts,
                       handoff=req.replayed > 0, e2e_s=e2e)

    def shed(req: _ClientReq, t: float):
        counts["shed"] += 1
        c = ctxs.pop(req.rid, None)
        if c is not None:
            col.finish(c, request=req.rid, error="shed",
                       retries=req.attempts,
                       e2e_s=max(0.0, t - req.arrival_t))
        answer(req, t)

    def fail_attempt(req: _ClientReq, t: float):
        """One placement/attempt failed: budget-gated retry or shed."""
        if req.attempts >= sc.max_retries:
            shed(req, t)
            return
        if not budget.try_spend():
            shed(req, t)
            return
        counts["retries"] += 1
        req.attempts += 1
        delay = backoff_delay(req.attempts, base=sc.backoff_base_s,
                              cap=1.0, rng=rng)
        col.span(ctxs.get(req.rid), spans.SPAN_RETRY, t, delay,
                 attempt=req.attempts,
                 budget_tokens=round(budget.tokens(), 2))
        req.ready_t = t + delay
        pending.append(req)

    def replay(rid: str, remaining_s: float, source: str, t: float):
        """Claim-gated handoff replay — progress preserved, exactly
        once per checkpoint (the sim analog of the engine's bit-exact
        refolded-prompt resume)."""
        key = f"{rid}@{source}#{remaining_s:.6f}"
        if not ledger.claim(key):
            return
        req = live.get(rid)
        if req is None:     # already answered (late checkpoint)
            return
        counts["handoff_replays"] += 1
        col.span(ctxs.get(rid), spans.SPAN_HANDOFF, t, 0.0,
                 source=source, remaining_s=round(remaining_s, 6))
        req.remaining_s = remaining_s
        req.replayed += 1
        req.tried = set()
        req.ready_t = t
        pending.append(req)

    def step(t: float, dt: float, arrivals: bool):
        nonlocal acc, rid_seq, next_event
        rollout["t"] = t
        # 1. chaos events reach their virtual time
        while next_event < len(events) and events[next_event]["t"] <= t:
            ev = events[next_event]
            next_event += 1
            if ev["kind"].startswith("publish"):
                version = str(ev["version"])
                if ev["kind"] == "publish_bad":
                    rollout["bad"].add(version)
                if ev["kind"] == "publish_corrupt":
                    # the watcher's verify-before-swap gate: a torn /
                    # corrupt publish is counted and rejected before
                    # any replica sees it (the real file-level gate is
                    # exercised by rollout_smoke and the unit tests —
                    # the sim pins the ORDERING: reject precedes offer)
                    rollout["corrupt_rejected"] += 1
                    continue
                if not controller.offer(version, now=t):
                    rollout["refused_offers"] += 1
                continue
            for name in ev["replicas"]:
                rep = fleet[name]
                if ev["kind"] == "preempt":
                    for rid, rem in rep.preempt():
                        outstanding.pop(rid, None)
                        replay(rid, rem, name, t)
                    placement.unbind_replica(name)
                elif ev["kind"] == "drain":
                    counts["drains"] += 1
                    for rid, rem in rep.drain():
                        outstanding.pop(rid, None)
                        replay(rid, rem, name, t)
                    placement.unbind_replica(name)
                elif ev["kind"] == "slow":
                    rep.slow_factor = float(ev.get("factor", 8.0))
                elif ev["kind"] == "recover":
                    rep.recover()
                elif ev["kind"] == "undrain":
                    rep.undrain()
        # 2. client arrivals (deterministic rate accumulator)
        if arrivals:
            import math

            rate = sc.arrival_rps + sc.wave_amp_rps * math.sin(
                2.0 * math.pi * t / sc.wave_period_s)
            acc += max(0.0, rate) * dt
            while acc >= 1.0:
                acc -= 1.0
                rid = f"q{rid_seq}"
                rid_seq += 1
                session = (f"s{rng.randrange(sc.sessions)}"
                           if rng.random() < sc.session_frac else None)
                service = sc.service_s * (
                    1.0 + sc.service_jitter * (2.0 * rng.random() - 1.0))
                req = _ClientReq(rid, session, t, service)
                live[rid] = req
                counts["requests"] += 1
                budget.record_request()
                if col.enabled:
                    c = col.new_context()
                    col.begin(c)
                    ctxs[rid] = c
                pending.append(req)
        # 3. placement pass over everything due
        due = [r for r in pending if r.ready_t <= t]
        for req in due:
            pending.remove(req)
            snapshot = views()
            try:
                name = placement.choose(snapshot, req.session,
                                        exclude=req.tried)
            except NoReplicaAvailable:
                fail_attempt(req, t)
                continue
            col.span(ctxs.get(req.rid), spans.SPAN_PLACEMENT, t, 0.0,
                     replica=name, attempt=req.attempts)
            if fleet[name].admit(req.rid, req.remaining_s):
                counts["backend_attempts"] += 1
                outstanding[req.rid] = (name, t + sc.request_timeout_s)
            else:
                req.tried.add(name)
                fail_attempt(req, t)
        # 4. replica physics + deliveries (ledger-deduplicated)
        for name, rep in fleet.items():
            for rid in rep.tick(dt):
                outstanding.pop(rid, None)
                if not ledger.deliver(rid):
                    counts["late_discarded"] += 1
                    continue
                req = live.get(rid)
                if req is not None:
                    latencies.append(t + dt - req.arrival_t)
                    counts["completed"] += 1
                    answer(req, t + dt)
        # 5. router-side timeouts: abandon the attempt, retry elsewhere
        #    (the zombie copy keeps grinding — its late completion is
        #    discarded by the ledger, never double-answered)
        for rid, (name, deadline) in list(outstanding.items()):
            if deadline <= t:
                del outstanding[rid]
                req = live.get(rid)
                if req is not None:
                    req.tried.add(name)
                    fail_attempt(req, t)
        # 6. SLO-burn hysteresis on fleet backlog
        backlog = sum(rep.backlog() for rep in fleet.values())
        if not slo["firing"] and backlog > sc.slo_fire_backlog \
                * total_slots:
            slo["firing"] = True
            slo["flaps"] += 1
        elif slo["firing"] and backlog < sc.slo_resolve_backlog \
                * total_slots:
            slo["firing"] = False
        # 7. canary evaluation on its own cadence (the controller's
        #    rollback path drains through _drain_cb -> replay, so a
        #    rollback's in-flight work re-enters placement this tick)
        if controller is not None and controller.state == "canary" \
                and t >= rollout["next_eval"]:
            rollout["next_eval"] = t + sc.rollout_eval_s
            controller.evaluate(now=t)

    t_wall0 = time.perf_counter()
    for _ in range(sc.n_ticks()):
        t = clock.now()
        step(t, sc.tick_s, arrivals=t < arrival_stop)
        clock.advance(sc.tick_s)
    drainout = 0
    while live and drainout < int(max_drainout_ticks):
        drainout += 1
        step(clock.now(), sc.tick_s, arrivals=False)
        clock.advance(sc.tick_s)
    wall_s = time.perf_counter() - t_wall0

    lost = len(live)                       # never answered = dropped
    duplicates = sum(1 for n in answers.values() if n > 1)
    amplification = ((counts["backend_attempts"]
                      - counts["handoff_replays"])
                     / max(1, counts["requests"]))
    observed = {
        "requests": counts["requests"],
        "completed": counts["completed"],
        "shed": counts["shed"],
        "lost": lost,
        "duplicates": duplicates,
        "retries": counts["retries"],
        "backend_attempts": counts["backend_attempts"],
        "handoff_replays": counts["handoff_replays"],
        "drains": counts["drains"],
        "late_discarded": counts["late_discarded"],
        "amplification": amplification,
        "budget": budget.stats(),
        "ledger": ledger.stats(),
        "slo_flaps": slo["flaps"],
        "slo_firing_at_end": slo["firing"],
    }
    rollout_obs = None
    if controller is not None:
        rollout_obs = {
            "rollbacks": len(controller.rollbacks),
            "rollback_episodes": list(controller.rollbacks),
            "promotions": list(controller.promotions),
            "versions_at_end": {n: fleet[n].version
                                for n in sorted(fleet)},
            "corrupt_rejected": rollout["corrupt_rejected"],
            "corrupt_loaded": rollout["corrupt_loaded"],
            "refused_offers": rollout["refused_offers"],
            "rollout_state": controller.state,
            "incumbent": controller.incumbent,
        }
        observed.update(rollout_obs)
    invariants = check_serve_scenario(observed, sc.expect)
    # invariant postmortem: when tracing is on and a conservation
    # invariant broke, dump the buffered hop traces of the offending
    # requests right into the verdict (lost = still live, never
    # answered; duplicated = answered more than once)
    offending: List[dict] = []
    if col.enabled and not all(r.ok for r in invariants):
        for rid in sorted(live)[:8]:
            offending.append({
                "request": rid, "state": "lost",
                "spans": col.peek(ctxs.get(rid))})
        for rid, n in sorted(answers.items()):
            if n > 1 and len(offending) < 24:
                entry = col.find(rid)
                offending.append({
                    "request": rid, "state": "duplicate", "answers": n,
                    "spans": (entry or {}).get("spans", [])})
    lat = sorted(latencies)

    def pct(p):
        return (round(lat[min(len(lat) - 1,
                              int(p * (len(lat) - 1)))], 4)
                if lat else None)

    result = ServeScenarioResult(
        name=sc.name,
        ok=all(r.ok for r in invariants),
        replicas=sc.replicas,
        ticks=sc.n_ticks() + drainout,
        duration_s=sc.duration_s,
        wall_s=round(wall_s, 3),
        requests=counts["requests"],
        completed=counts["completed"],
        shed=counts["shed"],
        lost=lost,
        duplicates=duplicates,
        retries=counts["retries"],
        backend_attempts=counts["backend_attempts"],
        handoff_replays=counts["handoff_replays"],
        drains=counts["drains"],
        late_discarded=counts["late_discarded"],
        amplification=round(amplification, 4),
        affinity_hits=placement.affinity_hits,
        slo_flaps=slo["flaps"],
        slo_firing_at_end=slo["firing"],
        p50_latency_s=pct(0.50),
        p99_latency_s=pct(0.99),
        budget=budget.stats(),
        invariants=invariants,
        offending_traces=offending,
        rollout=rollout_obs,
    )
    from bigdl_tpu import obs

    obs.get_tracer().event(
        spans.EVENT_SCENARIO, scenario=result.name, ok=result.ok,
        replicas=result.replicas, requests=result.requests,
        completed=result.completed, shed=result.shed, lost=result.lost,
        duplicates=result.duplicates, retries=result.retries,
        handoff_replays=result.handoff_replays,
        amplification=result.amplification, wall_s=result.wall_s,
        invariants={r.name: r.ok for r in result.invariants})
    return result


__all__ = ["SERVE_SCENARIOS", "ServeScenario", "ServeScenarioResult",
           "SimServeReplica", "load_serve_scenario",
           "run_serve_scenario"]
