"""The synthetic fleet and its fetch router.

:class:`SimFleet` owns N :class:`~bigdl_tpu.sim.host.SimHost`\\ s and
stands in for the HTTP transport between them and the real scrapers:
``fetch(url)`` is injected into the real
:class:`~bigdl_tpu.obs.aggregate.FleetAggregator` /
:class:`~bigdl_tpu.resilience.autoscale.EndpointScraper`, which then
exercise their genuine parse/degrade paths —

* a healthy host answers with its real ``/healthz`` JSON or
  ``/metrics`` Prometheus exposition;
* a **partitioned** host *times out*: the fetch blocks for
  ``partition_stall_s`` of real wall time before raising — the failure
  mode that makes a serial scrape of N peers cost N × timeout, which
  the bounded-pool concurrent scrape exists to fix (and the partition
  scenario measures);
* a **down** host (preempted / flap trough) refuses immediately.

``health_fetch`` is the dict-returning variant the supervisor's
:class:`~bigdl_tpu.resilience.supervisor.HangWatchdog` injects.
"""

from __future__ import annotations

import json
import re
import time
from typing import List, Optional

from bigdl_tpu.sim.host import SimHost

# the synthetic address space: "sim<host_id>:9000"
_URL_RE = re.compile(r"^https?://sim(\d+):\d+(/[a-z?=&0-9]*)$")


class SimFleet:
    """N synthetic hosts + the fetch router over them."""

    def __init__(self, n_hosts: int, clock, seed: int = 0,
                 alert_rules=None, alert_sink: Optional[str] = None,
                 partition_stall_s: float = 0.0, **host_kw):
        if n_hosts < 1:
            raise ValueError(f"need at least one host, got {n_hosts}")
        self.clock = clock
        self.partition_stall_s = float(partition_stall_s)
        self.hosts: List[SimHost] = [
            SimHost(i, clock, seed=seed, alert_rules=alert_rules,
                    alert_sink=alert_sink, **host_kw)
            for i in range(int(n_hosts))]

    # ------------------------------------------------------ addressing
    @property
    def addrs(self) -> List[str]:
        return [f"sim{h.host_id}:9000" for h in self.hosts]

    def _route(self, url: str):
        m = _URL_RE.match(url)
        if not m:
            raise ValueError(f"not a sim fleet url: {url!r}")
        host_id = int(m.group(1))
        if host_id >= len(self.hosts):
            raise ValueError(f"no sim host {host_id} (fleet of "
                             f"{len(self.hosts)})")
        return self.hosts[host_id], m.group(2)

    # --------------------------------------------------------- fetches
    def fetch(self, url: str) -> str:
        """The text-returning fetch the real scrapers inject.  Raises
        exactly the way a real transport fails: TimeoutError for a
        partitioned peer (after stalling ``partition_stall_s`` of real
        wall clock — the cost the concurrent scrape bounds),
        ConnectionRefusedError for a down one."""
        host, path = self._route(url)
        if host.partitioned:
            if self.partition_stall_s > 0:
                time.sleep(self.partition_stall_s)
            raise TimeoutError(
                f"simulated network partition: sim{host.host_id}")
        if not host.up:
            raise ConnectionRefusedError(
                f"simulated down host: sim{host.host_id}")
        if path == "/healthz":
            return json.dumps(host.health())
        if path == "/metrics":
            return host.metrics_text()
        raise ValueError(f"no sim route {path!r}")

    def health_fetch(self, url: str) -> Optional[dict]:
        """The dict-or-None fetch :class:`HangWatchdog` injects
        (unreachable reads as None — never as hung)."""
        try:
            return json.loads(self.fetch(url))
        except Exception:  # noqa: BLE001 — unreachable != hung
            return None

    def watchdog_fetch(self, host_id: int):
        """A watchdog fetch pinned to one host (the watchdog spells
        127.0.0.1 urls; this rewrites them onto the sim address
        space)."""
        def fetch(_url: str) -> Optional[dict]:
            return self.health_fetch(f"http://sim{int(host_id)}:9000"
                                     "/healthz")
        return fetch

    # --------------------------------------------------------- scenario
    def skew_clock(self, host_id: int, offset_s: float):
        """Skew one host's reported wall clock (multi-region NTP drift,
        a wedged timesync daemon): its ``/healthz`` ``time`` shifts by
        ``offset_s`` while the host otherwise behaves — exactly the
        insidious case the aggregator's staleness detection exists to
        exclude-and-account instead of folding into fleet percentiles."""
        self.hosts[int(host_id)].clock_skew_s = float(offset_s)

    def partition(self, host_id: int, on: bool = True):
        """Partition (or heal) one host — its fetches time out."""
        self.hosts[int(host_id)].partitioned = bool(on)

    # ------------------------------------------------------- lifecycle
    def tick(self, dt: float):
        for h in self.hosts:
            h.tick(dt)

    def evaluate_alerts(self) -> List[dict]:
        out = []
        for h in self.hosts:
            out.extend(h.evaluate_alerts())
        return out

    @property
    def up_count(self) -> int:
        return sum(1 for h in self.hosts if h.up)

    @property
    def transitions(self) -> List[dict]:
        out = []
        for h in self.hosts:
            out.extend(h.transitions)
        return out

    def __repr__(self) -> str:
        return (f"SimFleet({len(self.hosts)} hosts, {self.up_count} up, "
                f"t={self.clock.now():.1f})")
