"""Declarative chaos scenarios on the virtual clock.

A scenario is a JSON-able dict — loadable inline, from a file, or by
builtin name (the same resolution contract as ``BIGDL_ALERT_RULES`` /
``BIGDL_AUTOSCALE_RULES``) and validated LOUDLY: a typo'd chaos
scenario that silently does nothing is a fleet "validated" against
clear skies.

Schema::

    {
      "name": "diurnal",
      "duration_s": 600, "tick_s": 5,          # virtual seconds
      "start_world": 1,
      "autoscale": {"queue_high": 64, ...},    # AutoscaleConfig overrides
      "alert_rules": [...],                    # per-host pack (alerts.py
                                               # schema, resolve_for ok)
      "events": [ {"kind": ..., "at_s": ..., "until_s": ...,
                   "hosts": {"fraction"|"count"|"ids": ...}, ...} ],
      "expect": {...}                          # invariant parameters
    }

Event kinds (every virtual-time field ends in ``_s`` so time
compression can find it):

=============  ========================================================
``traffic``    offered-load wave: ``base`` + ``amplitude`` · half-cosine
               over ``period_s``; per-host queue depth =
               offered / world · (n_hosts / up_hosts) — the negative
               feedback that makes autoscale convergence a real claim
``straggler``  selected hosts run ``factor``× slower (step-time signal)
``stall``      selected hosts stop stepping (``/healthz`` stalled)
``partition``  selected hosts time out on fetch (not 404 — the
               expensive failure)
``preempt``    cascading: selected hosts drop at ``at_s + i·stagger_s``
               for ``down_s`` each, then restart with reset counters
``flap``       selected hosts alternate up/down every ``period_s``/2
``latency``    selected hosts' e2e request latency moves to ``e2e_s``
``goodput``    selected hosts' goodput ratio moves to ``ratio``
``poison_sink``  from ``at_s`` on, every host's alert sink fails
=============  ========================================================

``expect`` keys parameterize the invariant checker
(:mod:`bigdl_tpu.sim.invariants`); unknown keys are rejected — a typo'd
expectation silently passing is the exact failure class this subsystem
exists to remove.
"""

from __future__ import annotations

import copy
import json
import math
import random
from typing import Dict, Optional

from bigdl_tpu.obs import names

EVENT_KINDS = ("traffic", "straggler", "stall", "partition", "preempt",
               "flap", "latency", "goodput", "poison_sink")

# per-kind required extra fields (beyond kind/at_s/until_s/hosts)
_EVENT_REQUIRED = {
    "traffic": ("base",),
    "straggler": ("factor",),
    "stall": (),
    "partition": (),
    "preempt": ("down_s",),
    "flap": ("period_s",),
    "latency": ("e2e_s",),
    "goodput": ("ratio",),
    "poison_sink": (),
}

_EXPECT_KEYS = frozenset({
    "max_decisions", "min_decisions", "reasons",
    "no_decisions_during_s", "quiet_tail_s", "final_world",
    "alert_episodes", "alerts_required", "all_resolved",
    "max_scrape_cycle_s", "min_sink_failures",
    "bundles_per_episode",
})

_AUTOSCALE_KEYS = frozenset({
    "min_world", "max_world", "factor", "interval_s", "warmup_s",
    "cooldown_s", "hysteresis", "step_time_high", "step_time_low",
    "queue_high", "queue_low", "goodput_floor", "evict_stragglers",
    "p99_high", "p99_low", "rules",
})


def _compress_times(obj, factor: float):
    """Divide every virtual duration by ``factor``, in place-ish
    (returns a new structure).  A field is a virtual duration iff its
    key ends in ``_s`` — the schema spells every time field that way —
    except ``tick_s``: the tick period is preserved, so compression
    runs the same scenario shape in fewer ticks."""
    if factor == 1.0:
        return obj

    def scale(v):
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return v / factor
        if isinstance(v, list):
            return [scale(x) for x in v]
        if isinstance(v, dict):
            return {k: scale(x) for k, x in v.items()}
        return v

    def walk(v):
        if isinstance(v, dict):
            out = {}
            for k, x in v.items():
                if k.endswith("_s") and k != "tick_s":
                    out[k] = scale(x)
                else:
                    out[k] = walk(x)
            return out
        if isinstance(v, list):
            return [walk(x) for x in v]
        return v

    return walk(obj)


def _fail(name: str, msg: str):
    raise ValueError(f"scenario {name!r}: {msg}")


class Scenario:
    """One validated, host-bound chaos scenario."""

    def __init__(self, raw: dict):
        if not isinstance(raw, dict):
            raise ValueError(
                f"a scenario must be a JSON object, got "
                f"{type(raw).__name__}")
        self.raw = copy.deepcopy(raw)
        name = self.raw.get("name")
        if not name:
            raise ValueError(f"scenario missing a name: {raw!r}")
        self.name = str(name)
        self.description = str(self.raw.get("description", ""))
        self.duration_s = float(self.raw.get("duration_s", 0.0))
        self.tick_s = float(self.raw.get("tick_s", 5.0))
        if self.duration_s <= 0 or self.tick_s <= 0:
            _fail(self.name, "duration_s and tick_s must be > 0")
        self.hosts = int(self.raw.get("hosts", 0))  # 0 = caller default
        self.start_world = int(self.raw.get("start_world", 1))
        self.base_latency_s = float(self.raw.get("base_latency_s", 0.02))
        self.base_goodput = float(self.raw.get("base_goodput", 0.95))

        self.autoscale = dict(self.raw.get("autoscale") or {})
        bad = set(self.autoscale) - _AUTOSCALE_KEYS
        if bad:
            _fail(self.name, f"unknown autoscale override(s) "
                             f"{sorted(bad)} (one of "
                             f"{sorted(_AUTOSCALE_KEYS)})")
        self.alert_rules = list(self.raw.get("alert_rules") or [])

        self.expect = dict(self.raw.get("expect") or {})
        bad = set(self.expect) - _EXPECT_KEYS
        if bad:
            _fail(self.name, f"unknown expect key(s) {sorted(bad)} "
                             f"(one of {sorted(_EXPECT_KEYS)})")

        self.events = []
        for i, ev in enumerate(list(self.raw.get("events") or [])):
            self.events.append(self._validate_event(i, ev))
        self._bound: Optional[int] = None

    # ------------------------------------------------------ validation
    def _validate_event(self, i: int, ev) -> dict:
        if not isinstance(ev, dict):
            _fail(self.name, f"event #{i} is not an object: {ev!r}")
        kind = ev.get("kind")
        if kind not in EVENT_KINDS:
            _fail(self.name, f"event #{i}: unknown kind {kind!r} "
                             f"(one of {EVENT_KINDS})")
        out = dict(ev)
        out["at_s"] = float(ev.get("at_s", 0.0))
        out["until_s"] = float(ev.get("until_s", self.duration_s))
        if not 0.0 <= out["at_s"] < out["until_s"]:
            _fail(self.name, f"event #{i} ({kind}): need "
                             f"0 <= at_s < until_s, got "
                             f"[{out['at_s']}, {out['until_s']}]")
        for field in _EVENT_REQUIRED[kind]:
            if field not in ev:
                _fail(self.name, f"event #{i} ({kind}): missing "
                                 f"{field!r}")
        sel = ev.get("hosts")
        if sel is not None:
            if not isinstance(sel, dict) or len(sel) != 1 or \
                    next(iter(sel)) not in ("fraction", "count", "ids"):
                _fail(self.name,
                      f"event #{i} ({kind}): hosts selector must be "
                      f"exactly one of fraction/count/ids, got {sel!r}")
        out["hosts"] = sel
        out["_index"] = i
        return out

    # --------------------------------------------------------- binding
    def bind(self, n_hosts: int, seed: int = 0) -> "Scenario":
        """Resolve every event's host selector against a concrete
        fleet size, deterministically from ``seed``."""
        n = int(n_hosts)
        for ev in self.events:
            sel = ev["hosts"]
            if sel is None:
                ev["_ids"] = list(range(n))
                continue
            key, val = next(iter(sel.items()))
            if key == "ids":
                ids = sorted(int(x) for x in val)
                if ids and (ids[0] < 0 or ids[-1] >= n):
                    _fail(self.name,
                          f"event #{ev['_index']}: ids out of range "
                          f"for a {n}-host fleet: {ids}")
            else:
                k = (max(1, int(round(float(val) * n)))
                     if key == "fraction" else min(n, int(val)))
                rng = random.Random(
                    f"{seed}:{self.name}:{ev['_index']}")
                ids = sorted(rng.sample(range(n), k))
            ev["_ids"] = ids
        self._bound = n
        return self

    # ------------------------------------------------------- dynamics
    def _active(self, ev: dict, t: float) -> bool:
        return ev["at_s"] <= t < ev["until_s"]

    def offered(self, t: float) -> Optional[float]:
        """Offered load at virtual time ``t`` (None when no traffic
        event covers it)."""
        for ev in self.events:
            if ev["kind"] != "traffic" or not self._active(ev, t):
                continue
            base = float(ev["base"])
            amp = float(ev.get("amplitude", 0.0))
            if amp == 0.0:
                return base
            period = float(ev.get("period_s",
                                  ev["until_s"] - ev["at_s"]))
            phase = 2.0 * math.pi * (t - ev["at_s"]) / max(1e-9, period)
            return base + amp * 0.5 * (1.0 - math.cos(phase))
        return None

    def sink_poisoned(self, t: float) -> bool:
        return any(ev["kind"] == "poison_sink" and t >= ev["at_s"]
                   for ev in self.events)

    def apply(self, fleet, t: float, world: int):
        """Drive the fleet to this instant's scenario state (stateless
        recompute from the event windows, then edge-triggered up/down
        transitions so a returning host restarts like a fresh
        process)."""
        if self._bound is None or self._bound != len(fleet.hosts):
            raise RuntimeError(
                f"scenario {self.name!r} not bound to this fleet size "
                f"(bind({len(fleet.hosts)}) first)")
        hosts = fleet.hosts
        n = len(hosts)
        want_up = [True] * n
        for h in hosts:
            h.partitioned = False
            h.stalled = False
            h.slow_factor = 1.0
            h.latency_e2e_s = self.base_latency_s
            h.goodput_ratio = self.base_goodput
        for ev in self.events:
            kind = ev["kind"]
            if kind == "preempt":
                stagger = float(ev.get("stagger_s", 0.0))
                down = float(ev["down_s"])
                for idx, hid in enumerate(ev["_ids"]):
                    t0 = ev["at_s"] + idx * stagger
                    if t0 <= t < t0 + down:
                        want_up[hid] = False
                continue
            if not self._active(ev, t):
                continue
            if kind == "flap":
                half = max(1e-9, float(ev["period_s"]) / 2.0)
                if int((t - ev["at_s"]) // half) % 2 == 1:
                    for hid in ev["_ids"]:
                        want_up[hid] = False
            elif kind == "straggler":
                for hid in ev["_ids"]:
                    hosts[hid].slow_factor = float(ev["factor"])
            elif kind == "stall":
                for hid in ev["_ids"]:
                    hosts[hid].stalled = True
            elif kind == "partition":
                for hid in ev["_ids"]:
                    hosts[hid].partitioned = True
            elif kind == "latency":
                for hid in ev["_ids"]:
                    hosts[hid].latency_e2e_s = float(ev["e2e_s"])
            elif kind == "goodput":
                for hid in ev["_ids"]:
                    hosts[hid].goodput_ratio = float(ev["ratio"])
        # up/down edges AFTER all events voted
        for h, want in zip(hosts, want_up):
            if h.up and not want:
                h.up = False
            elif not h.up and want:
                h.restart()
        # traffic: the load the up hosts share, divided by the world
        # the controller bought — scale-ups drain the queue (negative
        # feedback), dead hosts pile their share onto the survivors
        offered = self.offered(t)
        if offered is not None:
            up = max(1, fleet.up_count)
            per_host = offered / max(1, int(world)) * (n / up)
            for h in hosts:
                h.queue_depth = per_host

    def n_ticks(self) -> int:
        return int(math.ceil(self.duration_s / self.tick_s))


# ------------------------------------------------------------ builtins
def _sim_autoscale(**over) -> dict:
    base = dict(min_world=1, max_world=8, factor=2, interval_s=5.0,
                warmup_s=10.0, cooldown_s=60.0, hysteresis=2)
    base.update(over)
    return base


def _queue_alert(value: float, name: str = "queue_backlog") -> dict:
    return {"name": name, "type": "threshold",
            "metric": names.SERVE_QUEUE_DEPTH, "op": ">",
            "value": value, "for": 2, "resolve_for": 2,
            "severity": "warning"}


def _goodput_alert(value: float = 0.5) -> dict:
    return {"name": "goodput_below_target", "type": "threshold",
            "metric": names.GOODPUT_RATIO, "op": "<", "value": value,
            "for": 2, "resolve_for": 2, "severity": "warning"}


BUILTIN_SCENARIOS: Dict[str, dict] = {
    # the capacity wave: traffic swells 20 -> ~1220 and back over the
    # day (the peak deliberately exceeds max-world capacity, so the
    # backlog alert gets real episodes); the controller must ride it up
    # and back down without a single up/down flap inside a cooldown
    # window
    "diurnal": {
        "name": "diurnal",
        "description": "diurnal traffic wave; autoscaler rides it up "
                       "and down without flapping",
        "duration_s": 600.0, "tick_s": 5.0, "start_world": 1,
        "autoscale": _sim_autoscale(queue_high=64.0, queue_low=8.0),
        "alert_rules": [_queue_alert(96.0)],
        "events": [
            {"kind": "traffic", "base": 20.0, "amplitude": 1200.0,
             "period_s": 600.0},
        ],
        "expect": {
            "max_decisions": 8, "min_decisions": 2,
            "reasons": ["queue_high", "queue_low"],
            "final_world": [2, 8],
            "alert_episodes": {"queue_backlog": [1, 4]},
            "alerts_required": ["queue_backlog"],
            "all_resolved": True,
        },
    },
    # correlated stragglers: 10% of hosts run 6x slow for five virtual
    # minutes — the slowest host gates the fleet step-time signal, the
    # per-host goodput alert fires exactly once per slow host
    "stragglers": {
        "name": "stragglers",
        "description": "correlated 6x stragglers on 10% of the fleet; "
                       "worst-host gating + one alert episode each",
        "duration_s": 600.0, "tick_s": 5.0, "start_world": 1,
        "autoscale": _sim_autoscale(step_time_high=0.35, max_world=2),
        "alert_rules": [_goodput_alert(0.5)],
        "events": [
            {"kind": "straggler", "at_s": 150.0, "until_s": 450.0,
             "hosts": {"fraction": 0.1}, "factor": 6.0},
            {"kind": "goodput", "at_s": 150.0, "until_s": 450.0,
             "hosts": {"fraction": 0.1}, "ratio": 0.3},
        ],
        "expect": {
            "max_decisions": 1, "min_decisions": 1,
            "reasons": ["step_time_high"],
            "final_world": [2, 2],
            "alert_episodes": {"goodput_below_target": [1, 1]},
            "alerts_required": ["goodput_below_target"],
            "all_resolved": True,
        },
    },
    # network partition: 30% of peers time out (not 404) for four
    # virtual minutes; absent signals must never breach a rule, and the
    # concurrent scrape must keep the cycle wall bounded
    "partition": {
        "name": "partition",
        "description": "30% of peers time out; conservative no-decision "
                       "degradation + bounded scrape cycles",
        "duration_s": 600.0, "tick_s": 5.0, "start_world": 1,
        "autoscale": _sim_autoscale(queue_high=64.0, queue_low=8.0),
        "alert_rules": [_queue_alert(64.0)],
        "events": [
            {"kind": "traffic", "base": 30.0},
            {"kind": "partition", "at_s": 150.0, "until_s": 400.0,
             "hosts": {"fraction": 0.3}},
        ],
        "expect": {
            "max_decisions": 0,
            "no_decisions_during_s": [[150.0, 400.0]],
            "final_world": [1, 1],
            "max_scrape_cycle_s": 1.0,
        },
    },
    # cascading preemptions: half the fleet drops in a 100s cascade,
    # each host down for two virtual minutes; survivors inherit the
    # load, breach once, the controller buys one doubling, the alert
    # resolves — exactly one episode per survivor
    "preemptions": {
        "name": "preemptions",
        "description": "cascading preemptions of 25% of the fleet; one "
                       "scale-up, one alert episode per survivor",
        "duration_s": 600.0, "tick_s": 5.0, "start_world": 1,
        "autoscale": _sim_autoscale(queue_high=64.0, queue_low=8.0),
        "alert_rules": [_queue_alert(60.0)],
        "events": [
            {"kind": "traffic", "base": 52.0},
            {"kind": "preempt", "at_s": 150.0,
             "hosts": {"fraction": 0.25}, "stagger_s": 2.0,
             "down_s": 120.0},
        ],
        "expect": {
            "max_decisions": 1, "min_decisions": 1,
            "reasons": ["queue_high"],
            "final_world": [2, 2],
            "alert_episodes": {"queue_backlog": [1, 2]},
            "alerts_required": ["queue_backlog"],
            "all_resolved": True,
        },
    },
    # flapping hosts + a poisoned alert sink: intermittent scrape
    # errors and failing sink deliveries must neither thrash the world
    # nor wedge/duplicate alert episodes
    "flapping": {
        "name": "flapping",
        "description": "flapping hosts + poisoned alert sink; no world "
                       "thrash, sink failures counted, episodes intact",
        "duration_s": 600.0, "tick_s": 5.0, "start_world": 1,
        "autoscale": _sim_autoscale(queue_high=64.0, queue_low=8.0),
        "alert_rules": [_goodput_alert(0.5)],
        "events": [
            {"kind": "traffic", "base": 30.0},
            {"kind": "flap", "at_s": 100.0, "until_s": 500.0,
             "hosts": {"count": 4}, "period_s": 40.0},
            {"kind": "goodput", "at_s": 200.0, "until_s": 280.0,
             "ratio": 0.3},
            {"kind": "poison_sink"},
        ],
        "expect": {
            "max_decisions": 0,
            "final_world": [1, 1],
            "alert_episodes": {"goodput_below_target": [1, 1]},
            "alerts_required": ["goodput_below_target"],
            "all_resolved": True,
            "min_sink_failures": 1,
        },
    },
    # alert storm: three separate fleet-wide goodput dips, so every
    # host's goodput alert fires three distinct episodes — and with
    # BIGDL_BUNDLE_DIR set the alert->bundle path must cut exactly ONE
    # manifest-valid debug bundle per firing transition (none dropped,
    # none duplicated across racing transitions, none torn)
    "alert_storm": {
        "name": "alert_storm",
        "description": "three goodput-dip pulses; three alert episodes "
                       "per host, one debug bundle per episode",
        "duration_s": 600.0, "tick_s": 5.0, "start_world": 1,
        "autoscale": _sim_autoscale(queue_high=64.0, queue_low=8.0),
        "alert_rules": [_goodput_alert(0.5)],
        "events": [
            {"kind": "goodput", "at_s": 100.0, "until_s": 160.0,
             "ratio": 0.3},
            {"kind": "goodput", "at_s": 250.0, "until_s": 310.0,
             "ratio": 0.3},
            {"kind": "goodput", "at_s": 400.0, "until_s": 460.0,
             "ratio": 0.3},
        ],
        "expect": {
            "max_decisions": 0,
            "final_world": [1, 1],
            "alert_episodes": {"goodput_below_target": [3, 3]},
            "alerts_required": ["goodput_below_target"],
            "all_resolved": True,
            "bundles_per_episode": True,
        },
    },
    # serving latency wave: fleet-wide e2e p99 rises past the band,
    # the controller scales to its ceiling, the wave passes, it scales
    # back — the serving-signal (histogram-bucket) path at fleet scale
    "latency_wave": {
        "name": "latency_wave",
        "description": "fleet-wide p99 wave; latency band scales up to "
                       "the ceiling and back down after",
        "duration_s": 600.0, "tick_s": 5.0, "start_world": 1,
        "autoscale": _sim_autoscale(p99_high=0.25, p99_low=0.05,
                                    max_world=4),
        "events": [
            {"kind": "latency", "at_s": 150.0, "until_s": 450.0,
             "e2e_s": 0.6},
        ],
        "expect": {
            "max_decisions": 6, "min_decisions": 3,
            "reasons": ["latency_p99_high", "latency_p99_low"],
            "final_world": [1, 2],
        },
    },
}


def load_scenario(spec, hosts: int = 0, seed: int = 0,
                  time_compression: float = 1.0) -> Scenario:
    """Resolve + validate one scenario: a builtin name, inline JSON, a
    JSON file path, or an already-parsed dict; then compress its
    virtual timeline and bind its host selectors."""
    if isinstance(spec, Scenario):
        raw = spec.raw
    elif isinstance(spec, dict):
        raw = spec
    elif isinstance(spec, str):
        if spec in BUILTIN_SCENARIOS:
            raw = BUILTIN_SCENARIOS[spec]
        elif spec.lstrip().startswith(("{", "[")):
            raw = json.loads(spec)
        else:
            try:
                with open(spec, "r", encoding="utf-8") as fh:
                    raw = json.load(fh)
            except FileNotFoundError:
                raise ValueError(
                    f"unknown scenario {spec!r}: not a builtin "
                    f"({sorted(BUILTIN_SCENARIOS)}), not inline JSON, "
                    "and no such file") from None
    else:
        raise ValueError(f"cannot load a scenario from "
                         f"{type(spec).__name__}")
    factor = float(time_compression)
    if factor <= 0:
        raise ValueError(f"time_compression must be > 0, got {factor}")
    sc = Scenario(_compress_times(raw, factor))
    n = int(hosts) if hosts else (sc.hosts or 0)
    if n <= 0:
        raise ValueError(f"scenario {sc.name!r}: no host count (pass "
                         "hosts= or set it in the scenario)")
    return sc.bind(n, seed=seed)
