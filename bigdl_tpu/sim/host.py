"""One synthetic host: the scrape surface of a real process, in memory.

A :class:`SimHost` is everything the control plane can *see* of a real
training/serving process, with the process itself abstracted away:

* a real :class:`~bigdl_tpu.obs.metrics.MetricsRegistry` holding the
  production families (``bigdl_serve_queue_depth``,
  ``bigdl_goodput_ratio``, the ``bigdl_request_latency_seconds`` e2e
  histogram, ``bigdl_heartbeat_age_seconds``) — ``metrics_text()`` is
  a genuine Prometheus exposition the real
  :func:`~bigdl_tpu.obs.metrics.parse_prometheus` reader consumes;
* a ``/healthz`` payload carrying the exact keys
  ``obs/server.health_payload`` serves (status, host, pid, attempt,
  time, step, step_age_s, goodput_ratio, alerts, heartbeat) — what
  :func:`~bigdl_tpu.resilience.autoscale.derive_signals` and the hang
  watchdog key on;
* its own REAL :class:`~bigdl_tpu.obs.alerts.AlertEngine` over its own
  registry — the per-host topology production runs — evaluated on the
  virtual clock, with transitions collected for the exactly-once
  invariant.

Scenario hooks are plain attributes (``queue_depth``,
``goodput_ratio``, ``latency_e2e_s``, ``slow_factor``, ``stalled``,
``up``, ``partitioned``) the scenario engine mutates between ticks;
``tick()`` advances the step counter on the virtual clock and
republishes the gauges (with a small deterministic per-host jitter so
hysteresis has real noise to prove itself against).

The latency histogram is re-observed fresh each tick (a windowed view:
the family is cleared, then ``latency_samples`` observations land at
the current level), so a latency wave moves the scraped p99 crisply
instead of drowning in cumulative history.
"""

from __future__ import annotations

import random
from typing import List, Optional

from bigdl_tpu.obs import names
from bigdl_tpu.obs.alerts import AlertEngine
from bigdl_tpu.obs.metrics import MetricsRegistry

# one decode/train step per this many virtual seconds, before the
# straggler slow_factor
DEFAULT_STEP_TIME_S = 0.1


class SimHost:
    """One synthetic host on the virtual clock."""

    def __init__(self, host_id: int, clock, seed: int = 0,
                 base_step_time_s: float = DEFAULT_STEP_TIME_S,
                 alert_rules: Optional[List[dict]] = None,
                 alert_sink: Optional[str] = None,
                 latency_samples: int = 20,
                 jitter: float = 0.03):
        self.host_id = int(host_id)
        self.clock = clock
        self.rng = random.Random((int(seed) << 20) ^ (host_id * 2654435761))
        self.base_step_time_s = float(base_step_time_s)
        self.latency_samples = int(latency_samples)
        self.jitter = float(jitter)

        # --- scenario-mutable state --------------------------------
        self.up = True               # down => connection refused
        self.partitioned = False     # => fetch times out (wall cost)
        self.stalled = False         # step stamp stops advancing
        self.clock_skew_s = 0.0      # /healthz clock offset (staleness)
        self.slow_factor = 1.0       # straggler multiplier on step time
        self.queue_depth = 0.0
        self.goodput_ratio = 0.95
        self.latency_e2e_s = 0.02

        # --- process-like state ------------------------------------
        self.attempt = 0
        self.started_at = clock.now()
        self._steps = 0.0
        self._last_step_wall: Optional[float] = None
        self.registry = MetricsRegistry()
        self.engine: Optional[AlertEngine] = None
        if alert_rules:
            self.engine = AlertEngine(alert_rules, registry=self.registry,
                                      sink=alert_sink, clock=clock)
        #: alert transitions this host emitted, in order (each dict is
        #: the engine's transition record plus ``host``)
        self.transitions: List[dict] = []
        self.sink_poisoned = False
        self._publish()

    # ---------------------------------------------------------- clock
    def tick(self, dt: float):
        """Advance one scenario tick of ``dt`` virtual seconds."""
        if self.up and not self.stalled:
            self._steps += dt / max(1e-9, self.base_step_time_s
                                    * self.slow_factor)
            self._last_step_wall = self.clock.now()
        if self.up:
            self._publish()

    def evaluate_alerts(self) -> List[dict]:
        """One alert-engine pass (no-op while down — a dead process
        evaluates nothing); transitions are collected for the
        exactly-once invariant."""
        if self.engine is None or not self.up:
            return []
        out = []
        for t in self.engine.evaluate():
            rec = dict(t, host=self.host_id)
            self.transitions.append(rec)
            out.append(rec)
        return out

    def restart(self):
        """Come back from a preemption/flap: a fresh process restarts
        its step counter and attempt index (the alert engine keeps its
        episode ordinals so transition pairing stays global)."""
        self.attempt += 1
        self._steps = 0.0
        self._last_step_wall = None
        self.started_at = self.clock.now()
        self.up = True

    # -------------------------------------------------------- surface
    def _jittered(self, v: float) -> float:
        if v <= 0 or self.jitter <= 0:
            return v
        return v * (1.0 + self.rng.uniform(-self.jitter, self.jitter))

    def _publish(self):
        reg = self.registry
        reg.gauge(names.SERVE_QUEUE_DEPTH,
                  "Requests waiting in the bounded admission queue"
                  ).set(self._jittered(self.queue_depth))
        reg.gauge(names.GOODPUT_RATIO,
                  "Productive step seconds over total accounted wall "
                  "seconds").set(min(1.0, max(
                      0.0, self._jittered(self.goodput_ratio))))
        age = self.step_age_s()
        if age is not None:
            reg.gauge(names.HEARTBEAT_AGE_SECONDS,
                      "Seconds since each peer's last heartbeat touch",
                      labels=("host",)).labels(host=self.host_id).set(age)
        hist = reg.histogram(
            names.REQUEST_LATENCY_SECONDS,
            "Request latency by engine and kind (ttft/per_token/e2e)",
            labels=("engine", "kind"))
        # windowed view: drop the previous tick's observations so the
        # scraped p99 tracks the CURRENT level (nearest-bucket
        # quantized), not the whole run's history
        hist.clear()
        fam_child = hist.labels(engine="lm", kind="e2e")
        for _ in range(self.latency_samples):
            fam_child.observe(self._jittered(self.latency_e2e_s))

    def step(self) -> Optional[int]:
        s = int(self._steps)
        return s if s >= 1 else None

    def step_age_s(self) -> Optional[float]:
        if self._last_step_wall is None:
            return None
        return round(self.clock.now() - self._last_step_wall, 6)

    def health(self) -> dict:
        """The ``/healthz`` JSON body — key-for-key the payload
        ``obs/server.health_payload`` serves."""
        step = self.step()
        status = "idle" if step is None else (
            "stalled" if self.stalled else "ok")
        now = self.clock.now()
        return {
            "status": status,
            "host": self.host_id,
            "pid": 40000 + self.host_id,
            "attempt": self.attempt,
            # a skewed host reports a skewed wall clock — the surface
            # the aggregator's staleness detection keys on
            "time": now + self.clock_skew_s,
            "port": 9000,
            "uptime_s": round(now - self.started_at, 6),
            "step": step,
            "step_age_s": self.step_age_s(),
            "goodput_ratio": round(self.goodput_ratio, 6),
            "alerts": (self.engine.active() if self.engine is not None
                       else []),
            "heartbeat": None,
            # continuous profiling plane: sim hosts run no sampler and
            # cut no bundles, but the contract keys must be present
            "prof_overhead": None,
            "bundles": 0,
        }

    def metrics_text(self) -> str:
        """The ``/metrics`` body: real Prometheus text exposition."""
        return self.registry.to_prometheus()

    def __repr__(self) -> str:
        flags = "".join(f for f, on in (
            ("D", not self.up), ("P", self.partitioned),
            ("S", self.stalled)) if on) or "ok"
        return (f"SimHost(h{self.host_id} {flags} step={self.step()} "
                f"q={self.queue_depth:.1f})")
