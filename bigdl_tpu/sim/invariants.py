"""Fleet-level invariants — what every chaos scenario must uphold.

A scenario run produces an observation bundle (decisions with virtual
timestamps, per-host alert transitions with episode ids, per-cycle
scrape walls, the final world, sink failure counts);
:func:`check_scenario` turns it + the scenario's ``expect`` block into
a list of :class:`InvariantResult` verdicts:

* **no_flap** — the autoscaler never issues opposite-direction
  decisions inside one cooldown window, and the decision count stays
  inside the scenario's declared bounds with every required reason
  present;
* **convergence** — the run ends at the expected world, quiet through
  the declared tail;
* **exactly_once_episodes** — per (host, rule): transitions strictly
  alternate firing → resolved, episode ids are consecutive and pair
  each resolve to its firing, per-host episode counts stay in the
  declared range, and (when declared) everything is resolved by
  scenario end.  This is the invariant that pins the alert-engine
  double-fire fix;
* **conservative_degradation** — no decision lands inside a window
  where the scenario declares signals unreliable (partitions): an
  absent signal must never breach a rule;
* **scrape_budget** — no scrape cycle's wall clock exceeded the
  declared bound (the concurrent bounded-pool scrape's contract; a
  serial scrape fails this the moment peers time out);
* **sink_failures** — a poisoned alert sink is *counted*, not wedging:
  at least the declared number of delivery failures landed while the
  episode invariant above still held.

Standalone probes for the properties a tick loop cannot express:

* :func:`check_aggregation_scaling` — the real
  :class:`~bigdl_tpu.obs.aggregate.FleetAggregator` snapshot cost at N
  hosts stays within a wall budget AND grows ~linearly (measured
  against a fleet a quarter the size);
* :func:`check_supervisor_flap` — the real
  :class:`~bigdl_tpu.resilience.supervisor.Supervisor` rides a
  flapping (preemption-class) child without spending ONE unit of the
  transient retry budget;
* :func:`check_watchdog` — the real :class:`~bigdl_tpu.resilience.
  supervisor.HangWatchdog` flags a genuinely stalled host and stays
  conservative on a partitioned (unreachable) one.

Serving data-plane invariants (the router chaos scenarios in
:mod:`bigdl_tpu.sim.serve` — :func:`check_serve_scenario` composes):

* **request_conservation** — every admitted request is answered
  exactly once (completed or an explicit shed): zero lost, zero
  duplicated across preemption dumps, drains, and handoff replays;
* **retry_amplification** — the shared retry budget's arithmetic
  bound holds (retries granted <= burst + ratio x requests) and
  end-to-end backend amplification stays <= 1 + ratio + slack — a
  brownout cannot turn into a retry storm;
* **slo_stability** — the backlog-driven SLO-burn alert fires at most
  the declared number of times and is resolved by scenario end:
  absorbing a preemption storm must not flap the alert.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List


@dataclasses.dataclass
class InvariantResult:
    """One invariant verdict (JSON-able via dataclasses.asdict)."""

    name: str
    ok: bool
    detail: str

    def __str__(self) -> str:
        return f"[{'PASS' if self.ok else 'FAIL'}] {self.name}: " \
               f"{self.detail}"


def _result(name: str, ok: bool, detail: str) -> InvariantResult:
    return InvariantResult(name, bool(ok), detail)


# ------------------------------------------------------------- checks
def check_no_flap(decisions: List[dict], cooldown_s: float,
                  expect: dict) -> InvariantResult:
    problems = []
    for prev, cur in zip(decisions, decisions[1:]):
        gap = cur["t"] - prev["t"]
        if cur["direction"] != prev["direction"] and gap < cooldown_s:
            problems.append(
                f"{prev['direction']}@{prev['t']:.0f}s then "
                f"{cur['direction']}@{cur['t']:.0f}s ({gap:.0f}s < "
                f"cooldown {cooldown_s:.0f}s)")
    lo = int(expect.get("min_decisions", 0))
    hi = expect.get("max_decisions")
    n = len(decisions)
    if n < lo:
        problems.append(f"only {n} decision(s), expected >= {lo}")
    if hi is not None and n > int(hi):
        problems.append(f"{n} decision(s), expected <= {hi}")
    reasons = [d["reason"] for d in decisions]
    for want in expect.get("reasons", []):
        if want not in reasons:
            problems.append(f"required reason {want!r} never decided "
                            f"(got {sorted(set(reasons))})")
    return _result(
        "no_flap", not problems,
        "; ".join(problems) or
        f"{n} decision(s), no up/down inside the "
        f"{cooldown_s:.0f}s cooldown")


def check_convergence(decisions: List[dict], final_world: int,
                      duration_s: float,
                      expect: dict) -> InvariantResult:
    problems = []
    fw = expect.get("final_world")
    if fw is not None:
        lo, hi = (fw if isinstance(fw, (list, tuple)) else (fw, fw))
        if not int(lo) <= int(final_world) <= int(hi):
            problems.append(f"final world {final_world} outside "
                            f"[{lo}, {hi}]")
    tail = expect.get("quiet_tail_s")
    if tail is not None:
        cutoff = duration_s - float(tail)
        late = [d for d in decisions if d["t"] >= cutoff]
        if late:
            problems.append(f"{len(late)} decision(s) inside the "
                            f"final {tail:.0f}s quiet tail")
    return _result("convergence", not problems,
                   "; ".join(problems) or f"settled at world "
                                          f"{final_world}")


def check_exactly_once_episodes(transitions: List[dict],
                                expect: dict) -> InvariantResult:
    """Per (host, rule): firing/resolved strictly alternate, episode
    ids are consecutive and pair each resolve with its firing — the
    'exactly once per episode' contract."""
    problems = []
    by_key: Dict[tuple, List[dict]] = {}
    for t in transitions:
        by_key.setdefault((t["host"], t["rule"]), []).append(t)
    fired_rules = set()
    episode_counts: Dict[str, List[int]] = {}
    for (host, rule), seq in sorted(by_key.items()):
        fired_rules.add(rule)
        episodes = 0
        expect_state = "firing"
        for t in seq:
            if t["state"] != expect_state:
                problems.append(
                    f"h{host}/{rule}: got {t['state']!r} where "
                    f"{expect_state!r} was due (episode "
                    f"{t.get('episode')})")
                break
            if t["state"] == "firing":
                episodes += 1
                if t.get("episode") != episodes:
                    problems.append(
                        f"h{host}/{rule}: firing #{episodes} carries "
                        f"episode id {t.get('episode')} — the same "
                        "episode fired twice or an id was skipped")
                    break
                expect_state = "resolved"
            else:
                if t.get("episode") != episodes:
                    problems.append(
                        f"h{host}/{rule}: resolve pairs episode "
                        f"{t.get('episode')} with firing {episodes}")
                    break
                expect_state = "firing"
        episode_counts.setdefault(rule, []).append(episodes)
        if expect.get("all_resolved") and seq and \
                seq[-1]["state"] != "resolved":
            problems.append(f"h{host}/{rule}: still firing at "
                            "scenario end")
    for rule, bounds in (expect.get("alert_episodes") or {}).items():
        lo, hi = (bounds if isinstance(bounds, (list, tuple))
                  else (bounds, bounds))
        for n in episode_counts.get(rule, []):
            if not int(lo) <= n <= int(hi):
                problems.append(f"{rule}: a host saw {n} episode(s), "
                                f"expected [{lo}, {hi}]")
                break
    for rule in expect.get("alerts_required", []):
        if rule not in fired_rules:
            problems.append(f"required alert {rule!r} never fired on "
                            "any host")
    n_eps = sum(sum(v) for v in episode_counts.values())
    return _result(
        "exactly_once_episodes", not problems,
        "; ".join(problems[:4]) or
        f"{n_eps} episode(s) across {len(by_key)} host-rule pairs, "
        "all paired")


def check_conservative(decisions: List[dict],
                       expect: dict) -> InvariantResult:
    windows = expect.get("no_decisions_during_s") or []
    bad = [d for d in decisions
           for a, b in windows if a <= d["t"] < b]
    return _result(
        "conservative_degradation", not bad,
        (f"{len(bad)} decision(s) inside degraded windows "
         f"{windows}: " + ", ".join(
             f"{d['reason']}@{d['t']:.0f}s" for d in bad[:4]))
        if bad else
        f"no decisions inside {len(windows)} degraded window(s)")


def check_scrape_budget(scrape_cycles: List[dict],
                        expect: dict) -> InvariantResult:
    budget = expect.get("max_scrape_cycle_s")
    if budget is None or not scrape_cycles:
        return _result("scrape_budget", True,
                       "no budget declared" if budget is None
                       else "no scrape cycles observed")
    worst = max(scrape_cycles, key=lambda c: c["wall_s"])
    mean = sum(c["wall_s"] for c in scrape_cycles) / len(scrape_cycles)
    ok = worst["wall_s"] <= float(budget)
    return _result(
        "scrape_budget", ok,
        f"worst cycle {worst['wall_s'] * 1000:.1f}ms "
        f"(mean {mean * 1000:.1f}ms, {len(scrape_cycles)} cycles, "
        f"budget {float(budget) * 1000:.0f}ms, worst had "
        f"{worst['down']} down peer(s))")


def check_sink(sink_failures: float, expect: dict) -> InvariantResult:
    need = expect.get("min_sink_failures")
    if need is None:
        return _result("sink_failures", True, "no sink expectation")
    ok = sink_failures >= int(need)
    return _result(
        "sink_failures", ok,
        f"{int(sink_failures)} failed sink delivery(ies) counted "
        f"(needed >= {need}) while the episode invariant held")


def check_scenario(observed: dict, expect: dict,
                   cooldown_s: float) -> List[InvariantResult]:
    """All applicable invariant checks over one scenario's observation
    bundle (the runner builds ``observed``)."""
    return [
        check_no_flap(observed["decisions"], cooldown_s, expect),
        check_convergence(observed["decisions"],
                          observed["final_world"],
                          observed["duration_s"], expect),
        check_exactly_once_episodes(observed["transitions"], expect),
        check_conservative(observed["decisions"], expect),
        check_scrape_budget(observed["scrape_cycles"], expect),
        check_sink(observed.get("sink_failures", 0.0), expect),
    ]


# --------------------------------------- serving data-plane invariants
def check_request_conservation(observed: dict,
                               expect: dict) -> InvariantResult:
    """Zero lost, zero duplicated — and every request accounted for:
    completed + shed == unique answers == requests."""
    problems = []
    lost = int(observed.get("lost", 0))
    dup = int(observed.get("duplicates", 0))
    if lost > int(expect.get("max_lost", 0)):
        problems.append(f"{lost} request(s) LOST (never answered)")
    if dup > int(expect.get("max_duplicates", 0)):
        problems.append(f"{dup} request(s) answered more than once")
    answered = observed["completed"] + observed["shed"]
    if answered + lost != observed["requests"]:
        problems.append(
            f"conservation broke: {observed['completed']} completed + "
            f"{observed['shed']} shed + {lost} lost != "
            f"{observed['requests']} requests")
    max_shed = expect.get("max_shed")
    if max_shed is not None and observed["shed"] > int(max_shed):
        problems.append(f"{observed['shed']} shed > allowed {max_shed}")
    max_late = expect.get("max_late_discarded")
    if max_late is not None and \
            observed.get("late_discarded", 0) > int(max_late):
        problems.append(f"{observed['late_discarded']} late zombie "
                        f"completion(s), allowed {max_late}")
    for key, label in (("min_handoff_replays", "handoff_replays"),
                       ("min_drains", "drains"),
                       ("min_retries", "retries")):
        need = expect.get(key)
        if need is not None and observed.get(label, 0) < int(need):
            problems.append(f"only {observed.get(label, 0)} {label}, "
                            f"scenario needs >= {need} to mean "
                            "anything")
    ledger = observed.get("ledger") or {}
    return _result(
        "request_conservation", not problems,
        "; ".join(problems) or
        f"{observed['requests']} requests -> "
        f"{observed['completed']} completed + {observed['shed']} shed, "
        f"0 lost, 0 duplicated ({observed.get('handoff_replays', 0)} "
        f"claim-gated replay(s), ledger dedup "
        f"{ledger.get('duplicates', 0)})")


def check_retry_amplification(observed: dict,
                              expect: dict) -> InvariantResult:
    """The budget's hard arithmetic (granted <= burst + ratio x
    requests) AND the end-to-end bound: backend attempts per client
    request <= 1 + ratio + slack."""
    problems = []
    b = observed["budget"]
    granted = int(b["retries_granted"])
    ceiling = float(b["burst"]) + float(b["ratio"]) * int(b["requests"])
    if granted > ceiling + 1e-9:
        problems.append(f"budget arithmetic broke: {granted} retries "
                        f"granted > burst {b['burst']:g} + "
                        f"{b['ratio']:g} x {b['requests']} requests "
                        f"= {ceiling:g}")
    slack = float(expect.get("amplification_slack", 0.05))
    bound = 1.0 + float(b["ratio"]) + slack
    amp = float(observed["amplification"])
    if amp > bound:
        problems.append(f"amplification {amp:.3f} > 1 + ratio "
                        f"{b['ratio']:g} + slack {slack:g} = "
                        f"{bound:.3f}")
    return _result(
        "retry_amplification", not problems,
        "; ".join(problems) or
        f"amplification {amp:.3f} <= {bound:.3f} "
        f"({granted} retries granted, {b['retries_denied']} denied, "
        f"ceiling {ceiling:.0f})")


def check_slo_stability(observed: dict,
                        expect: dict) -> InvariantResult:
    """The SLO-burn alert fires at most the declared number of times
    (default 1 — once for the incident) and is quiet by the end."""
    problems = []
    flaps = int(observed.get("slo_flaps", 0))
    max_flaps = int(expect.get("max_slo_flaps", 1))
    if flaps > max_flaps:
        problems.append(f"SLO-burn alert fired {flaps}x "
                        f"(allowed {max_flaps}) — flapping")
    if expect.get("slo_resolved", True) and \
            observed.get("slo_firing_at_end"):
        problems.append("SLO-burn alert still firing at scenario end")
    return _result(
        "slo_stability", not problems,
        "; ".join(problems) or
        f"{flaps} firing(s), resolved by scenario end")


def check_serve_scenario(observed: dict,
                         expect: dict) -> List[InvariantResult]:
    """All serving data-plane invariants over one scenario's
    observation bundle (:func:`bigdl_tpu.sim.serve.run_serve_scenario`
    builds ``observed``)."""
    return [
        check_request_conservation(observed, expect),
        check_retry_amplification(observed, expect),
        check_slo_stability(observed, expect),
    ]


# -------------------------------------------------- standalone probes
def check_aggregation_scaling(n_hosts: int, budget_s: float,
                              seed: int = 0, cycles: int = 3,
                              ratio_slack: float = 3.0
                              ) -> InvariantResult:
    """The real ``FleetAggregator.snapshot()`` over a fully healthy
    fleet of ``n_hosts`` must finish inside ``budget_s`` AND scale
    ~linearly: against a fleet a quarter the size, the cost ratio may
    not exceed the host ratio times ``ratio_slack`` (a quadratic
    aggregation blows this immediately)."""
    from bigdl_tpu.obs.aggregate import FleetAggregator
    from bigdl_tpu.sim.clock import VirtualClock
    from bigdl_tpu.sim.fleet import SimFleet

    def cycle_wall(n: int) -> float:
        clock = VirtualClock()
        fleet = SimFleet(n, clock, seed=seed)
        fleet.tick(1.0)
        agg = FleetAggregator(peers=fleet.addrs, fetch=fleet.fetch)
        best = float("inf")
        for _ in range(max(1, int(cycles))):
            t0 = time.perf_counter()
            snap = agg.snapshot()
            best = min(best, time.perf_counter() - t0)
            assert len(snap["hosts"]) == n, "snapshot dropped hosts"
        return best

    n_small = max(8, int(n_hosts) // 4)
    small = cycle_wall(n_small)
    full = cycle_wall(int(n_hosts))
    host_ratio = n_hosts / n_small
    grew = full / max(1e-9, small)
    ok = full <= float(budget_s) and grew <= host_ratio * ratio_slack
    return _result(
        "aggregation_scaling", ok,
        f"{n_hosts} hosts in {full * 1000:.1f}ms (budget "
        f"{budget_s * 1000:.0f}ms); vs {n_small} hosts "
        f"{small * 1000:.1f}ms -> grew {grew:.1f}x for {host_ratio:.1f}x "
        f"hosts (slack {ratio_slack:g}x)")


def check_supervisor_flap(flaps: int = 6,
                          max_retries: int = 3) -> InvariantResult:
    """A flapping child that exits the graceful-preemption way every
    time must ride the supervisor's free preemption path: zero
    transient retry budget spent, no give-up."""
    from bigdl_tpu.resilience.elastic import EXIT_PREEMPTED
    from bigdl_tpu.resilience.supervisor import Supervisor
    from bigdl_tpu.sim.clock import VirtualClock

    clock = VirtualClock()
    seen = {"launches": 0}

    def runner(cmd, env):
        seen["launches"] += 1
        clock.advance(30.0)  # the child "ran" half a virtual minute
        return EXIT_PREEMPTED if seen["launches"] <= int(flaps) else 0

    sup = Supervisor(["sim-flapping-child"], max_retries=max_retries,
                     runner=runner, sleep=clock.sleep)
    rc = sup.run()
    spent = sup.policy.attempts
    ok = rc == 0 and spent == 0 and sup.preemptions == int(flaps)
    return _result(
        "supervisor_retry_budget", ok,
        f"{flaps} flap(s) restarted free (rc {rc}, retry budget spent "
        f"{spent}/{max_retries}, preemptions {sup.preemptions}, "
        f"virtual wall {clock.now():.0f}s)")


def check_watchdog(fleet, stalled_id: int, partitioned_id: int,
                   timeout_s: float = 10.0,
                   hang_age_s: float = 60.0) -> InvariantResult:
    """The hang watchdog must flag a host whose step stamp stopped
    (positive evidence) and read an unreachable one as 'cannot tell',
    never as hung."""
    from bigdl_tpu.resilience.supervisor import HangWatchdog

    fleet.tick(1.0)  # make sure a first step stamp exists
    stalled_host = fleet.hosts[stalled_id]
    stalled_host.stalled = True
    fleet.clock.advance(hang_age_s)
    fleet.tick(0.0)
    fleet.hosts[partitioned_id].partitioned = True
    wd_stalled = HangWatchdog(timeout_s, port=9000,
                              fetch=fleet.watchdog_fetch(stalled_id))
    wd_part = HangWatchdog(timeout_s, port=9000,
                           fetch=fleet.watchdog_fetch(partitioned_id))
    saw_stall = wd_stalled.stalled()
    saw_part = wd_part.stalled()
    stalled_host.stalled = False
    fleet.hosts[partitioned_id].partitioned = False
    ok = saw_stall and not saw_part
    return _result(
        "watchdog_classification", ok,
        f"stalled host flagged={saw_stall} (age "
        f"{stalled_host.step_age_s()}s > {timeout_s:g}s), partitioned "
        f"host conservatively not-hung={not saw_part}")
