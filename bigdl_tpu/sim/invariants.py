"""Fleet-level invariants — what every chaos scenario must uphold.

A scenario run produces an observation bundle (decisions with virtual
timestamps, per-host alert transitions with episode ids, per-cycle
scrape walls, the final world, sink failure counts);
:func:`check_scenario` turns it + the scenario's ``expect`` block into
a list of :class:`InvariantResult` verdicts:

* **no_flap** — the autoscaler never issues opposite-direction
  decisions inside one cooldown window, and the decision count stays
  inside the scenario's declared bounds with every required reason
  present;
* **convergence** — the run ends at the expected world, quiet through
  the declared tail;
* **exactly_once_episodes** — per (host, rule): transitions strictly
  alternate firing → resolved, episode ids are consecutive and pair
  each resolve to its firing, per-host episode counts stay in the
  declared range, and (when declared) everything is resolved by
  scenario end.  This is the invariant that pins the alert-engine
  double-fire fix;
* **conservative_degradation** — no decision lands inside a window
  where the scenario declares signals unreliable (partitions): an
  absent signal must never breach a rule;
* **scrape_budget** — no scrape cycle's wall clock exceeded the
  declared bound (the concurrent bounded-pool scrape's contract; a
  serial scrape fails this the moment peers time out);
* **sink_failures** — a poisoned alert sink is *counted*, not wedging:
  at least the declared number of delivery failures landed while the
  episode invariant above still held;
* **bundle_per_episode** — with ``BIGDL_BUNDLE_DIR`` configured, every
  alert ``firing`` transition produced exactly ONE manifest-valid
  debug bundle (``obs/bundle.py``): none dropped, none duplicated,
  none torn.  Unconfigured runs pass with an explicit "not exercised"
  note so the scenario matrix stays runnable without a bundle dir.

Standalone probes for the properties a tick loop cannot express:

* :func:`check_aggregation_scaling` — the real
  :class:`~bigdl_tpu.obs.aggregate.FleetAggregator` snapshot cost at N
  hosts stays within a wall budget AND grows ~linearly (measured
  against a fleet a quarter the size);
* :func:`check_supervisor_flap` — the real
  :class:`~bigdl_tpu.resilience.supervisor.Supervisor` rides a
  flapping (preemption-class) child without spending ONE unit of the
  transient retry budget;
* :func:`check_watchdog` — the real :class:`~bigdl_tpu.resilience.
  supervisor.HangWatchdog` flags a genuinely stalled host and stays
  conservative on a partitioned (unreachable) one;
* :func:`check_rollup_exactness` — the two-tier leaf->root
  :class:`~bigdl_tpu.obs.rollup.RollupAggregator` merge is bit-equal
  to the flat single-tier merge (``_sum`` alone gets ulp slack) and
  derives the identical fleet p99 — the hierarchical-exactness
  invariant this PR pins;
* :func:`check_rollup_bounds` — with top-K active, no family exceeds
  ``top_k + 1`` logical series, drops are counted, the node's memory
  self-gauge tracks the bound (not N), and scrape walls stay budgeted;
* :func:`check_staleness_exclusion` — skewed-clock and partitioned
  hosts are flagged stale, excluded from fleet percentiles, and
  accounted in ``bigdl_fleet_stale_hosts``.

Serving data-plane invariants (the router chaos scenarios in
:mod:`bigdl_tpu.sim.serve` — :func:`check_serve_scenario` composes):

* **request_conservation** — every admitted request is answered
  exactly once (completed or an explicit shed): zero lost, zero
  duplicated across preemption dumps, drains, and handoff replays;
* **retry_amplification** — the shared retry budget's arithmetic
  bound holds (retries granted <= burst + ratio x requests) and
  end-to-end backend amplification stays <= 1 + ratio + slack — a
  brownout cannot turn into a retry storm;
* **slo_stability** — the backlog-driven SLO-burn alert fires at most
  the declared number of times and is resolved by scenario end:
  absorbing a preemption storm must not flap the alert.

Live-weight-rollout invariants (the ``weight_rollout`` scenario —
composed into :func:`check_serve_scenario` when the scenario's
``expect`` block declares rollout expectations):

* **rollback_exactly_once** — a bad canary triggers exactly the
  declared number of rollback episodes (hysteresis means one, not a
  promote/rollback flap), and the declared promotions all landed;
* **no_version_skew_after_settle** — after the run settles, every
  replica serves the expected incumbent version: no canary left
  behind, no half-promoted fleet;
* **corrupt_never_loaded** — a corrupt-mid-publish checkpoint is
  rejected by the verify-before-swap gate and reaches zero replicas;
* **zero_dropped_requests** — across promote, canary, rollback and
  the drain/handoff cycles they drive: zero lost, zero duplicated,
  zero shed.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List


@dataclasses.dataclass
class InvariantResult:
    """One invariant verdict (JSON-able via dataclasses.asdict)."""

    name: str
    ok: bool
    detail: str

    def __str__(self) -> str:
        return f"[{'PASS' if self.ok else 'FAIL'}] {self.name}: " \
               f"{self.detail}"


def _result(name: str, ok: bool, detail: str) -> InvariantResult:
    return InvariantResult(name, bool(ok), detail)


# ------------------------------------------------------------- checks
def check_no_flap(decisions: List[dict], cooldown_s: float,
                  expect: dict) -> InvariantResult:
    problems = []
    for prev, cur in zip(decisions, decisions[1:]):
        gap = cur["t"] - prev["t"]
        if cur["direction"] != prev["direction"] and gap < cooldown_s:
            problems.append(
                f"{prev['direction']}@{prev['t']:.0f}s then "
                f"{cur['direction']}@{cur['t']:.0f}s ({gap:.0f}s < "
                f"cooldown {cooldown_s:.0f}s)")
    lo = int(expect.get("min_decisions", 0))
    hi = expect.get("max_decisions")
    n = len(decisions)
    if n < lo:
        problems.append(f"only {n} decision(s), expected >= {lo}")
    if hi is not None and n > int(hi):
        problems.append(f"{n} decision(s), expected <= {hi}")
    reasons = [d["reason"] for d in decisions]
    for want in expect.get("reasons", []):
        if want not in reasons:
            problems.append(f"required reason {want!r} never decided "
                            f"(got {sorted(set(reasons))})")
    return _result(
        "no_flap", not problems,
        "; ".join(problems) or
        f"{n} decision(s), no up/down inside the "
        f"{cooldown_s:.0f}s cooldown")


def check_convergence(decisions: List[dict], final_world: int,
                      duration_s: float,
                      expect: dict) -> InvariantResult:
    problems = []
    fw = expect.get("final_world")
    if fw is not None:
        lo, hi = (fw if isinstance(fw, (list, tuple)) else (fw, fw))
        if not int(lo) <= int(final_world) <= int(hi):
            problems.append(f"final world {final_world} outside "
                            f"[{lo}, {hi}]")
    tail = expect.get("quiet_tail_s")
    if tail is not None:
        cutoff = duration_s - float(tail)
        late = [d for d in decisions if d["t"] >= cutoff]
        if late:
            problems.append(f"{len(late)} decision(s) inside the "
                            f"final {tail:.0f}s quiet tail")
    return _result("convergence", not problems,
                   "; ".join(problems) or f"settled at world "
                                          f"{final_world}")


def check_exactly_once_episodes(transitions: List[dict],
                                expect: dict) -> InvariantResult:
    """Per (host, rule): firing/resolved strictly alternate, episode
    ids are consecutive and pair each resolve with its firing — the
    'exactly once per episode' contract."""
    problems = []
    by_key: Dict[tuple, List[dict]] = {}
    for t in transitions:
        by_key.setdefault((t["host"], t["rule"]), []).append(t)
    fired_rules = set()
    episode_counts: Dict[str, List[int]] = {}
    for (host, rule), seq in sorted(by_key.items()):
        fired_rules.add(rule)
        episodes = 0
        expect_state = "firing"
        for t in seq:
            if t["state"] != expect_state:
                problems.append(
                    f"h{host}/{rule}: got {t['state']!r} where "
                    f"{expect_state!r} was due (episode "
                    f"{t.get('episode')})")
                break
            if t["state"] == "firing":
                episodes += 1
                if t.get("episode") != episodes:
                    problems.append(
                        f"h{host}/{rule}: firing #{episodes} carries "
                        f"episode id {t.get('episode')} — the same "
                        "episode fired twice or an id was skipped")
                    break
                expect_state = "resolved"
            else:
                if t.get("episode") != episodes:
                    problems.append(
                        f"h{host}/{rule}: resolve pairs episode "
                        f"{t.get('episode')} with firing {episodes}")
                    break
                expect_state = "firing"
        episode_counts.setdefault(rule, []).append(episodes)
        if expect.get("all_resolved") and seq and \
                seq[-1]["state"] != "resolved":
            problems.append(f"h{host}/{rule}: still firing at "
                            "scenario end")
    for rule, bounds in (expect.get("alert_episodes") or {}).items():
        lo, hi = (bounds if isinstance(bounds, (list, tuple))
                  else (bounds, bounds))
        for n in episode_counts.get(rule, []):
            if not int(lo) <= n <= int(hi):
                problems.append(f"{rule}: a host saw {n} episode(s), "
                                f"expected [{lo}, {hi}]")
                break
    for rule in expect.get("alerts_required", []):
        if rule not in fired_rules:
            problems.append(f"required alert {rule!r} never fired on "
                            "any host")
    n_eps = sum(sum(v) for v in episode_counts.values())
    return _result(
        "exactly_once_episodes", not problems,
        "; ".join(problems[:4]) or
        f"{n_eps} episode(s) across {len(by_key)} host-rule pairs, "
        "all paired")


def check_conservative(decisions: List[dict],
                       expect: dict) -> InvariantResult:
    windows = expect.get("no_decisions_during_s") or []
    bad = [d for d in decisions
           for a, b in windows if a <= d["t"] < b]
    return _result(
        "conservative_degradation", not bad,
        (f"{len(bad)} decision(s) inside degraded windows "
         f"{windows}: " + ", ".join(
             f"{d['reason']}@{d['t']:.0f}s" for d in bad[:4]))
        if bad else
        f"no decisions inside {len(windows)} degraded window(s)")


def check_scrape_budget(scrape_cycles: List[dict],
                        expect: dict) -> InvariantResult:
    budget = expect.get("max_scrape_cycle_s")
    if budget is None or not scrape_cycles:
        return _result("scrape_budget", True,
                       "no budget declared" if budget is None
                       else "no scrape cycles observed")
    worst = max(scrape_cycles, key=lambda c: c["wall_s"])
    mean = sum(c["wall_s"] for c in scrape_cycles) / len(scrape_cycles)
    ok = worst["wall_s"] <= float(budget)
    return _result(
        "scrape_budget", ok,
        f"worst cycle {worst['wall_s'] * 1000:.1f}ms "
        f"(mean {mean * 1000:.1f}ms, {len(scrape_cycles)} cycles, "
        f"budget {float(budget) * 1000:.0f}ms, worst had "
        f"{worst['down']} down peer(s))")


def check_sink(sink_failures: float, expect: dict) -> InvariantResult:
    need = expect.get("min_sink_failures")
    if need is None:
        return _result("sink_failures", True, "no sink expectation")
    ok = sink_failures >= int(need)
    return _result(
        "sink_failures", ok,
        f"{int(sink_failures)} failed sink delivery(ies) counted "
        f"(needed >= {need}) while the episode invariant held")


def check_bundles(observed: dict, expect: dict) -> InvariantResult:
    """With a bundle dir configured, the alert->bundle path produced
    exactly one manifest-valid debug bundle per firing transition."""
    if not expect.get("bundles_per_episode"):
        return _result("bundle_per_episode", True,
                       "no bundle expectation")
    if not observed.get("bundle_dir"):
        return _result(
            "bundle_per_episode", True,
            "BIGDL_BUNDLE_DIR unset — bundle plane not exercised")
    episodes = sum(1 for t in observed.get("transitions", [])
                   if t.get("state") == "firing")
    bundles = observed.get("bundles") or []
    valid = [b for b in bundles if b.get("ok")]
    torn = [b for b in bundles if not b.get("ok")]
    problems = []
    if torn:
        problems.append(
            f"{len(torn)} torn/invalid bundle(s): "
            + ", ".join(f"{b['name']} ({b.get('reason')})"
                        for b in torn[:3]))
    if len(valid) != episodes:
        problems.append(
            f"{len(valid)} manifest-valid bundle(s) for {episodes} "
            "firing transition(s) — the alert->bundle path dropped or "
            "duplicated an episode (is BIGDL_BUNDLE_RATE_LIMIT=0?)")
    return _result(
        "bundle_per_episode", not problems,
        "; ".join(problems) or
        f"{len(valid)} bundle(s), one per firing transition, all "
        "manifest-valid")


def check_scenario(observed: dict, expect: dict,
                   cooldown_s: float) -> List[InvariantResult]:
    """All applicable invariant checks over one scenario's observation
    bundle (the runner builds ``observed``)."""
    return [
        check_no_flap(observed["decisions"], cooldown_s, expect),
        check_convergence(observed["decisions"],
                          observed["final_world"],
                          observed["duration_s"], expect),
        check_exactly_once_episodes(observed["transitions"], expect),
        check_conservative(observed["decisions"], expect),
        check_scrape_budget(observed["scrape_cycles"], expect),
        check_sink(observed.get("sink_failures", 0.0), expect),
        check_bundles(observed, expect),
    ]


# --------------------------------------- serving data-plane invariants
def check_request_conservation(observed: dict,
                               expect: dict) -> InvariantResult:
    """Zero lost, zero duplicated — and every request accounted for:
    completed + shed == unique answers == requests."""
    problems = []
    lost = int(observed.get("lost", 0))
    dup = int(observed.get("duplicates", 0))
    if lost > int(expect.get("max_lost", 0)):
        problems.append(f"{lost} request(s) LOST (never answered)")
    if dup > int(expect.get("max_duplicates", 0)):
        problems.append(f"{dup} request(s) answered more than once")
    answered = observed["completed"] + observed["shed"]
    if answered + lost != observed["requests"]:
        problems.append(
            f"conservation broke: {observed['completed']} completed + "
            f"{observed['shed']} shed + {lost} lost != "
            f"{observed['requests']} requests")
    max_shed = expect.get("max_shed")
    if max_shed is not None and observed["shed"] > int(max_shed):
        problems.append(f"{observed['shed']} shed > allowed {max_shed}")
    max_late = expect.get("max_late_discarded")
    if max_late is not None and \
            observed.get("late_discarded", 0) > int(max_late):
        problems.append(f"{observed['late_discarded']} late zombie "
                        f"completion(s), allowed {max_late}")
    for key, label in (("min_handoff_replays", "handoff_replays"),
                       ("min_drains", "drains"),
                       ("min_retries", "retries")):
        need = expect.get(key)
        if need is not None and observed.get(label, 0) < int(need):
            problems.append(f"only {observed.get(label, 0)} {label}, "
                            f"scenario needs >= {need} to mean "
                            "anything")
    ledger = observed.get("ledger") or {}
    return _result(
        "request_conservation", not problems,
        "; ".join(problems) or
        f"{observed['requests']} requests -> "
        f"{observed['completed']} completed + {observed['shed']} shed, "
        f"0 lost, 0 duplicated ({observed.get('handoff_replays', 0)} "
        f"claim-gated replay(s), ledger dedup "
        f"{ledger.get('duplicates', 0)})")


def check_retry_amplification(observed: dict,
                              expect: dict) -> InvariantResult:
    """The budget's hard arithmetic (granted <= burst + ratio x
    requests) AND the end-to-end bound: backend attempts per client
    request <= 1 + ratio + slack."""
    problems = []
    b = observed["budget"]
    granted = int(b["retries_granted"])
    ceiling = float(b["burst"]) + float(b["ratio"]) * int(b["requests"])
    if granted > ceiling + 1e-9:
        problems.append(f"budget arithmetic broke: {granted} retries "
                        f"granted > burst {b['burst']:g} + "
                        f"{b['ratio']:g} x {b['requests']} requests "
                        f"= {ceiling:g}")
    slack = float(expect.get("amplification_slack", 0.05))
    bound = 1.0 + float(b["ratio"]) + slack
    amp = float(observed["amplification"])
    if amp > bound:
        problems.append(f"amplification {amp:.3f} > 1 + ratio "
                        f"{b['ratio']:g} + slack {slack:g} = "
                        f"{bound:.3f}")
    return _result(
        "retry_amplification", not problems,
        "; ".join(problems) or
        f"amplification {amp:.3f} <= {bound:.3f} "
        f"({granted} retries granted, {b['retries_denied']} denied, "
        f"ceiling {ceiling:.0f})")


def check_slo_stability(observed: dict,
                        expect: dict) -> InvariantResult:
    """The SLO-burn alert fires at most the declared number of times
    (default 1 — once for the incident) and is quiet by the end."""
    problems = []
    flaps = int(observed.get("slo_flaps", 0))
    max_flaps = int(expect.get("max_slo_flaps", 1))
    if flaps > max_flaps:
        problems.append(f"SLO-burn alert fired {flaps}x "
                        f"(allowed {max_flaps}) — flapping")
    if expect.get("slo_resolved", True) and \
            observed.get("slo_firing_at_end"):
        problems.append("SLO-burn alert still firing at scenario end")
    return _result(
        "slo_stability", not problems,
        "; ".join(problems) or
        f"{flaps} firing(s), resolved by scenario end")


def check_rollback_exactly_once(observed: dict,
                                expect: dict) -> InvariantResult:
    """A bad canary rolls back exactly the declared number of times —
    hysteresis means one decisive episode, never a promote/rollback
    flap — and the declared promotions all happened, in order."""
    problems = []
    want = int(expect.get("rollbacks", 0))
    got = int(observed.get("rollbacks", 0))
    if got != want:
        problems.append(f"{got} rollback episode(s), expected exactly "
                        f"{want}")
    promos = expect.get("promotions")
    if promos is not None and \
            list(observed.get("promotions") or []) != list(promos):
        problems.append(f"promotions {observed.get('promotions')} != "
                        f"expected {list(promos)}")
    state = observed.get("rollout_state")
    if state not in (None, "idle"):
        problems.append(f"controller still {state!r} at scenario end")
    return _result(
        "rollback_exactly_once", not problems,
        "; ".join(problems) or
        f"{got} rollback(s), promotions "
        f"{observed.get('promotions')}, controller idle")


def check_no_version_skew(observed: dict,
                          expect: dict) -> InvariantResult:
    """After the run settles every replica serves one version — the
    expected one when declared.  A canary left behind or a
    half-promoted fleet is exactly the skew the rollout tier exists to
    prevent."""
    problems = []
    versions = observed.get("versions_at_end") or {}
    distinct = sorted(set(versions.values()))
    if len(distinct) > 1:
        problems.append(f"fleet did not converge: {distinct} "
                        f"({versions})")
    settle = expect.get("settle_version")
    if settle is not None:
        skewed = sorted(n for n, v in versions.items() if v != settle)
        if skewed:
            problems.append(f"{skewed} not on expected {settle!r} "
                            f"({versions})")
    return _result(
        "no_version_skew_after_settle", not problems,
        "; ".join(problems) or
        f"all {len(versions)} replica(s) on "
        f"{distinct[0] if distinct else '?'}")


def check_corrupt_never_loaded(observed: dict,
                               expect: dict) -> InvariantResult:
    """The verify-before-swap gate held: every corrupt-mid-publish
    checkpoint was rejected, and none reached a replica."""
    problems = []
    need = int(expect.get("min_corrupt_rejected", 0))
    rejected = int(observed.get("corrupt_rejected", 0))
    if rejected < need:
        problems.append(f"only {rejected} corrupt publish(es) "
                        f"rejected, scenario injects >= {need}")
    loaded = int(observed.get("corrupt_loaded", 0))
    if loaded > 0:
        problems.append(f"{loaded} corrupt publish(es) REACHED a "
                        "replica — the verify gate is porous")
    return _result(
        "corrupt_never_loaded", not problems,
        "; ".join(problems) or
        f"{rejected} corrupt publish(es) rejected, 0 loaded")


def check_zero_dropped(observed: dict) -> InvariantResult:
    """The rollout path's hard conservation bar: promote, canary and
    rollback (with their drain/handoff cycles) drop NOTHING — zero
    lost, zero duplicated, zero shed."""
    problems = []
    for key in ("lost", "duplicates", "shed"):
        n = int(observed.get(key, 0))
        if n > 0:
            problems.append(f"{n} request(s) {key}")
    return _result(
        "zero_dropped_requests", not problems,
        "; ".join(problems) or
        f"{observed.get('requests', 0)} requests, 0 lost / 0 dup / "
        "0 shed across the rollout cycle")


def check_serve_scenario(observed: dict,
                         expect: dict) -> List[InvariantResult]:
    """All serving data-plane invariants over one scenario's
    observation bundle (:func:`bigdl_tpu.sim.serve.run_serve_scenario`
    builds ``observed``).  Rollout invariants join the list when the
    scenario declares rollout expectations."""
    out = [
        check_request_conservation(observed, expect),
        check_retry_amplification(observed, expect),
        check_slo_stability(observed, expect),
    ]
    if "rollbacks" in expect or "settle_version" in expect:
        out += [
            check_rollback_exactly_once(observed, expect),
            check_no_version_skew(observed, expect),
            check_corrupt_never_loaded(observed, expect),
            check_zero_dropped(observed),
        ]
    return out


# -------------------------------------------------- standalone probes
def check_aggregation_scaling(n_hosts: int, budget_s: float,
                              seed: int = 0, cycles: int = 3,
                              ratio_slack: float = 3.0
                              ) -> InvariantResult:
    """The real ``FleetAggregator.snapshot()`` over a fully healthy
    fleet of ``n_hosts`` must finish inside ``budget_s`` AND scale
    ~linearly: against a fleet a quarter the size, the cost ratio may
    not exceed the host ratio times ``ratio_slack`` (a quadratic
    aggregation blows this immediately)."""
    from bigdl_tpu.obs.aggregate import FleetAggregator
    from bigdl_tpu.sim.clock import VirtualClock
    from bigdl_tpu.sim.fleet import SimFleet

    def cycle_wall(n: int) -> float:
        clock = VirtualClock()
        fleet = SimFleet(n, clock, seed=seed)
        fleet.tick(1.0)
        agg = FleetAggregator(peers=fleet.addrs, fetch=fleet.fetch)
        best = float("inf")
        for _ in range(max(1, int(cycles))):
            t0 = time.perf_counter()
            snap = agg.snapshot()
            best = min(best, time.perf_counter() - t0)
            assert len(snap["hosts"]) == n, "snapshot dropped hosts"
        return best

    n_small = max(8, int(n_hosts) // 4)
    small = cycle_wall(n_small)
    full = cycle_wall(int(n_hosts))
    host_ratio = n_hosts / n_small
    grew = full / max(1e-9, small)
    ok = full <= float(budget_s) and grew <= host_ratio * ratio_slack
    return _result(
        "aggregation_scaling", ok,
        f"{n_hosts} hosts in {full * 1000:.1f}ms (budget "
        f"{budget_s * 1000:.0f}ms); vs {n_small} hosts "
        f"{small * 1000:.1f}ms -> grew {grew:.1f}x for {host_ratio:.1f}x "
        f"hosts (slack {ratio_slack:g}x)")


_ROLLUP_SELF = ("bigdl_rollup_", "bigdl_fleet_")


def _flat_merge(fleet, stale_after_s: float):
    """The single-tier reference: one flat ``FleetAggregator`` scrape
    over every host, live (ok and not stale) expositions policy-merged
    in one step.  Returns ``(merged_doc, stale_map, wall_s)``."""
    from bigdl_tpu.obs.aggregate import FleetAggregator
    from bigdl_tpu.obs.rollup import merge_parsed

    agg = FleetAggregator(peers=fleet.addrs, fetch=fleet.fetch,
                          stale_after_s=stale_after_s,
                          clock=fleet.clock.now)
    scraped = agg.scrape_peers(agg.peers)
    live = [p.get("metrics") for p in scraped
            if p.get("ok") and not p.get("stale")]
    return merge_parsed(live), dict(agg.last_stale), agg.last_scrape_s


def _comparable(doc: dict) -> dict:
    """Merged samples keyed ``(name, sorted labels)``, with the rollup
    pipeline's own self-metrics (``bigdl_rollup_*``/``bigdl_fleet_*``)
    filtered out — those exist only in the hierarchical plane."""
    out = {}
    for s in doc.get("samples") or []:
        if s["name"].startswith(_ROLLUP_SELF):
            continue
        out[(s["name"], tuple(sorted((s.get("labels") or {}).items())))] \
            = float(s["value"])
    return out


def check_rollup_exactness(n_hosts: int = 40, shard_size: int = 8,
                           seed: int = 0,
                           stale_after_s: float = 30.0
                           ) -> InvariantResult:
    """Hierarchical merge == flat merge, **bit-equal**: the two-tier
    leaf->root pipeline over the same live hosts must reproduce every
    counter, gauge, ``_bucket`` and ``_count`` sample of the flat
    single-tier merge exactly, and the fleet p99 derived from merged
    cumulative buckets must be identical.  The float ``_sum`` sample
    alone is allowed its last ulp (float addition is not associative
    across tiers; quantiles never read it)."""
    from bigdl_tpu.obs import names
    from bigdl_tpu.obs.rollup import build_tiers, fleet_quantile
    from bigdl_tpu.sim.clock import VirtualClock
    from bigdl_tpu.sim.fleet import SimFleet

    clock = VirtualClock()
    fleet = SimFleet(int(n_hosts), clock, seed=seed)
    fleet.tick(1.0)
    flat_doc, _, _ = _flat_merge(fleet, stale_after_s)
    root, leaves = build_tiers(
        fleet.addrs, fleet.fetch, shard_size=int(shard_size),
        top_k=0, stale_after_s=stale_after_s, clock=clock.now)
    hier_doc = root.refresh()

    flat, hier = _comparable(flat_doc), _comparable(hier_doc)
    problems = []
    if set(flat) != set(hier):
        only_flat = sorted(set(flat) - set(hier))[:3]
        only_hier = sorted(set(hier) - set(flat))[:3]
        problems.append(f"series sets differ: flat-only {only_flat}, "
                        f"hier-only {only_hier}")
    mismatched = 0
    for key in sorted(set(flat) & set(hier)):
        a, b = flat[key], hier[key]
        if key[0].endswith("_sum"):
            if abs(a - b) > 1e-9 * max(1.0, abs(a)):
                mismatched += 1
                problems.append(f"{key[0]}{dict(key[1])}: flat {a!r} "
                                f"vs hier {b!r} beyond _sum ulp slack")
        elif a != b:
            mismatched += 1
            problems.append(f"{key[0]}{dict(key[1])}: flat {a!r} != "
                            f"hier {b!r} (bit-equality required)")
        if mismatched >= 3:
            break
    p99_flat = fleet_quantile(flat_doc, names.REQUEST_LATENCY_SECONDS,
                              0.99, kind="e2e")
    p99_hier = fleet_quantile(hier_doc, names.REQUEST_LATENCY_SECONDS,
                              0.99, kind="e2e")
    if p99_flat is None or p99_flat != p99_hier:
        problems.append(f"fleet p99 diverged: flat {p99_flat} vs "
                        f"hier {p99_hier}")
    return _result(
        "rollup_exactness", not problems,
        "; ".join(problems[:4]) or
        f"{len(flat)} series bit-equal across {len(leaves)} leaf "
        f"shard(s) of {shard_size} (fleet p99 {p99_flat}s both ways)")


def check_rollup_bounds(n_hosts: int = 64, shard_size: int = 8,
                        top_k: int = 8, budget_s: float = 30.0,
                        seed: int = 0) -> InvariantResult:
    """The cardinality bound holds under load: with ``top_k`` active,
    no family in the root merge tracks more than ``top_k + 1`` logical
    series (the +1 is the ``other`` fold bucket), every drop is counted
    in ``bigdl_rollup_series_dropped_total``, the node's self-scraped
    memory estimate stays proportional to the bound (not to N hosts),
    and the scrape wall stays inside ``budget_s``."""
    from bigdl_tpu.obs import names
    from bigdl_tpu.obs.metrics import _base_family, parse_prometheus
    from bigdl_tpu.obs.rollup import build_tiers
    from bigdl_tpu.sim.clock import VirtualClock
    from bigdl_tpu.sim.fleet import SimFleet

    clock = VirtualClock()
    fleet = SimFleet(int(n_hosts), clock, seed=seed)
    fleet.tick(1.0)
    root, leaves = build_tiers(
        fleet.addrs, fleet.fetch, shard_size=int(shard_size),
        top_k=int(top_k), clock=clock.now)
    merged = root.refresh()

    problems = []
    families = merged.get("families") or {}
    per_family: Dict[str, set] = {}
    for s in merged.get("samples") or []:
        base = _base_family(s["name"], families)
        skey = tuple(sorted((k, v) for k, v in
                            (s.get("labels") or {}).items() if k != "le"))
        per_family.setdefault(base, set()).add(skey)
    worst_fam, worst_n = "", 0
    for fam, series in per_family.items():
        if len(series) > worst_n:
            worst_fam, worst_n = fam, len(series)
        if len(series) > int(top_k) + 1:
            problems.append(f"{fam} tracks {len(series)} logical "
                            f"series > top_k {top_k} + other")
    self_doc = parse_prometheus(root.registry.to_prometheus())
    by_name: Dict[str, float] = {}
    for s in self_doc["samples"]:
        by_name[s["name"]] = by_name.get(s["name"], 0.0) + s["value"]
    dropped = by_name.get(names.ROLLUP_SERIES_DROPPED_TOTAL, 0.0)
    leaf_dropped = sum(
        v for leaf in leaves
        for s in parse_prometheus(leaf.registry.to_prometheus())["samples"]
        if s["name"] == names.ROLLUP_SERIES_DROPPED_TOTAL
        for v in [s["value"]])
    if int(n_hosts) > int(top_k) and dropped + leaf_dropped <= 0:
        problems.append("per-host cardinality exceeded top_k but "
                        "bigdl_rollup_series_dropped_total never moved")
    tracked = by_name.get(names.ROLLUP_SERIES_TRACKED)
    if tracked != len(merged["samples"]):
        problems.append(f"self-scrape tracked {tracked} != merged "
                        f"{len(merged['samples'])} samples")
    mem = by_name.get(names.ROLLUP_MEMORY_BYTES, 0.0)
    mem_cap = 512.0 * max(1, len(merged["samples"]))
    if not 0 < mem <= mem_cap:
        problems.append(f"memory self-gauge {mem:.0f}B outside "
                        f"(0, {mem_cap:.0f}B]")
    walls = [leaf.last_scrape_s or 0.0 for leaf in leaves] + \
        [root.last_scrape_s or 0.0]
    if max(walls) > float(budget_s):
        problems.append(f"scrape wall {max(walls):.2f}s > budget "
                        f"{budget_s:g}s")
    return _result(
        "rollup_bounds", not problems,
        "; ".join(problems[:4]) or
        f"{n_hosts} hosts -> {len(merged['samples'])} tracked samples "
        f"(worst family {worst_fam} at {worst_n} <= top_k {top_k}+1, "
        f"{int(dropped + leaf_dropped)} drop(s) counted, "
        f"mem {mem:.0f}B, worst wall {max(walls) * 1000:.1f}ms)")


def check_staleness_exclusion(n_hosts: int = 16, skew_id: int = 3,
                              partition_id: int = 5, seed: int = 0,
                              stale_after_s: float = 30.0
                              ) -> InvariantResult:
    """A skewed-clock host and a partitioned host are flagged stale,
    **excluded** from the merge (their series never fold into fleet
    percentiles) and **accounted** (the stale map and the
    ``bigdl_fleet_stale_hosts`` gauge both carry them), while the fleet
    p99 still derives from the live remainder."""
    from bigdl_tpu.obs import names
    from bigdl_tpu.obs.metrics import parse_prometheus
    from bigdl_tpu.obs.rollup import build_tiers, fleet_quantile
    from bigdl_tpu.sim.clock import VirtualClock
    from bigdl_tpu.sim.fleet import SimFleet

    clock = VirtualClock()
    fleet = SimFleet(int(n_hosts), clock, seed=seed)
    fleet.tick(1.0)
    fleet.skew_clock(skew_id, 10.0 * float(stale_after_s))
    fleet.partition(partition_id)
    skew_addr = f"sim{int(skew_id)}:9000"
    part_addr = f"sim{int(partition_id)}:9000"

    flat_doc, stale, _ = _flat_merge(fleet, stale_after_s)
    root, leaves = build_tiers(
        fleet.addrs, fleet.fetch, top_k=0,
        stale_after_s=stale_after_s, clock=clock.now)
    root.refresh()
    fleet.partition(partition_id, on=False)

    problems = []
    if "skew" not in str(stale.get(skew_addr, "")):
        problems.append(f"skewed host {skew_addr} not flagged stale "
                        f"(stale map: {stale})")
    if part_addr not in stale:
        problems.append(f"partitioned host {part_addr} not flagged "
                        f"stale (stale map: {stale})")
    leaf_stale = {}
    for leaf in leaves:
        leaf_stale.update(leaf.stale)
    for addr in (skew_addr, part_addr):
        if addr not in leaf_stale:
            problems.append(f"hierarchical tier missed stale {addr}")
    # exclusion: the skewed host's per-host series must not appear
    host_key = str(int(skew_id))
    leaked = [s for s in flat_doc.get("samples") or []
              if (s.get("labels") or {}).get("host") == host_key]
    if leaked:
        problems.append(f"{len(leaked)} series from stale {skew_addr} "
                        "leaked into the merge")
    # accounting: the gauge on the root node carries the leaf counts
    gauge = sum(
        s["value"]
        for leaf in leaves
        for s in parse_prometheus(leaf.registry.to_prometheus())["samples"]
        if s["name"] == names.FLEET_STALE_HOSTS)
    if int(gauge) != len(leaf_stale):
        problems.append(f"bigdl_fleet_stale_hosts sums to {gauge:g}, "
                        f"stale map has {len(leaf_stale)}")
    p99 = fleet_quantile(flat_doc, names.REQUEST_LATENCY_SECONDS,
                         0.99, kind="e2e")
    if p99 is None:
        problems.append("fleet p99 vanished — live remainder lost")
    return _result(
        "staleness_exclusion", not problems,
        "; ".join(problems[:4]) or
        f"{len(stale)}/{n_hosts} host(s) stale "
        f"({', '.join(sorted(stale))}), excluded and accounted; fleet "
        f"p99 {p99}s from the {n_hosts - len(stale)} live host(s)")


def check_supervisor_flap(flaps: int = 6,
                          max_retries: int = 3) -> InvariantResult:
    """A flapping child that exits the graceful-preemption way every
    time must ride the supervisor's free preemption path: zero
    transient retry budget spent, no give-up."""
    from bigdl_tpu.resilience.elastic import EXIT_PREEMPTED
    from bigdl_tpu.resilience.supervisor import Supervisor
    from bigdl_tpu.sim.clock import VirtualClock

    clock = VirtualClock()
    seen = {"launches": 0}

    def runner(cmd, env):
        seen["launches"] += 1
        clock.advance(30.0)  # the child "ran" half a virtual minute
        return EXIT_PREEMPTED if seen["launches"] <= int(flaps) else 0

    sup = Supervisor(["sim-flapping-child"], max_retries=max_retries,
                     runner=runner, sleep=clock.sleep)
    rc = sup.run()
    spent = sup.policy.attempts
    ok = rc == 0 and spent == 0 and sup.preemptions == int(flaps)
    return _result(
        "supervisor_retry_budget", ok,
        f"{flaps} flap(s) restarted free (rc {rc}, retry budget spent "
        f"{spent}/{max_retries}, preemptions {sup.preemptions}, "
        f"virtual wall {clock.now():.0f}s)")


def check_watchdog(fleet, stalled_id: int, partitioned_id: int,
                   timeout_s: float = 10.0,
                   hang_age_s: float = 60.0) -> InvariantResult:
    """The hang watchdog must flag a host whose step stamp stopped
    (positive evidence) and read an unreachable one as 'cannot tell',
    never as hung."""
    from bigdl_tpu.resilience.supervisor import HangWatchdog

    fleet.tick(1.0)  # make sure a first step stamp exists
    stalled_host = fleet.hosts[stalled_id]
    stalled_host.stalled = True
    fleet.clock.advance(hang_age_s)
    fleet.tick(0.0)
    fleet.hosts[partitioned_id].partitioned = True
    wd_stalled = HangWatchdog(timeout_s, port=9000,
                              fetch=fleet.watchdog_fetch(stalled_id))
    wd_part = HangWatchdog(timeout_s, port=9000,
                           fetch=fleet.watchdog_fetch(partitioned_id))
    saw_stall = wd_stalled.stalled()
    saw_part = wd_part.stalled()
    stalled_host.stalled = False
    fleet.hosts[partitioned_id].partitioned = False
    ok = saw_stall and not saw_part
    return _result(
        "watchdog_classification", ok,
        f"stalled host flagged={saw_stall} (age "
        f"{stalled_host.step_age_s()}s > {timeout_s:g}s), partitioned "
        f"host conservatively not-hung={not saw_part}")
