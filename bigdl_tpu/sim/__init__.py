"""bigdl_tpu.sim — fleet-scale control-plane simulator.

Every operational policy in the tree — autoscaling bands and
hysteresis, alert/SLO burn-rate rules, the hang watchdog, fleet
aggregation, straggler detection, serving p99 signals — exists for
fleets of hundreds of hosts, yet has only ever executed against 1–2
real processes.  This package validates the control plane at the scale
it will face without ever owning a pod: hundreds of **synthetic hosts
in one process**, each an in-memory ``/metrics`` + ``/healthz``
endpoint speaking the exact contract the real scrapers consume, driven
by deterministic chaos scenarios on a **virtual clock**, with the
REAL policy objects in the loop:

* :mod:`bigdl_tpu.sim.clock` — the virtual clock every policy object
  is pointed at (``AutoscaleController(clock=...)``,
  ``AlertEngine(clock=...)``): a scenario hour costs microseconds;
* :mod:`bigdl_tpu.sim.host` — :class:`~bigdl_tpu.sim.host.SimHost`:
  one synthetic host — a real :class:`~bigdl_tpu.obs.metrics.
  MetricsRegistry` publishing the production gauge/histogram families
  and a ``/healthz`` payload with the exact keys
  ``obs/server.health_payload`` serves, plus its own REAL
  :class:`~bigdl_tpu.obs.alerts.AlertEngine` (the per-host topology
  production runs);
* :mod:`bigdl_tpu.sim.fleet` — :class:`~bigdl_tpu.sim.fleet.SimFleet`:
  the fetch router that stands in for HTTP — healthy hosts answer,
  partitioned hosts *time out* (costing real wall time, like a real
  partition), down hosts refuse;
* :mod:`bigdl_tpu.sim.scenario` — declarative, loudly-validated chaos
  scenarios: diurnal traffic waves, correlated stragglers, cascading
  preemptions, network partitions, flapping hosts, latency waves, a
  poisoned alert sink;
* :mod:`bigdl_tpu.sim.invariants` — the fleet-level properties every
  scenario must uphold: the autoscaler converges without flapping,
  alerts fire and resolve exactly once per episode, aggregation stays
  O(hosts) inside a wall-clock budget, scrape failures degrade
  conservatively, the supervisor never spends retry budget on a
  flapping (preemption-class) child;
* :mod:`bigdl_tpu.sim.runner` — the tick loop wiring all of it to the
  real :class:`~bigdl_tpu.resilience.autoscale.AutoscaleController`,
  :class:`~bigdl_tpu.resilience.autoscale.EndpointScraper` and
  :class:`~bigdl_tpu.obs.aggregate.FleetAggregator`.

``scripts/fleet_sim.py`` (``scripts/run-tests.sh --fleet``) runs the
scenario matrix at 200 hosts and banks ``FLEET_SIM.json`` for BENCH
``extras.fleet``; every future policy PR regresses against it.
Knobs: ``BIGDL_FLEET_HOSTS`` / ``BIGDL_FLEET_SCENARIO`` /
``BIGDL_FLEET_TIME_COMPRESSION`` / ``BIGDL_FLEET_SEED``
(``config.fleet``).
"""

from bigdl_tpu.sim.clock import VirtualClock
from bigdl_tpu.sim.fleet import SimFleet
from bigdl_tpu.sim.host import SimHost
from bigdl_tpu.sim.invariants import InvariantResult
from bigdl_tpu.sim.runner import ScenarioResult, run_scenario
from bigdl_tpu.sim.scenario import (
    BUILTIN_SCENARIOS,
    Scenario,
    load_scenario,
)
from bigdl_tpu.sim.serve import (
    SERVE_SCENARIOS,
    ServeScenario,
    ServeScenarioResult,
    SimServeReplica,
    load_serve_scenario,
    run_serve_scenario,
)

__all__ = [
    "VirtualClock", "SimHost", "SimFleet", "Scenario",
    "BUILTIN_SCENARIOS", "load_scenario", "InvariantResult",
    "ScenarioResult", "run_scenario",
    "SERVE_SCENARIOS", "ServeScenario", "ServeScenarioResult",
    "SimServeReplica", "load_serve_scenario", "run_serve_scenario",
]
