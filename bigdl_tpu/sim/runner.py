"""The simulation loop — real control plane, synthetic fleet.

One :func:`run_scenario` call wires the REAL policy objects together
exactly as the supervisor does in production — an
:class:`~bigdl_tpu.resilience.autoscale.EndpointScraper` (riding the
real :class:`~bigdl_tpu.obs.aggregate.FleetAggregator` bounded-pool
concurrent scrape) feeding
:func:`~bigdl_tpu.resilience.autoscale.derive_signals` inside a real
:class:`~bigdl_tpu.resilience.autoscale.AutoscaleController`, with a
real per-host :class:`~bigdl_tpu.obs.alerts.AlertEngine` on every
synthetic host — then drives them tick by tick through a chaos
scenario on the virtual clock:

1. the scenario mutates the fleet to its state at virtual ``t``
   (partitions, preemptions, waves, stragglers);
2. hosts advance their step counters and republish their gauges;
3. every host's alert engine evaluates (transitions collected with
   their episode ids);
4. the controller ticks — a non-dry-run decision is "executed" the way
   the supervisor would (``commit`` + ``on_launch``: new world, fresh
   warmup, cleared stamp memory) and recorded with its virtual
   timestamp;
5. the virtual clock advances one tick.

Decisions the controller makes are *fed back*: the traffic model
divides offered load by the committed world, so convergence claims are
about a closed loop, not an open-loop script.  After the run the
invariant checker (:mod:`bigdl_tpu.sim.invariants`) turns the
observation bundle into per-scenario verdicts, and a
``fleet.scenario`` trace event banks them for ``obs/report.py``'s
fleet section.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
import time
from typing import List, Optional

from bigdl_tpu.obs import names
from bigdl_tpu.sim.clock import VirtualClock
from bigdl_tpu.sim.fleet import SimFleet
from bigdl_tpu.sim.invariants import (
    InvariantResult,
    check_scenario,
    check_supervisor_flap,
    check_watchdog,
)
from bigdl_tpu.sim.scenario import Scenario, load_scenario

# a path whose directory never exists: every sink append fails —
# the "poisoned alert sink" failure mode, counted not wedging
_POISONED_SINK = os.path.join(
    tempfile.gettempdir(),
    f"bigdl-sim-poisoned-sink-{os.getpid()}-does-not-exist",
    "sink.jsonl")


@dataclasses.dataclass
class ScenarioResult:
    """One scenario's outcome: observations + invariant verdicts."""

    name: str
    ok: bool
    hosts: int
    ticks: int
    duration_s: float
    wall_s: float
    final_world: int
    decisions: List[dict]
    transitions: int
    episodes: int
    sink_failures: int
    scrape_worst_s: Optional[float]
    scrape_mean_s: Optional[float]
    invariants: List[InvariantResult]
    #: debug bundles written during the run (0 when BIGDL_BUNDLE_DIR
    #: is unset — the bundle invariant then reports "not exercised")
    bundles: int = 0

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["invariants"] = [dataclasses.asdict(r)
                           for r in self.invariants]
        return d

    def summary(self) -> str:
        inv = ", ".join(f"{r.name}={'ok' if r.ok else 'FAIL'}"
                        for r in self.invariants)
        return (f"scenario {self.name}: "
                f"{'PASS' if self.ok else 'FAIL'} "
                f"({self.hosts} hosts, {self.ticks} ticks, "
                f"{self.wall_s:.1f}s wall, world->{self.final_world}, "
                f"{len(self.decisions)} decision(s), "
                f"{self.episodes} episode(s)) [{inv}]")


class _RecordingScraper:
    """Wraps the real scraper to record per-cycle wall/ok/down."""

    def __init__(self, scraper, clock):
        self._scraper = scraper
        self._clock = clock
        self.cycles: List[dict] = []

    def __call__(self):
        t0 = time.perf_counter()
        scraped = self._scraper()
        ok = sum(1 for p in scraped if p.get("ok"))
        self.cycles.append({
            "t": self._clock.now(),
            "wall_s": time.perf_counter() - t0,
            "ok": ok, "down": len(scraped) - ok})
        return scraped


def _sink_failures_total() -> float:
    """Failed sink deliveries so far — the engine counts them on the
    PROCESS registry (``alerts._count_sink_failure``), so the runner
    measures the per-scenario delta of this."""
    from bigdl_tpu import obs

    for fam in obs.get_registry().families():
        if fam.name == names.ALERT_SINK_FAILURES_TOTAL:
            return sum(child.value for _k, child in fam.child_items())
    return 0.0


def run_scenario(spec, hosts: Optional[int] = None,
                 seed: Optional[int] = None,
                 time_compression: Optional[float] = None,
                 partition_stall_s: float = 0.02,
                 scrape_timeout_s: float = 0.25,
                 extra_probes: bool = True) -> ScenarioResult:
    """Run one scenario end to end and check its invariants.

    ``hosts`` / ``seed`` / ``time_compression`` default from
    ``config.fleet`` (the ``BIGDL_FLEET_*`` knobs).  When
    ``extra_probes`` is on, scenarios containing flap events also run
    the supervisor retry-budget and watchdog-classification probes."""
    from bigdl_tpu.config import refresh_from_env
    from bigdl_tpu.obs import alerts as alerts_mod
    from bigdl_tpu.config import AutoscaleConfig
    from bigdl_tpu.resilience.autoscale import (
        AutoscaleController,
        EndpointScraper,
    )

    from bigdl_tpu.obs import bundle as bundle_mod

    fcfg = refresh_from_env().fleet
    # debug bundles: snapshot the inventory before the run so the
    # bundle invariant judges only THIS scenario's alert->bundle output
    bundle_dir = refresh_from_env().obs.bundle_dir
    pre_bundles = ({b["name"] for b in bundle_mod.inventory(bundle_dir)}
                   if bundle_dir else set())
    n_hosts = int(hosts) if hosts else int(fcfg.hosts)
    seed = int(fcfg.seed) if seed is None else int(seed)
    compression = (float(fcfg.time_compression)
                   if time_compression is None
                   else float(time_compression))

    sc: Scenario = load_scenario(spec, hosts=n_hosts, seed=seed,
                                 time_compression=compression)
    clock = VirtualClock()
    rules = (alerts_mod.load_rules(json.dumps(sc.alert_rules))
             if sc.alert_rules else None)
    fleet = SimFleet(n_hosts, clock, seed=seed, alert_rules=rules,
                     partition_stall_s=partition_stall_s)
    scraper = _RecordingScraper(
        EndpointScraper(peers=fleet.addrs, fetch=fleet.fetch,
                        timeout_s=scrape_timeout_s), clock)
    cfg = AutoscaleConfig(enabled=True, **sc.autoscale)
    controller = AutoscaleController(cfg=cfg, world=sc.start_world,
                                     scrape=scraper, clock=clock)

    decisions: List[dict] = []
    poisoned = False
    sink_failures0 = _sink_failures_total()
    t_wall0 = time.perf_counter()
    for _ in range(sc.n_ticks()):
        t = clock.now()
        sc.apply(fleet, t, controller.world)
        if not poisoned and sc.sink_poisoned(t):
            poisoned = True
            for h in fleet.hosts:
                if h.engine is not None:
                    h.engine.sink = _POISONED_SINK
        fleet.tick(sc.tick_s)
        fleet.evaluate_alerts()
        decision = controller.tick()
        if decision is not None and not decision.dry_run:
            # execute the way the supervisor would: adopt the world,
            # restart the warmup clock, drop the stamp memory
            controller.commit(decision)
            controller.on_launch()
            decisions.append({
                "t": t, "direction": decision.direction,
                "reason": decision.reason,
                "old_world": decision.old_world,
                "new_world": decision.new_world,
                "signals": decision.signals})
        clock.advance(sc.tick_s)
    wall_s = time.perf_counter() - t_wall0

    transitions = fleet.transitions
    new_bundles = ([b for b in bundle_mod.inventory(bundle_dir)
                    if b["name"] not in pre_bundles]
                   if bundle_dir else [])
    observed = {
        "decisions": decisions,
        "transitions": transitions,
        "scrape_cycles": scraper.cycles,
        "final_world": controller.world,
        "duration_s": sc.duration_s,
        "sink_failures": _sink_failures_total() - sink_failures0,
        "bundle_dir": bundle_dir,
        "bundles": new_bundles,
    }
    invariants = check_scenario(observed, sc.expect, cfg.cooldown_s)
    if extra_probes and any(ev["kind"] == "flap" for ev in sc.events):
        invariants.append(check_supervisor_flap())
        if n_hosts >= 2:
            invariants.append(check_watchdog(fleet, 0, 1))

    episodes = sum(1 for t in transitions if t["state"] == "firing")
    cycles = scraper.cycles
    result = ScenarioResult(
        name=sc.name,
        ok=all(r.ok for r in invariants),
        hosts=n_hosts,
        ticks=sc.n_ticks(),
        duration_s=sc.duration_s,
        wall_s=round(wall_s, 3),
        final_world=controller.world,
        decisions=decisions,
        transitions=len(transitions),
        episodes=episodes,
        sink_failures=int(observed["sink_failures"]),
        scrape_worst_s=(round(max(c["wall_s"] for c in cycles), 6)
                        if cycles else None),
        scrape_mean_s=(round(sum(c["wall_s"] for c in cycles)
                             / len(cycles), 6) if cycles else None),
        invariants=invariants,
        bundles=len(new_bundles),
    )
    from bigdl_tpu import obs

    obs.get_tracer().event(
        "fleet.scenario", scenario=result.name, ok=result.ok,
        hosts=result.hosts, ticks=result.ticks,
        wall_s=result.wall_s, final_world=result.final_world,
        decisions=len(result.decisions), episodes=result.episodes,
        sink_failures=result.sink_failures, bundles=result.bundles,
        scrape_worst_s=result.scrape_worst_s,
        invariants={r.name: r.ok for r in result.invariants})
    return result
