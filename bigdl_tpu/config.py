"""Unified configuration (VERDICT r2 missing #5; SURVEY.md §5 "Config").

The reference spreads configuration across three tiers — a required
Spark-conf file (⟦dist/conf/spark-bigdl.conf⟧), ``bigdl.*`` JVM system
properties (bigdl.engineType, bigdl.coreNumber, bigdl.check.singleton,
…), and per-app scopt CLIs — with *no unified typed object*.  SURVEY §5
prescribes the rebuild use "one dataclass-based config + absl-style
flags; keep bigdl.* spellings as env aliases only where examples need
them".

This is that object.  One process-global :class:`BigDLConfig`, resolved
once from (highest wins): explicit ``configure(...)`` calls → ``BIGDL_*``
environment variables → dataclass defaults.  Every ``BIGDL_*`` env var
the framework honours is declared here — subsystems read the config
object, not ``os.environ`` — so ``python -c "import bigdl_tpu;
print(bigdl_tpu.config.describe())"`` is the single source of truth.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


def _env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    return default if v is None else int(v)


def _env_opt_int(name: str, default=None):
    v = os.environ.get(name)
    return default if v in (None, "") else int(v)


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    return default if v is None else float(v)


def _env_str(name: str, default):
    return os.environ.get(name, default)


#: Bootstrap variables the smoke harnesses export for their child
#: processes (repo path, scratch dir, A/B arm).  They are process
#: plumbing, not framework configuration, so they are deliberately NOT
#: config fields — but they are declared here so graftlint rule RD001
#: can tell a known harness contract from an ad-hoc env spelling.
#: Scripts may read them; library code may not.
HARNESS_ENV = ("BIGDL_REPO", "BIGDL_SMOKE_DIR", "BIGDL_SMOKE_BASELINE")


@dataclasses.dataclass
class ObsConfig:
    """Observability layer switches (``bigdl_tpu/obs``).

    Everything is off by default: the train loop takes a no-op fast
    path (shared null context managers, no per-step host-device sync).
    Setting ``trace_dir`` or ``metrics_dir`` implies ``enabled``.
    """

    # master switch for runtime stats (step-time reservoirs, compile
    # tracking) without any file output [BIGDL_OBS]
    enabled: bool = False
    # Chrome trace_event JSON (Perfetto-viewable) + JSONL structured
    # events are written here [BIGDL_TRACE_DIR]
    trace_dir: Optional[str] = None
    # Prometheus text exposition + JSONL metric snapshots are written
    # here (falls back to trace_dir when unset) [BIGDL_METRICS_DIR]
    metrics_dir: Optional[str] = None
    # step-time / dispatch-time reservoir capacity [BIGDL_OBS_RESERVOIR]
    reservoir_size: int = 4096
    # slow-step anomaly detector: a step slower than
    # median * slow_step_factor emits a structured `slow_step` trace
    # event with its child-span breakdown; <= 0 disables
    # [BIGDL_SLOW_STEP_FACTOR]
    slow_step_factor: float = 3.0
    # flight recorder: how many recent span/event records the tracer
    # retains in memory for postmortem bundles [BIGDL_FLIGHT_SPANS]
    flight_spans: int = 512
    # perf-regression gate: fail when the fresh step time exceeds the
    # trajectory's best by this factor (obs/regress.py)
    # [BIGDL_REGRESS_TOLERANCE]
    regress_tolerance: float = 1.5
    # training-health telemetry (obs/health.py): fetch the per-layer
    # grad/param/update-norm array from the device once every N steps;
    # 0 disables — the train step then compiles WITHOUT the health
    # output (identical signature to a pre-health build, zero extra
    # per-step host transfers) [BIGDL_HEALTH_EVERY]
    health_every: int = 0
    # rolling window for the numerics anomaly detector (loss / global
    # grad-norm spike vs rolling median) [BIGDL_HEALTH_WINDOW]
    health_window: int = 64
    # a loss or grad norm above median * this factor is an anomaly;
    # <= 0 disables the detector [BIGDL_HEALTH_SPIKE_FACTOR]
    health_spike_factor: float = 10.0
    # goodput ledger (obs/goodput.py): the bottleneck classifier runs
    # once every N productive steps; <= 0 disables the windowed
    # classifier (the ledger still records) [BIGDL_GOODPUT_WINDOW]
    goodput_window: int = 32
    # assumed interconnect bandwidth in GB/s for the comm-seconds
    # estimate (static wire bytes / bandwidth); 0 = unknown, the
    # classifier then never reports comm_bound [BIGDL_WIRE_GBPS]
    wire_gbps: float = 0.0
    # cross-host straggler detection (obs/aggregate.py): a host whose
    # step-time p50 exceeds the cross-host median by this factor is
    # flagged; <= 1 disables [BIGDL_STRAGGLER_FACTOR]
    straggler_factor: float = 1.5
    # live telemetry plane (obs/server.py): per-host HTTP endpoint
    # serving /metrics (Prometheus exposition), /healthz (JSON
    # liveness) and /trace?last=K (flight-recorder tail) on a daemon
    # thread.  0 = ephemeral port (tests), unset = off — no thread, no
    # socket, zero overhead [BIGDL_OBS_PORT]
    obs_port: Optional[int] = None
    # the server writes its actually-bound port here (atomic replace)
    # so a supervisor can find an ephemeral (port-0) child endpoint
    # [BIGDL_OBS_PORT_FILE]
    obs_port_file: Optional[str] = None
    # comma-separated host:port peer endpoints scraped into one live
    # fleet snapshot (obs/aggregate.FleetAggregator, report --watch)
    # [BIGDL_OBS_PEERS]
    obs_peers: Optional[str] = None
    # alert rule pack (obs/alerts.py): inline JSON list or a path to a
    # JSON file; unset = the default rule pack [BIGDL_ALERT_RULES]
    alert_rules: Optional[str] = None
    # alert sink: firing/resolved transitions append to this JSONL
    # file, or POST to it when it is an http(s):// webhook
    # [BIGDL_ALERT_SINK]
    alert_sink: Optional[str] = None
    # per-attempt connect/read timeout for the webhook sink POST (one
    # retry on failure; a dead sink costs at most 2x this per
    # transition and can never wedge the goodput window tick)
    # [BIGDL_ALERT_SINK_TIMEOUT]
    alert_sink_timeout: float = 1.0
    # request-scoped distributed tracing for the serving data plane
    # (obs/reqtrace.py): tail-sampling probability in [0, 1] for clean
    # requests — errored / retried / preempted / handed-off /
    # SLO-violating requests are always kept.  0 (the default)
    # disables the subsystem entirely: no contexts, no span buffering,
    # zero work on the decode hot path [BIGDL_REQTRACE_SAMPLE]
    reqtrace_sample: float = 0.0
    # bounded ring of kept completed request traces held in memory for
    # /trace?request=<id> lookups and postmortems
    # [BIGDL_REQTRACE_RING]
    reqtrace_ring: int = 256
    # strict metric registry: reject any bigdl_* metric registration
    # not declared in obs/names.py (or whose kind/labels disagree) and
    # enforce each family's label-cardinality ceiling.  CI and the
    # smokes run with this on; production defaults off so a hotfixed
    # counter can never crash a serving fleet [BIGDL_OBS_STRICT]
    strict: bool = False

    # ---- fleet-scale metrics pipeline (obs/rollup.py, obs/retain.py)
    # report --watch host table cap: render only the worst-K hosts by
    # gating signal (queue depth / step age / status), with a trailing
    # "... and N more hosts" line [BIGDL_WATCH_HOSTS]
    watch_hosts: int = 16
    # hosts per leaf RollupAggregator when assembling a tiered
    # pipeline (rollup.build_tiers); ~sqrt(fleet) keeps root and leaf
    # fan-in balanced [BIGDL_ROLLUP_SHARD]
    rollup_shard: int = 32
    # per-family label-cardinality bound on a rollup's merged
    # exposition: keep the top-K series by value, fold the rest into
    # an 'other' bucket (counted in
    # bigdl_rollup_series_dropped_total); <= 0 disables the bound
    # [BIGDL_ROLLUP_TOP_K]
    rollup_top_k: int = 64
    # staleness threshold: an ok peer whose /healthz clock skews from
    # the scraper's clock by more than this is excluded from fleet
    # merges and accounted in bigdl_fleet_stale_hosts; <= 0 disables
    # skew-based staleness [BIGDL_STALE_AFTER_S]
    stale_after_s: float = 30.0
    # retention store (obs/retain.py): points kept per downsampling
    # ring (raw / 10s / 1m) per series [BIGDL_RETAIN_POINTS]
    retain_points: int = 240
    # retention store hard series budget: past it, new series are
    # rejected (memory stays fixed) [BIGDL_RETAIN_SERIES]
    retain_series: int = 512

    # ---- continuous profiling + debug bundles (obs/prof.py, bundle.py)
    # always-on sampling profiler: samples/sec for the daemon thread
    # walking sys._current_frames(); <= 0 (the default) disables — no
    # thread, no clock reads, the off path is one config read
    # [BIGDL_PROF_HZ]
    prof_hz: float = 0.0
    # profiler self-overhead budget as a fraction of wall time; when
    # the cumulative sampling-work ratio exceeds this, samples are
    # SKIPPED (and counted) until the ratio recovers — the hard cap
    # behind bigdl_prof_overhead_ratio [BIGDL_PROF_BUDGET]
    prof_budget: float = 0.01
    # black-box debug bundles (obs/bundle.py) are written under this
    # directory on alert firings / supervisor restarts / GET /debugz;
    # unset disables every automatic trigger [BIGDL_BUNDLE_DIR]
    bundle_dir: Optional[str] = None
    # minimum seconds between two alert-triggered bundles for the SAME
    # rule (an alert storm must not fill the disk); 0 disables the
    # limit — every episode bundles [BIGDL_BUNDLE_RATE_LIMIT]
    bundle_rate_limit: float = 300.0

    @property
    def active(self) -> bool:
        return bool(self.enabled or self.trace_dir or self.metrics_dir
                    or self.obs_port is not None)

    @classmethod
    def from_env(cls) -> "ObsConfig":
        return cls(
            enabled=_env_bool("BIGDL_OBS", False),
            trace_dir=_env_str("BIGDL_TRACE_DIR", None),
            metrics_dir=_env_str("BIGDL_METRICS_DIR", None),
            reservoir_size=_env_int("BIGDL_OBS_RESERVOIR", 4096),
            slow_step_factor=_env_float("BIGDL_SLOW_STEP_FACTOR", 3.0),
            flight_spans=_env_int("BIGDL_FLIGHT_SPANS", 512),
            regress_tolerance=_env_float("BIGDL_REGRESS_TOLERANCE", 1.5),
            health_every=_env_int("BIGDL_HEALTH_EVERY", 0),
            health_window=_env_int("BIGDL_HEALTH_WINDOW", 64),
            health_spike_factor=_env_float("BIGDL_HEALTH_SPIKE_FACTOR",
                                           10.0),
            goodput_window=_env_int("BIGDL_GOODPUT_WINDOW", 32),
            wire_gbps=_env_float("BIGDL_WIRE_GBPS", 0.0),
            straggler_factor=_env_float("BIGDL_STRAGGLER_FACTOR", 1.5),
            obs_port=_env_opt_int("BIGDL_OBS_PORT", None),
            obs_port_file=_env_str("BIGDL_OBS_PORT_FILE", None),
            obs_peers=_env_str("BIGDL_OBS_PEERS", None),
            alert_rules=_env_str("BIGDL_ALERT_RULES", None),
            alert_sink=_env_str("BIGDL_ALERT_SINK", None),
            alert_sink_timeout=_env_float("BIGDL_ALERT_SINK_TIMEOUT", 1.0),
            reqtrace_sample=_env_float("BIGDL_REQTRACE_SAMPLE", 0.0),
            reqtrace_ring=_env_int("BIGDL_REQTRACE_RING", 256),
            strict=_env_bool("BIGDL_OBS_STRICT", False),
            watch_hosts=_env_int("BIGDL_WATCH_HOSTS", 16),
            rollup_shard=_env_int("BIGDL_ROLLUP_SHARD", 32),
            rollup_top_k=_env_int("BIGDL_ROLLUP_TOP_K", 64),
            stale_after_s=_env_float("BIGDL_STALE_AFTER_S", 30.0),
            retain_points=_env_int("BIGDL_RETAIN_POINTS", 240),
            retain_series=_env_int("BIGDL_RETAIN_SERIES", 512),
            prof_hz=_env_float("BIGDL_PROF_HZ", 0.0),
            prof_budget=_env_float("BIGDL_PROF_BUDGET", 0.01),
            bundle_dir=_env_str("BIGDL_BUNDLE_DIR", None),
            bundle_rate_limit=_env_float("BIGDL_BUNDLE_RATE_LIMIT",
                                         300.0),
        )


@dataclasses.dataclass
class TunerConfig:
    """Fusion-aware kernel auto-tuner (``bigdl_tpu/ops/autotune.py``).

    Off by default: dispatch then follows the hand-measured static
    policies in ``ops/attention.py`` / ``ops/conv_bn.py`` exactly.
    Enabled, every tunable call site (flash attention fwd/bwd, 1x1 and
    kxk conv+BN) resolves its impl and block sizes from the cached
    cost-model search instead.
    """

    # master switch [BIGDL_TUNER]
    enabled: bool = False
    # JSON decision store, keyed on (site, shape, dtype, platform);
    # unset = in-memory only (decisions die with the process)
    # [BIGDL_TUNER_CACHE]
    cache_path: Optional[str] = None
    # allow one-shot wall-clock measurement of candidates when inputs
    # are concrete (never inside a jit trace — there the cost model
    # decides); measured times are cached like any decision
    # [BIGDL_TUNER_MEASURE]
    measure: bool = False
    # timed iterations per measured candidate [BIGDL_TUNER_MEASURE_ITERS]
    measure_iters: int = 3

    @classmethod
    def from_env(cls) -> "TunerConfig":
        return cls(
            enabled=_env_bool("BIGDL_TUNER", False),
            cache_path=_env_str("BIGDL_TUNER_CACHE", None),
            measure=_env_bool("BIGDL_TUNER_MEASURE", False),
            measure_iters=_env_int("BIGDL_TUNER_MEASURE_ITERS", 3),
        )


@dataclasses.dataclass
class WireConfig:
    """Compressed-collective wire defaults (``bigdl_tpu/parallel/wire``).

    The process-wide answer to "what leaves the chip": DistriOptimizer
    resolves its gradient wire from here when the constructor leaves
    ``wire_dtype``/``wire_block``/``wire_ef`` unset, and every opt-in
    path (TP psum, MoE all_to_all, ring K/V rotation) passed a bare
    dtype string fills block/EF from here too.
    """

    # gradient-exchange wire dtype: "bfloat16" (cast, TPU-native),
    # "int8" / "fp8_e4m3" / "fp8_e5m2" (blockwise-scaled staged ring),
    # "float32"/"none" (uncompressed) [BIGDL_WIRE_DTYPE]
    dtype: str = "bfloat16"
    # elements per quantization scale for the scaled dtypes
    # [BIGDL_WIRE_BLOCK]
    block: int = 512
    # error feedback: carry each device's quantization residual across
    # steps so compression error dithers instead of biasing long runs
    # [BIGDL_WIRE_EF]
    error_feedback: bool = False

    @classmethod
    def from_env(cls) -> "WireConfig":
        return cls(
            dtype=_env_str("BIGDL_WIRE_DTYPE", "bfloat16"),
            block=_env_int("BIGDL_WIRE_BLOCK", 512),
            error_feedback=_env_bool("BIGDL_WIRE_EF", False),
        )


@dataclasses.dataclass
class AutoscaleConfig:
    """Autoscaling supervisor policy loop (``resilience/autoscale.py``).

    Off by default: the supervisor then only restarts, never resizes.
    Enabled, a policy loop inside the supervisor scrapes the live fleet
    signals (PR 8 ``/healthz``/``/metrics``), evaluates declarative
    scale rules, and executes a decision by checkpoint-stop-restart at
    the new world size through the elastic exit-code contract.
    """

    # master switch [BIGDL_AUTOSCALE]
    enabled: bool = False
    # world-size bounds a decision may never leave
    # [BIGDL_AUTOSCALE_MIN_WORLD / BIGDL_AUTOSCALE_MAX_WORLD]
    min_world: int = 1
    max_world: int = 8
    # scale step: up multiplies the world by this, down divides (the
    # ZeRO-1 shard quantum likes powers of two) [BIGDL_AUTOSCALE_FACTOR]
    factor: int = 2
    # seconds between policy evaluations [BIGDL_AUTOSCALE_INTERVAL]
    interval_s: float = 10.0
    # after a (re)launch, no signal is trusted for this long — compile
    # and restore make every fresh child look slow
    # [BIGDL_AUTOSCALE_WARMUP]
    warmup_s: float = 30.0
    # after an executed (or dry-run) decision, no further decision for
    # this long — one restart must finish paying for itself before the
    # next is allowed [BIGDL_AUTOSCALE_COOLDOWN]
    cooldown_s: float = 120.0
    # hysteresis: a rule must breach on this many CONSECUTIVE
    # evaluations before it may decide (a flapping signal resets its
    # streak and can never thrash the world) [BIGDL_AUTOSCALE_HYSTERESIS]
    hysteresis: int = 2
    # target step-time band: sustained step time above `high` scales
    # up, below `low` scales down; 0 disables either edge
    # [BIGDL_AUTOSCALE_STEP_TIME_HIGH / _LOW]
    step_time_high: float = 0.0
    step_time_low: float = 0.0
    # input/serving queue-depth band over the streaming tier's
    # bigdl_stream_buffer_depth / bigdl_stream_lag_records gauges:
    # sustained depth above `high` scales up (ingest outruns training),
    # below `low` scales down (paying for idle chips); 0 disables
    # [BIGDL_AUTOSCALE_QUEUE_HIGH / _LOW]
    queue_high: float = 0.0
    queue_low: float = 0.0
    # cost/throughput ceiling: live goodput ratio sustained below this
    # floor scales DOWN (overhead-bound runs don't get better with more
    # hosts — they get cheaper with fewer); 0 disables
    # [BIGDL_AUTOSCALE_GOODPUT_FLOOR]
    goodput_floor: float = 0.0
    # evict stragglers: a host /healthz reports as stalled triggers a
    # scale-down decision (reason straggler_evict) so the next launch
    # re-forms the world without it [BIGDL_AUTOSCALE_EVICT_STRAGGLERS]
    evict_stragglers: bool = False
    # serving latency band over the bigdl_request_latency_seconds
    # e2e histogram (resilience/autoscale.derive_signals computes the
    # fleet-worst p99 from the scraped buckets): sustained p99 above
    # `high` scales up, below `low` scales down; 0 disables
    # [BIGDL_AUTOSCALE_P99_HIGH / _LOW, seconds]
    p99_high: float = 0.0
    p99_low: float = 0.0
    # current world size as exported by the supervisor for its children
    # (the controller's starting point); 0 = unset, derive from
    # min_world [BIGDL_AUTOSCALE_WORLD]
    world: int = 0
    # dry-run: evaluate + count + trace every decision, execute none
    # [BIGDL_AUTOSCALE_DRY_RUN]
    dry_run: bool = False
    # rule pack override: inline JSON list or a path to a JSON file
    # (schema in resilience/autoscale.py); unset = rules derived from
    # the band knobs above [BIGDL_AUTOSCALE_RULES]
    rules: Optional[str] = None

    @classmethod
    def from_env(cls) -> "AutoscaleConfig":
        return cls(
            enabled=_env_bool("BIGDL_AUTOSCALE", False),
            min_world=_env_int("BIGDL_AUTOSCALE_MIN_WORLD", 1),
            max_world=_env_int("BIGDL_AUTOSCALE_MAX_WORLD", 8),
            factor=_env_int("BIGDL_AUTOSCALE_FACTOR", 2),
            interval_s=_env_float("BIGDL_AUTOSCALE_INTERVAL", 10.0),
            warmup_s=_env_float("BIGDL_AUTOSCALE_WARMUP", 30.0),
            cooldown_s=_env_float("BIGDL_AUTOSCALE_COOLDOWN", 120.0),
            hysteresis=_env_int("BIGDL_AUTOSCALE_HYSTERESIS", 2),
            step_time_high=_env_float("BIGDL_AUTOSCALE_STEP_TIME_HIGH",
                                      0.0),
            step_time_low=_env_float("BIGDL_AUTOSCALE_STEP_TIME_LOW", 0.0),
            queue_high=_env_float("BIGDL_AUTOSCALE_QUEUE_HIGH", 0.0),
            queue_low=_env_float("BIGDL_AUTOSCALE_QUEUE_LOW", 0.0),
            goodput_floor=_env_float("BIGDL_AUTOSCALE_GOODPUT_FLOOR", 0.0),
            evict_stragglers=_env_bool("BIGDL_AUTOSCALE_EVICT_STRAGGLERS",
                                       False),
            p99_high=_env_float("BIGDL_AUTOSCALE_P99_HIGH", 0.0),
            p99_low=_env_float("BIGDL_AUTOSCALE_P99_LOW", 0.0),
            world=_env_int("BIGDL_AUTOSCALE_WORLD", 0),
            dry_run=_env_bool("BIGDL_AUTOSCALE_DRY_RUN", False),
            rules=_env_str("BIGDL_AUTOSCALE_RULES", None),
        )


@dataclasses.dataclass
class ServeConfig:
    """Inference serving tier defaults (``bigdl_tpu/serving``).

    Constructor arguments on :class:`~bigdl_tpu.serving.LMEngine` /
    :class:`~bigdl_tpu.serving.ClassifierEngine` win; these are the
    process-wide fallbacks a deployment sets once.
    """

    # decode slots / classifier micro-batch rows [BIGDL_SERVE_MAX_BATCH]
    max_batch: int = 8
    # tokens per KV-cache page [BIGDL_SERVE_PAGE]
    page_size: int = 16
    # KV page pool size; 0 = full residency (every slot can hold a
    # max_len sequence) [BIGDL_SERVE_PAGES]
    num_pages: int = 0
    # bounded request-queue capacity — submits past it backpressure
    # the client [BIGDL_SERVE_QUEUE]
    queue_capacity: int = 64
    # int8 weights for the memory-bound decode matmuls (LM) / the
    # quantize() module swap (classifier) [BIGDL_SERVE_INT8]
    int8: bool = False
    # e2e latency SLO target in seconds; > 0 publishes the
    # bigdl_serve_latency_slo_ratio gauge the serve_latency_slo_burn
    # alert rule watches [BIGDL_SERVE_SLO_MS, milliseconds]
    slo_s: float = 0.0
    # "continuous" admits at step boundaries (the point of the tier);
    # "static" drains the whole batch first — the A/B baseline
    # [BIGDL_SERVE_ADMISSION]
    admission: str = "continuous"
    # HTTP front-end port for serving/server.py (0 = ephemeral);
    # unset = constructor default [BIGDL_SERVE_PORT]
    port: Optional[int] = None
    # paged decode-attention dispatch (ops/decode_attention.py):
    # "auto" = the static dense policy, overridden per shape by the
    # cached decode_attn auto-tuner site when BIGDL_TUNER=1; "dense" /
    # "fused" / "pallas" pin an impl [BIGDL_SERVE_DECODE_ATTN]
    decode_attn: str = "auto"
    # slice each step's page tables to the pow2 used-page prefix so
    # even the dense baseline stops gathering the empty pool
    # [BIGDL_SERVE_DECODE_BUCKET]
    decode_bucket: bool = True

    @classmethod
    def from_env(cls) -> "ServeConfig":
        return cls(
            max_batch=_env_int("BIGDL_SERVE_MAX_BATCH", 8),
            page_size=_env_int("BIGDL_SERVE_PAGE", 16),
            num_pages=_env_int("BIGDL_SERVE_PAGES", 0),
            queue_capacity=_env_int("BIGDL_SERVE_QUEUE", 64),
            int8=_env_bool("BIGDL_SERVE_INT8", False),
            slo_s=_env_float("BIGDL_SERVE_SLO_MS", 0.0) / 1000.0,
            admission=_env_str("BIGDL_SERVE_ADMISSION", "continuous"),
            port=_env_opt_int("BIGDL_SERVE_PORT", None),
            decode_attn=_env_str("BIGDL_SERVE_DECODE_ATTN", "auto"),
            decode_bucket=_env_bool("BIGDL_SERVE_DECODE_BUCKET", True),
        )


@dataclasses.dataclass
class RouterConfig:
    """Multi-replica serving router (``bigdl_tpu/serving/router.py``).

    The data-plane tier above N :class:`~bigdl_tpu.serving.LMEngine`
    replicas: session-affine, KV-pressure-aware placement, a shared
    retry *budget* (token bucket) so a browning-out replica cannot
    amplify load, and graceful drain/handoff.  Constructor arguments on
    :class:`~bigdl_tpu.serving.router.Router` win; these are the
    process-wide fallbacks.
    """

    # comma-separated replica endpoints ("host:port,host:port") the
    # router front-end load-balances over; unset = replicas are passed
    # programmatically [BIGDL_ROUTER_REPLICAS]
    replicas: Optional[str] = None
    # router HTTP port (0 = ephemeral); unset = constructor default
    # [BIGDL_ROUTER_PORT]
    port: Optional[int] = None
    # session-affinity binding TTL in seconds — a session re-placed
    # within the TTL lands on the replica holding its KV prefix;
    # <= 0 disables affinity [BIGDL_ROUTER_AFFINITY_TTL]
    affinity_ttl_s: float = 300.0
    # retry budget: tokens deposited per admitted request (the token
    # bucket is capped at `retry_budget_burst`), one spent per retry —
    # fleet-wide retries are capped at ~ratio x the request rate
    # [BIGDL_ROUTER_RETRY_BUDGET]
    retry_budget_ratio: float = 0.2
    # token-bucket cap (also the cold-start allowance)
    # [BIGDL_ROUTER_RETRY_BURST]
    retry_budget_burst: float = 8.0
    # per-request placement attempts past the first (a request is tried
    # on at most 1 + max_retries replicas) [BIGDL_ROUTER_MAX_RETRIES]
    max_retries: int = 2
    # per-attempt replica timeout in seconds [BIGDL_ROUTER_TIMEOUT]
    request_timeout_s: float = 30.0
    # drain deadline: a draining replica gets this long to finish its
    # in-flight decodes before the rest are checkpointed and handed
    # off [BIGDL_ROUTER_DRAIN_DEADLINE]
    drain_deadline_s: float = 10.0
    # weight of KV-page pressure (pages_in_use / pool) against queue
    # depth + in-flight count in the placement score
    # [BIGDL_ROUTER_KV_WEIGHT]
    kv_weight: float = 4.0
    # jittered-backoff base between placement retries (seconds)
    # [BIGDL_ROUTER_BACKOFF_BASE]
    backoff_base_s: float = 0.05
    # Retry-After seconds stamped on shed (503) responses
    # [BIGDL_ROUTER_RETRY_AFTER]
    retry_after_s: float = 1.0
    # exclude replicas whose exported host-clock staleness
    # (``staleness_s`` signal) exceeds BIGDL_STALE_AFTER_S from
    # placement — a skewed host's SLO and handoff timestamps cannot be
    # trusted [BIGDL_ROUTER_STALE_EXCLUDE]
    stale_exclude: bool = True

    @classmethod
    def from_env(cls) -> "RouterConfig":
        return cls(
            replicas=_env_str("BIGDL_ROUTER_REPLICAS", None),
            port=_env_opt_int("BIGDL_ROUTER_PORT", None),
            affinity_ttl_s=_env_float("BIGDL_ROUTER_AFFINITY_TTL", 300.0),
            retry_budget_ratio=_env_float("BIGDL_ROUTER_RETRY_BUDGET",
                                          0.2),
            retry_budget_burst=_env_float("BIGDL_ROUTER_RETRY_BURST", 8.0),
            max_retries=_env_int("BIGDL_ROUTER_MAX_RETRIES", 2),
            request_timeout_s=_env_float("BIGDL_ROUTER_TIMEOUT", 30.0),
            drain_deadline_s=_env_float("BIGDL_ROUTER_DRAIN_DEADLINE",
                                        10.0),
            kv_weight=_env_float("BIGDL_ROUTER_KV_WEIGHT", 4.0),
            backoff_base_s=_env_float("BIGDL_ROUTER_BACKOFF_BASE", 0.05),
            retry_after_s=_env_float("BIGDL_ROUTER_RETRY_AFTER", 1.0),
            stale_exclude=_env_bool("BIGDL_ROUTER_STALE_EXCLUDE", True),
        )


@dataclasses.dataclass
class RolloutConfig:
    """Live weight rollout (``bigdl_tpu/serving/rollout.py``).

    The online training->serving pipe: a checkpoint watcher hot-swaps
    manifest-verified weights into a live engine between decode steps,
    and a router-level canary controller promotes a new version to a
    fraction of replicas, auto-rolling back on SLO burn or output
    divergence with autoscaler-style hysteresis.
    """

    # directory the engine-side watcher polls for published checkpoint
    # prefixes (<version>.model.npz + <version>.manifest.json); unset =
    # watcher built programmatically only [BIGDL_ROLLOUT_WATCH]
    watch_dir: Optional[str] = None
    # watcher poll period in seconds [BIGDL_ROLLOUT_POLL]
    poll_s: float = 1.0
    # fraction of replicas a new version canaries on before full
    # promotion (at least one) [BIGDL_ROLLOUT_CANARY_FRACTION]
    canary_fraction: float = 0.25
    # canary replay divergence (fraction of mismatched tokens on the
    # pinned prompt set) past which a rollback breach is counted
    # [BIGDL_ROLLOUT_DIVERGENCE]
    divergence_threshold: float = 0.05
    # consecutive breached evaluations before a rollback fires (the
    # autoscaler's "for" hysteresis — one noisy window cannot flap)
    # [BIGDL_ROLLOUT_FOR]
    for_count: int = 2
    # consecutive CLEAN evaluations before the canary promotes to the
    # whole fleet [BIGDL_ROLLOUT_HOLD]
    hold_evals: int = 3
    # cooldown after a rollback: the same version cannot re-canary (and
    # no new offer is accepted) inside this window
    # [BIGDL_ROLLOUT_COOLDOWN]
    cooldown_s: float = 30.0
    # pinned prompt set the canary replays for the divergence signal:
    # count and per-prompt decode length [BIGDL_ROLLOUT_PROMPTS /
    # BIGDL_ROLLOUT_PROMPT_TOKENS]
    pinned_prompts: int = 4
    pinned_tokens: int = 8

    @classmethod
    def from_env(cls) -> "RolloutConfig":
        return cls(
            watch_dir=_env_str("BIGDL_ROLLOUT_WATCH", None),
            poll_s=_env_float("BIGDL_ROLLOUT_POLL", 1.0),
            canary_fraction=_env_float("BIGDL_ROLLOUT_CANARY_FRACTION",
                                       0.25),
            divergence_threshold=_env_float("BIGDL_ROLLOUT_DIVERGENCE",
                                            0.05),
            for_count=_env_int("BIGDL_ROLLOUT_FOR", 2),
            hold_evals=_env_int("BIGDL_ROLLOUT_HOLD", 3),
            cooldown_s=_env_float("BIGDL_ROLLOUT_COOLDOWN", 30.0),
            pinned_prompts=_env_int("BIGDL_ROLLOUT_PROMPTS", 4),
            pinned_tokens=_env_int("BIGDL_ROLLOUT_PROMPT_TOKENS", 8),
        )


@dataclasses.dataclass
class FleetSimConfig:
    """Fleet-scale control-plane simulator (``bigdl_tpu/sim``).

    The simulator stands up hundreds of synthetic ``/metrics`` +
    ``/healthz`` hosts in one process and drives the REAL autoscaling
    controller, alert engine and fleet aggregator through declarative
    chaos scenarios on a virtual clock (``scripts/fleet_sim.py``).
    These knobs parameterize that harness; they change nothing in a
    training or serving process.
    """

    # synthetic host count the scenarios run at [BIGDL_FLEET_HOSTS]
    hosts: int = 200
    # scenario selection: a builtin name (``bigdl_tpu/sim/scenario.py``
    # BUILTIN_SCENARIOS), a comma-separated list of names, inline JSON,
    # or a path to a JSON scenario file; unset = the smoke's default
    # matrix [BIGDL_FLEET_SCENARIO]
    scenario: Optional[str] = None
    # divide every virtual duration in the scenario (and the autoscale
    # policy windows it carries) by this factor — the CI knob that runs
    # the same scenario shape in fewer ticks.  The tick period itself
    # is preserved, so heavy compression coarsens signal dynamics
    # [BIGDL_FLEET_TIME_COMPRESSION]
    time_compression: float = 1.0
    # deterministic seed for host selection and per-host jitter
    # [BIGDL_FLEET_SEED]
    seed: int = 0

    @classmethod
    def from_env(cls) -> "FleetSimConfig":
        return cls(
            hosts=_env_int("BIGDL_FLEET_HOSTS", 200),
            scenario=_env_str("BIGDL_FLEET_SCENARIO", None),
            time_compression=_env_float("BIGDL_FLEET_TIME_COMPRESSION",
                                        1.0),
            seed=_env_int("BIGDL_FLEET_SEED", 0),
        )


@dataclasses.dataclass
class BigDLConfig:
    """Process-global framework configuration.

    Fields map 1:1 onto the reference's ``bigdl.*`` properties where one
    exists; the env alias is the ``BIGDL_*`` spelling shown per field.
    """

    # --- engine (reference: bigdl.check.singleton, Engine.init) ---------
    # refuse a second Engine.init in one process [BIGDL_CHECK_SINGLETON]
    check_singleton: bool = False
    # multi-host coordinator for jax.distributed.initialize
    # [BIGDL_COORDINATOR_ADDRESS / BIGDL_NUM_PROCESSES / BIGDL_PROCESS_ID]
    coordinator_address: Optional[str] = None
    num_processes: int = 1
    process_id: int = 0

    # --- elastic attempt index [BIGDL_ELASTIC_ATTEMPT] ------------------
    # which incarnation of an elastic run this process is (0 = first
    # launch); the supervisor exports it into every child's environment
    # and the goodput ledger / healthz payload key their shards on it
    elastic_attempt: int = 0

    # --- native host library [BIGDL_TPU_NO_NATIVE] ----------------------
    # skip loading the C++ host data-plane .so (numpy fallback)
    no_native: bool = False

    # --- logging (reference: LoggerFilter) ------------------------------
    # [BIGDL_DISABLE_LOGGER] / [BIGDL_LOG_PATH]
    disable_logger: bool = False
    log_path: Optional[str] = None

    # --- profiling [BIGDL_PROFILE] --------------------------------------
    # directory for a jax.profiler trace of the first optimizer steps
    profile_dir: Optional[str] = None

    # --- resilience (resilience/ package) -------------------------------
    # deterministic fault-injection plan for chaos tests, e.g.
    # "step:3:raise,step:7:nan_grad,ckpt:1:truncate" [BIGDL_FAULT_PLAN]
    fault_plan: Optional[str] = None
    # classified-retry backoff: base * 2^(attempt-1), capped, with
    # deterministic jitter [BIGDL_RETRY_BACKOFF_BASE / _MAX]
    retry_backoff_base: float = 0.5
    retry_backoff_max: float = 30.0
    # sliding-window retry budget: more than `budget` transient failures
    # inside `window` seconds stops retrying even if per-run attempts
    # remain [BIGDL_RETRY_WINDOW_SECONDS / BIGDL_RETRY_WINDOW_BUDGET]
    retry_window_seconds: float = 600.0
    retry_window_budget: int = 16
    # non-finite step guard: skip the weight update when grads/loss go
    # NaN/inf; escalate after N consecutive skips
    # [BIGDL_NONFINITE_GUARD / BIGDL_MAX_NONFINITE_SKIPS]
    nonfinite_guard: bool = True
    max_nonfinite_skips: int = 10
    # checkpoint retention: keep the newest K checkpoint pairs, 0 =
    # unlimited [BIGDL_CHECKPOINT_KEEP_LAST]
    checkpoint_keep_last: int = 0
    # --- elastic training (resilience/elastic.py) -----------------------
    # Engine.init installs a SIGTERM/SIGINT handler: finish the in-flight
    # step, emergency checkpoint, exit EXIT_PREEMPTED
    # [BIGDL_PREEMPTION_HANDLER]
    preemption_handler: bool = True
    # heartbeat peer-liveness for multi-host runs: a shared directory
    # every host touches a host-tagged file in; unset = off
    # [BIGDL_HEARTBEAT_DIR]
    heartbeat_dir: Optional[str] = None
    # touch the heartbeat file every K training steps
    # [BIGDL_HEARTBEAT_EVERY]
    heartbeat_every: int = 1
    # a peer silent past this many seconds raises PeerLostError instead
    # of hanging the next collective [BIGDL_HEARTBEAT_TIMEOUT]
    heartbeat_timeout: float = 60.0
    # supervisor hang watchdog (resilience/supervisor.py): a child
    # whose /healthz step stamp stops advancing for this many seconds
    # is killed and restarted as a transient failure — the hang class
    # heartbeats and exit codes cannot catch; <= 0 disables
    # [BIGDL_HANG_TIMEOUT]
    hang_timeout: float = 0.0
    # --- streaming datasets (dataset/stream.py) -------------------------
    # bounded-buffer capacity (records) of the stream source adapter —
    # the producer thread backpressures when the trainer falls this far
    # behind [BIGDL_STREAM_BUFFER]
    stream_buffer: int = 1024
    # records per "epoch" of an unbounded stream, so epoch-keyed
    # triggers (every_epoch checkpoints, max_epoch) stay meaningful on
    # continuous ingest; 0 = one endless epoch (use max_iteration)
    # [BIGDL_STREAM_EPOCH_RECORDS]
    stream_epoch_records: int = 0

    # --- overlapped training step (ISSUE 11) ----------------------------
    # bucketed comm/compute overlap: DistriOptimizer partitions the
    # flat gradient into ~this many MiB per bucket and launches the
    # compressed reduce-scatter per bucket (last-layer-first) so the
    # wire rides under the remaining backward; <= 0 = one monolithic
    # exchange (the pre-overlap behavior) [BIGDL_OVERLAP_BUCKET_MB]
    overlap_bucket_mb: float = 0.0
    # fully async checkpointing: trigger-driven checkpoints snapshot to
    # host synchronously (the only blocking span), then serialize +
    # fsync + manifest on a background writer thread.  Emergency /
    # preemption checkpoints ALWAYS stay synchronous — the process is
    # about to exit, there is no step to overlap
    # [BIGDL_CHECKPOINT_ASYNC]
    checkpoint_async: bool = False
    # double-buffered host->device input: batch N+1 is fetched,
    # prepared and device_put while step N is still in flight, so the
    # input pipeline overlaps device compute instead of stalling the
    # loop (disabled automatically under an active fault-injection
    # plan — chaos poisoning targets the foreground path)
    # [BIGDL_INPUT_DOUBLE_BUFFER]
    input_double_buffer: bool = False

    # --- autoscaling supervisor (resilience/autoscale.py) ---------------
    # [BIGDL_AUTOSCALE / _MIN_WORLD / _MAX_WORLD / _FACTOR / _INTERVAL /
    #  _WARMUP / _COOLDOWN / _HYSTERESIS / _STEP_TIME_HIGH / _STEP_TIME_LOW
    #  / _QUEUE_HIGH / _QUEUE_LOW / _GOODPUT_FLOOR / _EVICT_STRAGGLERS /
    #  _DRY_RUN / _RULES]
    autoscale: AutoscaleConfig = dataclasses.field(
        default_factory=AutoscaleConfig)

    # --- observability (obs/ package) -----------------------------------
    # span tracer / metrics registry / runtime profiling switches
    # [BIGDL_OBS / BIGDL_TRACE_DIR / BIGDL_METRICS_DIR /
    #  BIGDL_OBS_RESERVOIR]
    obs: ObsConfig = dataclasses.field(default_factory=ObsConfig)

    # --- kernel auto-tuner (ops/autotune.py) ----------------------------
    # [BIGDL_TUNER / BIGDL_TUNER_CACHE / BIGDL_TUNER_MEASURE /
    #  BIGDL_TUNER_MEASURE_ITERS]
    tuner: TunerConfig = dataclasses.field(default_factory=TunerConfig)

    # --- compressed collective wire (parallel/wire.py) ------------------
    # [BIGDL_WIRE_DTYPE / BIGDL_WIRE_BLOCK / BIGDL_WIRE_EF]
    wire: WireConfig = dataclasses.field(default_factory=WireConfig)

    # --- inference serving tier (serving/ package) ----------------------
    # [BIGDL_SERVE_MAX_BATCH / _PAGE / _PAGES / _QUEUE / _INT8 /
    #  _SLO_MS / _ADMISSION / _PORT]
    serve: ServeConfig = dataclasses.field(default_factory=ServeConfig)

    # --- multi-replica serving router (serving/router.py) ---------------
    # [BIGDL_ROUTER_REPLICAS / _PORT / _AFFINITY_TTL / _RETRY_BUDGET /
    #  _RETRY_BURST / _MAX_RETRIES / _TIMEOUT / _DRAIN_DEADLINE /
    #  _KV_WEIGHT / _BACKOFF_BASE / _RETRY_AFTER / _STALE_EXCLUDE]
    router: RouterConfig = dataclasses.field(default_factory=RouterConfig)

    # --- live weight rollout (serving/rollout.py) -----------------------
    # [BIGDL_ROLLOUT_WATCH / _POLL / _CANARY_FRACTION / _DIVERGENCE /
    #  _FOR / _HOLD / _COOLDOWN / _PROMPTS / _PROMPT_TOKENS]
    rollout: RolloutConfig = dataclasses.field(
        default_factory=RolloutConfig)

    # --- fleet-scale control-plane simulator (sim/ package) -------------
    # [BIGDL_FLEET_HOSTS / _SCENARIO / _TIME_COMPRESSION / _SEED]
    fleet: FleetSimConfig = dataclasses.field(
        default_factory=FleetSimConfig)

    # --- benchmarking [BENCH_* kept for bench.py compat] ----------------

    @classmethod
    def from_env(cls) -> "BigDLConfig":
        return cls(
            check_singleton=_env_bool("BIGDL_CHECK_SINGLETON", False),
            coordinator_address=_env_str("BIGDL_COORDINATOR_ADDRESS", None),
            num_processes=_env_int("BIGDL_NUM_PROCESSES", 1),
            process_id=_env_int("BIGDL_PROCESS_ID", 0),
            elastic_attempt=_env_int("BIGDL_ELASTIC_ATTEMPT", 0),
            no_native=_env_bool("BIGDL_TPU_NO_NATIVE", False),
            disable_logger=_env_bool("BIGDL_DISABLE_LOGGER", False),
            log_path=_env_str("BIGDL_LOG_PATH", None),
            profile_dir=_env_str("BIGDL_PROFILE", None),
            fault_plan=_env_str("BIGDL_FAULT_PLAN", None),
            retry_backoff_base=_env_float("BIGDL_RETRY_BACKOFF_BASE", 0.5),
            retry_backoff_max=_env_float("BIGDL_RETRY_BACKOFF_MAX", 30.0),
            retry_window_seconds=_env_float(
                "BIGDL_RETRY_WINDOW_SECONDS", 600.0),
            retry_window_budget=_env_int("BIGDL_RETRY_WINDOW_BUDGET", 16),
            nonfinite_guard=_env_bool("BIGDL_NONFINITE_GUARD", True),
            max_nonfinite_skips=_env_int("BIGDL_MAX_NONFINITE_SKIPS", 10),
            checkpoint_keep_last=_env_int("BIGDL_CHECKPOINT_KEEP_LAST", 0),
            preemption_handler=_env_bool("BIGDL_PREEMPTION_HANDLER", True),
            heartbeat_dir=_env_str("BIGDL_HEARTBEAT_DIR", None),
            heartbeat_every=_env_int("BIGDL_HEARTBEAT_EVERY", 1),
            heartbeat_timeout=_env_float("BIGDL_HEARTBEAT_TIMEOUT", 60.0),
            hang_timeout=_env_float("BIGDL_HANG_TIMEOUT", 0.0),
            stream_buffer=_env_int("BIGDL_STREAM_BUFFER", 1024),
            stream_epoch_records=_env_int("BIGDL_STREAM_EPOCH_RECORDS", 0),
            overlap_bucket_mb=_env_float("BIGDL_OVERLAP_BUCKET_MB", 0.0),
            checkpoint_async=_env_bool("BIGDL_CHECKPOINT_ASYNC", False),
            input_double_buffer=_env_bool("BIGDL_INPUT_DOUBLE_BUFFER",
                                          False),
            autoscale=AutoscaleConfig.from_env(),
            obs=ObsConfig.from_env(),
            tuner=TunerConfig.from_env(),
            wire=WireConfig.from_env(),
            serve=ServeConfig.from_env(),
            router=RouterConfig.from_env(),
            rollout=RolloutConfig.from_env(),
            fleet=FleetSimConfig.from_env(),
        )

    def describe(self) -> str:
        lines = [f"{f.name} = {getattr(self, f.name)!r}"
                 for f in dataclasses.fields(self)]
        return "BigDLConfig:\n  " + "\n  ".join(lines)


# the process-global instance (resolved from env at import)
config = BigDLConfig.from_env()

# fields pinned by an explicit configure() call: env refreshes skip them
_explicit: set = set()


def configure(**kwargs) -> BigDLConfig:
    """Override config fields programmatically (highest-priority tier).
    Returns the global config for chaining/inspection."""
    for k, v in kwargs.items():
        if not hasattr(config, k):
            raise AttributeError(f"unknown config field {k!r}; fields: "
                                 + ", ".join(f.name for f in
                                             dataclasses.fields(config)))
        setattr(config, k, v)
        _explicit.add(k)
    return config


def refresh_from_env() -> BigDLConfig:
    """Re-read ``BIGDL_*`` env vars for every field NOT pinned by
    configure().  Subsystems with a read-at-call-time contract (e.g.
    ``Engine.init`` honoring a coordinator exported after import) call
    this before reading the config."""
    fresh = BigDLConfig.from_env()
    for f in dataclasses.fields(fresh):
        if f.name not in _explicit:
            setattr(config, f.name, getattr(fresh, f.name))
    return config


def reload_from_env() -> BigDLConfig:
    """Re-resolve everything from the environment, dropping configure()
    overrides (tests mutate os.environ)."""
    _explicit.clear()
    return refresh_from_env()
