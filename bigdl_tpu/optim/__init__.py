"""bigdl_tpu.optim — training runtime.

Rebuild of «bigdl»/optim/ (SURVEY.md §2.1): OptimMethods, Triggers,
ValidationMethods, LocalOptimizer, DistriOptimizer, Metrics.
"""

from bigdl_tpu.optim.optim_method import (
    OptimMethod,
    SGD,
    Adam,
    Adagrad,
    Adadelta,
    Adamax,
    RMSprop,
    Ftrl,
    LBFGS,
    LarsSGD,
    Default,
    Poly,
    Step,
    MultiStep,
    Exponential,
    EpochDecay,
    Warmup,
    SequentialSchedule,
    Plateau,
)
from bigdl_tpu.optim.regularizer import L1Regularizer, L2Regularizer, L1L2Regularizer
from bigdl_tpu.optim.triggers import Trigger
from bigdl_tpu.optim.validation import (
    ValidationMethod,
    ValidationResult,
    Top1Accuracy,
    Top5Accuracy,
    Loss,
    MAE,
    TreeNNAccuracy,
    HitRatio,
    NDCG,
)
from bigdl_tpu.optim.optimizer import Optimizer, LocalOptimizer
from bigdl_tpu.optim.distri_optimizer import DistriOptimizer
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.optim.evaluator import (
    Evaluator,
    LocalValidator,
    Predictor,
    Validator,
)

__all__ = [
    "OptimMethod", "SGD", "Adam", "Adagrad", "Adadelta", "Adamax", "RMSprop",
    "Ftrl", "LBFGS", "LarsSGD",
    "Default", "Poly", "Step", "MultiStep", "Exponential", "EpochDecay",
    "Warmup", "SequentialSchedule", "Plateau",
    "L1Regularizer", "L2Regularizer", "L1L2Regularizer",
    "Trigger",
    "ValidationMethod", "ValidationResult", "Top1Accuracy", "Top5Accuracy",
    "Loss", "MAE", "TreeNNAccuracy", "HitRatio", "NDCG",
    "Optimizer", "LocalOptimizer", "DistriOptimizer", "Metrics",
    "Evaluator", "Predictor", "Validator", "LocalValidator",
]
