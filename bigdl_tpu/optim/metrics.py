"""Metrics — per-phase timers.

Rebuild of «bigdl»/optim/Metrics.scala (SURVEY.md §5 "Tracing"):
driver-side aggregated counters for "computing time average", "get weights
average", "aggregate gradient time" etc., logged per iteration/epoch.  The
reference aggregates via Spark accumulators; here the timers delegate to
the observability layer's labeled histogram registry
(:mod:`bigdl_tpu.obs.metrics`) — one ``bigdl_phase_seconds`` family
labeled by phase, with the reference's metric names kept verbatim as
label values so existing log parsers carry over, and Prometheus/JSONL
exposition for free through the registry.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Optional

from bigdl_tpu.obs.metrics import MetricsRegistry
from bigdl_tpu.obs import names

# per-phase driver wall time spans ~100us host phases to multi-second
# checkpoint/validation phases
PHASE_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


class Metrics:
    """Per-phase timer facade over a metrics registry.

    Each optimizer owns a private registry by default (so two trainers
    in one process never cross-pollute their averages, matching the
    reference's per-Optimizer accumulators); pass ``registry=`` to
    aggregate into a shared one.  The optimizer's end-of-run snapshot
    concatenates this registry into the global Prometheus exposition.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry if registry is not None else MetricsRegistry()
        self._family = self.registry.histogram(
            names.PHASE_SECONDS,
            "Per-phase driver wall time (reference Metrics.scala names)",
            labels=("phase",), buckets=PHASE_BUCKETS)

    def _child(self, name: str):
        return self._family.labels(phase=name)

    def add(self, name: str, value: float):
        self._child(name).observe(float(value))

    @contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def value(self, name: str) -> float:
        """Mean seconds per observation (the reference's "average")."""
        return self._child(name).mean

    def count(self, name: str) -> int:
        return self._child(name).count

    def total(self, name: str) -> float:
        return self._child(name).sum

    def snapshot(self) -> dict:
        """{phase: {count, total, mean}} — the registry-bridge form the
        obs layer and tests consume.  Per phase, count/total come from
        ONE locked histogram read, so a scrape racing a concurrent
        ``add()`` (the background checkpoint thread counts too) never
        shows a count without its total."""
        out = {}
        for (phase,), child in self._family.child_items():
            _, count, total = child.snapshot_state()
            out[phase] = {"count": count, "total": total,
                          "mean": total / count if count else 0.0}
        return out

    def summary(self) -> str:
        """Human log line: keeps the reference's "<phase> average: Xms"
        spelling (log parsers match on it) and appends count + total."""
        snap = self.snapshot()
        return ", ".join(
            f"{k} average: {v['mean'] * 1000:.2f}ms "
            f"(n={v['count']}, total={v['total'] * 1000:.1f}ms)"
            for k, v in sorted(snap.items())
        )

    def reset(self):
        self._family.clear()
