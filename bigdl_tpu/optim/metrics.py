"""Metrics — per-phase timers.

Rebuild of «bigdl»/optim/Metrics.scala (SURVEY.md §5 "Tracing"):
driver-side aggregated counters for "computing time average", "get weights
average", "aggregate gradient time" etc., logged per iteration/epoch.  The
reference aggregates via Spark accumulators; here a plain dict suffices
(one process drives the jitted step), with the same metric names so log
parsers carry over.
"""

from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager


class Metrics:
    def __init__(self):
        self._sums = defaultdict(float)
        self._counts = defaultdict(int)

    def add(self, name: str, value: float):
        self._sums[name] += value
        self._counts[name] += 1

    @contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def value(self, name: str) -> float:
        c = self._counts[name]
        return self._sums[name] / c if c else 0.0

    def summary(self) -> str:
        return ", ".join(
            f"{k} average: {self.value(k) * 1000:.2f}ms" for k in sorted(self._sums)
        )

    def reset(self):
        self._sums.clear()
        self._counts.clear()
