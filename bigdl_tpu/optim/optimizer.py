"""Optimizer factory + LocalOptimizer.

Rebuild of «bigdl»/optim/Optimizer.scala and LocalOptimizer.scala
(SURVEY.md §3.2).  The reference's LocalOptimizer runs multi-threaded
model replicas over a core pool with a synchronous gradient sum; on TPU
that intra-node replication "disappears — one XLA program per chip
already saturates the chip" (SURVEY.md §2.4), so LocalOptimizer is a
single jitted train step:

    loss, grads = value_and_grad(model.apply + criterion.loss)
    flat_grad -> [clipping processors] -> optim_method.step

The driver loop around it keeps reference semantics: ``Trigger``-driven
stop/validate/checkpoint, state table with epoch/neval counters, train
summaries, hyper-parameter logging.
"""

from __future__ import annotations

import logging
import os
import time
from functools import partial
from typing import Optional, Sequence

import numpy as np
from bigdl_tpu.obs import names

log = logging.getLogger("bigdl_tpu.optim")


def _jnp():
    import jax.numpy as jnp

    return jnp


class _GradClipper:
    """Parameter processors («bigdl»/optim/parameters/… SURVEY.md §2.1):
    global L2-norm clipping and constant clipping, applied to the
    gradient pytree inside the jitted step (and to the *sharded* flat
    gradient in DistriOptimizer, matching the reference's sharded
    application — a flat vector is the one-leaf pytree case)."""

    def __init__(self):
        self.l2_norm_clip: Optional[float] = None
        self.const_clip: Optional[tuple] = None

    def __call__(self, grad, global_sq_norm=None):
        import jax

        jnp = _jnp()
        g = grad
        if self.const_clip is not None:
            lo, hi = self.const_clip
            g = jax.tree.map(lambda a: jnp.clip(a, lo, hi), g)
        if self.l2_norm_clip is not None:
            if global_sq_norm is None:
                from bigdl_tpu.optim.optim_method import _global_sq_norm

                sq = _global_sq_norm(g)
            else:
                sq = global_sq_norm
            scale = jnp.minimum(1.0, self.l2_norm_clip / (jnp.sqrt(sq) + 1e-12))
            g = jax.tree.map(lambda a: a * scale, g)
        return g


class BaseOptimizer:
    """Shared builder API (reference: Optimizer's fluent setters)."""

    def __init__(self, model, dataset, criterion, batch_size=32):
        from bigdl_tpu.dataset import to_dataset
        from bigdl_tpu.optim.optim_method import SGD
        from bigdl_tpu.optim.triggers import Trigger
        from bigdl_tpu.optim.metrics import Metrics

        self.model = model
        self.dataset = to_dataset(dataset, batch_size)
        self.criterion = criterion
        self.batch_size = batch_size
        self.optim_method = SGD()
        self.end_when = Trigger.max_epoch(1)
        self.validation_trigger = None
        self.validation_dataset = None
        self.validation_methods = None
        self.checkpoint_path = None
        self.checkpoint_trigger = None
        self.train_summary = None
        self.val_summary = None
        self.metrics = Metrics()
        self._clipper = _GradClipper()
        self.max_retry = 5
        self.checkpoint_keep_last = 0
        # background checkpoint-write failure accounting: the failure is
        # recorded here and SURFACED on the next _checkpoint/optimize
        # call instead of dying as a log line (resilience satellite)
        self.checkpoint_write_failures = 0
        self._ckpt_write_error = None
        # non-finite step guard accounting
        self._nonfinite_consec = 0
        self._fault_injector = None
        # observability session handles; optimize() rebinds them from
        # the live config (NULL tracer / None reservoir / NULL ledger
        # = disabled)
        from bigdl_tpu.obs.goodput import NULL_LEDGER
        from bigdl_tpu.obs.trace import NULL_TRACER

        self._obs_tracer = NULL_TRACER
        self._obs_runtime = None
        self._obs_ledger = NULL_LEDGER
        # per-layer numerics telemetry (obs/health.py); optimize()
        # builds it from the live config, None = disabled
        self._health_monitor = None
        # static per-step collective byte footprint (obs/collectives.py)
        # — DistriOptimizer builds it with the train step; the driver
        # loop commits it once per resolved step
        self._collective_footprint = None
        # mixed-precision compute policy: None = full f32; "bfloat16"
        # runs fwd/bwd in bf16 with f32 master params + f32 grads/update
        # (the TPU-native recipe: MXU at 2x, normalizations stay f32)
        self.compute_dtype = None
        # elastic session (preemption polling + heartbeat liveness);
        # optimize() builds it from the live config, None outside a run
        self._elastic_session = None
        # batches to skip at the next epoch start — set by the resume
        # paths when the loaded checkpoint was written mid-epoch, so the
        # replay sees the exact batch the saved neval expects
        self._pending_fast_forward = 0
        # reference: InternalOptimizerUtil state table.  epoch_neval0 =
        # the neval of the current epoch's first batch, checkpointed so
        # a mid-epoch resume can fast-forward the data iterator to the
        # exact batch the saved neval expects (resilience/elastic.py)
        self.state = {"epoch": 1, "neval": 1, "loss": None, "score": None,
                      "epoch_finished": 0, "nonfinite_skips": 0,
                      "epoch_neval0": 1}

    # ---- fluent setters (camelCase parity aliases at the bottom) --------
    def set_optim_method(self, method):
        self.optim_method = method
        return self

    def set_end_when(self, trigger):
        self.end_when = trigger
        return self

    def set_validation(self, trigger=None, dataset=None, methods=None, batch_size=None):
        from bigdl_tpu.dataset import to_dataset

        self.validation_trigger = trigger
        self.validation_dataset = to_dataset(dataset, batch_size or self.batch_size)
        self.validation_methods = methods
        return self

    def set_checkpoint(self, path, trigger=None, background=None,
                       keep_last=None):
        """``background=True`` writes checkpoints fully async: the
        blocking part snapshots every array to host (the only span on
        the training critical path, stamped as the only
        ``checkpoint_save`` badput), then serialize/fsync/manifest run
        on a background writer thread.  At most one write is in flight;
        the next trigger waits for it.  Default from
        ``BIGDL_CHECKPOINT_ASYNC``; emergency/preemption checkpoints
        ALWAYS write synchronously regardless (the process is exiting —
        there is nothing to overlap, and the checkpoint must be durable
        before the exit code).

        ``keep_last=K`` keeps only the newest K checkpoint pairs on
        disk (GC after each write); default from
        ``config.checkpoint_keep_last``, 0 = unlimited."""
        from bigdl_tpu.config import refresh_from_env
        from bigdl_tpu.optim.triggers import Trigger

        config = refresh_from_env()
        os.makedirs(path, exist_ok=True)
        self.checkpoint_path = path
        self.checkpoint_trigger = trigger or Trigger.every_epoch()
        self.checkpoint_background = (config.checkpoint_async
                                      if background is None
                                      else bool(background))
        self.checkpoint_keep_last = (config.checkpoint_keep_last
                                     if keep_last is None else int(keep_last))
        return self

    def set_train_summary(self, summary):
        self.train_summary = summary
        return self

    def set_val_summary(self, summary):
        self.val_summary = summary
        return self

    def set_gradient_clipping_by_l2_norm(self, clip_norm: float):
        self._clipper.l2_norm_clip = clip_norm
        return self

    def set_constant_gradient_clipping(self, min_value: float, max_value: float):
        self._clipper.const_clip = (min_value, max_value)
        return self

    def disable_gradient_clipping(self):
        self._clipper.l2_norm_clip = None
        self._clipper.const_clip = None
        return self

    def set_compute_dtype(self, dtype):
        """Mixed precision: ``"bfloat16"`` (or a jnp dtype) runs the
        model fwd/bwd in that dtype while master params, gradients, the
        loss, and the optimizer update stay f32.  ``None`` disables."""
        self.compute_dtype = dtype
        return self

    # reference spellings
    setOptimMethod = set_optim_method
    setEndWhen = set_end_when
    setValidation = set_validation
    setCheckpoint = set_checkpoint
    setTrainSummary = set_train_summary
    setValSummary = set_val_summary
    setGradientClippingByL2Norm = set_gradient_clipping_by_l2_norm
    setConstantGradientClipping = set_constant_gradient_clipping

    # ---- shared helpers -------------------------------------------------
    def _summary_resilience(self, step, **counters):
        """Feed resilience counters to the train summary when one is set
        (guarded: user-supplied summary stubs may lack the method)."""
        add = getattr(self.train_summary, "add_resilience", None)
        if add is not None:
            add(step, **counters)

    def _raise_pending_ckpt_error(self):
        """Surface a background checkpoint-write failure recorded by
        ``_flush_checkpoints(raise_errors=False)`` — the next
        ``_checkpoint``/``optimize`` call must fail loudly, not keep
        training against a checkpoint sink that silently stopped
        persisting."""
        err = self._ckpt_write_error
        if err is not None:
            from bigdl_tpu.resilience.retry import CheckpointWriteError

            self._ckpt_write_error = None
            raise CheckpointWriteError(
                f"a background checkpoint write failed earlier "
                f"({self.checkpoint_write_failures} total write "
                f"failures): {err!r}") from err

    def _checkpoint(self):
        if not self.checkpoint_path:
            return
        self._raise_pending_ckpt_error()
        from bigdl_tpu.utils.serializer import (
            save_checkpoint,
            snapshot_checkpoint,
            write_checkpoint,
        )

        tag = f"{self.state['epoch']}_{self.state['neval']}"
        prefix = os.path.join(self.checkpoint_path, f"checkpoint_{tag}")
        extra = self._checkpoint_extra()
        keep = self.checkpoint_keep_last
        if getattr(self, "checkpoint_background", False):
            from concurrent.futures import ThreadPoolExecutor

            if getattr(self, "_ckpt_executor", None) is None:
                self._ckpt_executor = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="bigdl-ckpt")
                self._ckpt_future = None
            self._flush_checkpoints()  # at most one write in flight
            # snapshot-to-host is the ONLY blocking span (and the only
            # checkpoint_save badput); the extra dict — incl. the
            # exactly-once stream offset — was captured above, at
            # snapshot time, with every dispatched step resolved.  The
            # writer thread then owns plain numpy, no device refs.
            snap = snapshot_checkpoint(self.model, self.optim_method,
                                       extra, to_host=True)
            self._ckpt_future = self._ckpt_executor.submit(
                write_checkpoint, snap, prefix, keep, True)
            log.info("checkpoint scheduled at epoch %s iter %s",
                     self.state["epoch"], self.state["neval"])
            return
        save_checkpoint(prefix, self.model, self.optim_method, extra,
                        keep_last=keep)
        log.info("checkpoint saved at epoch %s iter %s", self.state["epoch"],
                 self.state["neval"])

    def _flush_checkpoints(self, raise_errors: bool = True):
        """Wait for an in-flight background checkpoint write — called
        before reads of the checkpoint dir and at the end of
        optimize().  ``raise_errors=False`` records the failure (next
        ``_checkpoint``/``optimize`` call surfaces it) instead of
        raising — used in the exception-path finally, where raising
        would mask the original error."""
        fut = getattr(self, "_ckpt_future", None)
        if fut is not None:
            self._ckpt_future = None
            try:
                fut.result()
            except Exception as e:
                self.checkpoint_write_failures += 1
                self._summary_resilience(
                    self.state["neval"],
                    checkpoint_write_failures=self.checkpoint_write_failures)
                from bigdl_tpu import obs

                obs.get_tracer().event(
                    "resilience.checkpoint_write_failed",
                    step=self.state["neval"], error=type(e).__name__,
                    total=self.checkpoint_write_failures)
                obs.get_registry().counter(
                    names.CHECKPOINT_WRITE_FAILURES_TOTAL,
                    "Background checkpoint writes that raised").inc()
                if raise_errors:
                    raise
                self._ckpt_write_error = e
                log.exception("background checkpoint write failed "
                              "(recorded; surfaces on the next "
                              "checkpoint/optimize call)")

    def _topology(self):
        """The checkpoint topology tag (resilience/elastic.py): how the
        writer's optimizer state is laid out, so restore can tell a
        same-world resume from a resize.  Local training keeps the
        native params pytree — nothing to re-partition."""
        return {"world_size": 1, "shard_layout": "tree",
                "step": self.state["neval"]}

    def _checkpoint_extra(self) -> dict:
        """Everything a resume needs beyond the arrays: trigger/LR
        counters, the epoch's starting neval (mid-epoch fast-forward),
        the writer topology, and — for streaming datasets — the trained
        stream offset/watermark (the exactly-once commit point,
        dataset/stream.py)."""
        extra = {"epoch": self.state["epoch"],
                 "neval": self.state["neval"],
                 "epoch_neval0": self.state.get("epoch_neval0",
                                                self.state["neval"]),
                 "topology": self._topology()}
        stream_state = getattr(self.dataset, "stream_checkpoint_state",
                               None)
        if stream_state is not None:
            extra["stream"] = stream_state()
        return extra

    def _elastic_shutdown(self, step, pvar, mod_state, opt_state):
        """Graceful preemption (resilience/elastic.py): the in-flight
        step already resolved — write back the live device state, write
        a synchronous emergency checkpoint through the hardened
        ``write_checkpoint`` path, and raise :class:`Preempted` (a
        SystemExit carrying EXIT_PREEMPTED).  The optimize() finally
        still flushes obs shards and any background checkpoint."""
        from bigdl_tpu import obs
        from bigdl_tpu.resilience import elastic

        signum = elastic.preemption_signal()
        # the request is being handled NOW: drop the flag so a later
        # optimize() in this process (tests, a supervisor running
        # in-process) doesn't re-preempt on the stale bit
        elastic.clear_preemption()
        log.warning(
            "preemption requested (signal %s) at iter %d — emergency "
            "checkpoint, then exit %d", signum, step,
            elastic.EXIT_PREEMPTED)
        self._write_back(pvar, mod_state)
        self.optim_method.state = opt_state
        tracer = obs.get_tracer()
        prefix = None
        if self.checkpoint_path:
            # serialize against an in-flight background write of the
            # same prefix (records, never raises: nothing may mask the
            # preemption exit)
            self._flush_checkpoints(raise_errors=False)
            tag = f"{self.state['epoch']}_{self.state['neval']}"
            prefix = os.path.join(self.checkpoint_path,
                                  f"checkpoint_{tag}")
            try:
                from bigdl_tpu.utils.serializer import save_checkpoint

                save_checkpoint(prefix, self.model, self.optim_method,
                                extra=self._checkpoint_extra(),
                                keep_last=self.checkpoint_keep_last)
                log.info("emergency checkpoint written: %s", prefix)
                tracer.event("elastic.emergency_checkpoint", step=step,
                             prefix=os.path.basename(prefix))
            except Exception as e:  # noqa: BLE001 — still exit preempted
                log.exception("emergency checkpoint failed; exiting "
                              "preempted without one")
                tracer.event("elastic.emergency_checkpoint_failed",
                             step=step, error=type(e).__name__)
                prefix = None
        obs.get_registry().counter(
            names.PREEMPTIONS_TOTAL,
            "Graceful preemption shutdowns (SIGTERM/SIGINT)").inc()
        tracer.event("elastic.preempted", step=step, signum=signum,
                     checkpoint=prefix and os.path.basename(prefix))
        raise elastic.Preempted(
            f"preempted (signal {signum}) at iter {step}; emergency "
            f"checkpoint: {prefix or 'none'}", step=step,
            checkpoint=prefix)

    def _prepare_batch(self, inp, tgt):
        """Hook: adjust a host batch before device transfer, or return
        None to drop it.  DistriOptimizer overrides to enforce mesh
        divisibility."""
        return inp, tgt

    def _detect_slow_step(self, n, dt, tracer, runtime):
        """Slow-step anomaly detector: a step slower than
        ``median * BIGDL_SLOW_STEP_FACTOR`` (default 3x) emits a
        structured ``slow_step`` trace event carrying the step's
        child-span breakdown (data_wait / batch_prep / device_put /
        step_dispatch durations out of the tracer's flight-recorder
        ring), so outliers self-diagnose instead of vanishing into the
        p99.  Only runs when the runtime profile is live (obs on); the
        median window is the step-time reservoir, which already holds
        this step."""
        from bigdl_tpu.config import config

        factor = config.obs.slow_step_factor
        if factor <= 0:
            return
        res = runtime.step_times
        if res.count < 8:
            return  # warmup: compiles dominate, the median is noise
        med = res.percentiles((0.5,))[0.5]
        if med is None or med <= 0 or dt <= med * factor:
            return
        breakdown = {}
        for rec in tracer.recent():
            if rec.get("kind") != "span" or rec.get("name") in (
                    "iteration", "computing"):
                continue
            if (rec.get("attrs") or {}).get("step") == n:
                breakdown[rec["name"]] = round(
                    breakdown.get(rec["name"], 0.0)
                    + float(rec.get("dur_s", 0.0)), 6)
        log.warning(
            "slow step %d: %.4fs vs median %.4fs (> %gx) — breakdown %s",
            n, dt, med, factor, breakdown or "unavailable (tracing off)")
        tracer.event("slow_step", step=n, dur_s=round(dt, 6),
                     median_s=round(med, 6), factor=factor,
                     breakdown=breakdown)
        from bigdl_tpu import obs

        obs.get_registry().counter(
            names.SLOW_STEPS_TOTAL,
            "Steps exceeding median * BIGDL_SLOW_STEP_FACTOR").inc()

    def _params_tree(self, pvar):
        """Device-resident training params -> the model's params pytree.
        Local training already holds the tree; DistriOptimizer overrides
        to unravel its flat ZeRO vector (on device, no host copy)."""
        return pvar

    def _run_validation(self, pvar=None, mstate=None):
        """Validation on device-resident params (VERDICT r2 #3): the
        trainer passes its live pvar/mstate so no host weight copy
        happens per trigger; the eval forward shards each batch P(data)
        over the trainer's mesh when one exists (reference: distributed
        Evaluator over the executors, SURVEY.md §3.6)."""
        if self.validation_dataset is None or not self.validation_methods:
            return None
        from bigdl_tpu.optim.evaluator import evaluate_dataset

        params = state = None
        if pvar is not None:
            params = self._params_tree(pvar)
            state = mstate
        results = evaluate_dataset(
            self.model, self.validation_dataset, self.validation_methods,
            mesh=getattr(self, "mesh", None), params=params, state=state,
        )
        for method, res in zip(self.validation_methods, results):
            value, _ = res.result()
            log.info("validation %s: %.6f", method.name, value)
            if self.val_summary is not None:
                self.val_summary.add_scalar(method.name, value, self.state["neval"])
        # first method's value is the reference's "score" for Trigger.maxScore
        self.state["score"] = results[0].result()[0]
        # Plateau schedule hook
        sched = getattr(self.optim_method, "learningrate_schedule", None)
        from bigdl_tpu.optim.optim_method import Plateau

        if isinstance(sched, Plateau):
            scale = sched.on_score(self.state["score"], self.optim_method.learningrate)
            if self.optim_method.state is not None:
                jnp = _jnp()
                self.optim_method.state["lr_scale"] = jnp.asarray(scale, jnp.float32)
        return results


class LocalOptimizer(BaseOptimizer):
    """Single-process trainer (reference: «bigdl»/optim/LocalOptimizer.scala).

    The driver loop here is shared with DistriOptimizer (which overrides
    ``_build_train_step``/``_init_opt_state``/``_put_batch`` to shard over
    the mesh) — mirroring how the reference shares Trigger/checkpoint/
    validation logic between its two optimizers.
    """

    def _init_params(self):
        """Device representation of the trainable parameters.  Local:
        the native pytree (no ravel/unravel copies on the hot path).
        DistriOptimizer overrides with the flat vector its ZeRO-1
        reduce-scatter shards.

        The tree is copied: the jitted step donates its input buffers,
        and the model must never be left holding donated (deleted)
        arrays."""
        import jax

        jnp = _jnp()
        return jax.tree.map(lambda a: jnp.array(a, copy=True),
                            self.model.params())

    def _cast_for_compute(self, p, inp):
        """Apply the mixed-precision policy: cast floating params and the
        input to compute_dtype.  The cast sits inside the differentiated
        function, so grads w.r.t. the f32 master params come back f32."""
        if self.compute_dtype is None:
            return p, inp
        import jax

        jnp = _jnp()
        ct = jnp.dtype(self.compute_dtype)
        cast = lambda a: (
            a.astype(ct)
            if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
            else a
        )
        return jax.tree.map(cast, p), cast(inp)

    def _loss_fn(self):
        """Returns loss_fn: (params, mstate, rng, inp, tgt) ->
        (loss_for_grad, (reported_loss, new_mstate))."""
        model, criterion = self.model, self.criterion

        def loss_fn(p, mstate, rng, inp, tgt):
            import jax

            jnp = _jnp()
            pc, inpc = self._cast_for_compute(p, inp)
            out, new_mstate = model.apply(pc, mstate, inpc, training=True,
                                          rng=rng)
            # the loss always evaluates in f32 (softmax/log numerics)
            out = jax.tree.map(
                lambda a: a.astype(jnp.float32)
                if hasattr(a, "dtype") and jnp.issubdtype(a.dtype,
                                                          jnp.floating)
                else a,
                out,
            )
            loss = criterion.loss(out, tgt) + model.regularization_loss(p)
            return loss, (loss, new_mstate)

        return loss_fn

    def _init_opt_state(self, pvar):
        opt = self.optim_method
        if opt.state is None:
            opt.state = opt.init_state(pvar)
        return opt.state

    def _build_train_step(self):
        import jax

        from bigdl_tpu.config import config

        jnp = _jnp()
        opt = self.optim_method
        clipper = self._clipper
        loss_fn = self._loss_fn()
        guard = config.nonfinite_guard
        # per-layer health telemetry (obs/health.py): pure device math
        # appended to the step ONLY when the monitor exists — disabled
        # runs compile the exact pre-health signature
        health_on = self._health_monitor is not None
        # freeze support (reference module.freeze): zero the gradients
        # of frozen subtrees — static at trace time, no cost unfrozen
        mask = self.model.grad_mask() if self.model.has_frozen() else None

        # params/opt state/model state buffers are donated: the step
        # updates in place on-device instead of allocating fresh HBM
        @partial(jax.jit, donate_argnums=(0, 1, 2))
        def train_step(p, opt_st, mstate, rng, inp, tgt):
            (_, (loss, new_mstate)), grad = jax.value_and_grad(
                loss_fn, has_aux=True
            )(p, mstate, rng, inp, tgt)
            if mask is not None:
                # mask BEFORE the clipper so frozen gradients cannot
                # inflate the global norm and over-shrink live ones
                grad = jax.tree.map(lambda g, s: g * s, grad, mask)
            # health stats see the pre-clip gradient (clipping hides
            # exactly the explosions the telemetry exists to show)
            grad_for_health = grad if health_on else None
            grad = clipper(grad)
            new_p, new_opt = opt.step(grad, p, opt_st)
            if mask is not None:
                # and mask the UPDATE too: optimizer-internal weight
                # decay adds wd*p past the zeroed gradient — frozen
                # parameters must not move at all
                new_p = jax.tree.map(
                    lambda old, new, s: old + s * (new - old),
                    p, new_p, mask)
            ok = jnp.array(True)
            if guard:
                # non-finite step guard: a NaN/inf gradient (or loss)
                # must not be trained on — params/opt state/model state
                # pass through unchanged and the driver counts the skip
                ok = jnp.isfinite(loss)
                for leaf in jax.tree.leaves(grad):
                    ok = ok & jnp.all(jnp.isfinite(leaf))
                keep = lambda new, old: jax.tree.map(
                    lambda a, b: jnp.where(ok, a, b)
                    if hasattr(a, "dtype") else a,
                    new, old)
                new_p = keep(new_p, p)
                new_opt = keep(new_opt, opt_st)
                new_mstate = keep(new_mstate, mstate)
            if health_on:
                from bigdl_tpu.obs import health as _health

                # (L, 4) per-layer [grad_sq, param_sq, update_sq,
                # nonfinite]; new_p is post-guard so a skipped step
                # reports a zero update
                stats = _health.tree_layer_stats(grad_for_health, p,
                                                 new_p)
                return new_p, new_opt, new_mstate, loss, ok, stats
            return new_p, new_opt, new_mstate, loss, ok

        return train_step

    def _put_batch(self, inp, tgt):
        jnp = _jnp()
        return jnp.asarray(inp), jnp.asarray(tgt)

    def optimize(self):
        import jax

        from bigdl_tpu import obs
        from bigdl_tpu.resilience.faults import get_injector

        # a background checkpoint write that failed in a previous
        # optimize() (recorded by the exception-path flush) surfaces
        # here, before any new work trusts the broken sink
        self._raise_pending_ckpt_error()
        inj = get_injector()
        self._fault_injector = inj if inj.active else None
        self._nonfinite_consec = 0
        # observability session: the tracer is NULL (shared no-op
        # context managers) and the runtime reservoir None when obs is
        # off, so the hot loop pays nothing — and nothing here ever
        # reads a device value, so enabling obs adds zero per-step
        # host-device synchronizations either way
        tracer = self._obs_tracer = obs.get_tracer()
        self._obs_runtime = obs.get_runtime() if obs.active() else None
        # goodput ledger (obs/goodput.py): interval stamps ride the
        # span boundaries below — the shared no-op object when obs is
        # off, so the hot loop pays method-call noise at most and never
        # a device read either way
        self._obs_ledger = obs.get_ledger()
        # live telemetry plane (obs/server.py): the /metrics + /healthz
        # endpoint exists only when BIGDL_OBS_PORT is set; unset, this
        # is one config read, no thread, no socket — and the loop below
        # skips the per-step stamp entirely
        from bigdl_tpu.obs import server as _obs_server

        self._obs_server = _obs_server.ensure_server()
        # continuous profiler (obs/prof.py): starts sampling with the
        # training loop when BIGDL_PROF_HZ > 0; off = one config read
        from bigdl_tpu.obs import prof as _obs_prof

        _obs_prof.get_profiler()
        if self._obs_server is not None:
            # the reference Metrics phase timers live in a private
            # registry; expose them on /metrics next to the process one
            _obs_server.register_registry(self.metrics.registry)
        # training-health telemetry: the monitor exists only when
        # BIGDL_HEALTH_EVERY > 0; its absence makes the step build the
        # exact health-less signature with zero extra host transfers
        from bigdl_tpu.obs import health as _health_mod

        self._health_monitor = _health_mod.monitor_from_config(
            self.model.params(), tracer=tracer,
            summary=self.train_summary)
        # elastic session: registers this loop as a preemption listener
        # (SIGTERM now drains gracefully instead of exiting from the
        # handler) and starts the heartbeat monitor on multi-host runs
        from bigdl_tpu.resilience import elastic as _elastic

        self._elastic_session = _elastic.ElasticSession.from_config()

        model = self.model
        model.training()

        pvar = self._init_params()
        # copy model/optimizer state before the first (donating) step so
        # the model and any pre-existing opt.state never alias deleted
        # buffers; after that, opt.state tracks the step outputs (only an
        # exception *during* a step can catch it transiently stale)
        copy = lambda t: jax.tree.map(
            lambda a: a.copy() if hasattr(a, "copy") else a, t
        )
        mod_state = copy(model.state())
        opt = self.optim_method
        opt_state = copy(self._init_opt_state(pvar))
        opt.state = opt_state
        # the build itself is traced; the returned step is wrapped so
        # first-call (trace+compile) vs cached-dispatch timing feeds the
        # runtime profile (obs/runtime.py)
        with tracer.span("build_train_step"):
            train_step = self._build_train_step()
        if self._obs_runtime is not None:
            train_step = obs.instrument_jit(
                train_step, "train_step", stats=self._obs_runtime,
                tracer=tracer, ledger=self._obs_ledger)

        base_key = jax.random.key(1234)
        wall_start = time.time()
        records_total = 0
        stop = False
        from bigdl_tpu.utils.profiler import StepProfiler

        profiler = StepProfiler()
        try:
            return self._optimize_loop(
                model, pvar, mod_state, opt, opt_state, train_step,
                base_key, wall_start, records_total, stop, profiler,
            )
        finally:
            # an exception mid-epoch must not leak an active trace — the
            # DistriOptimizer retry path would otherwise hit "profiler
            # already started" on its next attempt
            profiler.stop()
            # unregister the preemption listener + stop the heartbeat
            # thread (a retry attempt builds a fresh session)
            if self._elastic_session is not None:
                self._elastic_session.close()
                self._elastic_session = None
            # a background checkpoint still writing must become durable
            # before optimize() returns or the retry path reads the
            # checkpoint dir; write errors are logged here (raising in
            # a finally would mask an in-flight exception)
            self._flush_checkpoints(raise_errors=False)
            ex = getattr(self, "_ckpt_executor", None)
            if ex is not None:
                # no lingering non-daemon worker thread per optimizer
                ex.shutdown(wait=True)
                self._ckpt_executor = None
            # export the observability artifacts LAST so the snapshot
            # sees the final counter values (incl. any failure recorded
            # by the flush above); off = no-op
            if obs.active():
                obs.flush(extra_registries=[self.metrics.registry])

    def _optimize_loop(self, model, pvar, mod_state, opt, opt_state,
                       train_step, base_key, wall_start, records_total,
                       stop, profiler):
        import jax

        from bigdl_tpu import obs
        from bigdl_tpu.config import config
        from bigdl_tpu.resilience.retry import NonFiniteStepError

        max_nonfinite = config.max_nonfinite_skips
        # double-buffered host->device input (ISSUE 11): batch N+1 is
        # fetched, prepared and device_put right after step N
        # dispatches, so the whole input pipeline overlaps the in-
        # flight device step instead of stalling the loop top (the
        # input_bound badput the goodput ledger measures).  Chaos runs
        # keep the foreground path: the injector poisons host batches
        # at dispatch time, before the transfer.
        double_buffer = (config.input_double_buffer
                         and self._fault_injector is None)
        # session-local obs handles (set up by optimize()): tracer is the
        # shared no-op when disabled, runtime None — zero hot-loop cost
        tracer = self._obs_tracer
        runtime = self._obs_runtime
        monitor = self._health_monitor
        ledger = self._obs_ledger
        # step-advance stamp for /healthz + the supervisor hang
        # watchdog: one tuple rebind per resolved step, and only when
        # the live endpoint exists — the disabled path stays a None
        # check (the exact off-path the noop pin asserts)
        if getattr(self, "_obs_server", None) is not None:
            from bigdl_tpu.obs.server import note_step
        else:
            note_step = None
        # streaming datasets (dataset/stream.py): advance the trained
        # stream frontier once per dispatched batch, so the offset a
        # checkpoint carries covers exactly the batches in the weights
        note_stream = getattr(self.dataset, "note_batch_trained", None)

        # Async-dispatch pipelining: the device loss is read back ONE
        # iteration behind, so the next step is dispatched before the
        # host blocks — the device always has a step queued and the
        # per-step host<->device sync round trip (expensive through the
        # TPU relay) overlaps compute.  Loss-reading triggers
        # (Trigger.min_loss) force the exact per-step readback instead.
        # unknown user-supplied callables may read state["loss"], so
        # only triggers that DECLARE needs_loss=False may pipeline —
        # including a Parameters summary trigger, which is evaluated
        # per-iteration against the same state table
        _param_trig = (self.train_summary.get_summary_trigger("Parameters")
                       if self.train_summary is not None else None)
        sync_per_step = any(
            getattr(t, "needs_loss", True)
            for t in (self.end_when, self.validation_trigger,
                      self.checkpoint_trigger, _param_trig)
            if t is not None
        )
        pending = []  # [(n, loss_dev, ok_dev, batch_size, t_dispatch,
        #                 health_dev_or_None)]

        def resolve(n, loss_dev, ok_dev, bs, t0, health_dev=None):
            loss_val = float(loss_dev)
            # in pipelined steady state this spans dispatch -> observed
            # completion (~ device step time + one iteration's host work)
            dt = time.perf_counter() - t0
            self.metrics.add("computing time", dt)
            fp = self._collective_footprint
            if fp is not None:
                # one executed step's static collective bytes -> the
                # bigdl_collective_bytes_total counters (host dict math,
                # children pre-bound at step build)
                fp.commit()
            if runtime is not None:
                # feeds the step-time p50/p95/p99 reservoir; the span is
                # retroactive (complete) because under pipelining this
                # resolves one iteration after its dispatch
                runtime.record_step(dt)
                tracer.complete("computing", t0, dt, step=n)
                self._detect_slow_step(n, dt, tracer, runtime)
            # goodput: one productive-step interval (re-tagged rework
            # by the ledger when n is under the resume high-water mark)
            ledger.record("step", t0, dt, step=n)
            if note_step is not None:
                note_step(n)
            self.state["loss"] = loss_val
            if monitor is not None:
                # fetches the (L, 4) health array only every K steps —
                # or unconditionally when the guard tripped, because
                # localization IS the point of that fetch.  Runs before
                # the skip-escalation below so a NonFiniteStepError
                # never races the layer attribution out of the trace.
                monitor.on_step(n, health_dev, bool(ok_dev), loss_val)
            if self.train_summary is not None:
                self.train_summary.add_scalar("Loss", loss_val, n)
                self.train_summary.add_scalar(
                    "Throughput",
                    bs / max(1e-9, time.perf_counter() - t0), n)
            if not bool(ok_dev):
                # non-finite grads/loss: the guarded step already passed
                # weights/opt-state through unchanged — count the skip,
                # escalate after max_nonfinite consecutive ones
                self.state["nonfinite_skips"] += 1
                self._nonfinite_consec += 1
                log.warning(
                    "non-finite grads/loss at iter %d (loss=%r) — update "
                    "skipped (%d consecutive, %d total)", n, loss_val,
                    self._nonfinite_consec, self.state["nonfinite_skips"])
                self._summary_resilience(
                    n, nonfinite_skips=self.state["nonfinite_skips"])
                # structured resilience telemetry: an instant trace
                # event per skip (not only the cumulative counter)
                tracer.event("resilience.nonfinite_skip", step=n,
                             loss=loss_val,
                             consecutive=self._nonfinite_consec,
                             total=self.state["nonfinite_skips"])
                obs.get_registry().counter(
                    names.NONFINITE_SKIPS_TOTAL,
                    "Train steps skipped by the non-finite guard").inc()
                if self._nonfinite_consec >= max_nonfinite:
                    raise NonFiniteStepError(
                        f"{self._nonfinite_consec} consecutive non-finite "
                        f"training steps (iter {n}): diverged or poisoned "
                        "input — escalating to the retry policy")
            else:
                self._nonfinite_consec = 0
            if n % 20 == 0:
                log.info(
                    "Epoch %d iter %d loss %.5f (%.1f records/s)",
                    self.state["epoch"], n, loss_val,
                    records_total / max(1e-9, time.time() - wall_start),
                )
                log.debug("Metrics: %s", self.metrics.summary())

        def flush_pending():
            while pending:
                resolve(*pending.pop(0))

        while not stop:
            epoch = self.state["epoch"]
            epoch_start = time.time()
            # background host thread assembles the next minibatch while
            # the chip runs the current step (native.PrefetchIterator)
            from bigdl_tpu.native import PrefetchIterator

            batches = iter(PrefetchIterator(self.dataset.data(train=True)))
            batch_exhausted = False
            # mid-epoch resume (emergency / iteration-trigger
            # checkpoint): the saved neval is this many batches into the
            # epoch — consume them so the replayed data order matches
            # the uninterrupted run exactly (resilience/elastic.py)
            skip, self._pending_fast_forward = \
                self._pending_fast_forward, 0
            if skip > 0:
                log.info("mid-epoch resume: fast-forwarding %d batches "
                         "to iter %d", skip, self.state["neval"])
                tracer.event("elastic.fast_forward", batches=skip,
                             neval=self.state["neval"])
                for _ in range(skip):
                    try:
                        next(batches)
                    except StopIteration:
                        break
            # double-buffer slot: the prefetcher parks the next batch
            # (host arrays + device buffers) here while the current
            # step runs; a discarded staged batch (stop/preemption) is
            # harmless — streams re-read anything yielded-but-untrained
            staged = None
            staged_end = False

            def _prep_and_put(raw_inp, raw_tgt, step_tag):
                """One host batch through prepare + device transfer;
                None = dropped (its stream records are consumed)."""
                with tracer.span("batch_prep", step=step_tag):
                    prepared = self._prepare_batch(raw_inp, raw_tgt)
                if prepared is None:
                    if note_stream is not None:
                        log.warning("dropped a streaming batch at "
                                    "iter %d — its records are "
                                    "consumed, not trained", step_tag)
                        note_stream()
                    return None
                p_inp, p_tgt = prepared
                with self.metrics.timer("put batch time"), \
                        tracer.span("device_put", step=step_tag):
                    inp_d, tgt_d = self._put_batch(p_inp, p_tgt)
                return p_inp, p_tgt, inp_d, tgt_d

            def _prefetch(step_tag):
                """Double-buffer: pull the NEXT batch through the full
                prepare + device_put pipeline while the just-dispatched
                step is still in flight — traced as ``input_prefetch``
                (overlapped host work), never ``data_wait`` badput."""
                nonlocal staged_end
                t_pre = time.perf_counter()
                out = None
                while out is None:
                    try:
                        raw_inp, raw_tgt = next(batches)
                    except StopIteration:
                        staged_end = True
                        break
                    out = _prep_and_put(raw_inp, raw_tgt, step_tag)
                tracer.complete("input_prefetch", t_pre,
                                time.perf_counter() - t_pre,
                                step=step_tag)
                return out

            while True:
                # reference Metrics phases: the fused XLA step folds the
                # collective phases ("put gradient"/"aggregate"/"send
                # weights") into "computing time"; the host-side phases
                # stay separately visible (SURVEY.md §5 Tracing)
                n = self.state["neval"]
                batch = None
                if staged is not None:
                    # the double-buffered batch is already on device:
                    # the loop top pays ~0 input wait
                    batch, staged = staged, None
                    t_wait = time.perf_counter()
                    dt_wait = 0.0
                elif staged_end:
                    batch_exhausted = True
                    break
                else:
                    t_wait = time.perf_counter()
                    try:
                        inp, tgt = next(batches)
                    except StopIteration:
                        batch_exhausted = True
                        break
                    dt_wait = time.perf_counter() - t_wait
                self.metrics.add("data wait time", dt_wait)
                # elastic boundary: heartbeat + peer-liveness check (may
                # raise the classified-fatal PeerLostError BEFORE the
                # collective that would hang on a dead peer) and the
                # preemption flag a SIGTERM set — the in-flight step is
                # resolved, then emergency checkpoint + Preempted
                es = self._elastic_session
                if es is not None and es.on_iteration(n):
                    flush_pending()
                    self._elastic_shutdown(n, pvar, mod_state, opt_state)
                # trace phases mirror the reference Metrics names + the
                # named_scope phases of the jitted step; tracer is the
                # shared no-op object when observability is off
                tracer.complete("data_wait", t_wait, dt_wait, step=n)
                ledger.record("data_wait", t_wait, dt_wait, step=n)
                # child spans carry the step too: the slow-step detector
                # and the merged cross-host timeline both key on it
                with tracer.span("iteration", step=n):
                    if batch is not None:
                        # double-buffered: prepared + transferred while
                        # the previous step was in flight
                        inp, tgt, inp_d, tgt_d = batch
                    else:
                        with tracer.span("batch_prep", step=n):
                            prepared = self._prepare_batch(inp, tgt)
                        if prepared is None:
                            if note_stream is not None:
                                # a dropped batch still consumed its
                                # stream records: advance the frontier so
                                # the meta queue stays aligned (and say so
                                # — dropping stream records is a
                                # configuration smell)
                                log.warning("dropped a streaming batch at "
                                            "iter %d — its records are "
                                            "consumed, not trained", n)
                                note_stream()
                            continue  # dropped (e.g. sub-mesh partial batch)
                        inp, tgt = prepared
                        if self._fault_injector is not None:
                            # chaos hook: may raise InjectedFault
                            # (transient) or poison this batch to exercise
                            # the non-finite guard
                            action = self._fault_injector.on_step(n)
                            if action == "nan_grad":
                                inp = self._fault_injector.poison_batch(inp)
                        with self.metrics.timer("put batch time"), \
                                tracer.span("device_put", step=n):
                            inp_d, tgt_d = self._put_batch(inp, tgt)
                    profiler.step()
                    rng = jax.random.fold_in(base_key, n)
                    t0 = time.perf_counter()
                    # driver-side prep (batch_prep + device_put + rng
                    # fold) feeds the host_bound share of the window
                    # classifier; in pipelined steady state it overlaps
                    # device compute, so it is a share — not a cause
                    ledger.note_host_seconds(t0 - t_wait - dt_wait)
                    with tracer.span("step_dispatch", step=n):
                        out = train_step(
                            pvar, opt_state, mod_state, rng, inp_d, tgt_d
                        )
                    # health-enabled steps carry one extra output (the
                    # per-layer stats array); disabled steps keep the
                    # seed 5-tuple signature
                    pvar, opt_state, mod_state, loss, ok = out[:5]
                    health_dev = out[5] if monitor is not None else None
                    bs = np.asarray(inp).shape[0]
                    records_total += bs
                    if note_stream is not None:
                        note_stream()
                    if double_buffer and not staged_end:
                        # overlap the NEXT batch's fetch/prepare/
                        # device_put with the in-flight device step —
                        # this is the double-buffer: by the time the
                        # loop comes back around, the input is on device
                        staged = _prefetch(n + 1)
                    if sync_per_step:
                        resolve(n, loss, ok, bs, t0, health_dev)
                    else:
                        # the step is dispatched; reading back the
                        # PREVIOUS loss now lets the device run two-deep
                        flush_pending()
                        pending.append((n, loss, ok, bs, t0, health_dev))
                    if self.train_summary is not None:
                        # histograms stay on the synchronous path: pvar
                        # here IS step n's output and neval is still n,
                        # so the trigger timing and logged params match
                        # sync mode exactly (reference
                        # setSummaryTrigger("Parameters"))
                        ptrig = self.train_summary.get_summary_trigger(
                            "Parameters")
                        if ptrig is not None and ptrig(self.state):
                            self._write_param_histograms(pvar, n)
                    self.state["neval"] = n + 1
                    opt.state = opt_state
                    if self.validation_trigger is not None and \
                            self.validation_trigger(self.state):
                        flush_pending()
                        # device-resident params: no host weight copy per
                        # validation trigger (VERDICT r2 #3)
                        t_eval = time.perf_counter()
                        with tracer.span("validation", step=n):
                            self._run_validation(pvar, mod_state)
                        ledger.record("eval", t_eval,
                                      time.perf_counter() - t_eval,
                                      step=n)
                        model.training()
                    if self.checkpoint_trigger is not None and \
                            self.checkpoint_trigger(self.state):
                        flush_pending()
                        with tracer.span("checkpoint", step=n):
                            with self.metrics.timer("write back time"):
                                self._write_back(pvar, mod_state)
                            opt.state = opt_state
                            self._checkpoint()
                    if self.end_when(self.state):
                        stop = True
                        break
            flush_pending()
            if batch_exhausted and not stop:
                # epoch finished
                self.state["epoch_finished"] = epoch
                self.state["epoch"] = epoch + 1
                # the next epoch's first batch runs at the current neval
                # (mid-epoch-resume bookkeeping, checkpointed in extra)
                self.state["epoch_neval0"] = self.state["neval"]
                # in place: opt.state must stay the SAME dict object so a
                # Plateau lr_scale poke from the validation below is seen
                # by the next epoch's train_step
                opt_state["epoch"] = opt_state["epoch"] + 1.0
                opt.state = opt_state
                log.info(
                    "Epoch %d done in %.1fs", epoch, time.time() - epoch_start
                )
                # reference: per-phase Metrics averages logged every epoch
                # («bigdl»/optim/Metrics.scala; SURVEY.md §5 Tracing)
                log.info("Metrics: %s", self.metrics.summary())
                if self.validation_trigger is not None and self.validation_trigger(
                    self.state
                ):
                    t_eval = time.perf_counter()
                    with tracer.span("validation", epoch=epoch):
                        self._run_validation(pvar, mod_state)
                    ledger.record("eval", t_eval,
                                  time.perf_counter() - t_eval)
                    model.training()
                if self.checkpoint_trigger is not None and self.checkpoint_trigger(
                    self.state
                ):
                    with tracer.span("checkpoint", epoch=epoch):
                        self._write_back(pvar, mod_state)
                        opt.state = opt_state
                        self._checkpoint()
                if self.end_when(self.state):
                    stop = True
        flush_pending()
        self._write_back(pvar, mod_state)
        opt.state = opt_state
        self.model.evaluate()
        # normal completion: surface any background-checkpoint write
        # error to the caller instead of just logging it
        self._flush_checkpoints()
        return self.model

    def _write_back(self, pvar, mod_state):
        # copy: the next train_step donates pvar/mod_state buffers, and the
        # model must keep valid arrays (validation/checkpoint read them, and
        # the user may hold the model across an interrupted optimize())
        import jax

        jnp = _jnp()
        copy = lambda t: jax.tree.map(lambda a: jnp.array(a, copy=True), t)
        self.model.set_params(copy(pvar))
        self.model.set_state(copy(mod_state))

    def _write_param_histograms(self, pvar, step):
        """Per-layer weight histograms into the TrainSummary (reference:
        TrainSummary with the "Parameters" trigger set)."""
        import jax

        tree = self._params_tree(pvar)
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        for path, leaf in flat:
            tag = "/".join(
                str(getattr(k, "key", getattr(k, "idx", k))) for k in path
            )
            self.train_summary.add_histogram(tag, np.asarray(leaf), step)


def Optimizer(
    model=None,
    training_set=None,
    criterion=None,
    batch_size: int = 32,
    training_rdd=None,
    x=None,
    y=None,
    end_trigger=None,
    optim_method=None,
    distributed: Optional[bool] = None,
):
    """Factory (reference: Optimizer.apply dispatches Local vs Distri on
    the dataset type — SURVEY.md §3.2).  Here: a DistributedDataSet or a
    multi-device default mesh selects DistriOptimizer."""
    import jax

    from bigdl_tpu.dataset import DistributedDataSet, to_dataset

    data = training_set if training_set is not None else training_rdd
    if data is None and x is not None:
        data = (x, y)
    ds = to_dataset(data, batch_size)
    if distributed is None:
        distributed = isinstance(ds, DistributedDataSet) or len(jax.devices()) > 1
        if distributed and not isinstance(ds, DistributedDataSet):
            # auto-promotion on device count alone can surprise on dev
            # boxes with forced host devices — say so (the reference
            # dispatches on dataset type only)
            log.warning(
                "Optimizer: %d devices visible — auto-selecting "
                "DistriOptimizer; pass distributed=False (or a local "
                "dataset on one device) for LocalOptimizer",
                len(jax.devices()),
            )
    if distributed:
        from bigdl_tpu.optim.distri_optimizer import DistriOptimizer

        opt = DistriOptimizer(model, ds, criterion, batch_size)
    else:
        opt = LocalOptimizer(model, ds, criterion, batch_size)
    if optim_method is not None:
        opt.set_optim_method(optim_method)
    if end_trigger is not None:
        opt.set_end_when(end_trigger)
    return opt
