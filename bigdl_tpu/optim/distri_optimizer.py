"""DistriOptimizer — THE distributed trainer.

Rebuild of «bigdl»/optim/DistriOptimizer.scala + «bigdl»/parameters/
AllReduceParameter.scala (SURVEY.md §3.2, §2.5).

Reference data plane, per iteration (one Spark job):

    putGradients:   local flat gradient split into numPartition FP16
                    blocks pushed to slice owners via BlockManager
    aggregate:      owner sums its incoming blocks, /= numSamples,
                    clipping processors, optimMethod on the owned slice
    sendWeight:     owner publishes its updated weight slice
    getWeights:     every worker prefetches all slices next iteration

That push-to-owner / pull-from-owner pattern **is literally
reduce-scatter + all-gather** over a flat parameter vector with the
optimizer state sharded by owner (ZeRO-1 before the name).  The
TPU-native rebuild says exactly that, inside one jitted ``shard_map``
over the ``data`` mesh axis:

    grads  = vjp(local sub-batch)            # per-chip compute
    gshard = psum_scatter(flat(grads))       # "putGradients+aggregate"
    gshard /= global_batch; clip             # ParameterProcessors
    wshard, ostate = optim.step(gshard, wshard, ostate)   # owner update
    weights = all_gather(wshard)             # "sendWeight+getWeights"

The Spark job-per-iteration barrier becomes the implicit synchrony of the
jitted step; FP16 wire compression maps to an optional bf16 cast before
the reduce-scatter (native on TPU ICI), or to the stronger
``wire_dtype="int8"`` blockwise-quantized exchange (int8 payload +
per-block f32 scales through one all_to_all pair, f32 accumulation —
EQuARX-style, half the bf16 bytes).  The same step compiles for a
multi-host DCN+ICI mesh — XLA picks the collective implementation.
"""

from __future__ import annotations

import numpy as np

from bigdl_tpu.optim.optimizer import BaseOptimizer, LocalOptimizer
from bigdl_tpu.obs import names


def _jnp():
    import jax.numpy as jnp

    return jnp


def _shard_map(f, mesh, in_specs, out_specs):
    """Version-tolerant shard_map.  Replication checking is disabled:
    the gathered weight vector is replicated by construction
    (all_gather), which the static vma checker cannot infer."""
    import jax

    if hasattr(jax, "shard_map"):
        try:
            return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False)
        except TypeError:
            return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as sm

    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              check_rep=False)


def int8_blockwise_reduce_scatter(g, axis, n, block):
    """Quantized reduce-scatter (inside shard_map): ``g`` is the local
    flat gradient, length divisible by ``n * block``.

    Round 5 shipped this as a quantize-once / all_to_all / dequantize
    exchange; it is now the int8 face of the staged ring in
    ``parallel/wire.py`` — the partial sum for each chunk rides the
    ring ``n-1`` hops, re-quantized per hop (payload + f32 scales on
    the wire) with f32 accumulation, so the compression applies inside
    the reduction stages themselves (EQuARX, arXiv:2506.17615).  Same
    wire bytes as the a2a shape; the blockwise scale still bounds each
    hop's element error by its block's max/254."""
    from bigdl_tpu.parallel import wire

    out, _ = wire.reduce_scatter(
        g, axis, n, wire.WireSpec("int8", block=block))
    return out


class DistriOptimizer(LocalOptimizer):
    """Synchronous data-parallel trainer with ZeRO-1 sharded updates."""

    def __init__(self, model, dataset, criterion, batch_size=32, mesh=None,
                 wire_dtype=None, data_axes=None, int8_block=None,
                 wire_block=None, wire_ef=None, overlap_bucket_mb=None):
        super().__init__(model, dataset, criterion, batch_size)
        from bigdl_tpu.engine import Engine
        from bigdl_tpu.parallel import wire as W

        if mesh is None:
            if not Engine.is_initialized():
                Engine.init()
            mesh = Engine.mesh()
        self.mesh = mesh
        # hierarchical data parallelism (multi-slice): pass
        # data_axes=("dcn", "data") over a 2-level mesh and the batch /
        # flat-parameter shards split over BOTH axes — XLA then builds
        # the hierarchical collective (reduce-scatter inside each ICI
        # slice, cross-slice exchange over DCN) from the axis order
        self.axes = tuple(data_axes) if data_axes else (mesh.axis_names[0],)
        for a in self.axes:
            if a not in mesh.axis_names:
                raise ValueError(f"data axis {a!r} not in mesh axes "
                                 f"{mesh.axis_names}")
        self.axis = self.axes if len(self.axes) > 1 else self.axes[0]
        self.n_shards = 1
        for a in self.axes:
            self.n_shards *= mesh.shape[a]
        # reference: FP16CompressedTensor on-the-wire compression for
        # gradient blocks; bf16 is the TPU-native equivalent, int8 /
        # fp8 the blockwise-quantized EQuARX-style staged-ring options
        # (parallel/wire.py).  Unset knobs fall back to config
        # (BIGDL_WIRE_DTYPE / BIGDL_WIRE_BLOCK / BIGDL_WIRE_EF).
        from bigdl_tpu.config import config

        if wire_dtype is None:
            wire_dtype = config.wire.dtype
        if wire_dtype not in W.WIRE_DTYPES and \
                wire_dtype not in W.UNCOMPRESSED:
            # an unknown spelling must not silently train uncompressed
            raise ValueError(
                f"wire_dtype {wire_dtype!r} not supported; choose "
                "'bfloat16', 'int8', 'fp8_e4m3', 'fp8_e5m2', 'float32' "
                "or 'none'")
        self.wire_dtype = wire_dtype
        block = wire_block if wire_block is not None else int8_block
        if block is not None and int(block) < 1:
            raise ValueError(
                f"wire_block/int8_block must be positive, got {block}")
        if wire_dtype in W.WIRE_DTYPES:
            spec = W.WireSpec.from_config(
                dtype=wire_dtype, block=block, error_feedback=wire_ef)
        else:
            if wire_ef:
                raise ValueError(
                    "error feedback needs a compressed wire dtype "
                    f"(got {wire_dtype!r})")
            spec = None
        self.wire = spec
        # legacy spelling: the int8 wire's block knob names the block
        # for every scaled dtype
        self.int8_block = spec.block if spec is not None else \
            int(block) if block is not None else config.wire.block
        # the staged ring (scaled dtypes, or any EF wire) runs over ONE
        # ring; plain bf16 keeps the native psum_scatter, which XLA
        # lowers hierarchically
        self._staged_ring = spec is not None and (spec.scaled
                                                  or spec.error_feedback)
        if self._staged_ring and len(self.axes) > 1:
            raise NotImplementedError(
                f"the {wire_dtype!r} staged-ring wire over hierarchical "
                "data axes is not supported; use a single data axis or "
                "bfloat16")
        # bucketed comm/compute overlap (ISSUE 11): the gradient
        # exchange is split into ~bucket_mb MiB buckets launched
        # last-layer-first, so each bucket's reduce-scatter rides under
        # the remaining backward; <= 0 keeps the monolithic exchange.
        # The plan is derived lazily against the padded layout in
        # _init_opt_state (it needs the alignment quantum).
        if overlap_bucket_mb is None:
            overlap_bucket_mb = config.overlap_bucket_mb
        self.overlap_bucket_mb = float(overlap_bucket_mb)
        self._buckets = None
        self._pad = 0
        self._warned_batch_sizes = set()
        self._host_mask = None
        self._device_mask = None

    # ------------------------------------------------------------ sharding
    def _init_params(self):
        """The ZeRO-1 data plane works on the flat parameter vector (the
        reference's AllReduceParameter flat layout); keep the unravel
        closure for write-back."""
        from jax.flatten_util import ravel_pytree

        flat, unravel = ravel_pytree(self.model.params())
        self._unravel = unravel
        # static shape metadata for the collective byte footprint —
        # host-side ints, no device read
        self._flat_elems = int(flat.size)
        self._flat_dtype = str(flat.dtype)
        return flat

    def _params_tree(self, pvar):
        # unravel on device: the flat ZeRO vector -> params pytree with
        # no host round-trip (the unravel closure is a pure jax fn)
        return self._unravel(pvar)

    def _topology(self):
        """Checkpoint topology tag: the flat ZeRO-1 layout plus the
        world size and padding it was written under, so restore at a
        different world knows exactly what to strip and re-pad
        (resilience/elastic.py ensure_shard_layout)."""
        topo = {"world_size": self.n_shards,
                "shard_layout": "zero1_flat",
                "step": self.state["neval"],
                "flat_elems": getattr(self, "_flat_elems", None),
                "pad": self._pad,
                # the wire the run trained under — a resize-resume can
                # see whether an EF residual rides the optimizer state
                # without opening the npz
                "wire": {"dtype": self.wire_dtype,
                         "block": self.int8_block,
                         "ef": bool(self.wire is not None
                                    and self.wire.error_feedback)}}
        # overlapped runs leave the ZeRO-1 state vectors in the
        # bucketed shard-major layout — the manifest must carry the
        # plan so a resume at a different plan/world can re-permute
        # (resilience/elastic.ensure_shard_layout); single-bucket runs
        # omit the key (parameter-major, the historical layout)
        if self._buckets is not None and len(self._buckets) > 1:
            topo["buckets"] = [[s, z] for s, z in self._buckets]
        return topo

    def _write_back(self, pvar, mod_state):
        # unravel allocates fresh arrays; mod_state is copied so the model
        # never aliases buffers the donated step will delete
        import jax

        jnp = _jnp()
        self.model.set_params(self._unravel(pvar))
        self.model.set_state(
            jax.tree.map(lambda a: jnp.array(a, copy=True), mod_state)
        )

    def _init_opt_state(self, flat):
        """Optimizer state lives only on the owner shard (reference:
        «bigdl»/parameters/AllReduceParameter.scala — "optimizer state
        lives only there")."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        jnp = _jnp()
        n = self.n_shards
        # scaled wires (int8/fp8) need whole quantization blocks per
        # shard; everything else just whole shards
        quantum = n * self.int8_block \
            if (self.wire is not None and self.wire.scaled) else n
        self._pad = (-flat.size) % quantum
        shard_len = (flat.size + self._pad) // n
        # bucketed overlap plan (parallel/wire.py): contiguous quantum-
        # aligned slices of the padded flat layout, each ~bucket_mb MiB
        # of gradient; the step launches one exchange per bucket,
        # last-layer-first.  Summed wire bytes equal the monolithic
        # exchange exactly (every bucket is whole quanta).
        from bigdl_tpu.parallel import wire as _W

        itemsize = max(1, np.dtype(self._flat_dtype).itemsize) \
            if getattr(self, "_flat_dtype", None) else 4
        target = int(self.overlap_bucket_mb * (1 << 20) / itemsize) \
            if self.overlap_bucket_mb > 0 else 0
        self._buckets = _W.plan_buckets(flat.size + self._pad, quantum,
                                        target)
        opt = self.optim_method
        if opt.state is not None:
            # guard against an OptimMethod whose state was built by
            # LocalOptimizer (nested pytree slots) — the ZeRO data plane
            # needs flat shard-shaped state
            for v in opt.state.values():
                if not hasattr(v, "ndim"):
                    raise ValueError(
                        "optim_method.state was initialised for tree "
                        "parameters (LocalOptimizer); reset it (state=None) "
                        "before reusing the method with DistriOptimizer"
                    )
            # topology-aware resume (resilience/elastic.py): state
            # restored from a checkpoint written at a different world
            # size carries the OLD padded length — strip the old
            # alignment padding, re-pad for this mesh's quantum, and
            # re-place P(axis); same-world resumes pass through
            from bigdl_tpu.resilience import elastic

            opt.state = elastic.ensure_shard_layout(
                opt.state, flat_elems=int(flat.size), pad=self._pad,
                n_shards=n, mesh=self.mesh, axis=self.axis,
                topology=getattr(opt, "loaded_topology", None),
                buckets=self._buckets)
        if opt.state is None:
            # build state against a single shard-sized template, then
            # expand vector entries across the mesh
            template = jnp.zeros((shard_len,), flat.dtype)
            local = opt.init_state(template)
            sharded = {}
            for k, v in local.items():
                if v.ndim == 1 and v.shape[0] == shard_len:
                    full = jnp.tile(v, n)
                    sharded[k] = jax.device_put(
                        full, NamedSharding(self.mesh, P(self.axis))  # noqa: E501  (tuple spec shards over all data axes)
                    )
                else:
                    sharded[k] = jax.device_put(
                        v, NamedSharding(self.mesh, P())
                    )
            opt.state = sharded
        # error-feedback residual (parallel/wire.py): one f32 row per
        # device in flat-parameter coordinates, sharded so each device
        # owns exactly its own row.  Lives in the optimizer state so it
        # rides checkpoints with the flat ZeRO-1 vectors and is re-laid
        # -out by elastic.ensure_shard_layout on world resize (a
        # checkpointed residual from a DIFFERENT world is reset to
        # zeros there — safe: it is a correction term, not state the
        # update depends on).
        padded = flat.size + self._pad
        if self.wire is not None and self.wire.error_feedback:
            ef = opt.state.get("wire_ef")
            if ef is None or tuple(ef.shape) != (n, padded):
                opt.state["wire_ef"] = jax.device_put(
                    jnp.zeros((n, padded), jnp.float32),
                    NamedSharding(self.mesh, P(self.axis, None)))
        else:
            # resumed without EF: drop a checkpointed residual instead
            # of threading dead state through the step
            opt.state.pop("wire_ef", None)
        # stamp the method with the layout its state is NOW in: a later
        # re-init (second optimize(), a bucket-plan or world change)
        # then re-partitions from accurate provenance instead of a
        # stale checkpoint tag — with the bucketed shard-major layout,
        # "what order are these vectors in" is no longer answerable
        # from their length alone
        opt.loaded_topology = self._topology()
        return opt.state

    def _collective_byte_footprint(self):
        """The static wire-byte budget of one standard train step —
        every collective ``sharded_step`` programs, costed from shapes
        the driver already holds (obs/collectives.py cost model; no
        device reads, no extra syncs).  Publishes the per-step gauges +
        the int8-vs-f32 savings-ratio gauge and returns the bound
        footprint the driver loop commits per resolved step."""
        import jax

        from bigdl_tpu import obs
        from bigdl_tpu.config import config
        from bigdl_tpu.obs import collectives as C

        n = self.n_shards
        padded = self._flat_elems + self._pad
        pdtype = self._flat_dtype
        fp = C.StepFootprint()
        # ---- putGradients + aggregate: the gradient exchange ---------
        if self._staged_ring:
            ex = C.staged_ring_exchange_bytes(
                padded, n, self.int8_block, self.wire.wire_name)
            exchange = 0.0
            for name, b in ex.items():
                fp.add("ring_rs", name, b)
                exchange += b
        else:
            wire = {"bfloat16": "bfloat16", "float32": "float32"}.get(
                self.wire_dtype, pdtype)  # "none" ships the grad dtype
            exchange = C.reduce_scatter_bytes(padded, wire, n)
            fp.add("psum_scatter", wire, exchange)
        # global-norm psum on the sharded gradient (always computed)
        fp.add("psum", "float32", C.all_reduce_bytes(1, "float32", n))
        if config.nonfinite_guard:
            fp.add("pmin", "float32", C.all_reduce_bytes(1, "float32", n))
        if self._health_monitor is not None:
            # the (L, 4) per-layer health-stats psum (obs/health.py)
            n_layers = len(self._health_monitor.names)
            fp.add("psum", "float32",
                   C.all_reduce_bytes(n_layers * 4, "float32", n))
        # loss pmean/psum (scalar, f32 either way)
        fp.add("pmean", "float32", C.all_reduce_bytes(1, "float32", n))
        # sendWeight + getWeights: the full padded vector comes back
        fp.add("all_gather", pdtype, C.all_gather_bytes(padded, pdtype, n))
        # BN running stats pmean (floating model-state leaves)
        import jax.numpy as jnp

        for leaf in jax.tree.leaves(self.model.state()):
            if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype,
                                                         jnp.floating):
                fp.add("pmean", str(leaf.dtype),
                       C.all_reduce_bytes(int(leaf.size), leaf.dtype, n))
        fp.bind(obs.get_registry())
        # the goodput window classifier estimates comm seconds from the
        # same static budget (obs/goodput.py, BIGDL_WIRE_GBPS)
        self._obs_ledger.set_comm_bytes_per_step(fp.total())
        # overlap accounting (ISSUE 11): with K buckets, the first K-1
        # exchanges (in launch order) ride under the remaining backward
        # — only the final bucket's exchange (plus the gathers/psums the
        # update chain serializes on) is EXPOSED wall time.  The ledger
        # classifies comm_bound from the exposed bytes; the gauges make
        # the overlap itself observable (obs/report.py "overlap" block,
        # the exposed_comm_high alert rule).
        n_buckets = len(self._buckets) if self._buckets else 1
        registry = obs.get_registry()
        registry.gauge(
            names.OVERLAP_BUCKETS,
            "Gradient-exchange buckets of the overlapped step "
            "(1 = monolithic, no overlap)").set(float(n_buckets))
        if n_buckets > 1:
            hidden = exchange * (n_buckets - 1) / n_buckets
            exposed = fp.total() - hidden
            self._obs_ledger.set_exposed_comm_bytes_per_step(exposed)
            registry.gauge(
                names.OVERLAP_EXPOSED_COMM_FRACTION,
                "Share of the per-step collective bytes NOT hidden "
                "under backward by the bucketed exchange").set(
                round(exposed / fp.total(), 6) if fp.total() else 0.0)
            if config.obs.wire_gbps > 0:
                registry.gauge(
                    names.OVERLAP_EXPOSED_COMM_SECONDS,
                    "Estimated per-step collective seconds not hidden "
                    "by backward (exposed bytes / BIGDL_WIRE_GBPS)").set(
                    exposed / (config.obs.wire_gbps * 1e9))
        else:
            self._obs_ledger.set_exposed_comm_bytes_per_step(None)
        # the EQuARX argument as a gauge: f32 exchange bytes over what
        # the configured wire actually ships, on the gradient path
        f32_exchange = C.reduce_scatter_bytes(padded, "float32", n)
        ratio = C.record_savings("grad", f32_exchange, exchange,
                                 registry=obs.get_registry())
        tracer = obs.get_tracer()
        if tracer.enabled:
            tracer.event("collective.footprint",
                         wire_dtype=self.wire_dtype, n_shards=n,
                         padded_elems=padded,
                         bytes_per_step=round(fp.total(), 1),
                         savings_ratio=round(ratio, 4),
                         breakdown={k: round(v, 1)
                                    for k, v in fp.by_op().items()})
        return fp

    def _build_train_step(self):
        """Returns a dispatcher: full batches run the plain compiled
        step; a padded final batch (``_prepare_batch`` set a mask) runs
        a lazily-built masked variant whose gradient divides by the
        VALID sample count — the reference's SampleToMiniBatch padding
        semantics (VERDICT r3 weak #7), so the loss trajectory matches
        an unpadded single-device run exactly (modulo BN batch stats,
        which see the pad copies — same as the reference's padding)."""
        self._plain_step = self._build_step_impl(masked=False)
        self._masked_step = None
        # the masked final-batch variant adds only one scalar psum
        # (valid count) on top of this; the standard step's budget is
        # the per-step account
        self._collective_footprint = self._collective_byte_footprint()

        def dispatch(pvar, opt_state, mod_state, rng, inp, tgt):
            mask = self._device_mask
            if mask is None:
                return self._plain_step(pvar, opt_state, mod_state, rng,
                                        inp, tgt)
            if self._masked_step is None:
                self._masked_step = self._build_step_impl(masked=True)
            return self._masked_step(pvar, opt_state, mod_state, rng,
                                     inp, tgt, mask)

        return dispatch

    def _build_step_impl(self, masked: bool):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from bigdl_tpu.config import config

        jnp = _jnp()
        guard = config.nonfinite_guard
        opt = self.optim_method
        clipper = self._clipper
        loss_fn = self._loss_fn(masked=masked)
        n = self.n_shards
        axis = self.axis
        pad = self._pad
        wire = {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
                "none": None}.get(self.wire_dtype, None)
        wire_spec = self.wire
        staged_ring = self._staged_ring
        ef_on = wire_spec is not None and wire_spec.error_feedback
        global_batch = self.batch_size
        # overlap plan (ISSUE 11): contiguous quantum-aligned buckets of
        # the padded flat layout; one exchange per bucket, emitted
        # last-layer-first so each bucket's wire launches under the
        # remaining backward.  One bucket = the monolithic exchange.
        buckets = [(int(s), int(z)) for s, z in self._buckets]
        # per-layer health telemetry on the ZeRO shard (obs/health.py):
        # layer boundaries in the ravelled layout — each device
        # segment-sums its shard's contribution and ONE (L, 4) psum
        # makes every host's stats global
        health_on = self._health_monitor is not None
        boundaries = None
        if health_on:
            from bigdl_tpu.obs import health as H

            boundaries = jnp.asarray(
                np.cumsum(H.layer_sizes(self.model.params())), jnp.int32)
        # freeze support on the flat ZeRO vector.  VERDICT r4 weak #5:
        # do NOT embed a flat-param-sized f32 mask as a jit constant
        # (plus a second padded copy for the shard slice) — that doubles
        # HBM for the mask alone at large scale.  Frozen leaves occupy
        # contiguous ranges of the ravelled vector (ravel_pytree
        # concatenates in tree.leaves order), so record merged
        # (start, end) intervals host-side and rebuild any piece of the
        # mask on the fly from iota comparisons: O(#frozen-runs) cheap
        # vector ops, no O(n) constants.
        frozen_intervals = None
        if self.model.has_frozen():
            import jax as _jax

            sizes = [int(np.size(x))
                     for x in _jax.tree.leaves(self.model.params())]
            keeps = [float(x)
                     for x in _jax.tree.leaves(self.model.grad_mask())]
            if len(sizes) != len(keeps):  # tree.map used to raise here
                raise ValueError(
                    f"grad_mask leaves ({len(keeps)}) do not match "
                    f"params leaves ({len(sizes)})")
            frozen_intervals = []
            off = 0
            for sz, keep in zip(sizes, keeps):
                if keep == 0.0 and sz:
                    if frozen_intervals and frozen_intervals[-1][1] == off:
                        frozen_intervals[-1][1] = off + sz  # merge run
                    else:
                        frozen_intervals.append([off, off + sz])
                off += sz
            if off + pad >= 2 ** 31:
                # the on-the-fly mask addresses flat positions with an
                # int32 iota; past 2^31 elements it would wrap silently
                raise NotImplementedError(
                    "frozen-parameter masking indexes the ravelled "
                    f"vector with int32 ({off} params + {pad} pad "
                    ">= 2^31); shard the model (tensor parallelism) "
                    "or enable jax_enable_x64")

        def _keep_mask(offset, length, dtype):
            """1.0 where trainable, 0.0 inside a frozen interval, for
            flat positions [offset, offset+length) — offset may be a
            traced shard index."""
            idx = jax.lax.iota(jnp.int32, length) + offset
            m = jnp.ones((length,), dtype)
            for s, e in frozen_intervals:
                m = m * (1.0 - ((idx >= s) & (idx < e)).astype(dtype))
            return m

        def sharded_step(flat_p, opt_st, mstate, rng, inp, tgt, mask=None):
            # named_scopes carry the reference's Metrics phase names into
            # profiler traces / HLO metadata (SURVEY.md §5 Tracing)
            with jax.named_scope("computing"):
                # ---- local replica compute (per-core fwd/bwd) -----------
                args = (flat_p, mstate, rng, inp, tgt) + (
                    (mask,) if masked else ())
                (_, (loss_aux, new_mstate)), grad = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(*args)
                if frozen_intervals is not None:
                    grad = grad * _keep_mask(0, grad.shape[0], grad.dtype)
            with jax.named_scope("put_gradient"):
                # ---- putGradients + aggregateGradientPartition ----------
                # one exchange per overlap bucket, emitted last-layer-
                # first: the ravel layout is first-layer-first and the
                # backward resolves the LAST layers' gradients first, so
                # the highest-offset bucket's wire can start while the
                # rest of the backward is still running.  This device
                # ends up owning its slice of EVERY bucket (the shard-
                # major layout _topology records); one bucket reproduces
                # the monolithic exchange exactly.
                g = jnp.pad(grad, (0, pad))
                new_ef = None
                pieces = [None] * len(buckets)
                if staged_ring:
                    from bigdl_tpu.parallel import wire as W

                    # in-reduce quantization (parallel/wire.py): the
                    # partial sums ride the ring re-quantized per hop,
                    # accumulated in f32; with EF on, this device's
                    # residual rows (flat-parameter coords) ride along
                    # per bucket and come back updated
                    ef = opt_st.get("wire_ef")
                    ef_flat = None if ef is None else ef.reshape(-1)
                    ef_pieces = [None] * len(buckets)
                    for b in reversed(range(len(buckets))):
                        s, z = buckets[b]
                        ef_b = None if ef_flat is None else \
                            jax.lax.slice_in_dim(
                                ef_flat, s, s + z).reshape(n, z // n)
                        pieces[b], ef_pieces[b] = W.reduce_scatter(
                            jax.lax.slice_in_dim(g, s, s + z), axis, n,
                            wire_spec, ef=ef_b)
                    if ef_flat is not None:
                        # per-bucket rows flatten back to flat-parameter
                        # coords; ascending concat rebuilds the full row
                        new_ef = ef_pieces[0] if len(ef_pieces) == 1 \
                            else jnp.concatenate(
                                [e.reshape(-1) for e in ef_pieces])
                else:
                    if wire is not None and wire != g.dtype:
                        g = g.astype(wire)
                    for b in reversed(range(len(buckets))):
                        s, z = buckets[b]
                        pieces[b] = jax.lax.psum_scatter(
                            jax.lax.slice_in_dim(g, s, s + z), axis,
                            scatter_dimension=0, tiled=True)
                gshard = pieces[0] if len(pieces) == 1 \
                    else jnp.concatenate(pieces)
            with jax.named_scope("aggregate_gradient"):
                gshard = gshard.astype(flat_p.dtype)
                # reference: gradient /= numSamples — the global batch,
                # or the global VALID count under final-batch padding
                if masked:
                    valid = jax.lax.psum(jnp.sum(mask), axis)
                    gshard = gshard / valid
                else:
                    gshard = gshard / global_batch
                # ParameterProcessors on the *sharded* gradient, with the
                # global norm via psum — matching L2NormClippingProcessor
                sq = jax.lax.psum(jnp.sum(gshard * gshard), axis)
                # health stats see the batch-scaled, pre-clip gradient
                # (clipping hides exactly the explosions the telemetry
                # exists to show)
                g_for_health = gshard if health_on else None
                gshard = clipper(gshard, global_sq_norm=sq)
            if guard:
                # non-finite step guard: every replica must agree to
                # skip or the all_gathered weights diverge — pmin of the
                # local shard's finiteness is the global verdict
                ok_local = jnp.all(jnp.isfinite(gshard)) \
                    & jnp.isfinite(loss_aux)
                ok = jax.lax.pmin(
                    ok_local.astype(jnp.float32), axis) > 0
            else:
                ok = jnp.array(True)
            with jax.named_scope("optimizer_update"):
                # ---- owner-slice weight update (ZeRO-1) -----------------
                if isinstance(axis, tuple):
                    # combined owner index over hierarchical data axes,
                    # major-to-minor in axis order (matches the
                    # P(axes)-tuple shard layout psum_scatter produces)
                    idx = jax.lax.axis_index(axis[0])
                    for a in axis[1:]:
                        idx = idx * self.mesh.shape[a] \
                            + jax.lax.axis_index(a)
                else:
                    idx = jax.lax.axis_index(axis)
                shard_len = (flat_p.size + pad) // n
                padded_p = jnp.pad(flat_p, (0, pad))
                if len(buckets) == 1:
                    wshard = jax.lax.dynamic_slice(
                        padded_p, (idx * shard_len,), (shard_len,))
                else:
                    # bucketed ownership: this device's chunk of every
                    # bucket, ascending — element-aligned with gshard
                    wshard = jnp.concatenate([
                        jax.lax.dynamic_slice(
                            padded_p, (s + idx * (z // n),), (z // n,))
                        for s, z in buckets])
                # the EF residual is wire state, not optimizer state —
                # the method never sees it; it re-enters the state dict
                # updated by the staged ring above
                opt_in = {k: v for k, v in opt_st.items()
                          if k != "wire_ef"} if ef_on else opt_st
                new_wshard, new_opt = opt.step(gshard, wshard, opt_in)
                if ef_on:
                    new_opt = dict(new_opt)
                    new_opt["wire_ef"] = (
                        new_ef.reshape(opt_st["wire_ef"].shape)
                        if new_ef is not None else opt_st["wire_ef"])
                if guard:
                    # skipped step: owner shard and opt state pass
                    # through unchanged (graceful degradation — the
                    # driver counts the skip and may escalate)
                    new_wshard = jnp.where(ok, new_wshard, wshard)
                    new_opt = jax.tree.map(
                        lambda a, b: jnp.where(ok, a, b)
                        if hasattr(a, "dtype") else a,
                        new_opt, opt_st)
                if frozen_intervals is not None:
                    # mask the UPDATE as well as the gradient: optimizer
                    # -internal weight decay adds wd*p past the zeroed
                    # gradient — frozen parameters must not move at all.
                    # Padding positions (flat idx >= true size) fall in
                    # no frozen interval, so the tail mask is 1 — the
                    # padded lanes are discarded by the final slice.
                    if len(buckets) == 1:
                        mshard = _keep_mask(idx * shard_len, shard_len,
                                            wshard.dtype)
                    else:
                        mshard = jnp.concatenate([
                            _keep_mask(s + idx * (z // n), z // n,
                                       wshard.dtype)
                            for s, z in buckets])
                    new_wshard = wshard + mshard * (new_wshard - wshard)
                if health_on:
                    from bigdl_tpu.obs import health as H

                    # (L, 4) global per-layer stats: new_wshard is
                    # post-guard/post-freeze, so a skipped step reports
                    # a zero update; nonfinite counts come from the
                    # summed pre-clip gradient.  Bucketed shards are not
                    # contiguous in flat coords — hand the per-position
                    # coordinates over explicitly.
                    positions = None
                    if len(buckets) > 1:
                        positions = jnp.concatenate([
                            jax.lax.iota(jnp.int32, z // n)
                            + (s + idx * (z // n))
                            for s, z in buckets])
                    health_stats = H.flat_shard_stats(
                        g_for_health, wshard, new_wshard,
                        idx * shard_len, boundaries, axis,
                        positions=positions)
            with jax.named_scope("send_weights"):
                # ---- sendWeightPartition + getWeights -------------------
                if len(buckets) == 1:
                    new_flat = jax.lax.all_gather(new_wshard, axis,
                                                  tiled=True)
                else:
                    # per-bucket gather mirrors the per-bucket scatter;
                    # ascending concat restores flat-parameter order
                    off, parts = 0, []
                    for s, z in buckets:
                        c = z // n
                        parts.append(jax.lax.all_gather(
                            jax.lax.slice_in_dim(new_wshard, off,
                                                 off + c),
                            axis, tiled=True))
                        off += c
                    new_flat = jnp.concatenate(parts)
                new_flat = new_flat[: flat_p.size]
            if guard:
                # a poisoned forward also poisons BN running stats —
                # a skipped step must not keep NaN statistics either
                new_mstate = jax.tree.map(
                    lambda a, b: jnp.where(ok, a, b)
                    if hasattr(a, "dtype") else a,
                    new_mstate, mstate)
            # keep BN running stats in sync across replicas (the reference
            # leaves them per-replica; pmean is strictly better and free)
            new_mstate = jax.tree.map(
                lambda s: jax.lax.pmean(s, axis)
                if hasattr(s, "dtype") and jnp.issubdtype(s.dtype, jnp.floating)
                else s,
                new_mstate,
            )
            if masked:
                # true masked mean: sum of valid per-sample losses over
                # the global valid count (shards hold unequal counts)
                loss = jax.lax.psum(loss_aux, axis) / valid
            else:
                loss = jax.lax.pmean(loss_aux, axis)
            if health_on:
                return (new_flat, new_opt, new_mstate, loss, ok,
                        health_stats)
            return new_flat, new_opt, new_mstate, loss, ok

        opt_state_specs = {
            k: P(axis) if v.ndim == 1
            else (P(axis, None) if k == "wire_ef" else P())
            for k, v in opt.state.items()}
        mstate_spec = jax.tree.map(lambda _: P(), self.model.state())

        in_specs = (P(), opt_state_specs, mstate_spec, P(), P(axis), P(axis))
        if masked:
            in_specs = in_specs + (P(axis),)
        out_specs = (P(), opt_state_specs, mstate_spec, P(), P())
        if health_on:
            out_specs = out_specs + (P(),)  # psum'd -> replicated
        mapped = _shard_map(
            sharded_step,
            self.mesh,
            in_specs=in_specs,
            out_specs=out_specs,
        )
        # donate params/opt-state/model-state like LocalOptimizer: the
        # step updates in place on-device instead of holding two copies
        # of the flat vector + sharded velocity in HBM (the driver loop
        # rebinds from the outputs; _write_back copies before any host
        # read)
        return jax.jit(mapped, donate_argnums=(0, 1, 2))

    def _loss_fn(self, masked: bool = False):
        """Reference semantics: sub-model gradients are *summed* then
        divided by the global batch size (SURVEY.md §7 hard part 2).  The
        criterion's sizeAverage divides by the local sub-batch; multiply
        back so psum_scatter(sum) / global_batch is exact.

        ``masked=True`` builds the padded-final-batch variant: the
        criterion runs per sample (vmap over singleton batches — exact
        for every per-sample-decomposable criterion, which the classic
        set all is), pad rows are zero-weighted, and the aux loss is the
        local masked SUM (the sharded step divides by the global valid
        count)."""
        model, criterion = self.model, self.criterion
        local_bs = self.batch_size // self.n_shards
        unravel = self._unravel

        def forward(flat_p, mstate, rng, inp):
            import jax

            jnp = _jnp()
            p = unravel(flat_p)
            pc, inpc = self._cast_for_compute(p, inp)
            out, new_mstate = model.apply(pc, mstate, inpc, training=True,
                                          rng=rng)
            out = jax.tree.map(
                lambda a: a.astype(jnp.float32)
                if hasattr(a, "dtype") and jnp.issubdtype(a.dtype,
                                                          jnp.floating)
                else a,
                out,
            )
            return p, out, new_mstate

        if masked:
            def loss_fn(flat_p, mstate, rng, inp, tgt, mask):
                import jax

                jnp = _jnp()
                p, out, new_mstate = forward(flat_p, mstate, rng, inp)
                single = lambda t: jax.tree.map(lambda a: a[None], t)
                per = jax.vmap(
                    lambda o, t: criterion.loss(single(o), single(t))
                )(out, tgt)
                local_sum = jnp.sum(per * mask)
                total = local_sum + model.regularization_loss(p)
                return total, (local_sum, new_mstate)

            return loss_fn

        def loss_fn(flat_p, mstate, rng, inp, tgt):
            p, out, new_mstate = forward(flat_p, mstate, rng, inp)
            per_mean = criterion.loss(out, tgt)
            # un-average: total local loss; grads then sum over samples, and
            # the sharded step divides by the global batch afterwards
            total = per_mean * local_bs if getattr(
                criterion, "size_average", True
            ) else per_mean
            # each replica adds the full regularizer gradient before the
            # sum-then-/globalBatch — the reference does the same inside
            # every replica's accGradParameters
            total = total + model.regularization_loss(p)
            return total, (per_mean, new_mstate)

        return loss_fn

    def _prepare_batch(self, inp, tgt):
        """The P(data) input sharding needs the batch divisible by the
        mesh; PAD the remainder by repeating the last sample and mark
        the pad rows in a mask that ``_build_train_step``'s masked
        variant folds into the loss/gradient mean (the reference's
        SampleToMiniBatch padding — SURVEY.md §2.1 "Dataset core";
        VERDICT r3 weak #7).  Nothing is ever trimmed or dropped."""
        import logging

        bs = np.asarray(inp).shape[0]
        # per-process datasets yield LOCAL slices: divisibility is
        # against this process's device count, not the global mesh
        divisor = self.n_shards
        if getattr(self.dataset, "per_process", False):
            import jax

            divisor = max(1, self.n_shards // jax.process_count())
        rem = bs % divisor
        if rem == 0:
            self._host_mask = None
            return inp, tgt
        pad_n = divisor - rem
        if bs not in self._warned_batch_sizes:
            self._warned_batch_sizes.add(bs)
            logging.getLogger("bigdl_tpu.optim").info(
                "DistriOptimizer: batch of %d not divisible by the %d-way "
                "device split — padding with %d masked copies of the last "
                "sample (exact masked-mean semantics)", bs, divisor, pad_n,
            )
        inp = np.asarray(inp)
        tgt = np.asarray(tgt)
        inp = np.concatenate([inp, np.repeat(inp[-1:], pad_n, axis=0)])
        tgt = np.concatenate([tgt, np.repeat(tgt[-1:], pad_n, axis=0)])
        self._host_mask = np.concatenate(
            [np.ones(bs, np.float32), np.zeros(pad_n, np.float32)])
        return inp, tgt

    def _put_batch(self, inp, tgt):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        jnp = _jnp()
        sh = NamedSharding(self.mesh, P(self.axis))
        mask = getattr(self, "_host_mask", None)
        if getattr(self.dataset, "per_process", False) \
                and jax.process_count() > 1:
            # per-process shard -> global array without any host holding
            # the full batch (reference: executors feed their own cached
            # partition only)
            put = lambda a: jax.make_array_from_process_local_data(
                sh, np.asarray(a))
        else:
            put = lambda a: jax.device_put(jnp.asarray(a), sh)
        self._device_mask = None if mask is None else put(mask)
        return put(inp), put(tgt)

    def optimize(self):
        # reference: retryNum < maxRetry => reload last checkpoint and
        # continue (SURVEY.md §3.2 tail; §5 failure semantics).  The
        # blind retry became a classified policy (resilience/retry.py):
        # fatal errors (bad config — ValueError/TypeError/…) surface on
        # the FIRST attempt with zero checkpoint reloads; transient ones
        # (XLA/OSError/injected faults/non-finite escalation) back off
        # exponentially and reload the newest INTACT checkpoint.
        import logging
        import time

        from bigdl_tpu import obs
        from bigdl_tpu.resilience.retry import RetryPolicy, classify

        log = logging.getLogger("bigdl_tpu.optim")
        policy = RetryPolicy.from_config(max_retries=self.max_retry)
        retry_counter = obs.get_registry().counter(
            names.RETRY_ATTEMPTS_TOTAL,
            "Training failures handled by the retry policy",
            labels=("classification", "error"))
        while True:
            try:
                return super().optimize()
            except Exception as e:
                kind = classify(e)
                if not self.checkpoint_path or kind == "fatal":
                    # structured telemetry even for the non-retried path:
                    # a fatal config error at step N is exactly what a
                    # post-mortem trace must show
                    retry_counter.labels(classification=kind,
                                         error=type(e).__name__).inc()
                    obs.get_tracer().event(
                        "resilience.failure", classification=kind,
                        error=type(e).__name__, step=self.state["neval"],
                        retried=False)
                    raise
                delay = policy.record_failure(e)
                retry_counter.labels(classification="transient",
                                     error=type(e).__name__).inc()
                if delay is None:
                    log.error(
                        "retry budget exhausted after %d transient "
                        "failures; surfacing the last one", policy.attempts)
                    obs.get_tracer().event(
                        "resilience.retry_budget_exhausted",
                        attempts=policy.attempts,
                        error=type(e).__name__, step=self.state["neval"])
                    raise
                log.exception(
                    "transient training failure (%s); retry %d/%d from "
                    "last intact checkpoint in %.2fs",
                    type(e).__name__, policy.attempts, self.max_retry,
                    delay,
                )
                obs.get_tracer().event(
                    "resilience.retry", classification="transient",
                    error=type(e).__name__, attempt=policy.attempts,
                    max_retries=self.max_retry,
                    delay_s=round(delay, 4), step=self.state["neval"])
                self._summary_resilience(self.state["neval"],
                                         retries=policy.attempts)
                if delay > 0:
                    time.sleep(delay)
                from bigdl_tpu.utils.serializer import load_latest_checkpoint

                extra = load_latest_checkpoint(
                    self.checkpoint_path, self.model, self.optim_method
                )
                # rewind the driver-side counters to the checkpoint so
                # triggers/LR schedule/RNG all resume from the same point
                # (the reference re-runs from the checkpoint, not from the
                # crash iteration)
                if "epoch" in extra:
                    self.state["epoch"] = extra["epoch"]
                if "neval" in extra:
                    self.state["neval"] = extra["neval"]
                # a mid-epoch checkpoint (emergency / iteration trigger)
                # resumes `neval - epoch_neval0` batches into the epoch:
                # fast-forward the data iterator that far so the replay
                # stays batch-aligned with the uninterrupted run
                self.state["epoch_neval0"] = extra.get(
                    "epoch_neval0", self.state["neval"])
                self._pending_fast_forward = max(
                    0, self.state["neval"] - self.state["epoch_neval0"])
                # a streaming dataset seeks to the checkpoint's trained
                # offset instead of fast-forwarding an epoch replay —
                # the crashed attempt's records past the checkpoint are
                # re-read and re-trained exactly once
                from bigdl_tpu.resilience import elastic as _elastic

                _elastic.restore_stream(self, extra)
                # goodput: the in-process retry replays every step
                # between the checkpoint and the crash — stamp this
                # attempt's own max step as the rework high-water mark
                obs.get_ledger().stamp_resume(self.state["neval"])
                # re-stamp /healthz with the restored step so the hang
                # watchdog's stall clock restarts at the rewind instead
                # of reading the pre-crash stamp's age
                from bigdl_tpu.obs import server as _obs_server

                if _obs_server.get_server() is not None:
                    _obs_server.note_step(self.state["neval"])
