"""Prediction & evaluation.

Rebuild of the reference's Predictor / Evaluator path (SURVEY.md §3.6):
``model.predict(rdd)`` broadcast an evaluate-mode model and ran
forward-only per partition over the executors, folding ValidationResult
monoids per partition and reducing on the driver.

TPU-native equivalent (VERDICT r2 #3): the forward jits once with the
minibatch sharded ``P(data)`` over the Engine mesh — every chip
evaluates its slice of the batch, exactly like the executor-local
replicas — and the ValidationResult monoids fold on host after a
device->host gather of the (small) output logits.  Ragged tail batches
are padded to the mesh divisor on host and the padding sliced off the
output, so results are bit-identical to single-device evaluation.
"""

from __future__ import annotations

import weakref
from typing import Sequence

import numpy as np

# jitted eval-forward per module (see _forward_fn)
_EVAL_FWD_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _mesh_usable(mesh):
    """The sharded path needs a single-process mesh (multi-process
    gathers are per-host; the caller keeps the per-partition fold)."""
    import jax

    return (
        mesh is not None
        and mesh.devices.size > 1
        and jax.process_count() == 1
    )


def _forward_fn(model, params=None, state=None, mesh=None):
    import jax

    # cache the jitted forward per module so repeated validation
    # triggers reuse the compiled program (params/state are arguments,
    # so weight updates don't invalidate it; only new input shapes
    # retrace).  A WeakKeyDictionary rather than an on-module attribute:
    # a deepcopy of the tree (e.g. module.quantize()) would carry an
    # attribute over with its closure still pointing at the ORIGINAL
    # module — stale results at best, and the copy would pin the float
    # weights + compiled program alive.  The weak cache simply has no
    # entry for the copy, and entries die with their module.
    fwd = _EVAL_FWD_CACHE.get(model)
    if fwd is None:
        # the closure must hold the model WEAKLY — a strong reference
        # from the cache value back to its key would keep every entry
        # (and its compiled program) immortal.  Callers reach fwd only
        # through this cache or through the returned lambda below, and
        # both hold the model strongly, so the deref cannot fail while
        # fwd is reachable.
        model_ref = weakref.ref(model)

        @jax.jit
        def fwd(p, s, inp):
            out, _ = model_ref().apply(p, s, inp, training=False, rng=None)
            return out

        _EVAL_FWD_CACHE[model] = fwd
    if params is None:
        params = model.params()
    if state is None:
        state = model.state()

    if not _mesh_usable(mesh):
        # _m pins the model while the forward fn is in use
        return lambda inp, _m=model: fwd(params, state, inp), 1

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis = mesh.axis_names[0]
    n = int(mesh.shape[axis])
    data_sh = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())
    # params/state replicate once; batches shard along the leading axis
    params = jax.device_put(params, repl)
    state = jax.device_put(state, repl)

    def sharded(inp):
        if isinstance(inp, tuple):
            inp = tuple(jax.device_put(jnp.asarray(x), data_sh) for x in inp)
        else:
            inp = jax.device_put(jnp.asarray(inp), data_sh)
        return fwd(params, state, inp)

    sharded._pin = model  # keep the weakly-held model alive while in use
    return sharded, n


def _pad_batch(arr, divisor):
    """Pad the leading axis up to a multiple of ``divisor`` by repeating
    the last row; returns (padded, true_batch)."""
    arr = np.asarray(arr)
    b = arr.shape[0]
    pad = (-b) % divisor
    if pad:
        arr = np.concatenate([arr, np.repeat(arr[-1:], pad, axis=0)], axis=0)
    return arr, b


def evaluate_dataset(model, dataset, methods: Sequence, mesh="auto",
                     params=None, state=None):
    """Fold validation methods over a dataset (reference:
    model.evaluate(rdd, Array(new Top1Accuracy))).

    ``mesh`` shards each batch ``P(data)`` across the devices;
    ``params``/``state`` accept device-resident pytrees so a distributed
    trainer can validate without a host weight copy."""
    import jax.numpy as jnp

    mesh = _resolve_mesh(mesh)
    model.evaluate()
    fwd, divisor = _forward_fn(model, params=params, state=state, mesh=mesh)
    results = [None] * len(methods)
    for inp, tgt in dataset.data(train=False):
        if isinstance(inp, (tuple, list)):
            padded, b = zip(*[_pad_batch(x, divisor) for x in inp])
            true_b = b[0]
            out = fwd(tuple(jnp.asarray(x) for x in padded))
        else:
            padded, true_b = _pad_batch(inp, divisor)
            out = fwd(jnp.asarray(padded))
        out = np.asarray(out)[:true_b]
        for i, m in enumerate(methods):
            r = m.batch_result(out, tgt)
            results[i] = r if results[i] is None else results[i] + r
    return _allreduce_results(results, dataset)


def _allreduce_results(results, dataset):
    """Multi-host: a per-process dataset yields only this host's shard,
    so the ValidationResult monoids must sum across processes before
    anyone reads a score (reference: per-partition fold + driver reduce
    — SURVEY.md §3.6).  Single-process: no-op."""
    import jax

    if jax.process_count() == 1 or not getattr(dataset, "per_process", False):
        return results
    from jax.experimental import multihost_utils

    out = []
    for r in results:
        if r is None:
            out.append(r)
            continue
        gathered = multihost_utils.process_allgather(
            np.asarray([r.total, float(r.count)], np.float64))
        total = float(gathered[:, 0].sum())
        count = int(gathered[:, 1].sum())
        out.append(type(r)(total, count, r.name))
    return out


def predict(model, features, batch_size: int = 32, mesh="auto"):
    """Batched forward over an array of inputs; returns stacked host
    outputs (reference: model.predict).  With ``mesh``, each batch
    shards ``P(data)`` over the devices.  ``features`` may be a tuple/
    list of arrays for table-input models (e.g. merged two-tower
    graphs)."""
    import jax.numpy as jnp

    model.evaluate()
    fwd, divisor = _forward_fn(model, mesh=_resolve_mesh(mesh))
    outs = []
    # a TUPLE is a table input (one array per graph input); a list stays
    # the historical list-of-rows batch
    if isinstance(features, tuple):
        parts = [np.asarray(f) for f in features]
        n = parts[0].shape[0]
        for b in range(0, n, batch_size):
            padded, true_bs = zip(*[
                _pad_batch(p[b: b + batch_size], divisor) for p in parts])
            out = fwd(tuple(jnp.asarray(p) for p in padded))
            outs.append(np.asarray(out)[: true_bs[0]])
        return np.concatenate(outs, axis=0)
    feats = np.asarray(features)
    n = feats.shape[0]
    for b in range(0, n, batch_size):
        chunk, true_b = _pad_batch(feats[b : b + batch_size], divisor)
        outs.append(np.asarray(fwd(jnp.asarray(chunk)))[:true_b])
    return np.concatenate(outs, axis=0)


def predict_class(model, features, batch_size: int = 32, mesh="auto"):
    """Reference: predictClass — argmax + 1 (1-based labels)."""
    out = predict(model, features, batch_size, mesh=mesh)
    return np.argmax(out.reshape(out.shape[0], -1), axis=-1) + 1


def predict_image(model, image_frame, batch_size: int = 32, mesh="auto",
                  output_layer=None, predict_key="predict"):
    """Reference: ``model.predictImage(imageFrame)`` — run the model
    over an ImageFrame's (already-transformed) tensors and write each
    prediction back into the feature under ``predict_key``.  Returns
    the frame.  Features must have been through ``MatToTensor`` (or
    hold CHW arrays in their SAMPLE slot)."""
    from bigdl_tpu.transform.vision import ImageFeature

    feats = []
    for f in image_frame.features:
        t = f.get(ImageFeature.SAMPLE)
        if t is None:
            t = np.transpose(
                np.asarray(f.image, np.float32), (2, 0, 1))
        elif hasattr(t, "features"):  # a Sample record
            t = np.asarray(t.features)
        feats.append(np.asarray(t))
    out = predict(model, np.stack(feats), batch_size, mesh=mesh)
    for f, o in zip(image_frame.features, out):
        f[predict_key] = o
    return image_frame


def _resolve_mesh(mesh):
    """``"auto"`` -> the Engine mesh when initialized, else no mesh.
    Explicit ``None`` always means single-device (internal callers that
    manage their own mesh pass it outright)."""
    if mesh != "auto":
        return mesh
    from bigdl_tpu.engine import Engine

    return Engine.mesh() if Engine.is_initialized() else None


class Evaluator:
    """Reference API parity: ``Evaluator(model).test(dataset, methods)``
    (⟦«bigdl»/optim/Evaluator.scala⟧) over the same mesh-sharded path
    as :func:`evaluate_dataset` — the Engine mesh is picked up
    automatically when initialized."""

    def __init__(self, model):
        self.model = model

    def test(self, dataset, methods: Sequence, batch_size: int = 32,
             mesh="auto"):
        from bigdl_tpu.dataset import to_dataset

        return evaluate_dataset(
            self.model, to_dataset(dataset, batch_size), methods, mesh=mesh
        )


class Validator:
    """Reference API parity: ``Validator(model, dataset).test(methods)``
    (⟦«bigdl»/optim/Validator.scala⟧ — the classic validation entry,
    with ``LocalValidator`` as the local-mode spelling)."""

    def __init__(self, model, dataset=None, batch_size: int = 32):
        from bigdl_tpu.dataset import to_dataset

        self.model = model
        self.dataset = (to_dataset(dataset, batch_size)
                        if dataset is not None else None)

    def test(self, methods: Sequence, dataset=None, batch_size=None,
             mesh="auto"):
        from bigdl_tpu.dataset import to_dataset

        if dataset is not None:
            ds = to_dataset(dataset, batch_size or 32)
        else:
            ds = self.dataset
            if ds is not None and batch_size is not None:
                # honor an explicit batch size even against the
                # constructor-supplied dataset
                ds = to_dataset((ds.features, ds.labels), batch_size) \
                    if hasattr(ds, "features") else ds
        if ds is None:
            raise ValueError("Validator needs a dataset (constructor or "
                             "test argument)")
        return evaluate_dataset(self.model, ds, methods, mesh=mesh)


LocalValidator = Validator


class Predictor:
    """Reference API parity: ``Predictor(model).predict(features)``
    (⟦«bigdl»/optim/Predictor.scala⟧); ``predict_class`` returns 1-based
    labels like the reference's predictClass.  The Engine mesh is picked
    up automatically when initialized."""

    def __init__(self, model, batch_size: int = 32, mesh="auto"):
        self.model = model
        self.batch_size = batch_size
        self.mesh = mesh

    def predict(self, features):
        return predict(self.model, features, self.batch_size, self.mesh)

    def predict_class(self, features):
        return predict_class(self.model, features, self.batch_size,
                             self.mesh)
