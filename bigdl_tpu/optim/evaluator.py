"""Prediction & evaluation.

Rebuild of the reference's Predictor / Evaluator path (SURVEY.md §3.6):
``model.predict(rdd)`` broadcast an evaluate-mode model and ran
forward-only per partition, folding ValidationResult monoids.  Here: one
jitted forward, batched over the dataset; results fold on host.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np


def _forward_fn(model):
    import jax

    # cache the jitted forward on the module so repeated validation
    # triggers reuse the compiled program (params/state are arguments, so
    # weight updates don't invalidate it; only new input shapes retrace)
    fwd = getattr(model, "_jit_eval_fwd", None)
    if fwd is None:
        @jax.jit
        def fwd(p, s, inp):
            out, _ = model.apply(p, s, inp, training=False, rng=None)
            return out

        model._jit_eval_fwd = fwd
    params = model.params()
    state = model.state()
    return lambda inp: fwd(params, state, inp)


def evaluate_dataset(model, dataset, methods: Sequence):
    """Fold validation methods over a dataset (reference:
    model.evaluate(rdd, Array(new Top1Accuracy)))."""
    import jax.numpy as jnp

    model.evaluate()
    fwd = _forward_fn(model)
    results = [None] * len(methods)
    for inp, tgt in dataset.data(train=False):
        if isinstance(inp, (tuple, list)):
            out = fwd(tuple(jnp.asarray(x) for x in inp))
        else:
            out = fwd(jnp.asarray(inp))
        for i, m in enumerate(methods):
            r = m.batch_result(out, tgt)
            results[i] = r if results[i] is None else results[i] + r
    return results


def predict(model, features, batch_size: int = 32):
    """Batched forward over an array of inputs; returns stacked host
    outputs (reference: model.predict)."""
    import jax.numpy as jnp

    model.evaluate()
    fwd = _forward_fn(model)
    feats = np.asarray(features)
    outs = []
    n = feats.shape[0]
    for b in range(0, n, batch_size):
        chunk = feats[b : b + batch_size]
        outs.append(np.asarray(fwd(jnp.asarray(chunk))))
    return np.concatenate(outs, axis=0)


def predict_class(model, features, batch_size: int = 32):
    """Reference: predictClass — argmax + 1 (1-based labels)."""
    out = predict(model, features, batch_size)
    return np.argmax(out.reshape(out.shape[0], -1), axis=-1) + 1
