"""Validation methods & results.

Rebuild of «bigdl»/optim/ValidationMethod.scala: Top1Accuracy,
Top5Accuracy, Loss, MAE — each produces a monoid-like ValidationResult
merged across batches/partitions with ``+`` (the reference folds them per
partition, reduces on the driver; here they fold across device shards the
same way — SURVEY.md §3.6).
"""

from __future__ import annotations

import numpy as np


class ValidationResult:
    """(sum, count) monoid; ``result()`` -> (value, count)."""

    def __init__(self, total: float, count: int, name: str = ""):
        self.total = float(total)
        self.count = int(count)
        self.name = name

    def result(self):
        return (self.total / max(1, self.count), self.count)

    def __add__(self, other):
        return ValidationResult(
            self.total + other.total, self.count + other.count, self.name
        )

    def __repr__(self):
        v, c = self.result()
        return f"{self.name or 'ValidationResult'}: {v:.6f} (count {c})"


class ValidationMethod:
    name = "ValidationMethod"

    def batch_result(self, output, target) -> ValidationResult:
        """Fold one batch: model output + target -> partial result.
        Output/target are device or host arrays; folding happens on
        host after the jitted forward."""
        raise NotImplementedError

    def __repr__(self):
        return self.name


class Top1Accuracy(ValidationMethod):
    """«bigdl» Top1Accuracy — argmax+1 vs 1-based target."""

    name = "Top1Accuracy"

    def batch_result(self, output, target):
        out = np.asarray(output)
        t = np.asarray(target).reshape(-1).astype(np.int64)
        pred = np.argmax(out.reshape(-1, out.shape[-1]), axis=-1) + 1
        correct = int(np.sum(pred == t))
        return ValidationResult(correct, t.size, self.name)


class Top5Accuracy(ValidationMethod):
    """«bigdl» Top5Accuracy"""

    name = "Top5Accuracy"

    def batch_result(self, output, target):
        out = np.asarray(output)
        out2 = out.reshape(-1, out.shape[-1])
        t = np.asarray(target).reshape(-1).astype(np.int64)
        k = min(5, out2.shape[-1])
        top5 = np.argpartition(-out2, k - 1, axis=-1)[:, :k] + 1
        correct = int(np.sum(np.any(top5 == t[:, None], axis=1)))
        return ValidationResult(correct, t.size, self.name)


class Loss(ValidationMethod):
    """«bigdl» Loss validation method — average criterion value."""

    name = "Loss"

    def __init__(self, criterion=None):
        from bigdl_tpu.nn.criterion import ClassNLLCriterion

        self.criterion = criterion or ClassNLLCriterion()

    def batch_result(self, output, target):
        n = np.asarray(target).reshape(-1).shape[0]
        val = float(np.asarray(self.criterion.loss(output, target)))
        return ValidationResult(val * n, n, self.name)


class MAE(ValidationMethod):
    """«bigdl» MAE — mean absolute error for regression."""

    name = "MAE"

    def batch_result(self, output, target):
        out = np.asarray(output)
        t = np.asarray(target)
        n = out.shape[0]
        return ValidationResult(float(np.sum(np.abs(out - t))) / max(1, out[0].size),
                                n, self.name)


class TreeNNAccuracy(ValidationMethod):
    """⟦«bigdl»/optim/ValidationMethod.scala⟧ TreeNNAccuracy — accuracy
    for tree-structured outputs (Tree-LSTM sentiment): the prediction
    is the argmax of the ROOT node's distribution, i.e. the first slice
    along the node axis of a (batch, nodes, classes) output."""

    name = "TreeNNAccuracy"

    def batch_result(self, output, target):
        out = np.asarray(output)
        t = np.asarray(target)
        if out.ndim >= 3:
            out = out[:, 0]  # root node distribution
        if t.ndim >= 2:
            t = t[:, 0]
        t = t.reshape(-1).astype(np.int64)
        pred = np.argmax(out.reshape(-1, out.shape[-1]), axis=-1) + 1
        correct = int(np.sum(pred == t))
        return ValidationResult(correct, t.size, self.name)


class HitRatio(ValidationMethod):
    """⟦«bigdl»⟧ HitRatio@k (recommender evaluation): fraction of
    positives ranked inside the top k of their negative pool."""

    name = "HitRatio"

    def __init__(self, k: int = 10, neg_num: int = 99):
        self.k = k
        self.neg_num = neg_num

    def batch_result(self, output, target):
        out = np.asarray(output).reshape(-1, self.neg_num + 1)
        # item 0 of each group is the positive; hit if within top-k
        rank = np.sum(out > out[:, :1], axis=1) + 1
        hits = int(np.sum(rank <= self.k))
        return ValidationResult(hits, out.shape[0], self.name)


class NDCG(ValidationMethod):
    """⟦«bigdl»⟧ NDCG@k for the same positive-vs-negatives layout."""

    name = "NDCG"

    def __init__(self, k: int = 10, neg_num: int = 99):
        self.k = k
        self.neg_num = neg_num

    def batch_result(self, output, target):
        out = np.asarray(output).reshape(-1, self.neg_num + 1)
        rank = np.sum(out > out[:, :1], axis=1) + 1
        gain = np.where(rank <= self.k, 1.0 / np.log2(rank + 1.0), 0.0)
        return ValidationResult(float(np.sum(gain)), out.shape[0], self.name)
