"""Validation methods & results.

Rebuild of «bigdl»/optim/ValidationMethod.scala: Top1Accuracy,
Top5Accuracy, Loss, MAE — each produces a monoid-like ValidationResult
merged across batches/partitions with ``+`` (the reference folds them per
partition, reduces on the driver; here they fold across device shards the
same way — SURVEY.md §3.6).
"""

from __future__ import annotations

import numpy as np


class ValidationResult:
    """(sum, count) monoid; ``result()`` -> (value, count)."""

    def __init__(self, total: float, count: int, name: str = ""):
        self.total = float(total)
        self.count = int(count)
        self.name = name

    def result(self):
        return (self.total / max(1, self.count), self.count)

    def __add__(self, other):
        return ValidationResult(
            self.total + other.total, self.count + other.count, self.name
        )

    def __repr__(self):
        v, c = self.result()
        return f"{self.name or 'ValidationResult'}: {v:.6f} (count {c})"


class ValidationMethod:
    name = "ValidationMethod"

    def batch_result(self, output, target) -> ValidationResult:
        """Fold one batch: model output + target -> partial result.
        Output/target are device or host arrays; folding happens on
        host after the jitted forward."""
        raise NotImplementedError

    def __repr__(self):
        return self.name


class Top1Accuracy(ValidationMethod):
    """«bigdl» Top1Accuracy — argmax+1 vs 1-based target."""

    name = "Top1Accuracy"

    def batch_result(self, output, target):
        out = np.asarray(output)
        t = np.asarray(target).reshape(-1).astype(np.int64)
        pred = np.argmax(out.reshape(-1, out.shape[-1]), axis=-1) + 1
        correct = int(np.sum(pred == t))
        return ValidationResult(correct, t.size, self.name)


class Top5Accuracy(ValidationMethod):
    """«bigdl» Top5Accuracy"""

    name = "Top5Accuracy"

    def batch_result(self, output, target):
        out = np.asarray(output)
        out2 = out.reshape(-1, out.shape[-1])
        t = np.asarray(target).reshape(-1).astype(np.int64)
        k = min(5, out2.shape[-1])
        top5 = np.argpartition(-out2, k - 1, axis=-1)[:, :k] + 1
        correct = int(np.sum(np.any(top5 == t[:, None], axis=1)))
        return ValidationResult(correct, t.size, self.name)


class Loss(ValidationMethod):
    """«bigdl» Loss validation method — average criterion value."""

    name = "Loss"

    def __init__(self, criterion=None):
        from bigdl_tpu.nn.criterion import ClassNLLCriterion

        self.criterion = criterion or ClassNLLCriterion()

    def batch_result(self, output, target):
        n = np.asarray(target).reshape(-1).shape[0]
        val = float(np.asarray(self.criterion.loss(output, target)))
        return ValidationResult(val * n, n, self.name)


class MAE(ValidationMethod):
    """«bigdl» MAE — mean absolute error for regression."""

    name = "MAE"

    def batch_result(self, output, target):
        out = np.asarray(output)
        t = np.asarray(target)
        n = out.shape[0]
        return ValidationResult(float(np.sum(np.abs(out - t))) / max(1, out[0].size),
                                n, self.name)
