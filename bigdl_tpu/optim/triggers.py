"""Triggers — when to stop / validate / checkpoint.

Rebuild of «bigdl»/optim/Trigger.scala.  A trigger is a predicate over the
optimizer's state table (epoch / neval / loss / score counters), exactly
like the reference.
"""

from __future__ import annotations


class _TriggerBase:
    # True when the predicate reads state["loss"]: the optimizer loop
    # then resolves the device loss synchronously every iteration
    # instead of pipelining the readback one step behind
    needs_loss = False

    def __call__(self, state: dict) -> bool:
        raise NotImplementedError


class _EveryEpoch(_TriggerBase):
    def __init__(self):
        self._last = 0

    def __call__(self, state):
        e = state.get("epoch_finished", 0)
        if e > self._last:
            self._last = e
            return True
        return False


class _SeveralIteration(_TriggerBase):
    def __init__(self, interval: int):
        self.interval = interval

    def __call__(self, state):
        # state["neval"] is the *next* iteration number (starts at 1,
        # incremented after each step — reference semantics), so the
        # number of completed iterations is neval - 1
        done = state.get("neval", 1) - 1
        return done > 0 and done % self.interval == 0


class _MaxEpoch(_TriggerBase):
    def __init__(self, m: int):
        self.m = m

    def __call__(self, state):
        return state.get("epoch", 1) > self.m


class _MaxIteration(_TriggerBase):
    def __init__(self, m: int):
        self.m = m

    def __call__(self, state):
        # neval > m after exactly m completed iterations (reference:
        # state[Int]("neval") > max)
        return state.get("neval", 1) > self.m


class _MinLoss(_TriggerBase):
    needs_loss = True

    def __init__(self, m: float):
        self.m = m

    def __call__(self, state):
        loss = state.get("loss")
        return loss is not None and loss < self.m


class _MaxScore(_TriggerBase):
    def __init__(self, m: float):
        self.m = m

    def __call__(self, state):
        score = state.get("score")
        return score is not None and score > self.m


class _And(_TriggerBase):
    def __init__(self, *ts):
        self.ts = ts
        self.needs_loss = any(
            getattr(t, "needs_loss", False) for t in ts)

    def __call__(self, state):
        return all(t(state) for t in self.ts)


class _Or(_TriggerBase):
    def __init__(self, *ts):
        self.ts = ts
        self.needs_loss = any(
            getattr(t, "needs_loss", False) for t in ts)

    def __call__(self, state):
        return any(t(state) for t in self.ts)


class Trigger:
    """Factory namespace matching the reference's Trigger object."""

    @staticmethod
    def every_epoch():
        return _EveryEpoch()

    @staticmethod
    def several_iteration(interval: int):
        return _SeveralIteration(interval)

    @staticmethod
    def max_epoch(m: int):
        return _MaxEpoch(m)

    @staticmethod
    def max_iteration(m: int):
        return _MaxIteration(m)

    @staticmethod
    def min_loss(m: float):
        return _MinLoss(m)

    @staticmethod
    def max_score(m: float):
        return _MaxScore(m)

    @staticmethod
    def and_(*ts):
        return _And(*ts)

    @staticmethod
    def or_(*ts):
        return _Or(*ts)

    # camelCase aliases (reference spelling)
    everyEpoch = every_epoch
    severalIteration = several_iteration
    maxEpoch = max_epoch
    maxIteration = max_iteration
    minLoss = min_loss
    maxScore = max_score
