"""Optimization methods + learning-rate schedules.

Rebuild of «bigdl»/optim/{SGD,Adam,Adagrad,Adadelta,Adamax,RMSprop,Ftrl}.scala
(SURVEY.md §2.1 "OptimMethods": each has ``optimize(feval, x)`` mutating a
flat parameter tensor plus its own state table).

Every method is a pure, jittable ``step(grad, param, state) ->
(param, state)`` over an arbitrary **pytree** of parameters (all update
math is elementwise, expressed with ``jax.tree.map``).  A single flat
vector is just a one-leaf pytree, so DistriOptimizer runs the *same*
method unchanged on its ZeRO-1 weight shard inside ``shard_map`` — the
owner-slice update of the reference's ``AllReduceParameter`` scheme
(SURVEY.md §2.4 row 3) — while LocalOptimizer passes the native
parameter tree (no ravel/unravel copies on the hot path).

State counters live in the state dict as JAX scalars so stepping never
retraces.  ``optimize(feval, x)`` is kept as the BigDL-parity wrapper.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def _jnp():
    import jax.numpy as jnp

    return jnp


def _tmap(f, *trees):
    import jax

    return jax.tree.map(f, *trees)


def _global_sq_norm(tree):
    """Sum of squares over every leaf (scalar)."""
    import jax

    jnp = _jnp()
    leaves = jax.tree.leaves(tree)
    return sum(jnp.sum(l * l) for l in leaves)


# --------------------------------------------------------------------------
# Learning-rate schedules («bigdl»/optim/SGD.scala nested LearningRateSchedule)
# All pure: rate(lr0, state) -> scalar, where state carries neval/epoch.
# --------------------------------------------------------------------------


class LearningRateSchedule:
    def rate(self, lr0, state):
        raise NotImplementedError


class Default(LearningRateSchedule):
    """lr / (1 + neval * learningRateDecay) — the reference default."""

    def __init__(self):
        pass

    def rate(self, lr0, state):
        return lr0 / (1.0 + state["neval"] * state["lr_decay"])


class Poly(LearningRateSchedule):
    """«bigdl» SGD.Poly — lr * (1 - iter/maxIter)^power (ResNet recipe)."""

    def __init__(self, power: float, max_iteration: int):
        self.power, self.max_iteration = power, max_iteration

    def rate(self, lr0, state):
        jnp = _jnp()
        frac = jnp.minimum(state["neval"] / self.max_iteration, 1.0)
        return lr0 * (1.0 - frac) ** self.power


class Step(LearningRateSchedule):
    """«bigdl» SGD.Step — lr * gamma^(floor(neval/stepSize))."""

    def __init__(self, step_size: int, gamma: float):
        self.step_size, self.gamma = step_size, gamma

    def rate(self, lr0, state):
        jnp = _jnp()
        return lr0 * self.gamma ** jnp.floor(state["neval"] / self.step_size)


class MultiStep(LearningRateSchedule):
    """«bigdl» SGD.MultiStep — decay at given iteration milestones."""

    def __init__(self, step_sizes, gamma: float):
        self.step_sizes = list(step_sizes)
        self.gamma = gamma

    def rate(self, lr0, state):
        jnp = _jnp()
        n = state["neval"]
        k = sum((n >= s).astype(jnp.float32) for s in map(float, self.step_sizes))
        return lr0 * self.gamma ** k


class Exponential(LearningRateSchedule):
    """«bigdl» SGD.Exponential — lr * decayRate^(neval/decayStep)."""

    def __init__(self, decay_step: int, decay_rate: float, stair_case: bool = False):
        self.decay_step, self.decay_rate, self.stair_case = (
            decay_step,
            decay_rate,
            stair_case,
        )

    def rate(self, lr0, state):
        jnp = _jnp()
        e = state["neval"] / self.decay_step
        if self.stair_case:
            e = jnp.floor(e)
        return lr0 * self.decay_rate ** e


class EpochDecay(LearningRateSchedule):
    """«bigdl» SGD.EpochDecay — host-side function of epoch; resolved per
    step from the epoch counter using a decay lambda on 0.1 powers."""

    def __init__(self, decay_fn):
        self.decay_fn = decay_fn  # epoch -> decay exponent (host int math ok)

    def rate(self, lr0, state):
        # epoch is a traced scalar; the reference's decay fn is arbitrary
        # host code, so we approximate with a piecewise table up to 1000
        # epochs evaluated eagerly.
        jnp = _jnp()
        table = jnp.asarray(
            [0.1 ** float(self.decay_fn(e)) for e in range(1000)], dtype=jnp.float32
        )
        idx = jnp.clip(state["epoch"].astype(int), 0, 999)
        return lr0 * table[idx]


class Warmup(LearningRateSchedule):
    """«bigdl» SGD.Warmup — linear ramp by delta for warmupIteration
    steps, then hands off to the chained schedule (used by the ImageNet
    ResNet recipe via SequentialSchedule)."""

    def __init__(self, delta: float):
        self.delta = delta

    def rate(self, lr0, state):
        return lr0 + state["neval"] * self.delta


class SequentialSchedule(LearningRateSchedule):
    """«bigdl» SGD.SequentialSchedule — run schedule_i for maxIteration_i
    steps, offsetting neval for each successor."""

    def __init__(self, iteration_per_epoch: int = 1):
        self.schedules = []  # (schedule, duration)
        self.iteration_per_epoch = iteration_per_epoch

    def add(self, schedule: LearningRateSchedule, max_iteration: int):
        self.schedules.append((schedule, max_iteration))
        return self

    def rate(self, lr0, state):
        jnp = _jnp()
        n = state["neval"]
        rate = None
        offset = 0.0
        for sched, dur in self.schedules:
            sub = dict(state)
            sub["neval"] = jnp.maximum(n - offset, 0.0)
            r = sched.rate(lr0, sub)
            if rate is None:
                rate = r
            else:
                rate = jnp.where(n >= offset, r, rate)
            offset += dur
        return rate if rate is not None else lr0


class Plateau(LearningRateSchedule):
    """«bigdl» SGD.Plateau — reduce LR when a monitored score stops
    improving.  Inherently host-side (depends on validation results): the
    optimizer loop calls :meth:`on_score` between iterations; the traced
    step just reads the resulting ``lr_scale`` entry in the state."""

    def __init__(
        self,
        monitor: str = "score",
        factor: float = 0.1,
        patience: int = 10,
        mode: str = "min",
        epsilon: float = 1e-4,
        cooldown: int = 0,
        min_lr: float = 0.0,
    ):
        self.monitor, self.factor, self.patience = monitor, factor, patience
        self.mode, self.epsilon, self.cooldown, self.min_lr = (
            mode,
            epsilon,
            cooldown,
            min_lr,
        )
        self._best = None
        self._wait = 0
        self._cooldown_left = 0
        self.scale = 1.0

    def on_score(self, value: float, lr0: float):
        improved = (
            self._best is None
            or (self.mode == "min" and value < self._best - self.epsilon)
            or (self.mode == "max" and value > self._best + self.epsilon)
        )
        if improved:
            self._best = value
            self._wait = 0
        elif self._cooldown_left > 0:
            self._cooldown_left -= 1
        else:
            self._wait += 1
            if self._wait >= self.patience:
                new_scale = max(self.scale * self.factor, self.min_lr / max(lr0, 1e-12))
                self.scale = new_scale
                self._wait = 0
                self._cooldown_left = self.cooldown
        return self.scale

    def rate(self, lr0, state):
        return lr0 * state["lr_scale"]


# --------------------------------------------------------------------------
# OptimMethod base
# --------------------------------------------------------------------------


class OptimMethod:
    """Base class.  Pure ``step`` over parameter pytrees (a flat vector
    is the one-leaf case); stateful ``optimize(feval, x)`` for
    reference-API parity (mutation expressed by returning the new vector
    and keeping state on self)."""

    def __init__(self):
        self.state = None  # host-side mirror of the jittable state dict

    # ---- pure API -------------------------------------------------------
    def init_state(self, flat_param) -> dict:
        jnp = _jnp()
        return {
            "neval": jnp.zeros((), jnp.float32),
            "epoch": jnp.zeros((), jnp.float32),
            "lr_decay": jnp.asarray(getattr(self, "learningrate_decay", 0.0),
                                    jnp.float32),
            "lr_scale": jnp.ones((), jnp.float32),
            **self._extra_state(flat_param),
        }

    def _extra_state(self, flat_param) -> dict:
        return {}

    def current_rate(self, state):
        sched = getattr(self, "learningrate_schedule", None) or Default()
        return sched.rate(self.learningrate, state)

    def step(self, grad, param, state):
        """(grad tree, param tree, state) -> (new param tree, new state).
        Must be pure/jittable; runs unchanged on a ZeRO-1 flat shard."""
        raise NotImplementedError

    # ---- reference-parity API ------------------------------------------
    def optimize(self, feval, x):
        """Reference: OptimMethod.optimize(feval, x) — evaluate loss+grad
        at x, update in place, return (new_x, [loss])."""
        jnp = _jnp()
        x = jnp.asarray(x)
        if self.state is None:
            self.state = self.init_state(x)
        loss, grad = feval(x)
        new_x, self.state = self.step(jnp.asarray(grad), x, self.state)
        return new_x, [loss]

    def get_hyper_parameter(self) -> str:
        return f"learningrate={getattr(self, 'learningrate', None)}"

    # checkpoint support («bigdl» OptimMethod.save/load).  State entries
    # may be pytrees (nested string-keyed dicts matching the model's
    # parameter tree); they flatten to "/"-joined keys for npz storage.
    def get_state_arrays(self, materialize: bool = True):
        """Flatten the state table to "/"-joined keys.  With
        ``materialize=False`` the values stay device-array REFS (for an
        async checkpoint snapshot — the host transfer happens later)."""
        if self.state is None:
            return {}
        out = {}

        def walk(prefix, v):
            if isinstance(v, dict):
                if not v:
                    # empty pytree node (a parameter-less layer's slot):
                    # must survive the round trip or the restored state's
                    # tree structure no longer matches the params tree.
                    # An empty TOP-LEVEL state stays {} (prefix ""
                    # would otherwise round-trip as {'': {}}).
                    if prefix:
                        out[f"{prefix}/__emptydict__"] = np.zeros(0)
                    return
                for k, sub in v.items():
                    walk(f"{prefix}/{k}" if prefix else k, sub)
            else:
                out[prefix] = np.asarray(v) if materialize else v

        walk("", self.state)
        return out

    @staticmethod
    def _unflatten_state(arrays: dict) -> dict:
        jnp = _jnp()
        state: dict = {}
        for key, v in arrays.items():
            parts = key.split("/")
            d = state
            for p in parts[:-1]:
                d = d.setdefault(p, {})
            if parts[-1] == "__emptydict__":
                continue  # the setdefault walk already created the node
            d[parts[-1]] = jnp.asarray(v)
        return state

    def load_state_arrays(self, arrays: dict):
        self.state = self._unflatten_state(arrays)

    def save(self, path: str):
        """Reference: ``OptimMethod.save(path)`` — persists the method's
        hyperparameters (incl. LR schedule objects) AND its state table,
        so ``OptimMethod.load`` reconstructs a resumable method.

        Hyperparameters that cannot be pickled (e.g. a user lambda in
        ``EpochDecay``) are skipped — save never fails where the old
        state-only save succeeded; ``load`` reports them."""
        import pickle

        hyper = {}
        skipped = []
        for k, v in vars(self).items():
            if k == "state":
                continue
            try:
                pickle.dumps(v)
                hyper[k] = v
            except Exception:  # noqa: BLE001 — any unpicklable attr
                skipped.append(k)
        np.savez(
            path,
            __class__=type(self).__name__,
            __hyper__=np.frombuffer(
                pickle.dumps(hyper), dtype=np.uint8).copy(),
            __hyper_skipped__=np.asarray(skipped, dtype=object),
            **self.get_state_arrays(),
        )

    _CONTAINER_KEYS = ("__class__", "__hyper__", "__hyper_skipped__",
                       "__meta__")

    @staticmethod
    def load_state(path: str) -> dict:
        data = np.load(path, allow_pickle=True)
        return OptimMethod._unflatten_state(
            {k: data[k] for k in data.files
             if k not in OptimMethod._CONTAINER_KEYS}
        )

    @staticmethod
    def load(path: str) -> "OptimMethod":
        """Reference: ``OptimMethod.load(path)`` — rebuild the saved
        method (class + hyperparameters + state) for
        ``Optimizer(...).set_optim_method(OptimMethod.load(p))``
        resume.  Also reads the ``save_checkpoint`` ``.optim.npz``
        container (state + class, no hyperparameters) and fails fast
        when hyperparameters are missing or were unpicklable."""
        import json
        import pickle

        if not path.endswith(".npz"):
            path = path + ".npz"
        data = np.load(path, allow_pickle=True)
        if "__class__" in data.files:
            name = str(data["__class__"])
        elif "__meta__" in data.files:
            # serializer.save_checkpoint container: class name rides in
            # the JSON meta; it carries NO hyperparameters
            name = json.loads(bytes(data["__meta__"]).decode())["class"]
            raise ValueError(
                f"{path} is a save_checkpoint optimizer-state container "
                f"(class {name}, state only): reconstruct the "
                "OptimMethod with its hyperparameters and use "
                "load_checkpoint / load_state_arrays to restore state")
        else:
            raise ValueError(f"{path} is not an OptimMethod.save file")
        if "__hyper__" not in data.files:
            raise ValueError(
                f"{path} carries no hyperparameters (written by a "
                "pre-hyper save): reconstruct the OptimMethod manually "
                "and restore its state with OptimMethod.load_state")
        skipped = [str(s) for s in data["__hyper_skipped__"].tolist()] \
            if "__hyper_skipped__" in data.files else []
        if skipped:
            raise ValueError(
                f"{path}: hyperparameters {skipped} were unpicklable at "
                "save time; reconstruct the OptimMethod manually and "
                "restore its state with OptimMethod.load_state")

        def subclasses(cls):
            out = {}
            for sub in cls.__subclasses__():
                out[sub.__name__] = sub
                out.update(subclasses(sub))
            return out

        klass = subclasses(OptimMethod).get(name)
        if klass is None:
            raise ValueError(f"unknown OptimMethod class {name!r}")
        obj = klass.__new__(klass)
        obj.state = None
        vars(obj).update(pickle.loads(data["__hyper__"].tobytes()))
        state = OptimMethod._unflatten_state(
            {k: data[k] for k in data.files
             if k not in OptimMethod._CONTAINER_KEYS}
        )
        if state:
            obj.state = state
        return obj


class SGD(OptimMethod):
    """«bigdl»/optim/SGD.scala — momentum / dampening / nesterov /
    weightDecay / LR schedules."""

    def __init__(
        self,
        learningrate: float = 1e-3,
        learningrate_decay: float = 0.0,
        weightdecay: float = 0.0,
        momentum: float = 0.0,
        dampening: Optional[float] = None,
        nesterov: bool = False,
        learningrate_schedule: Optional[LearningRateSchedule] = None,
    ):
        super().__init__()
        self.learningrate = learningrate
        self.learningrate_decay = learningrate_decay
        self.weightdecay = weightdecay
        self.momentum = momentum
        self.dampening = momentum if dampening is None else dampening
        if nesterov and (momentum <= 0 or self.dampening != 0):
            raise ValueError(
                "nesterov requires momentum > 0 and dampening = 0 (reference check)"
            )
        self.nesterov = nesterov
        self.learningrate_schedule = learningrate_schedule

    def _extra_state(self, param):
        jnp = _jnp()
        if self.momentum > 0:
            return {"velocity": _tmap(jnp.zeros_like, param)}
        return {}

    def step(self, grad, param, state):
        lr = self.current_rate(state)
        wd, mom, damp = self.weightdecay, self.momentum, self.dampening
        g = _tmap(lambda gg, p: gg + wd * p, grad, param) if wd > 0 else grad
        new_state = dict(state)
        if mom > 0:
            v = _tmap(
                lambda vv, gg: mom * vv + (1.0 - damp) * gg,
                state["velocity"], g,
            )
            new_state["velocity"] = v
            g = _tmap(lambda gg, vv: gg + mom * vv, g, v) if self.nesterov else v
        new_param = _tmap(lambda p, gg: p - lr * gg, param, g)
        new_state["neval"] = state["neval"] + 1.0
        return new_param, new_state


class Adam(OptimMethod):
    """«bigdl»/optim/Adam.scala"""

    def __init__(
        self,
        learningrate: float = 1e-3,
        learningrate_decay: float = 0.0,
        beta1: float = 0.9,
        beta2: float = 0.999,
        epsilon: float = 1e-8,
    ):
        super().__init__()
        self.learningrate = learningrate
        self.learningrate_decay = learningrate_decay
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.learningrate_schedule = None

    def _extra_state(self, param):
        jnp = _jnp()
        return {
            "m": _tmap(jnp.zeros_like, param),
            "v": _tmap(jnp.zeros_like, param),
        }

    def step(self, grad, param, state):
        jnp = _jnp()
        lr = self.current_rate(state)
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        t = state["neval"] + 1.0
        m = _tmap(lambda mm, gg: b1 * mm + (1 - b1) * gg, state["m"], grad)
        v = _tmap(lambda vv, gg: b2 * vv + (1 - b2) * gg * gg, state["v"], grad)
        bc1 = 1 - b1 ** t
        bc2 = 1 - b2 ** t
        new_param = _tmap(
            lambda p, mm, vv: p - lr * (mm / bc1) / (jnp.sqrt(vv / bc2) + eps),
            param, m, v,
        )
        return new_param, {**state, "m": m, "v": v, "neval": t}


class Adagrad(OptimMethod):
    """«bigdl»/optim/Adagrad.scala"""

    def __init__(self, learningrate=1e-3, learningrate_decay=0.0, weightdecay=0.0):
        super().__init__()
        self.learningrate = learningrate
        self.learningrate_decay = learningrate_decay
        self.weightdecay = weightdecay
        self.learningrate_schedule = None

    def _extra_state(self, param):
        return {"accum": _tmap(_jnp().zeros_like, param)}

    def step(self, grad, param, state):
        jnp = _jnp()
        lr = self.current_rate(state)
        wd = self.weightdecay
        g = _tmap(lambda gg, p: gg + wd * p, grad, param) if wd > 0 else grad
        accum = _tmap(lambda a, gg: a + gg * gg, state["accum"], g)
        new_param = _tmap(
            lambda p, gg, a: p - lr * gg / (jnp.sqrt(a) + 1e-10), param, g, accum
        )
        return new_param, {**state, "accum": accum, "neval": state["neval"] + 1.0}


class Adadelta(OptimMethod):
    """«bigdl»/optim/Adadelta.scala"""

    def __init__(self, decayrate: float = 0.9, epsilon: float = 1e-10):
        super().__init__()
        self.learningrate = 1.0
        self.learningrate_decay = 0.0
        self.decayrate, self.epsilon = decayrate, epsilon
        self.learningrate_schedule = None

    def _extra_state(self, param):
        jnp = _jnp()
        return {
            "accum_g": _tmap(jnp.zeros_like, param),
            "accum_dx": _tmap(jnp.zeros_like, param),
        }

    def step(self, grad, param, state):
        jnp = _jnp()
        rho, eps = self.decayrate, self.epsilon
        ag = _tmap(
            lambda a, gg: rho * a + (1 - rho) * gg * gg, state["accum_g"], grad
        )
        dx = _tmap(
            lambda adx, a, gg: -jnp.sqrt(adx + eps) / jnp.sqrt(a + eps) * gg,
            state["accum_dx"], ag, grad,
        )
        adx = _tmap(
            lambda a, d: rho * a + (1 - rho) * d * d, state["accum_dx"], dx
        )
        return _tmap(lambda p, d: p + d, param, dx), {
            **state,
            "accum_g": ag,
            "accum_dx": adx,
            "neval": state["neval"] + 1.0,
        }


class Adamax(OptimMethod):
    """«bigdl»/optim/Adamax.scala"""

    def __init__(self, learningrate=2e-3, beta1=0.9, beta2=0.999, epsilon=1e-38):
        super().__init__()
        self.learningrate = learningrate
        self.learningrate_decay = 0.0
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.learningrate_schedule = None

    def _extra_state(self, param):
        jnp = _jnp()
        return {
            "m": _tmap(jnp.zeros_like, param),
            "u": _tmap(jnp.zeros_like, param),
        }

    def step(self, grad, param, state):
        jnp = _jnp()
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        t = state["neval"] + 1.0
        m = _tmap(lambda mm, gg: b1 * mm + (1 - b1) * gg, state["m"], grad)
        u = _tmap(
            lambda uu, gg: jnp.maximum(b2 * uu, jnp.abs(gg) + eps),
            state["u"], grad,
        )
        scale = self.learningrate / (1 - b1 ** t)
        new_param = _tmap(lambda p, mm, uu: p - scale * mm / uu, param, m, u)
        return new_param, {**state, "m": m, "u": u, "neval": t}


class RMSprop(OptimMethod):
    """«bigdl»/optim/RMSprop.scala"""

    def __init__(self, learningrate=1e-2, learningrate_decay=0.0, decayrate=0.99,
                 epsilon=1e-8):
        super().__init__()
        self.learningrate = learningrate
        self.learningrate_decay = learningrate_decay
        self.decayrate, self.epsilon = decayrate, epsilon
        self.learningrate_schedule = None

    def _extra_state(self, param):
        return {"accum": _tmap(_jnp().zeros_like, param)}

    def step(self, grad, param, state):
        jnp = _jnp()
        lr = self.current_rate(state)
        dr, eps = self.decayrate, self.epsilon
        accum = _tmap(
            lambda a, gg: dr * a + (1 - dr) * gg * gg, state["accum"], grad
        )
        new_param = _tmap(
            lambda p, gg, a: p - lr * gg / (jnp.sqrt(a) + eps), param, grad, accum
        )
        return new_param, {**state, "accum": accum, "neval": state["neval"] + 1.0}


class Ftrl(OptimMethod):
    """«bigdl»/optim/Ftrl.scala — FTRL-proximal for sparse/wide models."""

    def __init__(
        self,
        learningrate: float = 1e-3,
        learningrate_power: float = -0.5,
        initial_accumulator_value: float = 0.1,
        l1_regularization_strength: float = 0.0,
        l2_regularization_strength: float = 0.0,
        l2_shrinkage_regularization_strength: float = 0.0,
    ):
        super().__init__()
        self.learningrate = learningrate
        self.learningrate_decay = 0.0
        self.lr_power = learningrate_power
        self.init_accum = initial_accumulator_value
        self.l1 = l1_regularization_strength
        self.l2 = l2_regularization_strength
        self.l2_shrinkage = l2_shrinkage_regularization_strength
        self.learningrate_schedule = None

    def _extra_state(self, param):
        jnp = _jnp()
        return {
            "accum": _tmap(lambda p: jnp.full_like(p, self.init_accum), param),
            "linear": _tmap(jnp.zeros_like, param),
        }

    def step(self, grad, param, state):
        jnp = _jnp()
        lr = self.learningrate
        lr_power, l1_reg, l2 = self.lr_power, self.l1, self.l2
        shrink = self.l2_shrinkage

        def leaf(g, p, accum, lin):
            g_shrink = g + 2 * shrink * p if shrink > 0 else g
            accum_new = accum + g * g
            sigma = (accum_new ** -lr_power - accum ** -lr_power) / lr
            linear = lin + g_shrink - sigma * p
            quad = accum_new ** -lr_power / lr + 2 * l2
            new_p = jnp.where(
                jnp.abs(linear) > l1_reg,
                -(linear - jnp.sign(linear) * l1_reg) / quad,
                0.0,
            )
            return new_p, accum_new, linear

        triples = _tmap(leaf, grad, param, state["accum"], state["linear"])
        import jax

        new_param = jax.tree.map(
            lambda t: t[0], triples, is_leaf=lambda t: isinstance(t, tuple)
        )
        accum_new = jax.tree.map(
            lambda t: t[1], triples, is_leaf=lambda t: isinstance(t, tuple)
        )
        linear = jax.tree.map(
            lambda t: t[2], triples, is_leaf=lambda t: isinstance(t, tuple)
        )
        return new_param, {
            **state,
            "accum": accum_new,
            "linear": linear,
            "neval": state["neval"] + 1.0,
        }


class LBFGS(OptimMethod):
    """«bigdl»/optim/LBFGS.scala — limited-memory BFGS with the
    reference's default learningRate-scaled step (no line search; the
    reference's lineSearch hook defaults to a fixed step too).

    The two-loop recursion runs over a fixed ``ncorrection`` history
    window carried as stacked arrays so the step stays jittable
    (unrolled loops over a static history length).

    Note: ``ncorrection`` is capped at 16 (the reference default is 100,
    but the recursion unrolls into the compiled step — 2×ncorrection
    dot-products per update — and histories beyond ~16 measurably slow
    compilation and execution without improving convergence on the
    models this framework targets).  A warning is emitted when the cap
    engages.
    """

    _NCORRECTION_CAP = 16

    def __init__(self, max_iter: int = 20, max_eval: Optional[float] = None,
                 tolfun: float = 1e-5, tolx: float = 1e-9,
                 ncorrection: int = 16, learningrate: float = 1.0,
                 verbose: bool = False, linesearch=None):
        super().__init__()
        self.learningrate = learningrate
        self.learningrate_decay = 0.0
        self.max_iter = max_iter
        self.tolfun, self.tolx = tolfun, tolx
        if ncorrection > self._NCORRECTION_CAP:
            import warnings

            warnings.warn(
                f"LBFGS ncorrection={ncorrection} capped at "
                f"{self._NCORRECTION_CAP} (history unrolls into the "
                "compiled step)", stacklevel=2,
            )
        self.ncorrection = min(ncorrection, self._NCORRECTION_CAP)
        self.learningrate_schedule = None

    def _extra_state(self, param):
        import jax

        jnp = _jnp()
        m = self.ncorrection
        flat_zero = _tmap(jnp.zeros_like, param)

        def hist(t):
            return jax.tree.map(
                lambda a: jnp.zeros((m,) + a.shape, a.dtype), t
            )

        return {
            "s_hist": hist(flat_zero),   # param deltas
            "y_hist": hist(flat_zero),   # grad deltas
            "rho": jnp.zeros((m,), jnp.float32),
            "prev_param": flat_zero,
            "prev_grad": flat_zero,
            "hist_len": jnp.zeros((), jnp.float32),
        }

    def step(self, grad, param, state):
        import jax

        jnp = _jnp()
        m = self.ncorrection
        t = state["neval"]

        # ---- update history with (s, y) from the previous step --------
        s = _tmap(lambda p, pp: p - pp, param, state["prev_param"])
        y = _tmap(lambda g, pg: g - pg, grad, state["prev_grad"])
        sy = sum(jnp.sum(a * b) for a, b in zip(
            jax.tree.leaves(s), jax.tree.leaves(y)
        ))
        valid = (t > 0) & (sy > 1e-10)

        def rolled(h, new):
            return _tmap(
                lambda hh, nn: jnp.where(
                    valid,
                    jnp.concatenate([hh[1:], nn[None]], axis=0),
                    hh,
                ),
                h, new,
            )

        s_hist = rolled(state["s_hist"], s)
        y_hist = rolled(state["y_hist"], y)
        rho = jnp.where(
            valid,
            jnp.concatenate([state["rho"][1:],
                             (1.0 / jnp.maximum(sy, 1e-10))[None]]),
            state["rho"],
        )
        hist_len = jnp.where(valid,
                             jnp.minimum(state["hist_len"] + 1, m),
                             state["hist_len"])

        # ---- two-loop recursion --------------------------------------
        q = grad
        alphas = []
        for i in range(m - 1, -1, -1):
            live = (m - i) <= hist_len
            a_i = rho[i] * sum(
                jnp.sum(sh[i] * qq) for sh, qq in zip(
                    jax.tree.leaves(s_hist), jax.tree.leaves(q)
                )
            )
            a_i = jnp.where(live, a_i, 0.0)
            q = _tmap(lambda qq, yh: qq - a_i * yh[i], q, y_hist)
            alphas.append((i, a_i, live))
        # initial Hessian scaling gamma = sy/yy of most recent pair
        yy = sum(jnp.sum(yh[m - 1] ** 2) for yh in jax.tree.leaves(y_hist))
        sy_last = jnp.where(rho[m - 1] > 0, 1.0 / rho[m - 1], 1.0)
        gamma = jnp.where(hist_len > 0, sy_last / jnp.maximum(yy, 1e-10), 1.0)
        r = _tmap(lambda qq: gamma * qq, q)
        for i, a_i, live in reversed(alphas):
            b_i = rho[i] * sum(
                jnp.sum(yh[i] * rr) for yh, rr in zip(
                    jax.tree.leaves(y_hist), jax.tree.leaves(r)
                )
            )
            b_i = jnp.where(live, b_i, 0.0)
            r = _tmap(lambda rr, sh: rr + (a_i - b_i) * sh[i], r, s_hist)

        lr = self.learningrate
        new_param = _tmap(lambda p, rr: p - lr * rr, param, r)
        return new_param, {
            **state,
            "s_hist": s_hist, "y_hist": y_hist, "rho": rho,
            "prev_param": param, "prev_grad": grad,
            "hist_len": hist_len,
            "neval": t + 1.0,
        }


class LarsSGD(SGD):
    """LARS layer-wise adaptive-rate SGD («bigdl» has LarsSGD in later
    lines; included for large-batch ImageNet recipes).  The trust ratio
    is computed per pytree leaf — true layer-wise LARS when given the
    parameter tree; on a single flat vector it degenerates to one global
    ratio (the ZeRO-shard approximation)."""

    def __init__(self, learningrate=1e-3, trust_coefficient=0.001, **kw):
        super().__init__(learningrate=learningrate, **kw)
        self.trust_coefficient = trust_coefficient

    def step(self, grad, param, state):
        jnp = _jnp()
        tc, wd = self.trust_coefficient, self.weightdecay

        def scaled(gg, p):
            w_norm = jnp.linalg.norm(p)
            g_norm = jnp.linalg.norm(gg)
            trust = jnp.where(
                (w_norm > 0) & (g_norm > 0),
                tc * w_norm / (g_norm + wd * w_norm + 1e-12),
                1.0,
            )
            return gg * trust

        return super().step(_tmap(scaled, grad, param), param, state)
