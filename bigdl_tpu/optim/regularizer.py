"""Regularizers.

Rebuild of «bigdl»/optim/Regularizer.scala (L1L2Regularizer family).  The
reference adds regularizer *gradients* inside each layer's
accGradParameters; the rebuild adds the *penalty* to the jitted loss
(identical gradients via autodiff, and XLA fuses the extra terms).
"""

from __future__ import annotations


def _jnp():
    import jax.numpy as jnp

    return jnp


class L1L2Regularizer:
    def __init__(self, l1: float = 0.0, l2: float = 0.0):
        self.l1, self.l2 = l1, l2

    def __call__(self, param):
        jnp = _jnp()
        loss = 0.0
        if self.l1:
            loss = loss + self.l1 * jnp.sum(jnp.abs(param))
        if self.l2:
            loss = loss + 0.5 * self.l2 * jnp.sum(param * param)
        return loss


class L1Regularizer(L1L2Regularizer):
    def __init__(self, l1: float):
        super().__init__(l1=l1)


class L2Regularizer(L1L2Regularizer):
    def __init__(self, l2: float):
        super().__init__(l2=l2)
