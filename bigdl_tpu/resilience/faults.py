"""Deterministic fault injection — the chaos half of the resilience layer.

The reference inherits its failure model from Spark: executors die, tasks
are re-run, the driver reloads the last checkpoint when
``retryNum < maxRetry`` («bigdl»/optim/DistriOptimizer.scala tail,
SURVEY.md §3.2/§5).  None of that is exercisable on demand — you wait for
a preemption.  The rebuild makes every recovery path a *unit test*: a
config/env-driven fault plan

    BIGDL_FAULT_PLAN="step:3:raise,step:7:nan_grad,ckpt:1:truncate"

injects failures at exact, reproducible points:

* ``step:N:raise``     — raise :class:`InjectedFault` (classified
  transient) before dispatching training iteration ``neval == N``
* ``step:N:nan_grad``  — poison iteration N's input batch with NaN so
  the gradients go non-finite (exercises the non-finite step guard)
* ``ckpt:K:truncate``  — truncate the K-th checkpoint write's
  ``.model.npz`` to half its size (torn write / crashed host)
* ``ckpt:K:corrupt``   — flip bytes in the middle of the K-th write's
  ``.model.npz`` (bit rot the checksum manifest must catch)
* ``ckpt:K:delete``    — delete the K-th write's ``.model.npz``
* ``ckpt:K:drop_optim``— delete the K-th write's ``.optim.npz`` (a
  checkpoint missing its optimizer pair is not intact)
* ``publish:K:<action>`` — same four damage actions, applied to the
  K-th checkpoint *publish* (``serving/rollout.publish_checkpoint`` —
  the training->serving handover directory).  A mid-publish-corrupted
  checkpoint is exactly what the rollout watcher's verify-before-swap
  gate must refuse: never loaded, counted, event-stamped.

Every fault fires exactly once per injector lifetime: the retry path
replays the same ``neval`` range after reloading a checkpoint and must
not re-trip the fault it is recovering from (deterministic chaos, not a
crash loop).  Counters survive across retries inside one process;
``Engine.reset()`` / :func:`reset_injector` start a fresh plan.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import List, Optional

import numpy as np

log = logging.getLogger("bigdl_tpu.resilience")

_STEP_ACTIONS = ("raise", "nan_grad")
_CKPT_ACTIONS = ("truncate", "corrupt", "delete", "drop_optim")


class InjectedFault(RuntimeError):
    """A deliberately injected transient failure (retry-classified as
    transient — the whole point is to drive the recovery path)."""


@dataclasses.dataclass
class Fault:
    site: str      # "step" | "ckpt" | "publish"
    index: int     # step: the neval it fires at; ckpt/publish: 1-based
    action: str    # write (publish) count it fires on
    fired: bool = False


class FaultPlan:
    """Parsed, validated fault plan (see module docstring for syntax)."""

    def __init__(self, faults: Optional[List[Fault]] = None):
        self.faults = list(faults or [])

    @classmethod
    def parse(cls, spec: Optional[str]) -> "FaultPlan":
        faults = []
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            fields = part.split(":")
            if len(fields) != 3:
                raise ValueError(
                    f"bad fault spec {part!r}: want site:index:action, "
                    f"e.g. 'step:3:raise' (full plan: {spec!r})")
            site, idx, action = fields
            if site not in ("step", "ckpt", "publish"):
                raise ValueError(
                    f"bad fault site {site!r} in {part!r}: "
                    "want 'step', 'ckpt' or 'publish'")
            try:
                index = int(idx)
            except ValueError:
                raise ValueError(
                    f"bad fault index {idx!r} in {part!r}: want an int")
            allowed = (_STEP_ACTIONS if site == "step"
                       else _CKPT_ACTIONS)
            if action not in allowed:
                raise ValueError(
                    f"bad fault action {action!r} for site {site!r} in "
                    f"{part!r}: want one of {allowed}")
            faults.append(Fault(site, index, action))
        return cls(faults)

    def __bool__(self):
        return bool(self.faults)


class FaultInjector:
    """Stateful executor of a :class:`FaultPlan`.

    Hook points: the optimizer step dispatch calls :meth:`on_step` with
    the iteration counter; ``write_checkpoint`` calls
    :meth:`on_checkpoint_write` after the files are durable (so the
    corruption models post-write damage the integrity manifest must
    catch, not a failed write).
    """

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._step_faults = [f for f in plan.faults if f.site == "step"]
        self._ckpt_faults = [f for f in plan.faults if f.site == "ckpt"]
        self._publish_faults = [f for f in plan.faults
                                if f.site == "publish"]
        self.ckpt_writes = 0
        self.publish_writes = 0

    @property
    def active(self) -> bool:
        return bool(self.plan)

    # ------------------------------------------------------------- step site
    def on_step(self, neval: int) -> Optional[str]:
        """Called before dispatching iteration ``neval``.  Raises
        :class:`InjectedFault` for a ``raise`` fault; returns the action
        name for batch-level faults (``nan_grad``) the caller applies;
        returns None when nothing fires."""
        for f in self._step_faults:
            if not f.fired and f.index == neval:
                f.fired = True
                log.warning("fault injection: %s at step %d", f.action,
                            neval)
                if f.action == "raise":
                    raise InjectedFault(
                        f"injected fault at training step {neval}")
                return f.action
        return None

    @staticmethod
    def poison_batch(inp):
        """``nan_grad``: replace the input batch with NaN so the step's
        gradients (and loss) go non-finite."""
        a = np.asarray(inp, dtype=np.float32)
        return np.full_like(a, np.nan)

    # ------------------------------------------------------------- ckpt site
    def on_checkpoint_write(self, path_prefix: str):
        """Called after the ``path_prefix`` checkpoint pair (and its
        manifest) hit disk; applies any ckpt fault whose 1-based write
        index matches."""
        self.ckpt_writes += 1
        for f in self._ckpt_faults:
            if not f.fired and f.index == self.ckpt_writes:
                f.fired = True
                log.warning("fault injection: %s on checkpoint write #%d "
                            "(%s)", f.action, self.ckpt_writes, path_prefix)
                self._apply_ckpt_fault(f.action, path_prefix)

    def on_checkpoint_publish(self, path_prefix: str):
        """Called after a checkpoint is published into a rollout watch
        directory (files + manifest durable); applies any ``publish``
        fault whose 1-based publish index matches — post-write damage
        the watcher's verify-before-swap gate must catch."""
        self.publish_writes += 1
        for f in self._publish_faults:
            if not f.fired and f.index == self.publish_writes:
                f.fired = True
                log.warning("fault injection: %s on checkpoint publish "
                            "#%d (%s)", f.action, self.publish_writes,
                            path_prefix)
                self._apply_ckpt_fault(f.action, path_prefix)

    @staticmethod
    def _apply_ckpt_fault(action: str, path_prefix: str):
        model_path = path_prefix + ".model.npz"
        optim_path = path_prefix + ".optim.npz"
        if action == "truncate":
            size = os.path.getsize(model_path)
            os.truncate(model_path, size // 2)
        elif action == "corrupt":
            size = os.path.getsize(model_path)
            with open(model_path, "r+b") as fh:
                fh.seek(size // 2)
                chunk = bytearray(fh.read(64))
                fh.seek(size // 2)
                fh.write(bytes(b ^ 0xFF for b in chunk))
        elif action == "delete":
            os.remove(model_path)
        elif action == "drop_optim":
            if os.path.exists(optim_path):
                os.remove(optim_path)


# -------------------------------------------------------- process singleton
_injector: Optional[FaultInjector] = None
_plan_str: Optional[str] = None


def get_injector() -> FaultInjector:
    """The process-global injector, built from ``config.fault_plan``
    (env ``BIGDL_FAULT_PLAN``, read-at-call-time like Engine.init) and
    rebuilt whenever the plan string changes.  Fire-once state lives
    here so it survives optimizer retries within one plan."""
    global _injector, _plan_str
    from bigdl_tpu.config import refresh_from_env

    spec = refresh_from_env().fault_plan or ""
    if _injector is None or spec != _plan_str:
        _plan_str = spec
        _injector = FaultInjector(FaultPlan.parse(spec))
    return _injector


def reset_injector():
    """Drop the global injector (fresh fire-once counters); the next
    :func:`get_injector` rebuilds from the current config."""
    global _injector, _plan_str
    _injector = None
    _plan_str = None
