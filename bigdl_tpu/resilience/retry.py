"""Error classification + retry policy for the training driver.

The reference's failure semantics come from Spark («bigdl»/optim/
DistriOptimizer.scala): any Throwable in the iteration job is retried
``retryNum < maxRetry`` times by reloading the last checkpoint.  Blind
retry is wrong on both sides: a bad ``wire_dtype`` (ValueError) burns
every retry reloading checkpoints it can never use, while a genuinely
transient XLA/host hiccup deserves backoff, not an immediate hot loop.

This module gives ``DistriOptimizer.optimize`` the classified policy:

* :func:`classify` — ``"transient"`` (retry from checkpoint: OSError,
  RuntimeError incl. XLA runtime errors, :class:`InjectedFault`,
  :class:`NonFiniteStepError`) vs ``"fatal"`` (surface immediately:
  ValueError/TypeError/KeyError… — config/programming errors — plus
  :class:`CheckpointWriteError`, because retrying on top of a broken
  checkpoint sink only destroys more progress).  BaseExceptions
  (KeyboardInterrupt/SystemExit) are always fatal.
* :class:`RetryPolicy` — exponential backoff with deterministic jitter,
  a per-run attempt cap, and a sliding-window budget so a flapping
  failure that *keeps* recovering cannot retry forever.
* :func:`backoff_delay` — the one jittered-exponential-backoff formula,
  shared by :class:`RetryPolicy` and every caller that used to hand-roll
  an immediate-retry loop or a bare ``time.sleep``.
* :class:`RetryBudget` — a *shared* token-bucket budget across many
  concurrent requests (the serving router, the fleet scraper): each
  admitted request deposits ``ratio`` tokens, each retry spends one, so
  fleet-wide retry traffic is capped at ``~ratio x`` the request rate no
  matter how many individual requests see failures.  This is what stops
  a browning-out replica from turning N slow requests into N x retries
  of amplified load.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Optional

from bigdl_tpu.resilience.faults import InjectedFault


class NonFiniteStepError(RuntimeError):
    """N consecutive non-finite (skipped) steps: the run is diverging or
    an input shard is poisoned — escalate from skip-and-continue to the
    retry policy (reload last checkpoint)."""


class CheckpointWriteError(RuntimeError):
    """A background checkpoint write failed earlier; surfaced on the
    next ``_checkpoint``/``optimize`` call so the failure is never
    silently reduced to a log line."""


class PeerLostError(RuntimeError):
    """A multi-host peer stopped heartbeating (resilience/elastic.py).
    Classified FATAL in-process: retrying from a checkpoint at the same
    world size would hang in the first collective all over again — the
    process must exit so the supervisor/launcher can re-form the world
    (possibly at a new size; checkpoints are topology-tagged)."""


# config/programming errors: retrying cannot change the outcome
FATAL_TYPES = (
    ValueError,
    TypeError,
    KeyError,
    IndexError,
    AttributeError,
    NotImplementedError,
    AssertionError,
    ImportError,
    UnicodeError,
)


def classify(exc: BaseException) -> str:
    """``"transient"`` (retry from checkpoint) or ``"fatal"`` (raise)."""
    if not isinstance(exc, Exception):
        return "fatal"  # KeyboardInterrupt / SystemExit / GeneratorExit
    if isinstance(exc, (InjectedFault, NonFiniteStepError)):
        return "transient"
    if isinstance(exc, (CheckpointWriteError, PeerLostError)):
        return "fatal"
    if isinstance(exc, FATAL_TYPES):
        return "fatal"
    # OSError, RuntimeError (XlaRuntimeError subclasses it), MemoryError,
    # and anything unrecognised: the reference retried every Throwable —
    # keep that default for the unknown tail
    return "transient"


def backoff_delay(attempt: int, base: float = 0.5, cap: float = 30.0,
                  jitter: float = 0.1,
                  rng: Optional[random.Random] = None) -> float:
    """Jittered exponential backoff for attempt ``attempt`` (1-based):
    ``min(cap, base * 2^(attempt-1)) * (1 + jitter * U[0,1))``.  The
    jitter term decorrelates a thundering herd of callers that failed
    at the same instant; pass a seeded ``rng`` for reproducible chaos
    tests (no rng = module-level randomness)."""
    delay = min(float(cap), float(base) * (2.0 ** (max(1, int(attempt)) - 1)))
    u = (rng.random() if rng is not None else random.random())
    return delay * (1.0 + float(jitter) * u)


class RetryBudget:
    """Shared token-bucket retry budget across concurrent requests.

    Deliberately *count*-based, not clock-based: every admitted request
    deposits ``ratio`` tokens (the bucket is capped at ``burst``), and
    every retry anywhere in the process spends one.  Total retries are
    therefore bounded by ``burst + ratio * requests`` regardless of how
    failures are distributed — the retry-amplification cap the serving
    chaos scenarios assert — and the arithmetic is identical under a
    virtual clock and a wall clock.  Thread-safe; ``try_spend`` never
    blocks (an exhausted budget is a *shed load now* signal, never a
    queue)."""

    def __init__(self, ratio: float = 0.2, burst: float = 8.0,
                 initial: Optional[float] = None):
        if ratio < 0:
            raise ValueError(f"retry budget ratio must be >= 0, got {ratio}")
        self.ratio = float(ratio)
        self.burst = max(0.0, float(burst))
        self._tokens = self.burst if initial is None \
            else min(self.burst, float(initial))
        self._lock = threading.Lock()
        self.requests = 0
        self.spent = 0
        self.denied = 0

    def record_request(self) -> None:
        """One admitted request: deposit ``ratio`` tokens (capped)."""
        with self._lock:
            self.requests += 1
            self._tokens = min(self.burst, self._tokens + self.ratio)

    def try_spend(self, cost: float = 1.0) -> bool:
        """Spend ``cost`` tokens for one retry; False = budget
        exhausted, the caller must shed (503 + Retry-After), not wait."""
        with self._lock:
            if self._tokens >= cost:
                self._tokens -= cost
                self.spent += 1
                return True
            self.denied += 1
            return False

    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def stats(self) -> dict:
        with self._lock:
            return {"tokens": self._tokens, "burst": self.burst,
                    "ratio": self.ratio, "requests": self.requests,
                    "retries_granted": self.spent,
                    "retries_denied": self.denied}


class RetryPolicy:
    """Backoff + budget for transient training failures.

    ``record_failure`` returns the delay (seconds) to sleep before the
    next attempt, or ``None`` when the budget is exhausted and the
    caller must re-raise.  Jitter is drawn from a seeded PRNG so chaos
    tests are bit-reproducible.
    """

    def __init__(self, max_retries: int = 5, backoff_base: float = 0.5,
                 backoff_max: float = 30.0, jitter: float = 0.1,
                 window_seconds: float = 600.0, window_budget: int = 16,
                 seed: int = 0):
        self.max_retries = int(max_retries)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.jitter = float(jitter)
        self.window_seconds = float(window_seconds)
        self.window_budget = int(window_budget)
        self.attempts = 0
        self._window = deque()
        self._rng = random.Random(seed)

    @classmethod
    def from_config(cls, max_retries: Optional[int] = None) -> "RetryPolicy":
        from bigdl_tpu.config import refresh_from_env

        config = refresh_from_env()
        return cls(
            max_retries=5 if max_retries is None else max_retries,
            backoff_base=config.retry_backoff_base,
            backoff_max=config.retry_backoff_max,
            window_seconds=config.retry_window_seconds,
            window_budget=config.retry_window_budget,
        )

    def record_failure(self, exc: Optional[BaseException] = None,
                       now: Optional[float] = None) -> Optional[float]:
        """Account one transient failure.  Returns the backoff delay, or
        None when either the attempt cap or the sliding-window budget is
        blown.  ``now`` (monotonic seconds) is injectable for tests."""
        del exc  # classification happened at the caller; kept for logs
        t = time.monotonic() if now is None else now
        self.attempts += 1
        self._window.append(t)
        while self._window and self._window[0] < t - self.window_seconds:
            self._window.popleft()
        if self.attempts > self.max_retries:
            return None
        if len(self._window) > self.window_budget:
            return None
        return backoff_delay(self.attempts, base=self.backoff_base,
                             cap=self.backoff_max, jitter=self.jitter,
                             rng=self._rng)
