"""bigdl_tpu.resilience — fault tolerance for the training stack.

The reference BigDL leaned on Spark for every failure mode: task retry,
executor loss, driver ``retryNum < maxRetry`` checkpoint reload
(SURVEY.md §3.2/§5).  The TPU rebuild owns those semantics itself:

* :mod:`~bigdl_tpu.resilience.faults` — deterministic fault injection
  (``BIGDL_FAULT_PLAN``) so every recovery path runs in CI on CPU
* :mod:`~bigdl_tpu.resilience.retry` — transient/fatal error
  classification + exponential backoff with a sliding-window budget
* :mod:`~bigdl_tpu.resilience.elastic` — preemption-safe shutdown
  (SIGTERM → finish step → emergency checkpoint → exit
  ``EXIT_PREEMPTED``), heartbeat peer-liveness for multi-host runs
  (``PeerLostError`` instead of a hung psum), and topology-tagged
  checkpoints whose ZeRO shards re-partition on a world resize
* :mod:`~bigdl_tpu.resilience.supervisor` — ``python -m
  bigdl_tpu.resilience.supervisor -- <train cmd>`` restart loop,
  classifying exit codes against the retry budget
* :mod:`~bigdl_tpu.resilience.autoscale` — the policy loop that
  *drives* a resize: declarative rules over the live fleet signals
  (step time, stream queue depth, goodput, alerts, stragglers) decide
  a new world size; the supervisor executes it as a graceful
  checkpoint-stop-restart
* checkpoint integrity lives in ``bigdl_tpu/utils/serializer.py``
  (manifest checksums, verify-on-load, newest-intact fallback,
  keep-last-K rotation)
* the non-finite step guard lives in the jitted train steps
  (``optim/optimizer.py`` / ``optim/distri_optimizer.py``)
"""

from bigdl_tpu.resilience.autoscale import (
    AutoscaleController,
    Decision,
)
from bigdl_tpu.resilience.elastic import (
    EXIT_FATAL,
    EXIT_PREEMPTED,
    EXIT_TRANSIENT,
    ElasticSession,
    HeartbeatMonitor,
    Preempted,
    clear_preemption,
    ensure_shard_layout,
    install_preemption_handler,
    preemption_requested,
    record_resume,
    request_preemption,
    restore_latest,
    run_main,
)
from bigdl_tpu.resilience.faults import (
    Fault,
    FaultInjector,
    FaultPlan,
    InjectedFault,
    get_injector,
    reset_injector,
)
from bigdl_tpu.resilience.retry import (
    CheckpointWriteError,
    FATAL_TYPES,
    NonFiniteStepError,
    PeerLostError,
    RetryPolicy,
    classify,
)

__all__ = [
    "AutoscaleController",
    "CheckpointWriteError",
    "Decision",
    "EXIT_FATAL",
    "EXIT_PREEMPTED",
    "EXIT_TRANSIENT",
    "ElasticSession",
    "FATAL_TYPES",
    "Fault",
    "FaultInjector",
    "FaultPlan",
    "HeartbeatMonitor",
    "InjectedFault",
    "NonFiniteStepError",
    "PeerLostError",
    "Preempted",
    "RetryPolicy",
    "classify",
    "clear_preemption",
    "ensure_shard_layout",
    "get_injector",
    "install_preemption_handler",
    "preemption_requested",
    "record_resume",
    "request_preemption",
    "reset_injector",
    "restore_latest",
    "run_main",
]
