"""bigdl_tpu.resilience — fault tolerance for the training stack.

The reference BigDL leaned on Spark for every failure mode: task retry,
executor loss, driver ``retryNum < maxRetry`` checkpoint reload
(SURVEY.md §3.2/§5).  The TPU rebuild owns those semantics itself:

* :mod:`~bigdl_tpu.resilience.faults` — deterministic fault injection
  (``BIGDL_FAULT_PLAN``) so every recovery path runs in CI on CPU
* :mod:`~bigdl_tpu.resilience.retry` — transient/fatal error
  classification + exponential backoff with a sliding-window budget
* checkpoint integrity lives in ``bigdl_tpu/utils/serializer.py``
  (manifest checksums, verify-on-load, newest-intact fallback,
  keep-last-K rotation)
* the non-finite step guard lives in the jitted train steps
  (``optim/optimizer.py`` / ``optim/distri_optimizer.py``)
"""

from bigdl_tpu.resilience.faults import (
    Fault,
    FaultInjector,
    FaultPlan,
    InjectedFault,
    get_injector,
    reset_injector,
)
from bigdl_tpu.resilience.retry import (
    CheckpointWriteError,
    FATAL_TYPES,
    NonFiniteStepError,
    RetryPolicy,
    classify,
)

__all__ = [
    "CheckpointWriteError",
    "FATAL_TYPES",
    "Fault",
    "FaultInjector",
    "FaultPlan",
    "InjectedFault",
    "NonFiniteStepError",
    "RetryPolicy",
    "classify",
    "get_injector",
    "reset_injector",
]
