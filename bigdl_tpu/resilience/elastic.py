"""Elastic training — preemption, peer liveness, world-resize resume.

The reference survives process loss because Spark reschedules the task
and ``DistriOptimizer`` re-enters from the last checkpoint; nothing in
that story covers the TPU operational reality this module owns:

* **Preemption** — the scheduler's SIGTERM (or an operator's Ctrl-C)
  must *finish the in-flight step*, write an emergency checkpoint
  through the hardened ``write_checkpoint`` path, flush the obs shards,
  and exit with the distinct :data:`EXIT_PREEMPTED` code so a
  supervisor can tell "evicted, resume me" from "crashed".  The signal
  handler (installed by ``Engine.init``) only sets a flag; both
  optimizers poll it at iteration boundaries — no state is ever torn
  mid-step.
* **Peer liveness** — a hung host in a multi-host world stalls every
  peer *forever* inside the next collective (psum has no timeout).
  Each host touches a host-tagged heartbeat file every
  ``BIGDL_HEARTBEAT_EVERY`` steps; a monitor thread (plus an explicit
  per-iteration check) flags any peer silent past
  ``BIGDL_HEARTBEAT_TIMEOUT`` seconds and the training loop raises a
  classified-**fatal** :class:`PeerLostError` *before* entering the
  collective that would deadlock.
* **World resize** — checkpoints carry ``{world_size, shard_layout,
  step}`` topology metadata, and :func:`ensure_shard_layout`
  re-partitions the flat ZeRO-1 optimizer-state vectors written at N
  shards for an M-shard mesh (strip the old alignment padding, re-pad
  for the new quantum, re-place over the new mesh) — restore is
  topology-independent, so a 2-host checkpoint resumes on 1 host and
  vice versa.
* **Supervision** — ``python -m bigdl_tpu.resilience.supervisor``
  (resilience/supervisor.py) loops the training command, classifying
  exit codes against the PR 1 :class:`~bigdl_tpu.resilience.retry.
  RetryPolicy` budget.

Everything here is driven deterministically by the PR 1 fault plans and
plain POSIX signals, so every recovery path is a CPU unit test.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import threading
import time
from typing import Optional

from bigdl_tpu.resilience.retry import PeerLostError
from bigdl_tpu.obs import names

log = logging.getLogger("bigdl_tpu.resilience")

# -------------------------------------------------------------- exit codes
# The supervisor contract.  Distinct from shell/signal conventions
# (126/127/128+n) and from sysexits so nothing else can alias them:
# preempted = evicted mid-run with an emergency checkpoint on disk —
# restart costs no retry budget; transient = EX_TEMPFAIL, restart under
# the RetryPolicy budget; fatal = EX_CONFIG, restarting cannot help.
EXIT_PREEMPTED = 170
EXIT_TRANSIENT = 75
EXIT_FATAL = 78


class Preempted(SystemExit):
    """Graceful preemption shutdown.  A ``SystemExit`` subclass so an
    unhandled one exits the interpreter with :data:`EXIT_PREEMPTED`
    (the supervisor's "resume me" signal) and so the classified retry
    loop — which only catches ``Exception`` — never swallows it."""

    def __init__(self, message: str = "preempted", step: Optional[int] = None,
                 checkpoint: Optional[str] = None):
        super().__init__(EXIT_PREEMPTED)
        self.message = message
        self.step = step
        self.checkpoint = checkpoint

    def __str__(self):
        return self.message


# ------------------------------------------------------- preemption flag
# One process-wide flag: the signal handler SETS it (async-signal-thin:
# flag + bookkeeping only), training loops POLL it at iteration
# boundaries so the in-flight step always completes.
_flag = threading.Event()
_signum: Optional[int] = None
_listeners = 0
_listener_lock = threading.Lock()
_installed: dict = {}  # signum -> previous handler


def preemption_requested() -> bool:
    return _flag.is_set()


def preemption_signal() -> Optional[int]:
    """The signal number that requested preemption (None if requested
    programmatically or not at all)."""
    return _signum


def request_preemption(signum: Optional[int] = None):
    """Programmatic preemption (tests / cooperative shutdown): the next
    iteration boundary runs the same graceful path a SIGTERM would."""
    global _signum
    _signum = signum
    _flag.set()


def clear_preemption():
    """Drop the flag (test hook / after a handled preemption)."""
    global _signum
    _signum = None
    _flag.clear()


def _add_listener():
    global _listeners
    with _listener_lock:
        _listeners += 1


def _remove_listener():
    global _listeners
    with _listener_lock:
        _listeners = max(0, _listeners - 1)


def _handler(signum, frame):
    request_preemption(signum)
    log.warning("elastic: received signal %d — finishing the in-flight "
                "step, then emergency checkpoint + exit %d",
                signum, EXIT_PREEMPTED)
    try:
        from bigdl_tpu import obs

        obs.get_tracer().event("elastic.preempt_signal", signum=signum,
                               listeners=_listeners)
    except Exception:  # noqa: BLE001 — telemetry must not break shutdown
        pass
    if _listeners == 0:
        # no training loop is polling: nothing will ever act on the
        # flag, so exit from here (atexit still flushes obs shards).
        # SIGINT outside training keeps its interactive meaning.
        prev = _installed.get(signum)
        if signum == getattr(signal, "SIGINT", None):
            if callable(prev):
                return prev(signum, frame)
            raise KeyboardInterrupt
        raise Preempted(f"signal {signum} with no active training loop")


def install_preemption_handler(signals=None) -> bool:
    """Install the SIGTERM/SIGINT preemption handler (idempotent;
    called by ``Engine.init``).  Returns False when handlers cannot be
    installed (non-main thread) — training then simply lacks graceful
    preemption, it does not fail."""
    if signals is None:
        signals = (signal.SIGTERM, signal.SIGINT)
    ok = True
    for s in signals:
        if s in _installed:
            continue
        try:
            _installed[s] = signal.signal(s, _handler)
        except (ValueError, OSError):  # not the main thread / exotic env
            log.debug("elastic: cannot install handler for signal %s "
                      "(not the main thread?)", s)
            ok = False
    return ok


def uninstall_preemption_handler():
    """Restore the pre-install handlers (test hook)."""
    for s, prev in list(_installed.items()):
        try:
            signal.signal(s, prev)
        except (ValueError, OSError):
            pass
        _installed.pop(s, None)


# ---------------------------------------------------------- peer liveness
class HeartbeatMonitor:
    """Heartbeat-file peer liveness for multi-host runs.

    Each host writes ``heartbeat.h<host>`` in a shared directory every
    ``every_steps`` training steps (:meth:`beat`); :meth:`check` — run
    at every iteration boundary, plus a daemon thread for telemetry
    while the main thread is blocked on device work — compares every
    peer file's mtime against ``timeout_s`` and raises
    :class:`PeerLostError` (classified fatal) instead of letting the
    next psum hang forever on a dead peer.  A peer that never wrote a
    file at all counts from this monitor's start time, so a host that
    dies during bring-up is caught too."""

    def __init__(self, directory: str, host: int, n_hosts: int,
                 timeout_s: float = 60.0, every_steps: int = 1,
                 interval_s: Optional[float] = None, clock=time.time):
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.host = int(host)
        self.n_hosts = int(n_hosts)
        self.timeout_s = float(timeout_s)
        self.every_steps = max(1, int(every_steps))
        self.interval_s = (interval_s if interval_s is not None
                           else max(0.05, min(1.0, self.timeout_s / 4.0)))
        self._clock = clock
        self._started = clock()
        self._last_beat_step: Optional[int] = None
        self._lost: dict = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def path(self, host: int) -> str:
        return os.path.join(self.directory, f"heartbeat.h{host}")

    def beat(self, step: Optional[int] = None, force: bool = False):
        """Touch this host's heartbeat file (every ``every_steps``
        steps; ``force`` beats unconditionally, e.g. at session start)."""
        if not force and step is not None and \
                self._last_beat_step is not None and \
                0 <= step - self._last_beat_step < self.every_steps:
            # (a step that moved BACKWARDS — retry rewound neval —
            # always beats rather than starving until it catches up)
            return
        self._last_beat_step = step
        p = self.path(self.host)
        tmp = p + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump({"host": self.host, "step": step,
                           "ts": self._clock()}, fh)
            os.replace(tmp, p)
        except OSError as e:  # a full/blipping shared FS must not kill
            log.warning("heartbeat write failed: %s", e)  # the trainer

    def peer_ages(self, now: Optional[float] = None) -> dict:
        """Seconds since each peer's last beat (monitor start stands in
        for a peer that never beat)."""
        now = self._clock() if now is None else now
        ages = {}
        for h in range(self.n_hosts):
            if h == self.host:
                continue
            try:
                last = os.path.getmtime(self.path(h))
            except OSError:
                last = self._started
            ages[h] = now - last
        return ages

    def scan(self, now: Optional[float] = None) -> dict:
        """Flag peers silent past the timeout; returns {host: age}.
        Each newly lost peer emits one ``elastic.peer_lost`` trace
        event and one ``bigdl_peer_lost_total`` increment.  Every scan
        also mirrors the per-peer ages into
        ``bigdl_heartbeat_age_seconds{host}`` gauges — staleness as
        *data* the alert engine and ``/healthz`` can watch degrade,
        not only the terminal :class:`PeerLostError`."""
        ages = self.peer_ages(now)
        if ages:
            from bigdl_tpu import obs

            gauge = obs.get_registry().gauge(
                names.HEARTBEAT_AGE_SECONDS,
                "Seconds since each peer host's last heartbeat file "
                "write", labels=("host",))
            for h, age in ages.items():
                gauge.labels(host=h).set(round(max(0.0, age), 3))
        for h, age in ages.items():
            if age > self.timeout_s and h not in self._lost:
                self._lost[h] = age
                log.error("elastic: peer host %d silent for %.1fs "
                          "(timeout %.1fs)", h, age, self.timeout_s)
                from bigdl_tpu import obs

                obs.get_tracer().event(
                    "elastic.peer_lost", peer=h, age_s=round(age, 3),
                    timeout_s=self.timeout_s, host=self.host)
                obs.get_registry().counter(
                    names.PEER_LOST_TOTAL,
                    "Peers flagged dead by the heartbeat monitor").inc()
        return dict(self._lost)

    def check(self):
        """Raise :class:`PeerLostError` when any peer is lost — called
        at iteration boundaries, BEFORE the step that would hang."""
        lost = self.scan()
        if lost:
            detail = ", ".join(f"host {h} silent {age:.1f}s"
                               for h, age in sorted(lost.items()))
            raise PeerLostError(
                f"peer(s) lost past BIGDL_HEARTBEAT_TIMEOUT="
                f"{self.timeout_s:g}s: {detail}; refusing to enter the "
                "next collective (it would hang forever)")

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="bigdl-heartbeat", daemon=True)
            self._thread.start()

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.scan()
            except Exception:  # noqa: BLE001 — monitor must never die
                log.exception("heartbeat scan failed")

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


# ---------------------------------------------------------------- session
class ElasticSession:
    """Per-``optimize()`` elastic state: registers this loop as a
    preemption listener and owns the optional heartbeat monitor."""

    def __init__(self, monitor: Optional[HeartbeatMonitor] = None):
        self.monitor = monitor
        _add_listener()
        if monitor is not None:
            monitor.beat(force=True)
            monitor.start()

    @classmethod
    def from_config(cls) -> "ElasticSession":
        from bigdl_tpu.config import refresh_from_env

        cfg = refresh_from_env()
        monitor = None
        if cfg.heartbeat_dir and cfg.num_processes > 1:
            monitor = HeartbeatMonitor(
                cfg.heartbeat_dir, cfg.process_id, cfg.num_processes,
                timeout_s=cfg.heartbeat_timeout,
                every_steps=cfg.heartbeat_every)
        return cls(monitor)

    def on_iteration(self, step: int) -> bool:
        """Iteration-boundary poll: beat + peer check (may raise
        :class:`PeerLostError`); returns True when a preemption is
        pending and the caller must run its graceful shutdown."""
        if self.monitor is not None:
            self.monitor.beat(step)
            self.monitor.check()
        return _flag.is_set()

    def close(self):
        _remove_listener()
        if self.monitor is not None:
            self.monitor.stop()


# -------------------------------------------------- topology-aware resume
def ensure_shard_layout(state: dict, flat_elems: int, pad: int,
                        n_shards: int, mesh, axis,
                        topology: Optional[dict] = None,
                        buckets=None) -> dict:
    """Re-partition loaded ZeRO-1 optimizer state for the CURRENT mesh.

    The flat shard layout makes resize mechanical: a state vector saved
    at N shards is the padded flat-parameter layout (``flat_elems`` true
    entries + the N-world alignment padding), element-aligned with the
    ravelled weights.  Restoring at M shards = strip the old padding,
    re-pad for the M-world quantum, and place ``P(axis)`` over the new
    mesh.  Entries already matching the current layout (same-world
    resume, the common case) pass through untouched; scalars always do.

    **Bucketed overlap layouts** (ISSUE 11): a run trained with
    ``overlap_bucket_mb`` leaves the state vectors in shard-major
    bucket-chunk order — each device owns one chunk of every bucket —
    recorded as ``topology["buckets"]``.  Restoring under a different
    plan (or world) first un-permutes to flat-parameter coordinates via
    :func:`parallel.wire.bucket_param_coords`, strips/re-pads, then
    permutes into the NEW plan (``buckets``).  Same-plan same-world
    resumes still pass through bit-for-bit.

    The ``wire_ef`` error-feedback residual (parallel/wire.py; one
    ``(world, padded)`` f32 row per device, flat-parameter coords) is
    *per-device per-chunk* state — an N-world (or different-bucket-
    plan) residual has no chunk-assignment meaning under the new
    layout — so a resize or plan change **resets it to zeros**, per
    bucket and all at once.  Safe by construction: the residual is a
    correction term the next exchange re-derives; dropping it costs one
    step of ordinary (un-fed-back) quantization error, never
    correctness.  Same-world same-plan resumes keep the checkpointed
    residual bit-for-bit.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import jax.numpy as jnp

    from bigdl_tpu.parallel import wire as _W

    padded = flat_elems + pad
    old_topo = topology or {}
    old_buckets = old_topo.get("buckets")
    old_world = old_topo.get("world_size")
    plan_changed = not _W.buckets_equal(old_buckets, buckets)
    # multi-bucket plans at different worlds permute differently even
    # when the plan itself matches (chunk = size // world)
    if not plan_changed and buckets is not None and len(buckets) > 1 \
            and old_world is not None and int(old_world) != int(n_shards):
        plan_changed = True
    ef = state.get("wire_ef")
    ef_stale = ef is not None and (
        tuple(ef.shape) != (n_shards, padded) or plan_changed)
    stale = [k for k, v in state.items()
             if k != "wire_ef" and getattr(v, "ndim", None) == 1
             and v.shape[0] >= flat_elems
             and (v.shape[0] != padded or plan_changed)]
    if ef_stale:
        state = dict(state)
        state["wire_ef"] = jax.device_put(
            jnp.zeros((n_shards, padded), jnp.float32),
            NamedSharding(mesh, P(axis, None)))
        log.info("elastic: reset the wire_ef error-feedback residual "
                 "%s -> %s on world resize / bucket-plan change",
                 tuple(ef.shape), (n_shards, padded))
        from bigdl_tpu import obs

        obs.get_tracer().event(
            "elastic.ef_reset", old_shape=list(ef.shape),
            new_shape=[n_shards, padded],
            old_world=old_world, new_world=n_shards,
            plan_changed=bool(plan_changed))
    if not stale:
        return state
    old_len = state[stale[0]].shape[0]
    for k in stale:
        if state[k].shape[0] != old_len:
            raise ValueError(
                "inconsistent optimizer-state vector lengths "
                f"{ {k: int(state[k].shape[0]) for k in stale} }; the "
                "checkpoint does not look like one flat ZeRO layout")
    # index maps between shard-major and flat-parameter order; None =
    # identity (the monolithic single-bucket layout IS parameter-major)
    old_coords = None
    if old_buckets is not None and len(old_buckets) > 1:
        if not old_world:
            raise ValueError(
                "checkpoint topology carries a bucket plan but no "
                "world_size — cannot un-permute the shard-major state")
        old_coords = _W.bucket_param_coords(old_buckets, int(old_world))
        if old_coords.shape[0] != old_len:
            raise ValueError(
                f"topology bucket plan covers {old_coords.shape[0]} "
                f"elems but the state vectors hold {old_len}")
    new_coords = None
    if buckets is not None and len(buckets) > 1:
        new_coords = _W.bucket_param_coords(buckets, int(n_shards))
    new_state = dict(state)
    for k in stale:
        v = jnp.asarray(state[k])
        if old_coords is not None:
            # param_major[old_coords] = shard_major
            v = jnp.zeros_like(v).at[old_coords].set(v)
        v = v[:flat_elems]
        if pad:
            v = jnp.concatenate([v, jnp.zeros((pad,), v.dtype)])
        if new_coords is not None:
            v = v[new_coords]
        new_state[k] = jax.device_put(v, NamedSharding(mesh, P(axis)))
    log.info("elastic: re-partitioned optimizer state %s from a "
             "%s-shard layout (%d elems) to %d shards (%d elems)%s",
             sorted(stale), old_world or "?", old_len, n_shards, padded,
             " across bucket plans" if plan_changed and (
                 old_coords is not None or new_coords is not None)
             else "")
    from bigdl_tpu import obs

    obs.get_tracer().event(
        "elastic.resize", old_world=old_world, new_world=n_shards,
        old_elems=int(old_len), new_elems=int(padded),
        keys=sorted(stale), plan_changed=bool(plan_changed))
    return new_state


def record_resume(old_world: Optional[int], new_world: int,
                  step: Optional[int] = None):
    """Account one resume-from-checkpoint: ``bigdl_resumes_total``
    labeled with the resize (``"2to1"``, ``"none"`` for same-world,
    ``"unknown"`` for pre-topology checkpoints) + a trace event."""
    if old_world is None:
        resize = "unknown"
    elif int(old_world) == int(new_world):
        resize = "none"
    else:
        resize = f"{int(old_world)}to{int(new_world)}"
    from bigdl_tpu import obs

    obs.get_registry().counter(
        names.RESUMES_TOTAL,
        "Resumes from checkpoint, labeled by world resize",
        labels=("resize",)).labels(resize=resize).inc()
    obs.get_tracer().event("elastic.resume", resize=resize,
                           old_world=old_world, new_world=new_world,
                           step=step)
    return resize


def restore_stream(optimizer, extra) -> bool:
    """Apply a checkpoint's ``stream`` state to a streaming dataset
    (dataset/stream.py): seek the source back to the trained offset so
    the resume re-reads exactly the records the rolled-back weights
    never kept — the exactly-once half of a crash/resize resume.  A
    streaming resume replaces the epoch fast-forward (the stream seeks
    by offset, not by replaying an epoch's batch order).  Returns True
    when the optimizer's dataset is streaming.  Both resume paths call
    this: ``restore_latest`` and the DistriOptimizer in-process
    retry."""
    restore = getattr(getattr(optimizer, "dataset", None),
                      "stream_restore", None)
    if restore is None:
        return False
    restore((extra or {}).get("stream"))
    optimizer._pending_fast_forward = 0
    return True


def restore_latest(optimizer, directory: Optional[str] = None):
    """Resume an optimizer from the newest intact checkpoint in
    ``directory`` (default: its own checkpoint path): load weights +
    optimizer state (re-partitioned lazily by the step build when the
    world changed), rewind the epoch/neval/epoch-start counters so
    triggers, LR schedule, RNG folding, and the mid-epoch fast-forward
    all resume exactly, and account the resume.  Returns the
    checkpoint's extra dict, or None when the directory holds no
    checkpoint yet (a first boot is not an error)."""
    from bigdl_tpu.utils.serializer import load_latest_checkpoint

    d = directory or optimizer.checkpoint_path
    if not d or not os.path.isdir(d):
        return None
    try:
        extra = load_latest_checkpoint(d, optimizer.model,
                                       optimizer.optim_method)
    except FileNotFoundError:
        return None
    if "epoch" in extra:
        optimizer.state["epoch"] = extra["epoch"]
    if "neval" in extra:
        optimizer.state["neval"] = extra["neval"]
    optimizer.state["epoch_neval0"] = extra.get(
        "epoch_neval0", optimizer.state["neval"])
    # a mid-epoch checkpoint resumes N batches into its epoch: the
    # driver loop skips that many so the replayed data order matches
    optimizer._pending_fast_forward = max(
        0, optimizer.state["neval"] - optimizer.state["epoch_neval0"])
    # streaming datasets seek by offset instead (clears the
    # fast-forward: a stream has no epoch order to replay)
    restore_stream(optimizer, extra)
    topo = extra.get("topology") or {}
    record_resume(topo.get("world_size"),
                  getattr(optimizer, "n_shards", 1),
                  step=optimizer.state.get("neval"))
    # goodput (obs/goodput.py): stamp the prior attempt's max step —
    # read from the earlier attempts' ledger shards — as the rework
    # high-water mark, so the replayed steps between the restored step
    # and the pre-crash front are accounted as rework badput, not
    # productive time
    from bigdl_tpu import obs
    from bigdl_tpu.obs import server as _obs_server

    obs.get_ledger().stamp_resume(optimizer.state.get("neval"))
    # re-stamp /healthz with the restored step: a resume that rewinds
    # neval must restart the hang watchdog's stall clock, not inherit
    # the dead attempt's stamp age
    if _obs_server.get_server() is not None:
        _obs_server.note_step(optimizer.state.get("neval") or 0)
    return extra


# ------------------------------------------------------------- entrypoint
def run_main(fn) -> int:
    """Entry-point wrapper mapping training outcomes onto the elastic
    exit-code contract: 0 on success, :data:`EXIT_PREEMPTED` via the
    :class:`Preempted` SystemExit, classified-fatal errors →
    :data:`EXIT_FATAL`, everything transient → :data:`EXIT_TRANSIENT`.
    Use as ``sys.exit(elastic.run_main(main))``."""
    from bigdl_tpu.resilience.retry import classify

    try:
        fn()
        return 0
    except SystemExit:
        raise  # incl. Preempted: the code is already the contract
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # noqa: BLE001 — the mapping IS the point
        code = EXIT_FATAL if classify(e) == "fatal" else EXIT_TRANSIENT
        log.exception("elastic.run_main: %s -> exit %d",
                      type(e).__name__, code)
        raise SystemExit(code)
