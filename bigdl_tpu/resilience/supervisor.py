"""Restart supervisor — the process-level half of elastic training.

The reference's driver survives because Spark restarts failed tasks and
``DistriOptimizer`` re-enters from the last checkpoint; here the
scheduler kills whole JAX processes (preemption) and nobody restarts
them.  This module is that restarter::

    python -m bigdl_tpu.resilience.supervisor [options] -- \
        python train.py ...

It loops the command, classifying each exit against the elastic
exit-code contract (resilience/elastic.py):

* ``0`` — done, exit 0.
* :data:`~bigdl_tpu.resilience.elastic.EXIT_PREEMPTED` — the child shut
  down gracefully with an emergency checkpoint on disk.  Restart
  immediately; preemptions consume no retry budget (an eviction is not
  a failure), bounded only by ``--max-preemptions``.
* :data:`~bigdl_tpu.resilience.elastic.EXIT_FATAL` (and shell usage
  errors) — restarting cannot help; exit with the child's code.
* anything else — transient.  Back off and restart under the PR 1
  :class:`~bigdl_tpu.resilience.retry.RetryPolicy` budget (attempt cap
  + sliding window), then give up with the child's code.

Each launch exports ``BIGDL_ELASTIC_ATTEMPT`` (0-based launch counter)
and ``BIGDL_ELASTIC_PREEMPTIONS`` so the child can adapt — e.g. rebuild
its mesh over however many hosts survived and resume via
``elastic.restore_latest`` (checkpoints are topology-tagged, so a
2-host snapshot restores on 1 host).  SIGTERM/SIGINT to the supervisor
forwards to the child, waits for its graceful exit, and stops the loop
(a preempted supervisor must not immediately respawn what the scheduler
is evicting).
"""

from __future__ import annotations

import logging
import os
import signal
import subprocess
import sys
import time
from typing import Callable, List, Optional, Sequence

from bigdl_tpu.resilience.elastic import (
    EXIT_FATAL,
    EXIT_PREEMPTED,
)
from bigdl_tpu.resilience.retry import RetryPolicy

log = logging.getLogger("bigdl_tpu.resilience")


class Supervisor:
    """Run ``cmd`` in a classify-and-restart loop.

    ``runner(cmd, env) -> returncode`` is injectable so every branch of
    the loop is a unit test with no subprocesses; the default runner
    spawns the real child and forwards SIGTERM/SIGINT to it."""

    def __init__(self, cmd: Sequence[str], max_retries: int = 5,
                 max_preemptions: int = 1000,
                 policy: Optional[RetryPolicy] = None,
                 runner: Optional[Callable] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 fatal_codes: Sequence[int] = (EXIT_FATAL, 2, 126, 127)):
        if not cmd:
            raise ValueError("supervisor needs a command to run")
        self.cmd = list(cmd)
        self.max_preemptions = int(max_preemptions)
        self.policy = policy or RetryPolicy.from_config(
            max_retries=max_retries)
        self._runner = runner or self._spawn
        self._sleep = sleep
        self.fatal_codes = set(int(c) for c in fatal_codes)
        self.attempt = 0          # 0-based launch counter (all launches)
        self.preemptions = 0
        self._child: Optional[subprocess.Popen] = None
        self._terminated = False  # the supervisor itself was signalled

    # ------------------------------------------------------------- child
    def _spawn(self, cmd: List[str], env: dict) -> int:
        self._child = subprocess.Popen(cmd, env=env)
        try:
            return self._child.wait()
        finally:
            self._child = None

    def _forward_signal(self, signum, frame):
        del frame
        self._terminated = True
        log.warning("supervisor: signal %d — forwarding to child and "
                    "stopping the restart loop", signum)
        child = self._child
        if child is not None and child.poll() is None:
            try:
                child.send_signal(signum)
            except OSError:
                pass

    def install_signal_forwarding(self):
        """SIGTERM/SIGINT → forward to the child, then exit with its
        code instead of restarting (main() installs this; tests with a
        fake runner don't need it)."""
        for s in (signal.SIGTERM, signal.SIGINT):
            try:
                signal.signal(s, self._forward_signal)
            except (ValueError, OSError):
                pass

    # -------------------------------------------------------------- loop
    def _event(self, name: str, **attrs):
        from bigdl_tpu import obs

        obs.get_tracer().event(name, **attrs)

    def _count_restart(self, kind: str):
        from bigdl_tpu import obs

        obs.get_registry().counter(
            "bigdl_supervisor_restarts_total",
            "Child restarts, by exit classification",
            labels=("kind",)).labels(kind=kind).inc()

    def run(self) -> int:
        self._event("elastic.supervisor_start", cmd=self.cmd)
        while True:
            env = dict(os.environ)
            env["BIGDL_ELASTIC_ATTEMPT"] = str(self.attempt)
            env["BIGDL_ELASTIC_PREEMPTIONS"] = str(self.preemptions)
            log.info("supervisor: launch %d (preemptions so far: %d): %s",
                     self.attempt, self.preemptions, " ".join(self.cmd))
            rc = self._runner(self.cmd, env)
            self.attempt += 1
            if rc == 0:
                log.info("supervisor: command completed cleanly")
                self._event("elastic.supervisor_done", attempts=self.attempt)
                return 0
            if self._terminated:
                # the supervisor itself is being evicted: the child's
                # graceful exit code is the truth to report upward
                log.warning("supervisor: stopping after its own signal; "
                            "child exited %d", rc)
                return rc
            if rc == EXIT_PREEMPTED:
                self.preemptions += 1
                self._event("elastic.restart", kind="preempted", rc=rc,
                            attempt=self.attempt,
                            preemptions=self.preemptions)
                self._count_restart("preempted")
                if self.preemptions > self.max_preemptions:
                    log.error("supervisor: %d preemptions exceeds "
                              "--max-preemptions=%d; giving up",
                              self.preemptions, self.max_preemptions)
                    return rc
                log.warning("supervisor: child preempted (rc %d) — "
                            "resuming from the latest checkpoint "
                            "(no retry budget consumed)", rc)
                continue
            if rc in self.fatal_codes:
                log.error("supervisor: child exited %d (fatal — "
                          "restarting cannot help)", rc)
                self._event("elastic.supervisor_fatal", rc=rc,
                            attempt=self.attempt)
                return rc
            delay = self.policy.record_failure()
            self._event("elastic.restart", kind="transient", rc=rc,
                        attempt=self.attempt,
                        delay_s=None if delay is None else round(delay, 3))
            self._count_restart("transient")
            if delay is None:
                log.error("supervisor: retry budget exhausted after %d "
                          "transient failures; giving up with rc %d",
                          self.policy.attempts, rc)
                return rc
            log.warning("supervisor: child exited %d (transient) — "
                        "restart %d/%d in %.2fs", rc,
                        self.policy.attempts, self.policy.max_retries,
                        delay)
            if delay > 0:
                # backoff is badput the children never see — the
                # supervisor's own goodput shard carries it so the
                # aggregated cross-attempt ratio includes the wait
                from bigdl_tpu import obs

                t0 = time.perf_counter()
                self._sleep(delay)
                obs.get_ledger().record(
                    "supervisor_backoff", t0,
                    time.perf_counter() - t0, rc=rc)


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m bigdl_tpu.resilience.supervisor",
        description="Run a training command in a classify-and-restart "
                    "loop: preempted (rc %d) restarts free, transient "
                    "restarts under the retry budget, fatal (rc %d) "
                    "stops." % (EXIT_PREEMPTED, EXIT_FATAL))
    ap.add_argument("--max-retries", type=int, default=5,
                    help="transient-restart attempt cap (default 5)")
    ap.add_argument("--max-preemptions", type=int, default=1000,
                    help="preemption-restart cap (default 1000)")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="training command (prefix with --)")
    args = ap.parse_args(argv)
    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no command given; usage: ... -- python train.py")
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    sup = Supervisor(cmd, max_retries=args.max_retries,
                     max_preemptions=args.max_preemptions)
    sup.install_signal_forwarding()
    try:
        return sup.run()
    finally:
        from bigdl_tpu import obs

        if obs.active():
            obs.flush()


if __name__ == "__main__":
    raise SystemExit(main())
