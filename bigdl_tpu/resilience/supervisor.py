"""Restart supervisor — the process-level half of elastic training.

The reference's driver survives because Spark restarts failed tasks and
``DistriOptimizer`` re-enters from the last checkpoint; here the
scheduler kills whole JAX processes (preemption) and nobody restarts
them.  This module is that restarter::

    python -m bigdl_tpu.resilience.supervisor [options] -- \
        python train.py ...

It loops the command, classifying each exit against the elastic
exit-code contract (resilience/elastic.py):

* ``0`` — done, exit 0.
* :data:`~bigdl_tpu.resilience.elastic.EXIT_PREEMPTED` — the child shut
  down gracefully with an emergency checkpoint on disk.  Restart
  immediately; preemptions consume no retry budget (an eviction is not
  a failure), bounded only by ``--max-preemptions``.
* :data:`~bigdl_tpu.resilience.elastic.EXIT_FATAL` (and shell usage
  errors) — restarting cannot help; exit with the child's code.
* anything else — transient.  Back off and restart under the PR 1
  :class:`~bigdl_tpu.resilience.retry.RetryPolicy` budget (attempt cap
  + sliding window), then give up with the child's code.

Each launch exports ``BIGDL_ELASTIC_ATTEMPT`` (0-based launch counter)
and ``BIGDL_ELASTIC_PREEMPTIONS`` so the child can adapt — e.g. rebuild
its mesh over however many hosts survived and resume via
``elastic.restore_latest`` (checkpoints are topology-tagged, so a
2-host snapshot restores on 1 host).  SIGTERM/SIGINT to the supervisor
forwards to the child, waits for its graceful exit, and stops the loop
(a preempted supervisor must not immediately respawn what the scheduler
is evicting).
"""

from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import sys
import tempfile
import time
from typing import Callable, List, Optional, Sequence

from bigdl_tpu.resilience.elastic import (
    EXIT_FATAL,
    EXIT_PREEMPTED,
)
from bigdl_tpu.resilience.retry import RetryPolicy
from bigdl_tpu.obs import names

log = logging.getLogger("bigdl_tpu.resilience")


class HangWatchdog:
    """Classify a silent child as *hung* via its live ``/healthz``.

    Heartbeats catch a dead *host* and exit codes catch a dead
    *process*, but a child stuck inside a collective (or a wedged data
    loader) is alive by both measures while making zero progress.  The
    live telemetry plane closes that gap: both optimizers stamp every
    resolved step (``obs/server.note_step``), ``/healthz`` serves the
    stamp's age, and this watchdog polls it — a child whose
    ``step_age_s`` exceeds ``BIGDL_HANG_TIMEOUT`` is killed and
    restarted as a transient failure under the retry budget.

    The child's endpoint is found via ``BIGDL_OBS_PORT`` (>0), or —
    for ephemeral port 0 — via the ``BIGDL_OBS_PORT_FILE`` the child
    writes its bound port into (the supervisor injects a temp path
    when the launcher didn't).  Conservative by construction: any
    fetch failure, a missing port, or a child that has not resolved
    its *first* step yet (startup/compile can legitimately take longer
    than the hang budget) reads as "cannot tell", never as "hung".
    ``fetch`` is injectable so every branch unit-tests without HTTP."""

    def __init__(self, timeout_s: float, port: Optional[int] = None,
                 port_file: Optional[str] = None,
                 fetch: Optional[Callable[[str], Optional[dict]]] = None):
        self.timeout_s = float(timeout_s)
        self.port = int(port) if port else None
        self.port_file = port_file
        self._fetch = fetch or self._http_fetch
        self.last_payload: Optional[dict] = None

    @staticmethod
    def _http_fetch(url: str) -> Optional[dict]:
        import urllib.request

        try:
            with urllib.request.urlopen(url, timeout=1.0) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except Exception:  # noqa: BLE001 — unreachable != hung
            return None

    def _resolve_port(self) -> Optional[int]:
        if self.port:
            return self.port
        if self.port_file and os.path.isfile(self.port_file):
            try:
                with open(self.port_file, encoding="utf-8") as fh:
                    self.port = int(fh.read().strip() or 0) or None
            except (OSError, ValueError):
                self.port = None
        return self.port

    def health(self) -> Optional[dict]:
        """One ``/healthz`` poll (None when unreachable/unknown)."""
        port = self._resolve_port()
        if not port:
            return None
        payload = self._fetch(f"http://127.0.0.1:{port}/healthz")
        if payload is not None:
            self.last_payload = payload
        return payload

    def stalled(self) -> bool:
        """True only on positive evidence: the child answered and its
        newest step stamp is older than the hang budget."""
        payload = self.health()
        if not payload:
            return False
        age = payload.get("step_age_s")
        return age is not None and float(age) > self.timeout_s

    def collect_bundle(self) -> Optional[dict]:
        """Ask the child to write its own debug bundle via ``GET
        /debugz`` — a child wedged in a collective still answers: the
        obs server's request threads are daemons independent of the
        stuck main thread.  None when unreachable (a dead child's
        postmortem is its atexit flush + the supervisor-side bundle)."""
        port = self._resolve_port()
        if not port:
            return None
        return self._fetch(f"http://127.0.0.1:{port}/debugz")


class Supervisor:
    """Run ``cmd`` in a classify-and-restart loop.

    ``runner(cmd, env) -> returncode`` is injectable so every branch of
    the loop is a unit test with no subprocesses; the default runner
    spawns the real child and forwards SIGTERM/SIGINT to it."""

    def __init__(self, cmd: Sequence[str], max_retries: int = 5,
                 max_preemptions: int = 1000,
                 policy: Optional[RetryPolicy] = None,
                 runner: Optional[Callable] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 fatal_codes: Sequence[int] = (EXIT_FATAL, 2, 126, 127),
                 hang_timeout: Optional[float] = None,
                 autoscaler=None, stop_grace_s: float = 30.0):
        if not cmd:
            raise ValueError("supervisor needs a command to run")
        self.cmd = list(cmd)
        self.max_preemptions = int(max_preemptions)
        self.policy = policy or RetryPolicy.from_config(
            max_retries=max_retries)
        self._runner = runner or self._spawn
        self._sleep = sleep
        self.fatal_codes = set(int(c) for c in fatal_codes)
        if hang_timeout is None:
            from bigdl_tpu.config import refresh_from_env

            hang_timeout = refresh_from_env().hang_timeout
        self.hang_timeout = float(hang_timeout or 0.0)
        # autoscaling policy loop (resilience/autoscale.py): polled
        # from the child-wait loop; a decision gracefully stops the
        # child (emergency checkpoint) and relaunches at the new world
        self.autoscaler = autoscaler
        self.stop_grace_s = float(stop_grace_s)
        self.resizes = 0
        self._resize_decision = None
        # resize restarts get the SAME deterministic-jitter exponential
        # backoff shape as transient retries, but from their own policy
        # so legitimate resizes never eat the failure budget — repeated
        # rapid resizes back off harder (thrash damping on top of the
        # controller's cooldown)
        self._resize_policy = RetryPolicy.from_config(
            max_retries=1_000_000)
        self._resize_policy.window_budget = 1_000_000
        self.attempt = 0          # 0-based launch counter (all launches)
        self.preemptions = 0
        self.hangs = 0
        self._child: Optional[subprocess.Popen] = None
        self._terminated = False  # the supervisor itself was signalled
        self._hang_detected = False

    # ------------------------------------------------------------- child
    def _make_watchdog(self, env: dict) -> Optional[HangWatchdog]:
        """A watchdog for this launch, or None when disabled.  Needs
        BIGDL_HANG_TIMEOUT > 0 and a child live endpoint to poll
        (BIGDL_OBS_PORT; port 0 resolves through the port file the
        launch env carries — injected by :meth:`run` when absent)."""
        if self.hang_timeout <= 0:
            return None
        port_spec = env.get("BIGDL_OBS_PORT")
        if port_spec in (None, ""):
            log.warning("supervisor: BIGDL_HANG_TIMEOUT=%g set but "
                        "BIGDL_OBS_PORT is not — the hang watchdog "
                        "needs the child's /healthz; disabled",
                        self.hang_timeout)
            return None
        try:
            port = int(port_spec)
        except ValueError:
            return None
        return HangWatchdog(self.hang_timeout,
                            port=port if port > 0 else None,
                            port_file=env.get("BIGDL_OBS_PORT_FILE"))

    def _bind_autoscaler(self, env: dict):
        """Point the policy loop's scraper at this launch's live
        endpoint(s): explicit peers when the env names them, else the
        child's own /healthz via the same port / port-file resolution
        the hang watchdog uses."""
        if self.autoscaler is None:
            return
        peers = env.get("BIGDL_OBS_PEERS") or None
        port = None
        if not peers:
            try:
                port = int(env.get("BIGDL_OBS_PORT") or 0) or None
            except ValueError:
                port = None
        self.autoscaler.bind_endpoint(
            port=port, port_file=env.get("BIGDL_OBS_PORT_FILE"),
            peers=peers)
        self.autoscaler.on_launch()

    def _graceful_stop(self, why: str) -> int:
        """SIGTERM the child (graceful preemption: it finishes the
        in-flight step and writes an emergency checkpoint), escalate to
        SIGKILL only past ``stop_grace_s``."""
        log.warning("supervisor: stopping the child (%s) — SIGTERM, "
                    "grace %.1fs", why, self.stop_grace_s)
        self._child.terminate()
        try:
            return self._child.wait(timeout=self.stop_grace_s)
        except subprocess.TimeoutExpired:
            log.error("supervisor: child ignored SIGTERM for %.1fs — "
                      "killing it", self.stop_grace_s)
            self._child.kill()
        return self._child.wait()

    def _spawn(self, cmd: List[str], env: dict) -> int:
        self._child = subprocess.Popen(cmd, env=env)
        watchdog = self._make_watchdog(env)
        self._bind_autoscaler(env)
        try:
            if watchdog is None and self.autoscaler is None:
                return self._child.wait()
            # poll a few times per hang budget / policy interval:
            # fine-grained enough to catch a stall or act on a decision
            # promptly, coarse enough that the scrape cost is noise
            polls = [2.0]
            if watchdog is not None:
                polls.append(self.hang_timeout / 4.0)
            if self.autoscaler is not None:
                polls.append(self.autoscaler.cfg.interval_s / 2.0)
            poll = max(0.1, min(polls))
            while True:
                try:
                    return self._child.wait(timeout=poll)
                except subprocess.TimeoutExpired:
                    pass
                if self._terminated:
                    continue
                if watchdog is not None and watchdog.stalled():
                    payload = watchdog.last_payload or {}
                    log.error(
                        "supervisor: child step stamp stale for %.1fs "
                        "(step %s, budget %.1fs) — killing the hung "
                        "child", payload.get("step_age_s", -1.0),
                        payload.get("step"), self.hang_timeout)
                    self._hang_detected = True
                    # black-box capture BEFORE the kill: the child's
                    # own /debugz bundles what it was doing (its HTTP
                    # daemon threads answer even with the main thread
                    # wedged); gated on BIGDL_BUNDLE_DIR, best effort
                    try:
                        from bigdl_tpu.config import refresh_from_env

                        if refresh_from_env().obs.bundle_dir:
                            got = watchdog.collect_bundle()
                            if got and got.get("bundle"):
                                log.warning(
                                    "supervisor: hung child wrote "
                                    "debug bundle %s", got["bundle"])
                    except Exception:  # noqa: BLE001 — never delay the kill
                        pass
                    self._child.terminate()
                    try:
                        self._child.wait(timeout=5.0)
                    except subprocess.TimeoutExpired:
                        self._child.kill()
                    return self._child.wait()
                if self.autoscaler is not None \
                        and self._resize_decision is None:
                    decision = self.autoscaler.tick()
                    if decision is not None and not decision.dry_run:
                        self._resize_decision = decision
                        return self._graceful_stop(
                            f"autoscale {decision.direction} "
                            f"{decision.old_world}->"
                            f"{decision.new_world} [{decision.reason}]")
        finally:
            self._child = None

    def _forward_signal(self, signum, frame):
        del frame
        self._terminated = True
        log.warning("supervisor: signal %d — forwarding to child and "
                    "stopping the restart loop", signum)
        child = self._child
        if child is not None and child.poll() is None:
            try:
                child.send_signal(signum)
            except OSError:
                pass

    def install_signal_forwarding(self):
        """SIGTERM/SIGINT → forward to the child, then exit with its
        code instead of restarting (main() installs this; tests with a
        fake runner don't need it)."""
        for s in (signal.SIGTERM, signal.SIGINT):
            try:
                signal.signal(s, self._forward_signal)
            except (ValueError, OSError):
                pass

    # -------------------------------------------------------------- loop
    def _event(self, name: str, **attrs):
        from bigdl_tpu import obs

        obs.get_tracer().event(name, **attrs)

    def _count_restart(self, kind: str):
        from bigdl_tpu import obs

        obs.get_registry().counter(
            names.SUPERVISOR_RESTARTS_TOTAL,
            "Child restarts, by exit classification",
            labels=("kind",)).labels(kind=kind).inc()

    def _maybe_bundle(self, kind: str, rc: int):
        """Supervisor-side debug bundle around a crash/hang restart:
        the supervisor's own flight ring, registry (restart counters)
        and alert state, stamped with the exit classification — the
        half of the postmortem that survives the child.  Gated on
        BIGDL_BUNDLE_DIR; best effort."""
        try:
            from bigdl_tpu.config import refresh_from_env

            if not refresh_from_env().obs.bundle_dir:
                return
            from bigdl_tpu.obs import bundle

            bundle.build_bundle(
                reason=f"child {kind} rc={rc}",
                trigger="supervisor",
                context={"kind": kind, "rc": rc,
                         "attempt": self.attempt,
                         "hangs": self.hangs,
                         "preemptions": self.preemptions})
        except Exception:  # noqa: BLE001 — bundling never blocks restarts
            log.exception("supervisor: debug bundle failed")

    def _backoff_sleep(self, kind: str, rc: int, delay: float):
        """Sleep a restart backoff, visibly: one ``supervisor.backoff``
        trace event (what the chosen sleep was and why) plus the
        goodput-ledger record the cross-attempt ratio attributes —
        backoff is badput the children never see."""
        from bigdl_tpu import obs

        self._event("supervisor.backoff", kind=kind, rc=rc,
                    delay_s=round(delay, 3))
        if delay <= 0:
            return
        t0 = time.perf_counter()
        self._sleep(delay)
        obs.get_ledger().record("supervisor_backoff", t0,
                                time.perf_counter() - t0, rc=rc,
                                restart_kind=kind)

    def run(self) -> int:
        self._event("elastic.supervisor_start", cmd=self.cmd)
        while True:
            env = dict(os.environ)
            env["BIGDL_ELASTIC_ATTEMPT"] = str(self.attempt)
            env["BIGDL_ELASTIC_PREEMPTIONS"] = str(self.preemptions)
            if self.autoscaler is not None:
                # the world-size contract: the child sizes its mesh
                # from this (and the topology-tagged checkpoint makes
                # the resume re-partition to match)
                env["BIGDL_AUTOSCALE_WORLD"] = str(self.autoscaler.world)
            # hang watchdog / policy loop on an ephemeral child port:
            # the child must tell the supervisor where it bound, so
            # inject a per-launch port file when the launcher didn't
            if (self.hang_timeout > 0 or self.autoscaler is not None) \
                    and env.get("BIGDL_OBS_PORT") == "0" \
                    and not env.get("BIGDL_OBS_PORT_FILE"):
                env["BIGDL_OBS_PORT_FILE"] = os.path.join(
                    tempfile.gettempdir(),
                    f"bigdl-obs-port.{os.getpid()}.a{self.attempt}")
            pf = env.get("BIGDL_OBS_PORT_FILE")
            if pf:
                try:  # a stale file from a dead launch must not
                    os.unlink(pf)  # point the watchdog at a ghost port
                except OSError:
                    pass
            log.info("supervisor: launch %d (preemptions so far: %d): %s",
                     self.attempt, self.preemptions, " ".join(self.cmd))
            self._hang_detected = False
            rc = self._runner(self.cmd, env)
            hung = self._hang_detected
            resize = self._resize_decision
            self._resize_decision = None
            self.attempt += 1
            if rc == 0:
                log.info("supervisor: command completed cleanly")
                self._event("elastic.supervisor_done", attempts=self.attempt)
                return 0
            if self._terminated:
                # the supervisor itself is being evicted: the child's
                # graceful exit code is the truth to report upward
                log.warning("supervisor: stopping after its own signal; "
                            "child exited %d", rc)
                return rc
            if resize is not None:
                # the supervisor stopped this child itself to execute a
                # resize — the exit code says nothing (usually
                # EXIT_PREEMPTED from the graceful path; a child that
                # was ALREADY preempting when the decision landed exits
                # the same way and is handled identically).  Restart at
                # the new world, free of the retry budget, paced by the
                # resize backoff policy.
                self.resizes += 1
                self.autoscaler.commit(resize)
                log.warning("supervisor: resize %s executed (%s) — "
                            "relaunching at world %d (child rc %d)",
                            resize.resize, resize.reason,
                            self.autoscaler.world, rc)
                self._event("elastic.restart", kind="resize", rc=rc,
                            attempt=self.attempt,
                            direction=resize.direction,
                            reason=resize.reason,
                            old_world=resize.old_world,
                            new_world=resize.new_world)
                self._count_restart("resize")
                delay = self._resize_policy.record_failure() or 0.0
                self._backoff_sleep("resize", rc, delay)
                continue
            if rc == EXIT_PREEMPTED and not hung:
                self.preemptions += 1
                self._event("elastic.restart", kind="preempted", rc=rc,
                            attempt=self.attempt,
                            preemptions=self.preemptions)
                self._count_restart("preempted")
                if self.preemptions > self.max_preemptions:
                    log.error("supervisor: %d preemptions exceeds "
                              "--max-preemptions=%d; giving up",
                              self.preemptions, self.max_preemptions)
                    return rc
                log.warning("supervisor: child preempted (rc %d) — "
                            "resuming from the latest checkpoint "
                            "(no retry budget consumed)", rc)
                continue
            if rc in self.fatal_codes and not hung:
                log.error("supervisor: child exited %d (fatal — "
                          "restarting cannot help)", rc)
                self._event("elastic.supervisor_fatal", rc=rc,
                            attempt=self.attempt)
                return rc
            # a hang-killed child is transient BY CLASSIFICATION — the
            # watchdog produced the exit code, so the code itself says
            # nothing; it restarts under the same retry budget
            kind = "hang" if hung else "transient"
            if hung:
                self.hangs += 1
            self._maybe_bundle(kind, rc)
            delay = self.policy.record_failure()
            self._event("elastic.restart", kind=kind, rc=rc,
                        attempt=self.attempt,
                        delay_s=None if delay is None else round(delay, 3))
            self._count_restart(kind)
            if delay is None:
                log.error("supervisor: retry budget exhausted after %d "
                          "%s failures; giving up with rc %d",
                          self.policy.attempts, kind, rc)
                return rc
            log.warning("supervisor: child exited %d (%s) — "
                        "restart %d/%d in %.2fs", rc, kind,
                        self.policy.attempts, self.policy.max_retries,
                        delay)
            self._backoff_sleep(kind, rc, delay)


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m bigdl_tpu.resilience.supervisor",
        description="Run a training command in a classify-and-restart "
                    "loop: preempted (rc %d) restarts free, transient "
                    "restarts under the retry budget, fatal (rc %d) "
                    "stops." % (EXIT_PREEMPTED, EXIT_FATAL))
    ap.add_argument("--max-retries", type=int, default=5,
                    help="transient-restart attempt cap (default 5)")
    ap.add_argument("--max-preemptions", type=int, default=1000,
                    help="preemption-restart cap (default 1000)")
    ap.add_argument("--hang-timeout", type=float, default=None,
                    help="kill+restart a child whose /healthz step "
                         "stamp stops advancing for this many seconds "
                         "(default BIGDL_HANG_TIMEOUT; needs "
                         "BIGDL_OBS_PORT on the child)")
    ap.add_argument("--autoscale", action="store_true",
                    help="enable the autoscaling policy loop "
                         "(resilience/autoscale.py) even when "
                         "BIGDL_AUTOSCALE is unset; rules/bands come "
                         "from the BIGDL_AUTOSCALE_* knobs, the child "
                         "endpoint from BIGDL_OBS_PORT(_FILE)/"
                         "BIGDL_OBS_PEERS, and the chosen world is "
                         "exported as BIGDL_AUTOSCALE_WORLD")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="training command (prefix with --)")
    args = ap.parse_args(argv)
    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no command given; usage: ... -- python train.py")
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    from bigdl_tpu.config import refresh_from_env

    autoscaler = None
    if args.autoscale or refresh_from_env().autoscale.enabled:
        from bigdl_tpu.resilience.autoscale import AutoscaleController

        autoscaler = AutoscaleController.from_config()
    sup = Supervisor(cmd, max_retries=args.max_retries,
                     max_preemptions=args.max_preemptions,
                     hang_timeout=args.hang_timeout,
                     autoscaler=autoscaler)
    sup.install_signal_forwarding()
    try:
        return sup.run()
    finally:
        from bigdl_tpu import obs

        if obs.active():
            obs.flush()


if __name__ == "__main__":
    raise SystemExit(main())
