"""Autoscaling supervisor policy loop — signals in, world resizes out.

PR 5 made world-resize resume trajectory-correct (topology-tagged
checkpoints + flat ZeRO-1 repartitioning) and PR 8 gave every host a
live ``/metrics``/``/healthz`` surface; this module is the loop that
*drives* a resize: the TensorFlow-paper stance that
restart-from-checkpoint is the primary consistency mechanism, taken to
its autoscaling conclusion — a resize is just a supervised restart at a
new world size.

The pieces:

* :class:`EndpointScraper` — reads the fleet: ``BIGDL_OBS_PEERS`` when
  set (one scrape per peer via
  :meth:`~bigdl_tpu.obs.aggregate.FleetAggregator.scrape_peer`),
  otherwise the supervised child's own endpoint resolved exactly like
  the hang watchdog (``BIGDL_OBS_PORT`` / the port file the supervisor
  injects for port 0).  ``fetch`` is injectable so every policy branch
  unit-tests without sockets.
* :func:`derive_signals` — one scrape cycle -> the policy signal dict:
  ``step_time_s`` (from step-stamp deltas between successive scrapes —
  no histogram parsing, works on any child), ``queue_depth`` (the
  streaming tier's buffer depth / consumer lag gauges),
  ``goodput_ratio`` (worst host), ``alerts`` (active rule names),
  ``stragglers`` (hosts whose ``/healthz`` reads stalled),
  ``router_replicas`` (live backends from the
  ``bigdl_router_replicas{state="up"}`` gauge) and
  ``router_shed_rate`` (sheds/s from ``bigdl_router_shed_total``
  deltas between cycles — the serving data plane's load-pressure
  signal).
* declarative **rules** (:func:`load_rules`) — the same
  validated-loudly contract as the alert engine: each rule names a
  signal, a comparison, an action (``up``/``down``) and a ``for``
  hysteresis count; the default pack is derived from the
  ``BIGDL_AUTOSCALE_*`` band knobs.
* :class:`AutoscaleController` — evaluates the rules every
  ``interval_s`` with warmup after each (re)launch, per-rule
  consecutive-breach hysteresis, a cooldown after any decision, and
  min/max world clamping, so flapping signals cannot thrash the world.
  Decisions are first-class telemetry:
  ``bigdl_autoscale_decisions_total{direction,reason}`` + an
  ``elastic.autoscale`` trace event each, and ``dry_run`` mode counts
  and traces without ever executing.

Execution lives in the supervisor (resilience/supervisor.py): a
decision SIGTERMs the child (graceful preemption -> emergency
checkpoint with the stream offset riding it -> ``EXIT_PREEMPTED``),
then relaunches with ``BIGDL_AUTOSCALE_WORLD`` exported at the new
size; the child re-forms its mesh, ``elastic.restore_latest``
re-partitions the ZeRO state and seeks the stream — exactly-once,
counted in ``bigdl_resumes_total{resize}``.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from typing import Callable, List, Optional
from bigdl_tpu.obs import names

log = logging.getLogger("bigdl_tpu.resilience")

_OPS = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
    "nonempty": lambda v, _t: bool(v),
}
_ACTIONS = ("up", "down")
SIGNALS = ("step_time_s", "queue_depth", "goodput_ratio", "alerts",
           "stragglers", "step", "world", "p99_latency_s",
           "router_replicas", "router_shed_rate")

# queue gauges: the streaming tier's buffer/lag (dataset/stream.py)
# AND the serving tier's request queue (serving/batcher.py) — the
# queue_depth signal is the max over all of them on any host
_QUEUE_METRICS = (names.STREAM_BUFFER_DEPTH, names.STREAM_LAG_RECORDS,
                  names.SERVE_QUEUE_DEPTH)

# the serving tier's e2e request-latency histogram, as exposed on
# /metrics (bucket samples carry their literal _bucket name)
_LATENCY_BUCKET = "bigdl_request_latency_seconds_bucket"


def _hist_p99(buckets: dict) -> Optional[float]:
    """p99 upper-bound from cumulative ``{le: count}`` buckets (the
    conservative nearest-bucket estimate — +Inf falls back to the
    largest finite bound, so a pathological tail still yields a
    finite, breachable signal)."""
    total = buckets.get(float("inf"), 0.0)
    if total <= 0:
        return None
    finite = sorted(b for b in buckets if b != float("inf"))
    target = 0.99 * total
    for le in finite:
        if buckets[le] >= target:
            return le
    return finite[-1] if finite else None


@dataclasses.dataclass
class Decision:
    """One resize decision (already counted and traced when emitted)."""

    direction: str          # "up" | "down"
    reason: str             # rule name
    old_world: int
    new_world: int
    dry_run: bool = False
    signals: dict = dataclasses.field(default_factory=dict)

    @property
    def resize(self) -> str:
        return f"{self.old_world}to{self.new_world}"


def default_rules(cfg) -> List[dict]:
    """The rule pack the ``BIGDL_AUTOSCALE_*`` band knobs describe.
    Order is priority: straggler eviction and queue pressure outrank
    the step-time band, the cost floor comes last."""
    rules = []
    if cfg.evict_stragglers:
        rules.append({"name": "straggler_evict", "signal": "stragglers",
                      "op": "nonempty", "action": "down", "for": 1})
    if cfg.queue_high > 0:
        rules.append({"name": "queue_high", "signal": "queue_depth",
                      "op": ">", "value": cfg.queue_high, "action": "up"})
    if cfg.queue_low > 0:
        rules.append({"name": "queue_low", "signal": "queue_depth",
                      "op": "<", "value": cfg.queue_low, "action": "down"})
    if cfg.p99_high > 0:
        rules.append({"name": "latency_p99_high",
                      "signal": "p99_latency_s", "op": ">",
                      "value": cfg.p99_high, "action": "up"})
    if cfg.p99_low > 0:
        rules.append({"name": "latency_p99_low",
                      "signal": "p99_latency_s", "op": "<",
                      "value": cfg.p99_low, "action": "down"})
    if cfg.step_time_high > 0:
        rules.append({"name": "step_time_high", "signal": "step_time_s",
                      "op": ">", "value": cfg.step_time_high,
                      "action": "up"})
    if cfg.step_time_low > 0:
        rules.append({"name": "step_time_low", "signal": "step_time_s",
                      "op": "<", "value": cfg.step_time_low,
                      "action": "down"})
    if cfg.goodput_floor > 0:
        rules.append({"name": "cost_goodput_floor",
                      "signal": "goodput_ratio", "op": "<",
                      "value": cfg.goodput_floor, "action": "down"})
    return rules


def load_rules(spec: Optional[str], cfg) -> List[dict]:
    """Resolve + validate the rule pack: inline JSON list, a JSON file
    path, or (None) the default pack from the band knobs.  Validation
    is loud — a malformed autoscaling rule silently ignored is a world
    that never scales."""
    if spec is None:
        raw = default_rules(cfg)
    else:
        text = spec
        if not spec.lstrip().startswith(("[", "{")):
            with open(spec, "r", encoding="utf-8") as fh:
                text = fh.read()
        raw = json.loads(text)
    if not isinstance(raw, list):
        raise ValueError(f"autoscale rules must be a JSON list, got "
                         f"{type(raw).__name__}")
    rules = []
    seen = set()
    for i, r in enumerate(raw):
        if not isinstance(r, dict):
            raise ValueError(f"autoscale rule #{i} is not an object: {r!r}")
        missing = [k for k in ("name", "signal", "op", "action")
                   if k not in r]
        if missing:
            raise ValueError(f"autoscale rule #{i} missing {missing}")
        if r["op"] not in _OPS:
            raise ValueError(f"autoscale rule {r['name']!r}: unknown op "
                             f"{r['op']!r} (one of {sorted(_OPS)})")
        if r["action"] not in _ACTIONS:
            raise ValueError(f"autoscale rule {r['name']!r}: action must "
                             f"be one of {_ACTIONS}, got {r['action']!r}")
        if r["signal"] not in SIGNALS:
            raise ValueError(f"autoscale rule {r['name']!r}: unknown "
                             f"signal {r['signal']!r} (one of {SIGNALS})")
        if r["op"] != "nonempty" and "value" not in r:
            raise ValueError(f"autoscale rule {r['name']!r}: op "
                             f"{r['op']!r} needs a 'value'")
        if r["name"] in seen:
            raise ValueError(f"duplicate autoscale rule name "
                             f"{r['name']!r}")
        seen.add(r["name"])
        out = dict(r)
        out["for"] = max(1, int(r.get("for", cfg.hysteresis)))
        if "value" in out:
            out["value"] = float(out["value"])
        rules.append(out)
    return rules


class EndpointScraper:
    """One scrape cycle over the fleet: a list of
    ``{addr, ok, health, metrics}`` dicts (the
    ``FleetAggregator.scrape_peer`` shape).  Peers mode when ``peers``
    is set; otherwise the single supervised child found via
    ``port``/``port_file`` (the hang-watchdog resolution contract —
    port 0 resolves through the port file once the child writes it)."""

    def __init__(self, peers=None, port: Optional[int] = None,
                 port_file: Optional[str] = None, fetch=None,
                 timeout_s: float = 2.0):
        from bigdl_tpu.obs.aggregate import FleetAggregator

        if isinstance(peers, str):
            peers = [p.strip() for p in peers.split(",") if p.strip()]
        self.peers = list(peers or [])
        self.port = int(port) if port else None
        self.port_file = port_file
        self._agg = FleetAggregator(peers=[], fetch=fetch,
                                    timeout_s=timeout_s)

    def _resolve_port(self) -> Optional[int]:
        if self.port:
            return self.port
        if self.port_file and os.path.isfile(self.port_file):
            try:
                with open(self.port_file, encoding="utf-8") as fh:
                    self.port = int(fh.read().strip() or 0) or None
            except (OSError, ValueError):
                self.port = None
        return self.port

    def __call__(self) -> List[dict]:
        addrs = list(self.peers)
        if not addrs:
            port = self._resolve_port()
            if not port:
                return []
            addrs = [f"127.0.0.1:{port}"]
        # concurrent bounded-pool scrape: N partitioned peers cost
        # ceil(N/pool) timeouts per cycle, not N (and the cycle wall is
        # published as bigdl_fleet_scrape_seconds)
        return self._agg.scrape_peers(addrs)


def derive_signals(scraped: List[dict], prev_steps: dict,
                   world: int,
                   prev_counters: Optional[dict] = None) -> dict:
    """One scrape cycle -> the policy signal dict.  ``prev_steps``
    ({addr: (step, wall_time)}) is the controller's memory between
    cycles — step time derives from the stamp deltas, so any child that
    stamps ``note_step`` is measurable without histogram parsing.
    ``prev_counters`` ({addr: (shed_total, wall_time)}) is the same
    memory for counter deltas: ``router_shed_rate`` (sheds/s summed
    across routers) derives from ``bigdl_router_shed_total`` between
    cycles, and ``router_replicas`` counts the fleet's live backends
    from the ``bigdl_router_replicas{state="up"}`` gauge.  Conservative:
    a signal that cannot be derived is absent, and an absent signal
    never breaches a rule."""
    sig = {"world": world, "alerts": [], "stragglers": []}
    step_times, depths, ratios, steps, p99s = [], [], [], [], []
    replicas_up, shed_rates = [], []
    for peer in scraped:
        if not peer.get("ok"):
            continue
        lat_buckets: dict = {}
        shed_total = None
        h = peer.get("health") or {}
        addr = peer.get("addr", "?")
        step, now = h.get("step"), h.get("time")
        if step is not None:
            steps.append(int(step))
        if step is not None and now is not None:
            last = prev_steps.get(addr)
            prev_steps[addr] = (int(step), float(now))
            if last is not None and int(step) > last[0]:
                step_times.append(
                    (float(now) - last[1]) / (int(step) - last[0]))
        if h.get("goodput_ratio") is not None:
            ratios.append(float(h["goodput_ratio"]))
        for a in h.get("alerts") or []:
            rule = a.get("rule")
            if rule and rule not in sig["alerts"]:
                sig["alerts"].append(rule)
        if h.get("status") == "stalled":
            sig["stragglers"].append(h.get("host", addr))
        for s in (peer.get("metrics") or {}).get("samples", []):
            if s.get("name") in _QUEUE_METRICS:
                depths.append(float(s.get("value", 0.0)))
            elif s.get("name") == names.ROUTER_REPLICAS and \
                    (s.get("labels") or {}).get("state") == "up":
                replicas_up.append(float(s.get("value", 0.0)))
            elif s.get("name") == names.ROUTER_SHED_TOTAL:
                shed_total = (shed_total or 0.0) + float(
                    s.get("value", 0.0))
            elif s.get("name") == _LATENCY_BUCKET and \
                    (s.get("labels") or {}).get("kind") == "e2e":
                try:
                    le = float((s.get("labels") or {}).get("le", "nan"))
                except ValueError:
                    le = float("inf")  # "+Inf"
                lat_buckets[le] = lat_buckets.get(le, 0.0) + float(
                    s.get("value", 0.0))
        p99 = _hist_p99(lat_buckets)
        if p99 is not None:
            p99s.append(p99)
        if shed_total is not None and now is not None \
                and prev_counters is not None:
            last = prev_counters.get(addr)
            prev_counters[addr] = (shed_total, float(now))
            if last is not None and float(now) > last[1]:
                # max(0, Δ): a restarted router rewinds its counter —
                # that must read as quiet, not as a negative shed storm
                shed_rates.append(max(0.0, shed_total - last[0])
                                  / (float(now) - last[1]))
    if step_times:
        # the slowest host gates every synchronous collective
        sig["step_time_s"] = max(step_times)
    if depths:
        sig["queue_depth"] = max(depths)
    if ratios:
        sig["goodput_ratio"] = min(ratios)
    if steps:
        sig["step"] = max(steps)
    if p99s:
        # the worst host's tail gates the user-facing SLO
        sig["p99_latency_s"] = max(p99s)
    if replicas_up:
        sig["router_replicas"] = sum(replicas_up)
    if shed_rates:
        sig["router_shed_rate"] = sum(shed_rates)
    return sig


class AutoscaleController:
    """Evaluate the rules against live signals; emit clamped,
    hysteresis-gated, cooldown-paced :class:`Decision`\\ s.

    The controller owns the current ``world`` (what the supervisor
    exports as ``BIGDL_AUTOSCALE_WORLD``); the supervisor calls
    :meth:`tick` from its child-wait poll loop, executes non-dry-run
    decisions by graceful stop-restart, and :meth:`commit`\\ s them.
    ``scrape`` and ``clock`` are injectable so every policy branch is a
    socket-free unit test."""

    def __init__(self, cfg=None, world: Optional[int] = None,
                 rules: Optional[List[dict]] = None,
                 scrape: Optional[Callable[[], List[dict]]] = None,
                 clock: Callable[[], float] = time.monotonic):
        if cfg is None:
            from bigdl_tpu.config import refresh_from_env

            cfg = refresh_from_env().autoscale
        self.cfg = cfg
        self.rules = (load_rules(cfg.rules, cfg) if rules is None
                      else rules)
        if world is None:
            world = int(getattr(cfg, "world", 0) or 0) \
                or max(1, cfg.min_world)
        self.world = int(world)
        self._scrape = scrape
        self._scrape_injected = scrape is not None
        self._clock = clock
        self._streaks = {r["name"]: 0 for r in self.rules}
        self._prev_steps: dict = {}
        self._prev_counters: dict = {}
        self._launch_t = clock()
        self._last_eval: Optional[float] = None
        self._last_decision_t: Optional[float] = None
        self.decisions: List[Decision] = []

    @classmethod
    def from_config(cls, world: Optional[int] = None
                    ) -> "AutoscaleController":
        return cls(world=world)

    # ------------------------------------------------------- lifecycle
    def bind_endpoint(self, port: Optional[int] = None,
                      port_file: Optional[str] = None, peers=None):
        """Point the scraper at this launch's endpoint(s) (no-op when a
        scrape callable was injected at construction)."""
        if self._scrape_injected:
            return
        self._scrape = EndpointScraper(peers=peers, port=port,
                                       port_file=port_file)

    def on_launch(self):
        """A child (re)launched: restart the warmup clock, drop the
        step-stamp memory (a fresh process restarts its counters) and
        every breach streak."""
        self._launch_t = self._clock()
        self._prev_steps.clear()
        self._prev_counters.clear()
        for k in self._streaks:
            self._streaks[k] = 0

    def commit(self, decision: Decision):
        """The supervisor executed ``decision``: adopt the new world."""
        self.world = int(decision.new_world)

    # ------------------------------------------------------ evaluation
    def _propose(self, rule: dict) -> int:
        f = max(2, int(self.cfg.factor))
        if rule["action"] == "up":
            target = self.world * f
        else:
            target = max(1, self.world // f)
        return max(self.cfg.min_world, min(self.cfg.max_world, target))

    def _event(self, **attrs):
        from bigdl_tpu import obs

        obs.get_tracer().event("elastic.autoscale", **attrs)

    def evaluate(self, signals: dict,
                 now: Optional[float] = None) -> Optional[Decision]:
        """One policy evaluation over a derived signal dict.  Returns a
        decision (already counted/traced) or None.  Dry-run decisions
        are returned flagged — the supervisor never executes them."""
        now = self._clock() if now is None else now
        candidate = None
        for rule in self.rules:
            val = signals.get(rule["signal"])
            breached = val is not None and _OPS[rule["op"]](
                val, rule.get("value"))
            self._streaks[rule["name"]] = \
                self._streaks[rule["name"]] + 1 if breached else 0
            if breached and self._streaks[rule["name"]] >= rule["for"] \
                    and candidate is None:
                candidate = rule
        if candidate is None:
            return None
        if self._last_decision_t is not None and \
                now - self._last_decision_t < self.cfg.cooldown_s:
            # hysteresis survived but the cooldown gate holds: a fresh
            # restart must pay for itself before the next decision —
            # this is what keeps an immediate reverse decision from
            # thrashing the world
            self._event(suppressed="cooldown", rule=candidate["name"],
                        remaining_s=round(
                            self.cfg.cooldown_s
                            - (now - self._last_decision_t), 3))
            return None
        new_world = self._propose(candidate)
        if new_world == self.world:
            self._event(suppressed="at_bound", rule=candidate["name"],
                        world=self.world,
                        min_world=self.cfg.min_world,
                        max_world=self.cfg.max_world)
            return None
        decision = Decision(
            direction=candidate["action"], reason=candidate["name"],
            old_world=self.world, new_world=new_world,
            dry_run=bool(self.cfg.dry_run),
            signals={k: v for k, v in signals.items() if v not in
                     (None, [], {})})
        from bigdl_tpu import obs

        obs.get_registry().counter(
            names.AUTOSCALE_DECISIONS_TOTAL,
            "Autoscale resize decisions, by direction and rule",
            labels=("direction", "reason")).labels(
            direction=decision.direction, reason=decision.reason).inc()
        self._event(direction=decision.direction, reason=decision.reason,
                    old_world=decision.old_world,
                    new_world=decision.new_world,
                    dry_run=decision.dry_run, signals=decision.signals)
        log.warning("autoscale: %s %d -> %d (%s%s) signals=%s",
                    decision.direction, decision.old_world,
                    decision.new_world, decision.reason,
                    ", DRY RUN" if decision.dry_run else "",
                    decision.signals)
        self._last_decision_t = now
        for k in self._streaks:
            self._streaks[k] = 0
        self.decisions.append(decision)
        return decision

    def tick(self, now: Optional[float] = None) -> Optional[Decision]:
        """The supervisor's poll hook: rate-limited to ``interval_s``,
        silent through the post-launch warmup, conservative on scrape
        failure (no data, no decision)."""
        now = self._clock() if now is None else now
        if now - self._launch_t < self.cfg.warmup_s:
            return None
        if self._last_eval is not None and \
                now - self._last_eval < self.cfg.interval_s:
            return None
        self._last_eval = now
        if self._scrape is None:
            return None
        try:
            scraped = self._scrape()
        except Exception:  # noqa: BLE001 — a scrape bug must not kill
            log.exception("autoscale: scrape failed")  # the supervisor
            return None
        if not scraped or not any(p.get("ok") for p in scraped):
            return None
        signals = derive_signals(scraped, self._prev_steps, self.world,
                                 self._prev_counters)
        return self.evaluate(signals, now)
