"""Wide & Deep recommender.

Rebuild of the reference's wide-and-deep path (SURVEY.md §2.1 "Sparse
tensor": SparseLinear / LookupTableSparse / SparseJoinTable exist to
feed exactly this model family; the zoo's WideAndDeep assembled them
the same way).

Input encoding — the TPU-native fixed-slot layout
(``SparseTensor.to_padded``): one packed float matrix per batch

    x = [wide_ids (S_w) | wide_weights (S_w) | deep_ids (n_deep)]

* ``wide_ids``: 1-based indices into the wide (cross-feature) vocab,
  0 = padding; ``wide_weights`` the matching values.  The wide linear
  term ``sum_i w[id_i] * weight_i`` is an embedding bag with
  ``n_output = class_num`` — ``LookupTableSparse``'s padded dense path.
* ``deep_ids``: one 1-based categorical id per deep column, each with
  its own embedding table, concatenated into an MLP.

Static shapes mean the batch shards ``P(data)`` over the mesh and the
whole model jits into one XLA program — gathers + dense matmuls, no
host-side sparse scatter.  The COO ``SparseTensor`` surface
(nn/sparse.py) is the host-side data-prep companion.
"""

from __future__ import annotations

from typing import Sequence

from bigdl_tpu.nn import (
    CAddTable,
    Graph,
    Input,
    JoinTable,
    Linear,
    LogSoftMax,
    LookupTable,
    LookupTableSparse,
    Narrow,
    ReLU,
)


def build_wide_and_deep(
    wide_vocab: int,
    deep_vocabs: Sequence[int],
    class_num: int = 2,
    wide_slots: int = 8,
    embed_dim: int = 8,
    hidden_layers: Sequence[int] = (32, 16),
):
    """Wide & Deep graph over the packed fixed-slot input.

    x (B, 2 * wide_slots + len(deep_vocabs)) float32 packed as
    described in the module docstring.
    """
    n_deep = len(deep_vocabs)
    inp = Input()

    wide_ids = Narrow(2, 1, wide_slots)(inp)
    wide_wts = Narrow(2, wide_slots + 1, wide_slots)(inp)
    # wide linear term: embedding bag over the cross-feature vocab with
    # per-id weights, n_output = class_num (LookupTableSparse padded path)
    wide_out = LookupTableSparse(wide_vocab, class_num, combiner="sum")(
        wide_ids, wide_wts)

    # deep: per-column embeddings -> concat -> MLP
    embeds = []
    for c, vocab in enumerate(deep_vocabs):
        ids_c = Narrow(2, 2 * wide_slots + c + 1, 1)(inp)
        emb = LookupTable(vocab, embed_dim)(ids_c)   # (B, 1, D)
        embeds.append(emb)
    h = JoinTable(2, 3)(*embeds) if n_deep > 1 else embeds[0]
    from bigdl_tpu.nn import Reshape

    h = Reshape([n_deep * embed_dim], batch_mode=True)(h)
    width = n_deep * embed_dim
    for n in hidden_layers:
        h = ReLU()(Linear(width, n)(h))
        width = n
    deep_out = Linear(width, class_num)(h)

    out = LogSoftMax()(CAddTable()(wide_out, deep_out))
    return Graph([inp], [out])


def pack_batch(wide_sparse, deep_ids, wide_slots: int):
    """Host-side batch packer: COO wide features + (B, n_deep) deep ids
    -> the packed dense matrix ``build_wide_and_deep`` consumes.

    The packed matrix is float32 (one homogeneous array rides the
    P(data) pipeline), which represents integers exactly only below
    2**24 — large hashed-cross vocabs must be bucketed under that bound
    first; this packer refuses ids beyond it rather than silently
    gathering a neighboring embedding row."""
    import numpy as np

    ids, wts = wide_sparse.to_padded(wide_slots)
    deep = np.asarray(deep_ids)
    limit = 1 << 24
    if ids.max(initial=0) >= limit or deep.max(initial=0) >= limit:
        raise ValueError(
            "pack_batch: ids >= 2**24 do not survive the float32 packed "
            "encoding; hash/bucket the vocab below 16.7M first")
    return np.concatenate(
        [ids.astype(np.float32), wts, deep.astype(np.float32)], axis=1
    ).astype(np.float32)
