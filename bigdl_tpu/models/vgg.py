"""VGG-16/19 (ImageNet) and the CIFAR VGG.

Rebuild of «bigdl»/models/vgg/Vgg_16.scala / Vgg_19.scala (Caffe-layout
conv stacks) and VggForCifar10.scala (conv+BN variant).
"""

from __future__ import annotations

from bigdl_tpu.nn import (
    Dropout,
    Linear,
    LogSoftMax,
    ReLU,
    Reshape,
    Sequential,
    SpatialBatchNormalization,
    SpatialConvolution,
    SpatialMaxPooling,
)

_VGG16 = [2, 2, 3, 3, 3]
_VGG19 = [2, 2, 4, 4, 4]
_WIDTHS = [64, 128, 256, 512, 512]


def _build_vgg_imagenet(counts, class_num=1000):
    model = Sequential()
    n_in = 3
    for width, n in zip(_WIDTHS, counts):
        for _ in range(n):
            model.add(SpatialConvolution(n_in, width, 3, 3, 1, 1, 1, 1))
            model.add(ReLU())
            n_in = width
        model.add(SpatialMaxPooling(2, 2, 2, 2))
    model.add(Reshape([512 * 7 * 7])) \
        .add(Linear(512 * 7 * 7, 4096)).add(ReLU()).add(Dropout(0.5)) \
        .add(Linear(4096, 4096)).add(ReLU()).add(Dropout(0.5)) \
        .add(Linear(4096, class_num)) \
        .add(LogSoftMax())
    return model


def build_vgg16(class_num: int = 1000):
    """«bigdl»/models/vgg/Vgg_16.scala"""
    return _build_vgg_imagenet(_VGG16, class_num)


def build_vgg19(class_num: int = 1000):
    """«bigdl»/models/vgg/Vgg_19.scala"""
    return _build_vgg_imagenet(_VGG19, class_num)


def build_vgg_cifar(class_num: int = 10):
    """«bigdl»/models/vgg/VggForCifar10.scala — conv+BN blocks, two
    512-wide FC heads with BatchNormalization + Dropout."""
    from bigdl_tpu.nn import BatchNormalization

    model = Sequential()

    def conv_bn(n_in, n_out):
        model.add(SpatialConvolution(n_in, n_out, 3, 3, 1, 1, 1, 1))
        model.add(SpatialBatchNormalization(n_out))
        model.add(ReLU())

    cfg = [(3, 64), (64, 64), "M", (64, 128), (128, 128), "M",
           (128, 256), (256, 256), (256, 256), "M",
           (256, 512), (512, 512), (512, 512), "M",
           (512, 512), (512, 512), (512, 512), "M"]
    for item in cfg:
        if item == "M":
            model.add(SpatialMaxPooling(2, 2, 2, 2))
        else:
            conv_bn(*item)
    model.add(Reshape([512])) \
        .add(Linear(512, 512)).add(BatchNormalization(512)).add(ReLU()) \
        .add(Dropout(0.5)) \
        .add(Linear(512, class_num)) \
        .add(LogSoftMax())
    return model
