"""AlexNet (OWT single-tower variant).

Rebuild of «bigdl»/models/alexnet/AlexNet.scala (the AlexNet_OWT and
grouped original).
"""

from __future__ import annotations

from bigdl_tpu.nn import (
    Dropout,
    Linear,
    LogSoftMax,
    ReLU,
    Reshape,
    Sequential,
    SpatialConvolution,
    SpatialCrossMapLRN,
    SpatialMaxPooling,
)


def build_alexnet(class_num: int = 1000, has_dropout: bool = True):
    """AlexNet_OWT («bigdl» AlexNet.scala): 227x227 input."""
    model = Sequential()
    model.add(SpatialConvolution(3, 64, 11, 11, 4, 4).set_name("conv1")) \
        .add(ReLU()) \
        .add(SpatialMaxPooling(3, 3, 2, 2).set_name("pool1")) \
        .add(SpatialConvolution(64, 192, 5, 5, 1, 1, 2, 2).set_name("conv2")) \
        .add(ReLU()) \
        .add(SpatialMaxPooling(3, 3, 2, 2).set_name("pool2")) \
        .add(SpatialConvolution(192, 384, 3, 3, 1, 1, 1, 1).set_name("conv3")) \
        .add(ReLU()) \
        .add(SpatialConvolution(384, 256, 3, 3, 1, 1, 1, 1).set_name("conv4")) \
        .add(ReLU()) \
        .add(SpatialConvolution(256, 256, 3, 3, 1, 1, 1, 1).set_name("conv5")) \
        .add(ReLU()) \
        .add(SpatialMaxPooling(3, 3, 2, 2).set_name("pool5")) \
        .add(Reshape([256 * 6 * 6]))
    if has_dropout:
        model.add(Dropout(0.5))
    model.add(Linear(256 * 6 * 6, 4096).set_name("fc6")).add(ReLU())
    if has_dropout:
        model.add(Dropout(0.5))
    model.add(Linear(4096, 4096).set_name("fc7")).add(ReLU())
    model.add(Linear(4096, class_num).set_name("fc8")).add(LogSoftMax())
    return model


def build_alexnet_original(class_num: int = 1000):
    """The grouped/LRN original («bigdl» AlexNet.scala AlexNet):
    224x224 input, n_group=2 convs, cross-map LRN."""
    model = Sequential()
    model.add(SpatialConvolution(3, 96, 11, 11, 4, 4).set_name("conv1")) \
        .add(ReLU()) \
        .add(SpatialCrossMapLRN(5, 0.0001, 0.75).set_name("norm1")) \
        .add(SpatialMaxPooling(3, 3, 2, 2).set_name("pool1")) \
        .add(SpatialConvolution(96, 256, 5, 5, 1, 1, 2, 2, n_group=2)
             .set_name("conv2")) \
        .add(ReLU()) \
        .add(SpatialCrossMapLRN(5, 0.0001, 0.75).set_name("norm2")) \
        .add(SpatialMaxPooling(3, 3, 2, 2).set_name("pool2")) \
        .add(SpatialConvolution(256, 384, 3, 3, 1, 1, 1, 1).set_name("conv3")) \
        .add(ReLU()) \
        .add(SpatialConvolution(384, 384, 3, 3, 1, 1, 1, 1, n_group=2)
             .set_name("conv4")) \
        .add(ReLU()) \
        .add(SpatialConvolution(384, 256, 3, 3, 1, 1, 1, 1, n_group=2)
             .set_name("conv5")) \
        .add(ReLU()) \
        .add(SpatialMaxPooling(3, 3, 2, 2).set_name("pool5")) \
        .add(Reshape([256 * 6 * 6])) \
        .add(Linear(256 * 6 * 6, 4096).set_name("fc6")).add(ReLU()) \
        .add(Dropout(0.5)) \
        .add(Linear(4096, 4096).set_name("fc7")).add(ReLU()) \
        .add(Dropout(0.5)) \
        .add(Linear(4096, class_num).set_name("fc8")) \
        .add(LogSoftMax())
    return model
