"""Neural Collaborative Filtering (NCF / NeuralCF).

Rebuild of the reference's recommendation model (⟦«py»⟧ NCF example /
NeuralCF builder; evaluated with the HitRatio/NDCG ValidationMethods in
⟦«bigdl»/optim/ValidationMethod.scala⟧): a GMF branch (elementwise
product of user/item embeddings) concatenated with an MLP branch
(stacked dense layers over the concatenated embeddings), ending in a
rating classifier.

Input is a (B, 2) matrix of 1-based ``(user_id, item_id)`` pairs;
output is a (B, class_num) log-probability matrix (explicit-feedback
ratings with ClassNLLCriterion, the reference example's setup).

TPU note: the whole model is two embedding gathers + a handful of
dense matmuls — one fused XLA program; both branches batch onto the
MXU with no host-side feature crossing.
"""

from __future__ import annotations

from typing import Sequence

from bigdl_tpu.nn import (
    CMulTable,
    Graph,
    Input,
    JoinTable,
    Linear,
    LogSoftMax,
    LookupTable,
    ReLU,
    Select,
)


def build_ncf(
    user_count: int,
    item_count: int,
    class_num: int = 5,
    user_embed: int = 20,
    item_embed: int = 20,
    hidden_layers: Sequence[int] = (40, 20, 10),
    mf_embed: int = 20,
    include_mf: bool = True,
):
    """NeuralCF graph (reference NCF example defaults)."""
    inp = Input()
    users = Select(2, 1)(inp)   # (B,) 1-based user ids
    items = Select(2, 2)(inp)   # (B,) 1-based item ids

    mlp_u = LookupTable(user_count, user_embed)(users)
    mlp_i = LookupTable(item_count, item_embed)(items)
    h = JoinTable(2, 2)(mlp_u, mlp_i)
    width = user_embed + item_embed
    for n in hidden_layers:
        h = ReLU()(Linear(width, n)(h))
        width = n

    if include_mf:
        mf_u = LookupTable(user_count, mf_embed)(users)
        mf_i = LookupTable(item_count, mf_embed)(items)
        gmf = CMulTable()(mf_u, mf_i)
        h = JoinTable(2, 2)(gmf, h)
        width = mf_embed + width

    out = LogSoftMax()(Linear(width, class_num)(h))
    return Graph(inp, out)
