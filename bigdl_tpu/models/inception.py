"""Inception-v1 (GoogLeNet) and Inception-v2 (BN-Inception).

Rebuild of «bigdl»/models/inception/Inception_v1.scala — the
Inception_Layer_v1 module (4-branch Concat: 1x1 / 3x3-reduce+3x3 /
5x5-reduce+5x5 / pool+proj) and the NoAuxClassifier main tower (the
reference's primary training config) — and of Inception_v2.scala: the
BatchNorm variant where every conv is followed by
SpatialBatchNormalization, the 5x5 branch is factored into a double
3x3, and the grid-reduction modules (3c/4e) drop the 1x1 branch and
run their conv towers at stride 2 alongside a pass-through max-pool.
"""

from __future__ import annotations

from bigdl_tpu.nn import (
    Concat,
    Dropout,
    Linear,
    LogSoftMax,
    ReLU,
    Reshape,
    Sequential,
    SpatialAveragePooling,
    SpatialBatchNormalization,
    SpatialConvolution,
    SpatialCrossMapLRN,
    SpatialMaxPooling,
)
from bigdl_tpu.nn.layers import Xavier


def _conv_relu(n_in, n_out, kw, kh, sw=1, sh=1, pw=0, ph=0, name=""):
    seq = Sequential()
    seq.add(
        SpatialConvolution(n_in, n_out, kw, kh, sw, sh, pw, ph,
                           init_method=Xavier()).set_name(name + "conv")
    ).add(ReLU())
    return seq


def inception_layer_v1(n_in, config, name_prefix=""):
    """«bigdl» Inception_Layer_v1: config = [[1x1], [3x3 reduce, 3x3],
    [5x5 reduce, 5x5], [pool proj]]."""
    concat = Concat(2)
    c1 = Sequential().add(
        SpatialConvolution(n_in, config[0][0], 1, 1,
                           init_method=Xavier()).set_name(name_prefix + "1x1")
    ).add(ReLU())
    concat.add(c1)
    c3 = Sequential().add(
        SpatialConvolution(n_in, config[1][0], 1, 1,
                           init_method=Xavier()).set_name(name_prefix + "3x3_reduce")
    ).add(ReLU()).add(
        SpatialConvolution(config[1][0], config[1][1], 3, 3, 1, 1, 1, 1,
                           init_method=Xavier()).set_name(name_prefix + "3x3")
    ).add(ReLU())
    concat.add(c3)
    c5 = Sequential().add(
        SpatialConvolution(n_in, config[2][0], 1, 1,
                           init_method=Xavier()).set_name(name_prefix + "5x5_reduce")
    ).add(ReLU()).add(
        SpatialConvolution(config[2][0], config[2][1], 5, 5, 1, 1, 2, 2,
                           init_method=Xavier()).set_name(name_prefix + "5x5")
    ).add(ReLU())
    concat.add(c5)
    pool = Sequential().add(SpatialMaxPooling(3, 3, 1, 1, 1, 1).ceil()).add(
        SpatialConvolution(n_in, config[3][0], 1, 1,
                           init_method=Xavier()).set_name(name_prefix + "pool_proj")
    ).add(ReLU())
    concat.add(pool)
    return concat


def _conv_bn_relu(seq, n_in, n_out, kw=1, kh=1, sw=1, sh=1, pw=0, ph=0,
                  name=""):
    """conv + SpatialBatchNormalization + ReLU — the v2 building block
    («bigdl» Inception_v2.scala pairs every conv with an SpatialBN)."""
    seq.add(
        SpatialConvolution(n_in, n_out, kw, kh, sw, sh, pw, ph,
                           init_method=Xavier()).set_name(name)
    ).add(
        SpatialBatchNormalization(n_out).set_name(name + "/bn")
    ).add(ReLU())
    return seq


def inception_layer_v2(n_in, config, name_prefix=""):
    """«bigdl» Inception_Layer_v2.

    ``config = ([p1], [r3, c3], [rd3, cd3], (pool_kind, proj))``:
    1x1 branch (dropped when p1 == 0 — the stride-2 grid-reduction
    form), 3x3 branch, double-3x3 branch, and an avg/max pool branch
    with optional 1x1 projection.  When p1 == 0 the conv towers run
    their last conv at stride 2 and the pool branch is a bare stride-2
    max-pool pass-through.
    """
    reduce_grid = config[0][0] == 0
    stride = 2 if reduce_grid else 1
    concat = Concat(2)
    if not reduce_grid:
        c1 = Sequential()
        _conv_bn_relu(c1, n_in, config[0][0], name=name_prefix + "1x1")
        concat.add(c1)
    c3 = Sequential()
    _conv_bn_relu(c3, n_in, config[1][0], name=name_prefix + "3x3_reduce")
    _conv_bn_relu(c3, config[1][0], config[1][1], 3, 3, stride, stride, 1, 1,
                  name=name_prefix + "3x3")
    concat.add(c3)
    cd = Sequential()
    _conv_bn_relu(cd, n_in, config[2][0],
                  name=name_prefix + "double3x3_reduce")
    _conv_bn_relu(cd, config[2][0], config[2][1], 3, 3, 1, 1, 1, 1,
                  name=name_prefix + "double3x3a")
    _conv_bn_relu(cd, config[2][1], config[2][1], 3, 3, stride, stride, 1, 1,
                  name=name_prefix + "double3x3b")
    concat.add(cd)
    pool = Sequential()
    pool_kind, proj = config[3]
    if reduce_grid:
        pool.add(SpatialMaxPooling(3, 3, 2, 2).ceil()
                 .set_name(name_prefix + "pool"))
    else:
        if pool_kind == "max":
            pool.add(SpatialMaxPooling(3, 3, 1, 1, 1, 1).ceil()
                     .set_name(name_prefix + "pool"))
        else:
            pool.add(SpatialAveragePooling(3, 3, 1, 1, 1, 1).ceil()
                     .set_name(name_prefix + "pool"))
        _conv_bn_relu(pool, n_in, proj, name=name_prefix + "pool_proj")
    concat.add(pool)
    return concat


def build_inception_v2(class_num: int = 1000):
    """«bigdl» Inception_v2 (BN-Inception, 224x224 input)."""
    model = Sequential()
    _conv_bn_relu(model, 3, 64, 7, 7, 2, 2, 3, 3, name="conv1/7x7_s2")
    model.add(SpatialMaxPooling(3, 3, 2, 2).ceil().set_name("pool1/3x3_s2"))
    _conv_bn_relu(model, 64, 64, name="conv2/3x3_reduce")
    _conv_bn_relu(model, 64, 192, 3, 3, 1, 1, 1, 1, name="conv2/3x3")
    model.add(SpatialMaxPooling(3, 3, 2, 2).ceil().set_name("pool2/3x3_s2"))
    model \
        .add(inception_layer_v2(
            192, ([64], [64, 64], [64, 96], ("avg", 32)), "inception_3a/")) \
        .add(inception_layer_v2(
            256, ([64], [64, 96], [64, 96], ("avg", 64)), "inception_3b/")) \
        .add(inception_layer_v2(
            320, ([0], [128, 160], [64, 96], ("max", 0)), "inception_3c/")) \
        .add(inception_layer_v2(
            576, ([224], [64, 96], [96, 128], ("avg", 128)),
            "inception_4a/")) \
        .add(inception_layer_v2(
            576, ([192], [96, 128], [96, 128], ("avg", 128)),
            "inception_4b/")) \
        .add(inception_layer_v2(
            576, ([160], [128, 160], [128, 160], ("avg", 128)),
            "inception_4c/")) \
        .add(inception_layer_v2(
            608, ([96], [128, 192], [160, 192], ("avg", 128)),
            "inception_4d/")) \
        .add(inception_layer_v2(
            608, ([0], [128, 192], [192, 256], ("max", 0)),
            "inception_4e/")) \
        .add(inception_layer_v2(
            1056, ([352], [192, 320], [160, 224], ("avg", 128)),
            "inception_5a/")) \
        .add(inception_layer_v2(
            1024, ([352], [192, 320], [192, 224], ("max", 128)),
            "inception_5b/")) \
        .add(SpatialAveragePooling(7, 7, 1, 1).set_name("pool5/7x7_s1")) \
        .add(Reshape([1024])) \
        .add(Linear(1024, class_num,
                    init_method=Xavier()).set_name("loss3/classifier")) \
        .add(LogSoftMax())
    return model


def inception_recipe_optim(batch_size: int, n_epochs: int,
                           iterations_per_epoch: int,
                           base_lr: float = None):
    """The reference Inception recipe («bigdl» models/inception
    Train.scala): SGD + momentum + weight decay with a Poly(0.5)
    learning-rate decay over the full training run."""
    from bigdl_tpu.optim import SGD, Poly

    if base_lr is None:
        base_lr = 0.0898330 * batch_size / 1024.0
    max_iter = max(1, n_epochs * iterations_per_epoch)
    return SGD(learningrate=base_lr, momentum=0.9, dampening=0.0,
               weightdecay=1e-4,
               learningrate_schedule=Poly(0.5, max_iter))


def main(argv=None):
    """Console entry (reference: models/inception Train.scala CLI).

    With ``-f/--data-dir`` pointing at an ImageNet-style tree this is
    the TrainImageNet path: Inception v1 or v2 (``--version``) + the
    reference Poly recipe, file-backed distributed ingestion under
    DistriOptimizer.  Without a data dir it trains a few steps on a
    synthetic 224px task as a smoke path."""
    import argparse
    import logging

    import numpy as np

    from bigdl_tpu.nn import ClassNLLCriterion
    from bigdl_tpu.optim import Optimizer, Trigger

    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser()
    ap.add_argument("-f", "--data-dir", default=None,
                    help="ImageNet-style dir (train/<cls>/*.jpg); "
                         "absent = tiny synthetic smoke task")
    ap.add_argument("--version", choices=["v1", "v2"], default="v1")
    ap.add_argument("-b", "--batch-size", type=int, default=128)
    ap.add_argument("-e", "--max-epoch", type=int, default=1)
    ap.add_argument("--learning-rate", type=float, default=None)
    ap.add_argument("-n", "--num-samples", type=int, default=64)
    ap.add_argument("--checkpoint", default=None)
    args = ap.parse_args(argv)

    build = build_inception_v1 if args.version == "v1" \
        else build_inception_v2

    if args.data_dir:
        from bigdl_tpu.models.train_util import train_imagenet_folder

        train_imagenet_folder(
            build,
            lambda bs, ep, it: inception_recipe_optim(
                bs, ep, it, base_lr=args.learning_rate),
            args.data_dir, args.batch_size, args.max_epoch,
            checkpoint=args.checkpoint)
        return

    rs = np.random.RandomState(0)
    n = args.num_samples
    x = rs.rand(n, 3, 224, 224).astype(np.float32)
    y = (rs.randint(0, 10, n) + 1).astype(np.float32)
    model = build(class_num=10)
    bs = min(args.batch_size, n)
    opt = Optimizer(model, (x, y), ClassNLLCriterion(), batch_size=bs)
    opt.set_optim_method(inception_recipe_optim(
        bs, args.max_epoch, max(1, n // bs),
        base_lr=args.learning_rate))
    opt.set_end_when(Trigger.max_epoch(args.max_epoch))
    if args.checkpoint:
        opt.set_checkpoint(args.checkpoint, Trigger.every_epoch())
    opt.optimize()


def build_inception_v1(class_num: int = 1000, has_dropout: bool = True):
    """«bigdl» Inception_v1_NoAuxClassifier (224x224 input)."""
    model = Sequential()
    model.add(
        SpatialConvolution(3, 64, 7, 7, 2, 2, 3, 3,
                           init_method=Xavier()).set_name("conv1/7x7_s2")
    ).add(ReLU()) \
        .add(SpatialMaxPooling(3, 3, 2, 2).ceil().set_name("pool1/3x3_s2")) \
        .add(SpatialCrossMapLRN(5, 0.0001, 0.75).set_name("pool1/norm1")) \
        .add(SpatialConvolution(64, 64, 1, 1,
                                init_method=Xavier()).set_name("conv2/3x3_reduce")) \
        .add(ReLU()) \
        .add(SpatialConvolution(64, 192, 3, 3, 1, 1, 1, 1,
                                init_method=Xavier()).set_name("conv2/3x3")) \
        .add(ReLU()) \
        .add(SpatialCrossMapLRN(5, 0.0001, 0.75).set_name("conv2/norm2")) \
        .add(SpatialMaxPooling(3, 3, 2, 2).ceil().set_name("pool2/3x3_s2")) \
        .add(inception_layer_v1(192, [[64], [96, 128], [16, 32], [32]],
                                "inception_3a/")) \
        .add(inception_layer_v1(256, [[128], [128, 192], [32, 96], [64]],
                                "inception_3b/")) \
        .add(SpatialMaxPooling(3, 3, 2, 2).ceil()) \
        .add(inception_layer_v1(480, [[192], [96, 208], [16, 48], [64]],
                                "inception_4a/")) \
        .add(inception_layer_v1(512, [[160], [112, 224], [24, 64], [64]],
                                "inception_4b/")) \
        .add(inception_layer_v1(512, [[128], [128, 256], [24, 64], [64]],
                                "inception_4c/")) \
        .add(inception_layer_v1(512, [[112], [144, 288], [32, 64], [64]],
                                "inception_4d/")) \
        .add(inception_layer_v1(528, [[256], [160, 320], [32, 128], [128]],
                                "inception_4e/")) \
        .add(SpatialMaxPooling(3, 3, 2, 2).ceil()) \
        .add(inception_layer_v1(832, [[256], [160, 320], [32, 128], [128]],
                                "inception_5a/")) \
        .add(inception_layer_v1(832, [[384], [192, 384], [48, 128], [128]],
                                "inception_5b/")) \
        .add(SpatialAveragePooling(7, 7, 1, 1).set_name("pool5/7x7_s1"))
    if has_dropout:
        model.add(Dropout(0.4))
    model.add(Reshape([1024])) \
        .add(Linear(1024, class_num,
                    init_method=Xavier()).set_name("loss3/classifier")) \
        .add(LogSoftMax())
    return model


if __name__ == "__main__":
    main()
