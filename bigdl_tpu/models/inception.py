"""Inception-v1 (GoogLeNet).

Rebuild of «bigdl»/models/inception/Inception_v1.scala: the
Inception_Layer_v1 module (4-branch Concat: 1x1 / 3x3-reduce+3x3 /
5x5-reduce+5x5 / pool+proj) and the NoAuxClassifier main tower (the
reference's primary training config).
"""

from __future__ import annotations

from bigdl_tpu.nn import (
    Concat,
    Dropout,
    Linear,
    LogSoftMax,
    ReLU,
    Reshape,
    Sequential,
    SpatialAveragePooling,
    SpatialConvolution,
    SpatialCrossMapLRN,
    SpatialMaxPooling,
)
from bigdl_tpu.nn.layers import Xavier


def _conv_relu(n_in, n_out, kw, kh, sw=1, sh=1, pw=0, ph=0, name=""):
    seq = Sequential()
    seq.add(
        SpatialConvolution(n_in, n_out, kw, kh, sw, sh, pw, ph,
                           init_method=Xavier()).set_name(name + "conv")
    ).add(ReLU())
    return seq


def inception_layer_v1(n_in, config, name_prefix=""):
    """«bigdl» Inception_Layer_v1: config = [[1x1], [3x3 reduce, 3x3],
    [5x5 reduce, 5x5], [pool proj]]."""
    concat = Concat(2)
    c1 = Sequential().add(
        SpatialConvolution(n_in, config[0][0], 1, 1,
                           init_method=Xavier()).set_name(name_prefix + "1x1")
    ).add(ReLU())
    concat.add(c1)
    c3 = Sequential().add(
        SpatialConvolution(n_in, config[1][0], 1, 1,
                           init_method=Xavier()).set_name(name_prefix + "3x3_reduce")
    ).add(ReLU()).add(
        SpatialConvolution(config[1][0], config[1][1], 3, 3, 1, 1, 1, 1,
                           init_method=Xavier()).set_name(name_prefix + "3x3")
    ).add(ReLU())
    concat.add(c3)
    c5 = Sequential().add(
        SpatialConvolution(n_in, config[2][0], 1, 1,
                           init_method=Xavier()).set_name(name_prefix + "5x5_reduce")
    ).add(ReLU()).add(
        SpatialConvolution(config[2][0], config[2][1], 5, 5, 1, 1, 2, 2,
                           init_method=Xavier()).set_name(name_prefix + "5x5")
    ).add(ReLU())
    concat.add(c5)
    pool = Sequential().add(SpatialMaxPooling(3, 3, 1, 1, 1, 1).ceil()).add(
        SpatialConvolution(n_in, config[3][0], 1, 1,
                           init_method=Xavier()).set_name(name_prefix + "pool_proj")
    ).add(ReLU())
    concat.add(pool)
    return concat


def build_inception_v1(class_num: int = 1000, has_dropout: bool = True):
    """«bigdl» Inception_v1_NoAuxClassifier (224x224 input)."""
    model = Sequential()
    model.add(
        SpatialConvolution(3, 64, 7, 7, 2, 2, 3, 3,
                           init_method=Xavier()).set_name("conv1/7x7_s2")
    ).add(ReLU()) \
        .add(SpatialMaxPooling(3, 3, 2, 2).ceil().set_name("pool1/3x3_s2")) \
        .add(SpatialCrossMapLRN(5, 0.0001, 0.75).set_name("pool1/norm1")) \
        .add(SpatialConvolution(64, 64, 1, 1,
                                init_method=Xavier()).set_name("conv2/3x3_reduce")) \
        .add(ReLU()) \
        .add(SpatialConvolution(64, 192, 3, 3, 1, 1, 1, 1,
                                init_method=Xavier()).set_name("conv2/3x3")) \
        .add(ReLU()) \
        .add(SpatialCrossMapLRN(5, 0.0001, 0.75).set_name("conv2/norm2")) \
        .add(SpatialMaxPooling(3, 3, 2, 2).ceil().set_name("pool2/3x3_s2")) \
        .add(inception_layer_v1(192, [[64], [96, 128], [16, 32], [32]],
                                "inception_3a/")) \
        .add(inception_layer_v1(256, [[128], [128, 192], [32, 96], [64]],
                                "inception_3b/")) \
        .add(SpatialMaxPooling(3, 3, 2, 2).ceil()) \
        .add(inception_layer_v1(480, [[192], [96, 208], [16, 48], [64]],
                                "inception_4a/")) \
        .add(inception_layer_v1(512, [[160], [112, 224], [24, 64], [64]],
                                "inception_4b/")) \
        .add(inception_layer_v1(512, [[128], [128, 256], [24, 64], [64]],
                                "inception_4c/")) \
        .add(inception_layer_v1(512, [[112], [144, 288], [32, 64], [64]],
                                "inception_4d/")) \
        .add(inception_layer_v1(528, [[256], [160, 320], [32, 128], [128]],
                                "inception_4e/")) \
        .add(SpatialMaxPooling(3, 3, 2, 2).ceil()) \
        .add(inception_layer_v1(832, [[256], [160, 320], [32, 128], [128]],
                                "inception_5a/")) \
        .add(inception_layer_v1(832, [[384], [192, 384], [48, 128], [128]],
                                "inception_5b/")) \
        .add(SpatialAveragePooling(7, 7, 1, 1).set_name("pool5/7x7_s1"))
    if has_dropout:
        model.add(Dropout(0.4))
    model.add(Reshape([1024])) \
        .add(Linear(1024, class_num,
                    init_method=Xavier()).set_name("loss3/classifier")) \
        .add(LogSoftMax())
    return model
