"""PTB language model (Recurrent + LSTM, TimeDistributedCriterion).

Rebuild of «bigdl»/models/rnn/ (SimpleRNN / the PTB LM config named by
BASELINE.json): LookupTable embedding -> Recurrent(LSTM) stack ->
TimeDistributed(Linear) -> LogSoftMax, trained with
TimeDistributedCriterion(ClassNLLCriterion), evaluated by perplexity.
"""

from __future__ import annotations

import math

import numpy as np

from bigdl_tpu.nn import (
    ClassNLLCriterion,
    LogSoftMax,
    LookupTable,
    LSTM,
    Recurrent,
    Sequential,
    TimeDistributed,
    TimeDistributedCriterion,
    Linear,
)


def build_ptb_lm(vocab_size: int, embed_size: int = 128,
                 hidden_size: int = 128, num_layers: int = 1,
                 key_dropout: float = 0.0):
    model = Sequential()
    model.add(LookupTable(vocab_size, embed_size))
    n_in = embed_size
    for _ in range(num_layers):
        model.add(Recurrent().add(LSTM(n_in, hidden_size, p=key_dropout)))
        n_in = hidden_size
    model.add(TimeDistributed(Linear(hidden_size, vocab_size)))
    model.add(LogSoftMax())
    return model


def perplexity(model, x, y, batch_size: int = 32) -> float:
    """exp(mean NLL per token) — the PTB metric."""
    import jax.numpy as jnp

    crit = TimeDistributedCriterion(ClassNLLCriterion(), size_average=True)
    model.evaluate()
    total, count = 0.0, 0
    for b in range(0, x.shape[0], batch_size):
        xb = jnp.asarray(x[b : b + batch_size])
        yb = jnp.asarray(y[b : b + batch_size])
        out, _ = model.apply(model.params(), model.state(), xb,
                             training=False)
        # TimeDistributedCriterion(size_average) == mean NLL per token here
        nll = float(crit.loss(out, yb))
        total += nll * xb.shape[0]
        count += xb.shape[0]
    return math.exp(total / max(1, count))


def train_ptb(data_tokens=None, vocab_size: int = 100, batch_size: int = 20,
              num_steps: int = 20, max_epoch: int = 2,
              hidden_size: int = 128, learning_rate: float = 0.5):
    """Runnable PTB training (reference: models/rnn/Train.scala).  With
    no PTB text on disk, trains on the synthetic Markov stream."""
    from bigdl_tpu.dataset import ArrayDataSet
    from bigdl_tpu.dataset.text import ptb_bptt_batches, synthetic_ptb_stream
    from bigdl_tpu.optim import LocalOptimizer, SGD, Trigger

    if data_tokens is None:
        data_tokens = synthetic_ptb_stream(vocab_size=vocab_size)
    xs, ys = ptb_bptt_batches(data_tokens, batch_size, num_steps)
    x = xs.reshape(-1, num_steps)
    y = ys.reshape(-1, num_steps)
    model = build_ptb_lm(vocab_size, hidden_size=hidden_size,
                         embed_size=hidden_size)
    crit = TimeDistributedCriterion(ClassNLLCriterion(), size_average=True)
    opt = LocalOptimizer(model, (x, y), crit, batch_size=batch_size)
    opt.set_optim_method(SGD(learningrate=learning_rate))
    opt.set_end_when(Trigger.max_epoch(max_epoch))
    opt.set_gradient_clipping_by_l2_norm(5.0)  # the reference PTB recipe clips
    trained = opt.optimize()
    ppl = perplexity(trained, x, y, batch_size)
    return trained, opt, ppl


def main(argv=None):
    """Console entry (reference: models/rnn Train.scala — PTB LM)."""
    import argparse
    import logging

    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser()
    ap.add_argument("-b", "--batch-size", type=int, default=20)
    ap.add_argument("-e", "--max-epoch", type=int, default=2)
    args = ap.parse_args(argv)
    model, opt, ppl = train_ptb(batch_size=args.batch_size,
                                max_epoch=args.max_epoch)
    print(f"final train perplexity: {ppl:.2f}")


if __name__ == "__main__":
    main()
