"""LeNet-5 on MNIST.

Rebuild of «bigdl»/models/lenet/LeNet5.scala (+ Train.scala/Test.scala):
the reference's first-model milestone — Sequential(Reshape, conv5x5x6,
tanh, maxpool, conv5x5x12, tanh, maxpool, Linear(100), tanh, Linear(10),
LogSoftMax), trained with SGD + ClassNLLCriterion.
"""

from __future__ import annotations

from bigdl_tpu.nn import (
    Linear,
    LogSoftMax,
    Reshape,
    Sequential,
    SpatialConvolution,
    SpatialMaxPooling,
    Tanh,
)


def build_lenet5(class_num: int = 10) -> Sequential:
    model = Sequential()
    model.add(Reshape([1, 28, 28])) \
        .add(SpatialConvolution(1, 6, 5, 5).set_name("conv1_5x5")) \
        .add(Tanh()) \
        .add(SpatialMaxPooling(2, 2, 2, 2)) \
        .add(SpatialConvolution(6, 12, 5, 5).set_name("conv2_5x5")) \
        .add(Tanh()) \
        .add(SpatialMaxPooling(2, 2, 2, 2)) \
        .add(Reshape([12 * 4 * 4])) \
        .add(Linear(12 * 4 * 4, 100).set_name("fc1")) \
        .add(Tanh()) \
        .add(Linear(100, class_num).set_name("score")) \
        .add(LogSoftMax())
    return model


def train_lenet(
    data_dir: str = None,
    batch_size: int = 128,
    max_epoch: int = 2,
    learning_rate: float = 0.05,
    checkpoint_path: str = None,
    distributed: bool = False,
):
    """Runnable training entry (reference: models/lenet/Train.scala)."""
    from bigdl_tpu.dataset import ArrayDataSet
    from bigdl_tpu.dataset.mnist import load_mnist, normalize
    from bigdl_tpu.nn import ClassNLLCriterion
    from bigdl_tpu.optim import Optimizer, SGD, Top1Accuracy, Trigger

    x_train, y_train = load_mnist(data_dir, "train")
    x_test, y_test = load_mnist(data_dir, "test")
    train_ds = ArrayDataSet(normalize(x_train), y_train, batch_size)
    test_ds = ArrayDataSet(normalize(x_test), y_test, batch_size)

    model = build_lenet5()
    optimizer = Optimizer(
        model=model,
        training_set=train_ds,
        criterion=ClassNLLCriterion(),
        batch_size=batch_size,
        distributed=distributed,
    )
    optimizer.set_optim_method(SGD(learningrate=learning_rate)) \
        .set_end_when(Trigger.max_epoch(max_epoch)) \
        .set_validation(
            trigger=Trigger.every_epoch(),
            dataset=test_ds,
            methods=[Top1Accuracy()],
        )
    if checkpoint_path:
        optimizer.set_checkpoint(checkpoint_path)
    trained = optimizer.optimize()
    return trained, optimizer


def main(argv=None):
    """Console entry (reference: models/lenet Train.scala CLI)."""
    import argparse
    import logging

    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser()
    ap.add_argument("-f", "--data-dir", default=None)
    ap.add_argument("-b", "--batch-size", type=int, default=128)
    ap.add_argument("-e", "--max-epoch", type=int, default=2)
    ap.add_argument("--learning-rate", type=float, default=0.05)
    ap.add_argument("--checkpoint", default=None)
    ap.add_argument("--distributed", action="store_true")
    args = ap.parse_args(argv)
    train_lenet(args.data_dir, args.batch_size, args.max_epoch,
                args.learning_rate, args.checkpoint, args.distributed)


if __name__ == "__main__":
    main()
