"""Transformer language model — the long-context flagship.

No reference analogue: classic BigDL's sequence stack tops out at
Recurrent/LSTM BPTT windows (SURVEY.md §5 "long-context: absent").  This
model is the rebuild's new capability and the vehicle for the
sequence-parallel / ring-attention / tensor-parallel paths in
``bigdl_tpu.parallel``:

* token + learned positional embeddings,
* N pre-LN TransformerBlocks (Pallas flash attention on TPU),
* final LayerNorm + vocab projection.

Tokens are 0-based int32 (unlike LookupTable's 1-based parity
convention — this model has no reference API to mirror).
"""

from __future__ import annotations

import numpy as np

from bigdl_tpu.nn.attention import (
    LayerNorm,
    PositionalEmbedding,
    TransformerBlock,
    _Composite,
)
from bigdl_tpu.nn.layers import Linear, _to_device
from bigdl_tpu.nn.module import AbstractModule


class TokenEmbedding(AbstractModule):
    """0-based token embedding, N(0, 0.02) init (GPT convention)."""

    param_names = ("weight",)

    def __init__(self, vocab_size: int, dim: int):
        super().__init__()
        self._config = dict(vocab_size=vocab_size, dim=dim)
        self.vocab_size = vocab_size
        self.dim = dim
        self.reset()

    def reset(self):
        from bigdl_tpu.common import RandomGenerator

        self.weight = _to_device(
            RandomGenerator.RNG.normal(
                0.0, 0.02, size=(self.vocab_size, self.dim)
            ).astype(np.float32)
        )
        return self

    def update_output_pure(self, params, input, *, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.take(params["weight"], input.astype(jnp.int32), axis=0)


class TransformerLM(_Composite):
    """Decoder-only causal LM over (batch, seq) int tokens -> logits
    (batch, seq, vocab)."""

    def __init__(self, vocab_size: int, dim: int = 256, n_head: int = 4,
                 n_layer: int = 4, max_len: int = 1024, mlp_ratio: int = 4,
                 dropout: float = 0.0, attn_impl: str = "auto",
                 remat: bool = False):
        super().__init__()
        self._config = dict(vocab_size=vocab_size, dim=dim, n_head=n_head,
                            n_layer=n_layer, max_len=max_len,
                            mlp_ratio=mlp_ratio, dropout=dropout,
                            attn_impl=attn_impl, remat=remat)
        self.vocab_size = vocab_size
        self.dim = dim
        self.n_layer = n_layer
        # remat=True: per-block gradient checkpointing — backward
        # recomputes each block's forward instead of storing its
        # activations, cutting peak HBM from O(n_layer * seq * dim)
        # activations to O(sqrt-ish) at ~1/3 extra FLOPs (the long-
        # context training lever; pairs with ring/ulysses seq-parallel)
        self.remat = remat
        self._add_child("wte", TokenEmbedding(vocab_size, dim))
        self._add_child("wpe", PositionalEmbedding(max_len, dim))
        for i in range(n_layer):
            self._add_child(f"h{i}", TransformerBlock(
                dim, n_head, mlp_ratio=mlp_ratio, causal=True,
                attn_impl=attn_impl, dropout=dropout))
        self._add_child("ln_f", LayerNorm(dim))
        self._add_child("head", Linear(dim, vocab_size, with_bias=False))

    def apply(self, params, state, input, *, training=False, rng=None):
        import jax

        c = self._children
        x, _ = c["wte"].apply(params["wte"], {}, input)
        x, _ = c["wpe"].apply(params["wpe"], {}, x)
        for i in range(self.n_layer):
            key = None
            if rng is not None:
                key = jax.random.fold_in(rng, i)
            block = c[f"h{i}"]
            if self.remat:
                def blk(p, xx, _b=block, _k=key):
                    out, _ = _b.apply(p, {}, xx, training=training, rng=_k)
                    return out
                x = jax.checkpoint(blk)(params[f"h{i}"], x)
            else:
                x, _ = block.apply(params[f"h{i}"], {}, x,
                                   training=training, rng=key)
        x, _ = c["ln_f"].apply(params["ln_f"], {}, x)
        logits, _ = c["head"].apply(params["head"], {}, x)
        return logits, state

    def generate(self, params, prompt, max_new_tokens: int, *,
                 temperature: float = 0.0, rng=None, cache_dtype=None):
        """Autoregressive decoding with a static-shape KV cache.

        TPU-idiomatic two-phase decode: the prompt is prefetched in ONE
        batched forward (``TransformerBlock.prefill`` — the identical
        attention path training uses — also yields each layer's K/V),
        then a single compiled ``lax.scan`` step generates tokens, with
        per-layer (B, H, T_total, Dh) cache buffers updated in place by
        ``dynamic_update_slice`` (``TransformerBlock.decode_step``).
        All shapes static — no per-token retrace or dispatch.

        ``temperature=0`` is greedy argmax; ``>0`` samples categorical
        (requires ``rng``).  Returns (B, prompt_len + max_new_tokens)
        int32 token ids.

        ``cache_dtype`` sets the K/V buffer dtype; the default honors
        the model dtype (``wte`` weight) instead of hardcoding f32 —
        a bf16 model gets a bf16 cache, halving decode HBM traffic
        (scores still accumulate in the query dtype).
        """
        import jax
        import jax.numpy as jnp
        from jax import lax

        prompt = jnp.asarray(prompt).astype(jnp.int32)
        bsz, t0 = prompt.shape
        total = t0 + max_new_tokens
        max_len = self._config["max_len"]
        if total > max_len:
            raise ValueError(
                f"prompt {t0} + {max_new_tokens} new tokens exceeds "
                f"max_len {max_len}")
        if temperature > 0.0 and rng is None:
            raise ValueError("temperature sampling needs an rng key")
        if max_new_tokens <= 0:
            return prompt
        n_head = self._config["n_head"]
        head_dim = self.dim // n_head
        c = self._children
        key = rng if rng is not None else jax.random.key(0)
        if cache_dtype is None:
            cache_dtype = params["wte"]["weight"].dtype
        cache_dtype = jnp.dtype(cache_dtype)

        def sample(logits, key):
            if temperature > 0.0:
                key, sub = jax.random.split(key)
                nxt = jax.random.categorical(
                    sub, logits / temperature, axis=-1)
            else:
                nxt = jnp.argmax(logits, axis=-1)
            return nxt.astype(jnp.int32), key

        # ---- prefill: one batched forward over the whole prompt ----
        x = jnp.take(params["wte"]["weight"], prompt, axis=0)
        x = x + params["wpe"]["weight"][:t0][None]
        caches = {}
        for i in range(self.n_layer):
            x, kh, vh = c[f"h{i}"].prefill(params[f"h{i}"], x)
            ck = jnp.zeros((bsz, n_head, total, head_dim), cache_dtype)
            cv = jnp.zeros((bsz, n_head, total, head_dim), cache_dtype)
            caches[f"h{i}"] = (
                lax.dynamic_update_slice(ck, kh.astype(cache_dtype),
                                         (0, 0, 0, 0)),
                lax.dynamic_update_slice(cv, vh.astype(cache_dtype),
                                         (0, 0, 0, 0)),
            )
        h, _ = c["ln_f"].apply(params["ln_f"], {}, x[:, -1:, :])
        logits, _ = c["head"].apply(params["head"], {}, h)
        first, key = sample(logits[:, 0, :], key)

        tokens = jnp.zeros((bsz, total), jnp.int32)
        tokens = lax.dynamic_update_slice(tokens, prompt, (0, 0))
        tokens = lax.dynamic_update_slice(tokens, first[:, None], (0, t0))

        # ---- decode: scan over the remaining new tokens ------------
        def step(carry, t):
            tokens, caches, key = carry
            cur = lax.dynamic_slice(tokens, (0, t), (bsz, 1))
            x = jnp.take(params["wte"]["weight"], cur, axis=0)
            x = x + lax.dynamic_slice(
                params["wpe"]["weight"], (t, 0), (1, self.dim))[None]
            new_caches = {}
            for i in range(self.n_layer):
                ck, cv = caches[f"h{i}"]
                x, ck, cv = c[f"h{i}"].decode_step(
                    params[f"h{i}"], x, ck, cv, t)
                new_caches[f"h{i}"] = (ck, cv)
            h, _ = c["ln_f"].apply(params["ln_f"], {}, x)
            logits, _ = c["head"].apply(params["head"], {}, h)
            nxt, key = sample(logits[:, 0, :], key)
            tokens = lax.dynamic_update_slice(
                tokens, nxt[:, None], (0, t + 1))
            return (tokens, new_caches, key), None

        if max_new_tokens > 1:
            (tokens, _, _), _ = lax.scan(
                step, (tokens, caches, key),
                jnp.arange(t0, total - 1))
        return tokens

    def __repr__(self):
        return (f"TransformerLM(vocab={self.vocab_size}, dim={self.dim}, "
                f"layers={self.n_layer})")


def build_transformer_lm(vocab_size: int, **kw) -> TransformerLM:
    return TransformerLM(vocab_size, **kw)
