"""Transformer language model — the long-context flagship.

No reference analogue: classic BigDL's sequence stack tops out at
Recurrent/LSTM BPTT windows (SURVEY.md §5 "long-context: absent").  This
model is the rebuild's new capability and the vehicle for the
sequence-parallel / ring-attention / tensor-parallel paths in
``bigdl_tpu.parallel``:

* token + learned positional embeddings,
* N pre-LN TransformerBlocks (Pallas flash attention on TPU),
* final LayerNorm + vocab projection.

Tokens are 0-based int32 (unlike LookupTable's 1-based parity
convention — this model has no reference API to mirror).
"""

from __future__ import annotations

import numpy as np

from bigdl_tpu.nn.attention import (
    LayerNorm,
    PositionalEmbedding,
    TransformerBlock,
    _Composite,
)
from bigdl_tpu.nn.layers import Linear, _to_device
from bigdl_tpu.nn.module import AbstractModule


class TokenEmbedding(AbstractModule):
    """0-based token embedding, N(0, 0.02) init (GPT convention)."""

    param_names = ("weight",)

    def __init__(self, vocab_size: int, dim: int):
        super().__init__()
        self._config = dict(vocab_size=vocab_size, dim=dim)
        self.vocab_size = vocab_size
        self.dim = dim
        self.reset()

    def reset(self):
        from bigdl_tpu.common import RandomGenerator

        self.weight = _to_device(
            RandomGenerator.RNG.normal(
                0.0, 0.02, size=(self.vocab_size, self.dim)
            ).astype(np.float32)
        )
        return self

    def update_output_pure(self, params, input, *, training=False, rng=None):
        import jax.numpy as jnp

        return jnp.take(params["weight"], input.astype(jnp.int32), axis=0)


class TransformerLM(_Composite):
    """Decoder-only causal LM over (batch, seq) int tokens -> logits
    (batch, seq, vocab)."""

    def __init__(self, vocab_size: int, dim: int = 256, n_head: int = 4,
                 n_layer: int = 4, max_len: int = 1024, mlp_ratio: int = 4,
                 dropout: float = 0.0, attn_impl: str = "auto",
                 remat: bool = False):
        super().__init__()
        self._config = dict(vocab_size=vocab_size, dim=dim, n_head=n_head,
                            n_layer=n_layer, max_len=max_len,
                            mlp_ratio=mlp_ratio, dropout=dropout,
                            attn_impl=attn_impl, remat=remat)
        self.vocab_size = vocab_size
        self.dim = dim
        self.n_layer = n_layer
        # remat=True: per-block gradient checkpointing — backward
        # recomputes each block's forward instead of storing its
        # activations, cutting peak HBM from O(n_layer * seq * dim)
        # activations to O(sqrt-ish) at ~1/3 extra FLOPs (the long-
        # context training lever; pairs with ring/ulysses seq-parallel)
        self.remat = remat
        self._add_child("wte", TokenEmbedding(vocab_size, dim))
        self._add_child("wpe", PositionalEmbedding(max_len, dim))
        for i in range(n_layer):
            self._add_child(f"h{i}", TransformerBlock(
                dim, n_head, mlp_ratio=mlp_ratio, causal=True,
                attn_impl=attn_impl, dropout=dropout))
        self._add_child("ln_f", LayerNorm(dim))
        self._add_child("head", Linear(dim, vocab_size, with_bias=False))

    def apply(self, params, state, input, *, training=False, rng=None):
        import jax

        c = self._children
        x, _ = c["wte"].apply(params["wte"], {}, input)
        x, _ = c["wpe"].apply(params["wpe"], {}, x)
        for i in range(self.n_layer):
            key = None
            if rng is not None:
                key = jax.random.fold_in(rng, i)
            block = c[f"h{i}"]
            if self.remat:
                def blk(p, xx, _b=block, _k=key):
                    out, _ = _b.apply(p, {}, xx, training=training, rng=_k)
                    return out
                x = jax.checkpoint(blk)(params[f"h{i}"], x)
            else:
                x, _ = block.apply(params[f"h{i}"], {}, x,
                                   training=training, rng=key)
        x, _ = c["ln_f"].apply(params["ln_f"], {}, x)
        logits, _ = c["head"].apply(params["head"], {}, x)
        return logits, state

    def __repr__(self):
        return (f"TransformerLM(vocab={self.vocab_size}, dim={self.dim}, "
                f"layers={self.n_layer})")


def build_transformer_lm(vocab_size: int, **kw) -> TransformerLM:
    return TransformerLM(vocab_size, **kw)
