"""bigdl_tpu.models — reference model zoo.

Rebuild of «bigdl»/models/ (SURVEY.md §2.1 "Reference models"): lenet,
resnet (CIFAR + ImageNet), inception, vgg, alexnet, rnn (PTB LM),
autoencoder — each with a builder and a runnable train entry point.
"""

from bigdl_tpu.models.lenet import build_lenet5
