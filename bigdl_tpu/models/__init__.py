"""bigdl_tpu.models — reference model zoo.

Rebuild of «bigdl»/models/ (SURVEY.md §2.1 "Reference models"): lenet,
resnet (CIFAR + ImageNet), inception, vgg, alexnet, rnn (PTB LM),
autoencoder — each with a builder and a runnable train entry point.
"""

from bigdl_tpu.models.lenet import build_lenet5
from bigdl_tpu.models.resnet import (
    build_resnet_cifar,
    build_resnet_imagenet,
    imagenet_recipe_optim,
)
from bigdl_tpu.models.vgg import build_vgg16, build_vgg19, build_vgg_cifar
from bigdl_tpu.models.alexnet import build_alexnet, build_alexnet_original
from bigdl_tpu.models.inception import build_inception_v1, build_inception_v2
from bigdl_tpu.models.ncf import build_ncf
from bigdl_tpu.models.autoencoder import build_autoencoder
from bigdl_tpu.models.rnn import build_ptb_lm
from bigdl_tpu.models.transformer import TransformerLM, build_transformer_lm
from bigdl_tpu.models.wide_and_deep import build_wide_and_deep, pack_batch

__all__ = [
    "build_lenet5", "build_resnet_cifar", "build_resnet_imagenet",
    "imagenet_recipe_optim", "build_vgg16", "build_vgg19", "build_vgg_cifar",
    "build_alexnet", "build_alexnet_original", "build_inception_v1",
    "build_inception_v2", "build_ncf", "build_wide_and_deep", "pack_batch",
    "build_autoencoder", "build_ptb_lm", "TransformerLM",
    "build_transformer_lm",
]
