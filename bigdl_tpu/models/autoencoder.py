"""MNIST autoencoder.

Rebuild of «bigdl»/models/autoencoder/Autoencoder.scala (+ Train.scala):
784 -> 32 -> 784 MLP trained with MSECriterion against the input.
"""

from __future__ import annotations

from bigdl_tpu.nn import Linear, ReLU, Reshape, Sequential, Sigmoid


def build_autoencoder(class_num: int = 32):
    model = Sequential()
    model.add(Reshape([28 * 28])) \
        .add(Linear(28 * 28, class_num)) \
        .add(ReLU()) \
        .add(Linear(class_num, 28 * 28)) \
        .add(Sigmoid())
    return model


def train_autoencoder(data_dir=None, batch_size=128, max_epoch=3,
                      learning_rate=0.01):
    """Reference: models/autoencoder/Train.scala — target == input/255."""
    from bigdl_tpu.dataset import ArrayDataSet
    from bigdl_tpu.dataset.mnist import load_mnist
    from bigdl_tpu.nn import MSECriterion
    from bigdl_tpu.optim import Adagrad, LocalOptimizer, Trigger

    x, _ = load_mnist(data_dir, "train")
    x = (x / 255.0).astype("float32")
    flat_target = x.reshape(x.shape[0], -1)
    model = build_autoencoder()
    opt = LocalOptimizer(model, (x, flat_target), MSECriterion(), batch_size)
    opt.set_optim_method(Adagrad(learningrate=learning_rate))
    opt.set_end_when(Trigger.max_epoch(max_epoch))
    return opt.optimize(), opt
