"""Shared pieces of the model Train.scala-style CLIs.

The reference gives every model family its own Train.scala +
Utils.scala (SURVEY.md §2.1 "Reference models"); the rebuild keeps one
``main`` per model module but routes the common ImageNet-folder
training flow through here so checkpoint/validation/ingestion wiring
can't diverge between families.
"""

from __future__ import annotations


def train_imagenet_folder(
    build_model,
    make_optim,
    data_dir: str,
    batch_size: int,
    max_epoch: int,
    image_size: int = 224,
    checkpoint: str = None,
):
    """Train ``build_model(class_num)`` on an ImageNet-style directory
    tree (``<dir>/train/<wnid>/*.JPEG``) under DistriOptimizer.

    ``make_optim(batch_size, n_epochs, iterations_per_epoch)`` supplies
    the family's recipe (warmup/multistep for resnet, Poly for
    inception).  A ``val`` split is attached when present; its absence
    is not an error (matching the reference mains' optional
    ``--valFolder``), but a bad ``data_dir`` raises from the train-split
    loader."""
    from bigdl_tpu.dataset.imagenet import ImageFolderDataSet
    from bigdl_tpu.nn import ClassNLLCriterion
    from bigdl_tpu.optim import (
        DistriOptimizer, Top1Accuracy, Top5Accuracy, Trigger,
    )

    train_ds = ImageFolderDataSet(
        data_dir, batch_size=batch_size, train=True, image_size=image_size)
    model = build_model(class_num=train_ds.class_num())
    iters = max(1, train_ds.size() // batch_size)
    opt = DistriOptimizer(model, train_ds, ClassNLLCriterion(),
                          batch_size=batch_size)
    opt.set_optim_method(make_optim(batch_size, max_epoch, iters))
    opt.set_end_when(Trigger.max_epoch(max_epoch))
    try:
        val_ds = ImageFolderDataSet(
            data_dir, batch_size=batch_size, train=False,
            image_size=image_size)
        opt.set_validation(Trigger.every_epoch(), val_ds,
                           [Top1Accuracy(), Top5Accuracy()])
    except FileNotFoundError:
        pass  # no val split
    if checkpoint:
        opt.set_checkpoint(checkpoint, Trigger.every_epoch())
    opt.optimize()
    return model
