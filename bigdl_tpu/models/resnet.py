"""ResNet — CIFAR-10 and ImageNet variants.

Rebuild of «bigdl»/models/resnet/ResNet.scala (+ Train.scala /
TrainImageNet.scala): basic blocks for CIFAR (depth = 6n+2), bottleneck
blocks for ImageNet (ResNet-50/101/152), shortcut type B (1x1 conv
projection when shape changes), MSRA init, and the ImageNet recipe's
"zero gamma on the last BN of each block" trick (optimnet parity:
iniBN=true in the reference recipe).

Structure mirrors the reference: Sequential with ConcatTable(main,
shortcut) + CAddTable + ReLU per block — which XLA fuses into the same
HLO a hand-written residual add would give.
"""

from __future__ import annotations

import numpy as np

from bigdl_tpu.nn import (
    CAddTable,
    ConcatTable,
    Identity,
    Linear,
    LogSoftMax,
    ReLU,
    Reshape,
    Sequential,
    SpatialAveragePooling,
    SpatialBatchNormalization,
    SpatialConvolution,
    SpatialMaxPooling,
)
from bigdl_tpu.nn.layers import MsraFiller, Zeros


def _conv(n_in, n_out, k, stride=1, pad=None):
    if pad is None:
        pad = (k - 1) // 2
    return SpatialConvolution(
        n_in, n_out, k, k, stride, stride, pad, pad, with_bias=False,
        init_method=MsraFiller(False),
    )


def _bn(n, zero_init=False):
    bn = SpatialBatchNormalization(n)
    if zero_init:
        import jax.numpy as jnp

        bn.weight = jnp.zeros_like(bn.weight)
    return bn


def _shortcut(n_in, n_out, stride):
    """Shortcut type B («bigdl» ResNet.scala shortcut): identity when
    shapes agree, else 1x1 strided conv + BN."""
    if n_in == n_out and stride == 1:
        return Identity()
    return Sequential().add(_conv(n_in, n_out, 1, stride, 0)).add(_bn(n_out))


def basic_block(n_in, n_out, stride=1, zero_init_residual=True):
    main = Sequential() \
        .add(_conv(n_in, n_out, 3, stride)).add(_bn(n_out)).add(ReLU()) \
        .add(_conv(n_out, n_out, 3, 1)).add(_bn(n_out, zero_init_residual))
    return Sequential() \
        .add(ConcatTable().add(main).add(_shortcut(n_in, n_out, stride))) \
        .add(CAddTable()).add(ReLU())


def bottleneck(n_in, n_mid, stride=1, zero_init_residual=True, expansion=4):
    n_out = n_mid * expansion
    main = Sequential() \
        .add(_conv(n_in, n_mid, 1, 1, 0)).add(_bn(n_mid)).add(ReLU()) \
        .add(_conv(n_mid, n_mid, 3, stride)).add(_bn(n_mid)).add(ReLU()) \
        .add(_conv(n_mid, n_out, 1, 1, 0)).add(_bn(n_out, zero_init_residual))
    return Sequential() \
        .add(ConcatTable().add(main).add(_shortcut(n_in, n_out, stride))) \
        .add(CAddTable()).add(ReLU())


def build_resnet_cifar(depth: int = 20, class_num: int = 10):
    """CIFAR-10 ResNet (reference: ResNet(depth) with basic blocks,
    depth = 6n+2: 20/32/44/56/110)."""
    assert (depth - 2) % 6 == 0, "CIFAR depth must be 6n+2"
    n = (depth - 2) // 6
    model = Sequential()
    model.add(_conv(3, 16, 3, 1)).add(_bn(16)).add(ReLU())
    n_in = 16
    for stage, (width, stride) in enumerate([(16, 1), (32, 2), (64, 2)]):
        for i in range(n):
            model.add(basic_block(n_in, width, stride if i == 0 else 1))
            n_in = width
    model.add(SpatialAveragePooling(8, 8, 1, 1)) \
        .add(Reshape([64])) \
        .add(Linear(64, class_num)) \
        .add(LogSoftMax())
    return model


_IMAGENET_CFG = {
    50: (bottleneck, [3, 4, 6, 3]),
    101: (bottleneck, [3, 4, 23, 3]),
    152: (bottleneck, [3, 8, 36, 3]),
    18: (basic_block, [2, 2, 2, 2]),
    34: (basic_block, [3, 4, 6, 3]),
}


def build_resnet_imagenet(depth: int = 50, class_num: int = 1000):
    """ImageNet ResNet (reference: TrainImageNet recipe, shortcut B,
    bottleneck expansion 4)."""
    block, counts = _IMAGENET_CFG[depth]
    expansion = 4 if block is bottleneck else 1
    model = Sequential()
    model.add(_conv(3, 64, 7, 2, 3)).add(_bn(64)).add(ReLU()) \
        .add(SpatialMaxPooling(3, 3, 2, 2, 1, 1))
    n_in = 64
    for stage, (width, stride) in enumerate([(64, 1), (128, 2), (256, 2),
                                             (512, 2)]):
        for i in range(counts[stage]):
            if block is bottleneck:
                model.add(bottleneck(n_in, width, stride if i == 0 else 1))
                n_in = width * expansion
            else:
                model.add(basic_block(n_in, width, stride if i == 0 else 1))
                n_in = width
    model.add(SpatialAveragePooling(7, 7, 1, 1, global_pooling=True)) \
        .add(Reshape([n_in])) \
        .add(Linear(n_in, class_num)) \
        .add(LogSoftMax())
    return model


def imagenet_recipe_optim(batch_size: int, n_epochs: int = 90,
                          iterations_per_epoch: int = 5004,
                          base_lr: float = None, warmup_epochs: int = 5):
    """The reference ImageNet recipe («bigdl» TrainImageNet.scala):
    linear-scaled LR with gradual warmup then multistep decay at epochs
    30/60/80 — expressed as a SequentialSchedule over iterations."""
    from bigdl_tpu.optim import SGD, SequentialSchedule, Warmup, MultiStep

    if base_lr is None:
        base_lr = 0.1 * batch_size / 256.0
    warm_iters = warmup_epochs * iterations_per_epoch
    sched = SequentialSchedule(iterations_per_epoch)
    if warm_iters > 0:
        delta = (base_lr - 0.1) / max(1, warm_iters)
        sched.add(Warmup(delta), warm_iters)
    sched.add(
        # milestones are absolute epochs; SequentialSchedule offsets its
        # successor's neval by the warmup length, so subtract it here
        MultiStep(
            [e * iterations_per_epoch - warm_iters for e in (30, 60, 80)], 0.1
        ),
        n_epochs * iterations_per_epoch,
    )
    return SGD(learningrate=0.1 if warm_iters > 0 else base_lr,
               momentum=0.9, dampening=0.0, nesterov=True,
               weightdecay=1e-4, learningrate_schedule=sched)


def main(argv=None):
    """Console entry (reference: models/resnet TrainCIFAR10/TrainImageNet
    Train.scala CLI).

    With ``-f/--data-dir`` pointing at an ImageNet-style tree
    (``<dir>/train/<wnid>/*.JPEG``) this is the TrainImageNet path:
    ResNet-50 + the reference warmup/multistep recipe, file-backed
    distributed ingestion (dataset/imagenet.py) under DistriOptimizer.
    Without a data dir, the CIFAR variant trains on a synthetic task
    (examples/ has the full CIFAR pipeline)."""
    import argparse
    import logging

    import numpy as np

    from bigdl_tpu.nn import ClassNLLCriterion
    from bigdl_tpu.optim import Optimizer, SGD, Top1Accuracy, Trigger

    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser()
    ap.add_argument("-f", "--data-dir", default=None,
                    help="ImageNet-style dir (train/<cls>/*.jpg); "
                         "absent = synthetic CIFAR task")
    ap.add_argument("--depth", type=int, default=20)
    ap.add_argument("-b", "--batch-size", type=int, default=128)
    ap.add_argument("-e", "--max-epoch", type=int, default=1)
    ap.add_argument("--learning-rate", type=float, default=None,
                    help="base LR (ImageNet default: linear-scaled "
                         "0.1*batch/256; CIFAR default: 0.1)")
    ap.add_argument("-n", "--num-samples", type=int, default=1024)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--checkpoint", default=None)
    ap.add_argument("--distributed", action="store_true")
    args = ap.parse_args(argv)

    if args.data_dir:
        # ----- TrainImageNet path: real files, distributed ingestion ----
        from bigdl_tpu.models.train_util import train_imagenet_folder

        depth = args.depth if args.depth in _IMAGENET_CFG else 50
        train_imagenet_folder(
            lambda class_num: build_resnet_imagenet(
                depth=depth, class_num=class_num),
            lambda bs, ep, it: imagenet_recipe_optim(
                bs, n_epochs=ep, iterations_per_epoch=it,
                base_lr=args.learning_rate),
            args.data_dir, args.batch_size, args.max_epoch,
            image_size=args.image_size, checkpoint=args.checkpoint)
        return

    model = build_resnet_cifar(depth=args.depth)
    rs = np.random.RandomState(0)
    x = rs.rand(args.num_samples, 3, 32, 32).astype(np.float32)
    y = (rs.randint(0, 10, args.num_samples) + 1).astype(np.float32)
    opt = Optimizer(model, (x, y), ClassNLLCriterion(),
                    batch_size=args.batch_size,
                    distributed=args.distributed or None)
    opt.set_optim_method(SGD(learningrate=args.learning_rate or 0.1,
                             momentum=0.9))
    opt.set_end_when(Trigger.max_epoch(args.max_epoch))
    opt.set_validation(Trigger.every_epoch(), (x, y), [Top1Accuracy()])
    opt.optimize()


if __name__ == "__main__":
    main()
