"""Int8 quantized matmul — the bigquant replacement.

The reference's quantized inference path rides a native int8 gemm
(`com.intel.analytics.bigdl.bigquant.BigQuant`, SURVEY.md §2.3) with
per-output-channel scales.  The TPU-native equivalent is
``lax.dot_general`` on int8 operands with
``preferred_element_type=jnp.int32`` — the MXU multiplies int8 natively
at 2x+ the bf16 rate — followed by a per-channel rescale that XLA fuses
into the epilogue.
"""

from __future__ import annotations


def quantize_per_channel(w, axis: int = 0):
    """Symmetric per-channel int8 quantization of a float weight.

    Returns (w_int8, scale) with ``w ≈ w_int8 * scale`` broadcast along
    ``axis`` — the reference bigquant convention (per output channel).
    """
    import jax.numpy as jnp

    reduce_axes = tuple(i for i in range(w.ndim) if i != axis)
    absmax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, scale


def int8_matmul(x, w_q, w_scale, x_scale=None, impl=None):
    """y = x @ w_q.T * scales.

    x: float (..., K) activations — dynamically quantized per-row unless
    ``x_scale`` is given with an already-int8 ``x``.
    w_q: int8 (N, K); w_scale: (N, 1) float.

    impl: None = the int8 ``dot_general`` path (the static policy);
    "auto" consults the cached ``int8_mm`` auto-tuner site when
    ``BIGDL_TUNER=1`` (ops/autotune.py — static path wins by default,
    a measured probe can flip to "dequant"); "dequant" rescales the
    int8 weight back to float and runs a plain matmul — fewer ops on
    backends whose int8 gemm is slow, same per-channel quantization
    error (the weight was already rounded to int8).
    """
    import jax.numpy as jnp
    from jax import lax

    if impl in (None, "auto"):
        chosen = "int8"
        if impl == "auto":
            from bigdl_tpu.ops import autotune

            if autotune.enabled():
                rec = autotune.decide_int8_mm(
                    x.shape, w_q.shape, x.dtype,
                    arrays=(x, w_q, w_scale))
                if rec is not None:
                    chosen = rec.get("impl", "int8")
        impl = chosen
    if impl == "dequant":
        w = w_q.astype(jnp.float32) * w_scale          # (N, K)
        xf = (x.astype(jnp.float32) * x_scale
              if x_scale is not None else x)
        return jnp.matmul(xf, w.T)
    if impl != "int8":
        raise ValueError(f"impl must be auto|int8|dequant, got {impl!r}")
    if x_scale is None:
        # dynamic per-row symmetric activation quantization
        absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
        x_scale = jnp.maximum(absmax, 1e-8) / 127.0
        x_q = jnp.clip(jnp.round(x / x_scale), -127, 127).astype(jnp.int8)
    else:
        x_q = x
    acc = lax.dot_general(
        x_q, w_q,
        dimension_numbers=(((x_q.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return acc.astype(jnp.float32) * x_scale * w_scale.reshape(-1)
