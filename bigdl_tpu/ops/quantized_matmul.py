"""Int8 quantized matmul — the bigquant replacement.

The reference's quantized inference path rides a native int8 gemm
(`com.intel.analytics.bigdl.bigquant.BigQuant`, SURVEY.md §2.3) with
per-output-channel scales.  The TPU-native equivalent is
``lax.dot_general`` on int8 operands with
``preferred_element_type=jnp.int32`` — the MXU multiplies int8 natively
at 2x+ the bf16 rate — followed by a per-channel rescale that XLA fuses
into the epilogue.
"""

from __future__ import annotations


def quantize_per_channel(w, axis: int = 0):
    """Symmetric per-channel int8 quantization of a float weight.

    Returns (w_int8, scale) with ``w ≈ w_int8 * scale`` broadcast along
    ``axis`` — the reference bigquant convention (per output channel).
    """
    import jax.numpy as jnp

    reduce_axes = tuple(i for i in range(w.ndim) if i != axis)
    absmax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, scale


def int8_matmul(x, w_q, w_scale, x_scale=None):
    """y = x @ w_q.T * scales.

    x: float (..., K) activations — dynamically quantized per-row unless
    ``x_scale`` is given with an already-int8 ``x``.
    w_q: int8 (N, K); w_scale: (N, 1) float.
    """
    import jax.numpy as jnp
    from jax import lax

    if x_scale is None:
        # dynamic per-row symmetric activation quantization
        absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
        x_scale = jnp.maximum(absmax, 1e-8) / 127.0
        x_q = jnp.clip(jnp.round(x / x_scale), -127, 127).astype(jnp.int8)
    else:
        x_q = x
    acc = lax.dot_general(
        x_q, w_q,
        dimension_numbers=(((x_q.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return acc.astype(jnp.float32) * x_scale * w_scale.reshape(-1)
