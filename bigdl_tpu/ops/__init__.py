"""bigdl_tpu.ops — TPU kernels (Pallas) + lax reference implementations.

This is the rebuild's "native layer".  The reference BigDL ships
hand-written native kernels (MKL/MKL-DNN `.so` loaded via JNI,
SURVEY.md §2.3); on TPU the equivalent of that layer is XLA itself plus
hand-written Pallas kernels for the few hot ops where manual tiling or
fusion beats the compiler (attention, quantized matmul).

Every op here has (a) a pure jax/lax reference implementation that runs
anywhere, and (b) optionally a Pallas TPU kernel selected automatically
on TPU backends.  Numerics of (a) and (b) are locked together by tests
(tests/test_ops.py) — the same role the reference's Torch7 oracle specs
play for its native kernels (SURVEY.md §4.3).
"""

from bigdl_tpu.ops import autotune
from bigdl_tpu.ops.attention import dot_product_attention, flash_attention
from bigdl_tpu.ops.decode_attention import paged_decode_attention
from bigdl_tpu.ops.quantized_matmul import int8_matmul, quantize_per_channel

__all__ = [
    "autotune",
    "dot_product_attention",
    "flash_attention",
    "int8_matmul",
    "paged_decode_attention",
    "quantize_per_channel",
]
