"""Flash-decode over the paged KV cache — the serving hot path's kernel.

PR 12's continuous-batching decode step ran its attention the naive
way: ``gather_pages`` materialized a dense ``(B, H, max_pages*P, Dh)``
K/V copy **per layer per step** (page gather + transpose + reshape),
then full-width einsum attention masked the mostly-unallocated tail
with ``-inf`` — pure wasted HBM bandwidth in a regime that is entirely
memory-bound (one query token against a long scattered KV).  This
module is the flash-decoding answer (the decode-side sibling of
ops/attention.py's flash kernel):

* ``impl="dense"`` — the PR 12 math, verbatim: gather + masked softmax
  einsum.  It is the **static baseline** the auto-tuner can never lose
  to, and the path that preserves the temperature-0 bit-match-vs-
  ``generate()`` contract;
* ``impl="fused"`` — split-KV online softmax in plain lax: K/V are
  read **page-block by page-block through the page table** (a chunk of
  ``block_pages`` pages per iteration), each block's scores are
  softmax-accumulated into a carried ``(m, l, acc)`` running state,
  and one final rescale produces the output — the gathered dense copy
  (and its transpose materialization) never exists.  Runs everywhere
  XLA runs, including inside the TP ``shard_map`` body on the
  head-sharded cache;
* ``impl="pallas"`` — the true flash-decode TPU kernel: grid
  ``(B, H, pages)`` with the page table and lengths as **scalar
  prefetch** so each program's BlockSpec index map DMAs exactly the
  page the table names (trash-page contract below), ``(m, l, acc)``
  carried in VMEM scratch across the page grid dimension, output
  written on the final page.  Compiled Mosaic exists only on TPU;
  other backends run the interpreter (tests) or pick an XLA impl.

Mask contract (identical across impls, pinned by tests): position
``pos <= length`` attends, everything else is ``-inf`` before the
softmax — so page 0 (the reserved trash page unallocated table entries
point at) can hold arbitrary finite garbage and never contributes a
bit to any output.

Dispatch: ``impl="auto"`` follows :func:`static_decode_dispatch`
(always "dense" — the measured PR 12 baseline) unless the auto-tuner
is enabled (``BIGDL_TUNER=1``), in which case the cached
``decode_attn`` site search (ops/autotune.py) picks impl and
``block_pages`` per ``(B, H, Dh, P, pages, dtype, platform)`` — with
the dense path as the never-lose static policy.

The used-page prefix bucket (:func:`used_page_bucket`) is the other
half of the win and benefits **every** impl including dense: the
engine slices each step's page tables to the pow2 bucket covering
``max(lengths)//P + 1`` pages, so even the static baseline stops
paying for the empty pool.
"""

from __future__ import annotations

import functools
from typing import Optional


def used_page_bucket(max_length: int, page_size: int,
                     max_pages: int) -> int:
    """Host-side pow2 page bucket for one decode step: the smallest
    power of two >= the pages needed to cover position ``max_length``
    (the batch's longest slot writes its next token there, so
    ``max_length // P + 1`` pages are live), clamped to the table
    width.  Pow2 buckets keep the number of compiled step variants
    logarithmic."""
    page_size = max(1, int(page_size))
    need = max(1, int(max_length) // page_size + 1)
    b = 1
    while b < need:
        b *= 2
    return min(b, max(1, int(max_pages)))


def decode_hbm_bytes(impl: str, b: int, h: int, d: int, page_size: int,
                     maxp: int, kv_itemsize: int = 4) -> float:
    """Analytic HBM traffic of ONE layer's decode attention (the
    auto-tuner's Pallas/fused costing model, and the engine's
    bytes-per-token gauge).  All impls read the ``2 * B * maxp`` K/V
    pages the tables name; the dense path additionally writes and
    re-reads the materialized contiguous copy (the gather tax), plus
    the f32 score plane's round trip."""
    k = maxp * page_size
    pages = 2.0 * b * maxp * page_size * h * d * kv_itemsize  # K + V
    qio = 2.0 * b * h * d * 4                                 # q + out
    if impl == "dense":
        return pages * 3 + 2.0 * b * h * k * 4 + qio
    return pages + qio


def _mask_neg_inf(scores, pos, lengths):
    """``pos <= length`` attends; everything else -inf (the trash-page
    contract — one definition shared by dense and fused)."""
    import jax.numpy as jnp

    return jnp.where(pos <= lengths, scores, -jnp.inf)


# --------------------------------------------------------------------------
# dense — the PR 12 math, verbatim (static baseline / bit-match path)
# --------------------------------------------------------------------------


def _dense(q, kp, vp, tables, lengths, *, scale: float):
    """Gather + masked softmax einsum — exactly the op sequence the
    PR 12 ``paged_decode_math`` inlined, so the temperature-0 bit-match
    contract vs ``generate()`` is preserved byte for byte."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.serving.cache import gather_pages

    qh = q[:, :, None, :]                     # (B, H, 1, Dh)
    kall = gather_pages(kp, tables)           # (B, H, maxp*P, Dh)
    vall = gather_pages(vp, tables)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kall) * scale
    mask = (jnp.arange(kall.shape[2])[None, None, None, :]
            <= lengths[:, None, None, None])
    scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", probs, vall)
    return o[:, :, 0, :]


# --------------------------------------------------------------------------
# fused — split-KV online softmax over page blocks (XLA, runs anywhere)
# --------------------------------------------------------------------------


def _chunk_pages(maxp: int, block_pages: int) -> int:
    """Largest valid page-block size <= the request that divides the
    table width (0 / oversize requests collapse to the full width —
    one block, no loop)."""
    maxp = int(maxp)
    bp = int(block_pages)
    if bp <= 0 or bp >= maxp:
        return maxp
    while bp > 1 and maxp % bp:
        bp -= 1
    return bp


def _fused(q, kp, vp, tables, lengths, *, page_size: int, scale: float,
           block_pages: int = 0):
    """Online-softmax paged decode: page blocks are gathered one chunk
    at a time through the table (``(B, bp, H, P, Dh)`` — page layout,
    never the transposed contiguous copy), each chunk's masked scores
    fold into the carried ``(m, l, acc)``, one final rescale.  f32
    accumulation throughout."""
    import jax.numpy as jnp
    from jax import lax

    b, maxp = tables.shape
    h, d = q.shape[1], q.shape[2]
    p = int(page_size)
    bp = _chunk_pages(maxp, block_pages)
    n_chunks = maxp // bp
    qf = q.astype(jnp.float32) * scale        # (B, H, Dh)
    len_b = lengths[:, None, None, None]      # (B, 1, 1, 1)

    def block(tbl_c, c0, m, l, acc):
        """Fold pages [c0, c0+bp) (table slice ``tbl_c``) into the
        running state.  ``c0`` may be traced (fori path)."""
        kc = kp[tbl_c].astype(jnp.float32)    # (B, bp, H, P, Dh)
        vc = vp[tbl_c].astype(jnp.float32)
        s = jnp.einsum("bhd,bmhpd->bhmp", qf, kc)     # (B, H, bp, P)
        pos = ((c0 + jnp.arange(bp)) * p)[None, None, :, None] \
            + jnp.arange(p)[None, None, None, :]
        s = _mask_neg_inf(s, pos, len_b)
        s = s.reshape(b, h, bp * p)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # fully-masked-so-far rows keep m=-inf; shift 0 avoids NaN
        shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        pr = jnp.exp(s - shift[..., None])
        alpha = jnp.exp(jnp.where(jnp.isfinite(m), m - shift, -jnp.inf))
        l_new = l * alpha + jnp.sum(pr, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhmp,bmhpd->bhd", pr.reshape(b, h, bp, p), vc)
        return m_new, l_new, acc_new

    init = (jnp.full((b, h), -jnp.inf, jnp.float32),
            jnp.zeros((b, h), jnp.float32),
            jnp.zeros((b, h, d), jnp.float32))
    if n_chunks == 1:
        m, l, acc = block(tables, 0, *init)
    elif n_chunks <= 4:
        m, l, acc = init
        for c in range(n_chunks):
            m, l, acc = block(tables[:, c * bp:(c + 1) * bp],
                              c * bp, m, l, acc)
    else:
        def body(c, carry):
            tbl_c = lax.dynamic_slice_in_dim(tables, c * bp, bp, axis=1)
            return block(tbl_c, c * bp, *carry)

        m, l, acc = lax.fori_loop(0, n_chunks, body, init)
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


# --------------------------------------------------------------------------
# pallas — the TPU flash-decode kernel (scalar-prefetched page table)
# --------------------------------------------------------------------------


def _decode_kernel(tbl_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, page_size: int,
                   scale: float):
    """One (slot, head, page) program.  The BlockSpec index maps below
    already resolved this program's K/V block to the page the table
    names (scalar prefetch), so the kernel only sees a (P, Dh) tile;
    (m, l, acc) carry in VMEM scratch across the page grid dimension
    (fastest-varying, sequential on TPU)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    d = q_ref.shape[2]
    j = pl.program_id(2)
    ns = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full((1, 1), -jnp.inf, jnp.float32)
        l_scr[...] = jnp.zeros((1, 1), jnp.float32)
        acc_scr[...] = jnp.zeros((1, d), jnp.float32)

    q = q_ref[0].astype(jnp.float32) * scale           # (1, Dh)
    ks = k_ref[0, 0].astype(jnp.float32)               # (P, Dh)
    vs = v_ref[0, 0].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, ks, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)            # (1, P)
    pos = j * page_size + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    length = len_ref[pl.program_id(0)]
    s = jnp.where(pos <= length, s, -jnp.inf)

    m = m_scr[0, 0]
    m_new = jnp.maximum(m, jnp.max(s))
    shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - shift)
    alpha = jnp.exp(jnp.where(jnp.isfinite(m), m - shift, -jnp.inf))
    l_new = l_scr[0, 0] * alpha + jnp.sum(p)
    acc_new = acc_scr[...] * alpha + jax.lax.dot_general(
        p, vs, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)            # (1, Dh)
    m_scr[0, 0] = m_new
    l_scr[0, 0] = l_new
    acc_scr[...] = acc_new

    @pl.when(j == ns - 1)
    def _finalize():
        out = acc_scr[...] / jnp.maximum(l_scr[0, 0], 1e-30)
        o_ref[0] = out.astype(o_ref.dtype)


def _pallas(q, kp, vp, tables, lengths, *, page_size: int, scale: float,
            interpret: bool = False):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, d = q.shape
    maxp = tables.shape[1]
    p = int(page_size)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                 # tables, lengths
        grid=(b, h, maxp),
        in_specs=[
            pl.BlockSpec((1, 1, d), lambda i, hh, j, tbl, lens:
                         (i, hh, 0)),
            pl.BlockSpec((1, 1, p, d), lambda i, hh, j, tbl, lens:
                         (tbl[i, j], hh, 0, 0)),
            pl.BlockSpec((1, 1, p, d), lambda i, hh, j, tbl, lens:
                         (tbl[i, j], hh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, d), lambda i, hh, j, tbl, lens:
                               (i, hh, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, d), jnp.float32),
        ],
    )
    kernel = functools.partial(_decode_kernel, page_size=p, scale=scale)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=interpret,
    )(tables.astype(jnp.int32), lengths.astype(jnp.int32), q, kp, vp)


# --------------------------------------------------------------------------
# public dispatcher
# --------------------------------------------------------------------------


def static_decode_dispatch() -> tuple:
    """The hand-measured ``impl="auto"`` policy: the dense gather path
    — the PR 12 baseline and the auto-tuner's never-lose static
    choice.  (The fused/pallas paths must EARN dispatch through the
    tuner's cost model or a measured probe.)"""
    return "dense", 0


def paged_decode_attention(q, kp, vp, tables, lengths, *,
                           page_size: int, scale: Optional[float] = None,
                           impl: str = "auto", block_pages: int = 0,
                           interpret: bool = False):
    """One decode-attention step over the paged KV cache.

    q: ``(B, H, Dh)`` — one query token per slot.
    kp/vp: ``(num_pages, H, P, Dh)`` — one layer's page pool.
    tables: ``(B, maxp)`` int32 page table (maxp may be the engine's
    used-page bucket, not the full table width); lengths: ``(B,)``
    int32 — position ``pos <= length`` attends.

    impl: "auto" (static dense policy, overridden per shape by the
    cached ``decode_attn`` auto-tuner site when ``BIGDL_TUNER=1``),
    "dense", "fused", "pallas", or "pallas_interpret" (testing).
    ``block_pages`` sets the fused path's page-block chunk (0 = whole
    width, one block).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if impl == "auto":
        impl, block_pages = static_decode_dispatch()
        from bigdl_tpu.ops import autotune

        if autotune.enabled():
            rec = autotune.decide_decode_attn(
                q.shape, int(page_size), int(tables.shape[1]), q.dtype,
                kv_dtype=kp.dtype,
                arrays=(q, kp, vp, tables, lengths))
            if rec is not None:
                impl = rec.get("impl", impl)
                block_pages = int(rec.get("block_pages") or 0)
    if impl in ("pallas", "pallas_interpret"):
        import jax

        interpret = (interpret or impl == "pallas_interpret"
                     or jax.default_backend() != "tpu")
        return _pallas(q, kp, vp, tables, lengths, page_size=page_size,
                       scale=scale, interpret=interpret)
    if impl == "fused":
        return _fused(q, kp, vp, tables, lengths, page_size=page_size,
                      scale=scale, block_pages=block_pages)
    if impl != "dense":
        raise ValueError(
            f"impl must be auto|dense|fused|pallas, got {impl!r}")
    return _dense(q, kp, vp, tables, lengths, scale=scale)


__all__ = ["paged_decode_attention", "static_decode_dispatch",
           "used_page_bucket", "decode_hbm_bytes"]
