"""Scaled-dot-product attention: lax reference + Pallas flash kernel.

The reference framework predates attention entirely (SURVEY.md §5
"long-context: absent") — this op is a *new* capability, the hot inner
op of the Transformer/long-context stack (nn/attention.py,
parallel/ring_attention.py).

Design for the MXU/VMEM (pallas_guide.md):

* the Pallas kernel is a classic flash attention: grid over
  (batch*heads, query blocks), ``lax.fori_loop`` over key blocks, online
  softmax with running max ``m`` and normalizer ``l`` kept in VMEM
  scratch so the (T, T) score matrix never materialises in HBM;
* block sizes are multiples of the fp32 (8, 128) tile, MXU-sized 128
  where the sequence allows;
* matmuls carry ``preferred_element_type=jnp.float32`` so bf16 inputs
  accumulate in fp32 on the MXU.

``dot_product_attention`` is the public entry.  ``impl="auto"`` is
measurement-driven (see the dispatcher): the lax reference wins
throughput on the 2026-07 toolchain at every length whose softmax
residuals fit, so auto takes lax below T=4096 and the Pallas kernel in
the long-context regime, where flash's O(T) residuals — (q, k, v,
out, logsumexp) instead of per-layer (B, H, T, T) — are the
difference between fitting and OOM.  Both paths are differentiable —
the Pallas path via ``jax.custom_vjp`` with blockwise backward
kernels that never materialize a (T, T) array in either direction.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax


# --------------------------------------------------------------------------
# lax reference implementation
# --------------------------------------------------------------------------


def _reference_attention(q, k, v, *, causal: bool, scale: float,
                         mask=None, seq_offset: int = 0):
    """Plain softmax(q k^T) v.  (B, H, Tq, D) x (B, H, Tk, D).

    ``seq_offset`` shifts query positions for causal masking — used by
    ring attention where the local query block starts at a nonzero
    absolute position.
    """
    import jax.numpy as jnp

    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        qpos = jnp.arange(tq)[:, None] + seq_offset
        kpos = jnp.arange(tk)[None, :]
        scores = jnp.where(qpos >= kpos, scores, -jnp.inf)
    if mask is not None:
        scores = jnp.where(mask, scores, -jnp.inf)
    # guard fully-masked rows (ring attention partial blocks): softmax of
    # all -inf must give zeros, not NaN
    row_max = jnp.max(scores, axis=-1, keepdims=True)
    row_max = jnp.where(jnp.isfinite(row_max), row_max, 0.0)
    unnorm = jnp.exp(scores - row_max)
    denom = jnp.sum(unnorm, axis=-1, keepdims=True)
    probs = unnorm / jnp.maximum(denom, 1e-30)
    out = jnp.einsum(
        "bhqk,bhkd->bhqd", probs, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


# --------------------------------------------------------------------------
# Pallas flash attention (TPU)
# --------------------------------------------------------------------------


def _mask_causal(s, qi, block_q, ki, block_k):
    """-inf the future positions of a (block_q, block_k) score tile at
    block coordinates (qi, ki).  Single definition shared by the
    forward and both backward kernels so the mask convention can never
    desynchronize between them."""
    import jax.numpy as jnp
    from jax import lax

    qpos = qi * block_q + lax.broadcasted_iota(jnp.int32, s.shape, 0)
    kpos = ki * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(qpos >= kpos, s, -jnp.inf)


def _diag_kblocks(qi, block_q, block_k):
    """Number of key blocks a causal q-block touches (through its
    diagonal), shared by the forward and dq kernels."""
    from jax import lax

    return lax.div((qi + 1) * block_q + block_k - 1, block_k)


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                      block_k: int, scale: float, causal: bool,
                      seq_len: int):
    """One (batch*head, q-block) program: stream key blocks, online
    softmax.  Refs are VMEM blocks: q (1, block_q, d), k/v (1, T, d).
    Also writes the per-row logsumexp (in scaled-score units) so the
    blockwise backward can reconstruct P = exp(s - lse) without a
    second softmax pass."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # (block_q, d)

    m0 = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    def body(ki, carry):
        m, l, acc = carry
        ks = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        vs = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, ks, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, block_k)
        if causal:
            s = _mask_causal(s, qi, block_q, ki, block_k)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # fully-masked rows keep m=-inf; use 0 shift there to avoid NaNs
        shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - shift[:, None])
        alpha = jnp.exp(jnp.where(jnp.isfinite(m), m - shift, -jnp.inf))
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, vs, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    if causal:
        # process key blocks up to and including the diagonal
        nk = _diag_kblocks(qi, block_q, block_k)
        m, l, acc = lax.fori_loop(0, nk, body, (m0, l0, acc0))
    else:
        m, l, acc = lax.fori_loop(0, seq_len // block_k, body, (m0, l0, acc0))

    out = acc / jnp.maximum(l, 1e-30)[:, None]
    o_ref[0] = out.astype(o_ref.dtype)
    # lse rides as (1, T//block_q, block_q): Mosaic's block rule wants
    # the last two dims (8, 128)-divisible-or-full, which a (1, block_q)
    # row block violates.  The full plane is mapped for every j and
    # revisited (same block index), so each program writes only its row
    # and the block flushes once per batch*head.
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    lse_ref[0, pl.ds(qi, 1), :] = lse[None, :]


def _pick_block(t: int, preferred: int = 128) -> int:
    for b in (preferred, 64, 32, 16, 8):
        if t % b == 0:
            return b
    return 0


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "interpret")
)
def flash_attention(q, k, v, *, causal: bool = False,
                    scale: Optional[float] = None, interpret: bool = False):
    """Pallas flash attention.  q/k/v: (B, H, T, D) with T a multiple of
    8 and D a multiple of... anything (padded to 128 lanes by Mosaic).

    Differentiable with a true blockwise backward: the forward saves
    (q, k, v, out, logsumexp) — O(T) extra — and the backward kernels
    (_flash_bwd_dq_kernel / _flash_bwd_dkv_kernel) rebuild the score
    tiles from the logsumexp, so no (T, T) array is ever materialized,
    as residual OR transient, in either direction.
    """
    return _flash_attention_vjp(q, k, v, causal,
                                scale if scale is not None else q.shape[-1] ** -0.5,
                                interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_attention_vjp(q, k, v, causal, scale, interpret):
    return _flash_forward(q, k, v, causal, scale, interpret)


def _flash_forward(q, k, v, causal, scale, interpret, *,
                   with_lse: bool = False):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    b, h, t, d = q.shape
    block_q = _pick_block(t)
    block_k = _pick_block(t)
    if not block_q:
        out = _reference_attention(q, k, v, causal=causal, scale=scale)
        return (out, None) if with_lse else out

    kernel = functools.partial(
        _flash_fwd_kernel, block_k=block_k, scale=scale, causal=causal,
        seq_len=t,
    )
    qr = q.reshape(b * h, t, d)
    kr = k.reshape(b * h, t, d)
    vr = v.reshape(b * h, t, d)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, t // block_q, block_q),
                         lambda i, j: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, t // block_q, block_q),
                                 jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr)
    out = out.reshape(b, h, t, d)
    return (out, lse) if with_lse else out


# ---- blockwise backward (the true flash backward: no T^2 residuals,
# no T^2 transients — scores are rebuilt tile by tile from the saved
# logsumexp) ----


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                         dq_ref, *, block_k: int, scale: float,
                         causal: bool, seq_len: int):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    qi = pl.program_id(1)
    qs = q_ref[0].astype(jnp.float32) * scale      # (bq, d)
    do = g_ref[0].astype(jnp.float32)              # (bq, d)
    lse = lse_ref[0, pl.ds(qi, 1), :][0]           # (bq,)
    dlt = delta_ref[0, pl.ds(qi, 1), :][0]         # (bq,)

    def body(ki, acc):
        ks = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        vs = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            qs, ks, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bq, bk)
        if causal:
            s = _mask_causal(s, qi, block_q, ki, block_k)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            do, vs, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bq, bk)
        ds = p * (dp - dlt[:, None])
        return acc + jax.lax.dot_general(
            ds, ks, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bq, d)

    if causal:
        nk = _diag_kblocks(qi, block_q, block_k)
    else:
        nk = seq_len // block_k
    acc = lax.fori_loop(0, nk, body,
                        jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = (acc * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q: int, scale: float,
                          causal: bool, seq_len: int):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    block_k = k_ref.shape[1]
    d = k_ref.shape[2]
    kj = pl.program_id(1)
    ks = k_ref[0].astype(jnp.float32)              # (bk, d)
    vs = v_ref[0].astype(jnp.float32)              # (bk, d)

    def body(qi, carry):
        acc_dk, acc_dv = carry
        qs = q_ref[0, pl.ds(qi * block_q, block_q), :] \
            .astype(jnp.float32) * scale           # (bq, d)
        do = g_ref[0, pl.ds(qi * block_q, block_q), :] \
            .astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qi, 1), :][0]       # (bq,)
        dlt = delta_ref[0, pl.ds(qi, 1), :][0]
        s = jax.lax.dot_general(
            qs, ks, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bq, bk)
        if causal:
            s = _mask_causal(s, qi, block_q, kj, block_k)
        p = jnp.exp(s - lse[:, None])
        acc_dv = acc_dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bk, d)
        dp = jax.lax.dot_general(
            do, vs, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - dlt[:, None])
        acc_dk = acc_dk + jax.lax.dot_general(
            ds, qs, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bk, d)
        return acc_dk, acc_dv

    nq = seq_len // block_q
    q0 = lax.div(kj * block_k, block_q) if causal else 0
    z = jnp.zeros((block_k, d), jnp.float32)
    acc_dk, acc_dv = lax.fori_loop(q0, nq, body, (z, z))
    # qs carried the scale, so acc_dk is dL/dk exactly
    dk_ref[0] = acc_dk.astype(dk_ref.dtype)
    dv_ref[0] = acc_dv.astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal, scale, interpret):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    b, h, t, d = q.shape
    block_q = _pick_block(t)
    block_k = _pick_block(t)
    qr = q.reshape(b * h, t, d)
    kr = k.reshape(b * h, t, d)
    vr = v.reshape(b * h, t, d)
    gr = g.reshape(b * h, t, d)
    outr = out.reshape(b * h, t, d)
    # delta_i = sum_d dO_i . O_i — one fused elementwise+reduce in XLA;
    # carried at the lse layout (bh, T//bq, bq), see the fwd kernel
    delta = jnp.sum(gr.astype(jnp.float32) * outr.astype(jnp.float32),
                    axis=-1).reshape(b * h, t // block_q, block_q)

    lse_spec = pl.BlockSpec((1, t // block_q, block_q),
                            lambda i, j: (i, 0, 0))
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=block_k,
                          scale=scale, causal=causal, seq_len=t),
        grid=(b * h, t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            lse_spec,
            lse_spec,
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        interpret=interpret,
    )(qr, kr, vr, gr, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q,
                          scale=scale, causal=causal, seq_len=t),
        grid=(b * h, t // block_k),
        in_specs=[
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
            lse_spec,
            lse_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, t, d), v.dtype),
        ],
        interpret=interpret,
    )(qr, kr, vr, gr, lse, delta)

    shape = (b, h, t, d)
    return dq.reshape(shape), dk.reshape(shape), dv.reshape(shape)


def _flash_fwd_rule(q, k, v, causal, scale, interpret):
    out, lse = _flash_forward(q, k, v, causal, scale, interpret,
                              with_lse=True)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, scale, interpret, res, g):
    import jax

    q, k, v, out, lse = res
    if lse is None:
        # the forward fell back to the lax reference (untileable T):
        # recompute its vjp the same way
        def ref(q, k, v):
            return _reference_attention(q, k, v, causal=causal,
                                        scale=scale)

        _, vjp = jax.vjp(ref, q, k, v)
        return vjp(g)
    return _flash_backward(q, k, v, out, lse, g, causal, scale, interpret)


_flash_attention_vjp.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# --------------------------------------------------------------------------
# public dispatcher
# --------------------------------------------------------------------------


def dot_product_attention(q, k, v, *, causal: bool = False, mask=None,
                          scale: Optional[float] = None, impl: str = "auto",
                          seq_offset: int = 0):
    """Attention entry point used by nn.MultiHeadAttention.

    q, k, v: (batch, heads, seq, head_dim).

    impl: "auto" (measured policy — lax below T=4096, the Pallas flash
    kernel on TPU in the long-context regime where lax's per-layer
    (B, H, T, T) residuals stop fitting), "pallas", "pallas_interpret"
    (testing), or "lax".
    """
    import jax

    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    t = q.shape[-2]
    if impl == "auto":
        on_tpu = jax.default_backend() == "tpu"
        tiles = (
            mask is None and seq_offset == 0
            and q.shape == k.shape == v.shape
            and t >= 128 and t % 128 == 0
        )
        # Measured on the 2026-07 toolchain (TransformerLM train step,
        # TPU v5 lite, ms/step): XLA's fused attention beats the Pallas
        # flash forward at every length that fits its residuals —
        # T=512: 59.3 lax vs 64.7 pallas; T=1024: 76.2 vs 80.2;
        # T=2048: 114.1 vs 124.6.  What flash buys on TPU is MEMORY:
        # under jax.grad the lax path saves (B, H, T, T) softmax
        # residuals for EVERY layer simultaneously — the long-context
        # cliff.  The flash path saves (q, k, v, out, lse) — O(T) —
        # and its blockwise backward kernels rebuild score tiles from
        # the logsumexp, so no (T, T) array exists in either direction.
        # So auto prefers lax until the quadratic-residual regime and
        # flips to the kernel there.
        impl = "pallas" if (on_tpu and tiles and t >= 4096) else "lax"
    if impl in ("pallas", "pallas_interpret"):
        if mask is not None or seq_offset:
            raise ValueError(
                "the Pallas flash kernel supports neither an explicit mask "
                "nor seq_offset; use impl='lax' (ring attention does)"
            )
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               interpret=(impl == "pallas_interpret"))
    return _reference_attention(q, k, v, causal=causal, scale=scale,
                                mask=mask, seq_offset=seq_offset)
