"""Scaled-dot-product attention: lax reference + Pallas flash kernel.

The reference framework predates attention entirely (SURVEY.md §5
"long-context: absent") — this op is a *new* capability, the hot inner
op of the Transformer/long-context stack (nn/attention.py,
parallel/ring_attention.py).

Design for the MXU/VMEM (pallas_guide.md):

* the Pallas kernel is a classic flash attention: grid over
  (batch*heads, query blocks), ``lax.fori_loop`` over key blocks, online
  softmax with running max ``m`` and normalizer ``l`` kept in VMEM
  scratch so the (T, T) score matrix never materialises in HBM;
* block sizes are multiples of the fp32 (8, 128) tile, MXU-sized 128
  where the sequence allows;
* matmuls carry ``preferred_element_type=jnp.float32`` so bf16 inputs
  accumulate in fp32 on the MXU.

``dot_product_attention`` is the public entry.  ``impl="auto"`` is
measurement-driven (see the dispatcher): the lax reference wins
throughput on the 2026-07 toolchain at every length whose softmax
residuals fit, so auto takes lax below T=4096 and the Pallas kernel in
the long-context regime, where saving only (q, k, v) instead of
per-layer (B, H, T, T) residuals is the difference between fitting and
OOM.  Both paths are differentiable — the Pallas path via
``jax.custom_vjp`` with a lax-reference recompute backward (transient
per-layer T^2, not blockwise).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax


# --------------------------------------------------------------------------
# lax reference implementation
# --------------------------------------------------------------------------


def _reference_attention(q, k, v, *, causal: bool, scale: float,
                         mask=None, seq_offset: int = 0):
    """Plain softmax(q k^T) v.  (B, H, Tq, D) x (B, H, Tk, D).

    ``seq_offset`` shifts query positions for causal masking — used by
    ring attention where the local query block starts at a nonzero
    absolute position.
    """
    import jax.numpy as jnp

    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        qpos = jnp.arange(tq)[:, None] + seq_offset
        kpos = jnp.arange(tk)[None, :]
        scores = jnp.where(qpos >= kpos, scores, -jnp.inf)
    if mask is not None:
        scores = jnp.where(mask, scores, -jnp.inf)
    # guard fully-masked rows (ring attention partial blocks): softmax of
    # all -inf must give zeros, not NaN
    row_max = jnp.max(scores, axis=-1, keepdims=True)
    row_max = jnp.where(jnp.isfinite(row_max), row_max, 0.0)
    unnorm = jnp.exp(scores - row_max)
    denom = jnp.sum(unnorm, axis=-1, keepdims=True)
    probs = unnorm / jnp.maximum(denom, 1e-30)
    out = jnp.einsum(
        "bhqk,bhkd->bhqd", probs, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


# --------------------------------------------------------------------------
# Pallas flash attention (TPU)
# --------------------------------------------------------------------------


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int,
                      scale: float, causal: bool, seq_len: int):
    """One (batch*head, q-block) program: stream key blocks, online
    softmax.  Refs are VMEM blocks: q (1, block_q, d), k/v (1, T, d)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # (block_q, d)

    m0 = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    def body(ki, carry):
        m, l, acc = carry
        ks = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        vs = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, ks, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, block_k)
        if causal:
            qpos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kpos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(qpos >= kpos, s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # fully-masked rows keep m=-inf; use 0 shift there to avoid NaNs
        shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - shift[:, None])
        alpha = jnp.exp(jnp.where(jnp.isfinite(m), m - shift, -jnp.inf))
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, vs, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    if causal:
        # process key blocks up to and including the diagonal
        last = (qi + 1) * block_q  # exclusive end of query positions
        nk = lax.div(last + block_k - 1, block_k)
        m, l, acc = lax.fori_loop(0, nk, body, (m0, l0, acc0))
    else:
        m, l, acc = lax.fori_loop(0, seq_len // block_k, body, (m0, l0, acc0))

    out = acc / jnp.maximum(l, 1e-30)[:, None]
    o_ref[0] = out.astype(o_ref.dtype)


def _pick_block(t: int, preferred: int = 128) -> int:
    for b in (preferred, 64, 32, 16, 8):
        if t % b == 0:
            return b
    return 0


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "interpret")
)
def flash_attention(q, k, v, *, causal: bool = False,
                    scale: Optional[float] = None, interpret: bool = False):
    """Pallas flash attention.  q/k/v: (B, H, T, D) with T a multiple of
    8 and D a multiple of... anything (padded to 128 lanes by Mosaic).

    Differentiable: the backward recomputes attention with the lax
    reference (rematerialisation — trading FLOPs for HBM, the standard
    TPU bargain) so only the forward needs a hand kernel.
    """
    return _flash_attention_vjp(q, k, v, causal,
                                scale if scale is not None else q.shape[-1] ** -0.5,
                                interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_attention_vjp(q, k, v, causal, scale, interpret):
    return _flash_forward(q, k, v, causal, scale, interpret)


def _flash_forward(q, k, v, causal, scale, interpret):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    b, h, t, d = q.shape
    block_q = _pick_block(t)
    block_k = _pick_block(t)
    if not block_q:
        return _reference_attention(q, k, v, causal=causal, scale=scale)

    kernel = functools.partial(
        _flash_fwd_kernel, block_k=block_k, scale=scale, causal=causal,
        seq_len=t,
    )
    qr = q.reshape(b * h, t, d)
    kr = k.reshape(b * h, t, d)
    vr = v.reshape(b * h, t, d)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(b, h, t, d)


def _flash_fwd_rule(q, k, v, causal, scale, interpret):
    out = _flash_forward(q, k, v, causal, scale, interpret)
    return out, (q, k, v)


def _flash_bwd_rule(causal, scale, interpret, res, g):
    import jax

    q, k, v = res

    def ref(q, k, v):
        return _reference_attention(q, k, v, causal=causal, scale=scale)

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


_flash_attention_vjp.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# --------------------------------------------------------------------------
# public dispatcher
# --------------------------------------------------------------------------


def dot_product_attention(q, k, v, *, causal: bool = False, mask=None,
                          scale: Optional[float] = None, impl: str = "auto",
                          seq_offset: int = 0):
    """Attention entry point used by nn.MultiHeadAttention.

    q, k, v: (batch, heads, seq, head_dim).

    impl: "auto" (measured policy — lax below T=4096, the Pallas flash
    kernel on TPU in the long-context regime where lax's per-layer
    (B, H, T, T) residuals stop fitting), "pallas", "pallas_interpret"
    (testing), or "lax".
    """
    import jax

    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    t = q.shape[-2]
    if impl == "auto":
        on_tpu = jax.default_backend() == "tpu"
        tiles = (
            mask is None and seq_offset == 0
            and q.shape == k.shape == v.shape
            and t >= 128 and t % 128 == 0
        )
        # Measured on the 2026-07 toolchain (TransformerLM train step,
        # TPU v5 lite, ms/step): XLA's fused attention beats the Pallas
        # flash forward at every length that fits its residuals —
        # T=512: 59.3 lax vs 64.7 pallas; T=1024: 76.2 vs 80.2;
        # T=2048: 114.1 vs 124.6.  What flash buys on TPU is MEMORY:
        # under jax.grad the lax path saves (B, H, T, T) softmax
        # residuals for EVERY layer simultaneously — the long-context
        # cliff.  The flash path saves only (q, k, v): its backward
        # recompute (see _flash_bwd_rule) still materializes O(T^2)
        # scores, but transiently, one layer at a time — an
        # n_layers-fold cut in live memory, not a blockwise-backward
        # elimination of T^2 (that kernel does not exist here yet).
        # So auto prefers lax until the quadratic-residual regime and
        # flips to the kernel there (validated on chip at T=4096).
        impl = "pallas" if (on_tpu and tiles and t >= 4096) else "lax"
    if impl in ("pallas", "pallas_interpret"):
        if mask is not None or seq_offset:
            raise ValueError(
                "the Pallas flash kernel supports neither an explicit mask "
                "nor seq_offset; use impl='lax' (ring attention does)"
            )
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               interpret=(impl == "pallas_interpret"))
    return _reference_attention(q, k, v, causal=causal, scale=scale,
                                mask=mask, seq_offset=seq_offset)
