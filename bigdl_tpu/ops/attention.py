"""Scaled-dot-product attention: lax reference + Pallas flash kernel.

The reference framework predates attention entirely (SURVEY.md §5
"long-context: absent") — this op is a *new* capability, the hot inner
op of the Transformer/long-context stack (nn/attention.py,
parallel/ring_attention.py).

Design for the MXU/VMEM (pallas_guide.md):

* the Pallas kernel is a classic flash attention: grid over
  (batch*heads, query blocks, kv superblocks), ``lax.fori_loop`` over
  key tiles inside each superblock, online softmax with running max
  ``m`` and normalizer ``l`` carried in VMEM scratch ACROSS the kv
  grid dimension so the (T, T) score matrix never materialises in HBM
  and no kv length is too long to stream;
* block sizes are multiples of the fp32 (8, 128) tile, MXU-sized 128
  where the sequence allows; the kv superblock (``block_kv``) and the
  backward's q superblock (``block_qs``) are sized by the symmetric
  VMEM model in :func:`_flash_plan` — and are tunable per shape by
  ``ops.autotune``;
* matmuls carry ``preferred_element_type=jnp.float32`` so bf16 inputs
  accumulate in fp32 on the MXU.

``dot_product_attention`` is the public entry.  ``impl="auto"`` is a
measured policy (:func:`static_dispatch`): the lax reference wins
throughput on the 2026-07 toolchain at every length whose softmax
residuals fit, so auto takes lax below Tq*Tk = 4096^2 and the Pallas
kernel in the long-context regime, where flash's O(T) residuals — (q,
k, v, out, logsumexp) instead of per-layer (B, H, Tq, Tk) — are the
difference between fitting and OOM.  When the fusion-aware auto-tuner
is enabled (``BIGDL_TUNER=1``, ops/autotune.py) the static policy is
only the fallback: dispatch and block sizes come from the cached
cost-model search instead.  Both paths are differentiable — the Pallas
path via ``jax.custom_vjp`` with blockwise backward kernels that never
materialize a (Tq, Tk) array in either direction.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax


# --------------------------------------------------------------------------
# lax reference implementation
# --------------------------------------------------------------------------


def _reference_attention(q, k, v, *, causal: bool, scale: float,
                         mask=None, seq_offset: int = 0):
    """Plain softmax(q k^T) v.  (B, H, Tq, D) x (B, H, Tk, D).

    ``seq_offset`` shifts query positions for causal masking — used by
    ring attention where the local query block starts at a nonzero
    absolute position.
    """
    import jax.numpy as jnp

    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        qpos = jnp.arange(tq)[:, None] + seq_offset
        kpos = jnp.arange(tk)[None, :]
        scores = jnp.where(qpos >= kpos, scores, -jnp.inf)
    if mask is not None:
        scores = jnp.where(mask, scores, -jnp.inf)
    # guard fully-masked rows (ring attention partial blocks): softmax of
    # all -inf must give zeros, not NaN
    row_max = jnp.max(scores, axis=-1, keepdims=True)
    row_max = jnp.where(jnp.isfinite(row_max), row_max, 0.0)
    unnorm = jnp.exp(scores - row_max)
    denom = jnp.sum(unnorm, axis=-1, keepdims=True)
    probs = unnorm / jnp.maximum(denom, 1e-30)
    out = jnp.einsum(
        "bhqk,bhkd->bhqd", probs, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


# --------------------------------------------------------------------------
# Pallas flash attention (TPU)
# --------------------------------------------------------------------------


def _mask_causal(s, qi, block_q, ki, block_k, seq_offset=0):
    """-inf the future positions of a (block_q, block_k) score tile at
    GLOBAL block coordinates (qi, ki); ``seq_offset`` (static) shifts
    the query positions — chunked causal attention where the local
    query block starts at a nonzero absolute position.  Single
    definition shared by the forward and both backward kernels so the
    mask convention can never desynchronize between them."""
    import jax.numpy as jnp
    from jax import lax

    qpos = seq_offset + qi * block_q + lax.broadcasted_iota(
        jnp.int32, s.shape, 0)
    kpos = ki * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(qpos >= kpos, s, -jnp.inf)


def _diag_kblocks(qi, block_q, block_k, seq_offset=0, kv_len=None):
    """Number of key tiles a causal q-block touches (through its
    diagonal at query offset ``seq_offset``), clamped to the kv
    extent; shared by the forward and dq kernels."""
    import jax.numpy as jnp
    from jax import lax

    nk = lax.div(seq_offset + (qi + 1) * block_q + block_k - 1, block_k)
    if kv_len is not None:
        nk = jnp.minimum(nk, kv_len // block_k)
    return nk


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                      m_scr, l_scr, acc_scr, *,
                      block_k: int, scale: float, causal: bool,
                      kv_len: int, seq_offset: int = 0):
    """One (batch*head, q-block, kv-superblock) program: stream the
    superblock's key tiles, online softmax.  Refs are VMEM blocks: q
    (1, block_q, d), k/v (1, block_kv, d).  The running (m, l, acc)
    state lives in VMEM scratch and is CARRIED across the kv grid
    dimension (sequential on TPU, fastest-varying), so any kv length
    streams in superblocks the VMEM budget allows; output and the
    per-row logsumexp (scaled-score units, so the blockwise backward
    can rebuild P = exp(s - lse)) are written on the final superblock
    only."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    block_kv = k_ref.shape[1]
    spk = block_kv // block_k            # key tiles per superblock
    qi = pl.program_id(1)
    s = pl.program_id(2)
    ns = pl.num_programs(2)

    @pl.when(s == 0)
    def _init():
        m_scr[0] = jnp.full((block_q,), -jnp.inf, jnp.float32)
        l_scr[0] = jnp.zeros((block_q,), jnp.float32)
        acc_scr[...] = jnp.zeros((block_q, d), jnp.float32)

    q = q_ref[0].astype(jnp.float32) * scale  # (block_q, d)

    def body(ki, carry):
        m, l, acc = carry
        ks = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        vs = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        st = jax.lax.dot_general(
            q, ks, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, block_k)
        if causal:
            st = _mask_causal(st, qi, block_q, s * spk + ki, block_k,
                              seq_offset)
        m_new = jnp.maximum(m, jnp.max(st, axis=-1))
        # fully-masked rows keep m=-inf; use 0 shift there to avoid NaNs
        shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(st - shift[:, None])
        alpha = jnp.exp(jnp.where(jnp.isfinite(m), m - shift, -jnp.inf))
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, vs, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    if causal:
        # global diagonal tile count, clamped into this superblock
        nk = _diag_kblocks(qi, block_q, block_k, seq_offset, kv_len)
        hi = jnp.clip(nk - s * spk, 0, spk)
    else:
        hi = spk
    m, l, acc = lax.fori_loop(
        0, hi, body, (m_scr[0], l_scr[0], acc_scr[...]))
    m_scr[0] = m
    l_scr[0] = l
    acc_scr[...] = acc

    @pl.when(s == ns - 1)
    def _finalize():
        out = acc_scr[...] / jnp.maximum(l_scr[0], 1e-30)[:, None]
        o_ref[0] = out.astype(o_ref.dtype)
        # lse rides as (1, T//block_q, block_q): Mosaic's block rule
        # wants the last two dims (8, 128)-divisible-or-full, which a
        # (1, block_q) row block violates.  The full plane is mapped
        # for every (j, s) and revisited (same block index), so each
        # program writes only its row and the block flushes once per
        # batch*head.
        lse = m_scr[0] + jnp.log(jnp.maximum(l_scr[0], 1e-30))
        lse_ref[0, pl.ds(qi, 1), :] = lse[None, :]


# the flash kernels stream two whole (1, T, d) tensors per program when
# the sequence fits — k+v in the forward/dq kernels, q+g in the dkv
# kernel — as GRID-VARYING blocks, which Pallas double-buffers; cap
# their combined footprint (2 tensors x 2 buffers) well under the
# ~16 MB VMEM so the f32 accumulators and compiler temporaries still
# fit.  Sequences past the cap stream in superblocks instead
# (block_kv / block_qs below) — the budget then sizes the superblock,
# it no longer forbids the shape.  On-chip validated point: Tk=8192 at
# d=128 bf16 (8 MB with double-buffering).
_KV_VMEM_BUDGET = 8 * 1024 * 1024


def _kv_fits_vmem(t: int, d: int, dtype) -> bool:
    """Do two whole grid-varying (1, t, d) VMEM streams fit the budget?

    SYMMETRIC guard (round-5 ADVICE): the forward and dq kernels
    stream k+v over the kv length, but the dkv kernel streams q+g over
    the QUERY length — a large-Tq config that only checked Tk passed
    the forward and blew VMEM under ``jax.grad``.  Callers must hold
    this for both Tq and Tk (or fall back to superblock streaming, see
    :func:`_flash_plan`).  The factor 4 = 2 tensors x the
    double-buffering Pallas applies to grid-varying input blocks."""
    import jax.numpy as jnp

    return 4 * t * d * jnp.dtype(dtype).itemsize <= _KV_VMEM_BUDGET


def _pick_block(t: int, preferred: int = 128) -> int:
    for b in (preferred, 64, 32, 16, 8):
        if t % b == 0:
            return b
    return 0


def _largest_stream_block(t: int, tile: int, d: int, itemsize: int) -> int:
    """Largest superblock — a multiple of ``tile`` dividing ``t`` —
    whose two double-buffered (1, c, d) streams fit the VMEM budget;
    0 when even a single tile does not fit."""
    cap = _KV_VMEM_BUDGET // (4 * d * itemsize)
    if tile > cap:
        return 0
    nt = t // tile
    best = 0
    for m in range(1, nt + 1):
        if nt % m == 0 and m * tile <= cap:
            best = m * tile
    return best


def _flash_plan(tq: int, tk: int, d: int, dtype, *, block_q: int = 0,
                block_k: int = 0, block_kv: int = 0, block_qs: int = 0):
    """Symmetric VMEM feasibility model + tile plan for the flash
    kernels.  Returns ``(block_q, block_k, block_kv, block_qs)`` — the
    q/k tile sizes, the kv superblock streamed by the forward and dq
    kernels, and the q superblock streamed by the dkv kernel — or
    ``None`` when no feasible tiling exists (untileable T, or even one
    tile would blow the budget).  Explicit nonzero arguments (the
    auto-tuner's choices) are validated, not overridden."""
    import jax.numpy as jnp

    itemsize = jnp.dtype(dtype).itemsize
    bq = block_q or _pick_block(tq)
    bk = block_k or _pick_block(tk)
    if not bq or not bk or tq % bq or tk % bk:
        return None
    bkv = block_kv or (tk if _kv_fits_vmem(tk, d, dtype)
                       else _largest_stream_block(tk, bk, d, itemsize))
    bqs = block_qs or (tq if _kv_fits_vmem(tq, d, dtype)
                       else _largest_stream_block(tq, bq, d, itemsize))
    if (not bkv or not bqs or tk % bkv or bkv % bk
            or tq % bqs or bqs % bq):
        return None
    return (bq, bk, bkv, bqs)


# blocks = (block_q, block_k, block_kv, block_qs); 0 means auto
_AUTO_BLOCKS = (0, 0, 0, 0)


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "interpret",
                              "seq_offset", "block_q", "block_k",
                              "block_kv", "block_qs")
)
def flash_attention(q, k, v, *, causal: bool = False,
                    scale: Optional[float] = None, interpret: bool = False,
                    seq_offset: int = 0, block_q: int = 0, block_k: int = 0,
                    block_kv: int = 0, block_qs: int = 0):
    """Pallas flash attention.  q (B, H, Tq, D) against k/v
    (B, H, Tk, D) — Tq and Tk each a multiple of 8, D anything (padded
    to 128 lanes by Mosaic).  ``seq_offset`` (STATIC int >= 0) places
    the query block at a global position for chunked causal
    attention: q covers absolute positions [seq_offset, seq_offset+Tq)
    of the kv sequence.

    ``block_q``/``block_k`` override the q/k tile sizes and
    ``block_kv``/``block_qs`` the streamed superblocks (0 = let
    :func:`_flash_plan` choose) — the auto-tuner's knobs; invalid
    overrides fall back to the lax reference like any other infeasible
    shape.  Compiled Mosaic kernels exist only on TPU, so any other
    backend runs the interpreter automatically.

    Differentiable with a true blockwise backward: the forward saves
    (q, k, v, out, logsumexp) — O(T) extra — and the backward kernels
    (_flash_bwd_dq_kernel / _flash_bwd_dkv_kernel) rebuild the score
    tiles from the logsumexp, so no (Tq, Tk) array is ever
    materialized, as residual OR transient, in either direction.
    """
    if seq_offset < 0:
        raise ValueError("seq_offset must be >= 0")
    interpret = interpret or jax.default_backend() != "tpu"
    return _flash_attention_vjp(q, k, v, causal,
                                scale if scale is not None else q.shape[-1] ** -0.5,
                                interpret, seq_offset,
                                (block_q, block_k, block_kv, block_qs))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention_vjp(q, k, v, causal, scale, interpret, seq_offset,
                         blocks):
    return _flash_forward(q, k, v, causal, scale, interpret,
                          seq_offset=seq_offset, blocks=blocks)


def _flash_forward(q, k, v, causal, scale, interpret, *,
                   with_lse: bool = False, seq_offset: int = 0,
                   blocks=_AUTO_BLOCKS):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, tq, d = q.shape
    tk = k.shape[2]
    plan = _flash_plan(tq, tk, d, k.dtype, block_q=blocks[0],
                       block_k=blocks[1], block_kv=blocks[2],
                       block_qs=blocks[3])
    if plan is None:
        # untileable T, or even single-tile streaming would blow the
        # symmetric VMEM budget: lax reference (auto dispatch never
        # lands here — its predicate shares this plan)
        out = _reference_attention(q, k, v, causal=causal, scale=scale,
                                   seq_offset=seq_offset)
        return (out, None) if with_lse else out

    block_q, block_k, block_kv, _ = plan
    kernel = functools.partial(
        _flash_fwd_kernel, block_k=block_k, scale=scale, causal=causal,
        kv_len=tk, seq_offset=seq_offset,
    )
    qr = q.reshape(b * h, tq, d)
    kr = k.reshape(b * h, tk, d)
    vr = v.reshape(b * h, tk, d)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, tq // block_q, tk // block_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, s: (i, j, 0)),
            pl.BlockSpec((1, block_kv, d), lambda i, j, s: (i, s, 0)),
            pl.BlockSpec((1, block_kv, d), lambda i, j, s: (i, s, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, s: (i, j, 0)),
            pl.BlockSpec((1, tq // block_q, block_q),
                         lambda i, j, s: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, tq // block_q, block_q),
                                 jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, block_q), jnp.float32),
            pltpu.VMEM((1, block_q), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr)
    out = out.reshape(b, h, tq, d)
    return (out, lse) if with_lse else out


# ---- blockwise backward (the true flash backward: no T^2 residuals,
# no T^2 transients — scores are rebuilt tile by tile from the saved
# logsumexp) ----


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                         dq_ref, acc_scr, *, block_k: int, scale: float,
                         causal: bool, kv_len: int, seq_offset: int = 0):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    block_kv = k_ref.shape[1]
    spk = block_kv // block_k
    qi = pl.program_id(1)
    s = pl.program_id(2)
    ns = pl.num_programs(2)

    @pl.when(s == 0)
    def _init():
        acc_scr[...] = jnp.zeros((block_q, d), jnp.float32)

    qs = q_ref[0].astype(jnp.float32) * scale      # (bq, d)
    do = g_ref[0].astype(jnp.float32)              # (bq, d)
    lse = lse_ref[0, pl.ds(qi, 1), :][0]           # (bq,)
    dlt = delta_ref[0, pl.ds(qi, 1), :][0]         # (bq,)

    def body(ki, acc):
        ks = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        vs = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        st = jax.lax.dot_general(
            qs, ks, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bq, bk)
        if causal:
            st = _mask_causal(st, qi, block_q, s * spk + ki, block_k,
                              seq_offset)
        p = jnp.exp(st - lse[:, None])
        dp = jax.lax.dot_general(
            do, vs, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bq, bk)
        ds = p * (dp - dlt[:, None])
        return acc + jax.lax.dot_general(
            ds, ks, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bq, d)

    if causal:
        nk = _diag_kblocks(qi, block_q, block_k, seq_offset, kv_len)
        hi = jnp.clip(nk - s * spk, 0, spk)
    else:
        hi = spk
    acc_scr[...] = lax.fori_loop(0, hi, body, acc_scr[...])

    @pl.when(s == ns - 1)
    def _finalize():
        dq_ref[0] = (acc_scr[...] * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_scr, dv_scr, *,
                          block_q: int, scale: float, causal: bool,
                          seq_offset: int = 0):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    block_k = k_ref.shape[1]
    d = k_ref.shape[2]
    block_qs = q_ref.shape[1]
    spq = block_qs // block_q            # q tiles per superblock
    kj = pl.program_id(1)
    s = pl.program_id(2)
    ns = pl.num_programs(2)

    @pl.when(s == 0)
    def _init():
        dk_scr[...] = jnp.zeros((block_k, d), jnp.float32)
        dv_scr[...] = jnp.zeros((block_k, d), jnp.float32)

    ks = k_ref[0].astype(jnp.float32)              # (bk, d)
    vs = v_ref[0].astype(jnp.float32)              # (bk, d)

    def body(qi, carry):
        # ``qi`` is LOCAL to this q superblock; masks use the global
        # tile index s * spq + qi
        acc_dk, acc_dv = carry
        qs = q_ref[0, pl.ds(qi * block_q, block_q), :] \
            .astype(jnp.float32) * scale           # (bq, d)
        do = g_ref[0, pl.ds(qi * block_q, block_q), :] \
            .astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qi, 1), :][0]       # (bq,)
        dlt = delta_ref[0, pl.ds(qi, 1), :][0]
        st = jax.lax.dot_general(
            qs, ks, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bq, bk)
        if causal:
            st = _mask_causal(st, s * spq + qi, block_q, kj, block_k,
                              seq_offset)
        p = jnp.exp(st - lse[:, None])
        acc_dv = acc_dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bk, d)
        dp = jax.lax.dot_general(
            do, vs, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - dlt[:, None])
        acc_dk = acc_dk + jax.lax.dot_general(
            ds, qs, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bk, d)
        return acc_dk, acc_dv

    if causal:
        # first GLOBAL q tile whose rows reach this key block, clamped
        # into this superblock's local tile range:
        # q0 = floor(max(kj*block_k - seq_offset, 0) / block_q)
        q0 = lax.div(jnp.maximum(kj * block_k - seq_offset, 0), block_q)
        lo = jnp.clip(q0 - s * spq, 0, spq)
    else:
        lo = 0
    acc_dk, acc_dv = lax.fori_loop(lo, spq, body,
                                   (dk_scr[...], dv_scr[...]))
    dk_scr[...] = acc_dk
    dv_scr[...] = acc_dv

    @pl.when(s == ns - 1)
    def _finalize():
        # qs carried the scale, so dk_scr is dL/dk exactly
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal, scale, interpret,
                    seq_offset=0, blocks=_AUTO_BLOCKS):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, tq, d = q.shape
    tk = k.shape[2]
    # same deterministic plan as the forward (residual lse layout
    # depends on block_q, so the two must agree)
    block_q, block_k, block_kv, block_qs = _flash_plan(
        tq, tk, d, k.dtype, block_q=blocks[0], block_k=blocks[1],
        block_kv=blocks[2], block_qs=blocks[3])
    qr = q.reshape(b * h, tq, d)
    kr = k.reshape(b * h, tk, d)
    vr = v.reshape(b * h, tk, d)
    gr = g.reshape(b * h, tq, d)
    outr = out.reshape(b * h, tq, d)
    # delta_i = sum_d dO_i . O_i — one fused elementwise+reduce in XLA;
    # carried at the lse layout (bh, Tq//bq, bq), see the fwd kernel
    delta = jnp.sum(gr.astype(jnp.float32) * outr.astype(jnp.float32),
                    axis=-1).reshape(b * h, tq // block_q, block_q)

    lse_plane = pl.BlockSpec((1, tq // block_q, block_q),
                             lambda i, j, s: (i, 0, 0))
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=block_k,
                          scale=scale, causal=causal, kv_len=tk,
                          seq_offset=seq_offset),
        grid=(b * h, tq // block_q, tk // block_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, s: (i, j, 0)),
            pl.BlockSpec((1, block_kv, d), lambda i, j, s: (i, s, 0)),
            pl.BlockSpec((1, block_kv, d), lambda i, j, s: (i, s, 0)),
            pl.BlockSpec((1, block_q, d), lambda i, j, s: (i, j, 0)),
            lse_plane,
            lse_plane,
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j, s: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qr, kr, vr, gr, lse, delta)

    spq = block_qs // block_q
    lse_super = pl.BlockSpec((1, spq, block_q), lambda i, j, s: (i, s, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q,
                          scale=scale, causal=causal,
                          seq_offset=seq_offset),
        grid=(b * h, tk // block_k, tq // block_qs),
        in_specs=[
            pl.BlockSpec((1, block_qs, d), lambda i, j, s: (i, s, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, s: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, s: (i, j, 0)),
            pl.BlockSpec((1, block_qs, d), lambda i, j, s: (i, s, 0)),
            lse_super,
            lse_super,
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, j, s: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, s: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, tk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr, gr, lse, delta)

    return (dq.reshape(b, h, tq, d), dk.reshape(b, h, tk, d),
            dv.reshape(b, h, tk, d))


def _flash_fwd_rule(q, k, v, causal, scale, interpret, seq_offset, blocks):
    out, lse = _flash_forward(q, k, v, causal, scale, interpret,
                              with_lse=True, seq_offset=seq_offset,
                              blocks=blocks)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, scale, interpret, seq_offset, blocks, res, g):
    import jax

    q, k, v, out, lse = res
    if lse is None:
        # the forward fell back to the lax reference (untileable T):
        # recompute its vjp the same way
        def ref(q, k, v):
            return _reference_attention(q, k, v, causal=causal,
                                        scale=scale,
                                        seq_offset=seq_offset)

        _, vjp = jax.vjp(ref, q, k, v)
        return vjp(g)
    return _flash_backward(q, k, v, out, lse, g, causal, scale,
                           interpret, seq_offset, blocks=blocks)


_flash_attention_vjp.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# --------------------------------------------------------------------------
# public dispatcher
# --------------------------------------------------------------------------


def static_dispatch(q_shape, k_shape, v_shape, dtype, *, mask_is_none=True,
                    seq_offset=0, backend: Optional[str] = None):
    """The hand-measured ``impl="auto"`` policy as a pure function of
    STATIC shapes: returns ``(impl, plan)`` with impl in
    {"lax", "pallas"} and plan the :func:`_flash_plan` tiling (None on
    the lax path when flash is infeasible).  Single source of truth
    for the dispatcher, the auto-tuner's static baseline, and the
    tuner-off pinning tests."""
    t, d = q_shape[-2], q_shape[-1]
    tk = k_shape[-2]
    tiles = (
        mask_is_none
        and tuple(k_shape) == tuple(v_shape)
        and tuple(q_shape[:2]) == tuple(k_shape[:2])
        and q_shape[-1] == k_shape[-1]
        and t >= 128 and t % 128 == 0
        and tk >= 128 and tk % 128 == 0
        and isinstance(seq_offset, int) and seq_offset >= 0
    )
    # the plan holds the SYMMETRIC VMEM guard: _kv_fits_vmem over both
    # Tq and Tk (the dkv kernel streams whole q/g blocks, round-5
    # ADVICE), falling back to superblock streaming past the budget
    plan = _flash_plan(t, tk, d, dtype) if tiles else None
    if backend is None:
        backend = jax.default_backend()
    # Measured on the 2026-07 toolchain (TransformerLM train step,
    # TPU v5 lite, ms/step): XLA's fused attention beats the Pallas
    # flash forward at every length that fits its residuals —
    # T=512: 59.3 lax vs 64.7 pallas; T=1024: 76.2 vs 80.2;
    # T=2048: 114.1 vs 124.6.  What flash buys on TPU is MEMORY:
    # under jax.grad the lax path saves (B, H, Tq, Tk) softmax
    # residuals for EVERY layer simultaneously — the long-context
    # cliff.  The flash path saves (q, k, v, out, lse) — O(T) — and
    # its blockwise backward kernels rebuild score tiles from the
    # logsumexp, so no (Tq, Tk) array exists in either direction.
    # So auto prefers lax until the quadratic-residual regime and
    # flips to the kernel there.  The residual is (B, H, Tq, Tk), so
    # the flip watches the PRODUCT, and kv-superblock streaming keeps
    # the whole product regime reachable: a 2048-query chunk against a
    # 32k kv at d=128 streams the kv in 8k superblocks and takes the
    # flash path, where it previously bailed on the whole-kv VMEM
    # guard.
    impl = ("pallas" if (backend == "tpu" and plan is not None
                         and t * tk >= 4096 * 4096)
            else "lax")
    return impl, plan


def dot_product_attention(q, k, v, *, causal: bool = False, mask=None,
                          scale: Optional[float] = None, impl: str = "auto",
                          seq_offset: int = 0):
    """Attention entry point used by nn.MultiHeadAttention.

    q, k, v: (batch, heads, seq, head_dim).

    impl: "auto" (the measured :func:`static_dispatch` policy — lax
    below Tq*Tk = 4096^2, the Pallas flash kernel on TPU in the
    long-context regime where lax's per-layer (B, H, Tq, Tk) residuals
    stop fitting; with ``BIGDL_TUNER=1`` the cached auto-tuner search
    overrides it per shape), "pallas", "pallas_interpret" (testing),
    or "lax".
    """
    import jax

    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    blocks = {}
    if impl == "auto":
        impl, plan = static_dispatch(
            q.shape, k.shape, v.shape, q.dtype,
            mask_is_none=mask is None, seq_offset=seq_offset)
        from bigdl_tpu.ops import autotune

        if autotune.enabled():
            decision = autotune.decide_attention(
                q.shape, k.shape, q.dtype, causal=causal,
                seq_offset=seq_offset, static_impl=impl, plan=plan,
                arrays=(q, k, v) if mask is None else None)
            if decision is not None:
                impl = decision["impl"]
                if decision.get("blocks"):
                    bq, bk, bkv, bqs = decision["blocks"]
                    blocks = dict(block_q=bq, block_k=bk,
                                  block_kv=bkv, block_qs=bqs)
    if impl in ("pallas", "pallas_interpret"):
        if mask is not None:
            raise ValueError(
                "the Pallas flash kernel has no explicit-mask support; "
                "use impl='lax'"
            )
        if not isinstance(seq_offset, int):
            raise ValueError(
                "the Pallas flash kernel needs a STATIC (python int) "
                "seq_offset; traced offsets (ring attention's hops) "
                "use impl='lax'"
            )
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               interpret=(impl == "pallas_interpret"),
                               seq_offset=seq_offset, **blocks)
    return _reference_attention(q, k, v, causal=causal, scale=scale,
                                mask=mask, seq_offset=seq_offset)
