"""Scaled-dot-product attention: lax reference + Pallas flash kernel.

The reference framework predates attention entirely (SURVEY.md §5
"long-context: absent") — this op is a *new* capability, the hot inner
op of the Transformer/long-context stack (nn/attention.py,
parallel/ring_attention.py).

Design for the MXU/VMEM (pallas_guide.md):

* the Pallas kernel is a classic flash attention: grid over
  (batch*heads, query blocks), ``lax.fori_loop`` over key blocks, online
  softmax with running max ``m`` and normalizer ``l`` kept in VMEM
  scratch so the (T, T) score matrix never materialises in HBM;
* block sizes are multiples of the fp32 (8, 128) tile, MXU-sized 128
  where the sequence allows;
* matmuls carry ``preferred_element_type=jnp.float32`` so bf16 inputs
  accumulate in fp32 on the MXU.

``dot_product_attention`` is the public entry.  ``impl="auto"`` is
measurement-driven (see the dispatcher): the lax reference wins
throughput on the 2026-07 toolchain at every length whose softmax
residuals fit, so auto takes lax below T=4096 and the Pallas kernel in
the long-context regime, where flash's O(T) residuals — (q, k, v,
out, logsumexp) instead of per-layer (B, H, T, T) — are the
difference between fitting and OOM.  Both paths are differentiable —
the Pallas path via ``jax.custom_vjp`` with blockwise backward
kernels that never materialize a (T, T) array in either direction.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax


# --------------------------------------------------------------------------
# lax reference implementation
# --------------------------------------------------------------------------


def _reference_attention(q, k, v, *, causal: bool, scale: float,
                         mask=None, seq_offset: int = 0):
    """Plain softmax(q k^T) v.  (B, H, Tq, D) x (B, H, Tk, D).

    ``seq_offset`` shifts query positions for causal masking — used by
    ring attention where the local query block starts at a nonzero
    absolute position.
    """
    import jax.numpy as jnp

    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        qpos = jnp.arange(tq)[:, None] + seq_offset
        kpos = jnp.arange(tk)[None, :]
        scores = jnp.where(qpos >= kpos, scores, -jnp.inf)
    if mask is not None:
        scores = jnp.where(mask, scores, -jnp.inf)
    # guard fully-masked rows (ring attention partial blocks): softmax of
    # all -inf must give zeros, not NaN
    row_max = jnp.max(scores, axis=-1, keepdims=True)
    row_max = jnp.where(jnp.isfinite(row_max), row_max, 0.0)
    unnorm = jnp.exp(scores - row_max)
    denom = jnp.sum(unnorm, axis=-1, keepdims=True)
    probs = unnorm / jnp.maximum(denom, 1e-30)
    out = jnp.einsum(
        "bhqk,bhkd->bhqd", probs, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


# --------------------------------------------------------------------------
# Pallas flash attention (TPU)
# --------------------------------------------------------------------------


def _mask_causal(s, qi, block_q, ki, block_k, seq_offset=0):
    """-inf the future positions of a (block_q, block_k) score tile at
    block coordinates (qi, ki); ``seq_offset`` (static) shifts the
    query positions — chunked causal attention where the local query
    block starts at a nonzero absolute position.  Single definition
    shared by the forward and both backward kernels so the mask
    convention can never desynchronize between them."""
    import jax.numpy as jnp
    from jax import lax

    qpos = seq_offset + qi * block_q + lax.broadcasted_iota(
        jnp.int32, s.shape, 0)
    kpos = ki * block_k + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(qpos >= kpos, s, -jnp.inf)


def _diag_kblocks(qi, block_q, block_k, seq_offset=0, kv_len=None):
    """Number of key blocks a causal q-block touches (through its
    diagonal at query offset ``seq_offset``), clamped to the kv
    extent; shared by the forward and dq kernels."""
    import jax.numpy as jnp
    from jax import lax

    nk = lax.div(seq_offset + (qi + 1) * block_q + block_k - 1, block_k)
    if kv_len is not None:
        nk = jnp.minimum(nk, kv_len // block_k)
    return nk


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                      block_k: int, scale: float, causal: bool,
                      seq_len: int, seq_offset: int = 0):
    """One (batch*head, q-block) program: stream key blocks, online
    softmax.  Refs are VMEM blocks: q (1, block_q, d), k/v (1, T, d).
    Also writes the per-row logsumexp (in scaled-score units) so the
    blockwise backward can reconstruct P = exp(s - lse) without a
    second softmax pass."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # (block_q, d)

    m0 = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    def body(ki, carry):
        m, l, acc = carry
        ks = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        vs = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, ks, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, block_k)
        if causal:
            s = _mask_causal(s, qi, block_q, ki, block_k, seq_offset)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # fully-masked rows keep m=-inf; use 0 shift there to avoid NaNs
        shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - shift[:, None])
        alpha = jnp.exp(jnp.where(jnp.isfinite(m), m - shift, -jnp.inf))
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, vs, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    if causal:
        # process key blocks up to and including the diagonal
        nk = _diag_kblocks(qi, block_q, block_k, seq_offset, seq_len)
        m, l, acc = lax.fori_loop(0, nk, body, (m0, l0, acc0))
    else:
        m, l, acc = lax.fori_loop(0, seq_len // block_k, body, (m0, l0, acc0))

    out = acc / jnp.maximum(l, 1e-30)[:, None]
    o_ref[0] = out.astype(o_ref.dtype)
    # lse rides as (1, T//block_q, block_q): Mosaic's block rule wants
    # the last two dims (8, 128)-divisible-or-full, which a (1, block_q)
    # row block violates.  The full plane is mapped for every j and
    # revisited (same block index), so each program writes only its row
    # and the block flushes once per batch*head.
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    lse_ref[0, pl.ds(qi, 1), :] = lse[None, :]


# the flash kernels map k and v as whole (1, Tk, d) VMEM blocks per
# program; cap their combined footprint well under the ~16 MB VMEM so
# double-buffering and the f32 accumulators still fit.  On-chip
# validated points: Tk=8192 at d=128 bf16 (4 MB).
_KV_VMEM_BUDGET = 8 * 1024 * 1024


def _kv_fits_vmem(tk: int, d: int, dtype) -> bool:
    import jax.numpy as jnp

    return 2 * tk * d * jnp.dtype(dtype).itemsize <= _KV_VMEM_BUDGET


def _pick_block(t: int, preferred: int = 128) -> int:
    for b in (preferred, 64, 32, 16, 8):
        if t % b == 0:
            return b
    return 0


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "interpret",
                              "seq_offset")
)
def flash_attention(q, k, v, *, causal: bool = False,
                    scale: Optional[float] = None, interpret: bool = False,
                    seq_offset: int = 0):
    """Pallas flash attention.  q (B, H, Tq, D) against k/v
    (B, H, Tk, D) — Tq and Tk each a multiple of 8, D anything (padded
    to 128 lanes by Mosaic).  ``seq_offset`` (STATIC int >= 0) places
    the query block at a global position for chunked causal
    attention: q covers absolute positions [seq_offset, seq_offset+Tq)
    of the kv sequence.

    Differentiable with a true blockwise backward: the forward saves
    (q, k, v, out, logsumexp) — O(T) extra — and the backward kernels
    (_flash_bwd_dq_kernel / _flash_bwd_dkv_kernel) rebuild the score
    tiles from the logsumexp, so no (Tq, Tk) array is ever
    materialized, as residual OR transient, in either direction.
    """
    if seq_offset < 0:
        raise ValueError("seq_offset must be >= 0")
    return _flash_attention_vjp(q, k, v, causal,
                                scale if scale is not None else q.shape[-1] ** -0.5,
                                interpret, seq_offset)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention_vjp(q, k, v, causal, scale, interpret, seq_offset):
    return _flash_forward(q, k, v, causal, scale, interpret,
                          seq_offset=seq_offset)


def _flash_forward(q, k, v, causal, scale, interpret, *,
                   with_lse: bool = False, seq_offset: int = 0):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    b, h, tq, d = q.shape
    tk = k.shape[2]
    block_q = _pick_block(tq)
    block_k = _pick_block(tk)
    if not block_q or not block_k or not _kv_fits_vmem(tk, d, k.dtype):
        # untileable T, or the whole-kv (1, Tk, d) blocks these kernels
        # stream per program would blow the VMEM budget: lax reference
        # (auto dispatch never lands here — its predicate mirrors this)
        out = _reference_attention(q, k, v, causal=causal, scale=scale,
                                   seq_offset=seq_offset)
        return (out, None) if with_lse else out

    kernel = functools.partial(
        _flash_fwd_kernel, block_k=block_k, scale=scale, causal=causal,
        seq_len=tk, seq_offset=seq_offset,
    )
    qr = q.reshape(b * h, tq, d)
    kr = k.reshape(b * h, tk, d)
    vr = v.reshape(b * h, tk, d)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, tq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, tk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, tk, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, tq // block_q, block_q),
                         lambda i, j: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, tq // block_q, block_q),
                                 jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr)
    out = out.reshape(b, h, tq, d)
    return (out, lse) if with_lse else out


# ---- blockwise backward (the true flash backward: no T^2 residuals,
# no T^2 transients — scores are rebuilt tile by tile from the saved
# logsumexp) ----


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                         dq_ref, *, block_k: int, scale: float,
                         causal: bool, seq_len: int, seq_offset: int = 0):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    qi = pl.program_id(1)
    qs = q_ref[0].astype(jnp.float32) * scale      # (bq, d)
    do = g_ref[0].astype(jnp.float32)              # (bq, d)
    lse = lse_ref[0, pl.ds(qi, 1), :][0]           # (bq,)
    dlt = delta_ref[0, pl.ds(qi, 1), :][0]         # (bq,)

    def body(ki, acc):
        ks = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        vs = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            qs, ks, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bq, bk)
        if causal:
            s = _mask_causal(s, qi, block_q, ki, block_k, seq_offset)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            do, vs, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bq, bk)
        ds = p * (dp - dlt[:, None])
        return acc + jax.lax.dot_general(
            ds, ks, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bq, d)

    if causal:
        nk = _diag_kblocks(qi, block_q, block_k, seq_offset, seq_len)
    else:
        nk = seq_len // block_k
    acc = lax.fori_loop(0, nk, body,
                        jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = (acc * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q: int, scale: float,
                          causal: bool, q_len: int, seq_offset: int = 0):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    block_k = k_ref.shape[1]
    d = k_ref.shape[2]
    kj = pl.program_id(1)
    ks = k_ref[0].astype(jnp.float32)              # (bk, d)
    vs = v_ref[0].astype(jnp.float32)              # (bk, d)

    def body(qi, carry):
        acc_dk, acc_dv = carry
        qs = q_ref[0, pl.ds(qi * block_q, block_q), :] \
            .astype(jnp.float32) * scale           # (bq, d)
        do = g_ref[0, pl.ds(qi * block_q, block_q), :] \
            .astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qi, 1), :][0]       # (bq,)
        dlt = delta_ref[0, pl.ds(qi, 1), :][0]
        s = jax.lax.dot_general(
            qs, ks, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bq, bk)
        if causal:
            s = _mask_causal(s, qi, block_q, kj, block_k, seq_offset)
        p = jnp.exp(s - lse[:, None])
        acc_dv = acc_dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bk, d)
        dp = jax.lax.dot_general(
            do, vs, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - dlt[:, None])
        acc_dk = acc_dk + jax.lax.dot_general(
            ds, qs, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bk, d)
        return acc_dk, acc_dv

    nq = q_len // block_q
    if causal:
        # first q block whose global rows reach this key block:
        # q0 = floor(max(kj*block_k - seq_offset, 0) / block_q)
        q0 = lax.div(jnp.maximum(kj * block_k - seq_offset, 0), block_q)
    else:
        q0 = 0
    z = jnp.zeros((block_k, d), jnp.float32)
    acc_dk, acc_dv = lax.fori_loop(q0, nq, body, (z, z))
    # qs carried the scale, so acc_dk is dL/dk exactly
    dk_ref[0] = acc_dk.astype(dk_ref.dtype)
    dv_ref[0] = acc_dv.astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal, scale, interpret,
                    seq_offset=0):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    b, h, tq, d = q.shape
    tk = k.shape[2]
    block_q = _pick_block(tq)
    block_k = _pick_block(tk)
    qr = q.reshape(b * h, tq, d)
    kr = k.reshape(b * h, tk, d)
    vr = v.reshape(b * h, tk, d)
    gr = g.reshape(b * h, tq, d)
    outr = out.reshape(b * h, tq, d)
    # delta_i = sum_d dO_i . O_i — one fused elementwise+reduce in XLA;
    # carried at the lse layout (bh, Tq//bq, bq), see the fwd kernel
    delta = jnp.sum(gr.astype(jnp.float32) * outr.astype(jnp.float32),
                    axis=-1).reshape(b * h, tq // block_q, block_q)

    lse_spec = pl.BlockSpec((1, tq // block_q, block_q),
                            lambda i, j: (i, 0, 0))
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=block_k,
                          scale=scale, causal=causal, seq_len=tk,
                          seq_offset=seq_offset),
        grid=(b * h, tq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, tk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, tk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            lse_spec,
            lse_spec,
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
        interpret=interpret,
    )(qr, kr, vr, gr, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q,
                          scale=scale, causal=causal, q_len=tq,
                          seq_offset=seq_offset),
        grid=(b * h, tk // block_k),
        in_specs=[
            pl.BlockSpec((1, tq, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, tq, d), lambda i, j: (i, 0, 0)),
            lse_spec,
            lse_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, tk, d), v.dtype),
        ],
        interpret=interpret,
    )(qr, kr, vr, gr, lse, delta)

    return (dq.reshape(b, h, tq, d), dk.reshape(b, h, tk, d),
            dv.reshape(b, h, tk, d))


def _flash_fwd_rule(q, k, v, causal, scale, interpret, seq_offset):
    out, lse = _flash_forward(q, k, v, causal, scale, interpret,
                              with_lse=True, seq_offset=seq_offset)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, scale, interpret, seq_offset, res, g):
    import jax

    q, k, v, out, lse = res
    if lse is None:
        # the forward fell back to the lax reference (untileable T):
        # recompute its vjp the same way
        def ref(q, k, v):
            return _reference_attention(q, k, v, causal=causal,
                                        scale=scale,
                                        seq_offset=seq_offset)

        _, vjp = jax.vjp(ref, q, k, v)
        return vjp(g)
    return _flash_backward(q, k, v, out, lse, g, causal, scale,
                           interpret, seq_offset)


_flash_attention_vjp.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# --------------------------------------------------------------------------
# public dispatcher
# --------------------------------------------------------------------------


def dot_product_attention(q, k, v, *, causal: bool = False, mask=None,
                          scale: Optional[float] = None, impl: str = "auto",
                          seq_offset: int = 0):
    """Attention entry point used by nn.MultiHeadAttention.

    q, k, v: (batch, heads, seq, head_dim).

    impl: "auto" (measured policy — lax below T=4096, the Pallas flash
    kernel on TPU in the long-context regime where lax's per-layer
    (B, H, T, T) residuals stop fitting), "pallas", "pallas_interpret"
    (testing), or "lax".
    """
    import jax

    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    t = q.shape[-2]
    if impl == "auto":
        on_tpu = jax.default_backend() == "tpu"
        tk = k.shape[-2]
        tiles = (
            mask is None
            and k.shape == v.shape and q.shape[:2] == k.shape[:2]
            and q.shape[-1] == k.shape[-1]
            and t >= 128 and t % 128 == 0
            and tk >= 128 and tk % 128 == 0
            and isinstance(seq_offset, int) and seq_offset >= 0
            and _kv_fits_vmem(tk, q.shape[-1], k.dtype)
        )
        # Measured on the 2026-07 toolchain (TransformerLM train step,
        # TPU v5 lite, ms/step): XLA's fused attention beats the Pallas
        # flash forward at every length that fits its residuals —
        # T=512: 59.3 lax vs 64.7 pallas; T=1024: 76.2 vs 80.2;
        # T=2048: 114.1 vs 124.6.  What flash buys on TPU is MEMORY:
        # under jax.grad the lax path saves (B, H, T, T) softmax
        # residuals for EVERY layer simultaneously — the long-context
        # cliff.  The flash path saves (q, k, v, out, lse) — O(T) —
        # and its blockwise backward kernels rebuild score tiles from
        # the logsumexp, so no (T, T) array exists in either direction.
        # So auto prefers lax until the quadratic-residual regime and
        # flips to the kernel there.  The residual is (B, H, Tq, Tk),
        # so the flip watches the PRODUCT — a 2048-query chunk against
        # a 32k kv is deep in the cliff even though Tq is small.
        impl = ("pallas" if (on_tpu and tiles and t * tk >= 4096 * 4096)
                else "lax")
    if impl in ("pallas", "pallas_interpret"):
        if mask is not None:
            raise ValueError(
                "the Pallas flash kernel has no explicit-mask support; "
                "use impl='lax'"
            )
        if not isinstance(seq_offset, int):
            raise ValueError(
                "the Pallas flash kernel needs a STATIC (python int) "
                "seq_offset; traced offsets (ring attention's hops) "
                "use impl='lax'"
            )
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               interpret=(impl == "pallas_interpret"),
                               seq_offset=seq_offset)
    return _reference_attention(q, k, v, causal=causal, scale=scale,
                                mask=mask, seq_offset=seq_offset)
