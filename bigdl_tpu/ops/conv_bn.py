"""Fused conv + BatchNorm-statistics Pallas kernels (1x1 and kxk).

BASELINE.md's measured analysis: after the BN normalize pass was folded
into the compute dtype, the remaining BN bandwidth tax on ResNet-50 is
the separate statistics pass — every training-mode BN re-reads its
input activation once to reduce per-channel mean/variance.  These
kernels compute the convolution on the MXU and accumulate the BN
statistics **in the conv epilogue** while the output tile is still in
VMEM: per-channel sums of (y - shift) and (y - shift)^2, shift being
the running mean (the same shifted single-pass formulation
``nn.BatchNormalization`` uses, see layers.py).  The activation is
then never re-read for statistics.

Two kernels:

* ``1x1`` — W (O,C) @ X (C,HW) per sample.  Grid (O-tiles, N,
  HW-tiles); O is padded to the tile multiple (zero weight rows give
  exactly-zero stats contributions) and HW-tiles beyond the true
  extent are masked out of the statistics, so ANY (O, HW) works — the
  r03 ``block_o`` / VMEM fallbacks are gone (VERDICT r3 weak #2).
* ``kxk`` (3x3 with pad=1, the other half of ResNet-50's BN inputs) —
  per (O-tile, sample) program over the spatially-padded image: k*k
  unrolled tap dots W_t (O,C) @ X_shifted (C, Ho*Wo) accumulating in
  VMEM, stride 1/2 via a reshape-parity trick (strided vector loads
  are avoided).  Output + stats written once.

Backward is analytic (jax.custom_vjp): with cotangents (gy, gs1, gs2),
  dy_eff = gy + gs1[c] + 2 (y - shift) gs2[c]
  (dx, dw) = vjp of the plain conv at dy_eff   — standard XLA dots /
conv grads; only the forward needs the hand kernel (the backward reads
the activation anyway, there is no second pass to save).
"""

from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp

_log = logging.getLogger(__name__)

# per-core VMEM working budget for tile selection: real VMEM is ~16MB
# on v4/v5e; leave headroom for double-buffering + compiler temporaries
_VMEM_BUDGET = 10 * 1024 * 1024

# trace-time fallback ledger (VERDICT r4 item 3): every silent
# `_reference` bail used to be invisible — a production shape quietly
# regressing to XLA would never show in the headline number.  Each bail
# now appends {reason, x_shape, w_shape, stride, pad} here (shapes are
# static, so this fires once per compile, not per step) and logs a
# warning.  tests/test_conv_bn_paths.py pins every ResNet-50 fused
# call site to the Pallas path via `kernel_path`.
FALLBACK_LOG: list = []


def _note_fallback(reason, x_shape, w_shape, stride, pad):
    rec = {
        "reason": reason,
        "x_shape": tuple(int(s) for s in x_shape),
        "w_shape": tuple(int(s) for s in w_shape),
        "stride": int(stride),
        "pad": int(pad),
    }
    FALLBACK_LOG.append(rec)
    _log.warning("conv_bn_stats fell back to XLA: %s", rec)


def _conv_ref(x, w, stride, pad):
    return jax.lax.conv_general_dilated(
        x, w, (stride, stride), [(pad, pad), (pad, pad)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        preferred_element_type=jnp.float32,
    )


def _reference(x, w, shift, stride, pad):
    """Plain-XLA reference: x (N,C,H,W), w (O,C,kh,kw), shift (O,) f32."""
    y = _conv_ref(x, w, stride, pad)
    yc = y - shift[None, :, None, None]
    s1 = jnp.sum(yc, axis=(0, 2, 3))
    s2 = jnp.sum(yc * yc, axis=(0, 2, 3))
    return y.astype(x.dtype), s1, s2


def _round_up(v: int, m: int) -> int:
    return -(-v // m) * m


# --------------------------------------------------------------------------
# 1x1 kernel: grid (O-tiles, N, HW-tiles)
# --------------------------------------------------------------------------


def _fwd_kernel_1x1(x_ref, w_ref, shift_ref, y_ref, s1_ref, s2_ref, *,
                    hw_total, block_hw):
    from jax.experimental import pallas as pl

    n = pl.program_id(1)
    hi = pl.program_id(2)
    x = x_ref[0]                      # (C, block_hw)
    w = w_ref[...]                    # (block_o, C)
    y = jax.lax.dot_general(
        w, x, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                 # (block_o, block_hw) f32
    yc = y - shift_ref[...][:, None]
    if hw_total % block_hw:
        # last HW tile is partial: mask padded columns out of the stats
        # (zero-padded x gives y=0 there, but yc = -shift != 0)
        valid = jnp.minimum(block_hw, hw_total - hi * block_hw)
        col = jax.lax.broadcasted_iota(jnp.int32, yc.shape, 1)
        yc = jnp.where(col < valid, yc, 0.0)
    p1 = jnp.sum(yc, axis=1)
    p2 = jnp.sum(yc * yc, axis=1)

    @pl.when((n == 0) & (hi == 0))
    def _init():
        s1_ref[...] = p1
        s2_ref[...] = p2

    @pl.when((n > 0) | (hi > 0))
    def _acc():
        s1_ref[...] += p1
        s2_ref[...] += p2

    y_ref[0] = y.astype(y_ref.dtype)


def _tiles_1x1(o: int, c: int, hw: int, xbytes: int):
    """Pick (block_o, block_hw) fitting the VMEM budget.  block_o is a
    multiple of 8 (sublane), block_hw of 128 (lane)."""
    block_o = min(256, _round_up(o, 8))
    block_hw = _round_up(hw, 128)
    while True:
        # 2x input tiles (double buffering) + f32 compute tile + output
        vmem = (2 * (c * block_hw + block_o * c) * xbytes
                + block_o * block_hw * (4 + xbytes))
        if vmem <= _VMEM_BUDGET:
            return block_o, block_hw
        if block_hw > 512:
            block_hw = _round_up(block_hw // 2, 128)
        elif block_o > 8:
            block_o = max(8, block_o // 2)
        else:
            return block_o, block_hw  # smallest tile; let it ride


def _fwd_1x1(x, w, shift, interpret):
    """x (N, C, H, W), w (O, C), shift (O,) f32 ->
    (y (N, O, H, W), s1 (O,) f32, s2 (O,) f32)."""
    from jax.experimental import pallas as pl

    n, c, h, wd = x.shape
    o = w.shape[0]
    hw = h * wd
    block_o, block_hw = _tiles_1x1(o, c, hw, x.dtype.itemsize)
    o_pad = _round_up(o, block_o)
    hw_pad = _round_up(hw, block_hw)
    x2 = x.reshape(n, c, hw)
    if hw_pad != hw:
        x2 = jnp.pad(x2, ((0, 0), (0, 0), (0, hw_pad - hw)))
    wp = w if o_pad == o else jnp.pad(w, ((0, o_pad - o), (0, 0)))
    sp = shift if o_pad == o else jnp.pad(shift, (0, o_pad - o))

    kern = functools.partial(_fwd_kernel_1x1, hw_total=hw,
                             block_hw=block_hw)
    y2, s1, s2 = pl.pallas_call(
        kern,
        grid=(o_pad // block_o, n, hw_pad // block_hw),
        in_specs=[
            pl.BlockSpec((1, c, block_hw), lambda oi, ni, hi: (ni, 0, hi)),
            pl.BlockSpec((block_o, c), lambda oi, ni, hi: (oi, 0)),
            pl.BlockSpec((block_o,), lambda oi, ni, hi: (oi,)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_o, block_hw),
                         lambda oi, ni, hi: (ni, oi, hi)),
            pl.BlockSpec((block_o,), lambda oi, ni, hi: (oi,)),
            pl.BlockSpec((block_o,), lambda oi, ni, hi: (oi,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, o_pad, hw_pad), x.dtype),
            jax.ShapeDtypeStruct((o_pad,), jnp.float32),
            jax.ShapeDtypeStruct((o_pad,), jnp.float32),
        ],
        interpret=interpret,
    )(x2, wp, sp)
    y2 = y2[:, :o, :hw]
    return y2.reshape(n, o, h, wd), s1[:o], s2[:o]


# --------------------------------------------------------------------------
# kxk kernel: grid (O-tiles, N), whole (padded) image per program
# --------------------------------------------------------------------------


def _fwd_kernel_kxk(x_ref, w_ref, shift_ref, y_ref, s1_ref, s2_ref, *,
                    k, stride, ho, wo):
    from jax.experimental import pallas as pl

    n = pl.program_id(1)
    xp = x_ref[0]                     # (C, Hp, Wp) spatially pre-padded
    c = xp.shape[0]
    block_o = w_ref.shape[0]          # w block: (block_o, k*k*C) tap-major
    taps = []
    for t in range(k * k):
        dy, dx = t // k, t % k
        if stride == 1:
            xs = xp[:, dy:dy + ho, dx:dx + wo]
        else:
            # stride-2 extraction without strided loads: slice an even
            # extent, split the parity axis by reshape, keep phase 0
            xs = xp[:, dy:dy + 2 * ho, dx:dx + 2 * wo]
            xs = xs.reshape(c, ho, 2, wo, 2)[:, :, 0, :, 0]
        taps.append(xs.reshape(c, ho * wo))
    # tap-major im2col in VMEM: ONE (block_o, k*k*C) @ (k*k*C, HW) MXU
    # dot instead of k*k small K=C dots — k*k-fold deeper contraction
    # fills the 128-lane systolic array at every ResNet channel width
    xcat = jnp.concatenate(taps, axis=0)
    acc = jax.lax.dot_general(
        w_ref[...], xcat, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    yc = acc - shift_ref[...][:, None]
    p1 = jnp.sum(yc, axis=1)
    p2 = jnp.sum(yc * yc, axis=1)

    @pl.when(n == 0)
    def _init():
        s1_ref[...] = p1
        s2_ref[...] = p2

    @pl.when(n > 0)
    def _acc():
        s1_ref[...] += p1
        s2_ref[...] += p2

    y_ref[0] = acc.astype(y_ref.dtype)


def _kxk_plan(c: int, h: int, wd: int, o: int, k: int, stride: int,
              pad: int, xbytes: int):
    """Static kxk feasibility + tile plan.  Returns
    (block_o, ho, wo, reason) — ``reason`` is None when the Pallas
    kernel applies, else a human-readable bail cause (the kernel then
    uses the XLA reference path)."""
    hp, wp_ = h + 2 * pad, wd + 2 * pad
    ho = (hp - k) // stride + 1
    wo = (wp_ - k) // stride + 1

    # stride-2 reshape trick needs dy + 2*ho <= Hp for dy <= k-1;
    # guaranteed for ResNet shapes, bail to reference otherwise
    if stride not in (1, 2):
        return None, ho, wo, f"stride {stride} not in (1, 2)"
    if stride == 2 and (k - 1 + 2 * ho > hp or k - 1 + 2 * wo > wp_):
        return None, ho, wo, "stride-2 reshape-parity bounds"

    block_o = min(256, _round_up(o, 8))
    while block_o > 8:
        # padded image and weight block (both grid-varying, so Pallas
        # double-buffers them) + tap-concat im2col + f32 acc/output
        vmem = (2 * c * hp * wp_ * xbytes + k * k * c * ho * wo * xbytes
                + 2 * k * k * block_o * c * xbytes
                + block_o * ho * wo * (4 + xbytes))
        if vmem <= _VMEM_BUDGET:
            break
        block_o //= 2
    if (2 * c * hp * wp_ + k * k * c * ho * wo) * xbytes > _VMEM_BUDGET:
        return None, ho, wo, "padded image + im2col exceed VMEM budget"
    return block_o, ho, wo, None


def _fwd_kxk(x, w, shift, stride, pad, interpret):
    """x (N,C,H,W), w (O,C,k,k), shift (O,) f32 ->
    (y (N,O,Ho,Wo), s1, s2).  Torch-style symmetric padding."""
    from jax.experimental import pallas as pl

    n, c, h, wd = x.shape
    o, _, k, _ = w.shape
    hp, wp_ = h + 2 * pad, wd + 2 * pad

    block_o, ho, wo, reason = _kxk_plan(c, h, wd, o, k, stride, pad,
                                        x.dtype.itemsize)
    if reason is not None:
        _note_fallback(reason, x.shape, w.shape, stride, pad)
        return _reference(x, w, shift, stride, pad)
    o_pad = _round_up(o, block_o)

    xpad = jnp.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    # tap-major flattened weights: (O, k*k*C) matching the kernel's
    # im2col row order [tap0 c-rows, tap1 c-rows, ...]
    wt = jnp.transpose(w, (0, 2, 3, 1)).reshape(o, k * k * c)
    if o_pad != o:
        wt = jnp.pad(wt, ((0, o_pad - o), (0, 0)))
        shift = jnp.pad(shift, (0, o_pad - o))

    kern = functools.partial(_fwd_kernel_kxk, k=k, stride=stride,
                             ho=ho, wo=wo)
    y2, s1, s2 = pl.pallas_call(
        kern,
        grid=(o_pad // block_o, n),
        in_specs=[
            pl.BlockSpec((1, c, hp, wp_), lambda oi, ni: (ni, 0, 0, 0)),
            pl.BlockSpec((block_o, k * k * c), lambda oi, ni: (oi, 0)),
            pl.BlockSpec((block_o,), lambda oi, ni: (oi,)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_o, ho * wo), lambda oi, ni: (ni, oi, 0)),
            pl.BlockSpec((block_o,), lambda oi, ni: (oi,)),
            pl.BlockSpec((block_o,), lambda oi, ni: (oi,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, o_pad, ho * wo), x.dtype),
            jax.ShapeDtypeStruct((o_pad,), jnp.float32),
            jax.ShapeDtypeStruct((o_pad,), jnp.float32),
        ],
        interpret=interpret,
    )(xpad, wt, shift)
    return y2[:, :o].reshape(n, o, ho, wo), s1[:o], s2[:o]


# --------------------------------------------------------------------------
# custom_vjp wrapper (shared by both kernels)
# --------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _conv_bn_stats_vjp(x, w, shift, stride, pad, interpret):
    if w.shape[2] == 1 and w.shape[3] == 1 and pad == 0:
        if stride != 1:
            x = x[:, :, ::stride, ::stride]
        return _fwd_1x1(x, w[:, :, 0, 0], shift, interpret)
    return _fwd_kxk(x, w, shift, stride, pad, interpret)


def _fwd_rule(x, w, shift, stride, pad, interpret):
    out = _conv_bn_stats_vjp(x, w, shift, stride, pad, interpret)
    y, s1, _ = out
    return out, (x, w, y, shift, s1)


def _bwd_rule(stride, pad, interpret, res, cts):
    x, w, y, shift, s1 = res
    gy, gs1, gs2 = cts
    yc = y.astype(jnp.float32) - shift[None, :, None, None]
    gy_eff = (
        gy.astype(jnp.float32)
        + gs1[None, :, None, None]
        + 2.0 * yc * gs2[None, :, None, None]
    ).astype(x.dtype)

    # same-dtype conv (no preferred_element_type): its transpose would
    # otherwise pair an f32 cotangent with bf16 operands and fail; the
    # MXU accumulates the bf16 grads in f32 regardless
    def _conv_same_dtype(x_, w_):
        return jax.lax.conv_general_dilated(
            x_, w_, (stride, stride), [(pad, pad), (pad, pad)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )

    _, vjp = jax.vjp(_conv_same_dtype, x, w)
    dx, dw = vjp(gy_eff)
    # shift is normally running-state (no grad requested), but the
    # cotangent is cheap and exact: ds1/dshift = -n, ds2/dshift = -2 s1
    n = y.shape[0] * y.shape[2] * y.shape[3]
    gshift = -float(n) * gs1 - 2.0 * s1 * gs2
    return dx, dw, gshift


_conv_bn_stats_vjp.defvjp(_fwd_rule, _bwd_rule)


def conv_bn_stats(x, w, shift, *, stride: int = 1, pad: int = 0,
                  interpret: bool = False):
    """Fused conv + centered BN statistics.

    x (N, C, H, W); w (O, C, kh, kw) or (O, C) for 1x1; shift (O,) f32
    — typically the BN running mean.  Returns (y, s1, s2) with
    s1 = sum(y - shift) and s2 = sum((y - shift)^2) per channel in f32.
    Supports k=1 (stride subsampling outside the kernel) and odd k with
    symmetric torch-style padding at stride 1 or 2.
    """
    if w.ndim == 2:
        w = w[:, :, None, None]
    shift = shift.astype(jnp.float32)
    # compiled Mosaic kernels exist only on TPU; everything else
    # (CPU tests, the 8-virtual-device mesh, a hypothetical GPU box —
    # whose parallel grid would race the s1/s2 accumulation) runs the
    # interpreter
    interpret = interpret or jax.default_backend() != "tpu"
    return _conv_bn_stats_vjp(x, w, shift, stride, pad, interpret)


def conv1x1_bn_stats(x, w, shift, *, stride: int = 1,
                     interpret: bool = False):
    """1x1 fast path, kept as the r02 API: w (O, C)."""
    return conv_bn_stats(x, w, shift, stride=stride, pad=0,
                         interpret=interpret)


def kernel_path(x_shape, w_shape, *, stride: int = 1, pad: int = 0,
                itemsize: int = 2) -> str:
    """Which path ``conv_bn_stats`` takes for these STATIC shapes —
    ``"pallas_1x1"``, ``"pallas_kxk"``, or ``"xla:<reason>"``.

    Mirrors the exact dispatch in ``_conv_bn_stats_vjp`` / ``_kxk_plan``
    without tracing anything, so tests can pin every production call
    site to the Pallas path (VERDICT r4 item 3).  ``itemsize`` is the
    activation dtype's byte width (2 = bf16, the training compute
    dtype).  Decisions are batch-independent: the kxk grid iterates
    samples and the 1x1 kernel tiles (O, HW), so a shape proven at one
    batch holds at any batch.
    """
    n, c, h, wd = (int(s) for s in x_shape)
    w_shape = tuple(int(s) for s in w_shape)
    o = w_shape[0]
    k = 1 if len(w_shape) == 2 else w_shape[2]
    if k == 1 and (len(w_shape) == 2 or w_shape[3] == 1) and pad == 0:
        return "pallas_1x1"  # handles any (O, HW): padded + masked tiles
    _, _, _, reason = _kxk_plan(c, h, wd, o, k, stride, pad, itemsize)
    return "pallas_kxk" if reason is None else f"xla:{reason}"
