"""Fused 1x1-conv + BatchNorm-statistics Pallas kernel.

BASELINE.md's measured analysis: after the BN normalize pass was folded
into the compute dtype, the remaining BN bandwidth tax on ResNet-50 is
the separate statistics pass — every training-mode BN re-reads its
input activation once to reduce per-channel mean/variance.  Half of
ResNet-50's FLOPs flow through 1x1 convolutions whose outputs feed
straight into BN, so this kernel computes the 1x1 conv as an MXU
matmul (W (O,C) @ X (C,HW) per sample) and accumulates the BN
statistics **in the conv epilogue** while the output tile is still in
VMEM: per-channel sums of (y - shift) and (y - shift)^2, shift being
the running mean (the same shifted single-pass formulation
``nn.BatchNormalization`` uses, see layers.py).  The activation is
then never re-read for statistics.

Backward is analytic (jax.custom_vjp): with cotangents (gy, gs1, gs2),
  dy_eff = gy + gs1[c] + 2 (y - shift) gs2[c]
  dx     = W^T dy_eff          (one matmul)
  dW     = dy_eff X^T          (one matmul)
— standard XLA dots; only the forward needs the hand kernel (the
backward reads the activation anyway, there is no second pass to
save).

Grid: (O-tiles outer, N inner) so each stats tile is revisited by
consecutive programs and accumulates in VMEM, written back once.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _reference(x2, w, shift):
    """Plain-XLA reference: x2 (N, C, HW), w (O, C), shift (O,) f32."""
    y = jnp.einsum(
        "oc,nch->noh", w, x2, preferred_element_type=jnp.float32
    )
    yc = y - shift[None, :, None]
    s1 = jnp.sum(yc, axis=(0, 2))
    s2 = jnp.sum(yc * yc, axis=(0, 2))
    return y.astype(x2.dtype), s1, s2


def _fwd_kernel(x_ref, w_ref, shift_ref, y_ref, s1_ref, s2_ref):
    from jax.experimental import pallas as pl

    n = pl.program_id(1)
    x = x_ref[0]                      # (C, HW)
    w = w_ref[...]                    # (block_o, C)
    y = jax.lax.dot_general(
        w, x, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                 # (block_o, HW) f32
    yc = y - shift_ref[...][:, None]
    p1 = jnp.sum(yc, axis=1)
    p2 = jnp.sum(yc * yc, axis=1)

    @pl.when(n == 0)
    def _init():
        s1_ref[...] = p1
        s2_ref[...] = p2

    @pl.when(n > 0)
    def _acc():
        s1_ref[...] += p1
        s2_ref[...] += p2

    y_ref[0] = y.astype(y_ref.dtype)


def _pick_block_o(o: int) -> int:
    for b in (256, 128, 64, 32, 16, 8):
        if o % b == 0:
            return b
    return 0


def _fwd(x, w, shift, interpret):
    """x (N, C, H, W), w (O, C), shift (O,) f32 ->
    (y (N, O, H, W), s1 (O,) f32, s2 (O,) f32)."""
    from jax.experimental import pallas as pl

    n, c, h, wd = x.shape
    o = w.shape[0]
    hw = h * wd
    block_o = _pick_block_o(o)
    x2 = x.reshape(n, c, hw)
    if block_o == 0 or hw * max(c, block_o) * 4 > 6 * 1024 * 1024:
        y, s1, s2 = _reference(x2, w, shift)
        return y.reshape(n, o, h, wd), s1, s2

    y2, s1, s2 = pl.pallas_call(
        _fwd_kernel,
        grid=(o // block_o, n),
        in_specs=[
            pl.BlockSpec((1, c, hw), lambda oi, ni: (ni, 0, 0)),
            pl.BlockSpec((block_o, c), lambda oi, ni: (oi, 0)),
            pl.BlockSpec((block_o,), lambda oi, ni: (oi,)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_o, hw), lambda oi, ni: (ni, oi, 0)),
            pl.BlockSpec((block_o,), lambda oi, ni: (oi,)),
            pl.BlockSpec((block_o,), lambda oi, ni: (oi,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, o, hw), x.dtype),
            jax.ShapeDtypeStruct((o,), jnp.float32),
            jax.ShapeDtypeStruct((o,), jnp.float32),
        ],
        interpret=interpret,
    )(x2, w, shift)
    return y2.reshape(n, o, h, wd), s1, s2


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _conv1x1_bn_stats_vjp(x, w, shift, interpret):
    return _fwd(x, w, shift, interpret)


def _fwd_rule(x, w, shift, interpret):
    out = _fwd(x, w, shift, interpret)
    y, s1, _ = out
    return out, (x, w, y, shift, s1)


def _bwd_rule(interpret, res, cts):
    x, w, y, shift, s1 = res
    gy, gs1, gs2 = cts
    yc = y.astype(jnp.float32) - shift[None, :, None, None]
    gy_eff = (
        gy.astype(jnp.float32)
        + gs1[None, :, None, None]
        + 2.0 * yc * gs2[None, :, None, None]
    ).astype(x.dtype)
    dx = jnp.einsum(
        "nohw,oc->nchw", gy_eff, w, preferred_element_type=jnp.float32
    ).astype(x.dtype)
    dw = jnp.einsum(
        "nohw,nchw->oc", gy_eff, x, preferred_element_type=jnp.float32
    ).astype(w.dtype)
    # shift is normally running-state (no grad requested), but the
    # cotangent is cheap and exact: ds1/dshift = -n, ds2/dshift = -2 s1
    n = y.shape[0] * y.shape[2] * y.shape[3]
    gshift = -float(n) * gs1 - 2.0 * s1 * gs2
    return dx, dw, gshift


_conv1x1_bn_stats_vjp.defvjp(_fwd_rule, _bwd_rule)


def conv1x1_bn_stats(x, w, shift, *, stride: int = 1,
                     interpret: bool = False):
    """Fused 1x1 conv + centered BN statistics.

    x (N, C, H, W); w (O, C); shift (O,) f32 — typically the BN running
    mean.  ``stride`` subsamples the input first (a strided 1x1 conv
    reads only the kept positions; the slice is differentiable and
    outside the custom_vjp).  Returns (y, s1, s2) with
    s1 = sum(y - shift) and s2 = sum((y - shift)^2) per channel in f32.
    """
    if stride != 1:
        x = x[:, :, ::stride, ::stride]
    shift = shift.astype(jnp.float32)
    # compiled Mosaic kernels exist only on TPU; everything else
    # (CPU tests, the 8-virtual-device mesh, a hypothetical GPU box —
    # whose parallel grid would race the s1/s2 accumulation) runs the
    # interpreter
    interpret = interpret or jax.default_backend() != "tpu"
    return _conv1x1_bn_stats_vjp(x, w, shift, interpret)
