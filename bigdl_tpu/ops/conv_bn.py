"""Fused conv + BatchNorm-statistics Pallas kernels (1x1 and kxk).

BASELINE.md's measured analysis: after the BN normalize pass was folded
into the compute dtype, the remaining BN bandwidth tax on ResNet-50 is
the separate statistics pass — every training-mode BN re-reads its
input activation once to reduce per-channel mean/variance.  These
kernels compute the convolution on the MXU and accumulate the BN
statistics **in the conv epilogue** while the output tile is still in
VMEM: per-channel sums of (y - shift) and (y - shift)^2, shift being
the running mean (the same shifted single-pass formulation
``nn.BatchNormalization`` uses, see layers.py).  The activation is
then never re-read for statistics.

Two kernels:

* ``1x1`` — W (O,C) @ X (C,HW) per sample.  Grid (O-tiles, N,
  HW-tiles); O is padded to the tile multiple (zero weight rows give
  exactly-zero stats contributions) and HW-tiles beyond the true
  extent are masked out of the statistics, so ANY (O, HW) works — the
  r03 ``block_o`` / VMEM fallbacks are gone (VERDICT r3 weak #2).
* ``kxk`` (3x3 with pad=1, the other half of ResNet-50's BN inputs) —
  per (O-tile, sample) program over the FLATTENED spatially-padded
  image (C, Hp*Wp + k - 1): each tap is a lane-shifted 2-D slice, the
  k*k slices concatenate along sublanes into a tap-major im2col
  feeding one deep (block_o, k*k*C) @ (k*k*C, Ho*Wp) MXU dot; pad
  lanes are masked from the stats and sliced off by the caller.
  Pure-2-D because the 2026-07 Mosaic rejects 3-D vector shape casts
  (the r04 kernel's reshape died in infer-vector-layout).  Stride-2
  sites (the three ResNet stage-transition 3x3s) reach the SAME
  kernel through a space-to-depth rewrite outside the kernel
  (:func:`_s2d_rewrite`): the padded image's 2x2 phase blocks become
  4C channels and the kxk stride-2 conv becomes an equivalent
  (k//2+1)x(k//2+1) stride-1 conv with zero-scattered weights — plain
  XLA reshapes/transposes feeding the lane-shift kernel, no lane
  gathers (which this Mosaic has no layout for).  Strides > 2 still
  take the XLA reference path.

Backward is analytic (jax.custom_vjp): with cotangents (gy, gs1, gs2),
  dy_eff = gy + gs1[c] + 2 (y - shift) gs2[c]
  (dx, dw) = vjp of the plain conv at dy_eff   — standard XLA dots /
conv grads; only the forward needs the hand kernel (the backward reads
the activation anyway, there is no second pass to save).
"""

from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp
from bigdl_tpu.obs import names

_log = logging.getLogger(__name__)

# per-core VMEM working budget for tile selection: real VMEM is ~16MB
# on v4/v5e; leave headroom for double-buffering + compiler temporaries
_VMEM_BUDGET = 10 * 1024 * 1024

# trace-time fallback ledger (VERDICT r4 item 3): every silent
# `_reference` bail used to be invisible — a production shape quietly
# regressing to XLA would never show in the headline number.  Each bail
# now appends {reason, x_shape, w_shape, stride, pad} here (shapes are
# static, so this fires once per compile, not per step) and logs a
# warning.  tests/test_conv_bn_paths.py pins every ResNet-50 fused
# call site to the Pallas path via `kernel_path`.
FALLBACK_LOG: list = []


def _note_fallback(reason, x_shape, w_shape, stride, pad):
    rec = {
        "reason": reason,
        "x_shape": tuple(int(s) for s in x_shape),
        "w_shape": tuple(int(s) for s in w_shape),
        "stride": int(stride),
        "pad": int(pad),
    }
    FALLBACK_LOG.append(rec)
    _log.warning("conv_bn_stats fell back to XLA: %s", rec)
    # production visibility (round-5 ADVICE): a fused model silently
    # mixing Pallas and XLA dispatch — e.g. a VMEM-infeasible megapixel
    # site, or a stride-3 conv — must show up in the metrics scrape and
    # the trace, not only in the in-process test-harness list.  Fires
    # at trace time (shapes are static), so once per compile, and is
    # guarded: telemetry must never sink a kernel dispatch.
    try:
        from bigdl_tpu import obs

        k = rec["w_shape"][2] if len(rec["w_shape"]) > 2 else 1
        site = f"conv_bn_k{k}s{rec['stride']}"
        obs.get_registry().counter(
            names.KERNEL_FALLBACKS_TOTAL,
            "Fused-kernel call sites that fell back to the XLA "
            "reference path, by site (trace-time, once per compile)",
            labels=("site",)).labels(site=site).inc()
        obs.get_tracer().event("kernel.fallback", site=site, **rec)
    except Exception:  # noqa: BLE001 — never break the dispatch
        pass


def _conv_ref(x, w, stride, pad):
    return jax.lax.conv_general_dilated(
        x, w, (stride, stride), [(pad, pad), (pad, pad)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        preferred_element_type=jnp.float32,
    )


def _reference(x, w, shift, stride, pad):
    """Plain-XLA reference: x (N,C,H,W), w (O,C,kh,kw), shift (O,) f32."""
    y = _conv_ref(x, w, stride, pad)
    yc = y - shift[None, :, None, None]
    s1 = jnp.sum(yc, axis=(0, 2, 3))
    s2 = jnp.sum(yc * yc, axis=(0, 2, 3))
    return y.astype(x.dtype), s1, s2


def _round_up(v: int, m: int) -> int:
    return -(-v // m) * m


# --------------------------------------------------------------------------
# 1x1 kernel: grid (O-tiles, N, HW-tiles)
# --------------------------------------------------------------------------


def _fwd_kernel_1x1(x_ref, w_ref, shift_ref, y_ref, s1_ref, s2_ref, *,
                    hw_total, block_hw):
    # shift/s1/s2 ride as 2-D (1, block_o): 1-D refs trip XLA/Mosaic
    # layout disagreements on the 2026-07 toolchain ("XLA layout
    # {0:T(512)} does not match Mosaic layout {0:T(256)} for f32[512]")
    from jax.experimental import pallas as pl

    n = pl.program_id(1)
    hi = pl.program_id(2)
    x = x_ref[0]                      # (C, block_hw)
    w = w_ref[...]                    # (block_o, C)
    y = jax.lax.dot_general(
        w, x, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                 # (block_o, block_hw) f32
    yc = y - shift_ref[0][:, None]
    if hw_total % block_hw:
        # last HW tile is partial: mask padded columns out of the stats
        # (zero-padded x gives y=0 there, but yc = -shift != 0)
        valid = jnp.minimum(block_hw, hw_total - hi * block_hw)
        col = jax.lax.broadcasted_iota(jnp.int32, yc.shape, 1)
        yc = jnp.where(col < valid, yc, 0.0)
    p1 = jnp.sum(yc, axis=1)
    p2 = jnp.sum(yc * yc, axis=1)

    @pl.when((n == 0) & (hi == 0))
    def _init():
        s1_ref[0] = p1
        s2_ref[0] = p2

    @pl.when((n > 0) | (hi > 0))
    def _acc():
        s1_ref[0] += p1
        s2_ref[0] += p2

    y_ref[0] = y.astype(y_ref.dtype)


def _tiles_1x1(o: int, c: int, hw: int, xbytes: int,
               block_o_hint: int = 0):
    """Pick (block_o, block_hw) fitting the VMEM budget.  block_o is a
    multiple of 8 (sublane), block_hw of 128 (lane).
    ``block_o_hint`` caps the O-tile (the auto-tuner's knob)."""
    block_o = min(block_o_hint or 256, _round_up(o, 8))
    block_o = max(8, block_o - block_o % 8)
    block_hw = _round_up(hw, 128)
    while True:
        # 2x input tiles (double buffering) + f32 compute tile + output
        vmem = (2 * (c * block_hw + block_o * c) * xbytes
                + block_o * block_hw * (4 + xbytes))
        if vmem <= _VMEM_BUDGET:
            return block_o, block_hw
        if block_hw > 512:
            block_hw = _round_up(block_hw // 2, 128)
        elif block_o > 8:
            block_o = max(8, block_o // 2)
        else:
            return block_o, block_hw  # smallest tile; let it ride


def _fwd_1x1(x, w, shift, interpret, block_o_hint: int = 0):
    """x (N, C, H, W), w (O, C), shift (O,) f32 ->
    (y (N, O, H, W), s1 (O,) f32, s2 (O,) f32)."""
    from jax.experimental import pallas as pl

    n, c, h, wd = x.shape
    o = w.shape[0]
    hw = h * wd
    block_o, block_hw = _tiles_1x1(o, c, hw, x.dtype.itemsize,
                                   block_o_hint)
    o_pad = _round_up(o, block_o)
    hw_pad = _round_up(hw, block_hw)
    x2 = x.reshape(n, c, hw)
    if hw_pad != hw:
        x2 = jnp.pad(x2, ((0, 0), (0, 0), (0, hw_pad - hw)))
    wp = w if o_pad == o else jnp.pad(w, ((0, o_pad - o), (0, 0)))
    sp = (shift if o_pad == o
          else jnp.pad(shift, (0, o_pad - o)))[None, :]

    kern = functools.partial(_fwd_kernel_1x1, hw_total=hw,
                             block_hw=block_hw)
    y2, s1, s2 = pl.pallas_call(
        kern,
        grid=(o_pad // block_o, n, hw_pad // block_hw),
        in_specs=[
            pl.BlockSpec((1, c, block_hw), lambda oi, ni, hi: (ni, 0, hi)),
            pl.BlockSpec((block_o, c), lambda oi, ni, hi: (oi, 0)),
            pl.BlockSpec((1, block_o), lambda oi, ni, hi: (0, oi)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_o, block_hw),
                         lambda oi, ni, hi: (ni, oi, hi)),
            pl.BlockSpec((1, block_o), lambda oi, ni, hi: (0, oi)),
            pl.BlockSpec((1, block_o), lambda oi, ni, hi: (0, oi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, o_pad, hw_pad), x.dtype),
            jax.ShapeDtypeStruct((1, o_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, o_pad), jnp.float32),
        ],
        interpret=interpret,
    )(x2, wp, sp)
    y2 = y2[:, :o, :hw]
    return y2.reshape(n, o, h, wd), s1[0, :o], s2[0, :o]


# --------------------------------------------------------------------------
# kxk kernel: grid (O-tiles, N), whole (padded) image per program
# --------------------------------------------------------------------------


def _fwd_kernel_kxk(x_ref, w_ref, shift_ref, y_ref, s1_ref, s2_ref,
                    xcat_ref, *, k, wp_, ho, wo):
    """Pure-2-D formulation for the 2026-07 Mosaic (which rejects 3-D
    vector shape casts — the r04 kernel's ``(C,Ho,Wo)->(C,Ho*Wo)``
    reshape died with "infer-vector-layout: unsupported shape cast").

    The image block arrives FLATTENED: (C, Hp*Wp + k - 1), row-major
    padded rows of width Wp.  For output (r, j) at flat index r*Wp + j,
    tap (dy, dx) reads flat index (r+dy)*Wp + j + dx — a plain 2-D
    lane-shifted slice ``x[:, dy*Wp + dx :][:Ho*Wp]``.  The k*k shifted
    slices are STORED into a VMEM scratch to build the tap-major im2col
    (k*k*C, Ho*Wp) — stores materialize the scratch's offset-0 layout,
    the relayout mechanism this Mosaic does implement (a value-level
    concatenate of the slices dies with "offset mismatch on non-concat
    dimension"; scripts/kxk_probe.py measures the candidates) — feeding
    ONE deep MXU dot, exactly like the r04 design but with no 3-D
    shapes anywhere.  Lanes j in [Wo, Wp) are pad columns: their values
    are convolutions at invalid offsets — masked out of the statistics
    here, sliced away by the caller (the slice fuses into the
    consumer's normalize pass).  Stride 1 only: stride 2 needs lane
    gathers this Mosaic has no layout for, so those sites take the XLA
    reference path (``kernel_path`` reports it)."""
    from jax.experimental import pallas as pl

    n = pl.program_id(1)
    xp = x_ref[0]                     # (C, Hp*Wp + k - 1) flat padded
    c = xp.shape[0]
    for t in range(k * k):
        dy, dx = t // k, t % k
        start = dy * wp_ + dx
        xcat_ref[t * c:(t + 1) * c, :] = xp[:, start:start + ho * wp_]
    # tap-major im2col in VMEM: ONE (block_o, k*k*C) @ (k*k*C, Ho*Wp)
    # MXU dot instead of k*k small K=C dots — k*k-fold deeper
    # contraction fills the 128-lane systolic array at every ResNet
    # channel width
    acc = jax.lax.dot_general(
        w_ref[...], xcat_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                 # (block_o, Ho*Wp) f32
    yc = acc - shift_ref[0][:, None]
    # statistics: only lanes with (flat % Wp) < Wo are real outputs
    col = jax.lax.broadcasted_iota(jnp.int32, yc.shape, 1)
    yc = jnp.where(col % wp_ < wo, yc, 0.0)
    p1 = jnp.sum(yc, axis=1)
    p2 = jnp.sum(yc * yc, axis=1)

    @pl.when(n == 0)
    def _init():
        s1_ref[0] = p1
        s2_ref[0] = p2

    @pl.when(n > 0)
    def _acc():
        s1_ref[0] += p1
        s2_ref[0] += p2

    y_ref[0] = acc.astype(y_ref.dtype)


def _kxk_plan(c: int, h: int, wd: int, o: int, k: int, stride: int,
              pad: int, xbytes: int, block_o_hint: int = 0):
    """Static kxk feasibility + tile plan.  Returns
    (block_o, ho, wo, reason) — ``reason`` is None when the Pallas
    kernel applies, else a human-readable bail cause (the kernel then
    uses the XLA reference path).  ``block_o_hint`` caps the O-tile
    search (the auto-tuner's knob; 0 = budget-derived)."""
    hp, wp_ = h + 2 * pad, wd + 2 * pad
    ho = (hp - k) // stride + 1
    wo = (wp_ - k) // stride + 1

    if stride == 2:
        # space-to-depth rewrite (_s2d_rewrite): the stride-2 conv is
        # exactly a (k//2+1)x(k//2+1) stride-1 conv over the 4C-channel
        # phase image, so feasibility is the REWRITTEN problem's.  The
        # rewritten output extent equals the original's (ho, wo).
        kb = k // 2 + 1
        hb, wb = ho + kb - 1, wo + kb - 1
        block_o, _, _, reason = _kxk_plan(4 * c, hb, wb, o, kb, 1, 0,
                                          xbytes, block_o_hint)
        if reason is not None:
            reason = f"s2d: {reason}"
        return block_o, ho, wo, reason
    # the pure-2-D kernel maps tap (dy, dx) to a lane-shifted slice of
    # the flattened padded image, which only exists for stride 1
    # (stride 2 is rewritten to stride 1 above; higher strides would
    # need lane gathers the 2026-07 Mosaic has no layout for)
    if stride != 1:
        return None, ho, wo, f"stride {stride} != 1 (lane-shift kernel)"

    block_o = min(block_o_hint or 256, _round_up(o, 8))
    block_o = max(8, block_o - block_o % 8)
    while block_o > 8:
        # flat padded image block (grid-varying: double-buffered) +
        # tap-concat im2col at padded width + weights + f32 acc/output
        vmem = (2 * c * (hp * wp_ + k - 1) * xbytes
                + k * k * c * ho * wp_ * xbytes
                + 2 * k * k * block_o * c * xbytes
                + block_o * ho * wp_ * (4 + xbytes))
        if vmem <= _VMEM_BUDGET:
            break
        block_o //= 2
    if (2 * c * (hp * wp_ + k - 1) + k * k * c * ho * wp_) * xbytes \
            > _VMEM_BUDGET:
        return None, ho, wo, "padded image + im2col exceed VMEM budget"
    return block_o, ho, wo, None


def _s2d_rewrite(x, w, pad):
    """Space-to-depth rewrite of a kxk STRIDE-2 conv as an exactly
    equivalent stride-1 conv the lane-shift kernel can run.

    The padded image's 2x2 phase blocks become 4C channels
    (channel order ``(py*2 + px) * C + c``) and tap (dy, dx) of the
    original kernel lands at block offset (dy//2, dx//2), phase
    (dy%2, dx%2) of a (k//2+1)^2 block-space kernel — every other
    entry of the scattered weight is zero.  Output (r, j) of the
    rewritten conv reads padded pixels (2r+dy, 2j+dx): the stride-2
    conv, value for value, BN statistics included.  All plain XLA
    reshapes/transposes outside the kernel; the backward never sees
    any of it (the custom vjp differentiates the original conv)."""
    n, c, h, wd = x.shape
    o, _, k, _ = w.shape
    kb = k // 2 + 1
    ho = (h + 2 * pad - k) // 2 + 1
    wo = (wd + 2 * pad - k) // 2 + 1
    hb, wb = ho + kb - 1, wo + kb - 1
    # pad to the exact 2*hb x 2*wb block footprint the rewrite reads
    xp = jnp.pad(x, ((0, 0), (0, 0), (pad, 2 * hb - h - pad),
                     (pad, 2 * wb - wd - pad)))
    xs = xp.reshape(n, c, hb, 2, wb, 2).transpose(0, 3, 5, 1, 2, 4) \
        .reshape(n, 4 * c, hb, wb)
    w2 = jnp.zeros((o, 2, 2, c, kb, kb), w.dtype)
    for dy in range(k):
        for dx in range(k):
            w2 = w2.at[:, dy % 2, dx % 2, :, dy // 2, dx // 2] \
                .set(w[:, :, dy, dx])
    return xs, w2.reshape(o, 4 * c, kb, kb)


def _fwd_kxk(x, w, shift, stride, pad, interpret, block_o_hint: int = 0):
    """x (N,C,H,W), w (O,C,k,k), shift (O,) f32 ->
    (y (N,O,Ho,Wo), s1, s2).  Torch-style symmetric padding."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, c, h, wd = x.shape
    o, _, k, _ = w.shape
    hp, wp_ = h + 2 * pad, wd + 2 * pad

    block_o, ho, wo, reason = _kxk_plan(c, h, wd, o, k, stride, pad,
                                        x.dtype.itemsize, block_o_hint)
    if reason is not None:
        _note_fallback(reason, x.shape, w.shape, stride, pad)
        return _reference(x, w, shift, stride, pad)
    if stride == 2:
        xs, w2 = _s2d_rewrite(x, w, pad)
        return _fwd_kxk(xs, w2, shift, 1, 0, interpret, block_o_hint)
    o_pad = _round_up(o, block_o)

    # flattened spatially-padded image, plus k-1 trailing lanes so the
    # largest tap shift's slice stays in bounds (kernel docstring)
    xpad = jnp.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    xflat = xpad.reshape(n, c, hp * wp_)
    xflat = jnp.pad(xflat, ((0, 0), (0, 0), (0, k - 1)))
    # tap-major flattened weights: (O, k*k*C) matching the kernel's
    # im2col row order [tap0 c-rows, tap1 c-rows, ...]
    wt = jnp.transpose(w, (0, 2, 3, 1)).reshape(o, k * k * c)
    if o_pad != o:
        wt = jnp.pad(wt, ((0, o_pad - o), (0, 0)))
        shift = jnp.pad(shift, (0, o_pad - o))
    sp = shift[None, :]

    kern = functools.partial(_fwd_kernel_kxk, k=k, wp_=wp_, ho=ho, wo=wo)
    y2, s1, s2 = pl.pallas_call(
        kern,
        grid=(o_pad // block_o, n),
        in_specs=[
            pl.BlockSpec((1, c, hp * wp_ + k - 1),
                         lambda oi, ni: (ni, 0, 0)),
            pl.BlockSpec((block_o, k * k * c), lambda oi, ni: (oi, 0)),
            pl.BlockSpec((1, block_o), lambda oi, ni: (0, oi)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_o, ho * wp_), lambda oi, ni: (ni, oi, 0)),
            pl.BlockSpec((1, block_o), lambda oi, ni: (0, oi)),
            pl.BlockSpec((1, block_o), lambda oi, ni: (0, oi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, o_pad, ho * wp_), x.dtype),
            jax.ShapeDtypeStruct((1, o_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, o_pad), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((k * k * c, ho * wp_), x.dtype)],
        interpret=interpret,
    )(xflat, wt, sp)
    # unpad: (N, O, Ho, Wp)[..., :Wo] — the slice fuses into the
    # consumer's normalize pass, so y is never re-read for it
    y4 = y2[:, :o].reshape(n, o, ho, wp_)[:, :, :, :wo]
    return y4, s1[0, :o], s2[0, :o]


# --------------------------------------------------------------------------
# custom_vjp wrapper (shared by both kernels)
# --------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _conv_bn_stats_vjp(x, w, shift, stride, pad, interpret, impl,
                       block_o):
    # impl "xla" is a TUNER decision (measured/modelled cheaper for
    # this shape), not a feasibility bail — no fallback note
    if impl == "xla":
        return _reference(x, w, shift, stride, pad)
    if w.shape[2] == 1 and w.shape[3] == 1 and pad == 0:
        if stride != 1:
            x = x[:, :, ::stride, ::stride]
        return _fwd_1x1(x, w[:, :, 0, 0], shift, interpret, block_o)
    return _fwd_kxk(x, w, shift, stride, pad, interpret, block_o)


def _fwd_rule(x, w, shift, stride, pad, interpret, impl, block_o):
    out = _conv_bn_stats_vjp(x, w, shift, stride, pad, interpret, impl,
                             block_o)
    y, s1, _ = out
    return out, (x, w, y, shift, s1)


def _bwd_rule(stride, pad, interpret, impl, block_o, res, cts):
    x, w, y, shift, s1 = res
    gy, gs1, gs2 = cts
    yc = y.astype(jnp.float32) - shift[None, :, None, None]
    gy_eff = (
        gy.astype(jnp.float32)
        + gs1[None, :, None, None]
        + 2.0 * yc * gs2[None, :, None, None]
    ).astype(x.dtype)

    # same-dtype conv (no preferred_element_type): its transpose would
    # otherwise pair an f32 cotangent with bf16 operands and fail; the
    # MXU accumulates the bf16 grads in f32 regardless
    def _conv_same_dtype(x_, w_):
        return jax.lax.conv_general_dilated(
            x_, w_, (stride, stride), [(pad, pad), (pad, pad)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )

    _, vjp = jax.vjp(_conv_same_dtype, x, w)
    dx, dw = vjp(gy_eff)
    # shift is normally running-state (no grad requested), but the
    # cotangent is cheap and exact: ds1/dshift = -n, ds2/dshift = -2 s1
    n = y.shape[0] * y.shape[2] * y.shape[3]
    gshift = -float(n) * gs1 - 2.0 * s1 * gs2
    return dx, dw, gshift


_conv_bn_stats_vjp.defvjp(_fwd_rule, _bwd_rule)


def conv_bn_stats(x, w, shift, *, stride: int = 1, pad: int = 0,
                  interpret: bool = False, impl: str = "auto",
                  block_o: int = 0):
    """Fused conv + centered BN statistics.

    x (N, C, H, W); w (O, C, kh, kw) or (O, C) for 1x1; shift (O,) f32
    — typically the BN running mean.  Returns (y, s1, s2) with
    s1 = sum(y - shift) and s2 = sum((y - shift)^2) per channel in f32.
    Supports k=1 (stride subsampling outside the kernel) and odd k with
    symmetric torch-style padding at stride 1 or 2 (stride 2 via the
    space-to-depth rewrite).

    ``impl``: "auto" (Pallas when feasible; when the auto-tuner is on
    — ``BIGDL_TUNER=1``, ops/autotune.py — the cached per-shape search
    decides instead), "pallas" (static dispatch, no tuner), or "xla"
    (reference).  ``block_o`` caps the O-tile (0 = budget-derived) —
    the tuner's knob.
    """
    if w.ndim == 2:
        w = w[:, :, None, None]
    shift = shift.astype(jnp.float32)
    # compiled Mosaic kernels exist only on TPU; everything else
    # (CPU tests, the 8-virtual-device mesh, a hypothetical GPU box —
    # whose parallel grid would race the s1/s2 accumulation) runs the
    # interpreter
    interpret = interpret or jax.default_backend() != "tpu"
    if impl == "auto":
        impl = "pallas"
        from bigdl_tpu.ops import autotune

        if autotune.enabled():
            decision = autotune.decide_conv_bn(
                x.shape, w.shape, x.dtype, stride=stride, pad=pad,
                arrays=(x, w, shift), interpret=interpret)
            if decision is not None:
                impl = decision["impl"]
                block_o = block_o or int(decision.get("block_o") or 0)
    return _conv_bn_stats_vjp(x, w, shift, stride, pad, interpret,
                              impl, int(block_o))


def conv1x1_bn_stats(x, w, shift, *, stride: int = 1,
                     interpret: bool = False):
    """1x1 fast path, kept as the r02 API: w (O, C)."""
    return conv_bn_stats(x, w, shift, stride=stride, pad=0,
                         interpret=interpret)


def kernel_path(x_shape, w_shape, *, stride: int = 1, pad: int = 0,
                itemsize: int = 2) -> str:
    """Which path ``conv_bn_stats`` takes for these STATIC shapes —
    ``"pallas_1x1"``, ``"pallas_kxk"``, or ``"xla:<reason>"``.

    Mirrors the exact STATIC dispatch in ``_conv_bn_stats_vjp`` /
    ``_kxk_plan`` (stride-2 kxk sites route through the space-to-depth
    rewrite and report ``pallas_kxk`` when the rewritten problem fits
    VMEM) without tracing anything, so tests can pin every production
    call site to the Pallas path (VERDICT r4 item 3).  A
    tuner-enabled run may override per shape — this reports the
    tuner-OFF dispatch.  ``itemsize`` is the activation dtype's byte
    width (2 = bf16, the training compute dtype).  Decisions are
    batch-independent: the kxk grid iterates samples and the 1x1
    kernel tiles (O, HW), so a shape proven at one batch holds at any
    batch.
    """
    n, c, h, wd = (int(s) for s in x_shape)
    w_shape = tuple(int(s) for s in w_shape)
    o = w_shape[0]
    k = 1 if len(w_shape) == 2 else w_shape[2]
    if k == 1 and (len(w_shape) == 2 or w_shape[3] == 1) and pad == 0:
        return "pallas_1x1"  # handles any (O, HW): padded + masked tiles
    _, _, _, reason = _kxk_plan(c, h, wd, o, k, stride, pad, itemsize)
    return "pallas_kxk" if reason is None else f"xla:{reason}"
