"""Fusion-aware kernel auto-tuner — cached cost-model dispatch search.

The hot kernels used to dispatch on hand-picked constants (the
``t * tk >= 4096^2`` lax-vs-Pallas attention policy, budget-derived
conv block sizes), so entire shape regimes never reached the fast path
and the ones that did ran untuned blocks.  Following FADiff's
fusion-aware candidate-search approach (arXiv:2511.22348, PAPERS.md),
this module makes dispatch a measured, cached, regression-gated
decision:

* **candidates** — per call site (flash attention fwd/bwd, 1x1 and kxk
  conv+BN), a small set of ``impl x block-size`` configurations that
  pass the kernels' own symmetric VMEM feasibility models
  (``attention._flash_plan`` / ``conv_bn._kxk_plan``), always
  including the hand-measured static policy;
* **costing** — every XLA candidate is costed with the PR 4 HLO
  ``cost_analysis`` machinery (``obs.runtime.hlo_cost_analysis``, the
  ``instrument_jit`` path): the compiler's own FLOPs/bytes for the
  program it actually builds.  Pallas candidates are opaque custom
  calls to XLA, so they are costed by the kernel's own traffic plan
  (I/O + superblock re-streaming) — documented analytic bytes, same
  units.  The scalar score is a roofline sum
  ``flops/peak + bytes/bandwidth``;
* **measurement** — with ``BIGDL_TUNER_MEASURE=1`` and CONCRETE inputs
  (never inside a jit trace), candidates are additionally timed
  one-shot through a ``jax.jit(value_and_grad)`` probe — the same
  fwd+bwd composite the A/B harnesses (scripts/attn_ab.py,
  scripts/bn_ab.py) measure — and the measured times override the
  model;
* **never lose to the static policy** — the winner is the argmin with
  ties broken toward the static choice, and a measured winner is
  additionally gated through ``obs.regress.check`` (the same verdict
  machinery that gates bench runs against the BENCH_r*.json
  trajectory): a "tuned" config that regresses past the static
  baseline is discarded and the static policy kept, so tuned dispatch
  is >= 1.0x the hand-picked baseline by construction;
* **cache** — decisions persist as JSON under ``BIGDL_TUNER_CACHE``
  keyed on ``(site, shape, dtype, platform)``, so they survive
  restarts and chip-unavailable rounds (bank the evidence once, serve
  it forever).  A corrupt cache file degrades to the static policy —
  it never crashes a run and is never silently clobbered.

Observability: every decision emits a ``tuner.decision`` trace event
and ``bigdl_tuner_decisions_total{site,impl}``; cache traffic rides
``bigdl_tuner_cache_{hits,misses}_total`` and each wall-clock probe
``bigdl_tuner_measurements_total``.  ``obs/report.py`` renders the
"kernel auto-tuner" section from these.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional
from bigdl_tpu.obs import names

# rough per-platform (peak_flops, peak_hbm_bytes_per_s) for the
# roofline score.  Only the RANKING matters — every candidate of one
# decision is scored with the same constants.
_PEAKS = {
    "tpu": (180e12, 8.0e11),
    "gpu": (1.0e14, 1.0e12),
    "cpu": (2.0e11, 3.0e10),
}

# a MODEL-only (unmeasured) decision may flip the impl away from the
# static policy only when the modeled score beats static's by this
# factor — the analytic model is for ranking, not for close calls; the
# regimes flash exists for (quadratic residual traffic) clear the bar
# by 10-100x, marginal shapes stay on the measured static policy
_MODEL_MARGIN = 0.5

_lock = threading.Lock()
_cache = None
_cache_path = None


# --------------------------------------------------------------------------
# config / obs plumbing
# --------------------------------------------------------------------------


def _cfg():
    from bigdl_tpu.config import refresh_from_env

    return refresh_from_env().tuner


def enabled() -> bool:
    """Is the auto-tuner on (``BIGDL_TUNER=1``)?  Read at call time —
    the fault injector's contract, so tests and late exports work."""
    try:
        return bool(_cfg().enabled)
    except Exception:  # noqa: BLE001 — config must never sink dispatch
        return False


def platform() -> str:
    import jax

    try:
        return jax.default_backend()
    except Exception:  # noqa: BLE001 — backendless host
        return "unknown"


def _counter(name, desc, **labels):
    try:
        from bigdl_tpu import obs

        c = obs.get_registry().counter(name, desc,
                                       labels=tuple(labels) or ())
        (c.labels(**labels) if labels else c).inc()
    except Exception:  # noqa: BLE001 — telemetry never sinks dispatch
        pass


def _event(name, **attrs):
    try:
        from bigdl_tpu import obs

        obs.get_tracer().event(name, **attrs)
    except Exception:  # noqa: BLE001 — telemetry never sinks dispatch
        pass


# --------------------------------------------------------------------------
# decision cache
# --------------------------------------------------------------------------


class TunerCache:
    """JSON decision store.  ``{"version": 1, "decisions": {key: rec}}``.

    Load is tolerant: a corrupt/truncated file flips ``corrupt`` and
    the tuner serves the static policy for every miss (and never
    writes — the evidence stays on disk for the postmortem).  Writes
    are atomic (tmp + rename) so a killed run can't tear the store."""

    VERSION = 1

    def __init__(self, path: Optional[str]):
        self.path = path
        self.decisions: dict = {}
        self.corrupt = False
        self.hits = 0
        self.misses = 0
        if path and os.path.exists(path):
            try:
                with open(path, encoding="utf-8") as fh:
                    doc = json.load(fh)
                if (not isinstance(doc, dict)
                        or doc.get("version") != self.VERSION
                        or not isinstance(doc.get("decisions"), dict)):
                    raise ValueError("bad tuner cache schema")
                self.decisions = doc["decisions"]
            except (OSError, ValueError, json.JSONDecodeError):
                self.corrupt = True

    def get(self, key: str) -> Optional[dict]:
        rec = self.decisions.get(key)
        if rec is not None:
            self.hits += 1
            _counter(names.TUNER_CACHE_HITS_TOTAL,
                     "Tuner decisions served from the cache")
        else:
            self.misses += 1
            _counter(names.TUNER_CACHE_MISSES_TOTAL,
                     "Tuner cache misses (fresh searches)")
        return rec

    def put(self, key: str, rec: dict):
        if self.corrupt:
            return  # never clobber a corrupt store
        self.decisions[key] = rec
        if not self.path:
            return
        try:
            d = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(d, exist_ok=True)
            tmp = f"{self.path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump({"version": self.VERSION,
                           "decisions": self.decisions}, fh)
            os.replace(tmp, self.path)
        except OSError:
            pass  # in-memory decisions still serve this process

    def stats(self) -> dict:
        return {"path": self.path, "entries": len(self.decisions),
                "hits": self.hits, "misses": self.misses,
                "corrupt": self.corrupt}


def get_cache() -> TunerCache:
    """The process cache, rebuilt when ``BIGDL_TUNER_CACHE`` changes
    (read-at-call-time, like the tracer)."""
    global _cache, _cache_path
    path = _cfg().cache_path
    with _lock:
        if _cache is None or path != _cache_path:
            _cache = TunerCache(path)
            _cache_path = path
        return _cache


def reset():
    """Test hook: drop the cache singleton (next access reloads)."""
    global _cache, _cache_path
    with _lock:
        _cache = None
        _cache_path = None


def cache_key(site: str, shape_sig: str, dtype, plat: Optional[str] = None,
              extra: str = "") -> str:
    """Golden key format: ``site|shape|dtype|platform[|extra]`` — the
    (site, shape, dtype, platform) tuple the store is keyed on."""
    import jax.numpy as jnp

    key = f"{site}|{shape_sig}|{jnp.dtype(dtype).name}|{plat or platform()}"
    return f"{key}|{extra}" if extra else key


# --------------------------------------------------------------------------
# costing / measurement
# --------------------------------------------------------------------------


def _score(flops: float, bytes_: float, plat: Optional[str] = None) -> float:
    peak_f, peak_b = _PEAKS.get(plat or platform(), _PEAKS["cpu"])
    return flops / peak_f + bytes_ / peak_b


def _hlo_cost(jitted, args) -> Optional[dict]:
    """HLO ``cost_analysis`` of a jitted candidate via the PR 4 path
    (obs.runtime): the compiler's own FLOPs/bytes.  None when the
    backend can't cost it."""
    try:
        from bigdl_tpu.obs.runtime import abstract_args, hlo_cost_analysis

        return hlo_cost_analysis(jitted, abstract_args(args, {}))
    except Exception:  # noqa: BLE001 — costing is best-effort
        return None


def _concrete(arrays) -> bool:
    """Concrete device/host arrays (measurable), not tracers mid-jit."""
    import jax

    if arrays is None:
        return False
    return not any(isinstance(a, jax.core.Tracer) for a in arrays)


def _measure(jitted, args, iters: int) -> float:
    """One-shot wall-clock of a compiled candidate (median-free mean
    over ``iters`` after a compile+warmup call)."""
    import jax

    out = jitted(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(max(1, iters)):
        out = jitted(*args)
    jax.block_until_ready(out)
    _counter(names.TUNER_MEASUREMENTS_TOTAL,
             "Wall-clock candidate probes run by the auto-tuner")
    return (time.perf_counter() - t0) / max(1, iters)


def _gate_measured(tuned_label: str, tuned_s: float, static_label: str,
                   static_s: float) -> dict:
    """Regression-gate a measured tuned config against the static
    policy through ``obs.regress.check`` — the same verdict machinery
    (and ``BIGDL_REGRESS_TOLERANCE``) that gates bench runs against
    the BENCH_r*.json trajectory."""
    from bigdl_tpu.obs import regress

    plat = platform()
    fresh = {"source": f"tuned:{tuned_label}", "round": None,
             "platform": plat, "value": None, "step_time_s": tuned_s,
             "step_time_p95_s": None, "compile_count": None}
    base = [{"source": f"static:{static_label}", "round": 0,
             "platform": plat, "value": None, "step_time_s": static_s,
             "step_time_p95_s": None, "compile_count": None}]
    v = regress.check(fresh, base)
    return {"status": v["status"],
            "ratio": v.get("step_time_ratio"),
            "violations": v.get("violations", [])}


def _resolve(site, key, candidates, static_label, analytic, probes,
             arrays, use_hlo=True):
    """Core search: cache -> (score | measure) -> gate -> cache.

    ``candidates``: {label: decision-payload}; ``analytic``:
    {label: (flops, bytes)}; ``probes``: {label: fn(*arrays)} builders
    for the fwd+bwd measurement/HLO probe (XLA labels only get HLO
    costing; ``use_hlo=False`` keeps every candidate on the analytic
    model — the paged-gather sites, where HloCostAnalysis bills a
    gather at whole-operand bytes and erases the ranking)."""
    import jax

    cache = get_cache()
    with _lock:
        rec = cache.get(key)
    if rec is not None:
        _emit(site, rec, "cache")
        return rec

    if cache.corrupt:
        rec = dict(candidates[static_label], site=site, key=key,
                   label=static_label, source="corrupt_cache")
        _emit(site, rec, "corrupt_cache")
        return rec

    cfg = _cfg()
    plat = platform()
    scores = {}
    hlo = {}
    for label, (flops, bytes_) in analytic.items():
        fl, by = flops, bytes_
        if use_hlo and not label.startswith("pallas") and label in probes:
            # XLA candidates: the compiler's own count beats the model
            # (Pallas custom calls are opaque to HloCostAnalysis — the
            # analytic kernel traffic plan stands in)
            try:
                # one jit per DISTINCT candidate, once per cached search
                # — not a per-step re-jit  # graftlint: disable=JX003
                jitted = jax.jit(probes[label])
                cost = _hlo_cost(jitted, arrays) if arrays else None
            except Exception:  # noqa: BLE001
                cost = None
            if cost:
                hlo[label] = cost
                fl = cost.get("flops") or fl
                by = cost.get("bytes_accessed") or by
        scores[label] = _score(fl, by, plat)

    measured = {}
    if cfg.measure and _concrete(arrays):
        for label, probe in probes.items():
            if label not in candidates:
                continue
            try:
                # fresh jit per candidate is the measurement protocol
                # (cold compile excluded by the warmup call)
                measured[label] = _measure(  # graftlint: disable=JX003
                    jax.jit(probe), arrays, cfg.measure_iters)
            except Exception:  # noqa: BLE001 — one broken candidate
                measured.pop(label, None)   # must not sink the search

    gate = None
    if measured and static_label in measured:
        winner = min(measured, key=lambda c: measured[c])
        if measured[winner] >= measured[static_label]:
            winner = static_label  # ties and losses go static
        elif winner != static_label:
            gate = _gate_measured(winner, measured[winner],
                                  static_label, measured[static_label])
            if gate["status"] == "violation":
                winner = static_label
        source = "measured"
    else:
        winner = min(scores, key=lambda c: scores[c]) if scores \
            else static_label
        if winner not in candidates or \
                scores.get(winner, 0) >= scores.get(static_label,
                                                    float("inf")):
            winner = static_label  # model must BEAT static to deviate
        elif (candidates[winner].get("impl")
                != candidates[static_label].get("impl")
                and scores[winner] >= _MODEL_MARGIN
                * scores[static_label]):
            winner = static_label  # impl flips need a decisive margin
        source = "model"

    rec = dict(candidates[winner], site=site, key=key, label=winner,
               source=source, platform=plat, ts=round(time.time(), 3),
               static=static_label,
               scores={c: round(s, 9) for c, s in scores.items()})
    if measured:
        rec["measured_s"] = {c: round(s, 9) for c, s in measured.items()}
    if hlo:
        rec["hlo"] = hlo
    if gate:
        rec["gate"] = gate
    with _lock:
        cache.put(key, rec)
    _emit(site, rec, source)
    return rec


def _emit(site, rec, source):
    _counter(names.TUNER_DECISIONS_TOTAL,
             "Auto-tuner dispatch decisions, by call site and chosen "
             "impl", site=site, impl=rec.get("impl", "?"))
    _event("tuner.decision", site=site, key=rec.get("key"),
           impl=rec.get("impl"), label=rec.get("label"), source=source,
           static=rec.get("static"))


# --------------------------------------------------------------------------
# site: flash attention (fwd/bwd — one decision covers both, the
# custom_vjp ties them)
# --------------------------------------------------------------------------


def decide_attention(q_shape, k_shape, dtype, *, causal: bool,
                     seq_offset: int, static_impl: str, plan,
                     arrays=None) -> Optional[dict]:
    """Dispatch decision for ``dot_product_attention(impl="auto")``.
    Returns ``{"impl": "lax"|"pallas", "blocks": (bq,bk,bkv,bqs)|None}``
    (plus provenance) or None to mean "use the static policy"."""
    try:
        from bigdl_tpu.ops import attention as A

        b, h, tq, d = (int(s) for s in q_shape)
        tk = int(k_shape[-2])
        if not isinstance(seq_offset, int):
            return None  # traced offset: static policy (lax) only
        key = cache_key("attn", f"b{b}h{h}tq{tq}tk{tk}d{d}", dtype,
                        extra=f"c{int(causal)}o{seq_offset}")

        candidates = {"lax": {"impl": "lax", "blocks": None}}
        analytic = {"lax": _attn_cost("lax", None, b, h, tq, tk, d,
                                      dtype, causal)}
        scale = d ** -0.5
        interp = platform() != "tpu"

        def _lax_probe(q, k, v):
            import jax
            import jax.numpy as jnp

            def f(q, k, v):
                out = A._reference_attention(q, k, v, causal=causal,
                                             scale=scale,
                                             seq_offset=seq_offset)
                return jnp.sum(out.astype(jnp.float32) ** 2)

            val, grads = jax.value_and_grad(f, argnums=(0, 1, 2))(q, k, v)
            return val, grads

        probes = {"lax": _lax_probe}

        # Pallas candidates only where they would run COMPILED (TPU) or
        # where a wall-clock measurement can arbitrate — the analytic
        # model prices Mosaic kernels, not the CPU interpreter, so an
        # unmeasurable non-TPU search must stay on the static policy's
        # side of the impl question
        pallas_ok = (plan is not None
                     and (platform() == "tpu"
                          or (_cfg().measure and _concrete(arrays))))
        if pallas_ok:
            seen = set()
            for bq, bk in ((plan[0], plan[1]), (128, 128), (128, 64),
                           (64, 128), (64, 64)):
                p = A._flash_plan(tq, tk, d, dtype, block_q=bq,
                                  block_k=bk)
                if p is None or p in seen:
                    continue
                seen.add(p)
                label = f"pallas_q{p[0]}k{p[1]}v{p[2]}s{p[3]}"
                candidates[label] = {"impl": "pallas", "blocks": list(p)}
                analytic[label] = _attn_cost("pallas", p, b, h, tq, tk,
                                             d, dtype, causal)
                probes[label] = _flash_probe(A, p, causal, scale,
                                             seq_offset, interp)

        if static_impl == "lax" or plan is None:
            static_label = "lax"
        else:
            static_label = (f"pallas_q{plan[0]}k{plan[1]}"
                            f"v{plan[2]}s{plan[3]}")
        rec = _resolve("attn", key, candidates, static_label, analytic,
                       probes, arrays)
        if rec.get("blocks"):
            rec = dict(rec, blocks=tuple(rec["blocks"]))
        return rec
    except Exception:  # noqa: BLE001 — the tuner must never sink a step
        return None


def _flash_probe(A, plan, causal, scale, seq_offset, interp):
    def probe(q, k, v):
        import jax
        import jax.numpy as jnp

        def f(q, k, v):
            out = A.flash_attention(
                q, k, v, causal=causal, scale=scale, interpret=interp,
                seq_offset=seq_offset, block_q=plan[0], block_k=plan[1],
                block_kv=plan[2], block_qs=plan[3])
            return jnp.sum(out.astype(jnp.float32) ** 2)

        val, grads = jax.value_and_grad(f, argnums=(0, 1, 2))(q, k, v)
        return val, grads

    return probe


def _attn_cost(impl, plan, b, h, tq, tk, d, dtype, causal):
    """Analytic (flops, bytes) of the fwd+bwd composite.  The causal
    factor halves the touched tiles; backward recomputes the score
    tiles, hence the 3.5x flops multiplier (1 fwd + 2.5 bwd)."""
    import jax.numpy as jnp

    item = jnp.dtype(dtype).itemsize
    bh = b * h
    cf = 0.5 if causal else 1.0
    flops = 4.0 * bh * tq * tk * d * cf * 3.5
    io = bh * (2 * tq + 2 * tk) * d * item          # q, k, v, out
    if impl == "lax":
        # the (Tq, Tk) f32 score/prob plane makes HBM round trips in
        # both directions (write+read fwd, residual read + dP write
        # bwd) — the quadratic term the flash kernel deletes
        return flops, 3 * io + 4.0 * bh * tq * tk * 4 * cf
    bq, bk, bkv, bqs = plan
    ns_kv = tk // bkv
    ns_q = tq // bqs
    # kv superblocks are refetched per q-block once streaming kicks in
    # (grid index map varies in s), once per bh otherwise; the dkv
    # kernel mirrors that for the q+g streams
    kv_stream = bh * (tq // bq if ns_kv > 1 else 1) * 2 * tk * d * item
    q_stream = bh * (tk // bk if ns_q > 1 else 1) * 2 * tq * d * item
    return flops, 3 * io + 2 * kv_stream + q_stream


# --------------------------------------------------------------------------
# site: fused conv + BN statistics (1x1 / kxk)
# --------------------------------------------------------------------------


def decide_conv_bn(x_shape, w_shape, dtype, *, stride: int, pad: int,
                   arrays=None, interpret: bool = False) -> Optional[dict]:
    """Dispatch decision for ``conv_bn_stats(impl="auto")``.  Returns
    ``{"impl": "pallas"|"xla", "block_o": int}`` (plus provenance) or
    None for "use the static dispatch"."""
    try:
        import jax.numpy as jnp

        from bigdl_tpu.ops import conv_bn as C

        n, c, h, wd = (int(s) for s in x_shape)
        w_shape = tuple(int(s) for s in w_shape)
        o = w_shape[0]
        k = 1 if len(w_shape) == 2 else w_shape[2]
        site = "conv_bn_1x1" if k == 1 else "conv_bn_kxk"
        item = jnp.dtype(dtype).itemsize
        key = cache_key(site,
                        f"n{n}c{c}h{h}w{wd}o{o}k{k}s{stride}p{pad}",
                        dtype)

        static_path = C.kernel_path(x_shape, w_shape, stride=stride,
                                    pad=pad, itemsize=item)
        candidates = {"xla": {"impl": "xla", "block_o": 0}}
        analytic = {"xla": _conv_cost("xla", n, c, h, wd, o, k, stride,
                                      pad, item)}
        probes = {"xla": _conv_probe(C, stride, pad, interpret, "xla", 0)}

        blocks = []
        if static_path.startswith("pallas"):
            if k == 1:
                bo, _ = C._tiles_1x1(o, c, h * wd, item)
            else:
                bo, _, _, _ = C._kxk_plan(c, h, wd, o, k, stride, pad,
                                          item)
            blocks = sorted({bo, max(8, bo // 2)}, reverse=True)
        for bo in blocks:
            label = f"pallas_o{bo}"
            candidates[label] = {"impl": "pallas", "block_o": bo}
            analytic[label] = _conv_cost("pallas", n, c, h, wd, o, k,
                                         stride, pad, item)
            probes[label] = _conv_probe(C, stride, pad, interpret,
                                        "pallas", bo)

        static_label = f"pallas_o{blocks[0]}" if blocks else "xla"
        return _resolve(site, key, candidates, static_label, analytic,
                        probes, arrays)
    except Exception:  # noqa: BLE001 — the tuner must never sink a step
        return None


def _conv_probe(C, stride, pad, interpret, impl, block_o):
    def probe(x, w, shift):
        import jax
        import jax.numpy as jnp

        def f(x, w):
            y, s1, s2 = C._conv_bn_stats_vjp(x, w, shift, stride, pad,
                                             interpret, impl, block_o)
            return (jnp.sum(y.astype(jnp.float32) ** 2)
                    + jnp.sum(s1) + jnp.sum(s2))

        val, grads = jax.value_and_grad(f, argnums=(0, 1))(x, w)
        return val, grads

    return probe


def _conv_cost(impl, n, c, h, wd, o, k, stride, pad, item):
    """Analytic (flops, bytes) of the fused fwd+bwd.  The backward is
    the same analytic XLA conv-grad for both impls; the forward differs
    in whether the output is re-read for the statistics pass (XLA) and
    whether a space-to-depth copy is paid (Pallas stride-2)."""
    ho = (h + 2 * pad - k) // stride + 1
    wo = (wd + 2 * pad - k) // stride + 1
    flops = 2.0 * n * c * k * k * ho * wo * o * 3.0   # fwd + 2x bwd
    x_b = n * c * h * wd * item
    y_b = n * o * ho * wo * item
    w_b = o * c * k * k * item
    common = 3 * (x_b + y_b) + 2 * w_b                # fwd + bwd I/O
    if impl == "xla":
        # the separate statistics pass re-reads the conv output
        return flops, common + y_b
    s2d = 2 * x_b if (stride == 2 and k > 1) else 0   # phase-image copy
    return flops, common + s2d


# --------------------------------------------------------------------------
# site: paged decode attention (the serving hot path, ISSUE 13)
# --------------------------------------------------------------------------


def decide_decode_attn(q_shape, page_size: int, maxp: int, dtype, *,
                       kv_dtype=None, arrays=None) -> Optional[dict]:
    """Dispatch decision for the ``decode_attn`` site
    (``ops.decode_attention.paged_decode_attention(impl="auto")``).
    Returns ``{"impl": "dense"|"fused"|"pallas", "block_pages": int}``
    (plus provenance) or None for "use the static dense policy".

    Costing note: the XLA candidates here are scored by the documented
    analytic paged-traffic model (``decode_hbm_bytes``), NOT the HLO
    ``cost_analysis`` path — measured on CPU, HloCostAnalysis bills
    the page gather at whole-operand bytes (3.3 MB billed for a 0.5 MB
    indexed access on a 129-page pool), which makes dense and fused
    indistinguishable and erases exactly the gather tax this site
    exists to price.  Wall-clock measurement (``prewarm_decode_attn``
    with BIGDL_TUNER_MEASURE=1) still overrides the model."""
    try:
        import jax.numpy as jnp

        from bigdl_tpu.ops import decode_attention as D

        b, h, d = (int(s) for s in q_shape)
        p, maxp = int(page_size), int(maxp)
        kv_dtype = dtype if kv_dtype is None else kv_dtype
        item = jnp.dtype(kv_dtype).itemsize
        key = cache_key("decode_attn", f"b{b}h{h}d{d}p{p}m{maxp}", dtype)

        flops = 4.0 * b * h * maxp * p * d
        candidates = {"dense": {"impl": "dense", "block_pages": 0}}
        analytic = {"dense": (flops, D.decode_hbm_bytes(
            "dense", b, h, d, p, maxp, item))}
        probes = {"dense": _decode_probe(D, p, "dense", 0, False)}
        fused_bytes = D.decode_hbm_bytes("fused", b, h, d, p, maxp, item)
        for bp in sorted({maxp, 1, min(4, maxp)}, reverse=True):
            if maxp % bp:
                continue
            label = f"fused_p{bp}"
            candidates[label] = {"impl": "fused", "block_pages": bp}
            analytic[label] = (flops, fused_bytes)
            probes[label] = _decode_probe(D, p, "fused", bp, False)
        # the Pallas kernel only where it would run COMPILED (TPU) or
        # where a wall-clock probe can arbitrate (interpret mode)
        if platform() == "tpu" or (_cfg().measure and _concrete(arrays)):
            candidates["pallas"] = {"impl": "pallas", "block_pages": 1}
            analytic["pallas"] = (flops, D.decode_hbm_bytes(
                "pallas", b, h, d, p, maxp, item))
            probes["pallas"] = _decode_probe(
                D, p, "pallas", 1, platform() != "tpu")
        return _resolve("decode_attn", key, candidates, "dense",
                        analytic, probes, arrays, use_hlo=False)
    except Exception:  # noqa: BLE001 — the tuner must never sink a step
        return None


def _decode_probe(D, page_size, impl, block_pages, interp):
    def probe(q, kp, vp, tables, lengths):
        import jax.numpy as jnp

        out = D.paged_decode_attention(
            q, kp, vp, tables, lengths, page_size=page_size,
            impl=("pallas_interpret" if impl == "pallas" and interp
                  else impl), block_pages=block_pages)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    return probe


# --------------------------------------------------------------------------
# site: quantized matmul (int8 decode weights, ROADMAP "widen" item)
# --------------------------------------------------------------------------


def decide_int8_mm(x_shape, w_shape, dtype, *,
                   arrays=None) -> Optional[dict]:
    """Dispatch decision for ``ops.quantized_matmul.int8_matmul
    (impl="auto")``.  Returns ``{"impl": "int8"|"dequant"}`` (plus
    provenance) or None for the static int8 path.

    "int8" is the current implementation (dynamic per-row activation
    quantization + int8 ``dot_general`` with int32 accumulation —
    never-lose static); "dequant" rescales the int8 weight back to f32
    and runs a float matmul — fewer ops on backends whose int8 gemm is
    slow, at 4x the weight-stream bytes.  Both are XLA programs, so
    both ride the HLO ``cost_analysis`` costing when inputs are
    available."""
    try:
        import jax.numpy as jnp

        m = 1
        for s in x_shape[:-1]:
            m *= int(s)
        k = int(x_shape[-1])
        n = int(w_shape[0])
        key = cache_key("int8_mm", f"m{m}k{k}n{n}", dtype)
        flops = 2.0 * m * k * n
        x_b = m * k * 4.0
        out_b = m * n * 4.0
        analytic = {
            # int8: 1-byte weight stream + the dynamic activation
            # quantize round trip (read f32, write+read int8)
            "int8": (flops, n * k + n * 4 + x_b + 2.0 * m * k + out_b),
            # dequant: 1-byte weight read + f32 dequant copy write+read
            "dequant": (flops, n * k + n * 4 + 8.0 * n * k + x_b + out_b),
        }
        candidates = {"int8": {"impl": "int8"},
                      "dequant": {"impl": "dequant"}}
        probes = {lbl: _int8_mm_probe(lbl) for lbl in candidates}
        return _resolve("int8_mm", key, candidates, "int8", analytic,
                        probes, arrays)
    except Exception:  # noqa: BLE001 — the tuner must never sink a step
        return None


def _int8_mm_probe(impl):
    def probe(x, w_q, w_scale):
        import jax.numpy as jnp

        from bigdl_tpu.ops.quantized_matmul import int8_matmul

        y = int8_matmul(x, w_q, w_scale, impl=impl)
        return jnp.sum(y.astype(jnp.float32) ** 2)

    return probe


# --------------------------------------------------------------------------
# pre-warming + reporting
# --------------------------------------------------------------------------


def prewarm_attention(b, h, tq, tk, d, dtype="float32", *,
                      causal=True, seed=0):
    """Offline cache warmer: build concrete inputs and run one
    ``impl="auto"`` dispatch (measuring when BIGDL_TUNER_MEASURE=1).
    Returns the op output so callers can assert numerics."""
    import numpy as np

    import jax.numpy as jnp

    from bigdl_tpu.ops.attention import dot_product_attention

    rs = np.random.RandomState(seed)
    mk = lambda t: jnp.asarray(
        rs.randn(b, h, t, d).astype(np.float32)).astype(dtype)
    return dot_product_attention(mk(tq), mk(tk), mk(tk), causal=causal,
                                 impl="auto")


def prewarm_conv_bn(n, c, h, w, o, k, *, stride=1, pad=0,
                    dtype="float32", seed=0):
    """Offline cache warmer for a fused conv+BN site."""
    import numpy as np

    import jax.numpy as jnp

    from bigdl_tpu.ops.conv_bn import conv_bn_stats

    rs = np.random.RandomState(seed)
    x = jnp.asarray(rs.randn(n, c, h, w).astype(np.float32)).astype(dtype)
    wt = jnp.asarray(
        (rs.randn(o, c, k, k) * 0.1).astype(np.float32)).astype(dtype)
    shift = jnp.asarray(rs.randn(o).astype(np.float32))
    return conv_bn_stats(x, wt, shift, stride=stride, pad=pad)


def prewarm_decode_attn(b, h, d, *, page_size=16, maxp=4,
                        num_pages=None, dtype="float32", seed=0):
    """Offline cache warmer for the serving ``decode_attn`` site:
    synthetic paged K/V state with ragged lengths, one ``impl="auto"``
    dispatch on CONCRETE inputs (measured when BIGDL_TUNER_MEASURE=1).
    Returns the op output so callers can assert numerics."""
    import numpy as np

    import jax.numpy as jnp

    from bigdl_tpu.ops.decode_attention import paged_decode_attention

    rs = np.random.RandomState(seed)
    pool = int(num_pages or (b * maxp + 1))
    q = jnp.asarray(rs.randn(b, h, d).astype(np.float32)).astype(dtype)
    kp = jnp.asarray(
        rs.randn(pool, h, page_size, d).astype(np.float32)).astype(dtype)
    vp = jnp.asarray(
        rs.randn(pool, h, page_size, d).astype(np.float32)).astype(dtype)
    lengths = jnp.asarray(
        rs.randint(1, maxp * page_size, (b,)).astype(np.int32))
    tables = jnp.asarray(
        rs.randint(1, pool, (b, maxp)).astype(np.int32))
    return paged_decode_attention(q, kp, vp, tables, lengths,
                                  page_size=page_size, impl="auto")


def prewarm_int8_mm(m, k, n, *, dtype="float32", seed=0):
    """Offline cache warmer for the ``int8_mm`` site: quantize a
    random weight per output channel and run one ``impl="auto"``
    matmul on concrete inputs."""
    import numpy as np

    import jax.numpy as jnp

    from bigdl_tpu.ops.quantized_matmul import (int8_matmul,
                                                quantize_per_channel)

    rs = np.random.RandomState(seed)
    x = jnp.asarray(rs.randn(m, k).astype(np.float32)).astype(dtype)
    w = jnp.asarray((rs.randn(n, k) * 0.1).astype(np.float32))
    w_q, w_s = quantize_per_channel(w, axis=0)
    return int8_matmul(x, w_q, w_s, impl="auto")


def summary() -> dict:
    """Cache + decision snapshot for ``bench.py`` extras and the A/B
    harnesses' BENCH JSON evidence."""
    cache = get_cache()
    with _lock:
        decisions = [
            {"key": k, "site": r.get("site"), "impl": r.get("impl"),
             "label": r.get("label"), "source": r.get("source"),
             "static": r.get("static"),
             "measured_s": r.get("measured_s"),
             "gate": r.get("gate")}
            for k, r in sorted(cache.decisions.items())]
    return {"enabled": enabled(), "cache": cache.stats(),
            "decisions": decisions}
