"""Paged KV cache — the memory substrate of continuous batching.

``TransformerLM.generate`` keeps one contiguous ``(B, H, T_total, Dh)``
cache per layer, sized for the *longest possible* sequence and owned by
the whole batch for the whole decode — a request that finishes early
keeps its columns hot until the slowest batchmate drains.  Serving
needs the vLLM-style alternative: K/V live in fixed-size **pages**
(``(page_size, Dh)`` per head), each request owns only the pages its
tokens actually fill (a per-slot **page table**), pages return to a
free list the moment a request completes, and a new request is admitted
into the freed slot at the next step boundary.

Layout (one array per K and V, all layers stacked so the decode step
carries two device buffers instead of 2·L):

* ``kp``/``vp``: ``(n_layer, num_pages, n_head, page_size, head_dim)``
  device arrays in the cache dtype (defaults to the model dtype — bf16
  weights get a bf16 cache, halving decode HBM traffic);
* page table: ``(max_slots, max_pages_per_slot)`` int32, host-owned and
  shipped to the device per step (a few hundred bytes);
* page 0 is a reserved **trash page**: unallocated table entries and
  the padded tail of a bucketed prefill write there, and the decode
  mask (``position <= length``) guarantees it is never read.

The allocator is plain host Python — a free list and per-slot page
lists.  Decode grows a slot one page at a time as its length crosses a
page boundary; exhaustion is surfaced to the engine, which preempts the
youngest request (its pages return to the pool, the request re-queues
with its generated prefix as prompt) — the standard paged-attention
answer to overcommit.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np
from bigdl_tpu.obs import names


class PagedKVCache:
    """Host-side page allocator + device-side paged K/V buffers."""

    def __init__(self, n_layer: int, n_head: int, head_dim: int, *,
                 page_size: int = 16, num_pages: int = 64,
                 max_slots: int = 8, max_len: int = 256,
                 dtype=None):
        import jax.numpy as jnp

        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.n_layer = int(n_layer)
        self.n_head = int(n_head)
        self.head_dim = int(head_dim)
        self.page_size = int(page_size)
        self.max_slots = int(max_slots)
        self.max_len = int(max_len)
        # every slot must be able to address a full-length sequence
        self.max_pages_per_slot = -(-self.max_len // self.page_size)
        # +1: page 0 is the reserved trash page, never allocated
        self.num_pages = max(int(num_pages), 2)
        self.dtype = jnp.dtype(dtype) if dtype is not None else jnp.float32
        shape = (self.n_layer, self.num_pages, self.n_head,
                 self.page_size, self.head_dim)
        self.kp = jnp.zeros(shape, self.dtype)
        self.vp = jnp.zeros(shape, self.dtype)
        self.page_tables = np.zeros(
            (self.max_slots, self.max_pages_per_slot), np.int32)
        self.lengths = np.zeros((self.max_slots,), np.int32)
        self._free: List[int] = list(range(1, self.num_pages))
        self._slot_pages: List[List[int]] = [[] for _ in
                                             range(self.max_slots)]
        from bigdl_tpu import obs

        self._pages_gauge = obs.get_registry().gauge(
            names.SERVE_KV_PAGES_IN_USE,
            "KV-cache pages currently owned by in-flight requests")

    # --------------------------------------------------------- allocator
    def pages_for(self, n_tokens: int) -> int:
        return max(1, -(-int(n_tokens) // self.page_size))

    def free_pages(self) -> int:
        return len(self._free)

    def pages_in_use(self) -> int:
        return (self.num_pages - 1) - len(self._free)

    def can_admit(self, n_tokens: int) -> bool:
        return len(self._free) >= self.pages_for(n_tokens)

    def alloc(self, slot: int, n_tokens: int) -> List[int]:
        """Give ``slot`` enough pages for ``n_tokens``; returns the page
        ids (raises on exhaustion — the engine checks ``can_admit``
        first and preempts on decode-time growth failure)."""
        need = self.pages_for(n_tokens)
        if len(self._free) < need:
            raise RuntimeError(
                f"KV cache exhausted: need {need} pages, "
                f"{len(self._free)} free")
        pages = [self._free.pop() for _ in range(need)]
        self._slot_pages[slot] = pages
        row = np.zeros((self.max_pages_per_slot,), np.int32)
        row[:need] = pages
        self.page_tables[slot] = row
        self.lengths[slot] = 0
        self._pages_gauge.set(float(self.pages_in_use()))
        return pages

    def grow(self, slot: int) -> bool:
        """One more page for ``slot`` (its length is about to cross a
        page boundary).  False on exhaustion — the engine preempts."""
        if not self._free:
            return False
        pages = self._slot_pages[slot]
        if len(pages) >= self.max_pages_per_slot:
            return False
        page = self._free.pop()
        pages.append(page)
        self.page_tables[slot, len(pages) - 1] = page
        self._pages_gauge.set(float(self.pages_in_use()))
        return True

    def needs_growth(self, slot: int) -> bool:
        """True when the next token's position lands past the slot's
        allocated pages."""
        return (int(self.lengths[slot]) // self.page_size
                >= len(self._slot_pages[slot]))

    def release(self, slot: int):
        """Request finished (or preempted): pages back to the pool, the
        table row points at the trash page again."""
        self._free.extend(self._slot_pages[slot])
        self._slot_pages[slot] = []
        self.page_tables[slot] = 0
        self.lengths[slot] = 0
        self._pages_gauge.set(float(self.pages_in_use()))

    def slot_pages(self, slot: int) -> List[int]:
        return list(self._slot_pages[slot])

    # ------------------------------------------------------ device state
    def device_tables(self, pages: Optional[int] = None):
        """(page_tables, lengths) as jnp arrays for the next step.

        ``pages`` slices the table to its first N columns — the
        engine's used-page prefix bucket (ops/decode_attention.py
        ``used_page_bucket``), so a mostly-empty pool ships a few
        dozen bytes and the decode step never gathers the unallocated
        tail.  Entries past a slot's pages are 0 (trash) either way —
        the mask contract is unchanged."""
        import jax.numpy as jnp

        tables = self.page_tables
        if pages is not None and pages < self.max_pages_per_slot:
            tables = tables[:, :int(pages)]
        return (jnp.asarray(tables), jnp.asarray(self.lengths))

    def padded_positions(self) -> int:
        """Columns of the gathered per-slot attention window."""
        return self.max_pages_per_slot * self.page_size


def gather_pages(pages, page_table):
    """``(num_pages, H, P, Dh)`` pages + ``(B, maxp)`` table ->
    ``(B, H, maxp*P, Dh)`` per-slot contiguous K/V view (positions past
    a slot's length are trash and must be masked by the caller)."""
    b, maxp = page_table.shape
    g = pages[page_table]                      # (B, maxp, H, P, Dh)
    g = g.transpose(0, 2, 1, 3, 4)             # (B, H, maxp, P, Dh)
    return g.reshape(b, g.shape[1], maxp * g.shape[3], g.shape[4])


__all__ = ["PagedKVCache", "gather_pages"]
