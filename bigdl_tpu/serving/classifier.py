"""Micro-batching classifier serving — the stateless half of the tier.

A classifier (ResNet, MLP, anything with ``module.apply``) has no KV
state, so serving it is pure dynamic batching: requests queue through
the same :class:`~bigdl_tpu.serving.batcher.RequestQueue`, a worker
drains up to ``max_batch`` of them (waiting at most ``batch_window_s``
for stragglers to fill the batch), pads to the static batch shape one
jitted forward was compiled for, and fans the rows back out.

``int8=True`` swaps the module for its quantized twin through the
EXISTING ``nn.quantized.quantize()`` path — per-channel int8 Linear /
conv with eval-mode BN folded into the conv — so serving inherits the
reference's post-training-quantization semantics unchanged.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from bigdl_tpu.serving.batcher import RequestQueue, ServeRequest
from bigdl_tpu.serving.engine import LAT_META
from bigdl_tpu.obs import names


class ClassifierEngine:
    """Dynamic-batching inference over one ``AbstractModule``."""

    def __init__(self, module, *, max_batch: Optional[int] = None,
                 int8: Optional[bool] = None,
                 batch_window_s: float = 0.002,
                 queue_capacity: Optional[int] = None):
        import jax

        from bigdl_tpu.config import refresh_from_env

        cfg = refresh_from_env().serve
        self.int8 = cfg.int8 if int8 is None else bool(int8)
        if self.int8:
            from bigdl_tpu.nn.quantized import quantize

            module = quantize(module)
        self.module = module
        module.evaluate()
        self.max_batch = int(max_batch or cfg.max_batch)
        self.batch_window_s = float(batch_window_s)
        self.params = module.params()
        self.state = module.state()
        self.queue = RequestQueue(queue_capacity or cfg.queue_capacity)

        def fwd(params, x):
            out, _ = module.apply(params, self.state, x, training=False)
            return out

        self._fn = jax.jit(fwd)
        self._steps = 0
        self._occ_sum = 0.0
        self.completed = 0
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        from bigdl_tpu import obs

        reg = obs.get_registry()
        self._lat = reg.histogram(*LAT_META, labels=("engine", "kind"))
        self._req_counter = reg.counter(
            names.SERVE_REQUESTS_TOTAL,
            "Requests completed, by engine and status",
            labels=("engine", "status"))
        self._occ_gauge = reg.gauge(
            names.SERVE_BATCH_OCCUPANCY,
            "Mean fraction of decode slots occupied per step")

    def submit(self, features,
               timeout: Optional[float] = None) -> ServeRequest:
        req = ServeRequest(payload=np.asarray(features, np.float32))
        return self.queue.submit(req, timeout=timeout)

    def pump(self, wait_s: float = 0.01) -> bool:
        """Serve one micro-batch; True when anything was served."""
        reqs = self.queue.take(self.max_batch, timeout=wait_s)
        if not reqs:
            return False
        if len(reqs) < self.max_batch and self.batch_window_s > 0:
            deadline = time.monotonic() + self.batch_window_s
            while len(reqs) < self.max_batch \
                    and time.monotonic() < deadline:
                more = self.queue.take(self.max_batch - len(reqs),
                                       timeout=0.001)
                if not more:
                    break
                reqs.extend(more)
        n = len(reqs)
        batch = np.stack([r.payload for r in reqs])
        if n < self.max_batch:
            # pad to the compiled static batch with copies of row 0
            pad = np.broadcast_to(
                batch[:1], (self.max_batch - n,) + batch.shape[1:])
            batch = np.concatenate([batch, pad], axis=0)
        try:
            out = np.asarray(self._fn(self.params, batch))
            err = None
        except Exception as e:  # noqa: BLE001 — fail the batch, not the loop
            out, err = None, f"{type(e).__name__}: {e}"
        self._steps += 1
        self._occ_sum += n / self.max_batch
        self._occ_gauge.set(self._occ_sum / self._steps)
        for i, req in enumerate(reqs):
            if err is None:
                req.result = out[i]
            req.finish(err)
            self._lat.labels(engine="classifier", kind="e2e").observe(
                req.e2e_s)
            self._req_counter.labels(
                engine="classifier",
                status="error" if err else "ok").inc()
            self.completed += 1
        return True

    def start(self):
        if self._thread is not None:
            return self
        self._stop = False

        def loop():
            while not self._stop:
                if not self.pump(wait_s=0.02):
                    time.sleep(0.002)

        self._thread = threading.Thread(
            target=loop, name="bigdl-serve-classifier", daemon=True)
        self._thread.start()
        return self

    def close(self):
        self._stop = True
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.queue.close()

    def stats(self) -> dict:
        return {
            "requests": self.completed,
            "batches": self._steps,
            "occupancy_mean": (self._occ_sum / self._steps
                               if self._steps else None),
            "queue_depth": self.queue.depth(),
            "int8": self.int8,
        }


__all__ = ["ClassifierEngine"]
