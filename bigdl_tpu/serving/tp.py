"""TP-sharded decode over the compressed-collective wire.

Decode is memory-bound: one token's matmuls stream every weight byte
per step, so splitting the weights across ``tp`` devices divides the
per-device bytes (and the KV cache, sharded on the head axis) at the
price of two small cross-device reductions per block — exactly the two
Megatron psums, run here through ``parallel.wire_psum`` so an int8/fp8
wire compresses the only bytes serving puts on the interconnect.

Layout (``SERVE_TP_RULES``): attention wq/wk/wv rows (= heads) and
fc1 rows split over ``model``; wo and fc2 columns split (their products
are partial sums — ``psum`` after); embeddings, LayerNorms and the
vocab head stay replicated, so the sampled token is identical on every
device and leaves the shard_map replicated.  Prefill stays the
replicated single-device path (compute-bound; the engine writes its
K/V into the head-sharded pages through the normal jit path).

The per-step wire footprint is static — ``2 * n_layer`` psums of
``(batch, dim)`` f32 — and is recorded once at build time
(``bigdl_collective_bytes_total{op="serve_tp_psum"}`` plus the
``path="serve"`` wire-savings gauge).
"""

from __future__ import annotations

import numpy as np

# Megatron row/col split for the serving decode step (module paths of
# the TransformerLM params tree); everything unmatched is replicated.
SERVE_TP_RULES = (
    (r"attn/w[qkv]$", ("model", None)),
    (r"attn/b[qkv]$", ("model",)),
    (r"attn/wo$", (None, "model")),
    (r"fc1/weight$", ("model", None)),
    (r"fc1/bias$", ("model",)),
    (r"fc2/weight$", (None, "model")),
)


def _account(n_layer: int, batch: int, dim: int, tp: int, spec):
    """Static per-step byte model of the 2L block reductions; records
    the counters + the path="serve" savings gauge once at build."""
    from bigdl_tpu.obs import collectives as C
    from bigdl_tpu.parallel import wire as W

    elems = batch * dim
    baseline = C.all_reduce_bytes(elems, "float32", tp) * 2 * n_layer
    if spec is None:
        wire_bytes = baseline
        name = "float32"
    elif not spec.scaled:
        wire_bytes = C.all_reduce_bytes(elems, "bfloat16", tp) \
            * 2 * n_layer
        name = "bfloat16"
    else:
        padded, blk = W.psum_layout(elems, spec, tp)
        ex = sum(C.staged_ring_exchange_bytes(
            padded, tp, blk, spec.wire_name).values())
        ex += C.all_gather_bytes(padded, spec.wire_name, tp)
        ex += C.all_gather_bytes(padded // blk, "float32", tp)
        wire_bytes = ex * 2 * n_layer
        name = spec.wire_name
    C.record("serve_tp_psum", name, wire_bytes, axis_size=tp)
    if spec is not None:
        C.record_savings("serve", baseline, wire_bytes)
    return wire_bytes


def build_tp_decode_step(model, *, tp: int, wire=None, page_size: int,
                         max_batch: int, positions: int,
                         attn_impl: str = "auto"):
    """The engine's decode step, sharded ``tp`` ways on the first
    ``tp`` local devices.  Same signature as the single-host step:
    ``step(params, kp, vp, tables, lengths, tokens, temps, active,
    key) -> (kp, vp, next_tokens)`` with replicated params/cache
    accepted (GSPMD reshards on first call).  ``attn_impl`` is the
    paged decode-attention dispatch (ops/decode_attention.py) — the
    body sees the LOCAL head shard, so the tuner's ``decode_attn``
    site keys on the per-device shape."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    from bigdl_tpu.optim.distri_optimizer import _shard_map
    from bigdl_tpu.parallel import wire as W
    from bigdl_tpu.parallel.tensor_parallel import param_specs
    from bigdl_tpu.serving.engine import paged_decode_math

    del positions  # shapes flow through shard_map; kept for the API
    tp = int(tp)
    devices = jax.devices()
    if tp > len(devices):
        raise ValueError(f"tp={tp} but only {len(devices)} devices")
    mc = model._config
    n_head, dim = int(mc["n_head"]), model.dim
    hidden = int(mc["mlp_ratio"]) * dim
    if n_head % tp or hidden % tp:
        raise ValueError(
            f"tp={tp} must divide n_head={n_head} and the MLP hidden "
            f"{hidden}")
    mesh = Mesh(np.array(devices[:tp]), ("model",))
    spec = W.resolve(wire)
    _account(model.n_layer, max_batch, dim, tp, spec)

    pspecs = param_specs(model.params(), mesh, rules=SERVE_TP_RULES)
    cache_spec = P(None, None, "model", None, None)
    children = model._children
    n_layer = model.n_layer

    def body(params, kp, vp, tables, lengths, tokens, temps, active,
             key_data):
        key = jax.random.wrap_key_data(key_data)

        def psum_fn(x):
            v, _ = W.psum(x, "model", tp, spec)
            return v

        return paged_decode_math(
            children, n_layer, page_size, params, None, kp, vp,
            tables, lengths, tokens, temps, active, key,
            n_head=n_head // tp, psum=psum_fn, attn_impl=attn_impl)

    mapped = _shard_map(
        body, mesh,
        in_specs=(pspecs, cache_spec, cache_spec, P(), P(), P(), P(),
                  P(), P()),
        out_specs=(cache_spec, cache_spec, P()))

    def step(params, kp, vp, tables, lengths, tokens, temps, active,
             key):
        return mapped(params, kp, vp, tables, lengths, tokens, temps,
                      active, jax.random.key_data(key))

    return jax.jit(step, donate_argnums=(1, 2))


__all__ = ["SERVE_TP_RULES", "build_tp_decode_step"]
