"""HTTP front-end for the serving tier — stdlib only, like obs/server.

One :class:`ThreadingHTTPServer` fronting an :class:`LMEngine` and/or a
:class:`ClassifierEngine`:

* ``POST /v1/generate``  ``{"prompt": [ids], "max_new_tokens": N,
  "temperature": t}`` -> ``{"tokens": [...], "ttft_s": ..,
  "e2e_s": ..}`` (blocks until the request completes — each client
  connection holds one handler thread, which is exactly the concurrent-
  clients shape the serve smoke drives);
* ``POST /v1/classify`` ``{"inputs": [[...], ...]}`` ->
  ``{"outputs": [[...]], "classes": [...]}``;
* ``POST /admin/drain`` ``{"deadline_s": s}`` -> graceful drain: stops
  admissions, finishes what fits in the deadline, returns the
  checkpointed leftovers as ``{"handoffs": [...]}`` for the router to
  replay elsewhere;
* ``GET /stats`` -> both engines' stats dicts;
* ``GET /healthz`` -> liveness (the *metrics* endpoint stays obs/server
  — one telemetry plane, not two).

Backpressure is explicit: a queue that stays full past the admission
timeout — or an engine that is draining — answers **503 +
``Retry-After``** (and stamps ``bigdl_serve_rejects_total``), never a
4xx/5xx that a client would misread as "my request was bad" or "the
server is broken".  Only a malformed payload gets a 400.

Port 0 binds an ephemeral port (``.port`` has the real one).
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from bigdl_tpu.obs import names, reqtrace

log = logging.getLogger("bigdl_tpu.serving")


class ServingServer:
    def __init__(self, lm=None, classifier=None, *,
                 port: Optional[int] = None, host: str = "127.0.0.1",
                 request_timeout_s: float = 60.0):
        from bigdl_tpu.config import refresh_from_env

        from bigdl_tpu import obs

        cfg = refresh_from_env().serve
        if port is None:
            port = cfg.port if cfg.port is not None else 0
        self.lm = lm
        self.classifier = classifier
        self.request_timeout_s = float(request_timeout_s)
        self.retry_after_s = float(refresh_from_env().router.retry_after_s)
        self._rejects = obs.get_registry().counter(
            names.SERVE_REJECTS_TOTAL,
            "Admissions rejected 503 + Retry-After (queue full past "
            "the admission timeout, or the engine is draining)")
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: A003
                log.debug("serving: " + fmt, *args)

            def _send(self, obj, code=200, headers=None):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _reject(self, reason):
                outer._rejects.inc()
                # shed with *state*: the Retry-After basis plus the
                # engine's live admission picture, so a shed client
                # (or the router's logs) can see what it hit
                body = {"error": reason,
                        "retry_after_s": outer.retry_after_s}
                if outer.lm is not None:
                    try:
                        body["engine"] = {
                            "queue_depth": outer.lm.queue.depth(),
                            "draining": bool(outer.lm.draining)}
                    except Exception:  # noqa: BLE001 — shed anyway
                        pass
                return self._send(
                    body, 503,
                    headers={"Retry-After":
                             f"{max(1, round(outer.retry_after_s))}"})

            def do_GET(self):  # noqa: N802
                if self.path == "/healthz":
                    body = {"status": "ok"}
                    if outer.lm is not None:
                        # the fleet reads what each replica SERVES here
                        # — the rollout canary's version-skew check and
                        # the operator's stuck-rollout triage both key
                        # on this pair
                        body["weight_version"] = getattr(
                            outer.lm, "weight_version", None)
                        body["manifest_sha"] = getattr(
                            outer.lm, "manifest_sha", None)
                    return self._send(body)
                if self.path == "/stats":
                    return self._send({
                        "lm": outer.lm.stats() if outer.lm else None,
                        "classifier": (outer.classifier.stats()
                                       if outer.classifier else None)})
                return self._send({"error": "not found"}, 404)

            def _body(self):
                n = int(self.headers.get("Content-Length", 0) or 0)
                return json.loads(self.rfile.read(n) or b"{}")

            def do_POST(self):  # noqa: N802
                try:
                    payload = self._body()
                    if self.path == "/v1/generate":
                        return self._generate(payload)
                    if self.path == "/v1/classify":
                        return self._classify(payload)
                    if self.path == "/admin/drain":
                        return self._drain(payload)
                    return self._send({"error": "not found"}, 404)
                except TimeoutError as e:
                    # queue full past the admission timeout: overload,
                    # not a client error — tell the client to back off
                    return self._reject(f"overloaded: {e}")
                except RuntimeError as e:
                    # draining / closed queue: admissions are refused
                    return self._reject(str(e))
                except (KeyError, TypeError, ValueError) as e:
                    return self._send(
                        {"error": f"{type(e).__name__}: {e}"}, 400)
                except Exception as e:  # noqa: BLE001 — server bug
                    return self._send(
                        {"error": f"{type(e).__name__}: {e}"}, 500)

            def _generate(self, payload):
                from bigdl_tpu.serving.drain import HANDOFF_ERROR

                if outer.lm is None:
                    return self._reject("no LM engine")
                # a traced caller propagates its context in the
                # X-Bigdl-Trace header; from_header is tolerant and the
                # engine ignores the context unless its collector is on
                ctx = reqtrace.RequestTraceContext.from_header(
                    self.headers.get(reqtrace.TRACE_HEADER))
                req = outer.lm.submit(
                    payload["prompt"],
                    int(payload.get("max_new_tokens", 16)),
                    temperature=float(payload.get("temperature", 0.0)),
                    timeout=outer.request_timeout_s, trace=ctx)
                req.router_id = payload.get("request_id")
                req.wait(outer.request_timeout_s)
                if req.error == HANDOFF_ERROR:
                    # checkpointed mid-drain: hand the resume point back
                    # so the router replays it elsewhere exactly once
                    outer._rejects.inc()
                    return self._send(
                        {"error": "draining", "handoff": {
                            "prompt": [int(t) for t in req.payload],
                            "max_new_tokens": int(req.max_new_tokens),
                            "temperature": float(req.temperature),
                            "tokens_done": [int(t) for t in req.tokens],
                            "request_id": req.router_id,
                            "trace": (req.trace.to_header()
                                      if req.trace is not None
                                      else None),
                            "weight_version": getattr(
                                outer.lm, "weight_version", None)}},
                        503,
                        headers={"Retry-After":
                                 f"{max(1, round(outer.retry_after_s))}"})
                if req.error:
                    return self._send({"error": req.error}, 500)
                return self._send({
                    "id": req.id, "tokens": [int(t) for t in req.tokens],
                    "prompt_len": len(payload["prompt"]),
                    "ttft_s": req.ttft_s, "e2e_s": req.e2e_s})

            def _drain(self, payload):
                if outer.lm is None:
                    return self._send({"error": "no LM engine"}, 503)
                records = outer.lm.drain(
                    float(payload.get("deadline_s", 10.0)))
                return self._send(
                    {"handoffs": [hd.to_dict() for hd in records],
                     "draining": True})

            def _classify(self, payload):
                if outer.classifier is None:
                    return self._send(
                        {"error": "no classifier engine"}, 503)
                x = np.asarray(payload["inputs"], np.float32)
                reqs = [outer.classifier.submit(
                    row, timeout=outer.request_timeout_s) for row in x]
                outs = []
                for r in reqs:
                    r.wait(outer.request_timeout_s)
                    if r.error:
                        return self._send({"error": r.error}, 500)
                    outs.append(np.asarray(r.result))
                out = np.stack(outs)
                return self._send({
                    "outputs": out.tolist(),
                    "classes": np.argmax(
                        out.reshape(out.shape[0], -1), axis=-1)
                    .tolist()})

        self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1},
            name="bigdl-serving-http", daemon=True)
        self._thread.start()
        log.info("serving front-end on %s:%d", host, self.port)

    def url(self, path: str = "/stats") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def close(self):
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:  # noqa: BLE001
            pass
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


__all__ = ["ServingServer"]
