"""Graceful drain + exactly-once handoff for the serving data plane.

Draining a replica must never lose or duplicate a request.  The
machinery here is three small pieces the router and the engine share:

* :data:`HANDOFF_ERROR` — the sentinel ``ServeRequest.error`` value a
  draining engine finishes unfinished requests with.  A client blocked
  in ``req.wait`` unblocks, sees the sentinel, and knows the request
  was *checkpointed*, not failed: the generated-so-far tokens are in
  ``req.tokens`` and the refolded prompt (original prompt + generated
  prefix, the same fold the KV-page preemption path uses) is in
  ``req.payload`` — replaying that prompt elsewhere at temperature 0
  continues the decode bit-exactly;
* :class:`HandoffRecord` — the checkpoint itself, transport-agnostic
  (rides a JSON body between ServingServer and the router's HTTP
  replica client, or a plain object in-process / in the simulator);
* :class:`HandoffLedger` — the exactly-once gate.  Replays are *claim
  then replay*: ``claim(request_id)`` succeeds once, so when a replica
  dies mid-handoff and the same request surfaces on two recovery paths
  (the drain coordinator's orphan sweep AND the per-request retry
  loop), exactly one path replays it.  Deliveries are *deliver once*:
  ``deliver(request_id)`` returns False on a second completion, which
  the router counts as a duplicate (the invariant the drain chaos
  scenario pins at zero).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional

#: ServeRequest.error sentinel: "checkpointed by a drain, replay me"
HANDOFF_ERROR = "__drain_handoff__"


@dataclasses.dataclass
class HandoffRecord:
    """One checkpointed request, ready to replay on another replica."""

    prompt: List[int]            # original prompt + generated prefix
    max_new_tokens: int          # tokens still owed
    temperature: float = 0.0
    tokens_done: List[int] = dataclasses.field(default_factory=list)
    request_id: Optional[str] = None   # router id when router-placed
    source: Optional[str] = None       # replica the checkpoint left
    # serialized request-trace context (X-Bigdl-Trace header form) so a
    # replay continues under the SAME trace_id on the absorbing replica
    trace: Optional[str] = None
    # weight version the generated-so-far prefix was decoded under —
    # replaying on a replica serving a DIFFERENT version would continue
    # the decode under different weights and silently break the
    # temperature-0 bit-equal replay contract, so the absorber side
    # refuses (re-queues) on mismatch.  None = pre-rollout checkpoint,
    # accepted anywhere (backward compatible).
    weight_version: Optional[str] = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "HandoffRecord":
        return cls(prompt=[int(t) for t in d["prompt"]],
                   max_new_tokens=int(d["max_new_tokens"]),
                   temperature=float(d.get("temperature", 0.0)),
                   tokens_done=[int(t) for t in
                                d.get("tokens_done") or []],
                   request_id=d.get("request_id"),
                   source=d.get("source"),
                   trace=d.get("trace"),
                   weight_version=d.get("weight_version"))


class HandoffLedger:
    """Exactly-once accounting for replays and deliveries."""

    def __init__(self):
        self._lock = threading.Lock()
        self._claimed: Dict[str, int] = {}    # request id -> claim count
        self._delivered: set = set()
        self.duplicates = 0

    def claim(self, request_id: str) -> bool:
        """Claim the right to replay ``request_id``.  True exactly once
        per id; a second claimant (the race when a replica dies mid-
        handoff) is refused and must stand down."""
        rid = str(request_id)
        with self._lock:
            if rid in self._delivered:
                return False
            n = self._claimed.get(rid, 0)
            self._claimed[rid] = n + 1
            return n == 0

    def release(self, request_id: str) -> None:
        """Undo a claim whose replay could not start (the claimant's
        chosen replica refused) so another path may pick the request
        up; never called after the replay was actually submitted."""
        with self._lock:
            rid = str(request_id)
            if self._claimed.get(rid, 0) > 0:
                self._claimed[rid] -= 1

    def deliver(self, request_id: str) -> bool:
        """Record the request's single completion.  False = this id was
        already delivered — the caller found a duplicate."""
        rid = str(request_id)
        with self._lock:
            if rid in self._delivered:
                self.duplicates += 1
                return False
            self._delivered.add(rid)
            return True

    def delivered(self, request_id: str) -> bool:
        with self._lock:
            return str(request_id) in self._delivered

    def stats(self) -> dict:
        with self._lock:
            return {"claimed": len(self._claimed),
                    "delivered": len(self._delivered),
                    "duplicates": self.duplicates}


def drain_engine(engine, deadline_s: float = 10.0,
                 poll_s: float = 0.005) -> List[HandoffRecord]:
    """Drain one :class:`~bigdl_tpu.serving.LMEngine` in place.

    Admissions stop immediately (``engine.draining`` — ``submit``
    refuses with a RuntimeError the HTTP tier maps to 503 +
    Retry-After).  In-flight decodes get ``deadline_s`` to finish; at
    the deadline every still-active slot is preempted through the
    engine's own KV-preemption fold (generated tokens -> prompt) and
    everything left over — preempted, stashed, or still queued — is
    checkpointed into :class:`HandoffRecord`s.  Each checkpointed
    request is finished with :data:`HANDOFF_ERROR` so a blocked client
    unblocks and learns to replay."""
    engine.draining = True
    deadline = time.monotonic() + max(0.0, float(deadline_s))
    while time.monotonic() < deadline:
        with engine._lock:
            busy = (engine.active_count() or engine._stash
                    or engine.queue.depth() > 0)
        if not busy:
            break
        if engine._thread is None:
            engine.pump(wait_s=poll_s)
        else:
            time.sleep(poll_s)
    handoffs: List[HandoffRecord] = []
    with engine._lock:
        while engine.active_count():
            if engine._preempt_youngest() is None:
                break
        leftovers = list(engine._stash)
        engine._stash.clear()
        while engine.queue.depth() > 0:
            batch = engine.queue.take(engine.max_batch, timeout=0.0)
            if not batch:
                break
            leftovers.extend(batch)
        for req in leftovers:
            ctx = getattr(req, "trace", None)
            if ctx is not None:
                # the checkpointed request's engine-side trace ends
                # here, force-kept (handoff): the replay re-begins the
                # SAME trace_id on the absorbing replica.  finish()
                # runs BEFORE the record serializes the context so the
                # checkpoint header carries the force-keep flag across
                # the process boundary
                from bigdl_tpu.obs import reqtrace
                from bigdl_tpu.serving import spans
                col = reqtrace.get_collector()
                now = time.monotonic()
                col.span(ctx, spans.SPAN_HANDOFF, now, 0.0,
                         tokens_done=len(req.tokens),
                         owed=int(req.max_new_tokens), side="drain")
                col.finish(
                    ctx,
                    request=str(getattr(req, "router_id", None)
                                or req.id),
                    handoff=True,
                    e2e_s=max(0.0, now - req.t_submit))
            handoffs.append(HandoffRecord(
                prompt=[int(t) for t in req.payload],
                max_new_tokens=int(req.max_new_tokens),
                temperature=float(req.temperature),
                tokens_done=[int(t) for t in req.tokens],
                request_id=getattr(req, "router_id", None),
                trace=ctx.to_header() if ctx is not None else None,
                weight_version=getattr(engine, "weight_version", None)))
            req.finish(error=HANDOFF_ERROR)
    return handoffs


__all__ = ["HANDOFF_ERROR", "HandoffLedger", "HandoffRecord",
           "drain_engine"]
