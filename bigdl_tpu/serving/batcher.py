"""Request queue + dynamic batcher front half of the serving tier.

Requests flow ``client -> RequestQueue -> engine admission``.  The
queue deliberately reuses :class:`bigdl_tpu.dataset.stream.BoundedBuffer`
— the streaming tier's bounded producer/consumer adapter — because its
behavior is exactly what a serving ingress needs and its depth gauge
(``bigdl_stream_buffer_depth``) is already the queue-depth signal the
autoscaling policy loop (resilience/autoscale.py) natively scrapes:

* a full buffer **backpressures** (clients block in ``submit``, counted
  in ``bigdl_serve_admission_waits_total`` — requests are never
  dropped);
* the live total queue depth is additionally published as
  ``bigdl_serve_queue_depth`` (also in the autoscaler's queue-metric
  set), so a serving process and a streaming trainer can coexist
  without clobbering each other's signal.

Unlike stream records, requests are *not replayable* — the
:class:`_PushSource` ignores the replay offset contract and simply
yields submissions in arrival order; exactly-once here is trivial (a
request completes or its client times out and retries).
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import threading
import time
from typing import Any, List, Optional

import numpy as np

from bigdl_tpu.dataset.stream import BoundedBuffer, StreamSource
from bigdl_tpu.obs import names

_ids = itertools.count()


@dataclasses.dataclass
class ServeRequest:
    """One in-flight request (LM decode or classifier forward)."""

    payload: Any                      # prompt token ids / feature array
    max_new_tokens: int = 0           # LM only
    temperature: float = 0.0          # LM only
    id: int = dataclasses.field(default_factory=lambda: next(_ids))
    t_submit: float = dataclasses.field(default_factory=time.monotonic)
    t_first: Optional[float] = None   # first generated token (TTFT)
    t_done: Optional[float] = None
    tokens: List[int] = dataclasses.field(default_factory=list)
    result: Optional[np.ndarray] = None  # classifier output row(s)
    error: Optional[str] = None
    # request-trace context (obs.reqtrace.RequestTraceContext) when the
    # distributed tracing collector is on; None = untraced, and the
    # engine does zero trace work for this request
    trace: Optional[Any] = None
    _event: threading.Event = dataclasses.field(
        default_factory=threading.Event, repr=False)

    def finish(self, error: Optional[str] = None):
        self.error = error
        self.t_done = time.monotonic()
        self._event.set()

    def wait(self, timeout: Optional[float] = None) -> "ServeRequest":
        if not self._event.wait(timeout):
            raise TimeoutError(f"request {self.id} not done after "
                               f"{timeout:g}s")
        return self

    @property
    def done(self) -> bool:
        return self._event.is_set()

    @property
    def e2e_s(self) -> Optional[float]:
        return None if self.t_done is None else self.t_done - self.t_submit

    @property
    def ttft_s(self) -> Optional[float]:
        return None if self.t_first is None \
            else self.t_first - self.t_submit


class _PushSource(StreamSource):
    """Push-fed source: ``put`` appends, ``read`` yields in arrival
    order until :meth:`close`.  The bounded buffer downstream provides
    the depth gauge and producer backpressure; ``put`` itself blocks
    when the *unpulled* backlog reaches ``capacity`` so client-side
    backpressure composes with the buffer's."""

    def __init__(self, capacity: int):
        self.capacity = max(1, int(capacity))
        self._q: collections.deque = collections.deque()
        self._cond = threading.Condition()
        self._closed = False
        from bigdl_tpu import obs

        self._wait_counter = obs.get_registry().counter(
            names.SERVE_ADMISSION_WAITS_TOTAL,
            "Client submits that blocked on a full request queue")

    def put(self, item, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while len(self._q) >= self.capacity and not self._closed:
                self._wait_counter.inc()
                remain = None if deadline is None \
                    else deadline - time.monotonic()
                if remain is not None and remain <= 0:
                    raise TimeoutError(
                        f"request queue full for {timeout:g}s")
                self._cond.wait(timeout=0.05 if remain is None
                                else min(0.05, remain))
            if self._closed:
                raise RuntimeError("request queue is closed")
            self._q.append(item)
            self._cond.notify_all()

    def backlog(self) -> int:
        with self._cond:
            return len(self._q)

    def close(self):
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def read(self, offset: int):
        del offset  # requests are not replayable records
        while True:
            with self._cond:
                while not self._q and not self._closed:
                    self._cond.wait(timeout=0.05)
                if self._q:
                    item = self._q.popleft()
                    self._cond.notify_all()
                elif self._closed:
                    return
                else:
                    continue
            yield item


class RequestQueue:
    """Bounded request ingress: ``submit`` on any number of client
    threads, ``take`` on the engine's step loop."""

    def __init__(self, capacity: int = 64):
        self.capacity = max(1, int(capacity))
        self._source = _PushSource(self.capacity)
        self._buf = BoundedBuffer(self._source, self.capacity).start(0)
        self._closed = False
        from bigdl_tpu import obs

        self._depth_gauge = obs.get_registry().gauge(
            names.SERVE_QUEUE_DEPTH,
            "Requests queued ahead of engine admission (backlog + "
            "bounded buffer)")

    def depth(self) -> int:
        d = self._source.backlog() + self._buf.depth()
        self._depth_gauge.set(float(d))
        return d

    def submit(self, req: ServeRequest,
               timeout: Optional[float] = None) -> ServeRequest:
        if self._closed:
            raise RuntimeError("request queue is closed")
        self._source.put(req, timeout=timeout)
        self.depth()
        return req

    def take(self, max_n: int, timeout: float = 0.0) -> List[ServeRequest]:
        """Up to ``max_n`` queued requests; waits at most ``timeout``
        for the *first* one, then drains greedily without blocking."""
        out: List[ServeRequest] = []
        try:
            first = self._buf.get(timeout=max(1e-4, timeout))
        except TimeoutError:
            self.depth()
            return out
        if first is not None:
            out.append(first)
        while len(out) < max_n:
            if self._buf.depth() <= 0 and not self._source.backlog():
                break
            try:
                rec = self._buf.get(timeout=0.02)
            except TimeoutError:
                break
            if rec is None:
                break
            out.append(rec)
        self.depth()
        return out

    def close(self):
        self._closed = True
        self._source.close()
        self._buf.stop()
        self._depth_gauge.set(0.0)


__all__ = ["ServeRequest", "RequestQueue"]
