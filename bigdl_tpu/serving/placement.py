"""Replica placement policy for the serving router.

Pure host-side policy, no I/O and no clocks of its own — the router
(or the serving simulator) feeds it :class:`ReplicaView` snapshots
built from the signals every replica already exports
(``bigdl_serve_queue_depth``, ``bigdl_serve_kv_pages_in_use``) and an
injectable ``clock``, so the same object places requests on a wall
clock behind HTTP and on a virtual clock inside a chaos scenario.

Two concerns, in priority order:

* **session affinity** — a multi-turn conversation's KV prefix lives in
  ONE replica's paged cache; re-placing turn N+1 anywhere else pays a
  full re-prefill.  ``choose(session=...)`` therefore sticks to the
  session's bound replica while it stays eligible and the binding is
  inside ``affinity_ttl_s``.  A binding to a drained/dead replica is
  dropped (the KV prefix is gone — affinity to a corpse is worthless)
  and the session rebinds wherever the request lands next;
* **load- and KV-pressure-aware spread** — among eligible replicas the
  cheapest by ``queue_depth + in_flight + kv_weight * kv_frac`` wins
  (deterministic name tie-break).  ``kv_frac`` is page-pool occupancy:
  a replica whose pool is nearly exhausted will preempt whatever it
  admits next, which costs far more than a deeper queue — hence its
  own weight.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional


class NoReplicaAvailable(RuntimeError):
    """Every replica is down or draining — the caller must shed."""


@dataclasses.dataclass
class ReplicaView:
    """One replica's placement-relevant state, as the router sees it."""

    name: str
    up: bool = True
    draining: bool = False
    queue_depth: float = 0.0
    in_flight: int = 0          # router-side: placed, not yet completed
    kv_frac: float = 0.0        # pages_in_use / pool size, 0..1
    # host-clock skew past BIGDL_STALE_AFTER_S — its SLO windows and
    # handoff timestamps can't be trusted, so placement skips it
    stale: bool = False
    # weight version the replica serves (None = replica predates the
    # rollout tier) — version-pinned handoff replays match on this
    version: Optional[str] = None

    @property
    def eligible(self) -> bool:
        return self.up and not self.draining and not self.stale


class PlacementPolicy:
    """Session-affine, least-loaded placement over replica views."""

    def __init__(self, affinity_ttl_s: float = 300.0,
                 kv_weight: float = 4.0,
                 clock: Callable[[], float] = time.monotonic):
        self.affinity_ttl_s = float(affinity_ttl_s)
        self.kv_weight = float(kv_weight)
        self._clock = clock
        self._lock = threading.Lock()
        # session -> (replica name, binding expiry on self._clock)
        self._bind: Dict[str, tuple] = {}
        self.affinity_hits = 0
        self.rebinds = 0

    # ------------------------------------------------------------ affinity
    def lookup(self, session: Optional[str]) -> Optional[str]:
        """The session's bound replica, or None (no/expired binding)."""
        if not session or self.affinity_ttl_s <= 0:
            return None
        with self._lock:
            bound = self._bind.get(session)
            if bound is None:
                return None
            name, expires = bound
            if self._clock() >= expires:
                del self._bind[session]
                return None
            return name

    def bind(self, session: Optional[str], name: str) -> None:
        if not session or self.affinity_ttl_s <= 0:
            return
        with self._lock:
            prev = self._bind.get(session)
            if prev is not None and prev[0] != name:
                self.rebinds += 1
            self._bind[session] = (name, self._clock()
                                   + self.affinity_ttl_s)

    def unbind_replica(self, name: str) -> List[str]:
        """Drop every session bound to ``name`` (drained or dead — its
        KV prefixes are gone); returns the affected sessions."""
        with self._lock:
            gone = [s for s, (n, _) in self._bind.items() if n == name]
            for s in gone:
                del self._bind[s]
            return gone

    def bindings(self) -> Dict[str, str]:
        with self._lock:
            now = self._clock()
            return {s: n for s, (n, exp) in self._bind.items()
                    if now < exp}

    # ------------------------------------------------------------- scoring
    def score(self, view: ReplicaView) -> float:
        return (float(view.queue_depth) + float(view.in_flight)
                + self.kv_weight * float(view.kv_frac))

    def choose(self, views: Dict[str, ReplicaView],
               session: Optional[str] = None,
               exclude: Optional[set] = None) -> str:
        """Pick a replica for one request.  Affinity wins while the
        bound replica is eligible; otherwise least-loaded (score, then
        name).  ``exclude`` removes replicas already tried by this
        request's retry loop.  Binds/rebinds the session to whatever is
        returned.  Raises :class:`NoReplicaAvailable` when nothing is
        eligible — shedding is the caller's job (it owns the 503)."""
        exclude = exclude or set()
        bound = self.lookup(session)
        if bound is not None and bound not in exclude:
            view = views.get(bound)
            if view is not None and view.eligible:
                with self._lock:
                    self.affinity_hits += 1
                self.bind(session, bound)   # refresh the TTL
                return bound
        candidates = [v for n, v in views.items()
                      if v.eligible and n not in exclude]
        if not candidates:
            raise NoReplicaAvailable(
                f"no eligible replica among {sorted(views)} "
                f"(excluded {sorted(exclude)})")
        best = min(candidates, key=lambda v: (self.score(v), v.name))
        self.bind(session, best.name)
        return best.name

    def stats(self) -> dict:
        with self._lock:
            return {"bindings": len(self._bind),
                    "affinity_hits": self.affinity_hits,
                    "rebinds": self.rebinds}


__all__ = ["NoReplicaAvailable", "PlacementPolicy", "ReplicaView"]
